"""Multi-chip BNG: the fused pipeline under shard_map over a device Mesh.

Scale-out design (replacing the reference's HTTP/SSE + hashring node mesh,
SURVEY.md §2.3, with ICI collectives):

- **Packets are data-parallel**: the host ring steers each subscriber's
  traffic to a consistent chip (runtime/ring.py shard_of + bngring.cpp
  bng_ring_shard_of — the pkg/pool/peer.go owner-routing role, re-hosted
  at the ring): upstream by FNV-1a32(private src IP), downstream by NAT
  public-IP ownership, so each chip's batch region (assemble_sharded) is
  its own subscribers' traffic. affinity_shard_ip() is the same function
  on the control-plane side.
- **Flow state is chip-local**: NAT sessions / QoS buckets / antispoof
  bindings live on the chip that owns the subscriber — no cross-chip
  traffic for the hot NAT path (mirrors the reference where each node owns
  its subscribers' conntrack outright).
- **DHCP subscriber tables are hash-sharded across chips** with all-to-all
  key/result exchange (ops.table.sharded_lookup): DISCOVER/REQUEST can
  arrive on any chip (broadcasts, relays), and the 1M-entry table sharded
  over 8 chips is the capacity headline. Only 8-byte keys and 32-byte
  results ride ICI, never packets.
- **Stats are psum-reduced** over the mesh (the per-CPU-map -> global
  counter role, bpf maps PERCPU_ARRAY).

Host side: ShardedCluster owns one host-table stack per shard, routes
control-plane writes to the owner shard (DHCP tables by key hash; NAT/QoS/
spoof by the subscriber-affinity shard), and stacks the per-shard device
arrays with a leading mesh dimension.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bng_tpu.control.nat import NATManager
from bng_tpu.edge.tables import EdgeTables
from bng_tpu.ops.pipeline import PipelineGeom, PipelineTables, pipeline_step
from bng_tpu.ops import table as table_mod
from bng_tpu.ops.table import TableGeom, shard_owner
from bng_tpu.runtime.engine import (AntispoofTables, GardenTables, QoSTables,
                                    _apply_all_updates)
from bng_tpu.runtime.tables import (FastPathTables,
                                    PPPoEFastPathTables)
from bng_tpu.utils.net import mac_to_u64, split_u64

AXIS = "shard"


def make_mesh(n_devices: int) -> Mesh:
    devs = jax.devices()[:n_devices]
    if len(devs) < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have {len(jax.devices())}")
    return Mesh(np.array(devs), (AXIS,))


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the top-level API (check_vma
    kwarg) landed after 0.4.x; older jaxlibs ship it as
    jax.experimental.shard_map (check_rep kwarg). Replication checking is
    off either way — the stats psums are deliberately cross-chip."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
        except TypeError:
            # jax versions where shard_map is top-level but the kwarg is
            # still the older check_rep spelling
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)
    from jax.experimental.shard_map import shard_map as sm_exp

    return sm_exp(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def _sharded_geom(geom: PipelineGeom, n: int) -> PipelineGeom:
    """Mark the DHCP lookup tables as hash-sharded over the mesh axis.

    PUNT-SAFETY INVARIANT: only tables whose device-miss path falls
    through to an authoritative slow path may be sharded. The bounded
    all-to-all exchange punts overflow lanes as found=False
    (ops/table.py sharded_lookup); for the DHCP tables that turns a
    skew-overflowed DISCOVER into a slow-path request the host server
    answers from its authoritative state — degraded latency, never
    wrong behavior. Do NOT shard tables where found=False changes the
    verdict (antispoof would drop, QoS would unshape): keep those
    chip-local by subscriber affinity (qos_kernel enforces this for
    itself)."""
    dhcp = geom.dhcp._replace(
        sub=geom.dhcp.sub._replace(axis=AXIS, n_shards=n),
        vlan=geom.dhcp.vlan._replace(axis=AXIS, n_shards=n),
        cid=geom.dhcp.cid._replace(axis=AXIS, n_shards=n),
    )
    return geom._replace(dhcp=dhcp)


@functools.lru_cache(maxsize=4)
def _sharded_step_jit(mesh: Mesh, geom: PipelineGeom, n: int,
                      table_impl: str = "xla"):
    """`table_impl` pins the device_lookup implementation (Pallas fused
    probe vs XLA cascade — ops.table.forced_impl) for this compiled
    mesh program, same discipline as Engine._pipeline_jit."""
    geom_sh = _sharded_geom(geom, n)

    has_garden = geom.garden is not None
    has_pppoe = geom.pppoe is not None
    has_edge = geom.tap is not None

    def local_step(tables1, upd1, pkt, length, fa, now_s, now_us):
        # shard_map hands each chip a leading dim of 1: drop it
        tables = jax.tree.map(lambda x: x[0], tables1)
        upd = jax.tree.map(lambda x: x[0], upd1)
        # host table deltas land here, inside the donated step — the
        # bpf_map_update_elem replacement, same as the single-chip Engine
        tables = _apply_all_updates(tables, upd)
        with table_mod.forced_impl(table_impl):
            res = pipeline_step(tables, pkt, length, fa, geom_sh,
                                now_s, now_us)
        new_tables1 = jax.tree.map(lambda x: x[None], res.tables)
        # global stats over ICI (per-CPU map -> one counter)
        dhcp_stats = jax.lax.psum(res.dhcp_stats, AXIS)
        nat_stats = jax.lax.psum(res.nat_stats, AXIS)
        qos_stats = jax.lax.psum(res.qos_stats, AXIS)
        spoof_stats = jax.lax.psum(res.spoof_stats, AXIS)
        out = (res.verdict, res.out_pkt, res.out_len, new_tables1,
               dhcp_stats, nat_stats, qos_stats, spoof_stats,
               res.nat_punt, res.spoof_violation)
        if has_garden:
            out += (jax.lax.psum(res.garden_stats, AXIS),)
        if has_pppoe:
            out += (jax.lax.psum(res.pppoe_stats, AXIS),)
        if has_edge:
            # mirror wids stay per-lane (the host retire extracts flagged
            # frames from its own shard region); stats psum like the rest
            out += (res.mirror, jax.lax.psum(res.edge_stats, AXIS))
        return out

    out_specs = (P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(), P(), P(), P(),
                 P(AXIS), P(AXIS))
    if has_garden:
        out_specs += (P(),)
    if has_pppoe:
        out_specs += (P(),)
    if has_edge:
        out_specs += (P(AXIS), P())
    sharded = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(), P()),
        out_specs=out_specs,
    )
    return jax.jit(sharded, donate_argnums=(0,))


@functools.lru_cache(maxsize=4)
def _sharded_dhcp_jit(mesh: Mesh, geom: PipelineGeom, n: int,
                      table_impl: str = "xla"):
    """Sharded DHCP-only program — the multichip OFFER latency fast lane.

    Mirrors Engine._dhcp_jit (reference hook-order parity: the DHCP fast
    path is its own XDP program) over the mesh: parse + hash-sharded
    3-tier lookup (all-to-all key/result exchange) + OFFER compose, with
    stats psum-reduced. Shares (and donates) the same dhcp table leaves
    as the fused sharded step, so the two programs can never fork state.
    """
    from bng_tpu.ops.dhcp import dhcp_fastpath
    from bng_tpu.ops.parse import parse_batch
    from bng_tpu.runtime.tables import apply_fastpath_updates

    dhcp_geom = _sharded_geom(geom, n).dhcp

    def local(dhcp1, upd1, pkt, length, now_s):
        dhcp = jax.tree.map(lambda x: x[0], dhcp1)
        upd = jax.tree.map(lambda x: x[0], upd1)
        dhcp = apply_fastpath_updates(dhcp, upd)
        with table_mod.forced_impl(table_impl):
            par = parse_batch(pkt, length)
            res = dhcp_fastpath(pkt, length, par, dhcp, dhcp_geom, now_s)
        return (jax.tree.map(lambda x: x[None], dhcp), res.is_reply,
                res.out_pkt, res.out_len, jax.lax.psum(res.stats, AXIS))

    sharded = _shard_map(
        local,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
    )
    return jax.jit(sharded, donate_argnums=(0,))


class ShardTelemetry:
    """Per-shard stage histograms + verdict/punt counters — the
    observability prerequisite for promoting the 8-chip dryrun to the
    serving path (ROADMAP [scale]).

    The sharded step is ONE program over the mesh, so host-visible
    per-shard latency attribution has exactly two honest quantities:
    the host `dispatch` cost (device_put + drain + enqueue) and the
    `device_wait` force — each recorded as one lap per step into every
    shard's histogram that had real lanes in the batch (an idle shard
    accumulates nothing; `total` = dispatch + wait). What DOES differ
    per shard is the work: verdict counts (tx/fwd/drop/pass), NAT
    egress-miss punts and antispoof violations are counted from each
    shard's lane region of the batch.

    PASS accounting (the serving-path split, ISSUE 12): now that the
    ring classifier owns the steering decision, wrong-shard punts are
    counted EXACTLY at retire — a PASS lane whose frame's affinity
    owner (FNV-1a32 of the subscriber key, the same function the ring
    steers with) is not the shard it executed on increments
    `missteers`; every other PASS lane (DHCP misses answered by the
    host server, NAT new-flow punts, unknown return traffic) is a
    legitimate slow-path punt and stays in `pass_total`. Callers that
    assemble their own batches without steering metadata (dryrun's raw
    step()) record no missteer verdicts, so for them `pass_total`
    remains the historical upper bound. DHCP hits are psum-reduced ON
    DEVICE (ops cross-shard answer) — the host folds the global
    counter.

    Histograms are telemetry/hist.py LatencyHists, so per-shard
    distributions merge into a fleet-wide view by plain counter
    addition — the same associative/commutative merge law the
    slow-path fleet's worker histograms use (test-pinned).
    """

    STAGES = ("dispatch", "device_wait", "total")
    VERDICT_NAMES = ("pass", "drop", "tx", "fwd")

    def __init__(self, n_shards: int, batch_per_shard: int):
        from bng_tpu.telemetry.hist import LatencyHist

        self.n = n_shards
        self.b = batch_per_shard
        self.hists = [{s: LatencyHist() for s in self.STAGES}
                      for _ in range(n_shards)]
        self.frames = np.zeros((n_shards,), dtype=np.int64)
        self.verdicts = np.zeros((n_shards, 4), dtype=np.int64)
        self.nat_punts = np.zeros((n_shards,), dtype=np.int64)
        self.missteers = np.zeros((n_shards,), dtype=np.int64)
        self.violations = np.zeros((n_shards,), dtype=np.int64)
        self.dhcp_replies = np.zeros((n_shards,), dtype=np.int64)
        self.psum_dhcp_hits = 0
        self.steps = 0

    def _active(self, length) -> np.ndarray:
        real = (np.asarray(length) > 0).reshape(self.n, self.b)
        self.frames += real.sum(axis=1)
        return real

    def _lap(self, shard_active: np.ndarray, dispatch_us: float,
             wait_us: float) -> None:
        for i in np.nonzero(shard_active)[0]:
            h = self.hists[int(i)]
            h["dispatch"].record(dispatch_us)
            h["device_wait"].record(wait_us)
            h["total"].record(dispatch_us + wait_us)
        self.steps += 1

    def record_fused(self, length, verdict, nat_punt, viol,
                     dhcp_hits: int, dispatch_us: float,
                     wait_us: float, missteer=None) -> None:
        real = self._active(length)
        v = np.asarray(verdict).reshape(self.n, self.b)
        for k in range(4):
            self.verdicts[:, k] += ((v == k) & real).sum(axis=1)
        if nat_punt is not None:
            self.nat_punts += (np.asarray(nat_punt).reshape(self.n, self.b)
                               & real).sum(axis=1)
        if missteer is not None:
            # exact wrong-shard punts, classified at retire by the
            # serving path (the steering-ring owner recomputation) —
            # a subset of the PASS verdicts counted above
            self.missteers += (np.asarray(missteer).reshape(self.n, self.b)
                               & real).sum(axis=1)
        if viol is not None:
            self.violations += (np.asarray(viol).reshape(self.n, self.b)
                                & real).sum(axis=1)
        self.psum_dhcp_hits += int(dhcp_hits)
        self._lap(real.any(axis=1), dispatch_us, wait_us)

    def record_dhcp(self, length, is_reply, dhcp_hits: int,
                    dispatch_us: float, wait_us: float) -> None:
        real = self._active(length)
        rep = np.asarray(is_reply).reshape(self.n, self.b) & real
        self.dhcp_replies += rep.sum(axis=1)
        self.verdicts[:, 2] += rep.sum(axis=1)  # replies TX
        self.verdicts[:, 0] += (real & ~rep).sum(axis=1)  # misses punt
        self.psum_dhcp_hits += int(dhcp_hits)
        self._lap(real.any(axis=1), dispatch_us, wait_us)

    def merged(self):
        """Fold every shard's histograms into one per-stage view —
        LatencyHist.merge (counter addition), the fleet's worker-
        histogram discipline, so order never matters."""
        from bng_tpu.telemetry.hist import LatencyHist

        out = {s: LatencyHist() for s in self.STAGES}
        for shard in self.hists:
            for s in self.STAGES:
                out[s].merge(shard[s])
        return out

    def snapshot(self) -> dict:
        """The MULTICHIP JSON / metrics payload: per-shard stage
        summaries + counters, the merged view, and the psum-reduced
        global DHCP hit counter."""
        per_shard = []
        for i in range(self.n):
            verdicts = {name: int(self.verdicts[i, k])
                        for k, name in enumerate(self.VERDICT_NAMES)}
            # one consistent accounting everywhere: "pass" is LEGIT
            # slow-path punts only, missteers are their own counter
            # (sum(per-shard pass) == pass_total by construction)
            verdicts["pass"] -= int(self.missteers[i])
            per_shard.append({
                "frames": int(self.frames[i]),
                "verdicts": verdicts,
                "nat_punts": int(self.nat_punts[i]),
                "missteers": int(self.missteers[i]),
                "violations": int(self.violations[i]),
                "dhcp_replies": int(self.dhcp_replies[i]),
                "stages": {s: self.hists[i][s].summary()
                           for s in self.STAGES if self.hists[i][s].n},
            })
        return {
            "shards": self.n,
            "steps": self.steps,
            "psum_dhcp_hits": self.psum_dhcp_hits,
            # legitimate slow-path punts: missteers (exact wrong-shard
            # punts, counted at retire by the serving path) are SPLIT
            # OUT of the PASS class. Raw-step callers that record no
            # missteer verdicts still read this as the historical
            # upper bound (see class docstring).
            "pass_total": int(self.verdicts[:, 0].sum()
                              - self.missteers.sum()),
            "missteer_total": int(self.missteers.sum()),
            "nat_punt_total": int(self.nat_punts.sum()),
            "per_shard": per_shard,
            "merged_stages": {s: h.summary()
                              for s, h in self.merged().items() if h.n},
        }


class ShardedCluster:
    """N-shard BNG over a 1D mesh. Control-plane writes route to owners."""

    def __init__(
        self,
        n_shards: int,
        mesh: Mesh | None = None,
        batch_per_shard: int = 64,
        sub_nbuckets: int = 256,
        vlan_nbuckets: int = 64,
        cid_nbuckets: int = 64,
        max_pools: int = 16,
        nat_sessions_nbuckets: int = 256,
        nat_ports_per_subscriber: int = 1024,
        qos_nbuckets: int = 256,
        spoof_nbuckets: int = 256,
        public_ips: list[int] | None = None,
        garden_enabled: bool = True,
        pppoe_enabled: bool = False,
        pppoe_nbuckets: int = 256,
        server_mac: bytes = b"\x02\xbb\x00\x00\x00\x01",
        edge_enabled: bool = False,
        edge_nbuckets: int = 256,
    ):
        self.n = n_shards
        self.mesh = mesh if mesh is not None else make_mesh(n_shards)
        self.b = batch_per_shard
        # geometry-identical clone recipe (the blue/green standby builder
        # and the checkpoint N==M fast path both need an empty twin);
        # mesh rides along so the standby's jit cache keys HIT the live
        # cluster's compiled programs instead of recompiling the mesh
        self._ctor_kwargs = dict(
            n_shards=n_shards, batch_per_shard=batch_per_shard,
            sub_nbuckets=sub_nbuckets, vlan_nbuckets=vlan_nbuckets,
            cid_nbuckets=cid_nbuckets, max_pools=max_pools,
            nat_sessions_nbuckets=nat_sessions_nbuckets,
            nat_ports_per_subscriber=nat_ports_per_subscriber,
            qos_nbuckets=qos_nbuckets, spoof_nbuckets=spoof_nbuckets,
            public_ips=list(public_ips) if public_ips else None,
            garden_enabled=garden_enabled, pppoe_enabled=pppoe_enabled,
            pppoe_nbuckets=pppoe_nbuckets, server_mac=server_mac,
            edge_enabled=edge_enabled, edge_nbuckets=edge_nbuckets)
        self.fastpath = [
            FastPathTables(sub_nbuckets=sub_nbuckets, vlan_nbuckets=vlan_nbuckets,
                           cid_nbuckets=cid_nbuckets, max_pools=max_pools)
            for _ in range(n_shards)
        ]
        base_pub = public_ips or [0xCB007100 + i for i in range(n_shards)]
        if len(base_pub) < n_shards:
            # downstream ring steering is by public-IP ownership: a public
            # IP shared across shards is not expressible (return traffic
            # could only reach one of them) — reject at construction, not
            # at make_ring time
            raise ValueError(
                f"need >= {n_shards} public IPs for {n_shards} shards "
                f"(got {len(base_pub)}): each shard's NAT pool must own "
                f"its public IPs exclusively")
        self.nat = [
            NATManager(public_ips=[base_pub[i]],
                       sessions_nbuckets=nat_sessions_nbuckets,
                       ports_per_subscriber=nat_ports_per_subscriber,
                       sub_nat_nbuckets=256)
            for i in range(n_shards)
        ]
        self.qos = [QoSTables(nbuckets=qos_nbuckets) for _ in range(n_shards)]
        self.spoof = [AntispoofTables(nbuckets=spoof_nbuckets) for _ in range(n_shards)]
        # device walled-garden gate, chip-local like NAT/QoS (membership is
        # keyed by subscriber private IP = the affinity key). Optional: a
        # disabled feature must cost zero per batch (garden_enabled=False
        # compiles the kernel out, same as Engine's garden=None)
        self.garden = ([GardenTables(nbuckets=spoof_nbuckets)
                        for _ in range(n_shards)] if garden_enabled else None)
        # PPPoE session tables, chip-local like NAT/QoS: by_sid AND by_ip
        # rows live on the subscriber's affinity shard — the ring steers
        # session DATA by the inner src IP (bngring.h steering spec), so
        # the decap always happens where the session row is
        self.pppoe = ([PPPoEFastPathTables(nbuckets=pppoe_nbuckets,
                                           server_mac=server_mac)
                       for _ in range(n_shards)] if pppoe_enabled else None)
        # edge protection tables (tap mirror + route rewrite), chip-local
        # like NAT/QoS: both key on the subscriber private IP = the
        # affinity key, so the ring already steers the matching lanes to
        # the shard holding the row. Optional: a cluster without warrants
        # or route policy compiles the stage out entirely.
        self.edge = ([EdgeTables(nbuckets=edge_nbuckets)
                      for _ in range(n_shards)] if edge_enabled else None)
        # host retire hook for MIRROR-flagged lanes (lane, frame, wid) —
        # the Engine.mirror_sink analog; wire a MirrorPump here
        self.mirror_sink = None
        self.geom = PipelineGeom(
            dhcp=self.fastpath[0].geom,
            nat=self.nat[0].geom,
            qos=self.qos[0].geom,
            spoof=self.spoof[0].geom,
            garden=self.garden[0].geom if garden_enabled else None,
            pppoe=self.pppoe[0].geom if pppoe_enabled else None,
            tap=self.edge[0].geom if edge_enabled else None,
            route=self.edge[0].geom if edge_enabled else None,
        )
        # table-probe impl resolved once at cluster construction (the
        # Engine discipline); dryrun_multichip stamps it into the
        # MULTICHIP-TELEMETRY line so a Pallas multichip artifact can
        # never read as an XLA one
        self.table_impl = table_mod.resolved_table_impl()
        self._step = _sharded_step_jit(self.mesh, self.geom, self.n,
                                       self.table_impl)
        self._dhcp_step = _sharded_dhcp_jit(self.mesh, self.geom, self.n,
                                            self.table_impl)
        self.tables = None  # lazily built on first step / sync()
        # ping-pong ring staging: the in-flight batch owns one buffer set
        # while the next assembles into the other (Engine._staging role)
        self._ring_bufs = [None, None]
        self._stage_idx = 0
        self._inflight = None  # process_ring_pipelined window
        # per-step psum deltas folded by process_ring (Engine.stats role)
        self.stats: dict = {"slow_errors": 0}
        # count AND log slow-path failures (rate-limited; Engine parity)
        from bng_tpu.utils.structlog import SlowPathErrorLog

        self._slow_err_log = SlowPathErrorLog("sharded")
        # per-shard stage histograms + psum-hit/punt counters (merged
        # like the fleet's worker histograms). dryrun_multichip stamps
        # the snapshot into its MULTICHIP JSON; a composition root that
        # owns a cluster AND a BNGMetrics exports it via
        # BNGMetrics.collect_sharded (the serving-path promotion's
        # scrape source — `bng run` has no cluster yet)
        self.telemetry = ShardTelemetry(n_shards, batch_per_shard)
        # NAT public-IP -> owner shard, resolved lazily for the missteer
        # classifier (ownership is fixed at construction: each shard's
        # NATManager keeps its public_ips for its lifetime)
        self._pub_owner_cache: dict[int, int] | None = None

    # ---- owner routing (must match device shard_owner) ----
    def dhcp_sub_shard(self, mac) -> int:
        key = mac_to_u64(mac) if not isinstance(mac, int) else mac
        lo, hi = split_u64(key)
        words = [np.array([hi], dtype=np.uint32), np.array([lo], dtype=np.uint32)]
        return int(shard_owner(words, self.n)[0])

    def dhcp_vlan_shard(self, s_tag: int, c_tag: int) -> int:
        words = [np.array([(s_tag << 16) | c_tag], dtype=np.uint32)]
        return int(shard_owner(words, self.n)[0])

    def dhcp_cid_shard(self, circuit_id: bytes) -> int:
        from bng_tpu.runtime.tables import pack_cid_host

        w = pack_cid_host(circuit_id)
        words = [w[i : i + 1] for i in range(8)]
        return int(shard_owner(words, self.n)[0])

    def affinity_shard_ip(self, private_ip: int) -> int:
        """Traffic-placement shard for a subscriber's private IP.

        MUST match the host ring's per-frame steering decision bit-for-bit
        (ring.shard_of / bngring.cpp bng_ring_shard_of: FNV-1a32 over the
        4 wire-order IP bytes, mod n): the ring steers the subscriber's
        upstream traffic here, so this is the only shard where chip-local
        NAT/QoS/antispoof state for the subscriber is ever consulted.
        Place that state via allocate_nat/set_qos/add_spoof_binding below
        rather than indexing self.nat[...] directly."""
        from bng_tpu.utils.net import fnv1a32

        return fnv1a32(int(private_ip).to_bytes(4, "big")) % self.n

    # ---- subscriber-affinity service placement (owner-shard routing) ----
    def allocate_nat(self, private_ip: int, now: int = 0):
        """Allocate a NAT port block on the subscriber's owner shard.

        Returns (owner_shard, allocation) — the pkg/pool/peer.go
        owner-or-forward role: the ring steers the subscriber's packets to
        owner_shard, so its NAT state lives there and nowhere else."""
        o = self.affinity_shard_ip(private_ip)
        return o, self.nat[o].allocate_nat(private_ip, now)

    def handle_new_flow(self, src_ip: int, *args, **kw):
        o = self.affinity_shard_ip(src_ip)
        return o, self.nat[o].handle_new_flow(src_ip, *args, **kw)

    def set_qos(self, private_ip: int, **kw) -> int:
        o = self.affinity_shard_ip(private_ip)
        self.qos[o].set_subscriber(private_ip, **kw)
        return o

    def add_spoof_binding(self, mac, ipv4: int, mode: int) -> int:
        o = self.affinity_shard_ip(ipv4)
        self.spoof[o].add_binding(mac, ipv4, mode)
        return o

    def set_gardened(self, private_ip: int, gardened: bool) -> int:
        if self.garden is None:
            raise RuntimeError("device garden gate disabled for this cluster")
        o = self.affinity_shard_ip(private_ip)
        self.garden[o].set_gardened(private_ip, gardened)
        return o

    def allow_garden_destination(self, ip: int, port: int = 0,
                                 proto: int = 0) -> None:
        if self.garden is None:
            raise RuntimeError("device garden gate disabled for this cluster")
        for g in self.garden:  # policy is global; membership is per-shard
            g.allow_destination(ip, port, proto)

    def pppoe_session_up(self, sess) -> int:
        """Publish an OPEN PPPoE session on its affinity shard (both
        directions: by_sid for upstream decap, by_ip for downstream
        encap — the ring steers both sides there)."""
        if self.pppoe is None:
            raise RuntimeError("PPPoE disabled for this cluster")
        o = self.affinity_shard_ip(sess.assigned_ip)
        self.pppoe[o].session_up(sess)
        return o

    def pppoe_session_down(self, event) -> int:
        if self.pppoe is None:
            raise RuntimeError("PPPoE disabled for this cluster")
        sess = getattr(event, "session", event)
        o = self.affinity_shard_ip(sess.assigned_ip)
        self.pppoe[o].session_down(event)
        return o

    # ---- edge protection (rows live on the subscriber's affinity shard) --
    # The same duck-typed surface EdgeTables exposes, with owner routing
    # in front, so InterceptTapProgram/RouteProgram target a cluster
    # exactly as they target a single engine's tables.
    def _edge_or_raise(self) -> list[EdgeTables]:
        if self.edge is None:
            raise RuntimeError("edge protection disabled for this cluster")
        return self.edge

    def arm_tap(self, private_ip: int, wid: int, filters=()) -> int:
        edge = self._edge_or_raise()
        o = self.affinity_shard_ip(private_ip)
        edge[o].arm_tap(private_ip, wid, filters)
        # filter rows are warrant-global: replicate to every shard so
        # any shard's dense copy (and shard 0's at checkpoint time) is
        # authoritative for the whole cluster
        for i, e in enumerate(edge):
            if i != o:
                e.set_tap_filters(wid, filters)
        return o

    def disarm_tap(self, private_ip: int) -> bool:
        edge = self._edge_or_raise()
        return edge[self.affinity_shard_ip(private_ip)].disarm_tap(private_ip)

    def get_tap(self, private_ip: int):
        edge = self._edge_or_raise()
        return edge[self.affinity_shard_ip(private_ip)].get_tap(private_ip)

    def set_tap_filters(self, wid: int, filters) -> int:
        """Filter rows replicate cluster-wide (one warrant may arm IPs on
        several shards); returns the smallest per-shard write count so a
        truncation anywhere reads as dropped."""
        edge = self._edge_or_raise()
        return min(e.set_tap_filters(wid, filters) for e in edge)

    def set_route(self, private_ip: int, nh_mac: bytes, table_id: int,
                  klass: int = 0) -> int:
        edge = self._edge_or_raise()
        o = self.affinity_shard_ip(private_ip)
        edge[o].set_route(private_ip, nh_mac, table_id, klass)
        return o

    def clear_route(self, private_ip: int) -> bool:
        edge = self._edge_or_raise()
        return edge[self.affinity_shard_ip(private_ip)].clear_route(private_ip)

    def get_route(self, private_ip: int):
        edge = self._edge_or_raise()
        return edge[self.affinity_shard_ip(private_ip)].get_route(private_ip)

    def tap_rows(self):
        """Cluster-wide tap rows, sorted by IP (the audit surface)."""
        edge = self._edge_or_raise()
        return sorted((kv for e in edge for kv in e.tap_rows()),
                      key=lambda kv: kv[0])

    def route_rows(self):
        edge = self._edge_or_raise()
        return sorted((kv for e in edge for kv in e.route_rows()),
                      key=lambda kv: kv[0])

    def pub_ip_map(self) -> dict[int, int]:
        """NAT public IP -> owner shard (downstream ring steering).

        Raises when one public IP is claimed by multiple shards: downstream
        steering is by-IP only, so shared ownership is not expressible — a
        silent last-shard-wins map would punt every return packet of the
        other shards' flows to the slow path."""
        owners: dict[int, int] = {}
        for s in range(self.n):
            for ip in self.nat[s].public_ips:
                if ip in owners and owners[ip] != s:
                    raise ValueError(
                        f"public IP {ip:#x} owned by shards {owners[ip]} and "
                        f"{s}: downstream steering needs exclusive ownership "
                        f"(give each shard distinct public_ips)")
                owners[ip] = s
        return owners

    def make_ring(self, nframes: int = 4096, frame_size: int = 2048,
                  depth: int = 1024, prefer_native: bool = True):
        """A host packet ring steering frames to this cluster's shards.

        The assemble_sharded layout (shard i's lanes at rows i*b..(i+1)*b)
        is exactly step()'s batch contract, so `ring -> assemble_sharded ->
        step -> complete` is the full multichip I/O loop."""
        from bng_tpu.runtime.ring import make_ring as _mk

        ring = _mk(nframes, frame_size, depth, prefer_native=prefer_native,
                   n_shards=self.n)
        for ip, s in self.pub_ip_map().items():
            if not ring.steer_pub_ip(ip, s):
                # an unregistered public IP would silently fall back to
                # dst-IP hashing — every return packet punts on a wrong
                # shard. A ring that cannot express the placement is a
                # configuration error, not a degraded mode.
                raise RuntimeError(
                    f"ring steering table rejected public IP {ip:#x} "
                    f"(capacity/probe bound); reduce public IPs per ring")
        return ring

    # ---- control-plane writes ----
    def add_pool_all(self, pool_id: int, network: int, prefix_len: int, gateway: int,
                     dns1: int = 0, dns2: int = 0, lease_time: int = 3600) -> None:
        for fp in self.fastpath:
            fp.add_pool(pool_id, network, prefix_len, gateway, dns1, dns2, lease_time)

    def set_server_config_all(self, mac, ip: int) -> None:
        for fp in self.fastpath:
            fp.set_server_config(mac, ip)

    def add_subscriber(self, mac, **kw) -> int:
        o = self.dhcp_sub_shard(mac)
        self.fastpath[o].add_subscriber(mac, **kw)
        return o

    def add_subscribers_bulk(self, macs_u64, pool_ids, ips, lease_expiries,
                             **kw) -> np.ndarray:
        """Reference-scale sharded build: split 1M+ subscribers by owner
        shard (vectorized shard_owner — the same mix the device lookup
        routes with) and bulk-insert each shard's slice. Returns the [N]
        owner-shard array. Follow with sync_tables() for a full upload
        (maps sized for 1M: /root/reference/bpf/maps.h:10)."""
        macs_u64 = np.asarray(macs_u64, dtype=np.uint64)
        hi = (macs_u64 >> np.uint64(32)).astype(np.uint32)
        lo = (macs_u64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        owners = np.asarray(shard_owner([hi, lo], self.n))
        pool_ids = np.broadcast_to(np.asarray(pool_ids, dtype=np.uint32),
                                   macs_u64.shape)
        ips = np.broadcast_to(np.asarray(ips, dtype=np.uint32), macs_u64.shape)
        lease_expiries = np.broadcast_to(
            np.asarray(lease_expiries, dtype=np.uint32), macs_u64.shape)
        for s in range(self.n):
            m = owners == s
            if m.any():
                self.fastpath[s].add_subscribers_bulk(
                    macs_u64[m], pool_ids=pool_ids[m], ips=ips[m],
                    lease_expiries=lease_expiries[m], **kw)
        return owners

    def add_vlan_subscriber(self, s_tag: int, c_tag: int, **kw) -> int:
        o = self.dhcp_vlan_shard(s_tag, c_tag)
        self.fastpath[o].add_vlan_subscriber(s_tag, c_tag, **kw)
        return o

    def add_circuit_id_subscriber(self, circuit_id: bytes, **kw) -> int:
        o = self.dhcp_cid_shard(circuit_id)
        self.fastpath[o].add_circuit_id_subscriber(circuit_id, **kw)
        return o

    def remove_subscriber(self, mac) -> bool:
        return self.fastpath[self.dhcp_sub_shard(mac)].remove_subscriber(mac)

    def remove_vlan_subscriber(self, s_tag: int, c_tag: int) -> bool:
        o = self.dhcp_vlan_shard(s_tag, c_tag)
        return self.fastpath[o].remove_vlan_subscriber(s_tag, c_tag)

    def remove_circuit_id_subscriber(self, circuit_id: bytes) -> bool:
        o = self.dhcp_cid_shard(circuit_id)
        return self.fastpath[o].remove_circuit_id_subscriber(circuit_id)

    def touch_lease(self, mac, lease_expiry: int) -> bool:
        o = self.dhcp_sub_shard(mac)
        return self.fastpath[o].touch_lease(mac, lease_expiry)

    def get_subscriber(self, mac):
        return self.fastpath[self.dhcp_sub_shard(mac)].get_subscriber(mac)

    # ---- device sync ----
    def _stack(self, arrs, spec):
        stacked = np.stack([np.asarray(a) for a in arrs])
        return jax.device_put(stacked, NamedSharding(self.mesh, spec))

    def _stack_per_shard(self, per_shard):
        """Stack a per-shard pytree list on the mesh axis (the one
        stacking/sharding convention — used by drains and sync)."""
        return jax.tree.map(lambda *xs: self._stack(xs, P(AXIS)), *per_shard)

    def _drain_with_resync(self, drain):
        """Run a make-updates drain; on the bulk-build "full upload"
        signal answer with one full re-upload and drain again — the
        Engine._drain_with_resync contract, so a bulk build on a live
        cluster does not brick the step loop. (The re-upload resets
        device-authoritative counters/tokens, as documented there.)"""
        try:
            return drain()
        except RuntimeError as e:
            if "full upload" not in str(e):
                raise
            self.sync_tables()
            return drain()

    def _drain_updates(self):
        """Per-shard bounded update batches, stacked on the mesh axis.

        Same mechanism as Engine._drain_updates: host writes since the
        last step ride into the donated jitted step as fixed-size deltas,
        so device-authoritative state (NAT session counters, QoS tokens)
        is never clobbered by a full re-upload.
        """
        return self._drain_with_resync(lambda: self._stack_per_shard([
            (
                self.fastpath[i].make_updates(),
                self.nat[i].make_updates(),
                self.qos[i].up.make_update(self.qos[i].update_slots),
                self.qos[i].down.make_update(self.qos[i].update_slots),
                self.antispoof_upd(i),
                jnp.asarray(self.spoof[i].ranges),
                jnp.asarray(self.spoof[i].config),
                *((self.garden[i].subscribers.make_update(
                       self.garden[i].update_slots),
                   jnp.asarray(self.garden[i].allowed))
                  if self.garden is not None else ()),
                *(self.pppoe[i].make_updates()
                  if self.pppoe is not None else ()),
                *(self.edge[i].make_updates()
                  if self.edge is not None else ()),
            )
            for i in range(self.n)
        ]))

    def _drain_fastpath(self):
        """Fastpath-only drain (the DHCP fast lane's update path)."""
        return self._drain_with_resync(lambda: self._stack_per_shard(
            [self.fastpath[i].make_updates() for i in range(self.n)]))

    def antispoof_upd(self, i: int):
        return self.spoof[i].bindings.make_update(self.spoof[i].update_slots)

    def sync_tables(self) -> None:
        """Full upload of every shard's tables, stacked on the mesh axis.

        Initial upload only: after the first step(), incremental writes
        flow through _drain_updates — re-syncing would reset
        device-authoritative counters/tokens.
        """
        per_shard = []
        for i in range(self.n):
            t = PipelineTables(
                dhcp=self.fastpath[i].device_tables(),
                nat=self.nat[i].device_tables(),
                qos_up=self.qos[i].up.device_state(),
                qos_down=self.qos[i].down.device_state(),
                spoof=self.spoof[i].bindings.device_state(),
                spoof_ranges=jnp.asarray(self.spoof[i].ranges),
                spoof_config=jnp.asarray(self.spoof[i].config),
                garden=(self.garden[i].subscribers.device_state()
                        if self.garden is not None else None),
                garden_allowed=(jnp.asarray(self.garden[i].allowed)
                                if self.garden is not None else None),
                pppoe_by_sid=(self.pppoe[i].by_sid.device_state()
                              if self.pppoe is not None else None),
                pppoe_by_ip=(self.pppoe[i].by_ip.device_state()
                             if self.pppoe is not None else None),
                pppoe_server_mac=(jnp.asarray(self.pppoe[i].server_mac)
                                  if self.pppoe is not None else None),
                tap=(self.edge[i].tap.device_state()
                     if self.edge is not None else None),
                tap_filters=(jnp.asarray(self.edge[i].tap_filters)
                             if self.edge is not None else None),
                tap_config=(jnp.asarray(self.edge[i].tap_config)
                            if self.edge is not None else None),
                route=(self.edge[i].route.device_state()
                       if self.edge is not None else None),
            )
            per_shard.append(t)
        self.tables = self._stack_per_shard(per_shard)

    def _dispatch_dhcp(self, pkt, length, now_s: int):
        """device_put + fastpath drain + donated sharded DHCP step.
        Outputs stay device futures (async half)."""
        if self.tables is None:
            self.sync_tables()
        sh = NamedSharding(self.mesh, P(AXIS))
        pkt_d = jax.device_put(pkt, sh)
        len_d = jax.device_put(length.astype(np.uint32), sh)
        upd = self._drain_fastpath()
        dhcp1, is_reply, out_pkt, out_len, stats = self._dhcp_step(
            self.tables.dhcp, upd, pkt_d, len_d, jnp.uint32(now_s))
        self.tables = self.tables._replace(dhcp=dhcp1)
        return is_reply, out_pkt, out_len, stats

    def _dispatch_fused(self, pkt, length, from_access, now_s: int,
                        now_us: int):
        """device_put + full drain + donated sharded step. The ONE owner
        of the drain-before-tables-read donation invariant; outputs stay
        device futures (async half)."""
        if self.tables is None:
            self.sync_tables()
        sh = NamedSharding(self.mesh, P(AXIS))
        pkt_d = jax.device_put(pkt, sh)
        len_d = jax.device_put(length.astype(np.uint32), sh)
        fa_d = jax.device_put(from_access, sh)
        # drain FIRST: a bulk-build resync rebinds self.tables, and Python
        # evaluates arguments left-to-right — reading self.tables before
        # the drain would pass (and donate) the stale pre-resync reference
        upd = self._drain_updates()
        raw = self._step(self.tables, upd, pkt_d, len_d, fa_d,
                         jnp.uint32(now_s), jnp.uint32(now_us))
        self.tables = raw[3]
        return raw

    def dhcp_step(self, pkt: np.ndarray, length: np.ndarray, now_s: int):
        """One sharded DHCP-only step (the control-batch fast lane).

        Same layout contract as step(); only the fastpath update drain
        runs, and the shared dhcp table leaves thread through donated —
        NAT/QoS/antispoof deltas stay queued for the next fused step.
        Returns {"is_reply", "out_pkt", "out_len", "dhcp_stats"}.
        """
        from bng_tpu.ops.dhcp import ST_HIT

        t0 = time.perf_counter()
        is_reply, out_pkt, out_len, stats = self._dispatch_dhcp(
            pkt, length, now_s)
        t1 = time.perf_counter()
        out = {
            "is_reply": np.asarray(is_reply),
            "out_pkt": out_pkt,
            "out_len": np.asarray(out_len),
            "dhcp_stats": np.asarray(stats),
        }
        t2 = time.perf_counter()
        self.telemetry.record_dhcp(
            length, out["is_reply"], int(out["dhcp_stats"][ST_HIT]),
            (t1 - t0) * 1e6, (t2 - t1) * 1e6)
        return out

    def process_ring(self, ring, now_s: int, now_us: int,
                     pkt_slot: int = 2048, slow_path=None,
                     violation_sink=None) -> int:
        """One multichip production beat: drain a STEERING ring through
        the sharded step and demux verdicts back (the single-chip analog
        is Engine.process_ring; the batch layout contract is
        assemble_sharded's per-shard lane ranges = step()'s rows).

        Engine-parity semantics:
        - all-control batches (ring-classified DHCP, FLAG_DHCP_CTRL on
          every real lane) ride the sharded DHCP-only fast lane;
        - per-step stats deltas fold into self.stats;
        - the slow queue is drained lane-aligned: NAT new-flow punts
          create the session on the subscriber's OWNER shard inline,
          everything else goes to `slow_path(frame) -> reply|None` with
          replies injected on the TX ring; spoof violations reach
          `violation_sink(lane, frame)`.

        The ring must be one of this cluster's (make_ring) so shard i's
        region holds shard i's subscribers; pkt_slot must cover the
        ring's frame size or oversize frames would be staged truncated.
        Returns frames processed."""
        if pkt_slot < ring.frame_size:
            raise ValueError(
                f"pkt_slot {pkt_slot} < ring frame_size {ring.frame_size}: "
                f"oversize frames would be silently truncated")
        if self._inflight is not None:
            # a pipelined batch holds one of its ring's assemble windows;
            # retire it — WITH this call's handlers, or its PASS frames
            # would pop from the slow ring and vanish (Engine parity)
            self.flush_pipeline(slow_path, violation_sink)
        pkt, length, flags = self._staging(self._stage_idx, pkt_slot)
        got = ring.assemble_sharded(pkt, length, flags)
        if not got:
            return 0
        entry = self._dispatch_ring_batch(ring, pkt, length, flags, got,
                                          now_s, now_us)
        self._retire(entry, slow_path, violation_sink)
        return got

    def process_ring_pipelined(self, ring, now_s: int, now_us: int,
                               pkt_slot: int = 2048, slow_path=None,
                               violation_sink=None) -> int:
        """Double-buffered multichip ring loop: dispatch batch k+1, THEN
        retire k — host demux overlaps device execution, the same
        two-window design as Engine.process_ring_pipelined (engine.py)
        which the single-chip path uses to hold latency at load. Requires
        ring backends tolerating two outstanding assemble..complete
        windows (bngring MAX_INFLIGHT=2; complete() retires FIFO in this
        loop's order). Call flush_pipeline() before reading final state.
        Returns frames retired this call."""
        if pkt_slot < ring.frame_size:
            raise ValueError(
                f"pkt_slot {pkt_slot} < ring frame_size {ring.frame_size}: "
                f"oversize frames would be silently truncated")
        prev = self._inflight
        self._inflight = None
        try:
            # 1. feed the mesh first: assemble into the buffer prev is NOT
            # using, so its frames stay intact until retirement
            idx = 1 - self._stage_idx
            pkt, length, flags = self._staging(idx, pkt_slot)
            got = ring.assemble_sharded(pkt, length, flags)
            if got:
                try:
                    entry = self._dispatch_ring_batch(
                        ring, pkt, length, flags, got, now_s, now_us)
                except BaseException:
                    # fail closed: the assemble opened a ring window that
                    # must not wedge. complete() retires FIFO, so the
                    # previous (older) window must retire FIRST.
                    from bng_tpu.runtime.ring import VERDICT_DROP

                    self._retire(prev, slow_path, violation_sink)
                    prev = None
                    B = self.n * self.b
                    ring.complete(np.full((B,), VERDICT_DROP, dtype=np.uint8),
                                  pkt, length, B)
                    raise
                self._inflight = entry
                self._stage_idx = idx
        finally:
            # 2. retire the previous batch (even if dispatch raised) while
            # the mesh runs the new one
            retired = self._retire(prev, slow_path, violation_sink)
        return retired

    def flush_pipeline(self, slow_path=None, violation_sink=None) -> int:
        """Retire any in-flight pipelined batch (shutdown/test barrier)."""
        entry = self._inflight
        self._inflight = None
        return self._retire(entry, slow_path, violation_sink)

    def _staging(self, idx: int, pkt_slot: int):
        B = self.n * self.b
        if self._ring_bufs[idx] is None or \
                self._ring_bufs[idx][0].shape != (B, pkt_slot):
            self._ring_bufs[idx] = (np.zeros((B, pkt_slot), dtype=np.uint8),
                                    np.zeros((B,), dtype=np.uint32),
                                    np.zeros((B,), dtype=np.uint32))
        return self._ring_bufs[idx]

    def _dispatch_ring_batch(self, ring, pkt, length, flags, got,
                             now_s: int, now_us: int):
        """Dispatch one assembled window to the mesh WITHOUT forcing the
        outputs (they stay device futures until _retire) — the async half
        of the beat, so a pipelined caller overlaps demux with compute."""
        from bng_tpu.runtime.ring import FLAG_DHCP_CTRL

        real = length > 0
        all_ctrl = bool(((flags[real] & FLAG_DHCP_CTRL) != 0).all())
        t0 = time.perf_counter()
        if all_ctrl:  # the multichip OFFER-latency fast lane
            is_reply, out_pkt, out_len, stats = self._dispatch_dhcp(
                pkt, length, now_s)
            out = ("dhcp", is_reply, out_pkt, out_len, stats)
        else:
            out = ("fused", self._dispatch_fused(
                pkt, length, (flags & 0x1) != 0, now_s, now_us))
        dispatch_us = (time.perf_counter() - t0) * 1e6
        return (ring, out, pkt, length, flags, got, now_s, dispatch_us)

    def _retire(self, entry, slow_path, violation_sink) -> int:
        """Force a dispatched window's outputs and demux verdicts back to
        its ring (the sync half of the beat)."""
        if entry is None:
            return 0
        from bng_tpu.ops.dhcp import ST_HIT
        from bng_tpu.runtime.ring import VERDICT_PASS, VERDICT_TX

        ring, out, pkt, length, flags, got, now_s, dispatch_us = entry
        B = self.n * self.b
        real = length > 0
        t0 = time.perf_counter()
        if out[0] == "dhcp":
            _, is_reply, out_pkt, out_len, stats = out
            is_reply_h = np.asarray(is_reply)
            verdict = np.where(is_reply_h, np.uint8(VERDICT_TX),
                               np.uint8(VERDICT_PASS))
            punt = np.zeros((B,), dtype=bool)
            viol = np.zeros((B,), dtype=bool)
            mir = None
            stats_h = np.asarray(stats)
            self._fold_stats(dhcp=stats_h)
            out_pkt_h = np.asarray(out_pkt)
            out_len_h = np.asarray(out_len).astype(np.uint32)
            wait_us = (time.perf_counter() - t0) * 1e6
            self.telemetry.record_dhcp(length, is_reply_h,
                                       int(stats_h[ST_HIT]),
                                       dispatch_us, wait_us)
        else:
            (verdict_d, out_pkt, out_len, _tables, dhcp_stats, nat_stats,
             qos_stats, spoof_stats, nat_punt, viol_d, *tails) = out[1]
            tails = list(tails)
            g_stats = tails.pop(0) if self.garden is not None else None
            p_stats = tails.pop(0) if self.pppoe is not None else None
            mir = tails.pop(0) if self.edge is not None else None
            e_stats = tails.pop(0) if self.edge is not None else None
            verdict = np.asarray(verdict_d).astype(np.uint8)
            punt = np.asarray(nat_punt)
            viol = np.asarray(viol_d)
            dhcp_h = np.asarray(dhcp_stats)
            self._fold_stats(dhcp=dhcp_h,
                             nat=np.asarray(nat_stats),
                             qos=np.asarray(qos_stats),
                             spoof=np.asarray(spoof_stats),
                             garden=(np.asarray(g_stats)
                                     if g_stats is not None else None),
                             pppoe=(np.asarray(p_stats)
                                    if p_stats is not None else None),
                             edge=(np.asarray(e_stats)
                                   if e_stats is not None else None))
            out_pkt_h = np.asarray(out_pkt)
            out_len_h = np.asarray(out_len).astype(np.uint32)
            wait_us = (time.perf_counter() - t0) * 1e6
            # exact missteer classification (ISSUE 12): a PASS lane that
            # is not a NAT new-flow punt and whose affinity owner is a
            # DIFFERENT shard punted because the steering put it in the
            # wrong region — count it apart from legit slow-path punts
            missteer = np.zeros((B,), dtype=bool)
            for lane in np.nonzero((verdict == VERDICT_PASS) & real
                                   & ~punt)[0]:
                owner = self._frame_affinity_owner(
                    bytes(pkt[lane, : int(length[lane])]),
                    int(flags[lane]))
                if owner is not None and owner != lane // self.b:
                    missteer[lane] = True
            self.telemetry.record_fused(length, verdict, punt, viol,
                                        int(dhcp_h[ST_HIT]),
                                        dispatch_us, wait_us,
                                        missteer=missteer)
        ring.complete(verdict, out_pkt_h, out_len_h, B)

        if violation_sink is not None:
            for lane in np.nonzero(viol)[0]:
                violation_sink(int(lane),
                               bytes(pkt[lane, : int(length[lane])]))
        if mir is not None and self.mirror_sink is not None:
            mirw = np.asarray(mir)
            for lane in np.nonzero((mirw != 0) & real)[0]:
                # interception observes the ORIGINAL ring bytes even on
                # lanes the verdict demux above dropped (Engine parity)
                self.mirror_sink(int(lane),
                                 bytes(pkt[lane, : int(length[lane])]),
                                 int(mirw[lane]))
        # slow drain, lane-aligned with the PASS lanes complete() queued
        for lane in np.nonzero((verdict == VERDICT_PASS) & real)[0]:
            got_f = ring.slow_pop()
            if got_f is None:
                break  # slow ring overflowed during complete()
            frame, fl = got_f
            try:
                if punt[lane]:
                    self._punt_new_flow(frame, int(now_s))
                elif slow_path is not None:
                    reply = slow_path(frame)
                    if reply is not None:
                        ring.tx_inject(reply, from_access=(fl & 0x1) != 0)
            except Exception as e:  # noqa: BLE001 — slow path is untrusted input
                self.stats["slow_errors"] += 1
                self._slow_err_log.report(e, path="ring", lane=int(lane))
        return got

    def _fold_stats(self, **deltas) -> None:
        for k, v in deltas.items():
            if v is None:
                continue
            acc = self.stats.get(k)
            if acc is None:
                self.stats[k] = np.asarray(v, dtype=np.uint64).copy()
            else:
                acc += np.asarray(v, dtype=np.uint64)

    def _frame_affinity_owner(self, frame: bytes, flags: int) -> int | None:
        """Affinity owner shard of a frame's chip-local state, or None
        when no shard owns it (DHCP control, PPPoE control, non-IPv4,
        return traffic for an unregistered public IP — all of which any
        shard's slow path answers authoritatively). Mirrors the ring
        steering spec (runtime/ring.py shard_of / bngring.h): upstream
        by FNV-1a32(src IP), PPPoE session DATA by the inner src IP,
        downstream by NAT public-IP ownership."""
        from bng_tpu.runtime.ring import FLAG_DHCP_CTRL, FLAG_FROM_ACCESS
        from bng_tpu.utils.net import fnv1a32

        if (flags & FLAG_DHCP_CTRL) or len(frame) < 14:
            return None
        off = 12
        et = (frame[off] << 8) | frame[off + 1]
        for _ in range(2):
            if et not in (0x8100, 0x88A8):
                break
            off += 4
            if len(frame) < off + 2:
                return None
            et = (frame[off] << 8) | frame[off + 1]
        off += 2  # L3 start
        if et == 0x0800 and len(frame) >= off + 20 and (frame[off] >> 4) == 4:
            if flags & FLAG_FROM_ACCESS:
                return fnv1a32(frame[off + 12 : off + 16]) % self.n
            dst = int.from_bytes(frame[off + 16 : off + 20], "big")
            if self._pub_owner_cache is None:
                self._pub_owner_cache = self.pub_ip_map()
            return self._pub_owner_cache.get(dst)
        if (et == 0x8864 and (flags & FLAG_FROM_ACCESS)
                and len(frame) >= off + 8 + 20
                and frame[off] == 0x11 and frame[off + 1] == 0
                and ((frame[off + 6] << 8) | frame[off + 7]) == 0x0021
                and (frame[off + 8] >> 4) == 4):
            return fnv1a32(frame[off + 8 + 12 : off + 8 + 16]) % self.n
        return None

    def _punt_new_flow(self, frame: bytes, now: int) -> None:
        """Device egress-miss: create the session on the OWNER shard
        (Engine._punt_new_flow with owner routing in front)."""
        from bng_tpu.control import packets as P
        from bng_tpu.runtime.engine import Engine

        if self.pppoe is not None:
            # the punt carries the ORIGINAL ring bytes — for a PPPoE
            # subscriber still session-framed; strip to the inner IPv4
            # view or the flow permanently blackholes (Engine parity)
            frame = Engine._strip_pppoe_host(frame)
        try:
            d = P.decode(frame)
        except Exception:
            return
        if d.ethertype != 0x0800:
            return
        src_port = d.icmp_id if d.proto == 1 else d.src_port
        dst_port = 0 if d.proto == 1 else d.dst_port
        self.handle_new_flow(d.src_ip, d.dst_ip, src_port, dst_port,
                             d.proto, len(frame), now)

    def step(self, pkt: np.ndarray, length: np.ndarray, from_access: np.ndarray,
             now_s: int, now_us: int):
        """One sharded pipeline step.

        pkt: [N*b, L] uint8 (shard i's lanes at rows i*b..(i+1)*b).
        Returns (verdict, out_pkt, out_len, stats tuple...) — batch-sharded
        outputs are fetched to host.
        """
        from bng_tpu.ops.dhcp import ST_HIT

        t0 = time.perf_counter()
        out = self._dispatch_fused(pkt, length, from_access, now_s, now_us)
        t1 = time.perf_counter()
        (verdict, out_pkt, out_len, _new_tables, dhcp_stats, nat_stats,
         qos_stats, spoof_stats, nat_punt, viol, *tails) = out
        tails = list(tails)
        garden_stats = [tails.pop(0)] if self.garden is not None else []
        pppoe_stats = [tails.pop(0)] if self.pppoe is not None else []
        edge_out = list(tails[:2]) if self.edge is not None else []
        res = {
            "verdict": np.asarray(verdict),
            "out_pkt": out_pkt,
            "out_len": np.asarray(out_len),
            "dhcp_stats": np.asarray(dhcp_stats),
            "nat_stats": np.asarray(nat_stats),
            "qos_stats": np.asarray(qos_stats),
            "spoof_stats": np.asarray(spoof_stats),
            "nat_punt": np.asarray(nat_punt),
            "violation": np.asarray(viol),
            **({"garden_stats": np.asarray(garden_stats[0])}
               if garden_stats else {}),
            **({"pppoe_stats": np.asarray(pppoe_stats[0])}
               if pppoe_stats else {}),
            **({"mirror": np.asarray(edge_out[0]),
                "edge_stats": np.asarray(edge_out[1])}
               if edge_out else {}),
        }
        t2 = time.perf_counter()
        self.telemetry.record_fused(
            length, res["verdict"], res["nat_punt"], res["violation"],
            int(res["dhcp_stats"][ST_HIT]),
            (t1 - t0) * 1e6, (t2 - t1) * 1e6)
        return res

    # ---- serving-path operations (quiesce / checkpoint / swap / expiry) --

    def quiesce(self) -> int:
        """Drain barrier for the sharded serving loop: retire any
        in-flight pipelined window, then block until the mesh table
        state has materialized — after this no scatter is in flight, so
        a checkpoint or swap can read host/device state without
        interleaving with an update (Engine.quiesce parity). Returns
        frames retired. Callers that hold a ring's slow queue must
        flush through process_ring/flush_pipeline with handlers first."""
        n = self.flush_pipeline()
        if self.tables is not None:
            jax.block_until_ready(jax.tree_util.tree_leaves(self.tables))
        return n

    def resync_tables(self) -> None:
        """Full re-upload of every shard's host tables (the bulk-build /
        post-restore heal path — Engine.resync_tables parity). Resets
        device-authoritative words; fold first when they matter."""
        self.sync_tables()

    def fetch_session_vals(self, shard: int) -> np.ndarray:
        """One shard's device-authoritative NAT session rows (counters +
        last_seen) — the per-shard slice of the mesh-stacked array."""
        return np.asarray(self.tables.nat.sessions.vals)[shard]

    def fold_device_authoritative(self) -> None:
        """Pull the device-WRITTEN words back into every shard's host
        mirrors (NAT session counters/last_seen, QoS token buckets) —
        the pre-checkpoint fetch, per shard. Engine parity including the
        uploaded-mask discipline: host rows the bounded drain has not
        shipped yet stay authoritative. Call behind quiesce()."""
        from bng_tpu.ops.qtable import QW_FLAGS, QW_LAST_US, QW_TOKENS
        from bng_tpu.runtime.engine import Engine

        if self.tables is None:
            return
        sess_dev = np.asarray(self.tables.nat.sessions.vals)
        qos_up_dev = np.asarray(self.tables.qos_up.rows)
        qos_down_dev = np.asarray(self.tables.qos_down.rows)
        for i in range(self.n):
            sessions = self.nat[i].sessions
            mask = Engine._uploaded_mask(sessions,
                                         sessions.used.astype(bool))
            sessions.vals[mask] = sess_dev[i][mask]
            for host, dev_rows in ((self.qos[i].up, qos_up_dev[i]),
                                   (self.qos[i].down, qos_down_dev[i])):
                live = Engine._uploaded_mask(
                    host, (host.rows[:, QW_FLAGS] & 1) != 0)
                host.rows[live, QW_TOKENS] = dev_rows[live, QW_TOKENS]
                host.rows[live, QW_LAST_US] = dev_rows[live, QW_LAST_US]

    def expire(self, now: int) -> int:
        """NAT session expiry sweep against each shard's device-
        authoritative last-seen words (Engine.expire per shard)."""
        total = 0
        for i in range(self.n):
            dev = (self.fetch_session_vals(i)
                   if self.tables is not None else None)
            total += self.nat[i].expire_sessions(int(now), device_vals=dev)
        return total

    def pending_dirty(self) -> int:
        """Dirty slots across every shard's drained host mirror — 0
        means the mesh device chain is current (Engine.pending_dirty
        parity; the auditor's drain-completion test)."""
        total = 0
        for i in range(self.n):
            total += self.fastpath[i].dirty_count()
            total += sum(t.dirty_count() for t in (
                self.nat[i].sessions, self.nat[i].reverse,
                self.nat[i].sub_nat))
            total += self.qos[i].up.dirty_count()
            total += self.qos[i].down.dirty_count()
            total += self.spoof[i].bindings.dirty_count()
            if self.garden is not None:
                total += self.garden[i].subscribers.dirty_count()
            if self.pppoe is not None:
                total += self.pppoe[i].by_sid.dirty_count()
                total += self.pppoe[i].by_ip.dirty_count()
            if self.edge is not None:
                total += self.edge[i].dirty_count()
        return total

    def shard_components(self, i: int) -> dict:
        """One shard's host authorities, keyed the way the checkpoint
        codec names components (runtime/checkpoint.py sharded save /
        restore both walk this)."""
        out = {"fastpath": self.fastpath[i], "nat": self.nat[i],
               "qos": self.qos[i], "antispoof": self.spoof[i]}
        if self.garden is not None:
            out["garden"] = self.garden[i]
        if self.pppoe is not None:
            out["pppoe"] = self.pppoe[i]
        if self.edge is not None:
            out["edge"] = self.edge[i]
        return out

    def clone_empty(self, n_shards: int | None = None) -> "ShardedCluster":
        """A fresh, EMPTY cluster with identical per-shard geometry —
        the blue/green standby and the checkpoint re-shard target. Same
        n (default) reuses this cluster's mesh so the jit caches hit;
        a different n builds its own mesh."""
        kw = dict(self._ctor_kwargs)
        if n_shards is not None and n_shards != self.n:
            kw["n_shards"] = n_shards
            # per-shard public IPs regenerate for the new topology when
            # the original list was auto-derived (None); an explicit
            # list must still cover the new shard count
            if kw["public_ips"] is not None \
                    and len(kw["public_ips"]) < n_shards:
                raise ValueError(
                    f"cannot re-shard to {n_shards} shards: only "
                    f"{len(kw['public_ips'])} public IPs configured")
            return ShardedCluster(**kw)
        return ShardedCluster(mesh=self.mesh, **kw)

    def stats_summary(self) -> dict:
        """Aggregate serving counters for `bng run` stats() — the
        engine-stats analog of the sharded path."""
        t = self.telemetry
        return {
            "shards": self.n,
            "steps": t.steps,
            "frames": int(t.frames.sum()),
            "tx": int(t.verdicts[:, 2].sum()),
            "fwd": int(t.verdicts[:, 3].sum()),
            "dropped": int(t.verdicts[:, 1].sum()),
            # legit slow-path punts only — missteers are split out
            # (same accounting as snapshot()'s pass_total)
            "passed": int(t.verdicts[:, 0].sum() - t.missteers.sum()),
            "missteers": int(t.missteers.sum()),
            "nat_punts": int(t.nat_punts.sum()),
            "psum_dhcp_hits": t.psum_dhcp_hits,
            "slow_errors": int(self.stats.get("slow_errors", 0)),
        }


class ShardedFastPathSink:
    """FastPathTables WRITE facade over a ShardedCluster: the DHCP
    server, PoolManager and composition root mutate 'the fast path'
    through the one interface they already use, and every row lands on
    its owner shard (broadcast for pool/server config — those are
    replicated cluster-wide). The single-writer discipline is preserved:
    this object routes, the per-shard FastPathTables stay the authority,
    and deltas drain through each shard's bounded update batch.

    Accepts a cluster OR a zero-arg resolver returning one: long-lived
    holders (the DHCP server, built once at app construction) must pass
    a resolver reading the composition root's live reference, or a
    blue/green swap would strand every later write on the RETIRED
    cluster while the standby serves."""

    def __init__(self, cluster):
        self._cluster = cluster

    @property
    def cluster(self) -> ShardedCluster:
        c = self._cluster
        return c() if callable(c) else c

    # pool/server config is global: broadcast (add_pool_all discipline)
    def add_pool(self, *a, **kw) -> None:
        for fp in self.cluster.fastpath:
            fp.add_pool(*a, **kw)

    def remove_pool(self, pool_id: int) -> None:
        for fp in self.cluster.fastpath:
            fp.remove_pool(pool_id)

    def set_server_config(self, mac, ip: int) -> None:
        self.cluster.set_server_config_all(mac, ip)

    # subscriber rows route to their owner shard
    def add_subscriber(self, mac, **kw) -> None:
        self.cluster.add_subscriber(mac, **kw)

    def remove_subscriber(self, mac) -> bool:
        return self.cluster.remove_subscriber(mac)

    def add_vlan_subscriber(self, s_tag: int, c_tag: int, **kw) -> None:
        self.cluster.add_vlan_subscriber(s_tag, c_tag, **kw)

    def remove_vlan_subscriber(self, s_tag: int, c_tag: int) -> bool:
        return self.cluster.remove_vlan_subscriber(s_tag, c_tag)

    def add_circuit_id_subscriber(self, circuit_id: bytes, **kw) -> None:
        self.cluster.add_circuit_id_subscriber(circuit_id, **kw)

    def remove_circuit_id_subscriber(self, circuit_id: bytes) -> bool:
        return self.cluster.remove_circuit_id_subscriber(circuit_id)

    def touch_lease(self, mac, lease_expiry: int) -> bool:
        return self.cluster.touch_lease(mac, lease_expiry)

    def get_subscriber(self, mac):
        return self.cluster.get_subscriber(mac)
