"""Rendezvous (HRW) hashing + deterministic hashring allocation.

The reference's two scale-out placement mechanisms (SURVEY.md §2.3):

- Rendezvous hashing across peer nodes with ranked failover
  (pkg/pool/peer.go:723-790: FNV-1a hashCombine of node+key, owner =
  highest score; rendezvousRanked for failover order).
- Deterministic hashring IP allocation: candidate = hash(subscriberID +
  attempt) % poolSize with bounded linear probing
  (pkg/nexus/client.go:487-577, hashString FNV-1a :694).

Here they place subscribers/flows on chips and shards instead of nodes;
the same functions serve the control plane (peer pools, Nexus clients).
"""

from __future__ import annotations

from bng_tpu.utils.net import fnv1a32


def hash_combine(node: str, key: str) -> int:
    """FNV-1a over node+key (parity: peer.go:777-790)."""
    return fnv1a32((node + ":" + key).encode())


def rendezvous_owner(nodes: list[str], key: str) -> str | None:
    """Highest-random-weight owner (parity: rendezvousHash, peer.go:723-745)."""
    best, best_score = None, -1
    for n in nodes:
        s = hash_combine(n, key)
        if s > best_score or (s == best_score and (best is None or n < best)):
            best, best_score = n, s
    return best


def rendezvous_ranked(nodes: list[str], key: str) -> list[str]:
    """All nodes ranked by HRW score — failover order
    (parity: rendezvousRanked, peer.go:747-776)."""
    return [n for _, n in sorted(((hash_combine(n, key), n) for n in nodes),
                                 key=lambda t: (-t[0], t[1]))]


def hashring_allocate(
    subscriber_id: str,
    pool_size: int,
    is_free,  # Callable[[int], bool]
    max_attempts: int = 1024,
) -> int | None:
    """Deterministic hash-based index allocation with linear probing.

    Parity: AllocateIPForSubscriber (pkg/nexus/client.go:487-577):
    candidate = hash(subscriberID + ":" + attempt) % size, then accept the
    first free candidate. Deterministic across nodes: two BNGs allocating
    for the same subscriber pick the same address without coordination.
    """
    if pool_size <= 0:
        return None
    attempts = min(max_attempts, pool_size)
    for attempt in range(attempts):
        idx = fnv1a32(f"{subscriber_id}:{attempt}".encode()) % pool_size
        if is_free(idx):
            return idx
    return None
