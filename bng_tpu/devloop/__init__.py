"""Device-resident express serving loop (ISSUE 18).

The AOT express lane (ISSUE 13) made the device program minimal, but
the host still touches the device once per admission batch — ~1.1 ms of
dispatch ceremony (update drain, staging upload, executable call) per
batch on CPU, 20x the 50us OFFER budget before a single device cycle
runs. This package stops dispatching per batch:

- ``ring``   — the descriptor ring: fixed-geometry [k, B, XD_WORDS]
  uint32 express rows staged host-side in cycling double buffers, with
  device-resident head/tail/seq cursors threaded dispatch-to-dispatch.
- ``kernel`` — the persistent express megakernel: ONE AOT-compiled
  program that drains up to k ring slots per invocation, running the
  probe-only OFFER cascade (ops/express.express_verdicts — the PR-13
  program is the bit-identity oracle) per slot and streaming verdict
  rows back over the donated ring (the completion ring aliases the
  descriptor ring).
- ``host``   — the pump: fills slots from closed express batches,
  dispatches once per k batches (or deadline/flush with a partial
  fill), retires completions asynchronously through the PR-13 wire
  template patch-in, and falls back LOUDLY to the per-batch AOT lane
  on any geometry miss or injected fault.

Selected per scheduler via ``BNG_EXPRESS_LOOP=aot|devloop|auto``
(SchedulerConfig.express_loop); the default stays ``aot`` until the
devloop cohort has baselined in the perf ledger — the BNG_HOST_PATH /
BNG_TABLE_IMPL flip-after-measurement discipline.
"""

from bng_tpu.devloop.ring import (CUR_EPOCH, CUR_SEQ, CUR_TAIL, CUR_WORDS,
                                  DescriptorRing)

__all__ = ["CUR_EPOCH", "CUR_SEQ", "CUR_TAIL", "CUR_WORDS",
           "DescriptorRing"]
