"""The devloop host pump (ISSUE 18): k admission batches, one dispatch.

The pump is the express lane's serving strategy when
``BNG_EXPRESS_LOOP`` resolves to ``devloop``: the scheduler hands it
every closed express batch, and instead of dispatching per batch it
stages the batch's descriptor rows into the next slot of a
`DescriptorRing` (devloop/ring.py). The device is touched once per ring
— when the ring fills, on the ring deadline, or at flush/quiesce with a
partial fill — through the persistent megakernel (devloop/kernel.py),
and completions retire asynchronously per slot through the SAME wire
template patch-in path the per-batch AOT lane uses
(`TieredScheduler._retire_express_aot`), so reply bytes are identical
by construction, not by parallel implementation.

Telemetry attribution (the Dapper discipline — every us has a stage):

- ``lane_wait``   batch enqueue -> close (unchanged, per batch)
- ``loop_fill``   descriptor rows -> ring slot (per batch, measured)
- ``loop_wait``   slot staged -> ring dispatch (per batch, measured —
                  the latency the k-amortization trades away; the
                  ring deadline bounds it)
- ``dispatch``    the ONE megakernel dispatch, amortized per batch
                  (dur / slots): per-batch histograms stay comparable
                  with the per-batch lane, and sums are conserved
- ``loop_retire`` ring force + per-slot demux bookkeeping, amortized
                  per batch the same way

Fallbacks are Gray-Failure-loud (PAPERS.md): a megakernel geometry
miss, a compile failure at setup, or an injected
``devloop.dispatch`` fault re-dispatches every staged slot through the
per-batch AOT path AND counts `bng_express_fallback_total{reason}` +
fires the `express_fallback` flight-recorder trigger — serving never
stops, but a degraded loop can never masquerade as a healthy one.

Quiesce/drain contract: `flush()` dispatches any partial ring and
retires every in-flight ring, so after the scheduler's flush the ring
is empty, the cursor handle is live (nothing donated ahead of it) and
`audit()` can prove the device cursors agree with the host's slot
accounting — a snapshot/checkpoint never observes a half-retired ring.
"""

from __future__ import annotations

import concurrent.futures
from concurrent.futures import ThreadPoolExecutor
from typing import NamedTuple

import numpy as np

from bng_tpu.chaos.faults import fault_point
from bng_tpu.devloop.ring import CUR_SEQ, DescriptorRing
from bng_tpu.ops.dhcp import NSTATS
from bng_tpu.runtime.engine import Engine, _ExpressAotResult
from bng_tpu.runtime.lanes import (CLOSE_DEADLINE, CLOSE_FLUSH,
                                   CompletionRing, InflightEntry,
                                   LANE_EXPRESS)
from bng_tpu.telemetry import spans as tele


class _RingInflight(NamedTuple):
    """One megakernel dispatch in flight: the dispatch-worker future
    (resolving to a kernel.DevloopResult) plus the per-slot host retire
    metadata the device never sees."""

    fut: object            # Future[kernel.DevloopResult]
    slots: list            # [n_slots] lists of PendingFrame
    tokens: list           # [n_slots] telemetry batch tokens
    reasons: list          # [n_slots] batch close reasons
    dispatch_t: float
    meta: tuple            # dispatch-epoch (pools, server) snapshot


class DevloopPump:
    """Owns one DescriptorRing + its in-flight completion ring on
    behalf of a TieredScheduler's express lane."""

    def __init__(self, sched, k: int, depth: int = 2,
                 max_wait_us: float | None = None):
        self.sched = sched
        self.ring = DescriptorRing(k, sched.express.cfg.batch, depth)
        self._inflight = CompletionRing(depth)
        # ring deadline: a partial ring may wait at most this long after
        # its OLDEST slot was staged (defaults to the express lane's own
        # close deadline — the loop at most doubles the worst-case wait)
        self.max_wait_us = (max_wait_us if max_wait_us is not None
                            else sched.cfg.express_max_wait_us)
        self.dispatches = 0
        self.batches = 0
        self.fallback_slots = 0
        # The dispatch worker: ONE thread that only ever runs the pure
        # executable call (Engine.call_devloop_aot). On a real TPU the
        # runtime dispatches asynchronously and the worker merely waits;
        # on CPU XLA may run the computation inline in whichever thread
        # calls the executable, and whether the caller blocks is an OS
        # scheduling lottery (observed flipping per process on 1-core
        # hosts). Routing the call through the worker makes the serving
        # thread's dispatch cost deterministic — prepare + submit — on
        # every backend. Single worker => FIFO => the chain/cursor
        # threading below needs no locks.
        self._pool = None
        self._last_fut = None
        # Worker-local double buffers: the dhcp chain and cursor handle
        # the NEXT ring call consumes. The engine's published
        # tables.dhcp stays live and readable (nothing donated out from
        # under it) while rings are in flight; retires publish each
        # ring's output chain back monotonically (engine lags by at
        # most the in-flight depth, the bulk lane's read-replica
        # staleness class). None = seed from engine at next dispatch.
        self._dev_chain = None
        self._dev_cur = self.ring.cursors

    # -- dispatch worker --------------------------------------------------

    def _submit(self, fn, *args):
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="bng-devloop")
        fut = self._pool.submit(fn, *args)
        self._last_fut = fut
        return fut

    def _join(self) -> None:
        """Wait for the dispatch worker to go idle (errors surface at
        the owning ring's retire, not here)."""
        if self._last_fut is not None:
            concurrent.futures.wait([self._last_fut])

    def close(self) -> None:
        """Release the dispatch worker thread (engine adopt replaces the
        pump; the old one must not leak its thread)."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- fill (one closed express batch -> one ring slot) ----------------

    def add_batch(self, pend: list, now: float, reason: str) -> int:
        """Stage one closed express batch into the ring; dispatches the
        megakernel when the ring fills. Returns frames retired as a
        side effect of the in-flight ring overflowing its depth."""
        tok = tele.begin_batch(tele.LANE_EXPRESS_L, len(pend))
        if tok is not None:
            tele.observe(tele.LANE_WAIT, (now - pend[0].enq_t) * 1e6, tok)
        t0 = tele.t()
        rows = [p.desc.words for p in pend if p.desc is not None]
        idxs = ([i for i, p in enumerate(pend) if p.desc is not None]
                if rows else [])
        self.ring.fill_slot(rows, idxs, pend, tok, now)
        tele.lap(tele.LOOP_FILL, t0, tok)
        self.batches += 1
        if self.ring.head >= self.ring.k:
            return self._dispatch(now, reason)
        return 0

    # -- the beat ---------------------------------------------------------

    def poll(self, now: float) -> int:
        """Opportunistic retire of finished rings + the ring deadline
        close (a partial ring must not strand its slots past the loop
        deadline)."""
        retired = 0
        for entry in self._inflight.pop_ready(self._ready):
            retired += self._retire(entry)
        oldest = self.ring.oldest_fill_t
        if (oldest is not None
                and (now - oldest) * 1e6 >= self.max_wait_us):
            retired += self._dispatch(now, CLOSE_DEADLINE)
        return retired

    def flush(self, now: float) -> int:
        """Ship the partial ring and retire EVERYTHING in flight — the
        scheduler's flush/quiesce barrier. Afterwards the ring is empty
        and the cursor handle is live (audit() is legal)."""
        retired = 0
        if self.ring.head:
            retired += self._dispatch(now, CLOSE_FLUSH)
        while True:
            entry = self._inflight.pop_oldest()
            if entry is None:
                break
            retired += self._retire(entry)
        return retired

    @staticmethod
    def _ready(entry: _RingInflight) -> bool:
        if not entry.fut.done():
            return False
        if entry.fut.exception() is not None:
            return True  # retire now; the error surfaces there
        is_ready = getattr(entry.fut.result().blocks, "is_ready", None)
        return bool(is_ready()) if is_ready is not None else True

    # -- dispatch ---------------------------------------------------------

    def _run_ring(self, exe, prepared, device):
        """DISPATCH WORKER thread: the pure executable call plus the
        worker-local chain/cursor threading. Touches no engine or
        scheduler state — the main thread owns every drain, fault point,
        telemetry write and `engine.tables` publish."""
        res = Engine.call_devloop_aot(
            exe, self._dev_chain, self._dev_cur, prepared, device)
        self._dev_chain = res.dhcp_tables
        self._dev_cur = res.cursors
        return res

    def _barrier(self) -> int:
        """Retire every in-flight ring and idle the worker — the point
        past which the engine's published chain is the newest and no
        stale publish can follow. Required before any OTHER writer of
        the authoritative chain runs (per-batch fallback dispatch,
        update-slot-overflow resync)."""
        retired = 0
        while True:
            entry = self._inflight.pop_oldest()
            if entry is None:
                break
            retired += self._retire(entry)
        self._join()
        return retired

    def _dispatch(self, now: float, reason: str) -> int:
        sched = self.sched
        eng = sched.engine
        buf, n_slots, slots, tokens, fill_ts = self.ring.take()
        if n_slots == 0:
            return 0
        exe = (eng.devloop_aot(self.ring.k, self.ring.batch,
                               sched._express_dev)
               if sched._aot_ready else None)
        fp = fault_point("devloop.dispatch")
        if fp is not None and fp.kind == "fail":
            exe = None  # chaos: injected mid-storm loop fallback
        if exe is None:
            # LOUD fallback: every staged slot re-dispatches through the
            # per-batch AOT/jit path — service degrades to PR-13
            # latency, consistency and reply bytes are unchanged. The
            # direct path writes the authoritative chain itself, so the
            # loop drains first: in-flight rings publish and the worker
            # idles before the per-batch dispatches run.
            retired = self._barrier()
            self._dev_chain = None  # re-seed from engine next dispatch
            sched._note_fallback(
                "devloop_miss",
                f"no compiled megakernel for k={self.ring.k} "
                f"batch={self.ring.batch} impl={eng.table_impl}"
                + (" (injected)" if fp is not None else "")
                + f": {n_slots} slot(s) served per-batch")
            self.fallback_slots += n_slots
            for tok in tokens:
                tele.cancel_batch(tok)  # the direct path opens its own
            for pend, slot_reason in zip(slots, [reason] * n_slots):
                retired += sched._dispatch_express_direct(
                    pend, now, slot_reason)
            return retired
        retired = 0
        # resync barrier: a drain that would overflow the delta slots
        # rebuilds the device chain from full host state; everything in
        # flight must publish BEFORE that chain seeds the worker, or a
        # stale pre-resync chain could publish over it at retire.
        if (self._dev_chain is not None
                and eng.fastpath.dirty_count() > eng.fastpath.update_slots):
            retired += self._barrier()
            self._dev_chain = None
        t0 = tele.t()
        for tok, ft in zip(tokens, fill_ts):
            tele.observe(tele.LOOP_WAIT, (now - ft) * 1e6, tok)
        try:
            prepared, resynced = eng.prepare_devloop_dispatch(
                buf, n_slots, now, device=sched._express_dev)
            if resynced and self._dev_chain is not None:
                # the pre-check raced (a resync it did not predict):
                # drain everything in flight, then re-publish the
                # resync'd chain over whatever stale chain the last
                # retire just published
                fresh = eng.tables.dhcp
                retired += self._barrier()
                eng.adopt_devloop_chain(fresh, count=False)
                self._dev_chain = None
            if self._dev_chain is None:
                self._join()
                self._dev_chain = eng.tables.dhcp
            fut = self._submit(self._run_ring, exe, prepared,
                               sched._express_dev)
        except BaseException:
            for tok in tokens:
                tele.cancel_batch(tok)
            raise
        if t0 is not None:
            # DISPATCH in loop mode = what the serving thread actually
            # spent: update drain + ring upload + worker submit. The
            # device compute lands in LOOP_RETIRE where the force waits.
            dur_us = (tele.t() - t0) / 1000.0 / n_slots
            for tok in tokens:
                tele.observe(tele.DISPATCH, dur_us, tok)
        # dispatch-epoch config snapshot: the retire renders from the
        # rows the device verdicts saw (the _dispatch_express_direct
        # discipline, per ring instead of per batch)
        cfg_epoch = (eng.fastpath.pools.copy(), eng.fastpath.server.copy())
        self.dispatches += 1
        sched.express_aot_dispatches += n_slots
        tele.set_meta("express_program", "devloop")
        tele.set_meta("devloop_ring", {
            "k": self.ring.k, "slots": int(n_slots),
            "inflight": len(self._inflight) + 1,
            "occupancy_avg": round(self.ring.occupancy_avg(), 4)})
        for pend in slots:
            sched._observe_dispatch(LANE_EXPRESS, len(pend), reason)
        over = self._inflight.push(_RingInflight(
            fut, slots, tokens, [reason] * n_slots, now, cfg_epoch))
        if over is not None:
            retired += self._retire(over)
        return retired

    # -- retire -----------------------------------------------------------

    def _retire(self, entry: _RingInflight) -> int:
        """Force one ring's verdict blocks, publish its output chain and
        cursor handle, and retire each slot through the scheduler's
        per-batch AOT retire (wire template patch-in, slow-path fan-out,
        telemetry close) — the reply path is shared, not cloned."""
        t0 = tele.t()
        try:
            res = entry.fut.result()
            blocks = np.asarray(res.blocks)
        except BaseException:
            for tok in entry.tokens:
                tele.cancel_batch(tok)
            raise
        self.ring.adopt_cursors(res.cursors)
        self.sched.engine.adopt_devloop_chain(res.dhcp_tables)
        n_slots = len(entry.slots)
        if t0 is not None and n_slots:
            wait_us = (tele.t() - t0) / 1000.0 / n_slots
            for tok in entry.tokens:
                tele.observe(tele.LOOP_RETIRE, wait_us, tok)
        zero_stats = np.zeros((NSTATS,), dtype=np.uint32)
        retired = 0
        folded = False
        for s, pend in enumerate(entry.slots):
            if not pend:
                continue
            res_s = _ExpressAotResult(
                block=blocks[s],
                # the megakernel sums stats across slots: fold once
                dhcp_stats=(res.dhcp_stats if not folded
                            else zero_stats),
                nat_stats=res.nat_stats if not folded
                else np.zeros_like(res.nat_stats),
                qos_stats=res.qos_stats if not folded
                else np.zeros_like(res.qos_stats),
                spoof_stats=res.spoof_stats if not folded
                else np.zeros_like(res.spoof_stats))
            folded = True
            retired += self.sched._retire_express_aot(InflightEntry(
                res_s, pend, entry.dispatch_t, entry.reasons[s],
                trace=entry.tokens[s], meta=entry.meta))
        return retired

    # -- quiesce audit / observability ------------------------------------

    def audit(self) -> dict:
        """Cursor-vs-host agreement — legal only after flush() (nothing
        in flight). The quiesce pin: `seq` on device equals the host's
        total dispatched slot count, head is 0, the in-flight ring is
        empty."""
        cur = self.ring.read_cursors()
        return {
            "seq": int(cur[CUR_SEQ]),
            "slots_taken": self.ring.slots_taken - self.fallback_slots,
            "staged": self.ring.head,
            "inflight": len(self._inflight),
            "consistent": (int(cur[CUR_SEQ]) == (self.ring.slots_taken
                                                 - self.fallback_slots)
                           and self.ring.head == 0
                           and len(self._inflight) == 0),
        }

    def stats(self) -> dict:
        return {
            "k": self.ring.k,
            "dispatches": self.dispatches,
            "batches": self.batches,
            "fallback_slots": self.fallback_slots,
            "staged": self.ring.head,
            "inflight": len(self._inflight),
            "occupancy_avg": round(self.ring.occupancy_avg(), 4),
        }
