"""The persistent express megakernel (ISSUE 18).

ONE AOT-compiled program drains up to k descriptor-ring slots per
invocation: for each slot it runs the probe-only OFFER cascade
(ops/express.express_verdicts — the PR-13 AOT program, which stays the
bit-identity oracle AND the loud fallback) and streams the verdict rows
back over the donated ring, so the device-side completion ring IS the
descriptor ring. The host touches the device once per k admission
batches instead of once per batch; the per-dispatch ceremony (update
drain, executable call, placement) amortizes k-fold.

The slot axis is a `jax.lax.scan`, not a vmap: the compiled program
stays O(1) in k (one probe cascade body, k iterations), matching the
persistent-kernel shape the ROADMAP `[latency]` item names — on TPU the
same scan becomes the on-chip serving loop, with slots arriving via
device DMA instead of a host upload.

Table impl dispatch follows the PR-13 discipline exactly: the probe
cascade routes through ops/table.device_lookup under
``forced_impl(table_impl)``, so ``BNG_TABLE_IMPL=pallas`` serves the
ring through the fused Pallas probe kernel (interpret-mode on CPU in
tier-1) and ``xla`` through the reference lowering — the identity tests
pin both against the per-batch oracle.

Empty lanes and unfilled slots need no explicit mask: the host zeroes
them at staging, a zero descriptor row has no XF_VALID flag, and the
cascade's validity mask produces verdict 0 and no stats for it — the
same contract the per-batch AOT lane relies on for short batches.

Compiled executables are cached process-wide like `_EXPRESS_AOT`
(engine.py): (geometry, pools, update slots, k, batch, impl, device) ->
Compiled. A lookup miss at dispatch time is the LOUD fallback class the
pump counts and flight-records; it never compiles on the serving path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from bng_tpu.devloop.ring import CUR_EPOCH, CUR_SEQ, CUR_TAIL, CUR_WORDS
from bng_tpu.ops import table as table_mod
from bng_tpu.ops.dhcp import NSTATS
from bng_tpu.ops.express import XD_WORDS, express_verdicts
from bng_tpu.runtime.tables import apply_fastpath_updates


class DevloopResult(NamedTuple):
    """One megakernel dispatch (futures until the pump retires it).
    Shaped for Engine._fold_stats like _ExpressAotResult; `blocks`
    carries the per-slot verdict blocks, `cursors` the advanced
    device-resident cursor handle the ring adopts, `dhcp_tables` the
    output chain the retire publishes back to the engine."""

    dhcp_tables: object   # DHCPFastPathTables pytree (post-ring chain)
    blocks: "jax.Array"   # [k, B, XD_WORDS] uint32 (VB_* verdict cols)
    cursors: "jax.Array"  # [CUR_WORDS] uint32 (tail/seq/epoch advanced)
    dhcp_stats: "jax.Array"  # [NSTATS] summed across slots
    nat_stats: np.ndarray    # zeros (no NAT on this program)
    qos_stats: np.ndarray    # zeros
    spoof_stats: np.ndarray  # zeros


@functools.lru_cache(maxsize=8)
def _devloop_jit(geom, k: int, table_impl: str = "xla"):
    """The megakernel jit factory. Donates ONLY the descriptor ring
    (argnum 2): the per-slot verdict blocks are shaped exactly like it,
    so XLA aliases the completion ring onto the uploaded descriptor
    ring. The dhcp chain is deliberately NOT donated — the chain is
    double-buffered across ring boundaries so the engine's published
    `tables.dhcp` handle stays live and readable while a ring is in
    flight on the pump's dispatch worker; donation would poison every
    engine-side reader between dispatch and retire. (On-chip the
    double buffer is the classic persistent-kernel A/B table swap; the
    extra copy is one chain, not one per slot.) Cursors are 16 bytes —
    donating them would only make the retired handle unreadable."""

    def step(dhcp_tables, upd, ring, n_slots, cursors, now_s):
        dhcp_tables = apply_fastpath_updates(dhcp_tables, upd)
        with table_mod.forced_impl(table_impl):
            def slot(stats, desc):
                res = express_verdicts(dhcp_tables, desc, geom, now_s)
                return stats + res.stats, res.block

            stats, blocks = jax.lax.scan(
                slot, jnp.zeros((NSTATS,), dtype=jnp.uint32), ring)
        cursors = (cursors
                   .at[CUR_TAIL].set(n_slots)
                   .at[CUR_SEQ].add(n_slots)
                   .at[CUR_EPOCH].add(jnp.uint32(1)))
        return dhcp_tables, blocks, cursors, stats

    return jax.jit(step, donate_argnums=(2,))


# AOT-compiled megakernel executables, shared across engines of one
# geometry (the _EXPRESS_AOT discipline): key -> Compiled.
_DEVLOOP_AOT: dict = {}


def devloop_key(engine, k: int, batch: int, device) -> tuple:
    """Everything the compiled program's avals bake in — two engines
    differing in any of these must not share an executable (the
    _express_aot_key rationale, plus the ring's k axis)."""
    return (engine.fastpath.geom, len(engine.fastpath.pools),
            engine.fastpath.update_slots, k, batch, engine.table_impl,
            None if device is None else str(device))


def get_compiled(engine, k: int, batch: int, device=None):
    """The compiled megakernel for this ring geometry, or None — a None
    here is the GEOMETRY MISS the pump must fall back (loudly) from;
    it never compiles."""
    return _DEVLOOP_AOT.get(devloop_key(engine, k, batch, device))


def compile_devloop(engine, k: int, batch: int, device=None):
    """`jax.jit(...).lower(...).compile()` the megakernel for one fixed
    ring geometry — scheduler init / engine-adopt time, NEVER the
    dispatch path. Lowering uses the live chain's avals plus an EMPTY
    update batch (the compile_express_aot discipline: a real
    make_updates() here would consume dirty state the next dispatch
    needs)."""
    key = devloop_key(engine, k, batch, device)
    exe = _DEVLOOP_AOT.get(key)
    if exe is not None:
        return exe
    dev = device if device is not None else jax.devices()[0]
    upd = jax.device_put(engine.fastpath.empty_updates(), dev)
    ring = jax.device_put(
        jnp.zeros((k, batch, XD_WORDS), jnp.uint32), dev)
    cursors = jax.device_put(jnp.zeros((CUR_WORDS,), jnp.uint32), dev)
    n_d = jax.device_put(jnp.uint32(0), dev)
    now_d = jax.device_put(jnp.uint32(0), dev)
    exe = _devloop_jit(engine.fastpath.geom, k, engine.table_impl).lower(
        engine.tables.dhcp, upd, ring, n_d, cursors, now_d).compile()
    _DEVLOOP_AOT[key] = exe
    return exe
