"""Descriptor ring for the device-resident express loop (ISSUE 18).

One ring = k slots x B lanes x XD_WORDS uint32 — k closed express
batches staged as one [k, B, XD_WORDS] block that crosses the host->HBM
boundary ONCE. The megakernel (devloop/kernel.py) donates the block and
writes each slot's verdict columns over its descriptor rows, so the
completion ring aliases the descriptor ring on device: no second
allocation, no second transfer.

Host staging is double-buffered the way the AOT lane's `_desc_bufs`
are: `depth + 2` cycling [k, B, XD_WORDS] buffers, so slot fills for
ring i+1 write a different buffer than the (up to `depth`) rings still
in flight — batch i+1 uploads while batch i executes, and a buffer is
only rewritten after every dispatch that could still be reading it has
retired.

Cursors are DEVICE-resident: a [CUR_WORDS] uint32 array threaded
through every megakernel invocation (not donated — 16 bytes; donating
would only make the retired handle unreadable at audit). The host
never writes it after creation — the kernel advances tail/seq/epoch;
the host's only cursor mutators are `fill_slot` (the host-side head
advance) and `adopt_cursors` (swapping in the kernel's returned
handle at retire), both allowlisted in
analysis/passes/single_writer.py: a module outside the devloop pump
mutating ring cursors bypasses the quiesce/audit story the same way
an un-allowlisted table writer bypasses the event log. Reading the
cursors back (`read_cursors`) is only legal when nothing is in flight
— a newer handle may still be a future on the dispatch worker — which
is exactly the quiesce barrier's state.
"""

from __future__ import annotations

import numpy as np

from bng_tpu.ops.express import XD_WORDS

# device cursor layout ([CUR_WORDS] uint32, padded for alignment)
CUR_TAIL = 0   # slots drained by the LAST invocation (kernel-written)
CUR_SEQ = 1    # total slots drained since ring creation (kernel-written)
CUR_EPOCH = 2  # megakernel invocations since ring creation
CUR_WORDS = 4


class DescriptorRing:
    """Host half of one device ring: staging buffers, slot occupancy,
    the cursor handle, and the per-slot retire metadata (pending-frame
    lists + telemetry tokens) that never touches the device."""

    def __init__(self, k: int, batch: int, depth: int = 2):
        if k < 1:
            raise ValueError(f"devloop ring needs k >= 1 slots, got {k}")
        self.k = k
        self.batch = batch
        self.depth = max(1, depth)
        self._bufs = [np.zeros((k, batch, XD_WORDS), dtype=np.uint32)
                      for _ in range(self.depth + 2)]
        self._buf_i = 0
        self.head = 0  # filled slots in the CURRENT (staging) ring
        # per-slot retire metadata for the staging ring (host-only)
        self._slot_pend: list[list] = [[] for _ in range(k)]
        self._slot_tok: list = [None] * k
        self._slot_fill_t: list[float] = [0.0] * k
        # device cursor handle — numpy until the first dispatch converts
        # it to a device array; each retire adopts the kernel-returned
        # handle (the device-resident thread)
        self.cursors = np.zeros((CUR_WORDS,), dtype=np.uint32)
        # occupancy accounting for flight-record / bench fields
        self.rings_taken = 0
        self.slots_taken = 0
        self.batches_filled = 0

    # -- host-side mutators (single-writer allowlisted) -------------------

    def fill_slot(self, rows: list, idxs: list, pend: list, tok,
                  now: float) -> int:
        """Advance the host head: stage one closed express batch's
        descriptor rows into the next free slot of the staging ring
        (ONE stacked assignment, the AOT lane's fill discipline; unused
        lanes stay zero so the kernel's validity mask skips them).
        Returns the slot index."""
        if self.head >= self.k:
            raise IndexError("devloop ring overfilled: dispatch before "
                             f"filling slot {self.head} of {self.k}")
        s = self.head
        desc = self._bufs[self._buf_i][s]
        desc[:] = 0
        if rows:
            desc[idxs] = rows
        self._slot_pend[s] = pend
        self._slot_tok[s] = tok
        self._slot_fill_t[s] = now
        self.head = s + 1
        self.batches_filled += 1
        return s

    def take(self) -> tuple:
        """Close the staging ring for dispatch: returns (ring_buf,
        n_slots, slots, tokens, fill_ts) and rotates to the next
        staging buffer with head reset. Slots beyond n_slots stay
        zeroed in the returned buffer — the kernel's validity mask
        drains them as empty."""
        n = self.head
        buf = self._bufs[self._buf_i]
        if n < self.k:
            buf[n:] = 0  # a prior occupancy of this buffer must not
            # resurrect stale descriptors in the unfilled tail
        slots = self._slot_pend[:n]
        tokens = self._slot_tok[:n]
        fill_ts = self._slot_fill_t[:n]
        self._buf_i = (self._buf_i + 1) % len(self._bufs)
        self.head = 0
        self._slot_pend = [[] for _ in range(self.k)]
        self._slot_tok = [None] * self.k
        self._slot_fill_t = [0.0] * self.k
        self.rings_taken += 1
        self.slots_taken += n
        return buf, n, slots, tokens, fill_ts

    def adopt_cursors(self, handle) -> None:
        """Swap in the kernel-returned cursor handle (retire time: the
        newest retired ring's view of tail/seq/epoch)."""
        self.cursors = handle

    # -- queries ----------------------------------------------------------

    @property
    def oldest_fill_t(self) -> float | None:
        """Enqueue time of the oldest staged slot (deadline close)."""
        return self._slot_fill_t[0] if self.head else None

    def occupancy_avg(self) -> float:
        """Mean slots-per-dispatched-ring (1.0 == every ring full)."""
        if not self.rings_taken:
            return 0.0
        return self.slots_taken / (self.rings_taken * self.k)

    def read_cursors(self) -> np.ndarray:
        """Force + read the live cursor words. ONLY legal with nothing
        in flight (the quiesce/audit barrier): a newer handle may still
        be in flight on the dispatch worker until the last ring
        retires."""
        return np.asarray(self.cursors)
