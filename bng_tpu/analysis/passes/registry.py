"""Registry-consistency passes (BNG030–BNG035).

Five vocabularies in this codebase are load-bearing: a call site using
a name outside them doesn't fail loudly — it records telemetry into a
garbage stage index, registers a fault nobody can trigger, exports a
metric no dashboard scrapes, or writes a checkpoint component restore
can never read back. Each check here compares call sites against the
declared registry, both parsed from source (facts.py):

* **BNG030** — span stage/lane argument not in the fixed vocabulary of
  telemetry/spans.py (stages are array indexes; a stray name is an
  out-of-bounds store). String or bare-int stage arguments are flagged
  unconditionally — the vocabulary is attribute constants, not strings.
* **BNG031** — `fault_point("x")` / `mutate_point("x")` / FaultSpec
  point not registered in chaos/faults.py POINT_KINDS.
* **BNG032** — metric family declared without the `bng_` prefix.
* **BNG033** — checkpoint component keys asymmetric between the save
  path and the restore path of runtime/checkpoint.py.
* **BNG034** — flight-recorder trigger reason not declared as a TRIG_*
  constant in telemetry/recorder.py.
* **BNG035** — metric family constructed outside control/metrics.py
  (families live in BNGMetrics so /metrics exposition is complete).
"""

from __future__ import annotations

import ast

from bng_tpu.analysis import facts
from bng_tpu.analysis.core import (Finding, Pass, Project, call_name,
                                   dotted, scope_of, str_const)

# hook name -> which positional arg carries the stage / lane constant
STAGE_HOOKS = {"lap": 0, "stamp": 0, "observe": 0, "observe_many": 0,
               "span": 0, "merge_stage": 0}
LANE_HOOKS = {"begin_batch": 0}
FAULT_HOOKS = {"fault_point": 0, "mutate_point": 0}


class RegistryPass(Pass):
    name = "registry"
    description = ("span stages, fault points, metric families, "
                   "checkpoint components and trigger reasons all "
                   "declared in their registries")
    codes = {
        "BNG030": "span stage/lane outside the fixed vocabulary",
        "BNG031": "fault/mutate point not registered in POINT_KINDS",
        "BNG032": "metric family without the bng_ prefix",
        "BNG033": "checkpoint component key asymmetric between "
                  "save and restore",
        "BNG034": "flight-recorder trigger reason not declared",
        "BNG035": "metric family declared outside control/metrics.py",
    }

    def run(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        vocab = facts.stage_vocabulary(project)
        points = facts.fault_registry(project)
        reasons = facts.trigger_reasons(project)
        comps = facts.checkpoint_components(project)

        if vocab is None:
            out.append(self.config_finding(
                "stages", "span stage vocabulary not found in "
                f"{facts.SPANS_FILE} — BNG030 cannot run"))
        if points is None:
            out.append(self.config_finding(
                "fault-points", "POINT_KINDS not found in "
                f"{facts.FAULTS_FILE} — BNG031 cannot run"))
        if reasons is None:
            out.append(self.config_finding(
                "trigger-reasons", "TRIG_* reasons not found in "
                f"{facts.RECORDER_FILE} — BNG034 cannot run"))
        if comps is None:
            out.append(self.config_finding(
                "checkpoint-components", "save/restore component keys "
                f"not found in {facts.CHECKPOINT_FILE} — BNG033 cannot "
                f"run"))

        for sf in project.files:
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if vocab is not None and name in STAGE_HOOKS:
                    out.extend(self._check_stage(sf, node, name, *vocab))
                if vocab is not None and name in LANE_HOOKS:
                    out.extend(self._check_lane(sf, node, vocab[1]))
                if points is not None and name in FAULT_HOOKS:
                    out.extend(self._check_fault(sf, node, points))
                if points is not None and name == "FaultSpec":
                    out.extend(self._check_faultspec(sf, node, points))
                if name == "trigger" and reasons is not None:
                    out.extend(self._check_trigger(sf, node, reasons))
                out.extend(self._check_metric_decl(sf, node, name))
        if comps is not None:
            out.extend(self._check_components(comps))
        return out

    # -- BNG030 ----------------------------------------------------------

    def _check_stage(self, sf, node: ast.Call, hook: str,
                     stages: set, lanes: set):
        if not node.args:
            return
        arg = node.args[0]
        # only check hook-shaped call sites: tele.lap(...), spans.lap(...)
        # or self.lap(...) inside spans.py itself pass Name args through
        if isinstance(arg, ast.Attribute):
            if arg.attr.isupper() and arg.attr not in stages:
                yield Finding(
                    "BNG030", sf.path, node.lineno,
                    f"`{hook}({dotted(arg)})` uses a stage outside the "
                    f"fixed vocabulary — stages are array indexes, an "
                    f"unknown constant is an out-of-bounds store",
                    scope=scope_of(node), detail=arg.attr)
        elif isinstance(arg, ast.Constant):
            if isinstance(arg.value, (str, int)):
                yield Finding(
                    "BNG030", sf.path, node.lineno,
                    f"`{hook}({arg.value!r})` passes a literal stage — "
                    f"use the spans.py constants (the vocabulary is "
                    f"fixed; free-form names defeat the preallocated "
                    f"array design)",
                    scope=scope_of(node), detail=str(arg.value))

    def _check_lane(self, sf, node: ast.Call, lanes: set):
        if not node.args:
            return
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) and arg.attr.startswith("LANE_"):
            if arg.attr not in lanes:
                yield Finding(
                    "BNG030", sf.path, node.lineno,
                    f"`begin_batch({dotted(arg)})` uses an unknown lane "
                    f"constant",
                    scope=scope_of(node), detail=arg.attr)

    # -- BNG031 ----------------------------------------------------------

    def _check_fault(self, sf, node: ast.Call, points: set):
        if not node.args:
            return
        lit = str_const(node.args[0])
        if lit is not None and lit not in points:
            yield Finding(
                "BNG031", sf.path, node.lineno,
                f"fault point \"{lit}\" is not registered in "
                f"chaos/faults.py POINT_KINDS — the soak generator and "
                f"explicit plans can never fire it",
                scope=scope_of(node), detail=lit)

    def _check_faultspec(self, sf, node: ast.Call, points: set):
        lit = None
        if node.args:
            lit = str_const(node.args[0])
        for kw in node.keywords:
            if kw.arg == "point":
                lit = str_const(kw.value)
        if lit is not None and lit not in points:
            yield Finding(
                "BNG031", sf.path, node.lineno,
                f"FaultSpec(point=\"{lit}\") names an unregistered fault "
                f"point — no call site will ever honor it",
                scope=scope_of(node), detail=lit)

    # -- BNG032 / BNG035 -------------------------------------------------

    METRIC_DECLS = {"counter", "gauge", "histogram",
                    "Counter", "Gauge", "Histogram"}

    def _check_metric_decl(self, sf, node: ast.Call, name: str):
        if name not in self.METRIC_DECLS or not node.args:
            return
        fam = str_const(node.args[0])
        if fam is None:
            return
        if not fam.startswith("bng_"):
            yield Finding(
                "BNG032", sf.path, node.lineno,
                f"metric family \"{fam}\" lacks the bng_ prefix — the "
                f"exposition contract (metrics.go parity) is bng_*",
                scope=scope_of(node), detail=fam)
        if not sf.path.endswith("control/metrics.py"):
            yield Finding(
                "BNG035", sf.path, node.lineno,
                f"metric family \"{fam}\" declared outside "
                f"control/metrics.py — families live in BNGMetrics so "
                f"the /metrics exposition and collect loop stay complete",
                scope=scope_of(node), detail=fam)

    # -- BNG033 ----------------------------------------------------------

    def _check_components(self, comps: dict):
        save, restore = comps["save"], comps["restore"]
        for key in sorted(save - restore):
            yield Finding(
                "BNG033", facts.CHECKPOINT_FILE, comps["line"],
                f"checkpoint component \"{key}\" is written by the save "
                f"path but the restore path never consumes it — "
                f"state silently lost across warm restart",
                scope="restore_into", detail=f"save-only:{key}")
        for key in sorted(restore - save):
            yield Finding(
                "BNG033", facts.CHECKPOINT_FILE, comps["line"],
                f"checkpoint component \"{key}\" is consumed by restore "
                f"but never written by save — dead restore arm or a "
                f"missing save hook",
                scope="restore_into", detail=f"restore-only:{key}")
        for key in sorted(comps["payload"] - save):
            yield Finding(
                "BNG033", facts.CHECKPOINT_FILE, comps["line"],
                f"payload-JSON component \"{key}\" not produced by the "
                f"save path",
                scope="payload", detail=f"payload-only:{key}")

    # -- BNG034 ----------------------------------------------------------

    def _check_trigger(self, sf, node: ast.Call, reasons: set):
        if not node.args:
            return
        lit = str_const(node.args[0])
        if lit is not None and lit not in reasons:
            yield Finding(
                "BNG034", sf.path, node.lineno,
                f"flight-recorder trigger \"{lit}\" is not a declared "
                f"TRIG_* reason — dashboards key dumps on the fixed "
                f"reason set",
                scope=scope_of(node), detail=lit)
