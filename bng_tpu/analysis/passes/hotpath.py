"""Hot-path sync lint (BNG001) + disarmed-hook hygiene (BNG002/BNG003)
+ batch-native serving-path lint (BNG004).

The dataplane's latency discipline has two halves:

* **Dispatch scope never forces.** The submit/dispatch side of the
  engine, scheduler, lanes and fleet scatter must not synchronize with
  the device: no `np.asarray`/`np.array`/`jax.device_get`/`.item()` on
  a device value, no `float()`/`int()`/`bool()` or truthiness on a
  value tainted by a jitted-step result. Forces belong in the retire
  path (the completion ring is the single block point — lanes.py).
  BNG001 flags any force inside the dispatch-scoped functions.

* **Disarmed hooks never allocate.** The telemetry/chaos hook APIs are
  measured at 58–84 ns/call disarmed (PERF_NOTES §7/§8) because the
  disarmed path is one global load + `is None` compare. BNG003 flags a
  hook whose first effective statement is not that guard; BNG002 flags
  an allocation (literal, comprehension, f-string, lambda) reachable
  before the guard. Hooks are discovered, not listed: any module-level
  function in spans.py/faults.py that delegates to `_ACTIVE.<attr>`.

* **The serving path is batch-native.** ISSUE 14 rebuilt the
  ring->dispatch->reply host path as vectorized NumPy over
  structure-of-arrays staging; a reintroduced `for frame in batch`
  loop in one of those functions silently re-caps host throughput at
  per-frame-Python speed. BNG004 flags any `for`/`while` statement in
  the BATCH_SCOPE functions, EXCEPT `for ... in range(<int literal>)`
  (bounded vectorized iteration — the 2-tag VLAN walk, the 64-step TLV
  scan — iterates a constant, never the batch). Comprehensions are
  deliberately NOT flagged: a list comprehension feeding one stacked
  NumPy assignment is the batch-native staging idiom, and the
  per-frame handler boundaries (worker scatter, fallback demux) live
  behind them. Surviving per-frame loops — the scalar oracle twins the
  vector path is pinned against, and the pressured-path fallbacks with
  genuine sequential coupling — are baselined with justifications.

Taint for BNG001 is function-local and deliberately simple: a name
assigned from a dispatch call (`self._step(...)`, `_run_dhcp_batch`,
`pipeline_step`, ...) is device-tainted; attributes of a tainted name
(`res.verdict`) are tainted; a force call (`np.asarray`/`device_get`)
both *flags* and launders. Parameters named `res` (and `entry.res`
chains) are treated as device results — the retire-path convention.
"""

from __future__ import annotations

import ast

from bng_tpu.analysis.core import (Finding, Pass, Project, call_name,
                                   dotted, scope_of)

# dispatch-scoped functions: file suffix -> function (simple) names.
# The retire-side siblings (_retire*, _apply_ring_verdicts, process*)
# force deliberately and are NOT listed.
DISPATCH_SCOPE: dict[str, set[str]] = {
    "bng_tpu/runtime/engine.py": {
        "_dispatch_step", "_run_dhcp_batch", "dispatch_scheduled_bulk",
        "_drain_updates", "_make_bulk_updates", "_empty_updates",
        "_pack_frames", "_dispatch_fault", "_staging",
    },
    "bng_tpu/runtime/scheduler.py": {
        "submit", "classify", "_dispatch_express", "_dispatch_bulk",
        "_ensure_bulk_replica", "_copy_to_bulk", "_entry_ready",
    },
    "bng_tpu/runtime/lanes.py": {
        "push", "close_reason", "close_batch", "oldest_age_us",
        "pop_oldest", "pop_ready",
    },
    "bng_tpu/control/fleet.py": {
        "_scatter_fault", "shard_for_mac", "shard_for_frame", "shard_of",
    },
    "bng_tpu/telemetry/spans.py": set(),  # hooks handled by BNG002/003
    "bng_tpu/chaos/faults.py": set(),
}

# calls that synchronize host<->device when given a device value
FORCE_CALLS = {"asarray", "array", "device_get", "item", "copy_to_host"}
# calls whose *result* is a device-step future (taint sources)
DISPATCH_CALLS = {"_step", "_dhcp_step", "_dispatch_step",
                  "_run_dhcp_batch", "_run_step", "dispatch_scheduled_bulk",
                  "pipeline_step", "dhcp_fastpath"}
SCALAR_FORCES = {"float", "int", "bool"}

ALLOC_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp, ast.GeneratorExp, ast.Lambda, ast.JoinedStr)
ALLOC_CALLS = {"list", "dict", "set", "zeros", "empty", "ones", "full",
               "deque", "defaultdict"}

# batch-native scope (BNG004): the per-BATCH serving-path functions that
# must not loop per frame. file suffix -> function (simple) names; both
# the vector implementations (loop-free, enforced) and their scalar
# oracle twins (baselined) are listed — a NEW loop in either shows up.
BATCH_SCOPE: dict[str, set[str]] = {
    "bng_tpu/runtime/ring.py": {
        "rx_push_batch", "_rx_push_batch_vec", "_push_scalar",
        "assemble", "_assemble_vec",
        "assemble_sharded", "_assemble_sharded_vec", "complete",
        "_complete_vec", "_scatter_frames", "_scatter_rows_from",
        "_gather_rows", "tx_pop_batch",
    },
    "bng_tpu/runtime/engine.py": {"_pack_frames"},
    "bng_tpu/runtime/scheduler.py": {"_dispatch_express",
                                     "_express_replies_vec"},
    "bng_tpu/control/admission.py": {"admit_batch", "is_known_batch",
                                     "_admit_scalar_fallback"},
    "bng_tpu/control/fleet.py": {"handle_batch", "_admit_vec"},
    "bng_tpu/runtime/hostpath.py": {
        "pack_into", "classify_dhcp_batch", "shard_of_batch",
        "peek_dhcp_batch", "bootp_off_batch", "fnv1a32_cols", "stage",
    },
}


def _is_force_call(node: ast.Call) -> bool:
    name = call_name(node)
    if name not in FORCE_CALLS:
        return False
    if name == "item":
        return True  # x.item() — always a device sync on a jax value
    base = dotted(node.func)
    # np.asarray / np.array / numpy.* / jax.device_get — NOT jnp.asarray
    # (host->device staging is the dispatch path's job)
    return base.startswith(("np.", "numpy.", "jax.")) or base in FORCE_CALLS


class _Taint(ast.NodeVisitor):
    """Function-local device-result taint."""

    def __init__(self):
        self.tainted: set[str] = {"res"}

    def visit_Assign(self, node: ast.Assign):
        if self._taints(node.value):
            for tgt in node.targets:
                for e in (tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]):
                    if isinstance(e, ast.Name):
                        self.tainted.add(e.id)
        self.generic_visit(node)

    def _taints(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call) and call_name(expr) in DISPATCH_CALLS:
            return True
        if isinstance(expr, ast.Tuple):
            return any(self._taints(e) for e in expr.elts)
        return self.is_tainted(expr)

    def is_tainted(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr == "res":  # entry.res — the inflight convention
                return True
            return self.is_tainted(expr.value)
        if isinstance(expr, ast.Subscript):
            return self.is_tainted(expr.value)
        if isinstance(expr, (ast.BinOp,)):
            return self.is_tainted(expr.left) or self.is_tainted(expr.right)
        if isinstance(expr, ast.Compare):
            return (self.is_tainted(expr.left)
                    or any(self.is_tainted(c) for c in expr.comparators))
        if isinstance(expr, ast.Call):
            # method on a tainted value keeps the taint (.all(), ._replace)
            if isinstance(expr.func, ast.Attribute):
                return self.is_tainted(expr.func.value)
        return False


class HotPathPass(Pass):
    name = "hotpath"
    description = ("no device sync in dispatch scope; disarmed hooks "
                   "guard-first and allocation-free")
    codes = {
        "BNG001": "device sync (force/transfer) in a dispatch-scoped "
                  "hot function",
        "BNG002": "allocation on the disarmed path of a telemetry/chaos "
                  "hook",
        "BNG003": "hook delegates to _ACTIVE without a disarmed "
                  "fast-path guard",
        "BNG004": "per-frame Python loop in a batch-native serving-path "
                  "function",
    }

    def run(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for suffix, fn_names in DISPATCH_SCOPE.items():
            sf = project.find_file(suffix)
            if sf is None:
                continue
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name in fn_names):
                    out.extend(self._check_dispatch_fn(sf.path, node))
            if suffix.endswith(("spans.py", "faults.py")):
                out.extend(self._check_hooks(sf.path, sf.tree))
        for suffix, fn_names in BATCH_SCOPE.items():
            sf = project.find_file(suffix)
            if sf is None:
                continue
            for node in ast.walk(sf.tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name in fn_names):
                    out.extend(self._check_batch_fn(sf.path, node))
        return out

    # -- BNG004 ----------------------------------------------------------

    @staticmethod
    def _const_range(it: ast.AST) -> bool:
        """`range(<int literal>...)` — bounded vectorized iteration (the
        2-tag VLAN walk, the 64-step TLV scan), never the batch."""
        return (isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name)
                and it.func.id == "range"
                and len(it.args) >= 1
                and all(isinstance(a, ast.Constant)
                        and isinstance(a.value, int) for a in it.args))

    def _check_batch_fn(self, path: str, fn: ast.FunctionDef):
        scope = (scope_of(fn) + "." + fn.name).lstrip(".")
        for node in ast.walk(fn):
            if isinstance(node, ast.While):
                yield Finding(
                    "BNG004", path, node.lineno,
                    f"`while` loop in batch-native serving function "
                    f"`{fn.name}` — the vectorized host path must not "
                    f"iterate per frame (ISSUE 14); express the work as "
                    f"a NumPy pass or baseline the scalar oracle",
                    scope=scope, detail="while")
            elif isinstance(node, ast.For):
                if self._const_range(node.iter):
                    continue
                yield Finding(
                    "BNG004", path, node.lineno,
                    f"`for` loop in batch-native serving function "
                    f"`{fn.name}` — the vectorized host path must not "
                    f"iterate per frame (ISSUE 14); express the work as "
                    f"a NumPy pass or baseline the scalar oracle",
                    scope=scope,
                    detail=f"for:{ast.unparse(node.target)}")

    # -- BNG001 ----------------------------------------------------------

    def _check_dispatch_fn(self, path: str, fn: ast.FunctionDef):
        taint = _Taint()
        taint.visit(fn)
        scope = (scope_of(fn) + "." + fn.name).lstrip(".")
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if _is_force_call(node):
                    yield Finding(
                        "BNG001", path, node.lineno,
                        f"`{dotted(node.func)}()` forces a device value "
                        f"inside dispatch-scoped `{fn.name}` — forces "
                        f"belong in the retire path (completion ring)",
                        scope=scope, detail=dotted(node.func))
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in SCALAR_FORCES and node.args
                      and taint.is_tainted(node.args[0])):
                    yield Finding(
                        "BNG001", path, node.lineno,
                        f"`{node.func.id}()` on a device-step result in "
                        f"dispatch-scoped `{fn.name}` blocks the host on "
                        f"the device",
                        scope=scope, detail=f"{node.func.id}()")
            elif isinstance(node, (ast.If, ast.While)):
                if taint.is_tainted(node.test):
                    yield Finding(
                        "BNG001", path, node.lineno,
                        f"truthiness on a device-step result in "
                        f"dispatch-scoped `{fn.name}` is an implicit "
                        f"blocking transfer",
                        scope=scope, detail="truthiness")

    # -- BNG002 / BNG003 -------------------------------------------------

    def _hooks(self, tree: ast.Module):
        """Module-level functions that delegate to `_ACTIVE.<attr>`
        without declaring `global _ACTIVE` (arm/disarm mutate it and are
        not hot)."""
        for node in tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            has_global = any(isinstance(s, ast.Global) and
                             "_ACTIVE" in s.names for s in node.body)
            if has_global:
                continue
            delegates = any(
                isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "_ACTIVE"
                for n in ast.walk(node))
            if delegates:
                yield node

    @staticmethod
    def _is_guard_test(test: ast.AST) -> bool:
        """Does `test` contain `_ACTIVE is None` / `is not None`?"""
        for n in ast.walk(test):
            if (isinstance(n, ast.Compare)
                    and isinstance(n.left, ast.Name)
                    and n.left.id == "_ACTIVE"
                    and any(isinstance(op, (ast.Is, ast.IsNot))
                            for op in n.ops)):
                return True
        return False

    def _check_hooks(self, path: str, tree: ast.Module):
        for fn in self._hooks(tree):
            body = fn.body
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)):
                body = body[1:]  # docstring
            guard_idx = None
            for i, stmt in enumerate(body):
                if (isinstance(stmt, ast.If)
                        and self._is_guard_test(stmt.test)
                        and stmt.body
                        and isinstance(stmt.body[0], ast.Return)):
                    guard_idx = i
                    break
                if (isinstance(stmt, ast.Return) and stmt.value is not None
                        and self._is_guard_test(stmt.value)):
                    guard_idx = i  # `return _ACTIVE is not None` style
                    break
            if guard_idx is None:
                yield Finding(
                    "BNG003", path, fn.lineno,
                    f"hook `{fn.name}` delegates to _ACTIVE without an "
                    f"`if _ACTIVE is None: return` fast path — the "
                    f"disarmed cost contract (PERF_NOTES §7/§8) requires "
                    f"guard-first",
                    scope=fn.name, detail=fn.name)
                continue
            # disarmed path = statements up to the guard, plus the
            # guard's own test and early-return body (a `return []`
            # there would still allocate per disarmed call)
            for stmt in body[: guard_idx + 1]:
                if stmt is body[guard_idx] and isinstance(stmt, ast.If):
                    nodes = [n for sub in ([stmt.test] + stmt.body)
                             for n in ast.walk(sub)]
                else:
                    nodes = ast.walk(stmt)
                for n in nodes:
                    bad = isinstance(n, ALLOC_NODES) or (
                        isinstance(n, ast.Call)
                        and call_name(n) in ALLOC_CALLS)
                    if bad:
                        yield Finding(
                            "BNG002", path, n.lineno,
                            f"allocation on the DISARMED path of hook "
                            f"`{fn.name}` — disarmed cost must stay one "
                            f"global load + is-None compare",
                            scope=fn.name,
                            detail=type(n).__name__)
                        break
