"""Narrow-gather lint (BNG014) — table rows must be gather-wide.

PERF_NOTES §2's hardware finding: composed narrow gathers (<8-word
rows, 1-word-per-index in the limit) lower to ~7 ns/element serialized
loops on v5e, while >=8-word row gathers run at full speed. The qtable
bucket-packing (round 3) and the generic-table way_stride relayout
(round 3.6) killed every narrow PROBE gather, and ISSUE 11 widened the
last narrow VALUE rows (nat reverse 4->8, pppoe 6->8). This pass makes
that discipline machine-checked instead of folklore:

- **BNG014 / table construction**: any `HostTable(...)` whose resolved
  `val_words` is < 8 — its device `vals[slot]` gather is exactly the
  serialization shape. Widths resolve from int literals or from
  module-level integer constants anywhere in the scanned project (the
  registry-pass fact discipline: the repo's own AST is the source of
  truth). Probe-row width needs no check — `way_stride` rounds key
  rows up to 8 words by construction.
- **BNG014 / in-function gather**: inside ops/ device code, a
  subscript gather `arr[idx]` whose base was assigned in the same
  function from `np.zeros`/`jnp.zeros`/`ones`/`full` with a LITERAL
  last dim < 8 (or a 1-D literal shape) and a non-trivial index
  expression. Dynamic widths are out of scope — the table check above
  covers the real fleet, this one catches fresh narrow scratch arrays
  before they ship.

A narrow table a PR genuinely needs (host-only lookup tables never
gathered on device) is baselined with a justification like every other
pass's accepted debt.
"""

from __future__ import annotations

import ast

from bng_tpu.analysis.core import (Finding, Pass, Project, call_name,
                                   dotted, enclosing_function, scope_of)

MIN_ROW_WORDS = 8

# device-array constructors only (jnp.*): host-side numpy index ops in
# the same files (HostTable.bulk_insert's boolean masks) never reach
# the TPU gather unit and are out of scope
_ARRAY_CTORS = {"jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty"}


def _int_const(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


_AMBIGUOUS = object()  # same name, different values across modules


def _module_int_constants(project: Project):
    """(per_file {path: {NAME: value}}, global {NAME: value|_AMBIGUOUS})
    over every module-level `NAME = <int>` assignment in the scan set.
    Resolution is same-file first, then the global table — where a name
    defined with CONFLICTING values in two modules is poisoned rather
    than first-wins (the PR-9 class-name-collision lesson: a shadowed
    constant must make the width UNRESOLVED, never silently wrong)."""
    per_file: dict[str, dict[str, int]] = {}
    global_c: dict = {}
    for sf in project.files:
        mine = per_file.setdefault(sf.path, {})
        for stmt in sf.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                v = _int_const(stmt.value)
                if v is None:
                    continue
                name = stmt.targets[0].id
                mine.setdefault(name, v)
                if name in global_c and global_c[name] != v:
                    global_c[name] = _AMBIGUOUS
                else:
                    global_c.setdefault(name, v)
    return per_file, global_c


class NarrowGatherPass(Pass):
    name = "gather"
    description = ("<8-word table/value rows are the PERF_NOTES §2 "
                   "gather-serialization shape")
    codes = {
        "BNG014": "narrow gather: table value rows (or a gathered array's "
                  "rows) are < 8 words — the measured serialization shape",
    }

    def run(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        per_file, global_c = _module_int_constants(project)
        saw_table_ctor = False
        for sf in project.files:
            consts = dict(global_c)
            consts.update(per_file.get(sf.path, {}))  # same-file wins
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) \
                        and call_name(node) == "HostTable":
                    saw_table_ctor = True
                    out.extend(self._check_ctor(sf, node, consts))
            if sf.path.startswith("bng_tpu/ops/"):
                out.extend(self._check_local_gathers(sf))
        if not saw_table_ctor and project.find_file("ops/table.py"):
            # the fact source moved out from under the width check
            out.append(self.config_finding(
                "no-hosttable-ctors",
                "gather pass found ops/table.py but no HostTable "
                "construction anywhere in the scan set — width facts "
                "unextractable (BNG990: fail loud, not silently pass)"))
        return out

    # -- table constructions ------------------------------------------------

    def _check_ctor(self, sf, call: ast.Call, consts: dict[str, int]):
        width = None
        src = None
        args = list(call.args)
        # HostTable(nbuckets, key_words, val_words, ...) — positional 3rd
        if len(args) >= 3:
            width, src = self._resolve(args[2], consts)
        for kw in call.keywords:
            if kw.arg == "val_words":
                width, src = self._resolve(kw.value, consts)
        if width is None or width >= MIN_ROW_WORDS:
            return
        name = ""
        for kw in call.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                name = str(kw.value.value)
        yield Finding(
            "BNG014", sf.path, call.lineno,
            f"HostTable {name or '<unnamed>'} has val_words={width} "
            f"(< {MIN_ROW_WORDS}): its device vals[slot] gather is the "
            f"PERF_NOTES §2 narrow-row serialization shape — pad the "
            f"value rows to {MIN_ROW_WORDS} words (free HBM, the narrow "
            f"gather is not) or baseline with a justification",
            scope=scope_of(call), detail=f"{name or 'table'}-val_words-{width}"
            + (f"-{src}" if src else ""))

    @staticmethod
    def _resolve(node: ast.AST, consts: dict):
        v = _int_const(node)
        if v is not None:
            return v, None
        name = None
        if isinstance(node, ast.Name):
            name = node.id
        elif isinstance(node, ast.Attribute):  # module.CONST
            name = node.attr
        if name is None:
            return None, None
        got = consts.get(name)
        if got is _AMBIGUOUS:  # conflicting cross-module definitions
            return None, name
        return got, name

    # -- fresh narrow arrays gathered in ops/ device code -------------------

    def _check_local_gathers(self, sf):
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            narrow: dict[str, tuple[int, int]] = {}  # var -> (width, line)
            for stmt in ast.walk(fn):
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Call)
                        and dotted(stmt.value.func) in _ARRAY_CTORS):
                    w = self._literal_row_width(stmt.value)
                    if w is not None and w < MIN_ROW_WORDS:
                        narrow[stmt.targets[0].id] = (w, stmt.lineno)
            if not narrow:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Subscript):
                    continue
                base = node.value
                if not (isinstance(base, ast.Name) and base.id in narrow):
                    continue
                if enclosing_function(node) is not fn:
                    continue
                if self._trivial_index(node.slice):
                    continue
                w, line = narrow[base.id]
                yield Finding(
                    "BNG014", sf.path, node.lineno,
                    f"gather of `{base.id}` (built line {line} with "
                    f"{w}-word rows, < {MIN_ROW_WORDS}) by a computed "
                    f"index — the PERF_NOTES §2 serialization shape; "
                    f"pad the rows to {MIN_ROW_WORDS} words",
                    scope=f"{scope_of(node)}" or fn.name,
                    detail=f"{base.id}-rows-{w}")

    @staticmethod
    def _literal_row_width(call: ast.Call) -> int | None:
        """Last-dim width of a zeros/ones/full literal shape; a 1-D
        shape is width 1 (the worst case). Non-literal dims -> None."""
        if not call.args:
            return None
        shape = call.args[0]
        if isinstance(shape, ast.Tuple):
            if not shape.elts:
                return None
            last = _int_const(shape.elts[-1])
            return last if len(shape.elts) > 1 else 1
        if _int_const(shape) is not None:
            return 1
        return None

    @staticmethod
    def _trivial_index(sl: ast.AST) -> bool:
        """Constant / slice / constant-tuple indexing is not a gather."""
        if isinstance(sl, (ast.Slice, ast.Constant)):
            return True
        if isinstance(sl, ast.UnaryOp) and isinstance(sl.operand,
                                                      ast.Constant):
            return True
        if isinstance(sl, ast.Tuple):
            return all(NarrowGatherPass._trivial_index(e) for e in sl.elts)
        return False
