"""The bngcheck pass registry — one module per discipline."""

from __future__ import annotations

from bng_tpu.analysis.passes.concurrency import ConcurrencyPass
from bng_tpu.analysis.passes.fencing import FencingPass
from bng_tpu.analysis.passes.gather import NarrowGatherPass
from bng_tpu.analysis.passes.handlers import HandlerAuditPass
from bng_tpu.analysis.passes.hotpath import HotPathPass
from bng_tpu.analysis.passes.jit_discipline import JitDisciplinePass
from bng_tpu.analysis.passes.registry import RegistryPass
from bng_tpu.analysis.passes.single_writer import SingleWriterPass

ALL_PASSES = (HotPathPass, JitDisciplinePass, HandlerAuditPass,
              RegistryPass, SingleWriterPass, FencingPass,
              ConcurrencyPass, NarrowGatherPass)


def all_codes() -> dict[str, str]:
    """{BNG0xx -> description} over every registered pass."""
    out: dict[str, str] = {}
    for cls in ALL_PASSES:
        out.update(cls.codes)
    return dict(sorted(out.items()))


def build(select: set[str] | None = None):
    """Instantiate passes, optionally filtered by pass name or by a
    finding code the pass owns."""
    out = []
    for cls in ALL_PASSES:
        if select and cls.name not in select and not (
                select & set(cls.codes)):
            continue
        out.append(cls())
    return out
