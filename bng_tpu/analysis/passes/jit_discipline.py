"""Jit-discipline audit (BNG010/BNG011/BNG012).

Every `jax.jit` site in the tree is audited for the three retrace/
donation hazards that have actually bitten TPU dataplanes like this one:

* **BNG010 — uncached jit construction.** A `jax.jit(...)` call inside
  a plain function body builds a NEW jitted callable (and its trace
  cache) per invocation. Step factories must be module-level or
  `functools.lru_cache`d (the engine's `_pipeline_jit`/`_dhcp_jit`
  pattern: the cache is keyed on geometry so engines with one shape
  share one compile).

* **BNG011 — missing donation on a table-updating step.** A jitted step
  whose body applies host table deltas (`apply_fastpath_updates`,
  `apply_nat_updates`, `apply_update`, `apply_qupdate`, ...) threads
  the device tables through itself; without `donate_argnums` the old
  table buffers stay live across the step and HBM holds two copies of
  every table — the ROADMAP perf campaign's "donation/layout audit of
  the jitted step" as a repeatable pass.

* **BNG012 — per-batch Python scalar as a traced argument.** Calling a
  jitted step with a bare `int(...)`/`float(...)`/arithmetic scalar
  traces it at weak type; int-vs-float drift between call sites (or an
  accidental static annotation) retraces per batch. The codebase
  convention is fixed-width wrapping at the call site
  (`np.uint32(int(now))`), which BNG012 enforces. An unhashable value
  in `static_argnums` position is the same bug's other face and is
  flagged when the static arg is a literal list/dict.
"""

from __future__ import annotations

import ast

from bng_tpu.analysis.core import (Finding, Pass, Project, call_name,
                                   dotted, enclosing_function, scope_of)

APPLY_FNS = {"apply_fastpath_updates", "apply_nat_updates", "apply_update",
             "apply_qupdate", "_apply_all_updates", "apply_all_updates"}
# the AOT-compiled express entry (ops/express.py): a jitted step whose
# body runs the express probe program threads (and must donate) the
# dhcp chain AND the descriptor batch — the program's output verdict
# block aliases the descriptor staging buffer, so an undonated express
# step silently doubles both the table HBM and the per-dispatch
# allocation (ISSUE 13). Recognized like the apply fns: donation is
# required even if a refactor ever drops the in-step update apply.
EXPRESS_ENTRY_FNS = {"express_verdicts"}
CACHE_DECORATORS = {"lru_cache", "cache"}
# jitted-step callables at call sites (the engine/scheduler convention).
# `express_exe` is the AOT-compiled express executable (the engine's
# run_express_aot parameter name): same scalar discipline at call sites
# — an AOT executable rejects nothing at trace time (there is none), so
# a weak-typed scalar would surface as a shape error at dispatch.
STEP_CALLEES = {"_step", "_dhcp_step", "step_fn", "express_exe"}


def _is_jax_jit(node: ast.Call) -> tuple[bool, ast.Call | None]:
    """(is a jit site, the call carrying the jit kwargs).

    Handles `jax.jit(f, ...)` and `functools.partial(jax.jit, ...)`."""
    d = dotted(node.func)
    if d in ("jax.jit", "jit"):
        return True, node
    if d.endswith("partial") and node.args:
        if dotted(node.args[0]) in ("jax.jit", "jit"):
            return True, node
    return False, None


def _has_cache_decorator(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = dotted(dec.func if isinstance(dec, ast.Call) else dec)
        if d.rsplit(".", 1)[-1] in CACHE_DECORATORS:
            return True
    return False


def _kwarg(node: ast.Call, name: str) -> ast.AST | None:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


class JitDisciplinePass(Pass):
    name = "jit-discipline"
    description = ("jit factories cached, table steps donated, traced "
                   "scalars fixed-width")
    codes = {
        "BNG010": "jax.jit constructed inside an uncached function "
                  "(retrace/recompile per call)",
        "BNG011": "table-updating jitted step without donate_argnums",
        "BNG012": "bare Python scalar / unhashable static at a jitted "
                  "call site",
    }

    def run(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for sf in project.files:
            out.extend(self._check_file(sf.path, sf.tree))
        return out

    def _check_file(self, path: str, tree: ast.Module):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                is_jit, jit_call = _is_jax_jit(node)
                if is_jit:
                    yield from self._check_jit_site(path, node, jit_call)
                yield from self._check_step_call(path, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if (isinstance(dec, (ast.Name, ast.Attribute))
                            and dotted(dec) in ("jax.jit", "jit")):
                        yield from self._check_bare_jit(path, dec, node)

    # -- BNG010 / BNG011 -------------------------------------------------

    def _check_jit_site(self, path: str, node: ast.Call,
                        jit_call: ast.Call):
        scope = scope_of(node)
        # `@functools.partial(jax.jit, ...)` / `@jax.jit` decorating a
        # function: the construction site IS the decorated function's
        # scope, and the decorated function is the jitted body
        parent = getattr(node, "_bng_parent", None)
        decorated = (parent if isinstance(
            parent, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node in parent.decorator_list else None)
        fn = (enclosing_function(decorated) if decorated is not None
              else enclosing_function(node))
        if fn is not None and not _has_cache_decorator(fn):
            # constructed inside a function body: cached factory or bust
            yield Finding(
                "BNG010", path, node.lineno,
                f"jax.jit constructed inside `{fn.name}` without "
                f"functools.lru_cache — a new trace cache per call "
                f"(the `_pipeline_jit` factory pattern is the fix)",
                scope=scope, detail=f"jit-in-{fn.name}")
        # donation audit: does the jitted function apply table updates?
        if decorated is not None:
            inner = decorated
        else:
            target = jit_call.args[1] if (dotted(jit_call.func).endswith(
                "partial") and len(jit_call.args) > 1) else (
                jit_call.args[0] if jit_call.args else None)
            inner = self._resolve_local_fn(node, target)
        must_donate = APPLY_FNS | EXPRESS_ENTRY_FNS
        applies = False
        if inner is not None:
            applies = any(isinstance(n, ast.Call)
                          and call_name(n) in must_donate
                          for n in ast.walk(inner))
        elif fn is not None:
            # factory whose inner fn we couldn't chase (shard_map wrap):
            # any sibling local function applying updates counts
            applies = any(
                isinstance(s, ast.FunctionDef) and any(
                    isinstance(n, ast.Call) and call_name(n) in must_donate
                    for n in ast.walk(s))
                for s in ast.walk(fn))
        if applies:
            donate = (_kwarg(jit_call, "donate_argnums")
                      or _kwarg(jit_call, "donate_argnames"))
            if donate is None:
                yield Finding(
                    "BNG011", path, node.lineno,
                    "jitted step applies table updates (or runs the "
                    "express probe program) but has no donate_argnums — "
                    "the pre-step table buffers stay live and HBM holds "
                    "every table twice",
                    scope=scope, detail="missing-donate")
        # unhashable static args
        for kw_name in ("static_argnums", "static_argnames"):
            v = _kwarg(jit_call, kw_name)
            if isinstance(v, (ast.List, ast.Dict, ast.Set)):
                yield Finding(
                    "BNG012", path, node.lineno,
                    f"{kw_name} given a literal {type(v).__name__} — "
                    f"static argnums must be hashable tuples",
                    scope=scope, detail=f"unhashable-{kw_name}")

    def _check_bare_jit(self, path: str, dec: ast.AST,
                        decorated: ast.FunctionDef):
        """`@jax.jit` with no call parentheses — an ast.Attribute/Name,
        invisible to the Call walk above. Same BNG010 rule (construction
        happens when the enclosing function body runs), and BNG011 is
        unconditional on a table-applying body: the bare form cannot
        carry donate_argnums at all."""
        scope = scope_of(dec)
        fn = enclosing_function(decorated)
        if fn is not None and not _has_cache_decorator(fn):
            yield Finding(
                "BNG010", path, dec.lineno,
                f"jax.jit constructed inside `{fn.name}` without "
                f"functools.lru_cache — a new trace cache per call "
                f"(the `_pipeline_jit` factory pattern is the fix)",
                scope=scope, detail=f"jit-in-{fn.name}")
        if any(isinstance(n, ast.Call)
               and call_name(n) in (APPLY_FNS | EXPRESS_ENTRY_FNS)
               for n in ast.walk(decorated)):
            yield Finding(
                "BNG011", path, dec.lineno,
                "jitted step applies table updates (or runs the "
                "express probe program) but has no donate_argnums — "
                "the pre-step table buffers stay live and HBM holds "
                "every table twice",
                scope=scope, detail="missing-donate")

    @staticmethod
    def _resolve_local_fn(site: ast.AST, target: ast.AST | None):
        """Chase a Name/Lambda jit target to a local FunctionDef."""
        if isinstance(target, ast.Lambda):
            return target
        if not isinstance(target, ast.Name):
            return None
        fn = enclosing_function(site)
        space = fn.body if fn is not None else []
        for stmt in space:
            if (isinstance(stmt, ast.FunctionDef)
                    and stmt.name == target.id):
                return stmt
        return None

    # -- BNG012 at step call sites ---------------------------------------

    def _check_step_call(self, path: str, node: ast.Call):
        if call_name(node) not in STEP_CALLEES:
            return
        if not isinstance(node.func, ast.Attribute):
            return
        scope = scope_of(node)
        for i, arg in enumerate(node.args):
            bad = None
            if (isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Name)
                    and arg.func.id in ("int", "float")):
                bad = f"{arg.func.id}(...)"
            elif isinstance(arg, ast.BinOp):
                bad = "arithmetic expression"
            elif (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, (int, float))
                    and not isinstance(arg.value, bool)):
                bad = repr(arg.value)
            if bad is not None:
                yield Finding(
                    "BNG012", path, arg.lineno,
                    f"bare Python scalar ({bad}) as traced arg {i} of a "
                    f"jitted step — wrap it fixed-width at the call site "
                    f"(np.uint32(...)/np.float32(...)) or weak-type "
                    f"drift retraces per batch",
                    scope=scope, detail=f"scalar-arg-{i}")
