"""Error-handler audit (BNG020/BNG021) — the Yuan et al. OSDI'14 pass.

The failure study behind this pass found 92% of catastrophic
distributed-system failures rooted in *already-signaled* errors that a
handler then mishandled — and that the three dominant anti-patterns
(empty handler, catch-all that "logs and continues" without logging,
TODO handlers) are trivially statically checkable. Scope here is
`control/` and `runtime/` (the subsystems whose swallowed errors cost
leases, table rows or checkpoints), per ISSUE 6.

* **BNG020** — a broad handler (`except:`, `except Exception`,
  `except BaseException`) whose body is only `pass`/`...`: the error is
  fully swallowed.
* **BNG021** — a broad handler that neither re-raises, returns an error
  signal, structlogs, bumps a metric, nor increments an error counter:
  the error is converted to silence. A handler that does ANY of those
  is fine — the pass checks signal propagation, not style.

Narrow handlers (`except ValueError: pass`) are accepted: catching a
specific, expected signal and discarding it is the Pythonic non-local
`if`, and flagging it would bury the real findings in noise.
"""

from __future__ import annotations

import ast

from bng_tpu.analysis.core import Finding, Pass, Project, call_name, scope_of

SCOPE_PREFIXES = ("bng_tpu/control/", "bng_tpu/runtime/")

BROAD = {"Exception", "BaseException"}
LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical",
               "log", "report"}
METRIC_METHODS = {"inc", "dec", "observe", "set", "set_total", "add"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        tail = n.attr if isinstance(n, ast.Attribute) else (
            n.id if isinstance(n, ast.Name) else "")
        if tail in BROAD:
            return True
    return False


def _is_pass_only(handler: ast.ExceptHandler) -> bool:
    body = handler.body
    if (body and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)):
        body = body[1:]  # a docstring-style comment doesn't handle anything
    if not body:
        return True
    return all(isinstance(s, ast.Pass) or
               (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant)
                and s.value.value is Ellipsis)
               for s in body)


def _signals(handler: ast.ExceptHandler) -> bool:
    """Does the handler propagate the error signal anywhere?"""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Continue, ast.Break)):
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in LOG_METHODS or name in METRIC_METHODS:
                return True
            if name in ("print",):  # stderr diagnostics in CLI paths
                return True
        if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
            # error-counter convention: self.stats.slow_errors += 1 etc.
            return True
    return False


class HandlerAuditPass(Pass):
    name = "handler-audit"
    description = ("no swallowed broad exception handlers in control/ "
                   "and runtime/ (Yuan OSDI'14)")
    codes = {
        "BNG020": "broad except with pass-only body (error fully "
                  "swallowed)",
        "BNG021": "broad except that neither re-raises, logs, nor "
                  "counts",
    }

    def run(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for sf in project.files:
            if not sf.path.startswith(SCOPE_PREFIXES):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad(node):
                    continue
                scope = scope_of(node)
                if _is_pass_only(node):
                    out.append(Finding(
                        "BNG020", sf.path, node.lineno,
                        "broad exception handler swallows the error with "
                        "`pass` — log it (rate-limited structlog), count "
                        "it, or narrow the except",
                        scope=scope, detail="pass-only"))
                elif not _signals(node):
                    out.append(Finding(
                        "BNG021", sf.path, node.lineno,
                        "broad exception handler neither re-raises, "
                        "logs, nor bumps a metric — the signaled error "
                        "becomes silence (Yuan OSDI'14 class)",
                        scope=scope, detail="silent-handler"))
        return out
