"""Concurrency-ownership discipline (BNG060-BNG064) — the `_ctl` rule,
machine-checked (ISSUE 9).

The codebase has five execution contexts touching shared state: the
dataplane run loop, the OpsServer HTTP handler thread ("ctl"), the
metrics scrape path, HA syncer threads, and fleet worker processes.
The discipline — every cross-thread touch of loop-owned state goes
through `_ctl` (or the object's own lock) — was enforced only by
reviewer vigilance, and the last two review passes caught real races
by hand (the PR-7 OpsController check-then-act timeout; `ops_status`
racing loop-side fleet mutations). Yuan et al. (OSDI'14): most
catastrophic failures hide in exactly this kind of untested
error/concurrency interleaving; SAMC (OSDI'14): semantic awareness of
WHICH interleavings matter is what makes checking tractable. Here the
semantics are the context classification facts.py builds from the
repo's own AST (thread entry points -> call graph -> reachable context
sets + guaranteed-held lock sets).

* **BNG060** — an attribute mutated from >=2 thread contexts with no
  common lock across the mutation sites. "worker" is excluded: fleet
  workers run in separate processes (inline mode runs on the calling
  thread, which is already counted).
* **BNG061** — a lock `.acquire()`d without `with` or a try/finally
  release in the same function: an exception between acquire and
  release deadlocks every other context forever.
* **BNG062** — check-then-act: a function reads a shared attribute in
  a test and writes it later, without holding the lock its OTHER
  writers (in other contexts) agree on — the exact PR-7 OpsController
  bug class. Only fires when such a guard lock exists; when the
  writers have no common lock at all that is BNG060's finding.
* **BNG063** — a blocking call (sleep/join/pipe recv/...) while a lock
  is held in a function the run loop reaches: the dataplane stalls for
  the duration. Intentional barriers (the fleet gather IS the batch
  boundary) are baselined with a justification.
* **BNG064** — a Thread created in control/ by a class with no
  stop/join path: an orphan thread outlives close() and races
  teardown.

Like every pass, findings are baselined by the line-independent
identity; a missing fact source (no loop roots, no resolvable thread
target) is a loud BNG990, never a silent no-op.
"""

from __future__ import annotations

from bng_tpu.analysis import facts
from bng_tpu.analysis.core import Finding, Pass, Project

# cli.py rides along: BNGApp is the _ctl discipline's anchor class —
# leaving it out would make the very object the @owned_by stamp guards
# invisible to the static half
SCOPE_PREFIXES = ("bng_tpu/control/", "bng_tpu/runtime/", "bng_tpu/cli.py")
THREAD_SCOPE = ("bng_tpu/control/",)


def _racy(ctxs: set) -> frozenset:
    return frozenset(c for c in ctxs if c not in facts.NON_RACY_CONTEXTS)


class ConcurrencyPass(Pass):
    name = "concurrency"
    description = ("thread-ownership discipline: cross-context mutations "
                   "hold a common lock; no check-then-act, unreleased "
                   "acquires, blocking under loop locks, or orphan "
                   "threads")
    codes = {
        "BNG060": "attribute mutated from >=2 thread contexts with no "
                  "common lock",
        "BNG061": "lock acquired without `with`/try-finally release",
        "BNG062": "check-then-act on a shared attribute without the "
                  "writers' lock",
        "BNG063": "blocking call inside a held lock reachable from the "
                  "run loop",
        "BNG064": "Thread created in control/ without a stop/join path",
    }

    def run(self, project: Project) -> list[Finding]:
        model = facts.build_concurrency_model(project)
        out: list[Finding] = []
        for detail in model.missing_facts:
            out.append(self.config_finding(
                detail, f"concurrency fact source missing: {detail} — "
                        f"context classification would be blind"))
        for rec in model.unresolved:
            out.append(Finding(
                "BNG990", rec.get("path", "<analyzer>"),
                rec.get("line", 0),
                "thread entry point's target could not be resolved to a "
                "function — its context (and everything it mutates) is "
                "invisible to the concurrency pass",
                scope=rec.get("qual", ""),
                detail=f"thread-target:{rec.get('qual', '?')}"))

        sites = self._mutation_sites(model)
        flagged_060 = self._bng060(model, sites, out)
        self._bng062(model, sites, flagged_060, out)
        self._bng061(model, out)
        self._bng063(model, out)
        self._bng064(model, out)
        return out

    # -- shared: mutation sites per (class, attr) ------------------------

    def _mutation_sites(self, model) -> dict:
        """{(class identity, attr) -> [(fid, line, locks, contexts)]}
        over scoped files, reachable functions only. Class identity is
        (path, enclosing qual) — two same-named classes in different
        modules (every HTTP `Handler`) must NOT merge into one site
        list, or their disjoint contexts would fabricate a BNG060."""
        sites: dict = {}
        for fid, fact in model.functions.items():
            if fact.cls is None or not fact.path.startswith(SCOPE_PREFIXES):
                continue
            if fact.qual.rsplit(".", 1)[-1] in ("__init__", "__post_init__"):
                # writes in a constructor precede publication: no other
                # context can hold the object yet
                continue
            ctxs = _racy(model.contexts.get(fid, set()))
            if not ctxs:
                continue
            held = model.held.get(fid, frozenset())
            resolved = model.resolved_lines.get(fid, ())
            cls_id = (fact.path, fact.qual.rsplit(".", 1)[0])
            for attr, line, locks, kind in fact.writes:
                if kind == "mutcall" and line in resolved:
                    continue  # the callee's own writes carry the check
                sites.setdefault((cls_id, attr), []).append(
                    (fid, line, held | frozenset(locks), ctxs))
        return sites

    # -- BNG060 ----------------------------------------------------------

    def _bng060(self, model, sites, out: list[Finding]) -> set:
        flagged: set = set()
        for (cls_id, attr), rows in sorted(sites.items()):
            all_ctx: set = set()
            for _fid, _line, _locks, ctxs in rows:
                all_ctx |= ctxs
            if len(all_ctx) < 2:
                continue
            common = frozenset.intersection(
                *[locks for _f, _l, locks, _c in rows])
            if common:
                continue
            fid, line, _locks, _ctxs = sorted(rows)[0]
            fact = model.functions[fid]
            flagged.add((cls_id, attr))
            out.append(Finding(
                "BNG060", fact.path, line,
                f"`{fact.cls}.{attr}` is mutated from contexts "
                f"{{{', '.join(sorted(all_ctx))}}} with no common lock "
                f"across the mutation sites — take the owning lock at "
                f"every writer or hand one context a snapshot API",
                scope=fact.qual, detail=f"{fact.cls}.{attr}"))
        return flagged

    # -- BNG062 ----------------------------------------------------------

    def _bng062(self, model, sites, flagged_060: set,
                out: list[Finding]) -> None:
        emitted: set = set()
        for fid, fact in sorted(model.functions.items()):
            if fact.cls is None or not fact.path.startswith(SCOPE_PREFIXES):
                continue
            ctxs = _racy(model.contexts.get(fid, set()))
            if not ctxs or not fact.test_reads:
                continue
            held = model.held.get(fid, frozenset())
            cls_id = (fact.path, fact.qual.rsplit(".", 1)[0])
            written_attrs = {w[0] for w in fact.writes}
            for attr, line, locks in fact.test_reads:
                if attr not in written_attrs:
                    continue  # read-only test: not check-then-act
                if (cls_id, attr) in flagged_060:
                    continue  # already the stronger finding
                others = [r for r in sites.get((cls_id, attr), ())
                          if r[0] != fid and (r[3] - ctxs)]
                if not others:
                    continue  # no cross-context writer
                guard = frozenset.intersection(*[r[2] for r in others])
                if not guard:
                    continue  # no agreed guard: BNG060 territory
                # the TEST must hold the guard: a locked write after an
                # unlocked test still acts on a stale decision (the
                # PR-7 shape — the check passed just before the
                # deadline, the act landed after)
                mine = (frozenset(locks) | held) & guard
                if mine:
                    continue
                key = (fid, attr)
                if key in emitted:
                    continue
                emitted.add(key)
                out.append(Finding(
                    "BNG062", fact.path, line,
                    f"check-then-act on `{fact.cls}.{attr}`: tested here "
                    f"and written later without "
                    f"{{{', '.join(sorted(guard))}}} — the lock its "
                    f"cross-context writers hold (the PR-7 OpsController "
                    f"timeout bug class); the test result is stale by "
                    f"the time the write lands",
                    scope=fact.qual, detail=f"{fact.cls}.{attr}"))

    # -- BNG061 ----------------------------------------------------------

    def _bng061(self, model, out: list[Finding]) -> None:
        for fid, fact in sorted(model.functions.items()):
            if not fact.path.startswith(SCOPE_PREFIXES):
                continue
            safe = set(fact.releases_final)
            for tok, line in fact.acquires:
                if tok in safe:
                    continue
                out.append(Finding(
                    "BNG061", fact.path, line,
                    f"`{tok}.acquire()` without `with` or a try/finally "
                    f"release in the same function — an exception here "
                    f"deadlocks every other context on {tok} forever",
                    scope=fact.qual, detail=f"acquire:{tok}"))

    # -- BNG063 ----------------------------------------------------------

    def _bng063(self, model, out: list[Finding]) -> None:
        for fid, fact in sorted(model.functions.items()):
            if not fact.path.startswith(SCOPE_PREFIXES):
                continue
            if facts.CONTEXT_LOOP not in model.contexts.get(fid, set()):
                continue
            held = model.held.get(fid, frozenset())
            seen: set = set()
            for name, line, locks in fact.blocking:
                all_locks = held | frozenset(locks)
                if not all_locks or name in seen:
                    continue
                seen.add(name)
                out.append(Finding(
                    "BNG063", fact.path, line,
                    f"blocking `{name}()` while holding "
                    f"{{{', '.join(sorted(all_locks))}}} in a function "
                    f"the run loop reaches — the dataplane stalls for "
                    f"the full wait; move the block outside the lock or "
                    f"baseline with the justification that the pause IS "
                    f"the design",
                    scope=fact.qual, detail=f"{name}@{fact.qual}"))

    # -- BNG064 ----------------------------------------------------------

    def _bng064(self, model, out: list[Finding]) -> None:
        for rec in model.spawns:
            if rec["kind"] != "thread":
                continue
            path = rec.get("path", "")
            if not path.startswith(THREAD_SCOPE):
                continue
            if rec.get("has_stop"):
                continue
            # a cancel-closure nested in the spawning function also
            # counts as a stop path (the SSE reader idiom)
            fid = rec.get("fid", "")
            has_cancel = False
            if model.functions.get(fid) is not None:
                # nested defs of the spawning function live under its
                # qual prefix; one calling `<event>.set()` / `.join()`
                # is the cancel path (attribute calls only — a bare
                # `set()` is the builtin constructor, not a stop)
                prefix = fid + "."
                for ofid, ofact in model.functions.items():
                    if ofid.startswith(prefix) and any(
                            c.get("m") in ("set", "join")
                            for c in ofact.calls):
                        has_cancel = True
                        break
            if has_cancel:
                continue
            out.append(Finding(
                "BNG064", path, rec.get("line", 0),
                "Thread created with no stop/join path: the enclosing "
                "class has no stop/close/shutdown method and the "
                "spawning function builds no cancel closure — the "
                "thread outlives close() and races teardown",
                scope=rec.get("qual", ""),
                detail=f"thread:{rec.get('qual', '?')}"))
