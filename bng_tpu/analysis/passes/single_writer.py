"""Single-writer discipline for the device-mirror tables (BNG040/BNG041).

The fast-path tables have exactly one consistency story: host mirrors
are mutated by a small set of owner modules, deltas drain through the
bounded update batch into ONE donated jitted step, and everything else
reads. The chaos auditor proves the runtime half (host == device after
drain); this pass pins the static half — a new module that starts
calling `fastpath.add_subscriber(...)` or assigning `engine.tables`
bypasses the event-log replay and the auditor's assumptions.

* **BNG040** — a fast-path/device-mirror mutator called outside the
  allowlisted writer modules.
* **BNG041** — direct assignment to an engine's `.tables` outside the
  engine/restore modules (rebinding the device table pytree is the
  engine's own job; everyone else goes through resync/restore).

The allowlist is part of the invariant, reviewed like code: each entry
says WHY that module writes.
"""

from __future__ import annotations

import ast

from bng_tpu.analysis.core import Finding, Pass, Project, dotted, scope_of

# FastPathTables / QoS / antispoof / garden / pppoe mutating surface +
# the HostTable primitives they wrap
MUTATORS = {
    "add_subscriber", "remove_subscriber", "bulk_add_subscribers",
    "add_vlan_subscriber", "remove_vlan_subscriber",
    "add_pool", "set_server_config",
    "add_circuit_id", "remove_circuit_id",
    "insert", "bulk_insert",
    "set_gardened", "allow_destination",
    "set_subscriber", "bulk_set_subscribers",
    "add_binding", "add_binding_v6", "remove_binding",
    "resync_tables", "restore_arrays",
    "arm_tap", "disarm_tap", "set_tap_filters",
    "set_route", "clear_route",
    "fill_slot", "adopt_cursors",
    "watch", "reset", "reset_peer",
    "set_manifest", "accept_chunk",
}

# writer modules (path suffix -> why it is allowed to write)
ALLOWED_WRITERS = {
    "bng_tpu/runtime/tables.py": "the host authority itself",
    "bng_tpu/runtime/engine.py": "owns the device mirrors + drain",
    "bng_tpu/runtime/checkpoint.py": "restore hydration path",
    "bng_tpu/runtime/verify.py": "lowering verification builds fixtures",
    "bng_tpu/runtime/scheduler.py": "bulk replica management",
    "bng_tpu/control/dhcp_server.py": "DHCP lease lifecycle writer",
    "bng_tpu/control/fleet.py": "table-event-log replay (single writer)",
    "bng_tpu/control/pool.py": "pool provisioning",
    "bng_tpu/control/agent.py": "provisioning agent (composition root)",
    "bng_tpu/control/subscriber.py": "subscriber lifecycle manager",
    "bng_tpu/control/nat.py": "NAT host authority",
    "bng_tpu/control/statestore.py": "checkpoint store hydration",
    "bng_tpu/parallel/sharded.py": "sharded engine owns its shard tables",
    "bng_tpu/cli.py": "composition root provisioning",
    "bng_tpu/chaos/scenarios.py": "scenario fixtures build table state",
    "bng_tpu/chaos/storms.py": "storm fixtures build table state (same "
                               "role as scenarios.py; the CoA qos_hook "
                               "IS the cli composition-root hook, built "
                               "standalone)",
    "bng_tpu/chaos/invariants.py": "auditor drains pending deltas",
    "bng_tpu/loadtest/harness.py": "loadtest provisioning",
    "bng_tpu/cluster/instance.py": "cluster member composition root: "
                                   "builds its own instance's pools + "
                                   "fastpath from the carved spec "
                                   "(same role as cli.py, per member)",
    "bench.py": "bench provisioning",
    "bng_tpu/edge/tables.py": "edge host authority (tap/route mirrors)",
    "bng_tpu/edge/compile.py": "warrant/route compilers are the edge "
                               "tables' owning managers",
    "bng_tpu/devloop/ring.py": "descriptor-ring host authority: "
                               "fill_slot/adopt_cursors ARE the ring "
                               "cursor mutators (ISSUE 18)",
    "bng_tpu/devloop/host.py": "the devloop pump owns its ring: slot "
                               "fills at admission, cursor adoption at "
                               "retire — a writer outside the pump "
                               "bypasses the quiesce/audit story",
    "bng_tpu/cluster/coordinator.py": "fabric membership authority "
                                      "(ISSUE 19): watches slots on "
                                      "plan apply, resets the view + "
                                      "transport replay floor on "
                                      "promote — a second writer "
                                      "desyncs verdicts from the HA "
                                      "ladder",
    "bng_tpu/cluster/handoff/protocol.py":
        "state-transfer authority (ISSUE 20): set_manifest/accept_chunk "
        "advance the receiver's ACK cursor and chunk map — a second "
        "writer could half-hydrate a member past the digest gate",
}

# receiver names that mark the call as a fast-path table mutation
# (x.insert() on a dict-like in unrelated code must not trip the pass)
TABLE_RECEIVERS = {
    "fastpath", "tables", "sub", "vlan", "cid", "bindings", "subscribers",
    "qos", "up", "down", "antispoof", "garden", "pppoe", "by_sid", "by_ip",
    "edge", "tap", "route", "ring", "devloop", "cursors",
    "fabric_detector", "fabric_transport",
    "handoff", "receiver",
}


def _receiver_chain(node: ast.Call) -> list[str]:
    """Attribute names of the receiver: self.fastpath.sub.insert ->
    ["self", "fastpath", "sub"]."""
    parts: list[str] = []
    cur = node.func
    if isinstance(cur, ast.Attribute):
        cur = cur.value
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
    return parts


class SingleWriterPass(Pass):
    name = "single-writer"
    description = ("fast-path table mutators called only from the "
                   "allowlisted writer modules")
    codes = {
        "BNG040": "fast-path table mutator outside the writer allowlist",
        "BNG041": "engine.tables rebound outside the engine/restore "
                  "modules",
    }

    def run(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for sf in project.files:
            allowed = any(sf.path.endswith(suffix)
                          for suffix in ALLOWED_WRITERS)
            if allowed:
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    out.extend(self._check_call(sf, node))
                elif isinstance(node, ast.Assign):
                    out.extend(self._check_tables_assign(sf, node))
        return out

    def _check_call(self, sf, node: ast.Call):
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in MUTATORS:
            return
        chain = _receiver_chain(node)
        if not any(p in TABLE_RECEIVERS for p in chain):
            return
        yield Finding(
            "BNG040", sf.path, node.lineno,
            f"`{dotted(node.func)}()` mutates a fast-path table from a "
            f"non-writer module — route it through the owning manager "
            f"(or extend the reviewed allowlist in "
            f"analysis/passes/single_writer.py with a justification)",
            scope=scope_of(node), detail=node.func.attr)

    def _check_tables_assign(self, sf, node: ast.Assign):
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute) and tgt.attr == "tables"
                    and not isinstance(tgt.value, ast.Name)
                    or isinstance(tgt, ast.Attribute)
                    and tgt.attr == "tables"
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id != "self"):
                yield Finding(
                    "BNG041", sf.path, node.lineno,
                    "rebinding `<engine>.tables` outside the engine — "
                    "the device table pytree has one writer; use "
                    "resync_tables()/restore paths",
                    scope=scope_of(node), detail="tables-assign")
