"""Device-time fencing (BNG050) — no timing of async dispatches without
a force.

The gray-failure class that let three bench rounds publish CPU numbers
as TPU headlines (VERDICT r5, PR 5 postmortem): a wall-clock window
around an ASYNC jitted dispatch measures enqueue cost, not device time.
The telemetry design rule is explicit — device time comes only from
`profiling.profile_step_durations` (block_until_ready inside the
capture) or a window that contains its own force.

The pass finds function-local timing windows:

    t1 = time.perf_counter()          # origin
    ... statements ...
    lat = time.perf_counter() - t1    # close

and flags windows that contain a dispatch to one of the async step
surfaces (`_step`, `_dhcp_step`, `_dispatch_step`, `_run_dhcp_batch`,
`dispatch_scheduled_bulk`, `submit`/`poll`, `process_ring_pipelined`)
but no fence (`block_until_ready`, `device_get`, `np.asarray`,
`flush`/`flush_pipeline`/`quiesce`, `profile_step_durations`, `.item`).
Synchronous surfaces (`process`, `process_dhcp`, `process_ring`) force
their own outputs and are not dispatch hazards.
"""

from __future__ import annotations

import ast

from bng_tpu.analysis.core import Finding, Pass, Project, call_name, dotted

CLOCK_CALLS = {"time.time", "time.perf_counter", "time.perf_counter_ns",
               "time.monotonic", "perf_counter", "perf_counter_ns",
               "monotonic"}
ASYNC_DISPATCH = {"_step", "_dhcp_step", "_dispatch_step",
                  "_run_dhcp_batch", "dispatch_scheduled_bulk",
                  "submit", "poll", "process_ring_pipelined", "step_fn"}
FENCES = {"block_until_ready", "device_get", "asarray", "array", "item",
          "flush", "flush_pipeline", "quiesce", "profile_step_durations",
          "drain_completions_blocking", "wait"}


def _clock_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted(node.func) in CLOCK_CALLS)


class FencingPass(Pass):
    name = "fencing"
    description = ("wall-clock windows over async dispatches must "
                   "contain a force/fence")
    codes = {
        "BNG050": "timing window over an async device dispatch without "
                  "block_until_ready or another force",
    }

    def run(self, project: Project) -> list[Finding]:
        out: list[Finding] = []
        for sf in project.files:
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.extend(self._check_fn(sf, node))
        return out

    def _check_fn(self, sf, fn: ast.FunctionDef):
        stmts = self._flat_statements(fn)
        origins: dict[str, int] = {}  # clock var -> stmt index
        for idx, stmt in enumerate(stmts):
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and _clock_call(stmt.value)):
                origins[stmt.targets[0].id] = idx
                continue
            for var, start in list(origins.items()):
                if self._closes_window(stmt, var):
                    yield from self._check_window(
                        sf, fn, stmts[start + 1: idx + 1], stmt.lineno, var)
                    origins.pop(var, None)

    @staticmethod
    def _flat_statements(fn: ast.FunctionDef) -> list[ast.stmt]:
        """Statement stream in source order, descending into compound
        bodies (a window often opens before a loop and closes after)."""
        out: list[ast.stmt] = []

        def walk(body):
            for s in body:
                out.append(s)
                for attr in ("body", "orelse", "finalbody"):
                    inner = getattr(s, attr, None)
                    if inner:
                        walk(inner)
                for h in getattr(s, "handlers", ()):
                    walk(h.body)

        walk(fn.body)
        return out

    @staticmethod
    def _closes_window(stmt: ast.stmt, var: str) -> bool:
        """Does this statement compute `time.X() - var`?"""
        for node in ast.walk(stmt):
            if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                    and isinstance(node.right, ast.Name)
                    and node.right.id == var
                    and _clock_call(node.left)):
                return True
        return False

    def _check_window(self, sf, fn, window: list[ast.stmt],
                      close_line: int, var: str):
        dispatched = None
        fenced = False
        for stmt in window:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in ASYNC_DISPATCH and dispatched is None:
                    dispatched = (name, node.lineno)
                if name in FENCES:
                    fenced = True
        if dispatched is not None and not fenced:
            name, line = dispatched
            yield Finding(
                "BNG050", sf.path, close_line,
                f"timing window `{var}` (closed here) spans the async "
                f"dispatch `{name}` (line {line}) with no "
                f"block_until_ready/force — this measures enqueue cost, "
                f"not device time (the CPU-headline gray-failure class)",
                scope=f"{fn.name}", detail=f"{var}-{name}")
