"""Fact extraction: the repo's registries, read back out of its AST.

The registry-consistency passes check call sites against the *declared*
vocabularies — the span stage constants in telemetry/spans.py, the
fault-point registry in chaos/faults.py, the metric families in
control/metrics.py, the flight-recorder trigger reasons in
telemetry/recorder.py, and the checkpoint component keys in
runtime/checkpoint.py. All of these are parsed from source (never
imported), so the analyzer stays in lockstep with the code it checks:
renaming a stage constant updates the vocabulary and the check in the
same commit, and a fixture tree carrying miniature fact files gets a
consistent miniature vocabulary.

Every extractor returns None when its source file or declaration shape
is missing — the dependent pass turns that into a loud BNG990 config
finding instead of silently checking nothing.
"""

from __future__ import annotations

import ast

from bng_tpu.analysis.core import Project, str_const

SPANS_FILE = "bng_tpu/telemetry/spans.py"
FAULTS_FILE = "bng_tpu/chaos/faults.py"
RECORDER_FILE = "bng_tpu/telemetry/recorder.py"
CHECKPOINT_FILE = "bng_tpu/runtime/checkpoint.py"


def stage_vocabulary(project: Project) -> tuple[set[str], set[str]] | None:
    """(stage constant names, lane constant names) from spans.py — the
    tuple-unpacking assignments `(RING, ...) = range(N)` whose names are
    kept in lockstep with STAGE_NAMES/LANE_NAMES."""
    sf = project.find_file(SPANS_FILE)
    if sf is None:
        return None
    stages: set[str] = set()
    lanes: set[str] = set()
    for node in sf.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Tuple):
            continue
        names = [e.id for e in tgt.elts if isinstance(e, ast.Name)]
        if not names:
            continue
        if all(n.startswith("LANE_") for n in names):
            lanes.update(names)
        elif any(n in ("RING", "DISPATCH", "TOTAL") for n in names):
            stages.update(names)
    if not stages:
        return None
    return stages, lanes


def fault_registry(project: Project) -> set[str] | None:
    """Keys of POINT_KINDS in chaos/faults.py — the fault-point IDs the
    soak generator may draw and the call sites may reference."""
    sf = project.find_file(FAULTS_FILE)
    if sf is None:
        return None
    for node in sf.tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if (isinstance(tgt, ast.Name) and tgt.id == "POINT_KINDS"
                    and isinstance(value, ast.Dict)):
                keys = {str_const(k) for k in value.keys}
                keys.discard(None)
                return keys  # type: ignore[return-value]
    return None


def trigger_reasons(project: Project) -> set[str] | None:
    """Flight-recorder anomaly reasons: the TRIG_* string constants in
    telemetry/recorder.py."""
    sf = project.find_file(RECORDER_FILE)
    if sf is None:
        return None
    reasons: set[str] = set()
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.startswith("TRIG_")):
            v = str_const(node.value)
            if v:
                reasons.add(v)
    return reasons or None


def checkpoint_components(project: Project) -> dict | None:
    """Checkpoint component-key symmetry facts from runtime/checkpoint.py:

      save     — keys assigned via  meta["components"]["X"] = ...
      restore  — keys of the `targets = {...}` dict literal in the
                 restore path, plus keys tested with  "X" in comps
      payload  — the _PAYLOAD_JSON_COMPONENTS tuple

    Returns {"save": set, "restore": set, "payload": set, "line": int}.
    """
    sf = project.find_file(CHECKPOINT_FILE)
    if sf is None:
        return None
    save: set[str] = set()
    restore: set[str] = set()
    payload: set[str] = set()
    line = 1
    for node in ast.walk(sf.tree):
        # meta["components"]["X"] = ...
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Subscript)
                    and str_const(tgt.value.slice) == "components"):
                key = str_const(tgt.slice)
                if key:
                    save.add(key)
            # targets = {...}
            if (isinstance(tgt, ast.Name) and tgt.id == "targets"
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    key = str_const(k)
                    if key:
                        restore.add(key)
                line = node.lineno
            # _PAYLOAD_JSON_COMPONENTS = (...)
            if (isinstance(tgt, ast.Name)
                    and tgt.id == "_PAYLOAD_JSON_COMPONENTS"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                for e in node.value.elts:
                    key = str_const(e)
                    if key:
                        payload.add(key)
        # "X" in comps  (restore-side consumption)
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            if (isinstance(node.ops[0], ast.In)
                    and isinstance(node.comparators[0], ast.Name)
                    and node.comparators[0].id == "comps"):
                key = str_const(node.left)
                if key:
                    restore.add(key)
    if not save and not restore:
        return None
    # the statestore also declares payload components; fold them in
    ss = project.find_file("bng_tpu/control/statestore.py")
    if ss is not None:
        for node in ast.walk(ss.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "_PAYLOAD_JSON_COMPONENTS"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                for e in node.value.elts:
                    key = str_const(e)
                    if key:
                        payload.add(key)
    return {"save": save, "restore": restore, "payload": payload,
            "line": line}
