"""Fact extraction: the repo's registries, read back out of its AST.

The registry-consistency passes check call sites against the *declared*
vocabularies — the span stage constants in telemetry/spans.py, the
fault-point registry in chaos/faults.py, the metric families in
control/metrics.py, the flight-recorder trigger reasons in
telemetry/recorder.py, and the checkpoint component keys in
runtime/checkpoint.py. All of these are parsed from source (never
imported), so the analyzer stays in lockstep with the code it checks:
renaming a stage constant updates the vocabulary and the check in the
same commit, and a fixture tree carrying miniature fact files gets a
consistent miniature vocabulary.

Every extractor returns None when its source file or declaration shape
is missing — the dependent pass turns that into a loud BNG990 config
finding instead of silently checking nothing.

The second half of this module (ISSUE 9) is the **concurrency fact
layer**: thread entry points discovered from the repo's own AST
(`threading.Thread(target=...)`, HTTP handler classes, multiprocessing
targets, metrics scrape sources, the OpsController queue drain), a
module-level call graph with best-effort type resolution (parameter
annotations, `self.x = ClassName(...)` attribute types, the BNGApp
components-dict idiom, unique-method-name fallback), and a fixpoint
propagation that classifies every function by its reachable context
set and the lock set it is guaranteed to hold. The per-file extraction
is cached on disk keyed by (mtime, size) so `make verify-static`
stays inside its budget on warm runs.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path

from bng_tpu.analysis.core import Project, str_const

SPANS_FILE = "bng_tpu/telemetry/spans.py"
FAULTS_FILE = "bng_tpu/chaos/faults.py"
RECORDER_FILE = "bng_tpu/telemetry/recorder.py"
CHECKPOINT_FILE = "bng_tpu/runtime/checkpoint.py"


def stage_vocabulary(project: Project) -> tuple[set[str], set[str]] | None:
    """(stage constant names, lane constant names) from spans.py — the
    tuple-unpacking assignments `(RING, ...) = range(N)` whose names are
    kept in lockstep with STAGE_NAMES/LANE_NAMES."""
    sf = project.find_file(SPANS_FILE)
    if sf is None:
        return None
    stages: set[str] = set()
    lanes: set[str] = set()
    for node in sf.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Tuple):
            continue
        names = [e.id for e in tgt.elts if isinstance(e, ast.Name)]
        if not names:
            continue
        if all(n.startswith("LANE_") for n in names):
            lanes.update(names)
        elif any(n in ("RING", "DISPATCH", "TOTAL") for n in names):
            stages.update(names)
    if not stages:
        return None
    return stages, lanes


def fault_registry(project: Project) -> set[str] | None:
    """Keys of POINT_KINDS in chaos/faults.py — the fault-point IDs the
    soak generator may draw and the call sites may reference."""
    sf = project.find_file(FAULTS_FILE)
    if sf is None:
        return None
    for node in sf.tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for tgt in targets:
            if (isinstance(tgt, ast.Name) and tgt.id == "POINT_KINDS"
                    and isinstance(value, ast.Dict)):
                keys = {str_const(k) for k in value.keys}
                keys.discard(None)
                return keys  # type: ignore[return-value]
    return None


def trigger_reasons(project: Project) -> set[str] | None:
    """Flight-recorder anomaly reasons: the TRIG_* string constants in
    telemetry/recorder.py."""
    sf = project.find_file(RECORDER_FILE)
    if sf is None:
        return None
    reasons: set[str] = set()
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.startswith("TRIG_")):
            v = str_const(node.value)
            if v:
                reasons.add(v)
    return reasons or None


def checkpoint_components(project: Project) -> dict | None:
    """Checkpoint component-key symmetry facts from runtime/checkpoint.py:

      save     — keys assigned via  meta["components"]["X"] = ...
      restore  — keys of the `targets = {...}` dict literal in the
                 restore path, plus keys tested with  "X" in comps
      payload  — the _PAYLOAD_JSON_COMPONENTS tuple

    Returns {"save": set, "restore": set, "payload": set, "line": int}.
    """
    sf = project.find_file(CHECKPOINT_FILE)
    if sf is None:
        return None
    save: set[str] = set()
    restore: set[str] = set()
    payload: set[str] = set()
    line = 1
    for node in ast.walk(sf.tree):
        # meta["components"]["X"] = ...
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Subscript)
                    and str_const(tgt.value.slice) == "components"):
                key = str_const(tgt.slice)
                if key:
                    save.add(key)
            # targets = {...}
            if (isinstance(tgt, ast.Name) and tgt.id == "targets"
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    key = str_const(k)
                    if key:
                        restore.add(key)
                line = node.lineno
            # _PAYLOAD_JSON_COMPONENTS = (...)
            if (isinstance(tgt, ast.Name)
                    and tgt.id == "_PAYLOAD_JSON_COMPONENTS"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                for e in node.value.elts:
                    key = str_const(e)
                    if key:
                        payload.add(key)
        # "X" in comps  (restore-side consumption)
        if isinstance(node, ast.Compare) and len(node.ops) == 1:
            if (isinstance(node.ops[0], ast.In)
                    and isinstance(node.comparators[0], ast.Name)
                    and node.comparators[0].id == "comps"):
                key = str_const(node.left)
                if key:
                    restore.add(key)
    if not save and not restore:
        return None
    # the statestore also declares payload components; fold them in
    ss = project.find_file("bng_tpu/control/statestore.py")
    if ss is not None:
        for node in ast.walk(ss.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "_PAYLOAD_JSON_COMPONENTS"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                for e in node.value.elts:
                    key = str_const(e)
                    if key:
                        payload.add(key)
    return {"save": save, "restore": restore, "payload": payload,
            "line": line}


# ===========================================================================
# Concurrency facts (ISSUE 9): contexts, call graph, locks
# ===========================================================================
#
# Model limits, stated once (the pass docstrings reference them):
#
# * Resolution is deliberately UNDER-approximate: an edge is added only
#   when the receiver's type is known (annotation, constructor
#   assignment, components-dict idiom) or the method name is unique
#   across the project. A missed edge means a function classified in
#   fewer contexts — fewer findings, never false ones.
# * Lock identity is the attribute name ("_ctl", "_lock"), qualified
#   by nothing: two different objects' "_lock" compare equal. That
#   bias SUPPRESSES findings (a fake common lock) rather than
#   inventing them — the right direction for a lint.
# * The "worker" context runs in a separate *process* (inline mode
#   runs on the caller's own thread): it never shares an address space
#   with the thread contexts, so the race rules exclude it.

FACTS_VERSION = 3  # bump to invalidate the on-disk extraction cache
CACHE_NAME = ".bngcheck_cache.json"

CLI_FILE = "bng_tpu/cli.py"
OPSCTL_FILE = "bng_tpu/control/opsctl.py"

# canonical execution contexts; unlisted thread modules get thread:<stem>
CONTEXT_MODULE_MAP = {
    "bng_tpu/control/ha.py": "ha-sync",
    "bng_tpu/control/cluster_http.py": "ha-sync",
    "bng_tpu/control/opsctl.py": "ctl",
    "bng_tpu/control/metrics.py": "scrape",
}
CONTEXT_LOOP = "loop"
CONTEXT_WORKER = "worker"
CONTEXT_SCRAPE = "scrape"

# process isolation: "worker" never shares memory with the thread
# contexts (inline mode runs on the calling thread = already counted)
NON_RACY_CONTEXTS = {CONTEXT_WORKER}

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
# mutating container methods: self.X.append(...) mutates attribute X
MUTATING_METHODS = {"append", "appendleft", "add", "remove", "discard",
                    "clear", "pop", "popleft", "update", "extend",
                    "insert", "put", "put_nowait", "setdefault",
                    "remove_subscriber"}
# calls that block the calling thread (BNG063 inside a held lock)
BLOCKING_CALLS = {"sleep", "join", "recv", "recv_bytes", "accept",
                  "select", "wait"}
# a class with any of these methods is considered to have a thread
# stop/join path (BNG064)
STOP_METHODS = {"stop", "close", "shutdown", "disconnect", "cancel",
                "stop_all", "terminate"}


def _is_lock_name(attr: str, cls_locks: set[str] | None = None) -> bool:
    if cls_locks and attr in cls_locks:
        return True
    return attr == "_ctl" or "lock" in attr.lower()


def _trailing(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


@dataclass
class FnFact:
    """Extraction summary of one function (JSON-serializable)."""

    fid: str
    path: str
    qual: str
    cls: str | None
    line: int
    calls: list = field(default_factory=list)
    writes: list = field(default_factory=list)   # [attr, line, locks, kind]
    test_reads: list = field(default_factory=list)  # [attr, line, locks]
    blocking: list = field(default_factory=list)    # [name, line, locks]
    acquires: list = field(default_factory=list)    # [token, line]
    releases_final: list = field(default_factory=list)  # [token]

    def to_dict(self) -> dict:
        return {"fid": self.fid, "path": self.path, "qual": self.qual,
                "cls": self.cls, "line": self.line, "calls": self.calls,
                "writes": self.writes, "test_reads": self.test_reads,
                "blocking": self.blocking, "acquires": self.acquires,
                "releases_final": self.releases_final}

    @classmethod
    def from_dict(cls, d: dict) -> "FnFact":
        return cls(**d)


@dataclass
class ClassFact:
    name: str
    path: str
    line: int
    bases: list = field(default_factory=list)
    methods: dict = field(default_factory=dict)      # name -> fid
    lock_attrs: list = field(default_factory=list)
    attr_types: dict = field(default_factory=dict)   # attr -> [ClassName]
    subscript_types: dict = field(default_factory=dict)  # key -> [ClassName]
    has_stop: bool = False

    def to_dict(self) -> dict:
        return self.__dict__

    @classmethod
    def from_dict(cls, d: dict) -> "ClassFact":
        return cls(**d)


@dataclass
class FileSummary:
    path: str
    functions: dict = field(default_factory=dict)  # fid -> FnFact
    classes: dict = field(default_factory=dict)    # name -> ClassFact
    moddefs: dict = field(default_factory=dict)    # name -> fid
    localdefs: dict = field(default_factory=dict)  # parent fid -> {name: fid}
    imports: dict = field(default_factory=dict)    # alias -> dotted module
    from_imports: dict = field(default_factory=dict)  # name -> module
    spawns: list = field(default_factory=list)
    bindings: list = field(default_factory=list)   # [Cls, attr, TgtCls, meth]

    def to_dict(self) -> dict:
        return {"path": self.path,
                "functions": {k: v.to_dict()
                              for k, v in self.functions.items()},
                "classes": {k: v.to_dict() for k, v in self.classes.items()},
                "moddefs": self.moddefs, "localdefs": self.localdefs,
                "imports": self.imports, "from_imports": self.from_imports,
                "spawns": self.spawns, "bindings": self.bindings}

    @classmethod
    def from_dict(cls, d: dict) -> "FileSummary":
        out = cls(path=d["path"], moddefs=d["moddefs"],
                  localdefs=d["localdefs"], imports=d["imports"],
                  from_imports=d["from_imports"], spawns=d["spawns"],
                  bindings=d["bindings"])
        out.functions = {k: FnFact.from_dict(v)
                         for k, v in d["functions"].items()}
        out.classes = {k: ClassFact.from_dict(v)
                       for k, v in d["classes"].items()}
        return out


class _FileExtractor:
    """One pass over a file's AST producing its FileSummary."""

    def __init__(self, sf):
        self.sf = sf
        self.out = FileSummary(path=sf.path)

    def run(self) -> FileSummary:
        tree = self.sf.tree
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._imports(node)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._class(node, prefix="")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fid = self._fid(node.name)
                self.out.moddefs[node.name] = fid
                self._function(node, qual=node.name, cls=None, env={})
        return self.out

    # -- helpers ---------------------------------------------------------

    def _fid(self, qual: str) -> str:
        return f"{self.sf.path}::{qual}"

    def _imports(self, node) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                self.out.imports[a.asname or a.name.split(".")[0]] = a.name
        else:
            mod = node.module or ""
            for a in node.names:
                self.out.from_imports[a.asname or a.name] = mod

    # -- classes ---------------------------------------------------------

    def _class(self, node: ast.ClassDef, prefix: str,
               env: dict | None = None) -> None:
        qual = f"{prefix}{node.name}" if not prefix else f"{prefix}.{node.name}"
        cf = ClassFact(name=node.name, path=self.sf.path, line=node.lineno,
                       bases=[_trailing(b) for b in node.bases])
        # one shallow pre-scan of every method for lock attrs/attr types
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mqual = f"{qual}.{item.name}"
                cf.methods[item.name] = self._fid(mqual)
                if item.name in STOP_METHODS:
                    cf.has_stop = True
                self._scan_self_attrs(item, cf)
        self.out.classes.setdefault(node.name, cf)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closure vars of the enclosing function (the nested
                # HTTP-handler-class idiom: `ctl = controller` above the
                # class body) stay visible to the methods
                menv = dict(env or {})
                menv.update(self._param_env(item))
                self._function(item, qual=f"{qual}.{item.name}",
                               cls=node.name, env=menv)
            elif isinstance(item, ast.ClassDef):
                self._class(item, prefix=qual, env=env)

    def _param_env(self, fn) -> dict:
        env = {}
        args = list(fn.args.posonlyargs) + list(fn.args.args) + \
            list(fn.args.kwonlyargs)
        for a in args:
            if a.annotation is not None:
                t = _trailing(a.annotation)
                if t and t[:1].isupper():
                    env[a.arg] = ["cls", t]
        return env

    def _scan_self_attrs(self, fn, cf: ClassFact) -> None:
        """self.X = threading.Lock() / ClassName(...) / annotated param,
        plus components-dict constructor keys (c["k"] = ClassName(...)).
        Chained targets (`a = c["k"] = ClassName()`) register each, and
        repeated keys accumulate candidates (the ha component is an
        ActiveSyncer OR a StandbySyncer depending on role)."""
        ann = self._param_env(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    if isinstance(val, ast.Call):
                        t = _trailing(val.func)
                        if t in LOCK_FACTORIES:
                            if tgt.attr not in cf.lock_attrs:
                                cf.lock_attrs.append(tgt.attr)
                        elif t and t[:1].isupper():
                            got = cf.attr_types.setdefault(tgt.attr, [])
                            if t not in got:
                                got.append(t)
                    elif isinstance(val, ast.Name) and val.id in ann:
                        got = cf.attr_types.setdefault(tgt.attr, [])
                        if ann[val.id][1] not in got:
                            got.append(ann[val.id][1])
                elif isinstance(tgt, ast.Subscript):
                    key = str_const(tgt.slice)
                    if key and isinstance(val, ast.Call):
                        t = _trailing(val.func)
                        if t and t[:1].isupper() and t not in LOCK_FACTORIES:
                            got = cf.subscript_types.setdefault(key, [])
                            if t not in got:
                                got.append(t)

    # -- functions -------------------------------------------------------

    def _function(self, fn, qual: str, cls: str | None, env: dict) -> None:
        fid = self._fid(qual)
        fact = FnFact(fid=fid, path=self.sf.path, qual=qual, cls=cls,
                      line=fn.lineno)
        self.out.functions[fid] = fact
        walker = _BodyWalker(self, fact, cls, dict(env), qual)
        walker.walk(fn.body, frozenset())
        # BNG061 bookkeeping: acquire without a finally-release
        fact.releases_final = sorted(set(fact.releases_final))

    def resolve_type(self, expr, env, cls) -> list | None:
        """Symbolic type of an expression (resolved later at build):
        ["cls", Name] | ["attrof", <Cls|sym>, attr] | ["keyof", Cls, key].
        The attrof base may itself be symbolic (ctl.app -> ["attrof",
        ["cls", "OpsController"], "app"]) — resolution recurses."""
        if isinstance(expr, ast.Name):
            return env.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and cls:
                return ["attrof", cls, expr.attr]
            base = self.resolve_type(expr.value, env, cls)
            if base is not None:
                return ["attrof", base, expr.attr]
            return None
        if isinstance(expr, ast.Subscript):
            key = str_const(expr.slice)
            base_t = self.resolve_type(expr.value, env, cls)
            owner = self._dict_owner(expr.value, env, cls)
            if key and owner:
                return ["keyof", owner, key]
            _ = base_t
            return None
        if isinstance(expr, ast.Call):
            t = _trailing(expr.func)
            if t == "get":
                # c.get("fleet") / self.components.get("fleet")
                recv = expr.func.value if isinstance(expr.func,
                                                    ast.Attribute) else None
                key = str_const(expr.args[0]) if expr.args else None
                owner = self._dict_owner(recv, env, cls) if recv is not None \
                    else None
                if key and owner:
                    return ["keyof", owner, key]
                return None
            if t and t[:1].isupper() and t not in LOCK_FACTORIES:
                return ["cls", t]
        return None

    def _dict_owner(self, expr, env, cls) -> str | None:
        """Which class's subscript_types govern this dict expression?
        Covers `self.components[...]`, and locals aliased to a self
        attribute (`c = self.components`)."""
        if expr is None:
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return cls
        if isinstance(expr, ast.Name):
            t = env.get(expr.id)
            if t and t[0] == "attrof":
                return t[1]
        return None


class _BodyWalker:
    """Statement walker carrying the lexical lock set + local type env."""

    def __init__(self, ex: _FileExtractor, fact: FnFact, cls, env, qual):
        self.ex = ex
        self.fact = fact
        self.cls = cls
        self.env = env
        self.qual = qual
        cf = ex.out.classes.get(cls) if cls else None
        self.cls_locks = set(cf.lock_attrs) if cf else set()

    # -- lock tokens -----------------------------------------------------

    def _lock_token(self, expr) -> str | None:
        t = _trailing(expr)
        if t and _is_lock_name(t, self.cls_locks):
            return t
        return None

    # -- the walk --------------------------------------------------------

    def walk(self, stmts, locks: frozenset) -> None:
        for s in stmts:
            self._stmt(s, locks)

    def _stmt(self, s, locks) -> None:
        if isinstance(s, ast.With):
            inner = set(locks)
            for item in s.items:
                self._expr(item.context_expr, locks)
                tok = self._lock_token(item.context_expr)
                if tok:
                    inner.add(tok)
            self.walk(s.body, frozenset(inner))
        elif isinstance(s, (ast.If, ast.While)):
            self._expr(s.test, locks, is_test=True)
            self.walk(s.body, locks)
            self.walk(s.orelse, locks)
        elif isinstance(s, ast.For):
            self._expr(s.iter, locks)
            self._write_target(s.target, locks, kind="for")
            self.walk(s.body, locks)
            self.walk(s.orelse, locks)
        elif isinstance(s, ast.Try):
            self.walk(s.body, locks)
            for h in s.handlers:
                self.walk(h.body, locks)
            self.walk(s.orelse, locks)
            # record finally-side releases for the acquire check
            for node in s.finalbody:
                for call in ast.walk(node):
                    if isinstance(call, ast.Call) and \
                            _trailing(call.func) == "release":
                        tok = self._lock_token(call.func.value) \
                            if isinstance(call.func, ast.Attribute) else None
                        if tok:
                            self.fact.releases_final.append(tok)
            self.walk(s.finalbody, locks)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested_qual = f"{self.qual}.{s.name}"
            self.ex.out.localdefs.setdefault(self.fact.fid, {})[s.name] = \
                self.ex._fid(nested_qual)
            self.ex._function(s, qual=nested_qual, cls=self.cls,
                              env=dict(self.env))
        elif isinstance(s, ast.ClassDef):
            self.ex._class(s, prefix=self.qual, env=dict(self.env))
        elif isinstance(s, ast.Assign):
            self._expr(s.value, locks)
            for tgt in s.targets:
                self._write_target(tgt, locks, kind="assign")
            t = self.ex.resolve_type(s.value, self.env, self.cls)
            if t:
                for tgt in s.targets:
                    if isinstance(tgt, ast.Name):
                        self.env[tgt.id] = t
            # callable-attr binding: engine.slow_path_batch = fleet.meth
            if len(s.targets) == 1 and isinstance(s.targets[0],
                                                  ast.Attribute) \
                    and isinstance(s.value, ast.Attribute):
                tt = self.ex.resolve_type(s.targets[0].value, self.env,
                                          self.cls)
                vt = self.ex.resolve_type(s.value.value, self.env, self.cls)
                if tt and vt:
                    self.ex.out.bindings.append(
                        [tt, s.targets[0].attr, vt, s.value.attr])
        elif isinstance(s, ast.AugAssign):
            self._expr(s.value, locks)
            self._write_target(s.target, locks, kind="augassign")
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._expr(s.value, locks)
                self._write_target(s.target, locks, kind="assign")
        elif isinstance(s, ast.Expr):
            self._expr(s.value, locks)
        elif isinstance(s, ast.Return) and s.value is not None:
            self._expr(s.value, locks)
        elif isinstance(s, (ast.Raise,)):
            if s.exc is not None:
                self._expr(s.exc, locks)
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._expr(child, locks)
                elif isinstance(child, ast.stmt):
                    self._stmt(child, locks)

    # -- writes ----------------------------------------------------------

    def _self_attr_chain(self, expr) -> str | None:
        """First attribute off `self` in a chain: self.X.Y -> X."""
        chain = []
        cur = expr
        while isinstance(cur, (ast.Attribute, ast.Subscript)):
            if isinstance(cur, ast.Attribute):
                chain.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name) and cur.id == "self" and chain:
            return chain[-1]
        return None

    def _write_target(self, tgt, locks, kind: str) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._write_target(e, locks, kind)
            return
        if isinstance(tgt, ast.Subscript):
            attr = self._self_attr_chain(tgt.value)
            if attr is not None:
                self.fact.writes.append([attr, tgt.lineno, sorted(locks),
                                         "subscript"])
            self._expr(tgt.value, locks)
            return
        if isinstance(tgt, ast.Attribute):
            attr = self._self_attr_chain(tgt)
            if attr is not None and kind != "for":
                self.fact.writes.append([attr, tgt.lineno, sorted(locks),
                                         kind])
            self._expr(tgt.value, locks)

    # -- expressions: calls, blocking, reads -----------------------------

    def _expr(self, expr, locks, is_test: bool = False) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._call(node, locks)
            elif isinstance(node, ast.Attribute) and is_test:
                attr = self._self_attr_chain(node)
                if attr is not None and isinstance(node.ctx, ast.Load):
                    self.fact.test_reads.append([attr, node.lineno,
                                                 sorted(locks)])

    def _call(self, node: ast.Call, locks) -> None:
        name = _trailing(node.func)
        lk = sorted(locks)
        if name in BLOCKING_CALLS and not (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, (ast.Constant,
                                                 ast.JoinedStr))):
            # `"sep".join(...)` / b"".join(...) is string assembly, not
            # a thread join
            self.fact.blocking.append([name, node.lineno, lk])
        if name == "acquire" and isinstance(node.func, ast.Attribute):
            tok = self._lock_token(node.func.value)
            if tok:
                self.fact.acquires.append([tok, node.lineno])
        # spawn records -------------------------------------------------
        if name in ("Thread", "Process"):
            self._spawn(node, kind="thread" if name == "Thread"
                        else "process")
        if name == "add_source":
            self._scrape_source(node)
        if name == "subscribe":
            # callback registration: the delivery thread (not the
            # registering one) invokes the handed-over method — treat
            # `x.subscribe(self._on_change)` as an entry point in the
            # registering module's context
            for arg in node.args:
                if isinstance(arg, ast.Attribute) and \
                        isinstance(arg.value, ast.Name) and \
                        arg.value.id == "self":
                    self.ex.out.spawns.append(
                        {"kind": "callback", "line": node.lineno,
                         "qual": self.qual, "cls": self.cls,
                         "fid": self.fact.fid, "has_stop": True,
                         "target": {"k": "self", "m": arg.attr}})
        # mutating container method on a self attribute -----------------
        if name in MUTATING_METHODS and isinstance(node.func, ast.Attribute):
            attr = self._self_attr_chain(node.func.value)
            if attr is not None:
                self.fact.writes.append([attr, node.lineno, lk, "mutcall"])
        # the call edge itself ------------------------------------------
        desc = self._call_desc(node, name)
        if desc is not None:
            desc["locks"] = lk
            desc["line"] = node.lineno
            self.fact.calls.append(desc)

    def _call_desc(self, node: ast.Call, name: str) -> dict | None:
        f = node.func
        if isinstance(f, ast.Name):
            if name and name[:1].isupper():
                return {"k": "ctor", "n": name}
            return {"k": "name", "n": name} if name else None
        if isinstance(f, ast.Attribute):
            v = f.value
            if isinstance(v, ast.Name) and v.id == "self":
                return {"k": "self", "m": name}
            t = self.ex.resolve_type(v, self.env, self.cls)
            if t is not None:
                return {"k": "sym", "t": t, "m": name}
            if isinstance(v, ast.Name) and v.id in self.ex.out.imports:
                return {"k": "mod", "mod": self.ex.out.imports[v.id],
                        "m": name}
            return {"k": "meth", "m": name}
        return None

    # -- spawn/source records -------------------------------------------

    def _spawn(self, node: ast.Call, kind: str) -> None:
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        rec = {"kind": kind, "line": node.lineno, "qual": self.qual,
               "cls": self.cls, "target": None}
        if target is None:
            rec["target"] = {"k": "none"}
        elif isinstance(target, ast.Attribute):
            if _trailing(target) == "serve_forever":
                rec["target"] = {"k": "serve_forever"}
            elif isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                rec["target"] = {"k": "self", "m": target.attr}
            else:
                t = self.ex.resolve_type(target.value, self.env, self.cls)
                rec["target"] = ({"k": "sym", "t": t, "m": target.attr}
                                 if t else {"k": "unresolved",
                                            "repr": ast.dump(target)[:80]})
        elif isinstance(target, ast.Name):
            rec["target"] = {"k": "name", "n": target.id}
        else:
            rec["target"] = {"k": "unresolved",
                             "repr": ast.dump(target)[:80]}
        # stop-path evidence for BNG064: the enclosing class has a stop
        # method, or the enclosing function builds a cancel closure
        cf = self.ex.out.classes.get(self.cls) if self.cls else None
        rec["has_stop"] = bool(cf and cf.has_stop)
        rec["fid"] = self.fact.fid
        self.ex.out.spawns.append(rec)

    def _scrape_source(self, node: ast.Call) -> None:
        if not node.args:
            return
        arg = node.args[0]
        rec = {"kind": "source", "line": node.lineno, "qual": self.qual,
               "cls": self.cls, "fid": self.fact.fid}
        if isinstance(arg, ast.Lambda):
            # synthesize a function for the lambda body's calls
            lqual = f"{self.qual}.<scrape:{node.lineno}>"
            lfid = self.ex._fid(lqual)
            lfact = FnFact(fid=lfid, path=self.ex.sf.path, qual=lqual,
                           cls=self.cls, line=node.lineno)
            self.ex.out.functions[lfid] = lfact
            lw = _BodyWalker(self.ex, lfact, self.cls, dict(self.env),
                             lqual)
            lw._expr(arg.body, frozenset())
            rec["target"] = {"k": "fid", "fid": lfid}
        elif isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and arg.value.id == "self":
            rec["target"] = {"k": "self", "m": arg.attr}
        elif isinstance(arg, ast.Name):
            rec["target"] = {"k": "name", "n": arg.n
                             if hasattr(arg, "n") else arg.id}
        else:
            rec["target"] = {"k": "unresolved", "repr": ast.dump(arg)[:80]}
        self.ex.out.spawns.append(rec)


# ---------------------------------------------------------------------------
# extraction cache
# ---------------------------------------------------------------------------

def _extract_all(project: Project,
                 cache_path: Path | None) -> tuple[dict, bool]:
    """{path -> FileSummary} for the whole scan set, reusing the on-disk
    cache for files whose (mtime_ns, size) is unchanged. Returns
    (summaries, cache_hit_any)."""
    cache: dict = {}
    hit_any = False
    if cache_path is not None and cache_path.exists():
        try:
            raw = json.loads(cache_path.read_text(encoding="utf-8"))
            if raw.get("version") == FACTS_VERSION:
                cache = raw.get("files", {})
        except (OSError, ValueError):
            cache = {}
    summaries: dict[str, FileSummary] = {}
    # seed with the existing entries: a path-narrowed run must not
    # evict the rest of the repo's summaries (mtime keys already guard
    # staleness; entries for edited/deleted files refresh or go unused)
    out_cache: dict = dict(cache)
    missed = False
    for sf in project.files:
        try:
            st = sf.abspath.stat()
            key = [st.st_mtime_ns, st.st_size]
        except OSError:
            key = None
        ent = cache.get(sf.path)
        if key is not None and ent is not None and ent.get("key") == key:
            try:
                summaries[sf.path] = FileSummary.from_dict(ent["summary"])
                hit_any = True
                continue
            except (KeyError, TypeError):
                pass
        summary = _FileExtractor(sf).run()
        summaries[sf.path] = summary
        if key is not None:
            out_cache[sf.path] = {"key": key, "summary": summary.to_dict()}
            missed = True
    # a fully-warm run re-writes nothing: the multi-MB serialization is
    # the dominant warm-run cost (PERF_NOTES §11's flush spikes)
    if cache_path is not None and missed:
        try:
            cache_path.write_text(json.dumps(
                {"version": FACTS_VERSION, "files": out_cache}),
                encoding="utf-8")
        except OSError:
            pass
    return summaries, hit_any


# ---------------------------------------------------------------------------
# the model: entries, call graph, context + lock propagation
# ---------------------------------------------------------------------------

class ConcurrencyModel:
    """Resolved call graph + per-function context/lock classification."""

    def __init__(self):
        self.functions: dict[str, FnFact] = {}
        self.classes: dict[str, list] = {}        # name -> [ClassFact]
        self.entries: list[dict] = []             # {context, fid, via, line}
        self.unresolved: list[dict] = []          # spawn records w/o target
        self.contexts: dict[str, set] = {}        # fid -> context set
        self.held: dict[str, frozenset] = {}      # fid -> guaranteed locks
        self.edges: dict[str, list] = {}          # fid -> [(callee, locks)]
        self.spawns: list[dict] = []
        self.missing_facts: list[str] = []        # BNG990 details
        self.resolved_lines: dict[str, set] = {}  # fid -> call lines that
        self.cache_hit = False                    # resolved to a function

    # -- json ------------------------------------------------------------

    def contexts_report(self, prefixes=("bng_tpu/control/",
                                        "bng_tpu/runtime/")) -> dict:
        fns = {}
        for fid, ctxs in sorted(self.contexts.items()):
            if not ctxs:
                continue
            if prefixes and not fid.startswith(prefixes):
                continue
            fns[fid] = {"contexts": sorted(ctxs),
                        "locks_held": sorted(self.held.get(fid) or ())}
        return {
            "entries": sorted(
                ({"context": e["context"], "function": e["fid"],
                  "via": e["via"]} for e in self.entries),
                key=lambda e: (e["context"], e["function"])),
            "unresolved_entry_points": [
                {"path": u.get("path", ""), "line": u.get("line", 0),
                 "scope": u.get("qual", "")} for u in self.unresolved],
            "functions": fns,
        }


def _resolve_symbolic(model: ConcurrencyModel, t,
                      near_path: str | None = None) -> list[ClassFact]:
    """Resolve a symbolic type descriptor to candidate ClassFacts."""
    if t is None:
        return []
    kind = t[0]
    if kind == "cls":
        cands = model.classes.get(t[1], [])
        if near_path is not None:
            same = [c for c in cands if c.path == near_path]
            if same:
                return same
        return cands if len(cands) == 1 else []
    if kind == "attrof":
        bases = ([b for b in _resolve_symbolic(model, t[1], near_path)]
                 if isinstance(t[1], list)
                 else _resolve_symbolic(model, ["cls", t[1]], near_path))
        out: list[ClassFact] = []
        for cf in bases:
            for name in cf.attr_types.get(t[2], ()):
                out.extend(_resolve_symbolic(model, ["cls", name],
                                             cf.path))
        return out
    if kind == "keyof":
        bases = _resolve_symbolic(model, ["cls", t[1]], near_path)
        out = []
        for cf in bases:
            for name in cf.subscript_types.get(t[2], ()):
                out.extend(_resolve_symbolic(model, ["cls", name],
                                             cf.path))
        return out
    return []


def build_concurrency_model(project: Project,
                            cache_path: Path | str | None = "auto",
                            ) -> ConcurrencyModel:
    """Assemble the model. Memoized per Project instance (the pass and
    the CLI `--json contexts` dump share one build)."""
    memo = getattr(project, "_bng_concurrency_model", None)
    if memo is not None:
        return memo
    if cache_path == "auto":
        cache_path = project.root / CACHE_NAME
    cache_path = Path(cache_path) if cache_path is not None else None

    model = ConcurrencyModel()
    summaries, model.cache_hit = _extract_all(project, cache_path)

    # global indexes ------------------------------------------------------
    method_index: dict[str, list] = {}   # method name -> [fid]
    for summ in summaries.values():
        for cname, cf in summ.classes.items():
            model.classes.setdefault(cname, []).append(cf)
            for mname, fid in cf.methods.items():
                method_index.setdefault(mname, []).append(fid)
        model.functions.update(summ.functions)

    def _method_of(cf: ClassFact, m: str) -> str | None:
        """Method lookup including single-inheritance base walk."""
        seen = set()
        while cf is not None and id(cf) not in seen:
            seen.add(id(cf))
            if m in cf.methods:
                return cf.methods[m]
            nxt = None
            for bn in cf.bases:
                got = _resolve_symbolic(model, ["cls", bn], cf.path)
                if got:
                    nxt = got[0]
                    break
            cf = nxt
        return None

    bindings: dict[tuple, str] = {}      # (ClsName, attr) -> bound fid
    for summ in summaries.values():
        for tt, attr, vt, meth in summ.bindings:
            tcands = _resolve_symbolic(model, tt, summ.path)
            vcands = _resolve_symbolic(model, vt, summ.path)
            for tcf in tcands:
                for vcf in vcands:
                    got = _method_of(vcf, meth)
                    if got:
                        bindings[(tcf.name, attr)] = got

    def resolve_call(summ: FileSummary, fact: FnFact, desc) -> list[str]:
        k = desc["k"]
        if k == "self" or (k == "sym" and desc.get("t")):
            if k == "self":
                cands = _resolve_symbolic(model, ["cls", fact.cls],
                                          fact.path)
            else:
                cands = _resolve_symbolic(model, desc["t"], fact.path)
            m = desc["m"]
            out: list[str] = []
            for cf in cands:
                b = bindings.get((cf.name, m))
                if b:  # bound-callable attr (engine.slow_path_batch = ..)
                    out.append(b)
                    continue
                got = _method_of(cf, m)
                if got:
                    out.append(got)
            if out:
                return out
            if k == "self":
                return []
            k, desc = "meth", {"m": m}  # fall through to unique-name
        if k == "name":
            n = desc["n"]
            local = summ.localdefs.get(fact.fid, {})
            if n in local:
                return [local[n]]
            # nested def of an enclosing function (one level is enough)
            for parent, defs in summ.localdefs.items():
                if fact.fid.startswith(parent) and n in defs:
                    return [defs[n]]
            if n in summ.moddefs:
                return [summ.moddefs[n]]
            mod = summ.from_imports.get(n)
            if mod and mod.startswith("bng_tpu"):
                target = project.find_file(mod.replace(".", "/") + ".py")
                if target and target.path in summaries:
                    td = summaries[target.path].moddefs
                    if n in td:
                        return [td[n]]
            return []
        if k == "ctor":
            cands = _resolve_symbolic(model, ["cls", desc["n"]], fact.path)
            return [cf.methods["__init__"] for cf in cands
                    if "__init__" in cf.methods]
        if k == "mod":
            mod = desc["mod"]
            if mod.startswith("bng_tpu"):
                target = project.find_file(mod.replace(".", "/") + ".py")
                if target and target.path in summaries:
                    td = summaries[target.path].moddefs
                    if desc["m"] in td:
                        return [td[desc["m"]]]
            return []
        if k == "meth":
            cands = method_index.get(desc["m"], ())
            if len(cands) == 1 and not desc["m"].startswith("__"):
                return list(cands)
            return []
        return []

    # edges ---------------------------------------------------------------
    for summ in summaries.values():
        for fid, fact in summ.functions.items():
            outs = model.edges.setdefault(fid, [])
            for desc in fact.calls:
                resolved = resolve_call(summ, fact, desc)
                for callee in resolved:
                    outs.append((callee, frozenset(desc["locks"])))
                if resolved:
                    # a mutating-method call that resolved INTO a
                    # project function is analyzed there (with the
                    # callee's own locks) — remember the line so the
                    # pass doesn't double-count it as a raw container
                    # mutation of the receiver attribute
                    model.resolved_lines.setdefault(fid, set()).add(
                        desc["line"])
        model.spawns.extend(
            dict(s, path=summ.path) for s in summ.spawns)

    # entry points --------------------------------------------------------
    def add_entry(context: str, fid: str, via: str, line: int = 0):
        model.entries.append({"context": context, "fid": fid, "via": via,
                              "line": line})

    # 1. the run loop roots (the dataplane's own context)
    cli_sf = project.find_file(CLI_FILE)
    loop_found = False
    if cli_sf is not None and cli_sf.path in summaries:
        app = summaries[cli_sf.path].classes.get("BNGApp")
        if app is not None:
            for root in ("drive_once", "tick"):
                if root in app.methods:
                    add_entry(CONTEXT_LOOP, app.methods[root], "run-loop")
                    loop_found = True
    if not loop_found:
        model.missing_facts.append("loop-roots")

    # 2. the OpsController queue drain: run_pending executes the OPS
    # verbs on the loop thread (the getattr dispatch resolved from the
    # OPS dict literal, the queue-drain fact the pass depends on)
    ops_sf = project.find_file(OPSCTL_FILE)
    if ops_sf is not None and ops_sf.path in summaries:
        summ = summaries[ops_sf.path]
        ctl = summ.classes.get("OpsController")
        verbs: list[str] = []
        for node in ast.walk(ops_sf.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "OPS" \
                    and isinstance(node.value, ast.Dict):
                verbs = [str_const(v) for v in node.value.values
                         if str_const(v)]
        if ctl is not None and "run_pending" in ctl.methods:
            rp = ctl.methods["run_pending"]
            add_entry(CONTEXT_LOOP, rp, "ops-queue-drain")
            for verb in verbs:
                cands = method_index.get(verb, ())
                if len(cands) == 1:
                    model.edges.setdefault(rp, []).append(
                        (cands[0], frozenset()))
        elif ctl is not None:
            model.missing_facts.append("ops-queue-drain")

    # 3. spawn records: threads, processes, handlers, scrape sources
    for rec in model.spawns:
        path = rec["path"]
        summ = summaries[path]
        stem = Path(path).stem
        if rec["kind"] == "process":
            context = CONTEXT_WORKER
        elif rec["kind"] == "source":
            context = CONTEXT_SCRAPE
        else:
            context = CONTEXT_MODULE_MAP.get(path, f"thread:{stem}")
        tgt = rec["target"]
        fids: list[str] = []
        if tgt["k"] == "fid":
            fids = [tgt["fid"]]
        elif tgt["k"] == "self" and rec["cls"]:
            cf = summ.classes.get(rec["cls"])
            if cf and tgt["m"] in cf.methods:
                fids = [cf.methods[tgt["m"]]]
        elif tgt["k"] == "sym":
            for cf in _resolve_symbolic(model, tgt.get("t"), path):
                got = cf.methods.get(tgt["m"])
                if got:
                    fids.append(got)
        elif tgt["k"] == "name":
            local = summ.localdefs.get(rec["fid"], {})
            n = tgt["n"]
            if n in local:
                fids = [local[n]]
            elif n in summ.moddefs:
                fids = [summ.moddefs[n]]
        elif tgt["k"] == "serve_forever":
            # the server's worker threads run the module's handler
            # classes: every do_* method is an entry
            for cf in summ.classes.values():
                if any("BaseHTTPRequestHandler" in b for b in cf.bases):
                    fids.extend(fid for mname, fid in cf.methods.items()
                                if mname.startswith("do_"))
        if fids:
            for fid in fids:
                add_entry(context, fid, f"{rec['kind']}:{rec['qual']}",
                          rec["line"])
        elif rec["kind"] in ("thread", "process"):
            model.unresolved.append(rec)

    # HTTP handler classes whose server is started elsewhere (the
    # handler class IS the entry even if serve_forever is indirect)
    claimed = {e["fid"] for e in model.entries}
    for path, summ in summaries.items():
        context = CONTEXT_MODULE_MAP.get(path,
                                         f"thread:{Path(path).stem}")
        for cf in summ.classes.values():
            if any("BaseHTTPRequestHandler" in b for b in cf.bases):
                for mname, fid in cf.methods.items():
                    if mname.startswith("do_") and fid not in claimed:
                        add_entry(context, fid, "http-handler", cf.line)

    # propagation ---------------------------------------------------------
    contexts: dict[str, set] = {f: set() for f in model.functions}
    held: dict[str, frozenset | None] = {f: None for f in model.functions}
    work: list[str] = []
    for e in model.entries:
        fid = e["fid"]
        if fid not in contexts:
            continue
        contexts[fid].add(e["context"])
        held[fid] = frozenset() if held[fid] is None else held[fid]
        work.append(fid)
    seen_rounds = 0
    while work and seen_rounds < 200_000:
        seen_rounds += 1
        fid = work.pop()
        ctx = contexts[fid]
        h = held[fid] if held[fid] is not None else frozenset()
        for callee, locks in model.edges.get(fid, ()):
            if callee not in contexts:
                continue
            changed = False
            if not ctx <= contexts[callee]:
                contexts[callee] |= ctx
                changed = True
            cand = h | locks
            if held[callee] is None:
                held[callee] = cand
                changed = True
            elif not held[callee] <= cand:
                held[callee] = held[callee] & cand
                changed = True
            if changed:
                work.append(callee)
    if work:
        # the round cap is a runaway backstop far above any real graph;
        # hitting it means the classification is INCOMPLETE — say so
        # loudly (BNG990 via missing_facts), never under-report quietly
        model.missing_facts.append("propagation-truncated")
    model.contexts = contexts
    model.held = {f: (h if h is not None else frozenset())
                  for f, h in held.items()}
    project._bng_concurrency_model = model  # type: ignore[attr-defined]
    return model
