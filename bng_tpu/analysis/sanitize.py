"""Runtime sanitizer mode (BNG_SANITIZE=1): the dynamic cross-check of
the static transfer lint.

`sanitized()` arms, for the enclosed block:

* ``jax.transfer_guard_device_to_host("disallow")`` — an implicit
  device->host transfer (the BNG001 class: lazily consuming a device
  value where host code expected numpy) raises instead of silently
  blocking. Explicit forces (`jax.device_get`, `np.asarray`,
  `block_until_ready` — the blessed retire-path idioms) stay legal.
* ``jax.transfer_guard_host_to_device(h2d)`` — default "allow"; the
  planted-violation test passes "disallow" to prove the guard has
  teeth: feeding a raw numpy array (or a bare Python/np scalar) to a
  jitted step is an *implicit* h2d transfer and trips, while the
  engine's explicit `jnp.asarray`/`device_put` staging would not.
* ``jax.debug_nans`` — jitted programs re-checked for NaN production
  (forces outputs per call: correct, slow, opt-in).

**XLA:CPU caveat (measured on jaxlib 0.4.37, see tests):** the
device-to-host guard never fires on the CPU backend — `__array__`,
`.item()` and `float()` on a CPU jax array are serviced without a
guarded transfer. Host-to-device guards DO fire on CPU (scalar and
ndarray args to jitted calls trip "disallow"). So under
`BNG_SANITIZE=1` on the tier-1 CPU suite the effective checks are
debug_nans + h2d hygiene of the planted tests; on a real TPU the d2h
guard gains teeth with no change here. That asymmetry is why the
sanitizer is the *cross-check* and the static lint is the gate.

Wiring: tests/conftest.py applies `sanitized()` around every test
marked ``hotpath`` when BNG_SANITIZE=1 (`make verify-sanitize`);
anything may also use it directly as a context manager.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

SANITIZE_ENV = "BNG_SANITIZE"


def enabled() -> bool:
    """Is sanitizer mode requested? ("1", "true", "strict" accept)."""
    return os.environ.get(SANITIZE_ENV, "").lower() in ("1", "true",
                                                        "strict")


def strict() -> bool:
    """BNG_SANITIZE=strict also disallows implicit host->device
    transfers — only viable for code whose inputs are staged with
    explicit jnp.asarray/device_put end to end."""
    return os.environ.get(SANITIZE_ENV, "").lower() == "strict"


@contextmanager
def sanitized(h2d: str = "allow", d2h: str = "disallow",
              nans: bool = True):
    """Arm the transfer guards + debug_nans for the block.

    Imports jax lazily so `bng check` (static half) never pays for it.
    """
    import jax

    ctxs = [jax.transfer_guard_device_to_host(d2h),
            jax.transfer_guard_host_to_device(h2d)]
    if nans:
        ctxs.append(jax.debug_nans(True))
    # contextlib.ExitStack without the import ceremony
    entered = []
    try:
        for c in ctxs:
            c.__enter__()
            entered.append(c)
        yield
    finally:
        for c in reversed(entered):
            c.__exit__(None, None, None)
