"""Runtime sanitizer mode (BNG_SANITIZE=1): the dynamic cross-check of
the static transfer lint.

`sanitized()` arms, for the enclosed block:

* ``jax.transfer_guard_device_to_host("disallow")`` — an implicit
  device->host transfer (the BNG001 class: lazily consuming a device
  value where host code expected numpy) raises instead of silently
  blocking. Explicit forces (`jax.device_get`, `np.asarray`,
  `block_until_ready` — the blessed retire-path idioms) stay legal.
* ``jax.transfer_guard_host_to_device(h2d)`` — default "allow"; the
  planted-violation test passes "disallow" to prove the guard has
  teeth: feeding a raw numpy array (or a bare Python/np scalar) to a
  jitted step is an *implicit* h2d transfer and trips, while the
  engine's explicit `jnp.asarray`/`device_put` staging would not.
* ``jax.debug_nans`` — jitted programs re-checked for NaN production
  (forces outputs per call: correct, slow, opt-in).

**XLA:CPU caveat (measured on jaxlib 0.4.37, see tests):** the
device-to-host guard never fires on the CPU backend — `__array__`,
`.item()` and `float()` on a CPU jax array are serviced without a
guarded transfer. Host-to-device guards DO fire on CPU (scalar and
ndarray args to jitted calls trip "disallow"). So under
`BNG_SANITIZE=1` on the tier-1 CPU suite the effective checks are
debug_nans + h2d hygiene of the planted tests; on a real TPU the d2h
guard gains teeth with no change here. That asymmetry is why the
sanitizer is the *cross-check* and the static lint is the gate.

Wiring: tests/conftest.py applies `sanitized()` around every test
marked ``hotpath`` when BNG_SANITIZE=1 (`make verify-sanitize`);
anything may also use it directly as a context manager.

**Ownership assertions (ISSUE 9)** — the dynamic cross-check of the
static concurrency pass (BNG060-BNG062). `@owned_by("loop",
guard="_ctl")` stamps a class whose mutable state belongs to one
execution context. Disarmed (BNG_SANITIZE unset) the decorator returns
the class untouched — zero overhead, zero behavior change. Armed:

* threads announce their context with `ctx_enter("ctl")` /
  `with context("scrape"):` (the run loop, OpsServer handlers, the
  metrics collector, fleet worker mains and the HA SSE reader are
  pre-wired, each behind the same is-armed check);
* every attribute write on a stamped object from a *named* context
  other than the owner raises OwnershipViolation — unless the thread
  holds the object's guard lock (the instance's `guard` attribute is
  transparently wrapped in a hold-tracking proxy at construction);
* writes from unnamed threads (construction, unit tests that don't
  set a context) stay free, so arming the sanitizer never breaks
  single-threaded tests;
* with `owner=None` the first named-context writer stamps the owner
  per attribute — "records the owning context at first write".

This is how the barrier-forced interleaving tests prove the PR-7 race
fixes are real: the forced schedule that used to lose an update now
either takes `_ctl` (passes) or raises OwnershipViolation (the
reverted-fix run fails loudly instead of silently corrupting).
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager

SANITIZE_ENV = "BNG_SANITIZE"


def enabled() -> bool:
    """Is sanitizer mode requested? ("1", "true", "strict" accept)."""
    return os.environ.get(SANITIZE_ENV, "").lower() in ("1", "true",
                                                        "strict")


def strict() -> bool:
    """BNG_SANITIZE=strict also disallows implicit host->device
    transfers — only viable for code whose inputs are staged with
    explicit jnp.asarray/device_put end to end."""
    return os.environ.get(SANITIZE_ENV, "").lower() == "strict"


@contextmanager
def sanitized(h2d: str = "allow", d2h: str = "disallow",
              nans: bool = True):
    """Arm the transfer guards + debug_nans for the block.

    Imports jax lazily so `bng check` (static half) never pays for it.
    """
    import jax

    ctxs = [jax.transfer_guard_device_to_host(d2h),
            jax.transfer_guard_host_to_device(h2d)]
    if nans:
        ctxs.append(jax.debug_nans(True))
    # contextlib.ExitStack without the import ceremony
    entered = []
    try:
        for c in ctxs:
            c.__enter__()
            entered.append(c)
        yield
    finally:
        for c in reversed(entered):
            c.__exit__(None, None, None)


# ===========================================================================
# ownership assertions (ISSUE 9): @owned_by + context stamps
# ===========================================================================

class OwnershipViolation(AssertionError):
    """An unlocked cross-context mutation of owned state (the BNG060
    bug class, caught at runtime)."""


_TLS = threading.local()


def current_context() -> str | None:
    return getattr(_TLS, "ctx", None)


def ctx_enter(name: str) -> None:
    """Stamp the calling thread's execution context (sticky). No-op
    when the sanitizer is disarmed — callers may invoke unconditionally
    from thread mains; the armed check is one env-cached bool."""
    if _ARMED:
        _TLS.ctx = name


@contextmanager
def context(name: str):
    """Scoped context stamp (tests; request-scoped handler threads)."""
    if not _ARMED:
        yield
        return
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = name
    try:
        yield
    finally:
        _TLS.ctx = prev


class GuardedLock:
    """Lock proxy that knows whether the *current thread* holds it —
    what `@owned_by` needs to distinguish a locked cross-context write
    (legal) from an unlocked one (violation). Wraps the instance's
    guard attribute at construction time when armed."""

    def __init__(self, inner):
        self._inner = inner
        self._holds: dict[int, int] = {}  # thread ident -> depth

    def acquire(self, *a, **k) -> bool:
        got = self._inner.acquire(*a, **k)
        if got:
            me = threading.get_ident()
            self._holds[me] = self._holds.get(me, 0) + 1
        return got

    def release(self) -> None:
        me = threading.get_ident()
        depth = self._holds.get(me, 0) - 1
        if depth <= 0:
            self._holds.pop(me, None)
        else:
            self._holds[me] = depth
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def held_by_me(self) -> bool:
        return self._holds.get(threading.get_ident(), 0) > 0

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def owned_by(owner: str | None, guard: str | None = None,
             attrs: tuple[str, ...] | None = None):
    """Class decorator: assert the context-ownership discipline on
    every attribute write (armed only; disarmed returns cls as-is).

    owner  — the context that may mutate freely ("loop"); None infers
             it from the first named-context write, per attribute.
    guard  — name of the instance's lock attribute; a thread HOLDING
             that lock may mutate from any context (that is the whole
             point of the `_ctl` discipline).
    attrs  — restrict checking to these attributes (None = all).
    """

    def deco(cls):
        if not _ARMED:
            return cls

        orig_setattr = cls.__setattr__
        orig_init = cls.__init__

        def __init__(self, *a, **k):
            orig_init(self, *a, **k)
            if guard is not None:
                g = self.__dict__.get(guard)
                if g is not None and not isinstance(g, GuardedLock):
                    self.__dict__[guard] = GuardedLock(g)

        def __setattr__(self, name, value):
            ctx = getattr(_TLS, "ctx", None)
            if ctx is None or (attrs is not None and name not in attrs):
                return orig_setattr(self, name, value)
            owners = self.__dict__.setdefault("__bng_owners__", {})
            own = owners.setdefault(name, owner if owner is not None
                                    else ctx)
            if ctx != own:
                g = self.__dict__.get(guard) if guard is not None else None
                if not (isinstance(g, GuardedLock) and g.held_by_me()):
                    raise OwnershipViolation(
                        f"{type(self).__name__}.{name} is owned by "
                        f"{own!r} but mutated from context {ctx!r} "
                        f"without holding "
                        f"{guard if guard else '<no guard declared>'} — "
                        f"the BNG060 race class, live")
            return orig_setattr(self, name, value)

        cls.__init__ = __init__
        cls.__setattr__ = __setattr__
        cls.__bng_owned__ = (owner, guard, attrs)
        return cls

    return deco


# computed once at import: the decorator and the ctx stamps read it on
# hot paths (thread mains, per-request handlers) — one global load
_ARMED = enabled()
