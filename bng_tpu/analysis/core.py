"""bngcheck core: findings, the scanned project, and the pass driver.

The analyzer enforces the disciplines this codebase encodes only as
convention (ISSUE 6): fenced device time, the fixed span vocabulary,
registered fault points, single-writer device-mirror updates, donation
of the jitted step's table buffers, and Yuan-style error-handler
hygiene (OSDI'14: 92% of catastrophic failures hide in
already-signaled-but-mishandled errors — a statically checkable class).

Design constraints, in order:

1. **stdlib only.** `ast` + `json` + `pathlib`; importing the analyzer
   never imports jax (so `bng check` runs in milliseconds anywhere,
   including CI boxes with no accelerator stack).
2. **Stable, baselinable findings.** A Finding's identity is
   (code, path, scope, detail) — deliberately NOT the line number, so
   an unrelated edit above an accepted finding doesn't churn the
   baseline. file:line still rides along for humans.
3. **Passes are data + a visitor.** Each pass declares the codes it can
   emit; the driver owns discovery, fact extraction and baseline
   matching. A pass that cannot find its fact source (e.g. the span
   vocabulary moved) emits BNG990 instead of silently passing — the
   analyzer must fail loud when the repo drifts out from under it.
"""

from __future__ import annotations

import ast
import time
from dataclasses import dataclass, field
from pathlib import Path

ANALYZER_VERSION = 1

# self-check codes (any pass may emit these)
CODE_CONFIG = "BNG990"  # a pass's fact source is missing/unparseable


@dataclass(frozen=True)
class Finding:
    """One rule violation at a location.

    `scope` is the enclosing def/class qualname ("Engine._dispatch_step")
    and `detail` a short stable discriminator (the offending symbol) —
    together with code+path they form the baseline identity."""

    code: str
    path: str  # repo-relative posix path
    line: int
    message: str
    scope: str = ""
    detail: str = ""

    def key(self) -> tuple:
        return (self.code, self.path, self.scope, self.detail)

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "scope": self.scope, "detail": self.detail,
                "message": self.message}


@dataclass
class SourceFile:
    path: str  # repo-relative posix
    abspath: Path
    text: str
    tree: ast.Module

    @staticmethod
    def load(root: Path, abspath: Path) -> "SourceFile | None":
        try:
            text = abspath.read_text(encoding="utf-8")
            tree = ast.parse(text, filename=str(abspath))
        except (OSError, SyntaxError, ValueError):
            return None
        rel = abspath.relative_to(root).as_posix()
        return SourceFile(path=rel, abspath=abspath, text=text, tree=tree)


# default scan set: the package + the bench driver. tests/ is excluded —
# it plants violations deliberately (this file's own test fixtures) and
# exercises private surfaces the production rules don't govern.
SCAN_GLOBS = ("bng_tpu/**/*.py", "bench.py")


class Project:
    """Parsed view of the scan set + parent links for scope resolution."""

    def __init__(self, root: Path, files: list[SourceFile]):
        self.root = root
        self.files = files
        self._by_path = {f.path: f for f in files}
        for f in files:
            _link_parents(f.tree)

    @staticmethod
    def load(root: Path, paths: list[Path] | None = None) -> "Project":
        root = Path(root).resolve()
        if paths:
            abspaths: list[Path] = []
            for p in paths:
                p = Path(p)
                p = p if p.is_absolute() else root / p
                abspaths.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
        else:
            abspaths = []
            for g in SCAN_GLOBS:
                abspaths.extend(sorted(root.glob(g)))
        files = []
        seen = set()
        for ap in abspaths:
            ap = ap.resolve()
            if ap in seen or "__pycache__" in ap.parts:
                continue
            seen.add(ap)
            sf = SourceFile.load(root, ap)
            if sf is not None:
                files.append(sf)
        return Project(root, files)

    def file(self, rel_path: str) -> SourceFile | None:
        return self._by_path.get(rel_path)

    def find_file(self, suffix: str) -> SourceFile | None:
        """Locate a fact source by path suffix (survives fixture trees
        that mirror only the tail of the real layout)."""
        sf = self._by_path.get(suffix)
        if sf is not None:
            return sf
        for f in self.files:
            if f.path.endswith(suffix):
                return f
        return None


def _link_parents(tree: ast.Module) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._bng_parent = node  # type: ignore[attr-defined]


def scope_of(node: ast.AST) -> str:
    """Qualname of the enclosing def/class chain ("Engine.process")."""
    parts: list[str] = []
    cur = getattr(node, "_bng_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "_bng_parent", None)
    return ".".join(reversed(parts))


def enclosing_function(node: ast.AST):
    cur = getattr(node, "_bng_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "_bng_parent", None)
    return None


def call_name(node: ast.Call) -> str:
    """Trailing name of the called thing: f() -> "f", a.b.c() -> "c"."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def dotted(node: ast.AST) -> str:
    """Best-effort dotted form of a Name/Attribute chain ("jax.jit")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class Pass:
    """Base pass: subclasses set name/description/codes and implement
    run(project) -> list[Finding]."""

    name = "base"
    description = ""
    codes: dict[str, str] = {}

    def run(self, project: Project) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError

    def config_finding(self, detail: str, message: str) -> Finding:
        return Finding(code=CODE_CONFIG, path="<analyzer>", line=0,
                       scope=self.name, detail=detail, message=message)


@dataclass
class Report:
    """One analyzer run: everything the CLI and the tests consume."""

    findings: list[Finding]
    files_scanned: int
    passes_run: list[str]
    elapsed_s: float
    baselined: list[Finding] = field(default_factory=list)

    @property
    def new_findings(self) -> list[Finding]:
        return self.findings

    def to_dict(self) -> dict:
        return {
            "analyzer_version": ANALYZER_VERSION,
            "files_scanned": self.files_scanned,
            "passes": self.passes_run,
            "elapsed_s": round(self.elapsed_s, 3),
            "findings": [f.to_dict() for f in self.findings],
            "baselined": len(self.baselined),
        }


def run_passes(project: Project, passes: list[Pass]) -> Report:
    t0 = time.perf_counter()
    findings: list[Finding] = []
    for p in passes:
        findings.extend(p.run(project))
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.detail))
    return Report(findings=findings, files_scanned=len(project.files),
                  passes_run=[p.name for p in passes],
                  elapsed_s=time.perf_counter() - t0)
