"""`bng check` / `python -m bng_tpu.analysis` — the analyzer driver.

Exit codes:
    0  clean (every finding baselined WITH a justification, or none)
    1  at least one non-baselined finding, or a baseline entry still
       tagged "TODO: justify" (the justification is the review
       artifact — an unjustified acceptance is not an acceptance)
    2  analyzer-internal error (unreadable baseline, bad arguments)

Importing this module never imports jax — the analyzer is pure stdlib
`ast`, so `bng check` runs in milliseconds on any box (the <30s
acceptance bound is dominated by Python startup, not the scan).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from bng_tpu.analysis import baseline as baseline_mod
from bng_tpu.analysis.core import CODE_CONFIG, Project, run_passes
from bng_tpu.analysis.passes import all_codes, build


def default_root() -> Path:
    """The repo root: the directory holding the bng_tpu package."""
    return Path(__file__).resolve().parents[2]


def add_check_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: the repo scan set)")
    p.add_argument("--root", default=None,
                   help="repo root (default: the bng_tpu install root)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: "
                        "bng_tpu/analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report every finding)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from this run (new entries "
                        "tagged 'TODO: justify')")
    p.add_argument("--select", default=None,
                   help="comma-separated pass names or finding codes "
                        "(e.g. hotpath,BNG020)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--codes", action="store_true",
                   help="print the finding-code catalog and exit")


def run_check(args: argparse.Namespace) -> int:
    if args.codes:
        for code, desc in all_codes().items():
            print(f"{code}  {desc}")
        return 0

    if args.no_baseline and args.update_baseline:
        # --no-baseline discards the justifications --update-baseline
        # must carry over; combining them would rewrite the file with
        # every entry reset to the TODO tag.
        print("bng check: --no-baseline and --update-baseline are "
              "mutually exclusive", file=sys.stderr)
        return 2

    root = Path(args.root) if args.root else default_root()
    select = (set(s.strip() for s in args.select.split(","))
              if args.select else None)
    passes = build(select)
    if not passes:
        print(f"bng check: no pass matches --select {args.select}",
              file=sys.stderr)
        return 2

    project = Project.load(root, [Path(p) for p in args.paths] or None)
    report = run_passes(project, passes)

    if args.no_baseline:
        bl: dict = {}
        bl_path = None
    else:
        bl_path = Path(args.baseline) if args.baseline else (
            baseline_mod.DEFAULT_BASELINE)
        try:
            bl = baseline_mod.load(bl_path)
        except (json.JSONDecodeError, KeyError, OSError) as e:
            print(f"bng check: unreadable baseline {bl_path}: {e}",
                  file=sys.stderr)
            return 2
    new, accepted, stale = baseline_mod.split(report.findings, bl)
    report.findings, report.baselined = new, accepted
    # A selective run (--select, or explicit paths narrowing the scan)
    # can only vouch for the codes its passes emit against the files it
    # scanned — both the TODO rejection and the baseline rewrite below
    # must stay inside that scope.
    emittable = {c for p in passes for c in p.codes} | {CODE_CONFIG}
    scanned = {f.path for f in project.files} | {"<analyzer>"}
    # baseline.py's contract: entries stamped "TODO: justify" by
    # --update-baseline are review debt, and CI must reject them — an
    # entry nobody wrote a reason for is a silenced finding, not an
    # accepted one. (--update-baseline itself is exempt below: it is the
    # verb that CREATES the tag for the reviewer to replace.) Scoped:
    # an out-of-scope TODO entry is one this invocation can neither
    # re-verify nor re-stamp, so failing on it would leave a narrow
    # `--select`/path run permanently red.
    todo = sorted(k for k, just in bl.items()
                  if just.strip() == baseline_mod.TODO_TAG
                  and k[0] in emittable and k[1] in scanned)

    if args.update_baseline:
        # Baseline entries outside the run's scope must survive the
        # rewrite, or `--select hotpath --update-baseline` silently
        # wipes every other pass's justified entries.
        keep = {k: v for k, v in bl.items()
                if k[0] not in emittable or k[1] not in scanned}
        stale = [k for k in stale if k not in keep]
        out = baseline_mod.write(new + accepted, bl_path, old=bl,
                                 keep=keep)
        print(f"bng check: baseline rewritten: {out} "
              f"({len(new)} new, {len(accepted)} kept, "
              f"{len(keep)} out-of-scope preserved, "
              f"{len(stale)} stale dropped)")
        return 0

    if args.as_json:
        doc = report.to_dict()
        doc["stale_baseline_entries"] = [list(k) for k in stale]
        doc["todo_baseline_entries"] = [list(k) for k in todo]
        if "concurrency" in report.passes_run:
            # the per-function context classification, so reviewers can
            # audit the call-graph facts behind BNG06x findings (the
            # model is memoized on the Project — no second build)
            from bng_tpu.analysis import facts
            doc["contexts"] = facts.build_concurrency_model(
                project).contexts_report()
        print(json.dumps(doc, indent=2))
    else:
        for f in new:
            print(f"{f.location()}: {f.code} [{f.scope or '<module>'}] "
                  f"{f.message}")
        for k in todo:
            print(f"{k[1]}: {k[0]} [{k[2] or '<module>'}] baseline entry "
                  f"still tagged {baseline_mod.TODO_TAG!r} — write the "
                  f"justification in {bl_path}")
        if stale:
            print(f"bng check: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (code no longer "
                  f"produces them) — run --update-baseline",
                  file=sys.stderr)
        print(f"bng check: {len(new)} finding(s), {len(accepted)} "
              f"baselined ({len(todo)} unjustified), "
              f"{report.files_scanned} files, "
              f"{report.elapsed_s:.2f}s "
              f"[{', '.join(report.passes_run)}]",
              file=sys.stderr)
    return 1 if new or todo else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bng check",
        description="bngcheck: dataplane-invariant static analyzer")
    add_check_args(parser)
    return run_check(parser.parse_args(argv))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
