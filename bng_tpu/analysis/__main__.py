"""`python -m bng_tpu.analysis` — alias for `bng check`."""

import sys

from bng_tpu.analysis.cli import main

sys.exit(main())
