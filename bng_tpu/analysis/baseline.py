"""Baseline handling: accepted findings that don't block CI.

A baseline entry is a Finding identity (code, path, scope, detail —
line numbers deliberately excluded so unrelated edits don't churn it)
plus a mandatory one-line justification. `bng check` exits 1 on any
finding NOT in the baseline; `--update-baseline` rewrites the file from
the current run, preserving justifications of entries that survive and
stamping new ones with "TODO: justify" (CI should reject a TODO tag —
the justification is the review artifact).

Stale entries (baselined findings the code no longer produces) are
reported and dropped on update: a baseline that only grows becomes a
dead letter.
"""

from __future__ import annotations

import json
from pathlib import Path

from bng_tpu.analysis.core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"
TODO_TAG = "TODO: justify"


def load(path: Path | str | None = None) -> dict[tuple, str]:
    """{finding key -> justification}; empty when the file is absent."""
    path = Path(path) if path is not None else DEFAULT_BASELINE
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    out: dict[tuple, str] = {}
    for e in data.get("findings", ()):
        key = (e["code"], e["path"], e.get("scope", ""),
               e.get("detail", ""))
        out[key] = e.get("justification", TODO_TAG)
    return out


def split(findings: list[Finding],
          baseline: dict[tuple, str]) -> tuple[list[Finding],
                                               list[Finding], list[tuple]]:
    """(new, accepted, stale_keys): findings not in the baseline, the
    baselined ones, and baseline entries nothing matched."""
    new: list[Finding] = []
    accepted: list[Finding] = []
    seen: set[tuple] = set()
    for f in findings:
        k = f.key()
        if k in baseline:
            accepted.append(f)
            seen.add(k)
        else:
            new.append(f)
    stale = [k for k in baseline if k not in seen]
    return new, accepted, stale


def write(findings: list[Finding], path: Path | str | None = None,
          old: dict[tuple, str] | None = None,
          keep: dict[tuple, str] | None = None) -> Path:
    """Rewrite the baseline from `findings`, carrying over existing
    justifications; new entries get the TODO tag for review. `keep`
    entries (key -> justification) are preserved verbatim — the caller's
    out-of-scope set when the run was selective."""
    path = Path(path) if path is not None else DEFAULT_BASELINE
    old = old if old is not None else {}
    emitted: set[tuple] = set()
    rows: list[tuple[tuple, str]] = []
    for f in findings:
        k = f.key()
        if k not in emitted:
            emitted.add(k)
            rows.append((k, old.get(k, TODO_TAG)))
    for k, just in (keep or {}).items():
        if k not in emitted:
            emitted.add(k)
            rows.append((k, just))
    entries = [
        {"code": k[0], "path": k[1], "scope": k[2], "detail": k[3],
         "justification": just}
        for k, just in sorted(rows, key=lambda r: r[0])
    ]
    path.write_text(json.dumps(
        {"version": BASELINE_VERSION, "findings": entries},
        indent=1, sort_keys=False) + "\n", encoding="utf-8")
    return path
