"""bngcheck: dataplane-invariant static analysis + runtime sanitizers.

Static half (stdlib `ast`, no jax import): `bng check` /
`python -m bng_tpu.analysis` runs seven passes over the scan set and
compares findings against the checked-in baseline —

    hotpath         BNG001-003  dispatch scope never forces; disarmed
                                hooks guard-first, allocation-free
    jit-discipline  BNG010-012  cached jit factories, donated table
                                steps, fixed-width traced scalars
    handler-audit   BNG020-021  no swallowed broad excepts (Yuan '14)
    registry        BNG030-035  span/fault/metric/checkpoint/trigger
                                vocabularies consistent
    single-writer   BNG040-041  table mutators only from allowlisted
                                writer modules
    fencing         BNG050      no wall-clock over async dispatch
                                without a force
    concurrency     BNG060-064  the `_ctl` thread-ownership discipline:
                                cross-context mutations hold a common
                                lock, no check-then-act / unreleased
                                acquires / blocking under loop locks /
                                orphan threads — contexts classified
                                from the repo's own thread entry points
                                via a cached call-graph fact

Runtime half (`BNG_SANITIZE=1`, analysis/sanitize.py): arms
jax.transfer_guard + debug_nans around hot-path tests so the transfer
lint's claims are cross-checked dynamically (best-effort on XLA:CPU —
see the module docstring for which guards fire where), plus the
`@owned_by` ownership assertions — the dynamic cross-check of the
concurrency pass (unlocked cross-context mutation raises).
"""

from bng_tpu.analysis.core import (Finding, Project, Report,  # noqa: F401
                                   run_passes)
from bng_tpu.analysis.passes import ALL_PASSES, all_codes, build  # noqa: F401


def run_analysis(root, paths=None, select=None) -> "Report":
    """Programmatic entry: scan `root` and return the Report (no
    baseline applied — callers split against a baseline themselves)."""
    project = Project.load(root, paths)
    return run_passes(project, build(set(select) if select else None))
