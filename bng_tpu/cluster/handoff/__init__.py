"""Fabric state handoff: chunked, signed, resumable checkpoint transfer.

The carve-handoff lane for true multi-box deployment (ISSUE 20): a
joiner hydrates its carved blocks, a replan moves block state, and a
standby bootstraps across hosts — all by streaming checkpoint bytes
over the same authenticated fabric transport the membership beats ride.
"""

from .protocol import (DEFAULT_CHUNK_SIZE, HandoffError, HandoffManager,
                       StateReceiver, StateSender, build_handoff_checkpoint,
                       parse_handoff_checkpoint)

__all__ = [
    "DEFAULT_CHUNK_SIZE", "HandoffError", "HandoffManager",
    "StateReceiver", "StateSender", "build_handoff_checkpoint",
    "parse_handoff_checkpoint",
]
