"""Chunked, signed, resumable state transfer over the fabric transport.

Checkpoint bytes are framed into sequenced chunks that ride the same
authenticated datagram lane as the membership beats — every frame is
PSK-HMAC-signed by the transport, and the payload carries its own
defense in depth: a per-chunk CRC32, a manifest with the whole-payload
SHA-256, and (for checkpoint payloads) the `runtime/checkpoint.py`
structural gate run on the assembled bytes BEFORE anything hydrates.

The protocol is receiver-driven and never half-hydrates:

- **manifest** (`xfer_manifest`): transfer id, total length, chunk
  geometry, payload digest, purpose + caller meta. A receiver holding
  partial state for the same (src, xid, digest) keeps its chunks and
  ACKs its cursor — that IS resume; a different digest resets it.
- **chunks** (`xfer_chunk`): base64 payload slices sized under the
  transport's `MAX_DATAGRAM`, each with its own CRC32. A corrupt chunk
  is dropped and re-requested — rejection is always re-request, never
  partial acceptance.
- **acks** (`xfer_ack`): the receiver's contiguous cursor plus an
  explicit gap list (`need`). The sender retransmits needs first, then
  streams a bounded window past the highest ack. `reject=True` wipes
  both sides back to zero (assembled payload failed the digest or the
  checkpoint gate: the only safe cursor is 0).

`HandoffManager` multiplexes senders and receivers per node and owns
the cursor/manifest mutations (`set_manifest` / `accept_chunk` are on
the bngcheck single-writer allowlist — a second writer would desync the
ack cursor from the assembled bytes).
"""

from __future__ import annotations

import base64
import hashlib
import time
import zlib
from typing import Callable

# 4 KiB of raw payload per chunk: base64 inflates it to ~5.5 KiB and
# the signed JSON envelope stays safely under MAX_DATAGRAM (8 KiB).
# PERF_NOTES §22 has the sizing curve — bigger chunks amortize the
# HMAC+JSON overhead, smaller ones re-request less on corruption.
DEFAULT_CHUNK_SIZE = 4096
DEFAULT_WINDOW = 8
_MAX_NEED = 128  # gap list cap per ack (datagram bound)

KIND_MANIFEST = "xfer_manifest"
KIND_CHUNK = "xfer_chunk"
KIND_ACK = "xfer_ack"


class HandoffError(RuntimeError):
    """A transfer that cannot proceed (bad geometry, oversized chunk)."""


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# checkpoint payload helpers (the restore verification surface)
# ---------------------------------------------------------------------------

def build_handoff_checkpoint(seq: int, components: dict,
                             arrays: dict | None = None) -> bytes:
    """Wrap handoff state in the checkpoint container so the receiver
    reuses `verify_checkpoint_bytes` (magic + header CRC + payload CRC)
    as its hydration gate — the exact rejection surface restore has."""
    from bng_tpu.runtime.checkpoint import Checkpoint, encode_checkpoint

    return encode_checkpoint(Checkpoint(
        meta={"seq": int(seq), "kind": "fabric_handoff",
              "components": components},
        arrays=arrays or {}))


def parse_handoff_checkpoint(data: bytes) -> dict:
    """Verify + decode handoff bytes -> the components dict. Raises
    `CheckpointError` on any structural corruption (callers treat that
    as reject-to-re-request, never partial hydration)."""
    from bng_tpu.runtime.checkpoint import decode_checkpoint

    return dict(decode_checkpoint(data).meta.get("components", {}))


def verify_handoff_bytes(data: bytes) -> None:
    """The default assembled-payload gate: full checkpoint structural
    validation (header CRC, payload length, payload CRC32)."""
    from bng_tpu.runtime.checkpoint import verify_checkpoint_bytes

    verify_checkpoint_bytes(data)


# ---------------------------------------------------------------------------
# sender
# ---------------------------------------------------------------------------

class StateSender:
    """One outbound transfer: manifest + windowed chunk stream, driven
    by receiver acks and a retransmit timer (`pump`)."""

    def __init__(self, transport, dst: str, xid: str, data: bytes, *,
                 kind: str = "carve", meta: dict | None = None,
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 window: int = DEFAULT_WINDOW,
                 retry_interval_s: float = 1.0,
                 clock: Callable[[], float] = time.time):
        if chunk_size <= 0 or chunk_size > 5120:
            # 5120 raw -> ~6.9 KiB base64: the ceiling that still fits
            # the signed envelope in one datagram
            raise HandoffError(f"chunk_size {chunk_size} out of (0, 5120]")
        self.transport = transport
        self.dst = dst
        self.xid = xid
        self.data = data
        self.kind = kind
        self.meta = dict(meta or {})
        self.chunk_size = chunk_size
        self.window = window
        self.retry_interval_s = retry_interval_s
        self.clock = clock
        self.n_chunks = max(1, (len(data) + chunk_size - 1) // chunk_size)
        self.acked = 0          # receiver's contiguous cursor
        self.sent_high = 0      # chunks streamed past the cursor
        self.need: list[int] = []
        self.done = False
        self.rejected = 0
        self._manifest_acked = False
        self._last_progress = float(clock())
        self.stats = {"tx_chunks": 0, "retx_chunks": 0, "acks_rx": 0,
                      "manifests_tx": 0, "rejects_rx": 0}
        self._send_manifest()

    # -- wire --------------------------------------------------------------
    def _send_manifest(self) -> None:
        self.stats["manifests_tx"] += 1
        self.transport.send(self.dst, KIND_MANIFEST, {
            "xid": self.xid, "kind": self.kind,
            "total_len": len(self.data), "n_chunks": self.n_chunks,
            "chunk_size": self.chunk_size, "digest": _digest(self.data),
            "meta": self.meta})

    def _send_chunk(self, seq: int, retx: bool = False) -> None:
        lo = seq * self.chunk_size
        raw = self.data[lo: lo + self.chunk_size]
        self.stats["retx_chunks" if retx else "tx_chunks"] += 1
        self.transport.send(self.dst, KIND_CHUNK, {
            "xid": self.xid, "seq": seq,
            "crc": zlib.crc32(raw) & 0xFFFFFFFF,
            "data": base64.b64encode(raw).decode("ascii")})

    # -- ack absorption ----------------------------------------------------
    def on_ack(self, body: dict) -> None:
        if str(body.get("xid", "")) != self.xid or self.done:
            return
        self.stats["acks_rx"] += 1
        self._last_progress = float(self.clock())
        if body.get("reject"):
            # assembled payload failed the digest/checkpoint gate: the
            # only safe resume point is zero — restart the stream
            self.rejected += 1
            self.stats["rejects_rx"] += 1
            self.acked = 0
            self.sent_high = 0
            self.need = []
            self._send_manifest()
            return
        if body.get("done"):
            self.done = True
            self._manifest_acked = True
            self.acked = self.n_chunks
            return
        self._manifest_acked = True
        self.acked = max(self.acked, int(body.get("cursor", 0)))
        self.sent_high = max(self.sent_high, self.acked)
        need = [int(s) for s in (body.get("need") or ())
                if 0 <= int(s) < self.n_chunks]
        self.need = need

    # -- drive -------------------------------------------------------------
    def pump(self, now: float | None = None) -> int:
        """Advance the stream: retransmit requested gaps, then fill the
        window past the highest chunk in flight. Time-based fallback:
        no ack progress for `retry_interval_s` re-sends the manifest
        (lost-datagram recovery). Returns chunks sent this call."""
        if self.done:
            return 0
        now = float(now if now is not None else self.clock())
        sent = 0
        if not self._manifest_acked:
            if now - self._last_progress >= self.retry_interval_s:
                self._send_manifest()
                self._last_progress = now
            return 0
        for seq in self.need[: self.window]:
            self._send_chunk(seq, retx=True)
            sent += 1
        self.need = self.need[self.window:]
        while (sent < self.window and self.sent_high < self.n_chunks):
            self._send_chunk(self.sent_high)
            self.sent_high += 1
            sent += 1
        if sent == 0 and now - self._last_progress >= self.retry_interval_s:
            # everything streamed but the ack went quiet: nudge from
            # the receiver's last known cursor
            for seq in range(self.acked,
                             min(self.acked + self.window, self.n_chunks)):
                self._send_chunk(seq, retx=True)
                sent += 1
            self._last_progress = now
        return sent


# ---------------------------------------------------------------------------
# receiver
# ---------------------------------------------------------------------------

class _Transfer:
    """Receiver-side state for one (src, xid) stream."""

    __slots__ = ("src", "xid", "kind", "total_len", "n_chunks",
                 "chunk_size", "digest", "meta", "chunks", "cursor",
                 "complete", "delivered")

    def __init__(self, src: str, xid: str):
        self.src = src
        self.xid = xid
        self.kind = ""
        self.total_len = 0
        self.n_chunks = 0
        self.chunk_size = 0
        self.digest = ""
        self.meta: dict = {}
        self.chunks: dict[int, bytes] = {}
        self.cursor = 0
        self.complete = False
        self.delivered = False


class StateReceiver:
    """Inbound transfers for one node: ACK-cursor bookkeeping, gap
    re-requests, corruption rejection, resume. The single writer of the
    transfer cursor/manifest state (bngcheck BNG040 allowlist)."""

    def __init__(self, transport, *, ack_every: int = 4,
                 verify: Callable[[bytes], None] | None = verify_handoff_bytes,
                 on_complete: Callable[[str, dict, bytes], None] | None = None):
        self.transport = transport
        self.ack_every = ack_every
        self.verify = verify
        self.on_complete = on_complete
        self.transfers: dict[tuple, _Transfer] = {}
        self.stats = {"rx_chunks": 0, "rx_corrupt": 0, "rx_dup": 0,
                      "rx_orphan": 0, "resumes": 0, "rejects": 0,
                      "completed": 0, "acks_tx": 0}

    # -- manifest / chunk mutators (single-writer surface) -----------------
    def set_manifest(self, src: str, body: dict) -> _Transfer:
        """Adopt (or resume) a transfer from its manifest. Same digest
        on an in-progress transfer keeps the chunks already banked —
        the resume path; anything else starts clean."""
        xid = str(body.get("xid", ""))
        key = (src, xid)
        t = self.transfers.get(key)
        digest = str(body.get("digest", ""))
        if t is not None and not t.complete and t.digest == digest \
                and t.chunks:
            self.stats["resumes"] += 1
        elif t is None or t.digest != digest:
            t = self.transfers[key] = _Transfer(src, xid)
        t.kind = str(body.get("kind", ""))
        t.total_len = int(body.get("total_len", 0))
        t.n_chunks = int(body.get("n_chunks", 0))
        t.chunk_size = int(body.get("chunk_size", 0))
        t.digest = digest
        t.meta = dict(body.get("meta") or {})
        if t.n_chunks <= 0 or t.chunk_size <= 0:
            self.stats["rx_orphan"] += 1
            del self.transfers[key]
            return t
        self._ack(t)
        return t

    def accept_chunk(self, src: str, body: dict) -> None:
        """Bank one chunk: CRC-gate it, advance the contiguous cursor,
        re-request on any mismatch. Completion assembles + verifies the
        whole payload before a single byte is handed to the caller."""
        xid = str(body.get("xid", ""))
        t = self.transfers.get((src, xid))
        if t is None or t.complete:
            self.stats["rx_orphan" if t is None else "rx_dup"] += 1
            return
        try:
            seq = int(body["seq"])
            raw = base64.b64decode(str(body["data"]), validate=True)
            crc = int(body["crc"])
        except (KeyError, TypeError, ValueError):
            self.stats["rx_corrupt"] += 1
            self._ack(t)
            return
        if seq < 0 or seq >= t.n_chunks:
            self.stats["rx_orphan"] += 1
            return
        if (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
            # corrupt in flight: drop it; the gap list re-requests it
            self.stats["rx_corrupt"] += 1
            self._ack(t)
            return
        if seq in t.chunks:
            # duplicate = the sender missed an ack (retransmit storm):
            # re-ack so it re-learns the cursor instead of looping
            self.stats["rx_dup"] += 1
            self._ack(t)
            return
        self.stats["rx_chunks"] += 1
        t.chunks[seq] = raw
        while t.cursor in t.chunks:
            t.cursor += 1
        if len(t.chunks) >= t.n_chunks:
            self._finish(t)
        elif t.cursor >= t.n_chunks or len(t.chunks) % self.ack_every == 0 \
                or t.cursor != seq + 1:
            # cadence ack, plus an immediate one on out-of-order arrival
            # so the sender learns the gap without waiting a window
            self._ack(t)

    # -- completion --------------------------------------------------------
    def _finish(self, t: _Transfer) -> None:
        data = b"".join(t.chunks[i] for i in range(t.n_chunks))
        reason = ""
        if len(data) != t.total_len:
            reason = f"assembled {len(data)} != manifest {t.total_len}"
        elif _digest(data) != t.digest:
            reason = "payload digest mismatch"
        elif self.verify is not None:
            try:
                self.verify(data)
            except Exception as e:  # CheckpointError and kin
                reason = f"checkpoint gate: {e}"
        if reason:
            # never half-hydrate: wipe the banked chunks and make the
            # sender restart the stream from zero
            self.stats["rejects"] += 1
            t.chunks.clear()
            t.cursor = 0
            self.stats["acks_tx"] += 1
            self.transport.send(t.src, KIND_ACK, {
                "xid": t.xid, "cursor": 0, "need": [], "reject": True,
                "reason": reason})
            return
        t.complete = True
        self.stats["completed"] += 1
        self.stats["acks_tx"] += 1
        self.transport.send(t.src, KIND_ACK,
                            {"xid": t.xid, "cursor": t.n_chunks,
                             "need": [], "done": True})
        if self.on_complete is not None and not t.delivered:
            t.delivered = True
            self.on_complete(t.src, {"xid": t.xid, "kind": t.kind,
                                     "meta": t.meta}, data)

    def _ack(self, t: _Transfer) -> None:
        need = sorted(s for s in range(t.cursor, min(t.n_chunks,
                                                     t.cursor + 4096))
                      if s not in t.chunks and s < max(t.chunks, default=0))
        self.stats["acks_tx"] += 1
        self.transport.send(t.src, KIND_ACK, {
            "xid": t.xid, "cursor": t.cursor, "need": need[:_MAX_NEED],
            "done": t.complete})


# ---------------------------------------------------------------------------
# manager: one node's send+receive multiplexer
# ---------------------------------------------------------------------------

class HandoffManager:
    """Both halves behind one `handle(msg)` / `pump(now)` surface, the
    shape the coordinator's and member's fabric loops drive."""

    def __init__(self, transport, *,
                 clock: Callable[[], float] = time.time,
                 verify: Callable[[bytes], None] | None = verify_handoff_bytes,
                 on_complete: Callable[[str, dict, bytes], None] | None = None,
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 window: int = DEFAULT_WINDOW):
        self.transport = transport
        self.clock = clock
        self.chunk_size = chunk_size
        self.window = window
        self.receiver = StateReceiver(transport, verify=verify,
                                      on_complete=on_complete)
        self.senders: dict[tuple, StateSender] = {}
        self._xid_seq = 0

    def send(self, dst: str, data: bytes, *, kind: str = "carve",
             meta: dict | None = None, xid: str = "") -> StateSender:
        if not xid:
            self._xid_seq += 1
            xid = f"{kind}-{self._xid_seq}"
        s = StateSender(self.transport, dst, xid, data, kind=kind,
                        meta=meta, chunk_size=self.chunk_size,
                        window=self.window, clock=self.clock)
        self.senders[(dst, xid)] = s
        return s

    def handle(self, msg) -> bool:
        """Route one fabric message; True when it was handoff traffic."""
        if msg.kind == KIND_MANIFEST:
            self.receiver.set_manifest(msg.src, msg.body)
        elif msg.kind == KIND_CHUNK:
            self.receiver.accept_chunk(msg.src, msg.body)
        elif msg.kind == KIND_ACK:
            s = self.senders.get((msg.src, str(msg.body.get("xid", ""))))
            if s is not None:
                s.on_ack(msg.body)
        else:
            return False
        return True

    def pump(self, now: float | None = None) -> int:
        sent = 0
        for key in sorted(self.senders):
            sent += self.senders[key].pump(now)
        return sent

    def prune(self) -> None:
        self.senders = {k: s for k, s in self.senders.items() if not s.done}

    def stats(self) -> dict:
        out = dict(self.receiver.stats)
        out["tx_chunks"] = sum(s.stats["tx_chunks"]
                               for s in self.senders.values())
        out["retx_chunks"] = sum(s.stats["retx_chunks"]
                                 for s in self.senders.values())
        out["senders_done"] = sum(1 for s in self.senders.values() if s.done)
        out["senders_live"] = sum(1 for s in self.senders.values()
                                  if not s.done)
        return out
