"""ClusterCoordinator: N BNG instances composed into one system.

The composition root above the single-process boundary:

- **Membership** lives in a shared Nexus store (`MemoryStore` embedded,
  or any Store-shaped remote). Instances register under
  `cluster/instances/`; every membership change elects a carver
  (`elect_carver`: lowest sorted id) which writes the carve plan to
  `cluster/plan`. All members — carver included — apply the plan via
  the store watch, so the plan document is the only authority.
- **Carving** follows `plan.replan`'s never-half-allocate discipline:
  whole blocks only, survivors never disturbed, a leaver's blocks
  return to the free list only after its leases drained
  (`remove_instance` refuses a live book without `force=True`).
- **HA pairing**: each member gets an `ActiveSyncer` fed by its fleet's
  lease events (the TableEventLog replay discipline, relayed by the
  coordinator after every batch) and a `StandbySyncer` mirroring it.
  A `HealthMonitor`/`FailoverController` pair watches liveness; on
  promote, a fresh instance hydrates its lease books from the
  replicated sessions (`InlineInstance.hydrate_sessions`) and takes
  over the same member slot — steering is untouched, so the flash
  crowd's re-DORA lands on the promoted standby with sticky addresses.
- **Steering**: `instance_for_mac` over the sorted plan membership —
  the same FNV-1a32 family as worker and device sharding.

Checkpoint interop: the carve plan rides `runtime/checkpoint.py` as the
`cluster_plan` component (`checkpoint_plan`/`parse_plan`/`restore_plan`)
so a restarted coordinator resumes the exact carve epoch.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable

from bng_tpu.control.ha import (ActiveSyncer, FailoverController,
                                HealthMonitor, InMemorySessionStore, Role,
                                StandbySyncer)
from bng_tpu.control.nexus import MemoryStore, TypedStore
from bng_tpu.telemetry import spans as tele
from bng_tpu.telemetry.recorder import TRIG_HOST_LOSS, TRIG_MEMBER_SUSPECT
from bng_tpu.utils.net import ip_to_u32

from .fabric import FailureDetector
from .handoff import HandoffManager, build_handoff_checkpoint
from .instance import InlineInstance, InstanceSpec, ProcessInstance
from .plan import (ClusterPlan, InstancePlan, elect_carver, initial_plan,
                   instance_for_mac, replan)

_MEMBERS_PREFIX = "cluster/instances/"
_PLAN_KEY = "cluster/plan"

DEFAULT_SERVER_MAC = bytes.fromhex("02aabbccdd01")
DEFAULT_SERVER_IP = ip_to_u32("10.0.0.1")

# dev/test PSK for the fabric when the operator supplies none; a real
# multi-host deployment passes its own via `bng cluster run --fabric-psk`
DEFAULT_FABRIC_PSK = "bng-cluster-fabric-dev-psk"


@dataclass
class InstanceEntity:
    """Membership record in the Nexus store."""

    id: str
    joined_at: float = 0.0
    state: str = "up"


class _Member:
    """Coordinator-side slot for one instance: the serving stack plus
    its HA pair and failover machinery."""

    def __init__(self, instance_id: str):
        self.id = instance_id
        self.spec: InstanceSpec | None = None
        self.instance = None  # InlineInstance | ProcessInstance | None
        self.alive = True
        self.role = "active"  # active | promoted
        self.remote = False   # fabric-joined, served on another host
        self.serving_remote = False  # joiner runs a full serving stack
        self.hydrated_epoch = 0      # last plan epoch a handoff shipped
        self.handoff_xid = ""        # in-flight carve transfer id
        self.host = ""
        self.store: InMemorySessionStore | None = None
        self.syncer: ActiveSyncer | None = None
        self.standby_store: InMemorySessionStore | None = None
        self.standby: StandbySyncer | None = None
        self.monitor: HealthMonitor | None = None
        self.failover: FailoverController | None = None

    @property
    def pending(self) -> bool:
        return self.instance is None


class ClusterCoordinator:
    """Compose N instances behind one front door (inline mode for
    deterministic tests, process mode for real serving)."""

    def __init__(self, *, mode: str = "inline",
                 clock: Callable[[], float] | None = None,
                 store=None,
                 space_network: int = ip_to_u32("10.0.0.0"),
                 space_prefix_len: int = 10,
                 block_prefix_len: int | None = None,
                 nat_base: int = 0, nat_total: int = 0,
                 server_mac: bytes = DEFAULT_SERVER_MAC,
                 server_ip: int = DEFAULT_SERVER_IP,
                 ha: bool = True, n_workers: int = 1,
                 slice_size: int = 256, inbox_capacity: int = 4096,
                 sub_nbuckets: int = 0, lease_time: int = 3600,
                 ha_failover_delay_s: float = 2.0,
                 ha_probe_interval_s: float = 0.5,
                 ha_failure_threshold: int = 3,
                 fabric: bool = False,
                 fabric_psk: str = "",
                 fabric_bind: tuple = ("127.0.0.1", 0),
                 fabric_endpoint=None,
                 fabric_beat_interval_s: float = 0.5,
                 fabric_suspicion_threshold: int = 3,
                 fabric_gray_beats: int = 4,
                 fabric_startup_grace_s: float = 30.0):
        if mode not in ("inline", "process"):
            raise ValueError(f"cluster mode {mode!r}: expected "
                             f"'inline' or 'process'")
        import time

        self.mode = mode
        self.clock = clock or time.time
        self.store = store if store is not None else MemoryStore()
        self.space_network = space_network
        self.space_prefix_len = space_prefix_len
        self.block_prefix_len = block_prefix_len
        self.nat_base = nat_base
        self.nat_total = nat_total
        self.server_mac = server_mac
        self.server_ip = server_ip
        self.ha = ha
        self.n_workers = n_workers
        self.slice_size = slice_size
        self.inbox_capacity = inbox_capacity
        self.sub_nbuckets = sub_nbuckets
        self.lease_time = lease_time
        self.ha_failover_delay_s = ha_failover_delay_s
        self.ha_probe_interval_s = ha_probe_interval_s
        self.ha_failure_threshold = ha_failure_threshold

        self.members: dict[str, _Member] = {}
        self.plan: ClusterPlan | None = None
        self.recarves = 0
        self.failovers = 0
        self.refused_removes = 0
        self.shed_frames = 0
        self.host_losses = 0
        self.steered: dict[str, int] = {}
        self._hosts: dict[str, str] = {}
        self._lost_hosts: set = set()
        # host-loss hook (chaos + ops): called once per lost host with
        # (host, [member_ids]) AFTER the group promotion — the seam the
        # accounting-spool replay and alerting wire into
        self.on_host_loss = None
        # deterministic tests chain the remote members' own tick onto
        # the front door's reply wait (single-threaded SimTransport)
        self.remote_waiter = None
        self.fabric_events: list = []  # last 64 (peer, verdict) pairs

        # -- control fabric: the real-transport membership lane. The
        # coordinator is the star hub — members beat TO it, so it is
        # the sole observer and quorum is 1 (pipe-oracle semantics).
        # `fabric_endpoint` injects a SimTransport endpoint for the
        # deterministic chaos lane; `fabric=True` builds the UDP lane.
        self.fabric_beat_interval_s = fabric_beat_interval_s
        self.fabric_psk = fabric_psk or DEFAULT_FABRIC_PSK
        self.fabric_transport = fabric_endpoint
        self.fabric_detector: FailureDetector | None = None
        self.handoff: HandoffManager | None = None
        # real-transport mode waits on remote replies with a short
        # sleep; an injected SimTransport endpoint is single-threaded
        # and must never sleep (the test drives both sides itself)
        self._fabric_real = fabric and fabric_endpoint is None
        if fabric and fabric_endpoint is None:
            from bng_tpu.control.deviceauth import PSKAuthenticator

            from .fabric import UDPTransport
            self.fabric_transport = UDPTransport(
                "coordinator", PSKAuthenticator(psk=self.fabric_psk),
                bind=fabric_bind, clock=self.clock)
        if self.fabric_transport is not None:
            self.handoff = HandoffManager(self.fabric_transport,
                                          clock=self.clock)
            self.fabric_detector = FailureDetector(
                "coordinator", self.fabric_transport, clock=self.clock,
                beat_interval_s=fabric_beat_interval_s,
                suspicion_threshold=fabric_suspicion_threshold,
                gray_beats=fabric_gray_beats,
                startup_grace_s=fabric_startup_grace_s, quorum=1,
                on_verdict=self._on_fabric_verdict,
                on_message=self._on_fabric_message)

        self._hold_recarve = False
        self.registry = TypedStore(self.store, _MEMBERS_PREFIX.rstrip("/"),
                                   InstanceEntity)
        self._cancel_members = self.store.watch(_MEMBERS_PREFIX,
                                                self._on_membership)
        self._cancel_plan = self.store.watch(_PLAN_KEY, self._on_plan)

    # -- membership -------------------------------------------------------
    def add_instances(self, instance_ids: list, host: str = "",
                      remotes: dict | None = None) -> None:
        """Register a founding (or joining) batch in one carve: blocks
        deal across the whole batch instead of the first registrant
        swallowing the space. `host` tags the batch's placement for the
        plan's host axis (blocks interleave across hosts).

        `remotes` ({instance_id: host}) declares EXPECTED remote slots
        in the same carve: the founding deal interleaves their blocks
        on the host axis now, and the slot comes alive when its box
        `--join`s into it (ISSUE 20 multi-box deployment — the initial
        carve deals every block, so a slot declared later could only
        ever wait on the free list)."""
        remotes = dict(remotes or {})
        for iid in list(instance_ids) + sorted(remotes):
            if iid in self.members:
                raise ValueError(f"instance {iid!r} already registered")
        for iid in instance_ids:
            self.members[iid] = _Member(iid)
            self.members[iid].host = host
            self._hosts[iid] = host
        for iid, rhost in sorted(remotes.items()):
            m = self.members[iid] = _Member(iid)
            m.remote = True
            m.host = rhost
            self._hosts[iid] = rhost
        # hold the carve until the whole batch registered: the founding
        # set must carve TOGETHER, or the first registrant's initial
        # plan swallows every block and the rest join empty-handed
        self._hold_recarve = True
        try:
            for iid in list(instance_ids) + sorted(remotes):
                self.registry.put(iid, InstanceEntity(id=iid,
                                                      joined_at=self.clock()))
        finally:
            self._hold_recarve = False
        self._recarve()
        if self.plan is not None:
            # a restored plan may already cover this membership (carve
            # unchanged -> no new epoch): build the instances anyway
            self._apply_plan()

    def add_instance(self, instance_id: str, host: str = "") -> None:
        self.add_instances([instance_id], host=host)

    def add_remote_instance(self, instance_id: str, host: str,
                            addr: tuple | None = None,
                            serving: bool = False) -> None:
        """A fabric-joined member served on another host: it takes part
        in the carve (its blocks interleave on the host axis) and the
        failure detector watches its beats. With `serving=True` (the
        ISSUE 20 `--join` runtime) the coordinator streams its carve
        over the handoff lane and fronts it with a `RemoteInstance`
        handle, so steered frames are SERVED across the fabric; an
        announce-only joiner keeps the PR 19 shape — frames steered its
        way are shed and counted."""
        if instance_id in self.members:
            raise ValueError(f"instance {instance_id!r} already registered")
        m = _Member(instance_id)
        m.remote = True
        m.serving_remote = serving
        m.host = host
        self.members[instance_id] = m
        self._hosts[instance_id] = host
        if addr is not None and self.fabric_transport is not None:
            self.fabric_transport.add_peer(instance_id, addr)
        if self.fabric_detector is not None:
            self.fabric_detector.watch(instance_id, now=self.clock())
        self.registry.put(instance_id,
                          InstanceEntity(id=instance_id,
                                         joined_at=self.clock()))

    def remove_instance(self, instance_id: str, force: bool = False) -> bool:
        """Leave. Refused while the instance still holds leases — a
        block must drain before its addresses transfer (`force=True`
        drops the sessions, the operator's explicit loss)."""
        m = self.members.get(instance_id)
        if m is None:
            raise KeyError(f"unknown instance {instance_id!r}")
        held = m.instance.lease_count() if m.instance is not None else 0
        if not held and m.remote and m.store is not None:
            # a remote member's authoritative books live off-box; the
            # HA mirror on this host is the drain evidence we hold
            held = len(m.store)
        if m.instance is not None and not force and held:
            self.refused_removes += 1
            return False
        if m.instance is not None:
            m.instance.close()
        del self.members[instance_id]
        self.registry.delete(instance_id)
        return True

    def _on_membership(self, _key: str, _value) -> None:
        if not self._hold_recarve:
            self._recarve()

    def _recarve(self) -> None:
        ids = sorted(self.registry.list())
        carver = elect_carver(ids)
        if carver is None or carver not in self.members:
            return  # carver hosted elsewhere (or empty cluster)
        if self.plan is None:
            new = initial_plan(self.space_network, self.space_prefix_len,
                               ids, block_prefix_len=self.block_prefix_len,
                               nat_base=self.nat_base,
                               nat_total=self.nat_total, hosts=self._hosts)
        else:
            new = replan(self.plan, ids, hosts=self._hosts)
            if new is self.plan:
                return
        self.recarves += 1
        self.store.put(_PLAN_KEY, json.dumps(new.to_dict(),
                                             sort_keys=True).encode())

    # -- plan application -------------------------------------------------
    def _on_plan(self, _key: str, value: bytes | None) -> None:
        if value is None:
            return
        incoming = ClusterPlan.from_dict(json.loads(value))
        if self.plan is not None and incoming.epoch <= self.plan.epoch:
            return
        self.plan = incoming
        self._apply_plan()

    def _apply_plan(self) -> None:
        for iid, iplan in self.plan.members.items():
            m = self.members.get(iid)
            if m is None or not iplan.blocks:
                continue
            if m.remote:
                # serving joiners hydrate over the handoff stream; a
                # new epoch (join carve or replan block move) ships a
                # fresh carve checkpoint
                if (m.serving_remote and self.handoff is not None
                        and m.hydrated_epoch < self.plan.epoch):
                    self._start_handoff(m)
                continue
            if m.instance is None:
                m.spec = self._spec_for(iplan)
                m.instance = self._build_instance(m.spec)
                # only process members beat over the fabric; inline
                # members stay on the in-process flag oracle (watching
                # them would read their silence as failure)
                if self.fabric_detector is not None and self.mode == "process":
                    self.fabric_detector.watch(iid, now=self.clock())
                if self.ha:
                    self._wire_ha(m)
            elif hasattr(m.instance, "apply_plan"):
                # inline members adopt carve changes live; a process
                # member restarts on its next roll to pick them up
                m.spec = self._spec_for(iplan)
                m.instance.apply_plan(iplan)

    def _start_handoff(self, m: _Member) -> None:
        """Stream the member's carve to it as a verified checkpoint:
        the plan document, its spec parameters, and any HA sessions
        this host already mirrors for its slot (standby bootstrap /
        replan move — empty at first join). The receiver hydrates all
        of it or none of it."""
        iplan = self.plan.members[m.id]
        m.spec = self._spec_for(iplan)
        sessions = []
        if m.store is not None:
            sessions = [{"session_id": s.session_id, "mac": s.mac,
                         "ip": s.ip, "pool_id": s.pool_id,
                         "username": s.username,
                         "lease_expiry": s.lease_expiry,
                         "qos_policy": s.qos_policy}
                        for s in m.store.all()]
        data = build_handoff_checkpoint(self.plan.epoch, {
            "cluster_plan": self.plan.to_dict(),
            "member": {
                "instance_id": m.id,
                "spec": {"server_mac": self.server_mac.hex(),
                         "server_ip": self.server_ip,
                         "n_workers": self.n_workers,
                         "slice_size": self.slice_size,
                         "inbox_capacity": self.inbox_capacity,
                         "lease_time": self.lease_time,
                         "sub_nbuckets": self.sub_nbuckets},
                "sessions": sessions,
            },
        })
        sender = self.handoff.send(m.id, data, kind="carve",
                                   meta={"instance_id": m.id,
                                         "epoch": self.plan.epoch})
        m.handoff_xid = sender.xid
        m.hydrated_epoch = self.plan.epoch

    def _remote_pump(self) -> None:
        """Drive the fabric while a RemoteInstance waits for replies:
        drain the transport (the detector routes rbatch replies back
        through `_on_fabric_message`), let an injected waiter advance
        the far side (sim tests), breathe in real-UDP mode."""
        if self.fabric_detector is not None:
            self.fabric_detector.tick(self.clock())
        if self.remote_waiter is not None:
            self.remote_waiter()
        elif self._fabric_real:
            import time as _time

            _time.sleep(0.002)

    def _spec_for(self, iplan: InstancePlan) -> InstanceSpec:
        spec = InstanceSpec.from_plan(
            iplan, self.plan, server_mac=self.server_mac,
            server_ip=self.server_ip, n_workers=self.n_workers,
            slice_size=self.slice_size, inbox_capacity=self.inbox_capacity,
            lease_time=self.lease_time, sub_nbuckets=self.sub_nbuckets)
        if self.mode == "process" and self.fabric_transport is not None:
            # the child beats back to this address over the UDP fabric
            spec.fabric_addr = tuple(self.fabric_transport.addr)
            spec.fabric_psk = self.fabric_psk
            spec.beat_interval_s = self.fabric_beat_interval_s
        return spec

    def _build_instance(self, spec: InstanceSpec):
        if self.mode == "process":
            return ProcessInstance(spec)
        return InlineInstance(spec, clock=self.clock)

    # -- HA pairing -------------------------------------------------------
    def _wire_ha(self, m: _Member, checkpoint: dict | None = None) -> None:
        m.store = InMemorySessionStore()
        m.syncer = ActiveSyncer(m.store)
        if checkpoint is not None:
            m.syncer.restore_state(checkpoint)

        def transport(mm=m):
            if not mm.alive:
                raise ConnectionError(f"active {mm.id} down")
            return mm.syncer

        m.standby_store = InMemorySessionStore()
        m.standby = StandbySyncer(m.standby_store, transport)
        if checkpoint is not None:
            m.standby.bootstrap_state(checkpoint)
        m.failover = FailoverController(
            role=Role.STANDBY, failover_delay_s=self.ha_failover_delay_s,
            auto_failback=False,
            on_role_change=lambda role, iid=m.id: self._on_role_change(
                iid, role))
        # with a fabric, liveness comes from the detector (beats over
        # the transport), not the parent-side flag alone: a SIGKILL'd
        # process member stops beating, the detector demotes it, and
        # THIS probe goes false — no pipe heartbeat on the probe path.
        # `mm.alive` stays in the conjunction as the chaos kill verb.
        m.monitor = HealthMonitor(
            probe=lambda mm=m: mm.alive and self._fabric_probe(mm.id),
            interval_s=self.ha_probe_interval_s,
            failure_threshold=self.ha_failure_threshold,
            on_event=m.failover.handle_health_event)
        m.standby.tick(self.clock())

    def _fabric_probe(self, instance_id: str) -> bool:
        if self.fabric_detector is None:
            return True  # inline pipe-oracle mode: the flag decides
        return self.fabric_detector.probe(instance_id)

    def _relay_sessions(self, m: _Member, now: float) -> None:
        """Worker lease events -> SessionStates -> ActiveSyncer push:
        the parent-side single-writer replay, same discipline as the
        fleet's table-event relay."""
        if m.syncer is None or m.instance is None:
            return
        events = m.instance.drain_session_events()
        for op, payload in m.instance.session_states(events, now):
            if op == "put":
                m.syncer.push_change(payload)
            else:
                m.syncer.push_change(None, session_id=payload)

    def _on_role_change(self, instance_id: str, role: Role) -> None:
        if role == Role.ACTIVE:
            self._promote(instance_id)

    def _promote(self, instance_id: str) -> None:
        """Standby takes over the member slot: fresh stack on the same
        carve, lease books hydrated from the replicated sessions, HA
        pair re-wired with the promoted side as the new active."""
        m = self.members[instance_id]
        if m.standby is None or m.spec is None:
            return
        m.standby.disconnect()
        ckpt = m.standby.checkpoint_state()
        sessions = m.standby_store.all()
        promoted = self._build_instance(m.spec)
        if isinstance(promoted, InlineInstance):
            promoted.hydrate_sessions(sessions, now=self.clock())
        if m.instance is not None:
            m.instance.close()
        m.instance = promoted
        m.alive = True
        m.role = "promoted"
        self.failovers += 1
        if m.remote:
            # the slot moved hosts: it now serves LOCALLY on the
            # survivor, so the detector must stop expecting beats from
            # the dead box (a reset would re-demote the promoted slot)
            m.remote = False
            m.serving_remote = False
            m.handoff_xid = ""
            if self.fabric_detector is not None:
                self.fabric_detector.forget(m.id)
        elif self.fabric_detector is not None:
            # the slot is a new process with fresh counters: wipe the
            # old view AND the transport's replay floor, or the new
            # child's seq=1 beats all read as replays of the dead one
            self.fabric_detector.reset(m.id, now=self.clock())
        if self.fabric_detector is not None:
            reset_peer = getattr(self.fabric_transport, "reset_peer", None)
            if reset_peer is not None:
                reset_peer(m.id)
        self._wire_ha(m, checkpoint=ckpt)

    def kill_instance(self, instance_id: str) -> None:
        """Chaos verb: the instance stops answering (books frozen, the
        real crash shape). Health probes see it; failover owns
        recovery."""
        self.members[instance_id].alive = False

    # -- fabric verdicts --------------------------------------------------
    def _on_fabric_verdict(self, peer_id: str, state: str) -> None:
        """Detector transition: record it, flight-record it. Demotion
        itself flows through the probe path — the HealthMonitor /
        FailoverController machinery owns failover, same as ever."""
        self.fabric_events.append((peer_id, state))
        del self.fabric_events[:-64]
        tele.trigger(TRIG_MEMBER_SUSPECT, f"{peer_id} -> {state}")

    def _on_fabric_message(self, msg) -> None:
        """Non-beat fabric traffic. `join`: a member on another host
        announces itself — it enters the carve as a remote member (a
        `serving` joiner additionally gets the handoff stream and a
        RemoteInstance front). Handoff acks and remote-serving replies
        route to their owners; a re-sent join (the member's backoff
        retrying into an already-registered slot) is idempotent."""
        if msg.kind == "join":
            iid = str(msg.body.get("instance_id", ""))
            if not iid:
                return
            m = self.members.get(iid)
            if m is None:
                self.add_remote_instance(
                    iid, host=str(msg.body.get("host", "")),
                    serving=bool(msg.body.get("serving", False)))
                return
            if not m.remote:
                return  # a local member's id: not joinable from outside
            # a pre-declared slot (co-carved at founding) comes alive —
            # or a registered joiner's backoff re-sent the announce
            if bool(msg.body.get("serving", False)):
                m.serving_remote = True
            if self.fabric_detector is not None \
                    and iid not in self.fabric_detector.views:
                self.fabric_detector.watch(iid, now=self.clock())
            if (m.serving_remote and self.handoff is not None
                    and self.plan is not None
                    and iid in self.plan.members
                    and self.plan.members[iid].blocks
                    and m.hydrated_epoch < self.plan.epoch):
                self._start_handoff(m)
            return
        if self.handoff is not None and self.handoff.handle(msg):
            return
        if msg.kind in ("rbatch_reply", "rexpire_reply"):
            m = self.members.get(msg.src)
            if m is not None and m.instance is not None \
                    and hasattr(m.instance, "deliver"):
                m.instance.deliver(msg.body)

    def tick(self, now: float | None = None) -> None:
        """Drive the fabric detector, standby reconnects, health probes
        and failover state machines (all tick(now)-based,
        SimClock-compatible)."""
        now = now if now is not None else self.clock()
        if self.fabric_detector is not None:
            self.fabric_detector.tick(now)
        if self.handoff is not None:
            self.handoff.pump(now)
            self._adopt_hydrated_remotes()
        self._check_host_loss()
        for _iid, m in sorted(self.members.items()):
            if m.standby is not None:
                m.standby.tick(now)
            if m.monitor is not None:
                m.monitor.tick(now)
            if m.failover is not None:
                m.failover.tick(now)

    def _adopt_hydrated_remotes(self) -> None:
        """A serving joiner whose carve handoff the receiver fully
        acked becomes a steering target: front it with a
        RemoteInstance and wire its HA pair on THIS host (the
        surviving-host half that host-loss promotion hydrates from)."""
        from .member import RemoteInstance

        for iid, m in sorted(self.members.items()):
            if not (m.remote and m.serving_remote and m.handoff_xid
                    and m.instance is None):
                continue
            sender = self.handoff.senders.get((iid, m.handoff_xid))
            if sender is None or not sender.done:
                continue
            m.instance = RemoteInstance(
                self.fabric_transport, iid, m.spec, clock=self.clock,
                pump=self._remote_pump)
            m.alive = True
            if self.ha:
                self._wire_ha(m)

    def _check_host_loss(self) -> None:
        """The plan's host axis driving failure handling: when EVERY
        fabric-watched remote member on a host is DOWN by accusation
        quorum, the box is gone — promote the surviving-host HA halves
        as a group (no per-member failover-delay stagger; their state
        is already here)."""
        if self.fabric_detector is None:
            return
        by_host: dict[str, list] = {}
        for iid, m in sorted(self.members.items()):
            if m.remote and m.host and iid in self.fabric_detector.views:
                by_host.setdefault(m.host, []).append(m)
        for host, group in sorted(by_host.items()):
            if host in self._lost_hosts:
                continue
            if not all(self.fabric_detector.views[m.id].state == "down"
                       for m in group):
                continue
            self._lost_hosts.add(host)
            self.host_losses += 1
            tele.trigger(TRIG_HOST_LOSS,
                         f"host {host} lost: "
                         f"{[m.id for m in group]} down by quorum")
            for m in group:
                m.alive = False
                if m.standby is not None and m.spec is not None:
                    self._promote(m.id)
            if self.on_host_loss is not None:
                self.on_host_loss(host, [m.id for m in group])

    # -- the front door ---------------------------------------------------
    def member_ids(self) -> tuple:
        if self.plan is not None:
            return self.plan.serving_ids()
        return tuple(sorted(self.members))

    def handle_batch(self, items: list, now: float | None = None) -> list:
        """[(lane, frame)] -> [(lane, reply)] in lane order: steer each
        frame to its member by source MAC, serve per member, relay
        session events, re-merge."""
        now = now if now is not None else self.clock()
        ids = self.member_ids()
        groups: dict[str, list] = {}
        results: list = []
        for item in items:
            lane, frame = item[0], item[1]
            if len(frame) < 12 or not ids:
                self.shed_frames += 1
                results.append((lane, None))
                continue
            iid = instance_for_mac(frame[6:12], ids)
            groups.setdefault(iid, []).append((lane, frame))
        for iid in sorted(groups):
            m = self.members.get(iid)
            if m is None or m.instance is None or not m.alive:
                self.shed_frames += len(groups[iid])
                results.extend((lane, None) for lane, _f in groups[iid])
                continue
            self.steered[iid] = self.steered.get(iid, 0) + len(groups[iid])
            results.extend(m.instance.handle_batch(groups[iid], now))
            self._relay_sessions(m, now)
        results.sort(key=lambda r: r[0])
        return results

    def expire(self, now: int, max_reaps: int | None = None) -> int:
        total = 0
        for _iid, m in sorted(self.members.items()):
            if m.instance is not None and m.alive:
                total += m.instance.expire(now, max_reaps)
                self._relay_sessions(m, float(now))
        return total

    # -- checkpoint interop (runtime/checkpoint.py 'cluster_plan') --------
    def checkpoint_plan(self) -> dict:
        if self.plan is None:
            return {}
        return self.plan.to_dict()

    @staticmethod
    def parse_plan(state: dict) -> int:
        """Dry-parse (restore pre-check): raises on a corrupt plan,
        touches nothing. Returns the member count."""
        if not state:
            return 0
        return len(ClusterPlan.from_dict(state).members)

    def restore_plan(self, state: dict) -> int:
        """Resume a checkpointed carve: the plan document goes back
        through the store so every watcher applies it — restore is just
        a replayed carve."""
        if not state:
            return 0
        incoming = ClusterPlan.from_dict(state)
        self.store.put(_PLAN_KEY, json.dumps(incoming.to_dict(),
                                             sort_keys=True).encode())
        return len(incoming.members)

    # -- introspection ----------------------------------------------------
    def status(self) -> dict:
        members = {}
        for iid, m in sorted(self.members.items()):
            entry: dict = {"alive": m.alive, "role": m.role,
                           "pending": m.pending, "remote": m.remote,
                           "serving_remote": m.serving_remote,
                           "host": m.host,
                           "steered": self.steered.get(iid, 0)}
            if m.instance is not None:
                entry.update(m.instance.status())
            if m.syncer is not None:
                entry["ha"] = {
                    "active_sessions": len(m.store),
                    "standby_sessions": len(m.standby_store),
                    "standby_connected": bool(m.standby.connected),
                    "failover_state": m.failover.state.value,
                }
            members[iid] = entry
        out = {
            "mode": self.mode,
            "instances": len(self.members),
            "members": members,
            "recarves": self.recarves,
            "failovers": self.failovers,
            "refused_removes": self.refused_removes,
            "shed_frames": self.shed_frames,
            "host_losses": self.host_losses,
            "lost_hosts": sorted(self._lost_hosts),
        }
        if self.plan is not None:
            out["plan"] = {
                "epoch": self.plan.epoch,
                "blocks": self.plan.n_blocks,
                "free_blocks": len(self.plan.free),
                "addresses": self.plan.total_addresses(),
                "n_hosts": self.plan.n_hosts,
                "members": {iid: p.addresses()
                            for iid, p in sorted(self.plan.members.items())},
            }
        if self.fabric_detector is not None:
            out["fabric"] = self.fabric_detector.status()
            out["fabric"]["transport"] = dict(self.fabric_transport.stats)
            if self.handoff is not None:
                out["fabric"]["handoff"] = self.handoff.stats()
        return out

    def close(self) -> None:
        self._cancel_members()
        self._cancel_plan()
        if self.fabric_transport is not None:
            self.fabric_transport.close()
        for m in self.members.values():
            if m.instance is not None:
                m.instance.close()
