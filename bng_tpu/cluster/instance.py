"""One cluster member: a carved slice of the address space served by an
inline slow-path fleet, with session events drained for HA replication.

`InstanceSpec` is picklable (the `FleetSpec` mold) so process mode can
ship it to a child; `InlineInstance` is the in-process build both modes
share — process mode runs one inside the child and speaks a small pipe
verb protocol (`_instance_child`).

The HA seam: the fleet's `lease_hook` funnels worker lease events into
`_session_events`; the coordinator drains them after every batch and
pushes `SessionState`s through the instance's `ActiveSyncer` — the same
single-writer replay discipline as the fleet's TableEventLog, which is
what lets replication work identically for inline and process members.
Promotion is the reverse seam: `hydrate_sessions` rebuilds lease books
from replicated `SessionState`s via `SlowPathFleet.restore_state`, so a
promoted standby answers renewals with the original addresses.
"""

from __future__ import annotations

import multiprocessing as mp
from dataclasses import dataclass, field
from typing import Callable

from bng_tpu.control.admission import AdmissionConfig
from bng_tpu.control.fleet import FleetSpec, FleetPoolSpec, SlowPathFleet
from bng_tpu.control.ha import SessionState
from bng_tpu.control.pool import Pool, PoolManager

from .plan import CarvedBlock, InstancePlan


@dataclass
class InstanceSpec:
    """Everything needed to build (or rebuild) one member's stack —
    picklable, like `FleetSpec`."""

    instance_id: str
    server_mac: bytes
    server_ip: int
    blocks: list = field(default_factory=list)      # [(network, prefix_len, pool_id)]
    nat_ranges: list = field(default_factory=list)  # [(start_ip, count)]
    n_workers: int = 1
    slice_size: int = 256
    inbox_capacity: int = 4096
    lease_time: int = 3600
    dns_primary: int = 0
    sub_nbuckets: int = 0  # >0 builds FastPathTables as the table sink
    # control fabric (ISSUE 19): when set, a process child beats its
    # serving-health word to this coordinator address over the UDP
    # fabric — the probe path the pipe heartbeat used to simulate
    fabric_addr: tuple = ()
    fabric_psk: str = ""
    beat_interval_s: float = 0.5

    @classmethod
    def from_plan(cls, iplan: InstancePlan, cluster_plan, *, server_mac: bytes,
                  server_ip: int, **kw) -> "InstanceSpec":
        return cls(
            instance_id=iplan.instance_id,
            server_mac=server_mac, server_ip=server_ip,
            blocks=[(b.network, b.prefix_len, b.pool_id)
                    for b in iplan.blocks],
            nat_ranges=[cluster_plan.nat_range(b) for b in iplan.blocks
                        if cluster_plan.nat_total > 0],
            **kw)


def _build_pools(spec: InstanceSpec):
    fastpath = None
    if spec.sub_nbuckets > 0:
        from bng_tpu.runtime.tables import FastPathTables

        fastpath = FastPathTables(sub_nbuckets=spec.sub_nbuckets,
                                  vlan_nbuckets=64, cid_nbuckets=64,
                                  max_pools=max(16, len(spec.blocks) + 1))
        fastpath.set_server_config(spec.server_mac, spec.server_ip)
    pools = PoolManager(fastpath)
    for network, prefix_len, pool_id in spec.blocks:
        pools.add_pool(Pool(pool_id=pool_id, network=network,
                            prefix_len=prefix_len, gateway=spec.server_ip,
                            dns_primary=spec.dns_primary,
                            lease_time=spec.lease_time))
    return pools, fastpath


class InlineInstance:
    """One member: carved pools + inline fleet + session-event drain."""

    def __init__(self, spec: InstanceSpec, clock: Callable[[], float]):
        if not spec.blocks:
            raise ValueError(
                f"instance {spec.instance_id}: empty carve (no blocks)")
        self.spec = spec
        self.clock = clock
        self._session_events: list = []
        self.pools, self.fastpath = _build_pools(spec)
        self.fleet = self._build_fleet(self.pools, self.fastpath)
        self.batches = 0
        self.replies = 0

    def _build_fleet(self, pools, fastpath) -> SlowPathFleet:
        fspec = FleetSpec(
            server_mac=self.spec.server_mac, server_ip=self.spec.server_ip,
            pools=[FleetPoolSpec(pool_id=p.pool_id, network=p.network,
                                 prefix_len=p.prefix_len, gateway=p.gateway,
                                 dns_primary=p.dns_primary,
                                 dns_secondary=p.dns_secondary,
                                 lease_time=p.lease_time,
                                 client_class=p.client_class)
                   for p in pools.pools.values()],
            slice_size=self.spec.slice_size,
            low_watermark=max(1, self.spec.slice_size // 4))
        return SlowPathFleet(
            fspec, self.spec.n_workers, pools, mode="inline",
            table_sink=fastpath, clock=self.clock,
            admission=AdmissionConfig(inbox_capacity=self.spec.inbox_capacity),
            lease_hook=self._on_lease_event)

    # -- HA seam ----------------------------------------------------------
    def _on_lease_event(self, event: str, lease: dict, sid: str) -> None:
        self._session_events.append((event, lease, sid))

    def drain_session_events(self) -> list:
        out, self._session_events = self._session_events, []
        return out

    def session_states(self, events: list, now: float) -> list:
        """Lease events -> (op, SessionState|session_id) replication
        records (the cli `_ha_lease` closure shape, minus NAT which the
        carve plan owns cluster-side)."""
        out = []
        for event, lease, sid in events:
            if event == "stop":
                out.append(("delete", sid))
            else:
                out.append(("put", SessionState(
                    session_id=sid, mac=lease["mac"], ip=lease["ip"],
                    pool_id=lease["pool_id"],
                    username=lease.get("username") or "",
                    lease_expiry=float(lease["expiry"]),
                    qos_policy=lease.get("qos_policy") or "",
                    session_kind="ipoe", updated_at=now)))
        return out

    # -- serving ----------------------------------------------------------
    def handle_batch(self, items: list, now: float | None = None) -> list:
        self.batches += 1
        out = self.fleet.handle_batch(items, now)
        self.replies += sum(1 for _lane, rep in out if rep is not None)
        return out

    def expire(self, now: int, max_reaps: int | None = None) -> int:
        return self.fleet.expire(now, max_reaps)

    # -- promotion / carve changes ----------------------------------------
    def hydrate_sessions(self, sessions: list, now: float) -> int:
        """Rebuild lease books from replicated SessionStates (standby
        promotion). Routed through `SlowPathFleet.restore_state` so the
        re-shard, parent-pool claims and table rebuild all follow the
        checkpoint-restore discipline."""
        leases = []
        for s in sessions:
            if not s.mac or not s.ip:
                continue
            leases.append({"mac": s.mac, "ip": s.ip, "pool_id": s.pool_id,
                           "expiry": s.lease_expiry,
                           "session_id": s.session_id,
                           "username": s.username,
                           "qos_policy": s.qos_policy})
        state = {"workers": [{"worker_id": 0, "session_seq": len(leases),
                              "leases": leases, "offers": []}]}
        return self.fleet.restore_state(state)

    def apply_plan(self, iplan: InstancePlan) -> bool:
        """Adopt a re-carve. Added blocks rebuild the fleet through
        export/restore (the resize transfer discipline: leases survive,
        the new blocks arrive whole). A block may only LEAVE once it
        holds no leases — half-drained shrink is refused."""
        want = [(b.network, b.prefix_len, b.pool_id) for b in iplan.blocks]
        if want == self.spec.blocks:
            return True
        removed = [b for b in self.spec.blocks if b not in want]
        if removed:
            held = {lease.ip for _w, book in _books(self.fleet)
                    for lease in book.values()}
            for network, prefix_len, pool_id in removed:
                blk = CarvedBlock(network=network, prefix_len=prefix_len,
                                  index=pool_id - 1)
                if any(blk.contains(ip) for ip in held):
                    return False  # not drained — keep serving the old carve
        state = self.fleet.export_state()
        self.spec.blocks = want
        self.pools, self.fastpath = _build_pools(self.spec)
        self.fleet = self._build_fleet(self.pools, self.fastpath)
        self.fleet.restore_state(state)
        return True

    # -- introspection ----------------------------------------------------
    def lease_count(self) -> int:
        return sum(len(book) for _w, book in _books(self.fleet))

    def export_state(self) -> dict:
        return self.fleet.export_state()

    def status(self) -> dict:
        return {
            "instance_id": self.spec.instance_id,
            "blocks": list(self.spec.blocks),
            "addresses": sum(1 << (32 - pl) for _n, pl, _p in self.spec.blocks),
            "nat_ranges": list(self.spec.nat_ranges),
            "workers": self.spec.n_workers,
            "leases": self.lease_count(),
            "batches": self.batches,
            "replies": self.replies,
        }

    def close(self) -> None:
        self.fleet.close()


def _books(fleet: SlowPathFleet):
    from bng_tpu.chaos.invariants import _fleet_worker_books

    return _fleet_worker_books(fleet)


# ---------------------------------------------------------------------------
# process mode: the fleet.py child mold
# ---------------------------------------------------------------------------

def _beat_loop(spec: InstanceSpec, inst: InlineInstance, stop) -> None:
    """Child-side heartbeat: one signed UDP datagram per interval to
    the coordinator, carrying the serving-health word (`work` = batches
    accepted, `served` = replies produced). A SIGKILL takes this thread
    with the process — the beats just stop, which IS the failure signal
    the coordinator's detector consumes."""
    from bng_tpu.control.deviceauth import PSKAuthenticator

    from .fabric import UDPTransport

    ep = UDPTransport(spec.instance_id,
                      PSKAuthenticator(psk=spec.fabric_psk))
    ep.add_peer("coordinator", spec.fabric_addr)
    try:
        while not stop.wait(spec.beat_interval_s):
            ep.send("coordinator", "beat",
                    {"served": inst.replies, "work": inst.batches,
                     "accuse": []})
    finally:
        ep.close()


def _instance_child(spec: InstanceSpec, conn) -> None:
    """Child loop: verbs in, results out. The clock is wall time in the
    child — process mode is the real-serving lane, not the deterministic
    test lane."""
    import threading
    import time

    inst = InlineInstance(spec, clock=time.time)
    stop_beats = threading.Event()
    if spec.fabric_addr:
        threading.Thread(target=_beat_loop, args=(spec, inst, stop_beats),
                         daemon=True).start()
    try:
        while True:
            msg = conn.recv()
            verb = msg[0]
            if verb == "batch":
                _verb, items, now = msg
                out = inst.handle_batch(items, now)
                conn.send(("result", out, inst.drain_session_events()))
            elif verb == "expire":
                _verb, now, max_reaps = msg
                conn.send(("expired", inst.expire(now, max_reaps),
                           inst.drain_session_events()))
            elif verb == "status":
                conn.send(("status", inst.status()))
            elif verb == "export":
                conn.send(("state", inst.export_state()))
            elif verb == "stop":
                stop_beats.set()
                conn.send(("bye",))
                return
    except (EOFError, KeyboardInterrupt):
        stop_beats.set()
        return


class ProcessInstance:
    """Parent-side handle for a child-process member. Same surface as
    `InlineInstance` for the verbs the coordinator uses; session events
    ride back on each reply (the fleet's table-event relay discipline
    across the pipe)."""

    def __init__(self, spec: InstanceSpec, start_method: str | None = None):
        self.spec = spec
        ctx = mp.get_context(start_method or "spawn")
        self._conn, child = ctx.Pipe()
        self._proc = ctx.Process(target=_instance_child, args=(spec, child),
                                 daemon=True)
        self._proc.start()
        child.close()
        self._session_events: list = []
        self.batches = 0

    @property
    def pid(self) -> int | None:
        return self._proc.pid

    def _gather(self, want: str):
        tag, *rest = self._conn.recv()
        if tag != want:
            raise OSError(f"instance {self.spec.instance_id}: expected "
                          f"{want!r}, got {tag!r}")
        return rest

    def handle_batch(self, items: list, now: float | None = None) -> list:
        self.batches += 1
        self._conn.send(("batch", items, now))
        out, events = self._gather("result")
        self._session_events.extend(events)
        return out

    def expire(self, now: int, max_reaps: int | None = None) -> int:
        self._conn.send(("expire", now, max_reaps))
        n, events = self._gather("expired")
        self._session_events.extend(events)
        return n

    def drain_session_events(self) -> list:
        out, self._session_events = self._session_events, []
        return out

    def session_states(self, events: list, now: float) -> list:
        return InlineInstance.session_states(self, events, now)

    def status(self) -> dict:
        self._conn.send(("status",))
        return self._gather("status")[0]

    def export_state(self) -> dict:
        self._conn.send(("export",))
        return self._gather("state")[0]

    def lease_count(self) -> int:
        return int(self.status()["leases"])

    def close(self) -> None:
        try:
            self._conn.send(("stop",))
            self._gather("bye")
        except (OSError, EOFError, ValueError):
            pass
        self._proc.join(timeout=5)
        if self._proc.is_alive():
            self._proc.terminate()
