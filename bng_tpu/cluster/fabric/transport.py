"""Fabric transports: authenticated UDP datagrams + deterministic sim.

`UDPTransport` is the production lane: one datagram socket per node
(loopback multiport by default, so tests and single-host clusters need
no privileges), every message HMAC-signed with the existing
`control/deviceauth.py` PSK signer and checked for timestamp skew and
per-source sequence replay on receive. The wire format is one JSON
object per datagram — small (beats are ~200 bytes), debuggable with
tcpdump, and versioned (`v`) so a rolling restart across fabric
versions degrades to counted drops instead of crashes.

`SimTransport` is the deterministic twin the chaos scenarios drive: an
in-memory hub with per-link drop probability, delivery delay and
severed-link knobs. Partitions are **per directed link**, so the NEAT
shape — A↔B dead while both still reach C — is a first-class
configuration (`partition("a", "b")` cuts exactly that pair), not a
binary netsplit.

Both expose the same endpoint surface (`send` / `poll` / `add_peer` /
`stats`), so `membership.FailureDetector` runs unchanged on either.
"""

from __future__ import annotations

import json
import random
import socket
import time
from dataclasses import dataclass, field
from typing import Callable

FABRIC_VERSION = 1
MAX_DATAGRAM = 8192


@dataclass
class FabricMessage:
    """One verified fabric datagram."""

    src: str
    seq: int
    ts: float
    kind: str
    body: dict = field(default_factory=dict)


def _canonical(src: str, seq: int, ts: float, kind: str, body: dict) -> str:
    """The signed byte string: canonical JSON of everything but the
    signature. sort_keys + tight separators make signer and verifier
    byte-identical regardless of dict insertion order."""
    return json.dumps({"v": FABRIC_VERSION, "src": src, "seq": seq,
                       "ts": ts, "kind": kind, "body": body},
                      sort_keys=True, separators=(",", ":"))


class UDPTransport:
    """One node's fabric endpoint: a non-blocking UDP socket plus the
    peer address book. `bind=("127.0.0.1", 0)` (the default) takes an
    ephemeral loopback port — the multiport shape process-mode clusters
    and tests use; a real multi-host deployment binds its fabric
    address via `bng cluster run --listen`."""

    def __init__(self, node_id: str, authenticator,
                 bind: tuple = ("127.0.0.1", 0),
                 clock: Callable[[], float] = time.time,
                 max_skew_s: float = 300.0):
        self.node_id = node_id
        self.authenticator = authenticator
        self.clock = clock
        self.max_skew_s = max_skew_s
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(bind)
        self._sock.setblocking(False)
        self.addr: tuple = self._sock.getsockname()
        self.peers: dict[str, tuple] = {}
        self._seq = 0
        self._last_seq: dict[str, int] = {}
        self.stats = {"tx": 0, "tx_errors": 0, "rx": 0, "rx_bad_sig": 0,
                      "rx_replay": 0, "rx_skew": 0, "rx_malformed": 0}

    def add_peer(self, node_id: str, addr: tuple) -> None:
        self.peers[node_id] = (addr[0], int(addr[1]))

    def reset_peer(self, node_id: str) -> None:
        """Forget a peer's replay floor (member slot re-occupied by a
        fresh process whose seq restarts at 1)."""
        self._last_seq.pop(node_id, None)

    def send(self, dst: str, kind: str, body: dict) -> bool:
        addr = self.peers.get(dst)
        if addr is None:
            self.stats["tx_errors"] += 1
            return False
        self._seq += 1
        ts = float(self.clock())
        payload = _canonical(self.node_id, self._seq, ts, kind, body)
        sig = self.authenticator.sign_message(payload)
        wire = json.dumps({"v": FABRIC_VERSION, "src": self.node_id,
                           "seq": self._seq, "ts": ts, "kind": kind,
                           "body": body, "sig": sig},
                          separators=(",", ":")).encode()
        try:
            self._sock.sendto(wire, addr)
        except OSError:
            self.stats["tx_errors"] += 1
            return False
        self.stats["tx"] += 1
        return True

    def _verify(self, raw: bytes) -> FabricMessage | None:
        try:
            d = json.loads(raw)
            src = str(d["src"])
            seq = int(d["seq"])
            ts = float(d["ts"])
            kind = str(d["kind"])
            body = d["body"]
            sig = str(d["sig"])
            if int(d.get("v", 0)) != FABRIC_VERSION \
                    or not isinstance(body, dict):
                raise ValueError("bad version/body")
        except (KeyError, TypeError, ValueError, json.JSONDecodeError):
            self.stats["rx_malformed"] += 1
            return None
        expected = self.authenticator.sign_message(
            _canonical(src, seq, ts, kind, body))
        import hmac as _hmac

        if not _hmac.compare_digest(sig, expected):
            self.stats["rx_bad_sig"] += 1
            return None
        if abs(float(self.clock()) - ts) > self.max_skew_s:
            self.stats["rx_skew"] += 1
            return None
        if seq <= self._last_seq.get(src, 0):
            # replayed or reordered-behind datagram: beats are
            # idempotent state, only the freshest matters
            self.stats["rx_replay"] += 1
            return None
        self._last_seq[src] = seq
        self.stats["rx"] += 1
        return FabricMessage(src=src, seq=seq, ts=ts, kind=kind, body=body)

    def poll(self, max_msgs: int = 256) -> list[FabricMessage]:
        out: list[FabricMessage] = []
        while len(out) < max_msgs:
            try:
                raw, _peer = self._sock.recvfrom(MAX_DATAGRAM)
            except BlockingIOError:
                break
            except OSError:
                break
            msg = self._verify(raw)
            if msg is not None:
                # learned peer addressing: the datagram passed the PSK
                # signature + replay floor, so its source address is the
                # authenticated peer's current binding — record it so a
                # joiner we have never been told about (bng cluster run
                # --join from another box) can be answered
                self.peers[msg.src] = (_peer[0], int(_peer[1]))
                out.append(msg)
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# deterministic sim
# ---------------------------------------------------------------------------

class _SimEndpoint:
    """One node's view of the hub — the UDPTransport surface minus
    sockets. Peer addressing is by node id (the hub IS the network), so
    `add_peer` just records reachability intent for `send`'s fan-out
    callers."""

    def __init__(self, hub: "SimTransport", node_id: str):
        self.hub = hub
        self.node_id = node_id
        self.peers: dict[str, str] = {}
        self.stats = {"tx": 0, "tx_errors": 0, "rx": 0, "rx_dropped": 0,
                      "rx_cut": 0}

    @property
    def addr(self) -> tuple:
        return ("sim", 0)

    def add_peer(self, node_id: str, addr: tuple = ("sim", 0)) -> None:
        self.peers[node_id] = node_id

    def reset_peer(self, node_id: str) -> None:
        pass  # the hub has no replay floor (surface parity with UDP)

    def send(self, dst: str, kind: str, body: dict) -> bool:
        return self.hub._send(self, dst, kind, body)

    def poll(self, max_msgs: int = 256) -> list:
        return self.hub._poll(self, max_msgs)

    def close(self) -> None:
        pass


class SimTransport:
    """Deterministic in-memory datagram hub with per-link faults.

    Fault knobs are keyed per DIRECTED link `(src, dst)`:
      - `set_drop(a, b, p)` — seeded-RNG drop probability,
      - `set_delay(a, b, s)` — delivery latency (messages surface from
        `poll` only once the clock passes send+delay),
      - `partition(a, b)` — sever a↔b (both directions) while every
        other link stays up: the *partial* partition shape
        (`partition_oneway` cuts a single direction for asymmetric
        splits).

    All ordering is (deliver_at, send order): two runs with the same
    seed and clock produce byte-identical delivery sequences.
    """

    def __init__(self, clock: Callable[[], float], seed: int = 0):
        self.clock = clock
        self._rng = random.Random(seed)
        self._queues: dict[str, list] = {}
        self._endpoints: dict[str, _SimEndpoint] = {}
        self._order = 0
        self._drop: dict[tuple, float] = {}
        self._delay: dict[tuple, float] = {}
        self._cut: set[tuple] = set()
        self._seq: dict[str, int] = {}
        self.stats = {"sent": 0, "delivered": 0, "dropped": 0, "cut": 0}

    def endpoint(self, node_id: str) -> _SimEndpoint:
        ep = self._endpoints.get(node_id)
        if ep is None:
            ep = self._endpoints[node_id] = _SimEndpoint(self, node_id)
            self._queues[node_id] = []
        return ep

    # -- fault knobs ------------------------------------------------------
    def set_drop(self, a: str, b: str, p: float) -> None:
        """Drop probability on BOTH directions of link a↔b."""
        self._drop[(a, b)] = p
        self._drop[(b, a)] = p

    def set_delay(self, a: str, b: str, delay_s: float) -> None:
        self._delay[(a, b)] = delay_s
        self._delay[(b, a)] = delay_s

    def partition(self, a: str, b: str) -> None:
        """Sever exactly the a↔b link; a and b keep every other link —
        the partial-partition (NEAT) shape."""
        self._cut.add((a, b))
        self._cut.add((b, a))

    def partition_oneway(self, a: str, b: str) -> None:
        self._cut.add((a, b))

    def heal(self, a: str, b: str) -> None:
        self._cut.discard((a, b))
        self._cut.discard((b, a))

    def heal_all(self) -> None:
        self._cut.clear()

    # -- datagram path ----------------------------------------------------
    def _send(self, ep: _SimEndpoint, dst: str, kind: str,
              body: dict) -> bool:
        if dst not in self._queues:
            ep.stats["tx_errors"] += 1
            return False
        link = (ep.node_id, dst)
        self.stats["sent"] += 1
        ep.stats["tx"] += 1
        if link in self._cut:
            self.stats["cut"] += 1
            return True  # datagram semantics: the sender never learns
        p = self._drop.get(link, 0.0)
        if p > 0.0 and self._rng.random() < p:
            self.stats["dropped"] += 1
            return True
        self._seq[ep.node_id] = self._seq.get(ep.node_id, 0) + 1
        self._order += 1
        deliver_at = float(self.clock()) + self._delay.get(link, 0.0)
        msg = FabricMessage(src=ep.node_id, seq=self._seq[ep.node_id],
                            ts=float(self.clock()), kind=kind,
                            body=dict(body))
        self._queues[dst].append((deliver_at, self._order, msg))
        return True

    def _poll(self, ep: _SimEndpoint, max_msgs: int) -> list:
        now = float(self.clock())
        q = self._queues[ep.node_id]
        due = [item for item in q if item[0] <= now]
        if not due:
            return []
        due.sort(key=lambda t: (t[0], t[1]))
        due = due[:max_msgs]
        taken = set(id(item) for item in due)
        self._queues[ep.node_id] = [item for item in q
                                    if id(item) not in taken]
        out = [msg for _at, _o, msg in due]
        ep.stats["rx"] += len(out)
        self.stats["delivered"] += len(out)
        return out
