"""Cluster control fabric (ISSUE 19): the real transport under the
cluster's membership — authenticated UDP datagrams for production, a
deterministic `SimTransport` for tests and chaos, and a partition-aware
failure detector that understands the two failure shapes PAPERS.md
warns about: *partial* partitions (Alquraan et al., OSDI'18 NEAT) and
*gray* members that answer heartbeats but cannot serve (Huang et al.,
HotOS'17).
"""

from .membership import (PEER_DOWN, PEER_GRAY, PEER_SUSPECT, PEER_UP,
                         FailureDetector, PeerView)
from .transport import FabricMessage, SimTransport, UDPTransport

__all__ = [
    "FabricMessage", "UDPTransport", "SimTransport",
    "FailureDetector", "PeerView",
    "PEER_UP", "PEER_SUSPECT", "PEER_GRAY", "PEER_DOWN",
]
