"""Partition-aware membership: heartbeats, suspicion, gray detection.

The detector runs the same on the UDP fabric and the SimTransport.
Three failure shapes, three rules:

- **Dead** (crash / SIGKILL / full partition): beats stop. Local
  suspicion is the missed-beat count (`(now - last_seen) /
  beat_interval`); at `suspicion_threshold` the peer becomes *suspect*.
  A suspect is only demoted to *down* when a **quorum of observers
  accuses it** — each beat piggybacks the sender's own suspect set, so
  accusations travel on the beats themselves, no extra protocol.

- **Partial partition** (NEAT, Alquraan OSDI'18): A↔B dead while both
  reach C. A accuses B and B accuses A, but C accuses neither — no
  quorum forms on either side, nobody is demoted, and the carve plan
  never double-assigns a block across the split. In the coordinator's
  star topology (process members beat to the parent) the parent is the
  sole observer and passes `quorum=1`: there is no second vantage
  point, so local suspicion decides — exactly the pipe-oracle semantics
  it replaces.

- **Gray member** (Huang HotOS'17): beats keep arriving but the
  serving-health word stalls. Each beat carries two cumulative
  counters: `work` (batches accepted) and `served` (replies produced).
  If `work` advances across `gray_beats` consecutive beats while
  `served` does not, the member is wedged-in-serving — verdict *gray*.
  Gray needs no quorum: the evidence is the member's own signed beat,
  not an absence that a partition could explain.

Verdicts feed the coordinator through its existing HealthMonitor /
FailoverController machinery: `probe(peer)` returns False for gray and
down members, so a gray member is demoted exactly like a dead one.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

PEER_UP = "up"
PEER_SUSPECT = "suspect"
PEER_GRAY = "gray"
PEER_DOWN = "down"


@dataclass
class PeerView:
    """What this detector knows about one watched peer."""

    last_seen: float = 0.0
    beats_rx: int = 0
    served: int = -1
    work: int = -1
    stalled_beats: int = 0
    accused_by: set = field(default_factory=set)
    state: str = PEER_UP
    # suspect episodes that healed (beats resumed before any demotion):
    # the observable signature of a transient link partition
    partitions_observed: int = 0


class FailureDetector:
    """Heartbeat + suspicion failure detector over a fabric endpoint.

    `beat()` sends this node's serving-health word (and its current
    suspect set) to every peer on the endpoint; `tick(now)` drains the
    endpoint and advances every watched peer's state machine, returning
    the verdict transitions that happened this tick.
    """

    def __init__(self, node_id: str, endpoint, *,
                 clock: Callable[[], float] = time.time,
                 beat_interval_s: float = 0.5,
                 suspicion_threshold: int = 3,
                 gray_beats: int = 4,
                 startup_grace_s: float = 30.0,
                 quorum: int | None = None,
                 on_verdict: Callable[[str, str], None] | None = None,
                 on_message: Callable[[object], None] | None = None):
        self.node_id = node_id
        self.endpoint = endpoint
        self.clock = clock
        self.beat_interval_s = beat_interval_s
        self.suspicion_threshold = suspicion_threshold
        self.gray_beats = gray_beats
        self.startup_grace_s = startup_grace_s
        self._quorum = quorum
        self.on_verdict = on_verdict
        self.on_message = on_message
        self.views: dict[str, PeerView] = {}
        self.beats_tx = 0
        self.beats_rx = 0
        self.verdicts = {PEER_SUSPECT: 0, PEER_GRAY: 0, PEER_DOWN: 0}

    # -- membership of the watch set --------------------------------------
    def watch(self, peer_id: str, now: float | None = None) -> None:
        """Start watching a peer; the grace clock starts NOW (a freshly
        built member must get a full suspicion window before its first
        beat is due, or every join reads as a failure)."""
        v = self.views.get(peer_id)
        if v is None:
            v = self.views[peer_id] = PeerView()
        v.last_seen = float(now if now is not None else self.clock())

    def forget(self, peer_id: str) -> None:
        self.views.pop(peer_id, None)

    def reset(self, peer_id: str, now: float | None = None) -> None:
        """Wipe a peer's history (standby promotion: the slot is a new
        process with fresh counters)."""
        self.views[peer_id] = PeerView()
        self.watch(peer_id, now)

    def quorum_for(self, peer_id: str) -> int:
        """Observers of X = this node plus every other watched peer.
        Majority of them must accuse X before a down verdict — unless
        an explicit quorum was configured (the coordinator star passes
        1: it is the only observer)."""
        if self._quorum is not None:
            return self._quorum
        observers = 1 + sum(1 for p in self.views if p != peer_id)
        return observers // 2 + 1

    # -- sending ----------------------------------------------------------
    def suspects(self) -> list:
        return sorted(p for p, v in self.views.items()
                      if v.state in (PEER_SUSPECT, PEER_DOWN))

    def beat(self, served: int = 0, work: int = 0, backlog: bool = False,
             now: float | None = None) -> int:
        """One heartbeat to every peer: the serving-health word plus
        this node's accusation set. Returns peers reached."""
        del now  # the endpoint stamps ts from its own clock
        body = {"served": int(served), "work": int(work),
                "backlog": bool(backlog), "accuse": self.suspects()}
        sent = 0
        for peer in sorted(self.endpoint.peers):
            if self.endpoint.send(peer, "beat", body):
                sent += 1
        self.beats_tx += sent
        return sent

    # -- receiving + the state machine ------------------------------------
    def _absorb_beat(self, msg) -> None:
        v = self.views.get(msg.src)
        if v is not None:
            self.beats_rx += 1
            v.beats_rx += 1
            v.last_seen = float(self.clock())
            served = int(msg.body.get("served", 0))
            work = int(msg.body.get("work", 0))
            if v.work >= 0 and work > v.work and served <= v.served:
                # input advanced, output did not: the gray signature
                v.stalled_beats += 1
            elif served > v.served:
                v.stalled_beats = 0
            v.served = max(v.served, served)
            v.work = max(v.work, work)
        # accusations refresh with every beat: a peer that stops
        # accusing X (its link healed) withdraws its vote
        accused = set(msg.body.get("accuse", ()) or ())
        for target, tv in self.views.items():
            if target == msg.src:
                continue
            if target in accused:
                tv.accused_by.add(msg.src)
            else:
                tv.accused_by.discard(msg.src)

    def suspicion(self, peer_id: str, now: float | None = None) -> int:
        """Missed-beat count for a peer (0 = fresh)."""
        v = self.views.get(peer_id)
        if v is None:
            return 0
        now = float(now if now is not None else self.clock())
        return max(0, int((now - v.last_seen) / self.beat_interval_s))

    def tick(self, now: float | None = None) -> list:
        """Drain the endpoint, advance every watched peer's state.
        Returns [(peer_id, new_state)] for transitions this tick."""
        now = float(now if now is not None else self.clock())
        for msg in self.endpoint.poll():
            if msg.kind == "beat":
                self._absorb_beat(msg)
            elif self.on_message is not None:
                self.on_message(msg)
        out = []
        for peer in sorted(self.views):
            v = self.views[peer]
            if v.state == PEER_DOWN:
                continue  # terminal until reset()
            new = v.state
            missed = int((now - v.last_seen) / self.beat_interval_s)
            # a peer that has NEVER beaten gets the startup grace
            # instead of the missed-beat window: a spawning process
            # member needs seconds to import before its first beat,
            # and suspecting it mid-start flaps the failover machinery
            if v.beats_rx == 0 and (now - v.last_seen) < self.startup_grace_s:
                continue
            if v.stalled_beats >= self.gray_beats:
                new = PEER_GRAY
            elif missed >= self.suspicion_threshold:
                v.accused_by.add(self.node_id)
                new = (PEER_DOWN
                       if len(v.accused_by) >= self.quorum_for(peer)
                       else PEER_SUSPECT)
            elif v.state in (PEER_SUSPECT, PEER_UP):
                v.accused_by.discard(self.node_id)
                if v.state == PEER_SUSPECT:
                    v.partitions_observed += 1  # healed: beats resumed
                new = PEER_UP
            if new != v.state:
                v.state = new
                if new in self.verdicts:
                    self.verdicts[new] += 1
                out.append((peer, new))
                if self.on_verdict is not None:
                    self.on_verdict(peer, new)
        return out

    # -- the probe the coordinator's HealthMonitor consumes ---------------
    def probe(self, peer_id: str) -> bool:
        """False once the fabric has demoted the peer (gray or down) —
        the HealthMonitor failure-threshold machinery owns what happens
        next, same as the pipe-flag oracle it replaces."""
        v = self.views.get(peer_id)
        return v is None or v.state not in (PEER_GRAY, PEER_DOWN)

    # -- introspection (collect_fabric scrape source) ---------------------
    def status(self) -> dict:
        return {
            "node_id": self.node_id,
            "beats_tx": self.beats_tx,
            "beats_rx": self.beats_rx,
            "verdicts": dict(self.verdicts),
            "partitions_observed": sum(v.partitions_observed
                                       for v in self.views.values()),
            "peers": {p: {"state": v.state, "beats_rx": v.beats_rx,
                          "stalled_beats": v.stalled_beats,
                          "accused_by": sorted(v.accused_by),
                          "served": v.served, "work": v.work}
                      for p, v in sorted(self.views.items())},
        }
