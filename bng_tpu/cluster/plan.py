"""Cluster carve plan: disjoint address ownership across N instances.

The cluster address space is split into equal power-of-two blocks (each
block is a well-formed `Pool` network). The plan assigns whole blocks to
instances and keeps unassigned blocks on a free list — a block is always
owned by exactly one instance or free, never split and never shared.
Re-carving on join/leave follows the `SlowPathFleet.resize` transfer
discipline one level up: a leaving instance's blocks return to the free
list only after its leases drained, and a member's blocks never move
while it stays a member (never-half-allocate).

NAT public ranges ride on the same block index: block `i` of the space
implies NAT slice `i` of the NAT range, so NAT disjointness is inherited
from block disjointness instead of being tracked separately.

Steering uses the same FNV-1a32 family as `fleet.shard_for_mac` — one
placement function across worker sharding, device sharding and the
cluster front door. `steer_macs_u48` is the bit-exact vectorized form
for storm-scale steering (millions of MACs in one numpy pass).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from bng_tpu.utils.net import FNV1A32_OFFSET, FNV1A32_PRIME, fnv1a32

_U32 = 0xFFFFFFFF


# ---------------------------------------------------------------------------
# steering
# ---------------------------------------------------------------------------

def instance_for_mac(mac: bytes, member_ids: tuple) -> str:
    """Steer a subscriber MAC to a member instance id. `member_ids`
    MUST be sorted — every caller (coordinator, storm, audit) sorts the
    same way, so placement is a pure function of (mac, membership)."""
    if not member_ids:
        raise ValueError("no cluster members to steer to")
    return member_ids[fnv1a32(mac[:6]) % len(member_ids)]


def steer_macs_u48(mac_u48, n: int):
    """Vectorized FNV-1a32 over big-endian 6-byte MACs packed as u48
    ints -> member index array. Bit-exact vs `fnv1a32(mac[:6]) % n`
    (pinned by tests on a seeded sample)."""
    import numpy as np

    if n <= 0:
        raise ValueError("n must be positive")
    m = np.asarray(mac_u48, dtype=np.uint64)
    h = np.full(m.shape, FNV1A32_OFFSET, dtype=np.uint32)
    prime = np.uint32(FNV1A32_PRIME)
    with np.errstate(over="ignore"):
        for shift in (40, 32, 24, 16, 8, 0):
            h = (h ^ ((m >> np.uint64(shift)) & np.uint64(0xFF)).astype(
                np.uint32)) * prime
    return (h % np.uint32(n)).astype(np.int64)


# ---------------------------------------------------------------------------
# plan structures
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CarvedBlock:
    """One power-of-two slice of the cluster space. `index` is the
    block's position in the split (ties the NAT slice to it); `pool_id`
    is stable for the block's lifetime so a Pool built from it keeps
    its identity across instances."""

    network: int
    prefix_len: int
    index: int

    @property
    def pool_id(self) -> int:
        return self.index + 1  # pool ids are 1-based everywhere else

    @property
    def size(self) -> int:
        return 1 << (32 - self.prefix_len)

    @property
    def last(self) -> int:
        return self.network + self.size - 1

    def contains(self, ip: int) -> bool:
        return self.network <= ip <= self.last

    def to_dict(self) -> dict:
        return {"network": self.network, "prefix_len": self.prefix_len,
                "index": self.index}

    @classmethod
    def from_dict(cls, d: dict) -> "CarvedBlock":
        return cls(network=int(d["network"]), prefix_len=int(d["prefix_len"]),
                   index=int(d["index"]))


@dataclass
class InstancePlan:
    """One instance's carve: whole blocks plus the NAT slices they
    imply. `host` is the placement axis the fabric added: which machine
    the instance runs on ("" = unplaced, the single-host legacy)."""

    instance_id: str
    blocks: list = field(default_factory=list)  # list[CarvedBlock]
    host: str = ""

    def addresses(self) -> int:
        return sum(b.size for b in self.blocks)

    def contains(self, ip: int) -> bool:
        return any(b.contains(ip) for b in self.blocks)

    def to_dict(self) -> dict:
        return {"instance_id": self.instance_id,
                "blocks": [b.to_dict() for b in self.blocks],
                "host": self.host}

    @classmethod
    def from_dict(cls, d: dict) -> "InstancePlan":
        return cls(instance_id=d["instance_id"],
                   blocks=[CarvedBlock.from_dict(b) for b in d["blocks"]],
                   host=str(d.get("host", "")))


@dataclass
class ClusterPlan:
    """The carve authority: which instance owns which blocks.

    `epoch` increments on every assignment change — instances compare it
    to decide whether to re-apply, and checkpoints carry it so a
    restarted coordinator resumes from the same carve.
    """

    space_network: int
    space_prefix_len: int
    block_prefix_len: int
    nat_base: int = 0
    nat_total: int = 0
    epoch: int = 0
    members: dict = field(default_factory=dict)  # id -> InstancePlan
    free: list = field(default_factory=list)     # list[CarvedBlock]

    # -- derived ----------------------------------------------------------
    @property
    def n_blocks(self) -> int:
        return 1 << (self.block_prefix_len - self.space_prefix_len)

    def member_ids(self) -> tuple:
        return tuple(sorted(self.members))

    def serving_ids(self) -> tuple:
        """Members that own blocks — the steering set. A joiner waiting
        on free blocks is a member but not yet a steering target (it
        has no addresses to answer with)."""
        return tuple(sorted(i for i, p in self.members.items() if p.blocks))

    def hosts(self) -> dict:
        return {i: p.host for i, p in sorted(self.members.items())}

    @property
    def n_hosts(self) -> int:
        """Distinct placement hosts in the carve ("" counts as one host:
        the unplaced single-machine legacy)."""
        return max(1, len({p.host for p in self.members.values()}))

    def total_addresses(self) -> int:
        return sum(p.addresses() for p in self.members.values())

    def nat_range(self, block: CarvedBlock) -> tuple[int, int]:
        """(start_ip, count) NAT slice implied by a block's index."""
        if self.nat_total <= 0:
            return (0, 0)
        per = self.nat_total // self.n_blocks
        return (self.nat_base + block.index * per, per)

    def owner_of(self, ip: int) -> str | None:
        for iid, p in self.members.items():
            if p.contains(ip):
                return iid
        return None

    # -- serialization (checkpoint / nexus payload) -----------------------
    def to_dict(self) -> dict:
        return {
            "space_network": self.space_network,
            "space_prefix_len": self.space_prefix_len,
            "block_prefix_len": self.block_prefix_len,
            "nat_base": self.nat_base,
            "nat_total": self.nat_total,
            "epoch": self.epoch,
            "members": {k: v.to_dict()
                        for k, v in sorted(self.members.items())},
            "free": [b.to_dict() for b in self.free],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterPlan":
        return cls(
            space_network=int(d["space_network"]),
            space_prefix_len=int(d["space_prefix_len"]),
            block_prefix_len=int(d["block_prefix_len"]),
            nat_base=int(d.get("nat_base", 0)),
            nat_total=int(d.get("nat_total", 0)),
            epoch=int(d["epoch"]),
            members={k: InstancePlan.from_dict(v)
                     for k, v in d["members"].items()},
            free=[CarvedBlock.from_dict(b) for b in d["free"]],
        )


# ---------------------------------------------------------------------------
# carving
# ---------------------------------------------------------------------------

def _split_blocks(space_network: int, space_prefix_len: int,
                  block_prefix_len: int) -> list[CarvedBlock]:
    n = 1 << (block_prefix_len - space_prefix_len)
    size = 1 << (32 - block_prefix_len)
    return [CarvedBlock(network=space_network + i * size,
                        prefix_len=block_prefix_len, index=i)
            for i in range(n)]


def default_block_prefix(space_prefix_len: int, n_members: int) -> int:
    """Smallest power-of-two block count that covers the membership
    (minimum 4 blocks so a small cluster still has free blocks to grow
    into)."""
    want = max(4, n_members)
    bits = 0
    while (1 << bits) < want:
        bits += 1
    block_prefix = space_prefix_len + bits
    if block_prefix > 30:  # a /31-/32 block cannot hold a usable pool
        raise ValueError(
            f"space /{space_prefix_len} too small for {n_members} members")
    return block_prefix


def _deal_order(ids: list, hosts: dict | None) -> list:
    """Dealing order for round-robin block assignment. Without a host
    map this is plain sorted-id order (the single-host legacy). With
    hosts, consecutive deals alternate across sorted host groups, so an
    N-host cluster spreads each stretch of the space over machines —
    losing one host takes out interleaved blocks, not a contiguous run.
    Deterministic: pure function of (ids, hosts)."""
    if not hosts or not any(hosts.get(i, "") for i in ids):
        return list(ids)
    groups: dict[str, list] = {}
    for i in ids:
        groups.setdefault(hosts.get(i, ""), []).append(i)
    hkeys = sorted(groups)
    order: list = []
    cursors = {h: 0 for h in hkeys}
    while len(order) < len(ids):
        for h in hkeys:
            g = groups[h]
            if cursors[h] < len(g):
                order.append(g[cursors[h]])
                cursors[h] += 1
    return order


def initial_plan(space_network: int, space_prefix_len: int,
                 member_ids: list, *, block_prefix_len: int | None = None,
                 nat_base: int = 0, nat_total: int = 0,
                 hosts: dict | None = None) -> ClusterPlan:
    """Carve the space for the founding membership: blocks dealt
    round-robin in sorted-id order — deterministic, so every elected
    carver computes the identical plan. With a `hosts` map (instance id
    -> host name) the deal interleaves across hosts-of-processes."""
    ids = sorted(member_ids)
    if block_prefix_len is None:
        block_prefix_len = default_block_prefix(space_prefix_len,
                                                max(1, len(ids)))
    blocks = _split_blocks(space_network, space_prefix_len, block_prefix_len)
    hosts = hosts or {}
    plan = ClusterPlan(space_network=space_network,
                       space_prefix_len=space_prefix_len,
                       block_prefix_len=block_prefix_len,
                       nat_base=nat_base, nat_total=nat_total, epoch=1,
                       members={i: InstancePlan(i, host=hosts.get(i, ""))
                                for i in ids},
                       free=[])
    if ids:
        order = _deal_order(ids, hosts)
        for i, b in enumerate(blocks):
            plan.members[order[i % len(order)]].blocks.append(b)
    else:
        plan.free = blocks
    return plan


def replan(plan: ClusterPlan, member_ids: list,
           hosts: dict | None = None) -> ClusterPlan:
    """Re-carve for a new membership. Discipline:

    - a surviving member's blocks NEVER move (never-half-allocate);
    - a departed member's blocks go to the free list — the coordinator
      only calls this after that instance drained, so the transfer is
      whole-block and lease-free;
    - free blocks deal round-robin to members that hold NO blocks yet
      (joiners), interleaved across hosts when a host map is given.
      Members already serving keep exactly their carve — rebalancing an
      occupied block would mean moving live leases, the half-allocate
      this plan exists to forbid. A joiner arriving with nothing free
      stays pending until a leaver returns blocks.

    Returns a NEW plan (epoch+1) when anything changed, else the same
    plan object.
    """
    ids = sorted(member_ids)
    old_ids = plan.member_ids()
    carried = {i: plan.members[i].host for i in ids if i in plan.members}
    hosts = {**carried, **(hosts or {})}

    members = {i: InstancePlan(i, list(plan.members[i].blocks),
                               host=hosts.get(i, ""))
               if i in plan.members else InstancePlan(i,
                                                      host=hosts.get(i, ""))
               for i in ids}
    free = list(plan.free)
    for iid in old_ids:
        if iid not in members:
            free.extend(plan.members[iid].blocks)
    free.sort(key=lambda b: b.index)

    changed = tuple(ids) != old_ids
    changed = changed or any(plan.members[i].host != members[i].host
                             for i in ids if i in plan.members)
    joiners = _deal_order(sorted(i for i in ids if not members[i].blocks),
                          hosts)
    k = 0
    while free and joiners:
        members[joiners[k % len(joiners)]].blocks.append(free.pop(0))
        k += 1
        changed = True

    if not changed:
        return plan
    return ClusterPlan(space_network=plan.space_network,
                       space_prefix_len=plan.space_prefix_len,
                       block_prefix_len=plan.block_prefix_len,
                       nat_base=plan.nat_base, nat_total=plan.nat_total,
                       epoch=plan.epoch + 1, members=members, free=free)


def elect_carver(member_ids) -> str | None:
    """Lowest sorted id carves — the same deterministic election every
    member computes locally from the membership list."""
    ids = sorted(member_ids)
    return ids[0] if ids else None
