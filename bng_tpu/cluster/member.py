"""Remote serving member: the `--join` runtime and its coordinator hook.

PR 19 left a `--join`ed box announce-only: it beat into the carve but
every frame steered its way was shed. This module is the serving half
(ISSUE 20):

- **`MemberRuntime`** runs on the joining box. It announces itself with
  capped exponential backoff (deterministic jitter, loud give-up),
  hydrates its carved blocks from the coordinator's handoff stream
  (`cluster/handoff` — verified checkpoint bytes, never half-hydrated),
  brings up its own `InlineInstance` fleet+engine stack, serves steered
  batches locally, and ships lease/HA deltas back on each reply — the
  `ProcessInstance` pipe discipline re-homed onto the fabric.

- **`RemoteInstance`** is the coordinator-side handle with the
  `InlineInstance` verb surface: `handle_batch` fans frames out as
  signed `rbatch` datagrams, waits (deadline-bounded) for the member's
  replies, and drains the session events that rode back so the
  coordinator's ActiveSyncer/StandbySyncer pair keeps the member's HA
  half on a SURVIVING host — which is exactly what host-loss promotion
  hydrates from.

Steering stays one function: the member re-checks `instance_for_mac`
on every frame it serves and counts `missteers` (must be 0 — the same
placement law end to end).
"""

from __future__ import annotations

import base64
import time
from typing import Callable

from bng_tpu.utils.net import fnv1a32

from .handoff import HandoffManager, parse_handoff_checkpoint
from .instance import InlineInstance, InstanceSpec
from .plan import ClusterPlan, instance_for_mac

# rbatch fan-out: frames per datagram. 8 DHCP frames at ~600 B each
# base64-inflate to ~6.4 KiB — under the transport's MAX_DATAGRAM with
# envelope headroom.
RBATCH_GROUP = 8

JOIN_BACKOFF_BASE_S = 0.5
JOIN_BACKOFF_CAP_S = 8.0
JOIN_DEADLINE_S = 60.0


def _join_delay(node_id: str, attempt: int,
                base_s: float = JOIN_BACKOFF_BASE_S,
                cap_s: float = JOIN_BACKOFF_CAP_S) -> float:
    """Capped exponential backoff with deterministic jitter: the jitter
    is a hash of (node_id, attempt), so a whole rack rejoining after a
    power event de-synchronizes WITHOUT losing replayability (chaos
    runs under a seed must see identical retry timelines)."""
    raw = min(cap_s, base_s * (2 ** min(attempt, 16)))
    frac = (fnv1a32(f"{node_id}/{attempt}".encode()) % 1000) / 1000.0
    return raw * (0.5 + 0.5 * frac)


def _b64(frame) -> str | None:
    return None if frame is None else base64.b64encode(
        bytes(frame)).decode("ascii")


def _unb64(s) -> bytes | None:
    return None if s is None else base64.b64decode(s)


class MemberRuntime:
    """The joining box's loop: join -> hydrate -> serve -> beat.

    Everything is `tick(now)`-driven over an injected transport+clock,
    so the SimTransport chaos lane runs it byte-deterministically and
    the CLI runs the same object over UDP at wall-clock cadence.
    """

    def __init__(self, transport, node_id: str, host: str, *,
                 clock: Callable[[], float] = time.time,
                 beat_interval_s: float = 0.5,
                 join_deadline_s: float = JOIN_DEADLINE_S,
                 join_backoff_base_s: float = JOIN_BACKOFF_BASE_S,
                 join_backoff_cap_s: float = JOIN_BACKOFF_CAP_S,
                 log: Callable[[str], None] | None = None):
        self.transport = transport
        self.node_id = node_id
        self.host = host
        self.clock = clock
        self.beat_interval_s = beat_interval_s
        self.join_deadline_s = join_deadline_s
        self.join_backoff_base_s = join_backoff_base_s
        self.join_backoff_cap_s = join_backoff_cap_s
        self.log = log or (lambda _msg: None)
        self.handoff = HandoffManager(transport, clock=clock,
                                      on_complete=self._on_handoff)
        self.instance: InlineInstance | None = None
        self.plan: ClusterPlan | None = None
        self.state = "joining"  # joining | hydrating | serving | gave_up
        self.join_retries = 0
        self.missteers = 0
        self.batches_served = 0
        self.epoch = 0
        self._started = float(clock())
        self._next_join = float(clock())
        self._next_beat = float(clock())
        self._join_attempt = 0

    # -- fabric loop -------------------------------------------------------
    def tick(self, now: float | None = None) -> None:
        now = float(now if now is not None else self.clock())
        for msg in self.transport.poll():
            if self.handoff.handle(msg):
                if self.state == "joining":
                    self.state = "hydrating"
                continue
            if msg.kind == "rbatch":
                self._serve_rbatch(msg)
            elif msg.kind == "rexpire":
                self._serve_rexpire(msg)
        self.handoff.pump(now)
        if self.state == "joining":
            self._drive_join(now)
        if self.state in ("hydrating", "serving") and now >= self._next_beat:
            served = self.instance.replies if self.instance else 0
            work = self.instance.batches if self.instance else 0
            self.transport.send("coordinator", "beat",
                                {"served": served, "work": work,
                                 "accuse": []})
            self._next_beat = now + self.beat_interval_s

    def _drive_join(self, now: float) -> None:
        if now < self._next_join:
            return
        if now - self._started > self.join_deadline_s:
            self.state = "gave_up"
            self.log(f"cluster join: GIVING UP after "
                     f"{self._join_attempt} attempts over "
                     f"{self.join_deadline_s:.0f}s — coordinator "
                     f"unreachable")
            return
        if self._join_attempt > 0:
            self.join_retries += 1
        self.transport.send("coordinator", "join",
                            {"instance_id": self.node_id,
                             "host": self.host, "serving": True})
        self._join_attempt += 1
        self._next_join = now + _join_delay(
            self.node_id, self._join_attempt,
            self.join_backoff_base_s, self.join_backoff_cap_s)

    # -- hydration (handoff completion) ------------------------------------
    def _on_handoff(self, _src: str, manifest: dict, data: bytes) -> None:
        """A verified carve checkpoint arrived whole: build (or re-plan)
        the serving stack. Corrupt streams never reach here — the
        receiver already rejected them back to re-request."""
        comps = parse_handoff_checkpoint(data)
        plan_doc = comps.get("cluster_plan")
        member = comps.get("member") or {}
        if not plan_doc or member.get("instance_id") != self.node_id:
            return
        self.plan = ClusterPlan.from_dict(plan_doc)
        self.epoch = self.plan.epoch
        iplan = self.plan.members.get(self.node_id)
        if iplan is None or not iplan.blocks:
            return
        spec_kw = dict(member.get("spec") or {})
        spec = InstanceSpec.from_plan(
            iplan, self.plan,
            server_mac=bytes.fromhex(spec_kw.pop("server_mac", "02aabbccdd01")),
            server_ip=int(spec_kw.pop("server_ip", 0)), **spec_kw)
        if self.instance is None:
            self.instance = InlineInstance(spec, clock=self.clock)
        else:
            ok = self.instance.apply_plan(iplan)
            if not ok:
                return  # un-drained shrink: keep serving the old carve
        sessions = member.get("sessions") or []
        if sessions:
            self.instance.hydrate_sessions(
                [_SessionView(s) for s in sessions], now=self.clock())
        self.state = "serving"

    # -- serving verbs -----------------------------------------------------
    def _serve_rbatch(self, msg) -> None:
        if self.instance is None:
            self.transport.send(msg.src, "rbatch_reply", {
                "bid": msg.body.get("bid"), "replies": None,
                "events": [], "error": "not serving"})
            return
        items = [(int(lane), _unb64(fr))
                 for lane, fr in (msg.body.get("items") or ())]
        ids = self.plan.serving_ids() if self.plan else (self.node_id,)
        for _lane, frame in items:
            if frame is not None and len(frame) >= 12 \
                    and instance_for_mac(frame[6:12], ids) != self.node_id:
                self.missteers += 1
        now = msg.body.get("now")
        out = self.instance.handle_batch(items,
                                         float(now) if now is not None
                                         else self.clock())
        self.batches_served += 1
        self.transport.send(msg.src, "rbatch_reply", {
            "bid": msg.body.get("bid"),
            "replies": [[lane, _b64(rep)] for lane, rep in out],
            "events": self.instance.drain_session_events()})

    def _serve_rexpire(self, msg) -> None:
        n = 0
        events: list = []
        if self.instance is not None:
            n = self.instance.expire(int(msg.body.get("now", 0)),
                                     msg.body.get("max_reaps"))
            events = self.instance.drain_session_events()
        self.transport.send(msg.src, "rexpire_reply", {
            "bid": msg.body.get("bid"), "expired": n, "events": events})

    # -- introspection -----------------------------------------------------
    def status(self) -> dict:
        out = {
            "node_id": self.node_id, "host": self.host,
            "state": self.state, "epoch": self.epoch,
            "join_retries": self.join_retries,
            "missteers": self.missteers,
            "batches_served": self.batches_served,
            "handoff": self.handoff.stats(),
        }
        if self.instance is not None:
            out["instance"] = self.instance.status()
        return out

    def close(self) -> None:
        if self.instance is not None:
            self.instance.close()
        self.transport.close()


class _SessionView:
    """Duck-typed SessionState over the handoff's JSON session dicts
    (`InlineInstance.hydrate_sessions` reads attributes)."""

    __slots__ = ("session_id", "mac", "ip", "pool_id", "username",
                 "lease_expiry", "qos_policy")

    def __init__(self, d: dict):
        self.session_id = d.get("session_id", "")
        self.mac = d.get("mac", "")
        self.ip = int(d.get("ip", 0))
        self.pool_id = int(d.get("pool_id", 0))
        self.username = d.get("username", "")
        self.lease_expiry = float(d.get("lease_expiry", 0.0))
        self.qos_policy = d.get("qos_policy", "")


# ---------------------------------------------------------------------------
# coordinator-side handle
# ---------------------------------------------------------------------------

class RemoteInstance:
    """`InlineInstance` verb surface for a member served on another
    host: batches fan out as signed datagram groups, replies + session
    events ride back. The wait is deadline-bounded — a dead remote
    sheds its frames (reply None) instead of wedging the front door;
    the detector demotes it on the beat lane, not here."""

    def __init__(self, transport, instance_id: str, spec: InstanceSpec, *,
                 clock: Callable[[], float] = time.time,
                 pump: Callable[[], None] | None = None,
                 reply_timeout_s: float = 5.0,
                 max_pump_idle: int = 2000):
        self.transport = transport
        self.instance_id = instance_id
        self.spec = spec
        self.clock = clock
        # called while waiting for replies: the coordinator passes its
        # fabric drain (detector tick routes rbatch_reply back here);
        # deterministic tests chain the member's own tick onto it
        self.pump = pump or (lambda: None)
        self.reply_timeout_s = reply_timeout_s
        self.max_pump_idle = max_pump_idle
        self._bid = 0
        self._mail: dict[int, dict] = {}
        self._session_events: list = []
        self.batches = 0
        self.shed_batches = 0
        self.closed = False

    def deliver(self, body: dict) -> None:
        """Coordinator routes `rbatch_reply`/`rexpire_reply` here."""
        bid = body.get("bid")
        if bid is not None:
            self._mail[int(bid)] = body

    def _await(self, bid: int) -> dict | None:
        deadline = float(self.clock()) + self.reply_timeout_s
        idle = 0
        while bid not in self._mail:
            self.pump()
            idle += 1
            if bid in self._mail:
                break
            if float(self.clock()) > deadline or idle > self.max_pump_idle:
                return None
        return self._mail.pop(bid, None)

    def handle_batch(self, items: list, now: float | None = None) -> list:
        self.batches += 1
        groups = [items[i:i + RBATCH_GROUP]
                  for i in range(0, len(items), RBATCH_GROUP)]
        results: list = []
        for group in groups:
            self._bid += 1
            bid = self._bid
            self.transport.send(self.instance_id, "rbatch", {
                "bid": bid, "now": now,
                "items": [[lane, _b64(frame)] for lane, frame in group]})
            reply = self._await(bid)
            if reply is None or reply.get("replies") is None:
                self.shed_batches += 1
                results.extend((lane, None) for lane, _f in group)
                continue
            results.extend((int(lane), _unb64(rep))
                           for lane, rep in reply["replies"])
            self._session_events.extend(
                tuple(ev) for ev in reply.get("events", ()))
        return results

    def expire(self, now: int, max_reaps: int | None = None) -> int:
        self._bid += 1
        bid = self._bid
        self.transport.send(self.instance_id, "rexpire",
                            {"bid": bid, "now": int(now),
                             "max_reaps": max_reaps})
        reply = self._await(bid)
        if reply is None:
            return 0
        self._session_events.extend(
            tuple(ev) for ev in reply.get("events", ()))
        return int(reply.get("expired", 0))

    def drain_session_events(self) -> list:
        out, self._session_events = self._session_events, []
        return out

    def session_states(self, events: list, now: float) -> list:
        return InlineInstance.session_states(self, events, now)

    def status(self) -> dict:
        return {"instance_id": self.instance_id, "remote_serving": True,
                "blocks": list(self.spec.blocks),
                "batches": self.batches,
                "shed_batches": self.shed_batches}

    def export_state(self) -> dict:
        return {}

    def lease_count(self) -> int:
        # the authoritative books live on the remote box; the HA store
        # mirrors them, which is what removal/drain decisions consult
        return 0

    def close(self) -> None:
        self.closed = True
