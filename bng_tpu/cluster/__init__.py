"""Cluster-of-BNGs: compose N instances into one audited, failover-
capable system (membership + carve plan + HA pairing + one front door).
"""

from .coordinator import ClusterCoordinator, InstanceEntity
from .instance import InlineInstance, InstanceSpec, ProcessInstance
from .plan import (CarvedBlock, ClusterPlan, InstancePlan, elect_carver,
                   initial_plan, instance_for_mac, replan, steer_macs_u48)

__all__ = [
    "CarvedBlock", "ClusterCoordinator", "ClusterPlan", "InlineInstance",
    "InstanceEntity", "InstancePlan", "InstanceSpec", "ProcessInstance",
    "elect_carver", "initial_plan", "instance_for_mac", "replan",
    "steer_macs_u48",
]
