"""Cluster-of-BNGs: compose N instances into one audited, failover-
capable system (membership + carve plan + HA pairing + one front door).
"""

from .coordinator import ClusterCoordinator, InstanceEntity
from .handoff import HandoffManager, StateReceiver, StateSender
from .instance import InlineInstance, InstanceSpec, ProcessInstance
from .member import MemberRuntime, RemoteInstance
from .plan import (CarvedBlock, ClusterPlan, InstancePlan, elect_carver,
                   initial_plan, instance_for_mac, replan, steer_macs_u48)

__all__ = [
    "CarvedBlock", "ClusterCoordinator", "ClusterPlan", "HandoffManager",
    "InlineInstance", "InstanceEntity", "InstancePlan", "InstanceSpec",
    "MemberRuntime", "ProcessInstance", "RemoteInstance", "StateReceiver",
    "StateSender", "elect_carver", "initial_plan", "instance_for_mac",
    "replan", "steer_macs_u48",
]
