"""Prototype: device_lookup with 1D flattened probe gathers vs current."""
import time
import numpy as np
import jax
import jax.numpy as jnp

from bng_tpu.ops.hashing import SEED1, SEED2, hash_words
from bng_tpu.ops.table import TableState, device_lookup

B = 8192
nbuckets, stash = 8192, 64
S = nbuckets * 4 + stash
WAYS = 4
rng = np.random.default_rng(0)
table = TableState(
    keys=jnp.asarray(rng.integers(0, 2**32, size=(S, 1), dtype=np.uint64).astype(np.uint32)),
    vals=jnp.asarray(rng.integers(0, 2**32, size=(S, 8), dtype=np.uint64).astype(np.uint32)),
    used=jnp.ones((S,), jnp.uint32))
ips = jnp.asarray(rng.integers(0, 2**32, size=B, dtype=np.uint64).astype(np.uint32))
active = jnp.ones((B,), dtype=bool)
now_us = jnp.uint32(1)


def lookup_1d(state, query, nbuckets, stash):
    """Probe gathers flattened to 1D (fast path on TPU tiled layouts)."""
    B, K = query.shape
    words = [query[:, k] for k in range(K)]
    mask = np.uint32(nbuckets - 1)
    b1 = hash_words(words, SEED1) & mask
    b2 = hash_words(words, SEED2) & mask

    used_1d = state.used
    key_cols = [state.keys[:, k] for k in range(K)]  # K arrays of [S]

    def probe(b):
        # [B, WAYS] slot indices, but gather each way as a 1D gather
        base = (b * WAYS).astype(jnp.int32)
        ms, ss = [], []
        for w in range(WAYS):
            s = base + w
            u = used_1d[s]
            eq = u != 0
            for k in range(K):
                eq = eq & (key_cols[k][s] == words[k])
            ms.append(eq)
            ss.append(s)
        return ss, ms

    s1, m1 = probe(b1)
    s2, m2 = probe(b2)
    cand_slots = jnp.stack(s1 + s2, axis=1)  # [B, 2W]
    cand_match = jnp.stack(m1 + m2, axis=1)

    if stash > 0:
        base = nbuckets * WAYS
        stash_keys = jax.lax.dynamic_slice_in_dim(state.keys, base, stash, axis=0)
        stash_used = jax.lax.dynamic_slice_in_dim(state.used, base, stash, axis=0)
        sm = jnp.all(stash_keys[None, :, :] == query[:, None, :], axis=-1) & (
            stash_used[None, :] != 0)
        s_slots = jnp.broadcast_to(base + jnp.arange(stash, dtype=jnp.int32)[None, :], sm.shape)
        cand_slots = jnp.concatenate([cand_slots, s_slots], axis=1)
        cand_match = jnp.concatenate([cand_match, sm], axis=1)

    found = jnp.any(cand_match, axis=1)
    first = jnp.argmax(cand_match, axis=1)
    slot = jnp.take_along_axis(cand_slots, first[:, None], axis=1)[:, 0].astype(jnp.int32)
    vals = jnp.where(found[:, None], state.vals[slot], 0)
    return found, slot, vals


def refill(found, vals):
    rate_lo = vals[:, 0]; rate_hi = vals[:, 1]
    limited = found & active & ((rate_lo | rate_hi) != 0)
    burst = vals[:, 2]; tokens = vals[:, 3]; last = vals[:, 4]
    elapsed = (now_us - last).astype(jnp.float32)
    rate_bps = rate_lo.astype(jnp.float32) + rate_hi.astype(jnp.float32) * jnp.float32(2.0**32)
    avail = jnp.minimum(tokens.astype(jnp.float32) + elapsed * (rate_bps / 8.0) * 1e-6,
                        burst.astype(jnp.float32))
    return limited, avail


@jax.jit
def v_old(table, q):
    res = device_lookup(table, q[:, None], nbuckets, stash)
    return refill(res.found, res.vals)


@jax.jit
def v_1d(table, q):
    found, slot, vals = lookup_1d(table, q[:, None], nbuckets, stash)
    return refill(found, vals)


# correctness against each other
o1 = jax.block_until_ready(v_old(table, ips))
o2 = jax.block_until_ready(v_1d(table, ips))
assert np.array_equal(np.asarray(o1[0]), np.asarray(o2[0]))
assert np.allclose(np.asarray(o1[1]), np.asarray(o2[1]))
print("outputs match")
time.sleep(3)

for rnd in range(3):
    for name, fn in (("old", v_old), ("1d", v_1d)):
        t0 = time.perf_counter()
        outs = [fn(table, ips) for _ in range(50)]
        jax.block_until_ready(outs)
        print(f"r{rnd} {name:4s} {(time.perf_counter()-t0)/50*1e6:9.1f} us", flush=True)
    time.sleep(1)

# blocked-each for the 1d variant (check poll-bucket artifact gone)
lat = []
for _ in range(30):
    t0 = time.perf_counter()
    o = v_1d(table, ips)
    jax.block_until_ready(o)
    lat.append((time.perf_counter() - t0) * 1e6)
print(f"1d blocked-each p50: {np.percentile(lat, 50):.1f} us")
