#!/bin/bash
# One-window TPU validation (PERF_NOTES §3.6, VERDICT r3 item 1).
#
# Runs everything the round needs while the axon tunnel is up, each step in
# its own process (a stuck client can wedge the relay — PERF_NOTES §3.5), with
# a health probe between steps so a mid-window outage aborts cleanly instead
# of hanging the remaining steps.  Results land in bench_runs.jsonl via
# bench.py's _persist; the transcript goes to $LOG.
#
# Step order = information value per VERDICT r3: the lowering gate first
# (cheap, gates everything), then config 3 (way-granular QoS — the round's
# load-bearing unknown), config 2 (NAT44 regression check vs the 33.2 Mpps
# r3 number), config 6 (DHCP standalone @1M subs), config 4 (never yet
# completed on TPU), config 5 (sharded, n=1 geometry on the single chip),
# then the headline fused pipeline at 1M subscribers.
set -u -o pipefail
cd "$(dirname "$0")"
LOG=${TPU_RUN_LOG:-/tmp/tpu_validation.log}
LOCK=/tmp/tpu_run.lock
if ! mkdir "$LOCK" 2>/dev/null; then
  echo "another tpu_run.sh holds $LOCK; exiting" | tee -a "$LOG"; exit 2
fi
trap 'rmdir "$LOCK" 2>/dev/null' EXIT
FAILED=0

# Chip is known up inside the window: no capture-on-return probing per step.
export BNG_BENCH_PROBE_WINDOW=0 BNG_BENCH_PROBE_TIMEOUT=60 BNG_BENCH_PROBE_TRIES=1

probe() {
  timeout 75 python -c "import jax, jax.numpy as j; (j.ones((8,8))@j.ones((8,8))).block_until_ready()" >/dev/null 2>&1
}
step() {
  echo "=== $1 ($(date -u +%H:%M:%S))" | tee -a "$LOG"
  if ! BNG_BENCH_TIMEOUT=$2 timeout $(($2 + 60)) bash -c "$3" 2>&1 \
      | grep -v WARNING | tail -12 | tee -a "$LOG"; then
    # a failed step taints the window (no done-marker) but the remaining
    # steps still run — partial hardware numbers beat none
    echo "STEP FAILED: $1" | tee -a "$LOG"; FAILED=1
  fi
  probe || { echo "TUNNEL DEAD after $1 ($(date -u +%H:%M:%S))" | tee -a "$LOG"; exit 1; }
}

probe || { echo "tunnel down at start ($(date -u +%H:%M:%S))" | tee -a "$LOG"; exit 1; }
echo "=== window open $(date -u +%H:%M:%S)" | tee -a "$LOG"
step "lowering-gate" 600  "python bench.py --verify-lowering"
step "config3-qos"   900  "python bench.py --config 3"
step "config2-nat"   900  "python bench.py --config 2"
step "config6-dhcp"  900  "python bench.py --config 6"
step "config4-pppoe" 900  "python bench.py --config 4"
step "config5-shard" 900  "python bench.py --config 5"
# reference NAT capacity (bpf/nat44.c:38-40): 4M sessions / 2M EIM
# endpoints — VERDICT r4 item 8's no-throughput-cliff check vs the 100k
# config-2 number. Build alone is ~75s host-side; budget accordingly.
step "config2-4M"    1500 "BNG_BENCH_FLOWS=4000000 BNG_BENCH_EIM_SHARE=2 python bench.py --config 2"
# Pallas-vs-XLA table-probe A/B (ISSUE 11): the same configs under both
# impls, impl-keyed ledger cohorts (never silently compared, rc=3 gate),
# then the stage-driven autotune sweep. The headline runs IMPL=auto so
# the unattended round self-times both and ships the winner — the bench
# line records the choice. Taint-marker semantics unchanged: a failed
# step marks FAILED, the window keeps going.
step "config3-ab-pallas" 900 "BNG_TABLE_IMPL=pallas python bench.py --config 3"
step "config6-ab-pallas" 900 "BNG_TABLE_IMPL=pallas python bench.py --config 6"
# AOT express OFFER A/B (ISSUE 13): jit full-program vs AOT minimal-
# program express on hardware — both offer_device_only_p99_us cohorts
# land in the ledger under distinct express_path identities (the gate
# refuses a cross-architecture trend with rc=3), and the 50us verdict
# is finally measured against the architecture built to pass it.
step "express-ab"    1200 "python bench.py --express-ab"
step "express-ab-pallas" 1200 "BNG_TABLE_IMPL=pallas python bench.py --express-ab"

# Device-resident serving loop (ISSUE 18): --express-ab is three-way
# (aot / devloop / jit) with the ring at the default k=8 above; sweep
# the remaining k points so PERF_NOTES §20's CPU k-curve gets its
# on-chip twin. Every line lands in its own express_loop=devloop
# ledger cohort (the gate refuses cross-loop trends with rc=3).
step "devloop-k1"    900  "BNG_DEVLOOP_K=1 python bench.py --express-ab"
step "devloop-k4"    900  "BNG_DEVLOOP_K=4 python bench.py --express-ab"
step "devloop-k16"   900  "BNG_DEVLOOP_K=16 python bench.py --express-ab"

# Host serving-loop A/B (ISSUE 14): scalar per-frame vs vectorized
# batch-native host path feeding real chips — both summed-host-stage
# cohorts land under distinct host_path identities, and the recorded
# host_mpps_ceiling is the number every future on-chip headline is
# bounded by (the device can't outrun the host that feeds it).
step "host-ab"       1200 "python bench.py --host-ab"

# Wire pump A/B (ISSUE 15): scalar per-frame vs batch-native vector
# pump over the full wire loop (memory rung — a TPU VM has no spare
# NIC queue, but the pump cost is pure host work and transfers to any
# rung). Both summed-wire-stage cohorts land under distinct wire_pump
# identities; the recorded wire_mpps_ceiling bounds what any AF_XDP
# deployment in front of these chips can move per pump core.
step "wire-ab"       900  "python bench.py --wire-ab"
step "autotune"      1800 "BNG_TABLE_IMPL=auto python bench.py --autotune"
step "headline-1M"   2400 "BNG_BENCH_SUBS=1000000 BNG_BENCH_FLOWS=1000000 BNG_TABLE_IMPL=auto python bench.py"
step "headline-1M-xla" 2400 "BNG_BENCH_SUBS=1000000 BNG_BENCH_FLOWS=1000000 BNG_TABLE_IMPL=xla python bench.py"
# the AGGREGATE serving headline (ISSUE 12): the promoted sharded path —
# steered ring + process_ring_pipelined over every chip on the slice —
# under auto AND pinned xla. n_shards rides the ledger cohort key, so
# these lines gate only against sharded history (rc=3 vs single-device).
N_CHIPS=$(timeout 75 python -c "import jax; print(len(jax.devices()))" 2>/dev/null || echo 8)
step "sharded-headline" 2400 "BNG_BENCH_SUBS=1000000 BNG_TABLE_IMPL=auto python bench.py --shards $N_CHIPS"
step "sharded-headline-xla" 2400 "BNG_BENCH_SUBS=1000000 BNG_TABLE_IMPL=xla python bench.py --shards $N_CHIPS"
if [ "$FAILED" -ne 0 ]; then
  echo "DONE WITH FAILURES $(date -u +%H:%M:%S)" | tee -a "$LOG"; exit 1
fi
echo "ALL DONE $(date -u +%H:%M:%S)" | tee -a "$LOG"
touch /tmp/tpu_run.done
