/* bngring implementation — see bngring.h for the design contract.
 *
 * SPSC rings follow the classic AF_XDP layout: free-running 32-bit
 * producer/consumer cursors, power-of-two capacity, entries addressed by
 * cursor & mask. Producer publishes with release, consumer observes with
 * acquire; each side caches the opposite cursor to avoid cross-core
 * traffic on every op (the if_xdp.h / io_uring discipline).
 */
#include "bngring.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <new>

namespace {

inline bool is_pow2(uint32_t v) { return v != 0 && (v & (v - 1)) == 0; }

/* One SPSC descriptor ring. Producer-side and consumer-side state live on
 * separate cache lines (the if_xdp.h discipline): without the padding
 * every publish invalidates the opposite core's line. */
struct Ring {
  bng_desc *entries = nullptr;
  uint32_t mask = 0;
  alignas(64) std::atomic<uint32_t> prod{0};
  uint32_t cached_cons = 0; /* producer's view */
  alignas(64) std::atomic<uint32_t> cons{0};
  uint32_t cached_prod = 0; /* consumer's view */

  bool init(uint32_t depth) {
    entries = static_cast<bng_desc *>(calloc(depth, sizeof(bng_desc)));
    mask = depth - 1;
    return entries != nullptr;
  }
  void fini() { free(entries); }

  uint32_t size() const { return mask + 1; }

  bool push(const bng_desc &d) {
    uint32_t p = prod.load(std::memory_order_relaxed);
    if (p - cached_cons == size()) {
      cached_cons = cons.load(std::memory_order_acquire);
      if (p - cached_cons == size()) return false; /* full */
    }
    entries[p & mask] = d;
    prod.store(p + 1, std::memory_order_release);
    return true;
  }

  bool pop(bng_desc *out) {
    uint32_t c = cons.load(std::memory_order_relaxed);
    if (cached_prod == c) {
      cached_prod = prod.load(std::memory_order_acquire);
      if (cached_prod == c) return false; /* empty */
    }
    *out = entries[c & mask];
    cons.store(c + 1, std::memory_order_release);
    return true;
  }

  uint32_t pending() const {
    return prod.load(std::memory_order_acquire) -
           cons.load(std::memory_order_acquire);
  }
};

/* Bounded MPMC ring (Vyukov per-slot-sequence queue) for the FILL pool.
 *
 * Unlike the directional rings, frame alloc/free crosses every thread in
 * the deployment: the wire thread allocates (rx_reserve) and recycles
 * rx-full rejects, the engine thread frees drops in batch_complete and
 * allocates in tx_inject, and the slow-path thread recycles after
 * slow_pop. An SPSC cursor pair corrupts under that pattern (round-1
 * ADVICE finding); per-slot sequence numbers make every push/pop a CAS
 * claim + independent publish, safe from any thread. */
struct MpmcRing {
  /* cells padded to a cache line and the two cursors on separate lines
   * (Vyukov's own layout): three threads hammer this ring at frame rate,
   * and false sharing would serialize the CAS claims */
  struct alignas(64) Cell {
    std::atomic<uint32_t> seq{0};
    bng_desc d{};
  };
  Cell *cells = nullptr;
  uint32_t mask = 0;
  alignas(64) std::atomic<uint32_t> prod{0};
  alignas(64) std::atomic<uint32_t> cons{0};

  bool init(uint32_t depth) {
    cells = new (std::nothrow) Cell[depth];
    if (!cells) return false;
    for (uint32_t i = 0; i < depth; i++)
      cells[i].seq.store(i, std::memory_order_relaxed);
    mask = depth - 1;
    return true;
  }
  void fini() { delete[] cells; }

  bool push(const bng_desc &d) {
    uint32_t pos = prod.load(std::memory_order_relaxed);
    for (;;) {
      Cell &c = cells[pos & mask];
      uint32_t seq = c.seq.load(std::memory_order_acquire);
      int32_t dif = static_cast<int32_t>(seq - pos);
      if (dif == 0) {
        if (prod.compare_exchange_weak(pos, pos + 1,
                                       std::memory_order_relaxed)) {
          c.d = d;
          c.seq.store(pos + 1, std::memory_order_release);
          return true;
        }
      } else if (dif < 0) {
        return false; /* full */
      } else {
        pos = prod.load(std::memory_order_relaxed);
      }
    }
  }

  bool pop(bng_desc *out) {
    uint32_t pos = cons.load(std::memory_order_relaxed);
    for (;;) {
      Cell &c = cells[pos & mask];
      uint32_t seq = c.seq.load(std::memory_order_acquire);
      int32_t dif = static_cast<int32_t>(seq - (pos + 1));
      if (dif == 0) {
        if (cons.compare_exchange_weak(pos, pos + 1,
                                       std::memory_order_relaxed)) {
          *out = c.d;
          c.seq.store(pos + mask + 1, std::memory_order_release);
          return true;
        }
      } else if (dif < 0) {
        return false; /* empty */
      } else {
        pos = cons.load(std::memory_order_relaxed);
      }
    }
  }

  uint32_t pending() const {
    return prod.load(std::memory_order_acquire) -
           cons.load(std::memory_order_acquire);
  }
};

} // namespace

struct bng_ring {
  uint8_t *umem = nullptr;
  uint64_t umem_size = 0;
  uint32_t frame_size = 0;
  uint32_t nframes = 0;

  MpmcRing fill; /* free frames (addr only) — any-thread alloc/free */
  Ring rx;   /* wire -> engine */
  Ring tx;   /* engine TX verdicts -> wire (same port) */
  Ring fwd;  /* engine FWD verdicts -> wire (other port) */
  Ring slow; /* engine PASS verdicts -> slow path */

  /* in-flight batches (assemble..complete windows). TWO slots so a
   * double-buffered engine can assemble+dispatch batch k+1 before
   * completing batch k — the device then always has work enqueued while
   * the host demuxes verdicts (SURVEY §7 dispatch design). complete()
   * retires strictly FIFO. */
  static constexpr uint32_t MAX_INFLIGHT = 2;
  bng_desc *inflight[MAX_INFLIGHT] = {nullptr, nullptr};
  uint32_t inflight_n[MAX_INFLIGHT] = {0, 0};
  uint32_t inflight_head = 0; /* oldest outstanding batch */
  uint32_t inflight_count = 0;
  uint32_t inflight_cap = 0;

  bng_ring_stats stats{};
};

extern "C" {

bng_ring *bng_ring_create(uint32_t nframes, uint32_t frame_size,
                          uint32_t depth) {
  if (!is_pow2(nframes) || !is_pow2(depth) || frame_size < 64) return nullptr;
  auto *r = new (std::nothrow) bng_ring();
  if (!r) return nullptr;
  r->frame_size = frame_size;
  r->nframes = nframes;
  r->umem_size = static_cast<uint64_t>(nframes) * frame_size;
  /* PAGE alignment, size rounded to a page multiple: AF_XDP's
   * XDP_UMEM_REG requires a page-aligned area (bngxsk.cpp registers this
   * exact buffer), aligned_alloc requires size % alignment == 0, and a
   * page is trivially cache-line aligned for the staging copies. */
  const uint64_t page = 4096;
  uint64_t alloc_size = (r->umem_size + page - 1) & ~(page - 1);
  r->umem = static_cast<uint8_t *>(aligned_alloc(page, alloc_size));
  bool ok = r->umem && r->fill.init(nframes) && r->rx.init(depth) &&
            r->tx.init(depth) && r->fwd.init(depth) && r->slow.init(depth);
  r->inflight_cap = depth;
  for (uint32_t i = 0; i < bng_ring::MAX_INFLIGHT; i++) {
    r->inflight[i] = static_cast<bng_desc *>(calloc(depth, sizeof(bng_desc)));
    ok = ok && r->inflight[i];
  }
  if (!ok) {
    bng_ring_destroy(r);
    return nullptr;
  }
  memset(r->umem, 0, r->umem_size);
  /* all frames start free */
  for (uint32_t i = 0; i < nframes; i++) {
    bng_desc d{static_cast<uint64_t>(i) * frame_size, 0, 0};
    r->fill.push(d);
  }
  return r;
}

void bng_ring_destroy(bng_ring *r) {
  if (!r) return;
  r->fill.fini();
  r->rx.fini();
  r->tx.fini();
  r->fwd.fini();
  r->slow.fini();
  for (uint32_t i = 0; i < bng_ring::MAX_INFLIGHT; i++) free(r->inflight[i]);
  free(r->umem);
  delete r;
}

uint8_t *bng_ring_umem(bng_ring *r) { return r->umem; }
uint64_t bng_ring_umem_size(bng_ring *r) { return r->umem_size; }
uint32_t bng_ring_frame_size(bng_ring *r) { return r->frame_size; }

static bool valid_addr(bng_ring *r, uint64_t addr) {
  return addr < r->umem_size && addr % r->frame_size == 0;
}

uint64_t bng_ring_rx_reserve(bng_ring *r) {
  bng_desc d;
  if (!r->fill.pop(&d)) {
    r->stats.fill_empty++;
    return UINT64_MAX;
  }
  return d.addr;
}

/* Genuine-DHCP classifier (0-2 VLAN tags), mirroring the fast path's
 * eligibility parse (dhcp_fastpath.c: op==BOOTREQUEST + magic cookie).
 * Deliberately strict — only frames the DHCP-only device program would
 * actually consider are classified, so the fast lane can never swallow
 * natable port-67 transit, fragments, or non-DHCP floods (those keep the
 * fused pipeline's NAT/antispoof/QoS treatment). Runs once per RX frame. */
static uint32_t classify_dhcp(const uint8_t *p, uint32_t len) {
  if (len < 14) return 0;
  uint32_t off = 12;
  uint32_t et = (static_cast<uint32_t>(p[off]) << 8) | p[off + 1];
  for (int i = 0; i < 2 && (et == 0x8100 || et == 0x88a8); i++) {
    off += 4;
    if (len < off + 2) return 0;
    et = (static_cast<uint32_t>(p[off]) << 8) | p[off + 1];
  }
  off += 2; /* L3 start */
  if (et != 0x0800 || len < off + 20) return 0;
  if ((p[off] >> 4) != 4) return 0;
  uint32_t ihl = (p[off] & 0x0F) * 4u;
  if (ihl < 20 || p[off + 9] != 17) return 0; /* UDP */
  /* fragmented packets (MF set or nonzero offset) carry no parseable L4 */
  uint32_t fragword = (static_cast<uint32_t>(p[off + 6]) << 8) | p[off + 7];
  if (fragword & 0x3FFFu) return 0;
  uint32_t l4 = off + ihl;
  if (len < l4 + 8) return 0;
  uint32_t dport = (static_cast<uint32_t>(p[l4 + 2]) << 8) | p[l4 + 3];
  if (dport != 67) return 0;
  /* BOOTP: op==BOOTREQUEST and the DHCP magic cookie at +236 */
  uint32_t bootp = l4 + 8;
  if (len < bootp + 240 || p[bootp] != 1) return 0;
  uint32_t magic = (static_cast<uint32_t>(p[bootp + 236]) << 24) |
                   (static_cast<uint32_t>(p[bootp + 237]) << 16) |
                   (static_cast<uint32_t>(p[bootp + 238]) << 8) |
                   p[bootp + 239];
  return magic == 0x63825363u ? BNG_DESC_F_DHCP_CTRL : 0;
}

int bng_ring_rx_submit(bng_ring *r, uint64_t addr, uint32_t len,
                       uint32_t flags) {
  if (!valid_addr(r, addr) || len > r->frame_size) {
    r->stats.bad_desc++;
    return -1;
  }
  /* direction gate: the fused pipeline only answers access-side DHCP
   * (dhcp_tx = is_reply & from_access) — a network-side frame must never
   * enter the fast lane.  The classifier is authoritative: a caller's
   * pre-set DHCP_CTRL bit is cleared first, so a stale/hostile flags word
   * can never route a network-side frame around NAT/antispoof/QoS. */
  flags &= ~BNG_DESC_F_DHCP_CTRL;
  if (flags & BNG_DESC_F_FROM_ACCESS)
    flags |= classify_dhcp(r->umem + addr, len);
  bng_desc d{addr, len, flags};
  if (!r->rx.push(d)) {
    r->stats.rx_full++;
    r->fill.push(d); /* recycle */
    return -1;
  }
  return 0;
}

int bng_ring_rx_push(bng_ring *r, const uint8_t *data, uint32_t len,
                     uint32_t flags) {
  if (len > r->frame_size) {
    r->stats.bad_desc++;
    return -1;
  }
  uint64_t addr = bng_ring_rx_reserve(r);
  if (addr == UINT64_MAX) return -1;
  memcpy(r->umem + addr, data, len);
  return bng_ring_rx_submit(r, addr, len, flags);
}

uint32_t bng_batch_assemble(bng_ring *r, uint8_t *out, uint32_t *out_len,
                            uint32_t *out_flags, uint32_t max_batch,
                            uint32_t slot) {
  if (r->inflight_count >= bng_ring::MAX_INFLIGHT) return 0; /* windows full */
  if (max_batch > r->inflight_cap) max_batch = r->inflight_cap;
  uint32_t tail =
      (r->inflight_head + r->inflight_count) % bng_ring::MAX_INFLIGHT;
  uint32_t n = 0;
  bng_desc d;
  while (n < max_batch && r->rx.pop(&d)) {
    uint32_t copy = d.len < slot ? d.len : slot;
    memcpy(out + static_cast<size_t>(n) * slot, r->umem + d.addr, copy);
    if (copy < slot)
      memset(out + static_cast<size_t>(n) * slot + copy, 0, slot - copy);
    out_len[n] = copy;
    out_flags[n] = d.flags;
    r->inflight[tail][n] = d;
    n++;
  }
  if (n == 0) return 0; /* empty assemble opens no window */
  r->inflight_n[tail] = n;
  r->inflight_count++;
  r->stats.rx += n;
  return n;
}

int bng_batch_complete(bng_ring *r, const uint8_t *verdict,
                       const uint8_t *out, const uint32_t *out_len,
                       uint32_t n, uint32_t slot) {
  /* retires the OLDEST outstanding batch; n must match its size */
  uint32_t head = r->inflight_head;
  if (r->inflight_count == 0 || n != r->inflight_n[head] ||
      n > r->inflight_cap)
    return -1;
  for (uint32_t i = 0; i < n; i++) {
    bng_desc d = r->inflight[head][i];
    uint8_t v = verdict[i];
    if (v == BNG_VERDICT_TX || v == BNG_VERDICT_FWD) {
      /* device rewrote the packet: copy staged bytes back over the frame */
      uint32_t len = out_len[i];
      if (len > r->frame_size) len = r->frame_size;
      if (out) {
        memcpy(r->umem + d.addr, out + static_cast<size_t>(i) * slot,
               len < slot ? len : slot);
      }
      d.len = len;
      Ring &dst = (v == BNG_VERDICT_TX) ? r->tx : r->fwd;
      if (dst.push(d)) {
        if (v == BNG_VERDICT_TX) r->stats.tx++;
        else r->stats.fwd++;
      } else {
        r->stats.tx_full++;
        r->fill.push(d);
      }
    } else if (v == BNG_VERDICT_PASS) {
      if (r->slow.push(d)) r->stats.slow++;
      else {
        r->stats.tx_full++;
        r->fill.push(d);
      }
    } else { /* DROP (and any unknown verdict fails closed) */
      r->stats.drop++;
      r->fill.push(d);
    }
  }
  r->inflight_n[head] = 0;
  r->inflight_head = (head + 1) % bng_ring::MAX_INFLIGHT;
  r->inflight_count--;
  return 0;
}

int bng_ring_tx_inject(bng_ring *r, const uint8_t *data, uint32_t len,
                       uint32_t flags) {
  if (len > r->frame_size) {
    r->stats.bad_desc++;
    return -1;
  }
  bng_desc d;
  if (!r->fill.pop(&d)) {
    r->stats.fill_empty++;
    return -1;
  }
  memcpy(r->umem + d.addr, data, len);
  d.len = len;
  d.flags = flags;
  if (!r->tx.push(d)) {
    r->stats.tx_full++;
    r->fill.push(d);
    return -1;
  }
  r->stats.tx++;
  return 0;
}

static int pop_from(bng_ring *r, Ring &ring, uint8_t *buf, uint32_t cap,
                    uint32_t *flags) {
  bng_desc d;
  if (!ring.pop(&d)) return 0;
  int rc;
  if (d.len <= cap) {
    memcpy(buf, r->umem + d.addr, d.len);
    rc = static_cast<int>(d.len);
  } else {
    rc = -1;
  }
  if (flags) *flags = d.flags;
  r->fill.push(d); /* recycle */
  return rc;
}

int bng_ring_tx_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                    uint32_t *flags) {
  return pop_from(r, r->tx, buf, cap, flags);
}
int bng_ring_fwd_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                     uint32_t *flags) {
  return pop_from(r, r->fwd, buf, cap, flags);
}
int bng_ring_slow_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                      uint32_t *flags) {
  return pop_from(r, r->slow, buf, cap, flags);
}

uint32_t bng_ring_rx_pending(bng_ring *r) { return r->rx.pending(); }
uint32_t bng_ring_tx_pending(bng_ring *r) { return r->tx.pending(); }
uint32_t bng_ring_fwd_pending(bng_ring *r) { return r->fwd.pending(); }
uint32_t bng_ring_slow_pending(bng_ring *r) { return r->slow.pending(); }
uint32_t bng_ring_free_frames(bng_ring *r) { return r->fill.pending(); }

void bng_ring_get_stats(bng_ring *r, bng_ring_stats *out) {
  *out = r->stats;
}

/* Move up to budget frames per direction between two rings' output sides
 * and the peer's RX. TX and FWD both land on the peer wire (a loopback
 * cable has one far end). */
static uint32_t pump_dir(bng_ring *src, bng_ring *dst, uint32_t budget) {
  uint32_t moved = 0;
  bng_desc d;
  while (moved < budget) {
    bool got = src->tx.pop(&d);
    if (!got) got = src->fwd.pop(&d);
    if (!got) break;
    /* flags flip: frames leaving the access side arrive at the core side.
     * The stale direction-specific DHCP-control bit needs no handling
     * here: rx_submit clears and re-derives it authoritatively for every
     * submitted frame. */
    uint32_t fl = d.flags ^ BNG_DESC_F_FROM_ACCESS;
    bng_ring_rx_push(dst, src->umem + d.addr, d.len, fl);
    src->fill.push(d);
    moved++;
  }
  return moved;
}

int bng_wire_pump(bng_ring *a, bng_ring *b, uint32_t budget) {
  uint32_t m = pump_dir(a, b, budget);
  m += pump_dir(b, a, budget);
  return static_cast<int>(m);
}

uint32_t bng_abi_desc_size(void) { return sizeof(bng_desc); }
uint32_t bng_abi_desc_addr_off(void) { return offsetof(bng_desc, addr); }
uint32_t bng_abi_desc_len_off(void) { return offsetof(bng_desc, len); }
uint32_t bng_abi_desc_flags_off(void) { return offsetof(bng_desc, flags); }
uint32_t bng_abi_stats_size(void) { return sizeof(bng_ring_stats); }
uint32_t bng_abi_version(void) { return 1; }

} /* extern "C" */
