/* bngring implementation — see bngring.h for the design contract.
 *
 * SPSC rings follow the classic AF_XDP layout: free-running 32-bit
 * producer/consumer cursors, power-of-two capacity, entries addressed by
 * cursor & mask. Producer publishes with release, consumer observes with
 * acquire; each side caches the opposite cursor to avoid cross-core
 * traffic on every op (the if_xdp.h / io_uring discipline).
 */
#include "bngring.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <new>

namespace {

inline bool is_pow2(uint32_t v) { return v != 0 && (v & (v - 1)) == 0; }

/* One SPSC descriptor ring. Producer-side and consumer-side state live on
 * separate cache lines (the if_xdp.h discipline): without the padding
 * every publish invalidates the opposite core's line. */
struct Ring {
  bng_desc *entries = nullptr;
  uint32_t mask = 0;
  alignas(64) std::atomic<uint32_t> prod{0};
  uint32_t cached_cons = 0; /* producer's view */
  alignas(64) std::atomic<uint32_t> cons{0};
  uint32_t cached_prod = 0; /* consumer's view */

  bool init(uint32_t depth) {
    entries = static_cast<bng_desc *>(calloc(depth, sizeof(bng_desc)));
    mask = depth - 1;
    return entries != nullptr;
  }
  void fini() { free(entries); }

  uint32_t size() const { return mask + 1; }

  bool push(const bng_desc &d) {
    uint32_t p = prod.load(std::memory_order_relaxed);
    if (p - cached_cons == size()) {
      cached_cons = cons.load(std::memory_order_acquire);
      if (p - cached_cons == size()) return false; /* full */
    }
    entries[p & mask] = d;
    prod.store(p + 1, std::memory_order_release);
    return true;
  }

  bool pop(bng_desc *out) {
    uint32_t c = cons.load(std::memory_order_relaxed);
    if (cached_prod == c) {
      cached_prod = prod.load(std::memory_order_acquire);
      if (cached_prod == c) return false; /* empty */
    }
    *out = entries[c & mask];
    cons.store(c + 1, std::memory_order_release);
    return true;
  }

  uint32_t pending() const {
    return prod.load(std::memory_order_acquire) -
           cons.load(std::memory_order_acquire);
  }
};

/* Bounded MPMC ring (Vyukov per-slot-sequence queue) for the FILL pool.
 *
 * Unlike the directional rings, frame alloc/free crosses every thread in
 * the deployment: the wire thread allocates (rx_reserve) and recycles
 * rx-full rejects, the engine thread frees drops in batch_complete and
 * allocates in tx_inject, and the slow-path thread recycles after
 * slow_pop. An SPSC cursor pair corrupts under that pattern (round-1
 * ADVICE finding); per-slot sequence numbers make every push/pop a CAS
 * claim + independent publish, safe from any thread. */
struct MpmcRing {
  /* cells padded to a cache line and the two cursors on separate lines
   * (Vyukov's own layout): three threads hammer this ring at frame rate,
   * and false sharing would serialize the CAS claims */
  struct alignas(64) Cell {
    std::atomic<uint32_t> seq{0};
    bng_desc d{};
  };
  Cell *cells = nullptr;
  uint32_t mask = 0;
  alignas(64) std::atomic<uint32_t> prod{0};
  alignas(64) std::atomic<uint32_t> cons{0};

  bool init(uint32_t depth) {
    cells = new (std::nothrow) Cell[depth];
    if (!cells) return false;
    for (uint32_t i = 0; i < depth; i++)
      cells[i].seq.store(i, std::memory_order_relaxed);
    mask = depth - 1;
    return true;
  }
  void fini() { delete[] cells; }

  bool push(const bng_desc &d) {
    uint32_t pos = prod.load(std::memory_order_relaxed);
    for (;;) {
      Cell &c = cells[pos & mask];
      uint32_t seq = c.seq.load(std::memory_order_acquire);
      int32_t dif = static_cast<int32_t>(seq - pos);
      if (dif == 0) {
        if (prod.compare_exchange_weak(pos, pos + 1,
                                       std::memory_order_relaxed)) {
          c.d = d;
          c.seq.store(pos + 1, std::memory_order_release);
          return true;
        }
      } else if (dif < 0) {
        return false; /* full */
      } else {
        pos = prod.load(std::memory_order_relaxed);
      }
    }
  }

  bool pop(bng_desc *out) {
    uint32_t pos = cons.load(std::memory_order_relaxed);
    for (;;) {
      Cell &c = cells[pos & mask];
      uint32_t seq = c.seq.load(std::memory_order_acquire);
      int32_t dif = static_cast<int32_t>(seq - (pos + 1));
      if (dif == 0) {
        if (cons.compare_exchange_weak(pos, pos + 1,
                                       std::memory_order_relaxed)) {
          *out = c.d;
          c.seq.store(pos + mask + 1, std::memory_order_release);
          return true;
        }
      } else if (dif < 0) {
        return false; /* empty */
      } else {
        pos = cons.load(std::memory_order_relaxed);
      }
    }
  }

  uint32_t pending() const {
    return prod.load(std::memory_order_acquire) -
           cons.load(std::memory_order_acquire);
  }
};

} // namespace

/* Public-IP -> shard steering map: fixed-size open addressing with the
 * bounded-probe discipline the fast-path tables use everywhere
 * (nat44.c:423 bounds probes for the verifier; same style here).
 *
 * THREADING: single writer (control thread, bng_ring_steer_pub_ip),
 * many readers (wire thread inside rx_submit). Publication protocol:
 * the writer stores ip first, then shard_plus1 with release; a reader
 * that observes shard_plus1 != 0 with acquire therefore sees the
 * matching ip. Entries are never deleted; an existing IP's shard may be
 * updated at runtime (the atomic store makes the switch clean). */
struct PubMap {
  static constexpr uint32_t SLOTS = 1024;
  static constexpr uint32_t MAX_PROBE = 64;
  struct Ent {
    std::atomic<uint32_t> ip{0};
    std::atomic<uint32_t> shard_plus1{0}; /* 0 = empty */
  };
  Ent ents[SLOTS];
};

struct bng_ring {
  uint8_t *umem = nullptr;
  uint64_t umem_size = 0;
  uint32_t frame_size = 0;
  uint32_t nframes = 0;
  uint32_t n_shards = 1;

  MpmcRing fill; /* free frames (addr only) — any-thread alloc/free */
  Ring *rxq = nullptr; /* wire -> engine, one SPSC queue per shard */
  Ring tx;   /* engine TX verdicts -> wire (same port) */
  Ring fwd;  /* engine FWD verdicts -> wire (other port) */
  Ring slow; /* engine PASS verdicts -> slow path */
  PubMap pubmap; /* downstream steering: NAT public IP -> owner shard */

  /* in-flight batches (assemble..complete windows). TWO slots so a
   * double-buffered engine can assemble+dispatch batch k+1 before
   * completing batch k — the device then always has work enqueued while
   * the host demuxes verdicts (SURVEY §7 dispatch design). complete()
   * retires strictly FIFO. */
  static constexpr uint32_t MAX_INFLIGHT = 2;
  bng_desc *inflight[MAX_INFLIGHT] = {nullptr, nullptr};
  uint32_t inflight_n[MAX_INFLIGHT] = {0, 0};
  uint32_t inflight_head = 0; /* oldest outstanding batch */
  uint32_t inflight_count = 0;
  uint32_t inflight_cap = 0;

  bng_ring_stats stats{};
};

extern "C" {

bng_ring *bng_ring_create_sharded(uint32_t nframes, uint32_t frame_size,
                                  uint32_t depth, uint32_t n_shards) {
  if (!is_pow2(nframes) || !is_pow2(depth) || frame_size < 64) return nullptr;
  if (n_shards < 1 || n_shards > 64) return nullptr;
  auto *r = new (std::nothrow) bng_ring();
  if (!r) return nullptr;
  r->frame_size = frame_size;
  r->nframes = nframes;
  r->n_shards = n_shards;
  r->umem_size = static_cast<uint64_t>(nframes) * frame_size;
  /* PAGE alignment, size rounded to a page multiple: AF_XDP's
   * XDP_UMEM_REG requires a page-aligned area (bngxsk.cpp registers this
   * exact buffer), aligned_alloc requires size % alignment == 0, and a
   * page is trivially cache-line aligned for the staging copies. */
  const uint64_t page = 4096;
  uint64_t alloc_size = (r->umem_size + page - 1) & ~(page - 1);
  r->umem = static_cast<uint8_t *>(aligned_alloc(page, alloc_size));
  r->rxq = new (std::nothrow) Ring[n_shards];
  bool ok = r->umem && r->rxq && r->fill.init(nframes) && r->tx.init(depth) &&
            r->fwd.init(depth) && r->slow.init(depth);
  for (uint32_t s = 0; ok && s < n_shards; s++) ok = r->rxq[s].init(depth);
  /* a sharded batch is n_shards regions of up to depth rows each */
  r->inflight_cap = depth * n_shards;
  for (uint32_t i = 0; i < bng_ring::MAX_INFLIGHT; i++) {
    r->inflight[i] =
        static_cast<bng_desc *>(calloc(r->inflight_cap, sizeof(bng_desc)));
    ok = ok && r->inflight[i];
  }
  if (!ok) {
    bng_ring_destroy(r);
    return nullptr;
  }
  memset(r->umem, 0, r->umem_size);
  /* all frames start free */
  for (uint32_t i = 0; i < nframes; i++) {
    bng_desc d{static_cast<uint64_t>(i) * frame_size, 0, 0};
    r->fill.push(d);
  }
  return r;
}

bng_ring *bng_ring_create(uint32_t nframes, uint32_t frame_size,
                          uint32_t depth) {
  return bng_ring_create_sharded(nframes, frame_size, depth, 1);
}

void bng_ring_destroy(bng_ring *r) {
  if (!r) return;
  r->fill.fini();
  if (r->rxq)
    for (uint32_t s = 0; s < r->n_shards; s++) r->rxq[s].fini();
  delete[] r->rxq;
  r->tx.fini();
  r->fwd.fini();
  r->slow.fini();
  for (uint32_t i = 0; i < bng_ring::MAX_INFLIGHT; i++) free(r->inflight[i]);
  free(r->umem);
  delete r;
}

uint32_t bng_ring_n_shards(bng_ring *r) { return r->n_shards; }

uint8_t *bng_ring_umem(bng_ring *r) { return r->umem; }
uint64_t bng_ring_umem_size(bng_ring *r) { return r->umem_size; }
uint32_t bng_ring_frame_size(bng_ring *r) { return r->frame_size; }

static bool valid_addr(bng_ring *r, uint64_t addr) {
  return addr < r->umem_size && addr % r->frame_size == 0;
}

/* Return a frame to the fill pool, normalized to its chunk base: wire
 * descriptors may carry a copy-mode headroom offset (rx_submit_batch),
 * and the pool hands out whole chunks. */
static void recycle(bng_ring *r, uint64_t addr) {
  bng_desc d{addr - addr % r->frame_size, 0, 0};
  r->fill.push(d);
}

uint64_t bng_ring_rx_reserve(bng_ring *r) {
  bng_desc d;
  if (!r->fill.pop(&d)) {
    r->stats.fill_empty++;
    return UINT64_MAX;
  }
  return d.addr;
}

/* Genuine-DHCP classifier (0-2 VLAN tags), mirroring the fast path's
 * eligibility parse (dhcp_fastpath.c: op==BOOTREQUEST + magic cookie).
 * Deliberately strict — only frames the DHCP-only device program would
 * actually consider are classified, so the fast lane can never swallow
 * natable port-67 transit, fragments, or non-DHCP floods (those keep the
 * fused pipeline's NAT/antispoof/QoS treatment). Runs once per RX frame. */
static uint32_t classify_dhcp(const uint8_t *p, uint32_t len) {
  if (len < 14) return 0;
  uint32_t off = 12;
  uint32_t et = (static_cast<uint32_t>(p[off]) << 8) | p[off + 1];
  for (int i = 0; i < 2 && (et == 0x8100 || et == 0x88a8); i++) {
    off += 4;
    if (len < off + 2) return 0;
    et = (static_cast<uint32_t>(p[off]) << 8) | p[off + 1];
  }
  off += 2; /* L3 start */
  if (et != 0x0800 || len < off + 20) return 0;
  if ((p[off] >> 4) != 4) return 0;
  uint32_t ihl = (p[off] & 0x0F) * 4u;
  if (ihl < 20 || p[off + 9] != 17) return 0; /* UDP */
  /* fragmented packets (MF set or nonzero offset) carry no parseable L4 */
  uint32_t fragword = (static_cast<uint32_t>(p[off + 6]) << 8) | p[off + 7];
  if (fragword & 0x3FFFu) return 0;
  uint32_t l4 = off + ihl;
  if (len < l4 + 8) return 0;
  uint32_t dport = (static_cast<uint32_t>(p[l4 + 2]) << 8) | p[l4 + 3];
  if (dport != 67) return 0;
  /* BOOTP: op==BOOTREQUEST and the DHCP magic cookie at +236 */
  uint32_t bootp = l4 + 8;
  if (len < bootp + 240 || p[bootp] != 1) return 0;
  uint32_t magic = (static_cast<uint32_t>(p[bootp + 236]) << 24) |
                   (static_cast<uint32_t>(p[bootp + 237]) << 16) |
                   (static_cast<uint32_t>(p[bootp + 238]) << 8) |
                   p[bootp + 239];
  return magic == 0x63825363u ? BNG_DESC_F_DHCP_CTRL : 0;
}

/* FNV-1a32 — must match bng_tpu/utils/net.py fnv1a32 bit-for-bit (the
 * control plane computes subscriber affinity with the Python twin). */
static uint32_t fnv1a32_bytes(const uint8_t *p, uint32_t n) {
  uint32_t h = 2166136261u;
  for (uint32_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 16777619u;
  }
  return h;
}

static int pubmap_find(const PubMap &m, uint32_t ip, bool for_insert) {
  uint8_t key[4] = {static_cast<uint8_t>(ip >> 24),
                    static_cast<uint8_t>(ip >> 16),
                    static_cast<uint8_t>(ip >> 8), static_cast<uint8_t>(ip)};
  uint32_t h = fnv1a32_bytes(key, 4);
  for (uint32_t probe = 0; probe < PubMap::MAX_PROBE; probe++) {
    uint32_t slot = (h + probe) & (PubMap::SLOTS - 1);
    const PubMap::Ent &e = m.ents[slot];
    if (e.shard_plus1.load(std::memory_order_acquire) == 0)
      return for_insert ? static_cast<int>(slot) : -1;
    if (e.ip.load(std::memory_order_relaxed) == ip)
      return static_cast<int>(slot);
  }
  return -1;
}

int bng_ring_steer_pub_ip(bng_ring *r, uint32_t ip, uint32_t shard) {
  if (shard >= r->n_shards) return -1;
  int slot = pubmap_find(r->pubmap, ip, /*for_insert=*/true);
  if (slot < 0) return -1;
  /* ip before shard_plus1-with-release: a concurrent reader that sees the
   * entry occupied sees the right ip (PubMap threading contract above) */
  r->pubmap.ents[slot].ip.store(ip, std::memory_order_relaxed);
  r->pubmap.ents[slot].shard_plus1.store(shard + 1, std::memory_order_release);
  return 0;
}

/* Steering decision — spec in bngring.h; Python twin: ring.py shard_of.
 * Walks the same L2/L3 prefix as classify_dhcp (0-2 VLAN tags). */
uint32_t bng_ring_shard_of(bng_ring *r, const uint8_t *p, uint32_t len,
                           uint32_t flags) {
  uint32_t n = r->n_shards;
  if (n == 1) return 0;
  if (len < 14) return 0;
  if (!(flags & BNG_DESC_F_DHCP_CTRL)) {
    uint32_t off = 12;
    uint32_t et = (static_cast<uint32_t>(p[off]) << 8) | p[off + 1];
    for (int i = 0; i < 2 && (et == 0x8100 || et == 0x88a8); i++) {
      off += 4;
      if (len < off + 2) break;
      et = (static_cast<uint32_t>(p[off]) << 8) | p[off + 1];
    }
    off += 2; /* L3 start */
    if (et == 0x0800 && len >= off + 20 && (p[off] >> 4) == 4) {
      if (flags & BNG_DESC_F_FROM_ACCESS) {
        /* upstream: subscriber = src private IP */
        return fnv1a32_bytes(p + off + 12, 4) % n;
      }
      /* downstream: NAT public IP owner, else dst-IP hash */
      const uint8_t *dst = p + off + 16;
      uint32_t dip = (static_cast<uint32_t>(dst[0]) << 24) |
                     (static_cast<uint32_t>(dst[1]) << 16) |
                     (static_cast<uint32_t>(dst[2]) << 8) | dst[3];
      int slot = pubmap_find(r->pubmap, dip, /*for_insert=*/false);
      if (slot >= 0) {
        uint32_t s =
            r->pubmap.ents[slot].shard_plus1.load(std::memory_order_relaxed) -
            1;
        if (s < n) return s;
      }
      return fnv1a32_bytes(dst, 4) % n;
    }
    /* PPPoE session DATA (PPP proto IPv4): steer by the INNER src IP —
     * the affinity key the decap'd packet's chip-local NAT/QoS/session
     * state is placed with.  PPPoE control falls through to the sticky
     * MAC hash (any shard's slow path handles negotiation). */
    if (et == 0x8864 && (flags & BNG_DESC_F_FROM_ACCESS) &&
        len >= off + 8 + 20 && p[off] == 0x11 && p[off + 1] == 0 &&
        ((static_cast<uint32_t>(p[off + 6]) << 8) | p[off + 7]) == 0x0021 &&
        (p[off + 8] >> 4) == 4) {
      return fnv1a32_bytes(p + off + 8 + 12, 4) % n;
    }
  }
  /* DHCP control (any shard correct; MAC = sticky) and non-IPv4 */
  return fnv1a32_bytes(p + 6, 6) % n;
}

int bng_ring_rx_submit(bng_ring *r, uint64_t addr, uint32_t len,
                       uint32_t flags) {
  if (!valid_addr(r, addr) || len > r->frame_size) {
    r->stats.bad_desc++;
    return -1;
  }
  /* direction gate: the fused pipeline only answers access-side DHCP
   * (dhcp_tx = is_reply & from_access) — a network-side frame must never
   * enter the fast lane.  The classifier is authoritative: a caller's
   * pre-set DHCP_CTRL bit is cleared first, so a stale/hostile flags word
   * can never route a network-side frame around NAT/antispoof/QoS. */
  flags &= ~BNG_DESC_F_DHCP_CTRL;
  if (flags & BNG_DESC_F_FROM_ACCESS)
    flags |= classify_dhcp(r->umem + addr, len);
  uint32_t shard = bng_ring_shard_of(r, r->umem + addr, len, flags);
  bng_desc d{addr, len, flags};
  if (!r->rxq[shard].push(d)) {
    r->stats.rx_full++;
    recycle(r, addr);
    return -1;
  }
  return 0;
}

uint32_t bng_ring_rx_reserve_batch(bng_ring *r, uint64_t *out_addrs,
                                   uint32_t n) {
  uint32_t got = 0;
  bng_desc d;
  while (got < n && r->fill.pop(&d)) out_addrs[got++] = d.addr;
  if (got < n) r->stats.fill_empty++; /* one per dry pump round (scalar) */
  return got;
}

uint32_t bng_ring_rx_submit_batch(bng_ring *r, const uint64_t *addrs,
                                  const uint32_t *lens, uint32_t flags,
                                  uint8_t *out_ok, uint32_t n) {
  uint32_t ok_n = 0;
  const uint32_t fsz = r->frame_size;
  for (uint32_t i = 0; i < n; i++) {
    uint64_t addr = addrs[i];
    out_ok[i] = 0;
    if (addr >= r->umem_size) { /* garbage addr: nothing to recycle */
      r->stats.bad_desc++;
      continue;
    }
    uint32_t off = static_cast<uint32_t>(addr % fsz);
    if (lens[i] > fsz - off) { /* does not fit the chunk room: drop.
         The scalar pump pre-validates identically (no ring stat), so
         pump_stats stay bit-equal across paths. */
      recycle(r, addr);
      continue;
    }
    uint32_t fl = flags & ~BNG_DESC_F_DHCP_CTRL; /* rx_submit gate */
    if (fl & BNG_DESC_F_FROM_ACCESS)
      fl |= classify_dhcp(r->umem + addr, lens[i]);
    uint32_t shard = bng_ring_shard_of(r, r->umem + addr, lens[i], fl);
    bng_desc d{addr, lens[i], fl};
    if (!r->rxq[shard].push(d)) {
      r->stats.rx_full++;
      recycle(r, addr);
      continue;
    }
    out_ok[i] = 1;
    ok_n++;
  }
  return ok_n;
}

uint32_t bng_ring_frame_free_batch(bng_ring *r, const uint64_t *addrs,
                                   uint32_t n) {
  uint32_t freed = 0;
  for (uint32_t i = 0; i < n; i++) {
    if (addrs[i] >= r->umem_size) {
      r->stats.bad_desc++;
      continue;
    }
    recycle(r, addrs[i]);
    freed++;
  }
  return freed;
}

int bng_ring_rx_push(bng_ring *r, const uint8_t *data, uint32_t len,
                     uint32_t flags) {
  if (len > r->frame_size) {
    r->stats.bad_desc++;
    return -1;
  }
  uint64_t addr = bng_ring_rx_reserve(r);
  if (addr == UINT64_MAX) return -1;
  memcpy(r->umem + addr, data, len);
  return bng_ring_rx_submit(r, addr, len, flags);
}

static void stage_frame(bng_ring *r, uint8_t *out, uint32_t *out_len,
                        uint32_t *out_flags, uint32_t row, uint32_t slot,
                        const bng_desc &d) {
  uint32_t copy = d.len < slot ? d.len : slot;
  memcpy(out + static_cast<size_t>(row) * slot, r->umem + d.addr, copy);
  if (copy < slot)
    memset(out + static_cast<size_t>(row) * slot + copy, 0, slot - copy);
  out_len[row] = copy;
  out_flags[row] = d.flags;
}

uint32_t bng_batch_assemble(bng_ring *r, uint8_t *out, uint32_t *out_len,
                            uint32_t *out_flags, uint32_t max_batch,
                            uint32_t slot) {
  if (r->inflight_count >= bng_ring::MAX_INFLIGHT) return 0; /* windows full */
  if (max_batch > r->inflight_cap) max_batch = r->inflight_cap;
  uint32_t tail =
      (r->inflight_head + r->inflight_count) % bng_ring::MAX_INFLIGHT;
  uint32_t n = 0;
  bng_desc d;
  /* round-robin over shard queues so no shard starves (n_shards==1 is
   * the plain single-queue drain) */
  uint32_t idle = 0;
  for (uint32_t s = 0; n < max_batch && idle < r->n_shards;
       s = (s + 1) % r->n_shards) {
    if (!r->rxq[s].pop(&d)) {
      idle++;
      continue;
    }
    idle = 0;
    stage_frame(r, out, out_len, out_flags, n, slot, d);
    r->inflight[tail][n] = d;
    n++;
  }
  if (n == 0) return 0; /* empty assemble opens no window */
  r->inflight_n[tail] = n;
  r->inflight_count++;
  r->stats.rx += n;
  return n;
}

uint32_t bng_batch_assemble_sharded(bng_ring *r, uint8_t *out,
                                    uint32_t *out_len, uint32_t *out_flags,
                                    uint32_t b_per_shard, uint32_t slot) {
  if (r->inflight_count >= bng_ring::MAX_INFLIGHT) return 0; /* windows full */
  uint32_t total = r->n_shards * b_per_shard;
  if (b_per_shard == 0 || total > r->inflight_cap) return 0;
  uint32_t tail =
      (r->inflight_head + r->inflight_count) % bng_ring::MAX_INFLIGHT;
  uint32_t got = 0;
  bng_desc d;
  for (uint32_t s = 0; s < r->n_shards; s++) {
    for (uint32_t k = 0; k < b_per_shard; k++) {
      uint32_t row = s * b_per_shard + k;
      if (r->rxq[s].pop(&d)) {
        stage_frame(r, out, out_len, out_flags, row, slot, d);
        r->inflight[tail][row] = d;
        got++;
      } else {
        /* padding lane: zeroed so stale caller-buffer bytes can never be
         * parsed as a packet; complete() skips it via the addr marker */
        memset(out + static_cast<size_t>(row) * slot, 0, slot);
        out_len[row] = 0;
        out_flags[row] = 0;
        r->inflight[tail][row] = bng_desc{UINT64_MAX, 0, 0};
      }
    }
  }
  if (got == 0) return 0; /* nothing pending: no window opened */
  r->inflight_n[tail] = total;
  r->inflight_count++;
  r->stats.rx += got;
  return got;
}

int bng_batch_complete(bng_ring *r, const uint8_t *verdict,
                       const uint8_t *out, const uint32_t *out_len,
                       uint32_t n, uint32_t slot) {
  /* retires the OLDEST outstanding batch; n must match its size */
  uint32_t head = r->inflight_head;
  if (r->inflight_count == 0 || n != r->inflight_n[head] ||
      n > r->inflight_cap)
    return -1;
  for (uint32_t i = 0; i < n; i++) {
    bng_desc d = r->inflight[head][i];
    if (d.addr == UINT64_MAX) continue; /* sharded-assemble padding lane */
    uint8_t v = verdict[i];
    if (v == BNG_VERDICT_TX || v == BNG_VERDICT_FWD) {
      /* device rewrote the packet: copy staged bytes back over the frame.
       * Clamp to the chunk ROOM — a headroom-offset descriptor
       * (rx_submit_batch) owns only frame_size - off bytes of its chunk */
      uint32_t room =
          r->frame_size - static_cast<uint32_t>(d.addr % r->frame_size);
      uint32_t len = out_len[i];
      if (len > room) len = room;
      if (out) {
        memcpy(r->umem + d.addr, out + static_cast<size_t>(i) * slot,
               len < slot ? len : slot);
      }
      d.len = len;
      Ring &dst = (v == BNG_VERDICT_TX) ? r->tx : r->fwd;
      if (dst.push(d)) {
        if (v == BNG_VERDICT_TX) r->stats.tx++;
        else r->stats.fwd++;
      } else {
        r->stats.tx_full++;
        recycle(r, d.addr);
      }
    } else if (v == BNG_VERDICT_PASS) {
      if (r->slow.push(d)) r->stats.slow++;
      else {
        r->stats.tx_full++;
        recycle(r, d.addr);
      }
    } else { /* DROP (and any unknown verdict fails closed) */
      r->stats.drop++;
      recycle(r, d.addr);
    }
  }
  r->inflight_n[head] = 0;
  r->inflight_head = (head + 1) % bng_ring::MAX_INFLIGHT;
  r->inflight_count--;
  return 0;
}

int bng_ring_tx_inject(bng_ring *r, const uint8_t *data, uint32_t len,
                       uint32_t flags) {
  if (len > r->frame_size) {
    r->stats.bad_desc++;
    return -1;
  }
  bng_desc d;
  if (!r->fill.pop(&d)) {
    r->stats.fill_empty++;
    return -1;
  }
  memcpy(r->umem + d.addr, data, len);
  d.len = len;
  d.flags = flags;
  if (!r->tx.push(d)) {
    r->stats.tx_full++;
    r->fill.push(d);
    return -1;
  }
  r->stats.tx++;
  return 0;
}

/* Descriptor-based output pops for the AF_XDP wire: the frame STAYS in
 * UMEM (the kernel reads it directly for TX); the caller returns it to
 * the fill pool with bng_ring_frame_free after the completion ring
 * reports it sent. The copying *_pop variants below remain for
 * non-UMEM consumers (slow path, tests). */
static int pop_desc_from(bng_ring *r, Ring &ring, uint64_t *addr,
                         uint32_t *len, uint32_t *flags) {
  bng_desc d;
  if (!ring.pop(&d)) return 0;
  (void)r;
  *addr = d.addr;
  *len = d.len;
  if (flags) *flags = d.flags;
  return 1;
}

int bng_ring_tx_pop_desc(bng_ring *r, uint64_t *addr, uint32_t *len,
                         uint32_t *flags) {
  return pop_desc_from(r, r->tx, addr, len, flags);
}
int bng_ring_fwd_pop_desc(bng_ring *r, uint64_t *addr, uint32_t *len,
                          uint32_t *flags) {
  return pop_desc_from(r, r->fwd, addr, len, flags);
}

uint32_t bng_ring_out_pop_desc_batch(bng_ring *r, uint64_t *addrs,
                                     uint32_t *lens, uint32_t cap) {
  uint32_t n = 0;
  bng_desc d;
  /* tx drains first, then fwd — the scalar pump's per-frame pop order */
  while (n < cap && r->tx.pop(&d)) {
    addrs[n] = d.addr;
    lens[n] = d.len;
    n++;
  }
  while (n < cap && r->fwd.pop(&d)) {
    addrs[n] = d.addr;
    lens[n] = d.len;
    n++;
  }
  return n;
}

int bng_ring_frame_free(bng_ring *r, uint64_t addr) {
  if (!valid_addr(r, addr)) {
    r->stats.bad_desc++;
    return -1;
  }
  bng_desc d{addr, 0, 0};
  r->fill.push(d);
  return 0;
}

static int pop_from(bng_ring *r, Ring &ring, uint8_t *buf, uint32_t cap,
                    uint32_t *flags) {
  bng_desc d;
  if (!ring.pop(&d)) return 0;
  int rc;
  if (d.len <= cap) {
    memcpy(buf, r->umem + d.addr, d.len);
    rc = static_cast<int>(d.len);
  } else {
    rc = -1;
  }
  if (flags) *flags = d.flags;
  recycle(r, d.addr);
  return rc;
}

int bng_ring_tx_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                    uint32_t *flags) {
  return pop_from(r, r->tx, buf, cap, flags);
}
int bng_ring_fwd_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                     uint32_t *flags) {
  return pop_from(r, r->fwd, buf, cap, flags);
}
int bng_ring_slow_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                      uint32_t *flags) {
  return pop_from(r, r->slow, buf, cap, flags);
}

uint32_t bng_ring_rx_pending(bng_ring *r) {
  uint32_t sum = 0;
  for (uint32_t s = 0; s < r->n_shards; s++) sum += r->rxq[s].pending();
  return sum;
}
uint32_t bng_ring_shard_rx_pending(bng_ring *r, uint32_t shard) {
  return shard < r->n_shards ? r->rxq[shard].pending() : 0;
}
uint32_t bng_ring_tx_pending(bng_ring *r) { return r->tx.pending(); }
uint32_t bng_ring_fwd_pending(bng_ring *r) { return r->fwd.pending(); }
uint32_t bng_ring_slow_pending(bng_ring *r) { return r->slow.pending(); }
uint32_t bng_ring_free_frames(bng_ring *r) { return r->fill.pending(); }

void bng_ring_get_stats(bng_ring *r, bng_ring_stats *out) {
  *out = r->stats;
}

/* Move up to budget frames per direction between two rings' output sides
 * and the peer's RX. TX and FWD both land on the peer wire (a loopback
 * cable has one far end). */
static uint32_t pump_dir(bng_ring *src, bng_ring *dst, uint32_t budget) {
  uint32_t moved = 0;
  bng_desc d;
  while (moved < budget) {
    bool got = src->tx.pop(&d);
    if (!got) got = src->fwd.pop(&d);
    if (!got) break;
    /* flags flip: frames leaving the access side arrive at the core side.
     * The stale direction-specific DHCP-control bit needs no handling
     * here: rx_submit clears and re-derives it authoritatively for every
     * submitted frame. */
    uint32_t fl = d.flags ^ BNG_DESC_F_FROM_ACCESS;
    bng_ring_rx_push(dst, src->umem + d.addr, d.len, fl);
    recycle(src, d.addr);
    moved++;
  }
  return moved;
}

int bng_wire_pump(bng_ring *a, bng_ring *b, uint32_t budget) {
  uint32_t m = pump_dir(a, b, budget);
  m += pump_dir(b, a, budget);
  return static_cast<int>(m);
}

uint32_t bng_abi_desc_size(void) { return sizeof(bng_desc); }
uint32_t bng_abi_desc_addr_off(void) { return offsetof(bng_desc, addr); }
uint32_t bng_abi_desc_len_off(void) { return offsetof(bng_desc, len); }
uint32_t bng_abi_desc_flags_off(void) { return offsetof(bng_desc, flags); }
uint32_t bng_abi_stats_size(void) { return sizeof(bng_ring_stats); }
uint32_t bng_abi_version(void) { return 3; }

} /* extern "C" */
