/* bngring — AF_XDP-style zero-copy packet ring for the TPU dataplane.
 *
 * This is the native host runtime the build plan calls for (SURVEY.md §7
 * "I/O: C++ host runtime implementing the AF_XDP zero-copy ring — the new
 * pkg/ebpf role"). The reference's pkg/ebpf loads BPF programs and talks to
 * kernel maps (pkg/ebpf/loader.go:74-661); here the "program" runs on the
 * TPU, so the native layer's job is moving frames:
 *
 *   NIC/driver -> UMEM frames -> RX ring -> batch assembler -> [B,L] buffer
 *       -> (TPU pipeline, Python/JAX) -> verdicts -> TX/forward/slow rings
 *
 * Layout mirrors AF_XDP (if_xdp.h): one UMEM frame area + descriptor
 * rings, power-of-two sized, lock-free.
 *
 * THREADING CONTRACT. The directional rings are SPSC — exactly one thread
 * per side:
 *
 *     ring   producer side                 consumer side
 *     rx     wire thread (rx_submit/push)  engine thread (batch_assemble)
 *     tx     engine thread (complete,      wire thread (tx_pop, wire_pump)
 *            tx_inject)
 *     fwd    engine thread (complete)      wire thread (fwd_pop, wire_pump)
 *     slow   engine thread (complete)      slow-path thread (slow_pop)
 *
 * The FILL pool is the exception: frame alloc/free crosses all three
 * threads (wire allocates + recycles rx-full rejects; engine frees drops
 * and allocates for tx_inject; slow-path recycles after slow_pop), so it
 * is a bounded MPMC ring (per-slot sequence numbers) and every API is
 * fill-safe from any thread. Single-threaded drivers (the Python engine
 * loop, tests) trivially satisfy the contract.
 *
 * The batch assembler writes frames into a caller-provided contiguous
 * [B, slot] buffer — the same buffer handed to jax.device_put — so the
 * only copy on the hot path is the unavoidable host->HBM DMA staging.
 * Verdict application (bng_batch_complete) is the XDP_TX / XDP_PASS /
 * TC_ACT_SHOT demux of the reference's hook returns (SURVEY.md §1 L0).
 *
 * C ABI throughout: consumed from Python via ctypes (no pybind11 in the
 * image) and from any future C++ driver (AF_XDP socket, DPDK port).
 */
#ifndef BNGRING_H
#define BNGRING_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Verdicts — must match bng_tpu/ops/pipeline.py VERDICT_*. */
enum bng_verdict {
  BNG_VERDICT_PASS = 0, /* slow path (XDP_PASS role) */
  BNG_VERDICT_DROP = 1, /* TC_ACT_SHOT role */
  BNG_VERDICT_TX = 2,   /* device-built reply out same port (XDP_TX role) */
  BNG_VERDICT_FWD = 3,  /* rewritten, forward out the other port */
};

/* Frame descriptor — the xdp_desc role (addr is a UMEM byte offset). */
typedef struct bng_desc {
  uint64_t addr;
  uint32_t len;
  uint32_t flags; /* bit0: from_access; bit1: DHCP control frame */
} bng_desc;

#define BNG_DESC_F_FROM_ACCESS 0x1u
/* Set by the ring on RX submit for ACCESS-SIDE frames that parse as
 * genuine DHCP: IPv4 non-fragment UDP dst:67 with BOOTREQUEST op and the
 * DHCP magic cookie (0-2 VLAN tags). The consumer may route an
 * all-control batch through the DHCP-only device program (the
 * reference's standalone-XDP hook order, where a DHCP reply never
 * traverses the TC chain); everything else keeps the fused pipeline's
 * NAT/antispoof/QoS treatment. */
#define BNG_DESC_F_DHCP_CTRL 0x2u

typedef struct bng_ring_stats {
  uint64_t rx;          /* frames assembled into batches */
  uint64_t tx;          /* TX verdict frames queued */
  uint64_t fwd;         /* FWD verdict frames queued */
  uint64_t drop;        /* DROP verdict frames recycled */
  uint64_t slow;        /* PASS verdict frames queued for slow path */
  uint64_t fill_empty;  /* producer stalls: no free frame in fill ring */
  uint64_t rx_full;     /* producer stalls: rx ring full */
  uint64_t tx_full;     /* tx/fwd/slow ring full -> frame dropped */
  uint64_t bad_desc;    /* descriptor validation failures */
} bng_ring_stats;

typedef struct bng_ring bng_ring; /* opaque */

/* ---- lifecycle ---- */

/* Create a ring pair over a private UMEM.
 * nframes, depth: power of two. frame_size: bytes per UMEM slot (>= 64). */
bng_ring *bng_ring_create(uint32_t nframes, uint32_t frame_size,
                          uint32_t depth);

/* Sharded variant: n_shards (1..64) per-shard RX queues of `depth` each.
 * rx_submit steers every frame to its owner shard (the pkg/pool/peer.go
 * owner-routing role, re-hosted at the host ring so each chip's batch is
 * its own subscribers' traffic — the placement invariant chip-local
 * NAT/QoS state depends on, bng_tpu/parallel/sharded.py).
 *
 * STEERING SPEC (bit-for-bit mirror: bng_tpu/runtime/ring.py shard_of):
 *   - DHCP control frames (BNG_DESC_F_DHCP_CTRL): FNV-1a32(src MAC) % n.
 *     Any shard is CORRECT for DHCP (tables are hash-sharded with
 *     all-to-all exchange); MAC keeps a subscriber's control traffic
 *     sticky for cache locality.
 *   - access-side IPv4: FNV-1a32(4 src-IP bytes, wire order) % n —
 *     the subscriber's private IP, matching the control plane's
 *     affinity placement of NAT/QoS/antispoof state.
 *   - network-side IPv4: public-IP exact-match table (set per shard via
 *     bng_ring_steer_pub_ip — downstream NAT state lives on the shard
 *     that owns the public IP); miss -> FNV-1a32(4 dst-IP bytes) % n.
 *   - access-side PPPoE session DATA (ethertype 0x8864, ver_type 0x11,
 *     code 0, PPP proto 0x0021, inner version 4): FNV-1a32(4 INNER
 *     src-IP bytes) % n — the decap'd packet's affinity key, so the
 *     chip-local PPPoE session/NAT/QoS state and the traffic meet.
 *     PPPoE control (discovery/LCP/auth/IPCP) falls to the MAC hash.
 *   - non-IPv4 / unparseable: FNV-1a32(src MAC) % n (len<14: shard 0).
 */
bng_ring *bng_ring_create_sharded(uint32_t nframes, uint32_t frame_size,
                                  uint32_t depth, uint32_t n_shards);
void bng_ring_destroy(bng_ring *r);

uint32_t bng_ring_n_shards(bng_ring *r);

/* Register a NAT public IP (host byte order) as owned by `shard`.
 * Bounded-probe open addressing; returns 0, or -1 when the map is full /
 * shard out of range. Updating an existing IP's shard is allowed. */
int bng_ring_steer_pub_ip(bng_ring *r, uint32_t ip, uint32_t shard);

/* Steering decision for a frame (exposed for parity tests and
 * non-UMEM producers). flags: the would-be descriptor flags AFTER
 * classification (FROM_ACCESS + DHCP_CTRL). */
uint32_t bng_ring_shard_of(bng_ring *r, const uint8_t *data, uint32_t len,
                           uint32_t flags);

/* Raw UMEM view (for tests / zero-copy producers). */
uint8_t *bng_ring_umem(bng_ring *r);
uint64_t bng_ring_umem_size(bng_ring *r);
uint32_t bng_ring_frame_size(bng_ring *r);

/* ---- producer side (driver / wire) ---- */

/* Push one frame: grabs a free UMEM slot, copies data, enqueues on RX.
 * Returns 0 on success, -1 if no free frame or RX full. */
int bng_ring_rx_push(bng_ring *r, const uint8_t *data, uint32_t len,
                     uint32_t flags);

/* Zero-copy producer path: reserve a free frame (returns UMEM offset or
 * UINT64_MAX), write into bng_ring_umem()+off, then submit. */
uint64_t bng_ring_rx_reserve(bng_ring *r);
int bng_ring_rx_submit(bng_ring *r, uint64_t addr, uint32_t len,
                       uint32_t flags);

/* ---- batch wire verbs (the vector wire pump, ISSUE 15) ----
 *
 * The AF_XDP pump moves frames in batches; these verbs make one ctypes
 * call cover what the scalar pump did per frame. Descriptors on this
 * path are HEADROOM-AWARE: the kernel reports chunk_base + headroom for
 * copy-mode RX, and rx_submit_batch accepts that address as-is (no
 * normalizing memmove) — the descriptor carries the offset address all
 * the way through assemble/complete/TX, and every fill-pool recycle
 * normalizes back to the chunk base. */

/* Pop up to n free frames into out_addrs. Counts ONE fill_empty when
 * the pool runs dry mid-batch (the scalar reserve loop's break counts
 * one per pump round). Returns frames reserved. */
uint32_t bng_ring_rx_reserve_batch(bng_ring *r, uint64_t *out_addrs,
                                   uint32_t n);

/* Submit n received frames (addr may carry a headroom offset inside its
 * chunk). Per frame: classify (access side), steer, enqueue. EVERY
 * failed frame returns to the fill pool (normalized to its chunk base):
 * rx-full counts stats.rx_full; a length that does not fit the chunk
 * room (frame_size - headroom) is dropped without a ring stat — the
 * scalar pump pre-validates the same way, so the two paths' pump_stats
 * agree. out_ok[i] = 1 submitted / 0 dropped. Returns count submitted.
 * An addr outside the UMEM counts bad_desc and cannot be recycled. */
uint32_t bng_ring_rx_submit_batch(bng_ring *r, const uint64_t *addrs,
                                  const uint32_t *lens, uint32_t flags,
                                  uint8_t *out_ok, uint32_t n);

/* Return n UMEM frames to the fill pool, each normalized to its chunk
 * base (kernel TX completions report the headroom-offset address that
 * was queued). Returns count freed; invalid addrs count bad_desc. */
uint32_t bng_ring_frame_free_batch(bng_ring *r, const uint64_t *addrs,
                                   uint32_t n);

/* Drain up to cap output descriptors — the tx ring first, then fwd
 * (the scalar pump's per-frame pop order) — into addrs/lens. Frames
 * stay in UMEM (zero-copy TX); recycle via frame_free_batch after the
 * kernel completion ring reports them. Returns count popped. */
uint32_t bng_ring_out_pop_desc_batch(bng_ring *r, uint64_t *addrs,
                                     uint32_t *lens, uint32_t cap);

/* ---- consumer side (TPU engine) ---- */

/* Pop up to max_batch RX frames into out[b*slot .. b*slot+len) and
 * out_len[b]/out_flags[b]; parks the popped descriptors in the in-flight
 * table. Frames longer than slot are truncated (slot bytes staged; full
 * frame stays in UMEM for TX-side use). Returns number of frames. */
uint32_t bng_batch_assemble(bng_ring *r, uint8_t *out, uint32_t *out_len,
                            uint32_t *out_flags, uint32_t max_batch,
                            uint32_t slot);

/* Sharded assemble: fixed per-shard lane ranges. Shard s's frames land
 * in rows [s*b_per_shard, s*b_per_shard + k_s); unfilled rows are zeroed
 * (len 0, flags 0) so the device pipeline sees invalid lanes (verdict
 * PASS) and complete() recycles nothing for them. The batch's row layout
 * matches ShardedCluster.step's contract (shard i's lanes at rows
 * i*b..(i+1)*b). Opens one in-flight window of n_shards*b_per_shard rows
 * — complete() must be called with n = n_shards*b_per_shard. Returns the
 * number of REAL frames staged (0 = nothing pending, no window opened). */
uint32_t bng_batch_assemble_sharded(bng_ring *r, uint8_t *out,
                                    uint32_t *out_len, uint32_t *out_flags,
                                    uint32_t b_per_shard, uint32_t slot);

/* Apply per-lane verdicts to the in-flight batch from the last assemble.
 * For TX/FWD lanes, rewritten bytes come from out[b*slot..] with
 * out_len[b] (device-rewritten packet); the frame is updated in UMEM and
 * queued on the tx/fwd ring. PASS lanes go to the slow ring; DROP lanes
 * are recycled to the fill pool. n must equal the last assemble count.
 * Returns 0, or -1 if no batch is in flight / n mismatch. */
int bng_batch_complete(bng_ring *r, const uint8_t *verdict,
                       const uint8_t *out, const uint32_t *out_len,
                       uint32_t n, uint32_t slot);

/* Inject a host-built frame onto the TX ring (slow-path replies: the
 * reference's Go server answers via its own socket, pkg/dhcp/server.go;
 * here replies leave through the same wire as device TX). Returns 0, or
 * -1 if no free frame / ring full. */
int bng_ring_tx_inject(bng_ring *r, const uint8_t *data, uint32_t len,
                       uint32_t flags);

/* Descriptor-based output pops for the AF_XDP wire: the frame stays in
 * UMEM (zero-copy TX); return it to the fill pool with
 * bng_ring_frame_free once the kernel's completion ring reports it
 * sent. Returns 1 with addr/len/flags filled, 0 when empty. */
int bng_ring_tx_pop_desc(bng_ring *r, uint64_t *addr, uint32_t *len,
                         uint32_t *flags);
int bng_ring_fwd_pop_desc(bng_ring *r, uint64_t *addr, uint32_t *len,
                          uint32_t *flags);
/* Return a UMEM frame to the fill pool (post-TX-completion, or an
 * unused rx_reserve). Returns 0, or -1 on an invalid address. */
int bng_ring_frame_free(bng_ring *r, uint64_t addr);

/* Drain one frame from the tx / fwd / slow ring into buf (cap bytes).
 * Returns frame length, 0 if empty, or -1 on truncation (frame bigger
 * than cap; frame is consumed). Recycles the UMEM frame. */
int bng_ring_tx_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                    uint32_t *flags);
int bng_ring_fwd_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                     uint32_t *flags);
int bng_ring_slow_pop(bng_ring *r, uint8_t *buf, uint32_t cap,
                      uint32_t *flags);

/* Pending counts (consumer-visible). rx_pending sums all shards;
 * shard_rx_pending reads one shard's queue. */
uint32_t bng_ring_rx_pending(bng_ring *r);
uint32_t bng_ring_shard_rx_pending(bng_ring *r, uint32_t shard);
uint32_t bng_ring_tx_pending(bng_ring *r);
uint32_t bng_ring_fwd_pending(bng_ring *r);
uint32_t bng_ring_slow_pending(bng_ring *r);
uint32_t bng_ring_free_frames(bng_ring *r);

void bng_ring_get_stats(bng_ring *r, bng_ring_stats *out);

/* ---- loopback wire (tests / demo) ----
 * Connect two rings so a's TX+FWD output is delivered into b's RX and
 * vice versa; bng_wire_pump moves up to budget frames per direction.
 * This is the stub-platform role of the reference's _stub.go backends
 * (SURVEY.md §4.6) — same API as a real port, memory transport. */
int bng_wire_pump(bng_ring *a, bng_ring *b, uint32_t budget);

/* ---- ABI self-description (layout tests, test/ebpf/maps_test.go role) */
uint32_t bng_abi_desc_size(void);
uint32_t bng_abi_desc_addr_off(void);
uint32_t bng_abi_desc_len_off(void);
uint32_t bng_abi_desc_flags_off(void);
uint32_t bng_abi_stats_size(void);
uint32_t bng_abi_version(void);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* BNGRING_H */
