/* bngxsk — AF_XDP socket scaffold for the zero-copy wire path.
 *
 * Role parity: the reference's loader picks its attach rung at runtime —
 * driver native mode, then generic/SKB mode, then a stub for dev boxes
 * (pkg/ebpf/loader.go:294-315 ladder). Here the same ladder applies to
 * the AF_XDP *socket* that feeds the TPU dataplane's ring:
 *
 *     rung 0  XDP_ZEROCOPY bind  — NIC DMAs straight into the UMEM the
 *                                  batch assembler stages to the TPU
 *     rung 1  XDP_COPY bind      — generic mode, one kernel copy
 *     rung 2  unavailable        — caller falls back to the in-memory
 *                                  bngring (tests, CI, TPU-only pods)
 *
 * No libbpf/libxdp in the image: UMEM registration, ring mmaps and the
 * bind are done with raw setsockopt/mmap against <linux/if_xdp.h>, which
 * is all AF_XDP actually needs (the library only adds convenience).
 * Everything degrades cleanly: on kernels/containers without AF_XDP
 * support (no CAP_NET_RAW, no NIC queue), open() reports the failed rung
 * and the Python side (bng_tpu/runtime/xsk.py) steps down the ladder.
 *
 * C ABI via ctypes, matching bngring.cpp's binding style.
 */
#include <cstring>
#include <new>

#ifdef __linux__
#include <errno.h>
#include <linux/if_xdp.h>
#include <net/if.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <unistd.h>
#endif

#include <stdint.h>

extern "C" {

/* ladder rungs (returned by bng_xsk_mode) */
enum bng_xsk_mode {
  BNG_XSK_ZEROCOPY = 0,
  BNG_XSK_COPY = 1,
  BNG_XSK_UNAVAILABLE = 2,
};

/* error codes from bng_xsk_open (negative) */
enum bng_xsk_err {
  BNG_XSK_E_SOCKET = -1,   /* socket(AF_XDP) failed: kernel/caps */
  BNG_XSK_E_UMEM = -2,     /* XDP_UMEM_REG rejected */
  BNG_XSK_E_RINGS = -3,    /* ring size setsockopts failed */
  BNG_XSK_E_MMAP = -4,     /* ring mmap failed */
  BNG_XSK_E_IFACE = -5,    /* interface does not exist */
  BNG_XSK_E_BIND = -6,     /* both zerocopy and copy binds failed */
};

struct bng_xsk {
#ifdef __linux__
  int fd = -1;
  int mode = BNG_XSK_UNAVAILABLE;
  uint32_t ifindex = 0;
  uint32_t queue = 0;
  /* mapped rings (producer/consumer pointers + descriptor arrays) */
  void *rx_map = nullptr, *tx_map = nullptr;
  void *fr_map = nullptr, *cr_map = nullptr;
  size_t rx_map_len = 0, tx_map_len = 0, fr_map_len = 0, cr_map_len = 0;
  uint32_t ring_size = 0;
  /* cached ring views */
  uint32_t *rx_prod = nullptr, *rx_cons = nullptr;
  xdp_desc *rx_ring = nullptr;
  uint32_t *tx_prod = nullptr, *tx_cons = nullptr;
  xdp_desc *tx_ring = nullptr;
  uint32_t *fr_prod = nullptr, *fr_cons = nullptr;
  uint64_t *fr_ring = nullptr;
  uint32_t *cr_prod = nullptr, *cr_cons = nullptr;
  uint64_t *cr_ring = nullptr;
#else
  int fd = -1;
  int mode = BNG_XSK_UNAVAILABLE;
#endif
};

/* Rung probe: can this kernel/container create an AF_XDP socket at all?
 * Cheap (one socket syscall), no interface needed. */
int bng_xsk_probe(void) {
#ifdef __linux__
  int fd = socket(AF_XDP, SOCK_RAW, 0);
  if (fd < 0) return BNG_XSK_UNAVAILABLE;
  close(fd);
  return BNG_XSK_COPY; /* socket works; bind mode resolved at open() */
#else
  return BNG_XSK_UNAVAILABLE;
#endif
}

#ifdef __linux__
static bool map_ring(int fd, uint64_t pgoff, size_t desc_size,
                     uint32_t entries, const xdp_ring_offset &off,
                     void **map, size_t *map_len, uint32_t **prod,
                     uint32_t **cons, void **ring) {
  size_t len = off.desc + static_cast<size_t>(entries) * desc_size;
  void *m = mmap(nullptr, len, PROT_READ | PROT_WRITE,
                 MAP_SHARED | MAP_POPULATE, fd, pgoff);
  if (m == MAP_FAILED) return false;
  *map = m;
  *map_len = len;
  *prod = reinterpret_cast<uint32_t *>(static_cast<uint8_t *>(m) + off.producer);
  *cons = reinterpret_cast<uint32_t *>(static_cast<uint8_t *>(m) + off.consumer);
  *ring = static_cast<uint8_t *>(m) + off.desc;
  return true;
}
#endif

/* Open an AF_XDP socket bound to ifname/queue over the caller's UMEM
 * (the bngring frame area — zero-copy through to the batch assembler).
 * Tries XDP_ZEROCOPY first, then XDP_COPY (the driver->generic ladder).
 * Returns a handle, or nullptr with *err set to the failed rung. */
bng_xsk *bng_xsk_open(const char *ifname, uint32_t queue, void *umem_area,
                      uint64_t umem_size, uint32_t frame_size,
                      uint32_t ring_size, int *err) {
#ifndef __linux__
  if (err) *err = BNG_XSK_E_SOCKET;
  (void)ifname; (void)queue; (void)umem_area; (void)umem_size;
  (void)frame_size; (void)ring_size;
  return nullptr;
#else
  auto fail = [&](int e, bng_xsk *s) -> bng_xsk * {
    if (err) *err = e;
    if (s) {
      /* unmap everything mapped so far — a retrying supervisor must not
       * accumulate ring mappings across failed opens */
      if (s->rx_map) munmap(s->rx_map, s->rx_map_len);
      if (s->tx_map) munmap(s->tx_map, s->tx_map_len);
      if (s->fr_map) munmap(s->fr_map, s->fr_map_len);
      if (s->cr_map) munmap(s->cr_map, s->cr_map_len);
      if (s->fd >= 0) close(s->fd);
      delete s;
    }
    return nullptr;
  };

  /* kernel UMEM constraints up front: page-aligned area, power-of-two
   * chunk in [2048, page]. bngring allocates page-aligned since r3; a
   * mismatched frame_size is a config error, not a bind-mode problem. */
  if ((reinterpret_cast<uint64_t>(umem_area) & 4095) != 0 ||
      frame_size < 2048 || frame_size > 4096 ||
      (frame_size & (frame_size - 1)) != 0)
    return fail(BNG_XSK_E_UMEM, nullptr);

  uint32_t ifindex = if_nametoindex(ifname);
  if (ifindex == 0) return fail(BNG_XSK_E_IFACE, nullptr);

  auto *s = new (std::nothrow) bng_xsk();
  if (!s) return fail(BNG_XSK_E_SOCKET, nullptr);
  s->fd = socket(AF_XDP, SOCK_RAW, 0);
  if (s->fd < 0) return fail(BNG_XSK_E_SOCKET, s);
  s->ifindex = ifindex;
  s->queue = queue;
  s->ring_size = ring_size;

  xdp_umem_reg reg{};
  reg.addr = reinterpret_cast<uint64_t>(umem_area);
  reg.len = umem_size;
  reg.chunk_size = frame_size;
  reg.headroom = 0;
  if (setsockopt(s->fd, SOL_XDP, XDP_UMEM_REG, &reg, sizeof(reg)) != 0)
    return fail(BNG_XSK_E_UMEM, s);

  if (setsockopt(s->fd, SOL_XDP, XDP_UMEM_FILL_RING, &ring_size,
                 sizeof(ring_size)) != 0 ||
      setsockopt(s->fd, SOL_XDP, XDP_UMEM_COMPLETION_RING, &ring_size,
                 sizeof(ring_size)) != 0 ||
      setsockopt(s->fd, SOL_XDP, XDP_RX_RING, &ring_size,
                 sizeof(ring_size)) != 0 ||
      setsockopt(s->fd, SOL_XDP, XDP_TX_RING, &ring_size,
                 sizeof(ring_size)) != 0)
    return fail(BNG_XSK_E_RINGS, s);

  xdp_mmap_offsets offs{};
  socklen_t optlen = sizeof(offs);
  if (getsockopt(s->fd, SOL_XDP, XDP_MMAP_OFFSETS, &offs, &optlen) != 0)
    return fail(BNG_XSK_E_RINGS, s);

  void *ring_ptr;
  if (!map_ring(s->fd, XDP_PGOFF_RX_RING, sizeof(xdp_desc), ring_size,
                offs.rx, &s->rx_map, &s->rx_map_len, &s->rx_prod,
                &s->rx_cons, &ring_ptr))
    return fail(BNG_XSK_E_MMAP, s);
  s->rx_ring = static_cast<xdp_desc *>(ring_ptr);
  if (!map_ring(s->fd, XDP_PGOFF_TX_RING, sizeof(xdp_desc), ring_size,
                offs.tx, &s->tx_map, &s->tx_map_len, &s->tx_prod,
                &s->tx_cons, &ring_ptr))
    return fail(BNG_XSK_E_MMAP, s);
  s->tx_ring = static_cast<xdp_desc *>(ring_ptr);
  if (!map_ring(s->fd, XDP_UMEM_PGOFF_FILL_RING, sizeof(uint64_t), ring_size,
                offs.fr, &s->fr_map, &s->fr_map_len, &s->fr_prod,
                &s->fr_cons, &ring_ptr))
    return fail(BNG_XSK_E_MMAP, s);
  s->fr_ring = static_cast<uint64_t *>(ring_ptr);
  if (!map_ring(s->fd, XDP_UMEM_PGOFF_COMPLETION_RING, sizeof(uint64_t),
                ring_size, offs.cr, &s->cr_map, &s->cr_map_len, &s->cr_prod,
                &s->cr_cons, &ring_ptr))
    return fail(BNG_XSK_E_MMAP, s);
  s->cr_ring = static_cast<uint64_t *>(ring_ptr);

  sockaddr_xdp sxdp{};
  sxdp.sxdp_family = AF_XDP;
  sxdp.sxdp_ifindex = ifindex;
  sxdp.sxdp_queue_id = queue;
  /* rung 0: zero-copy driver mode */
  sxdp.sxdp_flags = XDP_ZEROCOPY;
  if (bind(s->fd, reinterpret_cast<sockaddr *>(&sxdp), sizeof(sxdp)) == 0) {
    s->mode = BNG_XSK_ZEROCOPY;
    return s;
  }
  /* rung 1: generic copy mode */
  sxdp.sxdp_flags = XDP_COPY;
  if (bind(s->fd, reinterpret_cast<sockaddr *>(&sxdp), sizeof(sxdp)) == 0) {
    s->mode = BNG_XSK_COPY;
    return s;
  }
  return fail(BNG_XSK_E_BIND, s);
#endif
}

int bng_xsk_mode(bng_xsk *s) { return s ? s->mode : BNG_XSK_UNAVAILABLE; }
int bng_xsk_fd(bng_xsk *s) { return s ? s->fd : -1; }

void bng_xsk_close(bng_xsk *s) {
  if (!s) return;
#ifdef __linux__
  if (s->rx_map) munmap(s->rx_map, s->rx_map_len);
  if (s->tx_map) munmap(s->tx_map, s->tx_map_len);
  if (s->fr_map) munmap(s->fr_map, s->fr_map_len);
  if (s->cr_map) munmap(s->cr_map, s->cr_map_len);
  if (s->fd >= 0) close(s->fd);
#endif
  delete s;
}

#ifdef __linux__
/* Submit free frame addrs to the kernel fill ring. Returns count taken. */
uint32_t bng_xsk_fill(bng_xsk *s, const uint64_t *addrs, uint32_t n) {
  uint32_t prod = __atomic_load_n(s->fr_prod, __ATOMIC_RELAXED);
  uint32_t cons = __atomic_load_n(s->fr_cons, __ATOMIC_ACQUIRE);
  uint32_t free_slots = s->ring_size - (prod - cons);
  if (n > free_slots) n = free_slots;
  for (uint32_t i = 0; i < n; i++)
    s->fr_ring[(prod + i) & (s->ring_size - 1)] = addrs[i];
  __atomic_store_n(s->fr_prod, prod + n, __ATOMIC_RELEASE);
  return n;
}

/* Drain received descriptors: out_addrs/out_lens arrays of cap entries. */
uint32_t bng_xsk_rx(bng_xsk *s, uint64_t *out_addrs, uint32_t *out_lens,
                    uint32_t cap) {
  uint32_t cons = __atomic_load_n(s->rx_cons, __ATOMIC_RELAXED);
  uint32_t prod = __atomic_load_n(s->rx_prod, __ATOMIC_ACQUIRE);
  uint32_t n = prod - cons;
  if (n > cap) n = cap;
  for (uint32_t i = 0; i < n; i++) {
    const xdp_desc &d = s->rx_ring[(cons + i) & (s->ring_size - 1)];
    out_addrs[i] = d.addr;
    out_lens[i] = d.len;
  }
  __atomic_store_n(s->rx_cons, cons + n, __ATOMIC_RELEASE);
  return n;
}

/* Queue frames for transmit; kick with sendto. Returns count queued. */
uint32_t bng_xsk_tx(bng_xsk *s, const uint64_t *addrs, const uint32_t *lens,
                    uint32_t n) {
  uint32_t prod = __atomic_load_n(s->tx_prod, __ATOMIC_RELAXED);
  uint32_t cons = __atomic_load_n(s->tx_cons, __ATOMIC_ACQUIRE);
  uint32_t free_slots = s->ring_size - (prod - cons);
  if (n > free_slots) n = free_slots;
  for (uint32_t i = 0; i < n; i++) {
    xdp_desc &d = s->tx_ring[(prod + i) & (s->ring_size - 1)];
    d.addr = addrs[i];
    d.len = lens[i];
    d.options = 0;
  }
  __atomic_store_n(s->tx_prod, prod + n, __ATOMIC_RELEASE);
  if (n) sendto(s->fd, nullptr, 0, MSG_DONTWAIT, nullptr, 0);
  return n;
}

/* Reclaim completed TX frame addrs. */
uint32_t bng_xsk_complete(bng_xsk *s, uint64_t *out_addrs, uint32_t cap) {
  uint32_t cons = __atomic_load_n(s->cr_cons, __ATOMIC_RELAXED);
  uint32_t prod = __atomic_load_n(s->cr_prod, __ATOMIC_ACQUIRE);
  uint32_t n = prod - cons;
  if (n > cap) n = cap;
  for (uint32_t i = 0; i < n; i++)
    out_addrs[i] = s->cr_ring[(cons + i) & (s->ring_size - 1)];
  __atomic_store_n(s->cr_cons, cons + n, __ATOMIC_RELEASE);
  return n;
}
#else
uint32_t bng_xsk_fill(bng_xsk *, const uint64_t *, uint32_t) { return 0; }
uint32_t bng_xsk_rx(bng_xsk *, uint64_t *, uint32_t *, uint32_t) { return 0; }
uint32_t bng_xsk_tx(bng_xsk *, const uint64_t *, const uint32_t *, uint32_t) {
  return 0;
}
uint32_t bng_xsk_complete(bng_xsk *, uint64_t *, uint32_t) { return 0; }
#endif

} /* extern "C" */
