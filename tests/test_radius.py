"""RADIUS subsystem tests: codec crypto, client failover, accounting
spool/recovery, CoA processing (pkg/radius parity)."""

import hashlib
import struct

import pytest

from bng_tpu.control.radius import packet as rp
from bng_tpu.control.radius.accounting import AccountingManager
from bng_tpu.control.radius.client import AuthResult, RadiusClient, RadiusServerConfig
from bng_tpu.control.radius.coa import CoAProcessor, CoAServer
from bng_tpu.control.radius.packet import (
    RadiusPacket,
    decrypt_password,
    encrypt_password,
    new_request_authenticator,
)
from bng_tpu.control.radius.policy import DEFAULT_POLICIES, PolicyManager

SECRET = b"s3cr3t"


class FakeRadiusServer:
    """Wire-accurate in-memory RADIUS server (the httpmock role)."""

    def __init__(self, secret=SECRET, users=None, drop_first=0):
        self.secret = secret
        self.users = users or {}
        self.drop_first = drop_first
        self.requests = []

    def __call__(self, data, host, port, timeout):
        if self.drop_first > 0:
            self.drop_first -= 1
            return None
        req = RadiusPacket.decode(data)
        self.requests.append((host, port, req))
        if req.code == rp.ACCESS_REQUEST:
            user = req.get_str(rp.USER_NAME)
            entry = self.users.get(user)
            chap = req.get(rp.CHAP_PASSWORD)
            if chap is not None:
                # CHAP (RFC 2865 §2.2): octet 0 = ident, rest = MD5
                # response over (ident || secret || challenge)
                import hashlib

                challenge = req.get(rp.CHAP_CHALLENGE) or b""
                ok = entry is not None and chap[1:] == hashlib.md5(
                    chap[:1] + entry["password"].encode() + challenge
                ).digest()
            else:
                pw = decrypt_password(req.get(rp.USER_PASSWORD), self.secret,
                                      req.authenticator).decode()
                ok = entry is not None and entry["password"] == pw
            if ok:
                resp = RadiusPacket(rp.ACCESS_ACCEPT, req.id)
                for t, v in entry.get("attrs", []):
                    resp.add(t, v)
            else:
                resp = RadiusPacket(rp.ACCESS_REJECT, req.id)
                resp.add(rp.REPLY_MESSAGE, "bad credentials")
        elif req.code == rp.ACCOUNTING_REQUEST:
            resp = RadiusPacket(rp.ACCOUNTING_RESPONSE, req.id)
        else:
            return None
        return resp.encode(self.secret, request_auth=req.authenticator)


def make_client(server, **kw):
    return RadiusClient(
        [RadiusServerConfig("10.0.0.5", secret=SECRET, timeout_s=0.1, retries=2)],
        transport=server, **kw,
    )


class TestCodec:
    def test_password_roundtrip(self):
        auth = new_request_authenticator()
        for pw in (b"short", b"exactly16bytes!!", b"a much longer password than one block"):
            enc = encrypt_password(pw, SECRET, auth)
            assert len(enc) % 16 == 0
            assert decrypt_password(enc, SECRET, auth) == pw

    def test_packet_roundtrip(self):
        p = RadiusPacket(rp.ACCESS_REQUEST, 42, new_request_authenticator())
        p.add(rp.USER_NAME, "alice")
        p.add(rp.NAS_PORT, 7)
        raw = p.encode(SECRET)
        q = RadiusPacket.decode(raw)
        assert q.code == rp.ACCESS_REQUEST and q.id == 42
        assert q.get_str(rp.USER_NAME) == "alice"
        assert q.get_int(rp.NAS_PORT) == 7

    def test_accounting_request_authenticator(self):
        p = RadiusPacket(rp.ACCOUNTING_REQUEST, 9)
        p.add(rp.ACCT_SESSION_ID, "sess-1")
        raw = p.encode(SECRET)
        q = RadiusPacket.decode(raw)
        assert q.verify_request(SECRET, raw)
        # tampered packet fails
        bad = bytearray(raw)
        bad[-1] ^= 0xFF
        q2 = RadiusPacket.decode(bytes(bad))
        assert not q2.verify_request(SECRET, bytes(bad))

    def test_message_authenticator_present(self):
        p = RadiusPacket(rp.ACCESS_REQUEST, 1, new_request_authenticator())
        p.add(rp.USER_NAME, "bob")
        raw = p.encode(SECRET, sign_message_authenticator=True)
        q = RadiusPacket.decode(raw)
        ma = q.get(rp.MESSAGE_AUTHENTICATOR)
        assert ma is not None and len(ma) == 16 and ma != b"\x00" * 16


class TestClient:
    def test_authenticate_accept_with_attributes(self):
        server = FakeRadiusServer(users={"alice": {
            "password": "pw123",
            "attrs": [(rp.FRAMED_IP_ADDRESS, 0x0A000042),
                      (rp.SESSION_TIMEOUT, 3600),
                      (rp.FILTER_ID, "residential-100mbps")],
        }})
        c = make_client(server)
        r = c.authenticate("alice", "pw123", mac=bytes.fromhex("02deadbeef01"))
        assert r is not None and r.success
        assert r.framed_ip == 0x0A000042
        assert r.session_timeout == 3600
        assert r.policy_name == "residential-100mbps"
        assert c.stats["auth_ok"] == 1
        # calling-station-id formatting
        _, _, req = server.requests[0]
        assert req.get_str(rp.CALLING_STATION_ID) == "02-DE-AD-BE-EF-01"

    def test_reject(self):
        server = FakeRadiusServer(users={"alice": {"password": "right"}})
        c = make_client(server)
        r = c.authenticate("alice", "wrong")
        assert r is not None and not r.success
        assert c.stats["auth_reject"] == 1

    def test_timeout_returns_none(self):
        c = make_client(lambda *a: None)
        assert c.authenticate("alice", "pw") is None
        assert c.stats["auth_timeout"] == 1

    def test_retry_then_success(self):
        server = FakeRadiusServer(users={"a": {"password": "p"}}, drop_first=1)
        c = make_client(server)
        r = c.authenticate("a", "p")
        assert r is not None and r.success

    def test_failover_to_second_server(self):
        calls = []

        def transport(data, host, port, timeout):
            calls.append(host)
            if host == "10.0.0.5":
                return None  # primary dead
            return FakeRadiusServer(users={"a": {"password": "p"}})(data, host, port, timeout)

        c = RadiusClient([
            RadiusServerConfig("10.0.0.5", secret=SECRET, timeout_s=0.01, retries=2),
            RadiusServerConfig("10.0.0.6", secret=SECRET, timeout_s=0.01, retries=2),
        ], transport=transport)
        r = c.authenticate("a", "p")
        assert r is not None and r.success
        assert c.stats["failovers"] == 1
        assert "10.0.0.6" in calls

    def test_accounting_start_stop(self):
        server = FakeRadiusServer()
        c = make_client(server)
        assert c.send_accounting("sess-1", rp.ACCT_START, username="a", framed_ip=1)
        assert c.send_accounting("sess-1", rp.ACCT_STOP, session_time=10,
                                 input_octets=1000, output_octets=2000,
                                 terminate_cause=rp.TERM_USER_REQUEST)
        acct = [r for _, _, r in server.requests if r.code == rp.ACCOUNTING_REQUEST]
        assert len(acct) == 2
        assert acct[0].get_int(rp.ACCT_STATUS_TYPE) == rp.ACCT_START
        assert acct[1].get_int(rp.ACCT_SESSION_TIME) == 10


class TestAccountingManager:
    def test_interim_and_stop(self):
        t = [1000.0]
        server = FakeRadiusServer()
        c = make_client(server, clock=lambda: t[0])
        m = AccountingManager(c, interim_interval_s=300, clock=lambda: t[0])
        m.start("s1", "alice", 0x0A000001)
        assert m.interim_tick() == 0  # not due yet
        t[0] += 301
        m.update_counters("s1", 111, 222)
        assert m.interim_tick() == 1
        t[0] += 100
        assert m.stop("s1")
        types = [r.get_int(rp.ACCT_STATUS_TYPE) for _, _, r in server.requests]
        assert types == [rp.ACCT_START, rp.ACCT_INTERIM, rp.ACCT_STOP]

    def test_offline_queue_and_retry(self):
        server_up = [False]
        real = FakeRadiusServer()

        def transport(*a):
            return real(*a) if server_up[0] else None

        c = RadiusClient([RadiusServerConfig("h", secret=SECRET, timeout_s=0.01, retries=1)],
                         transport=transport)
        m = AccountingManager(c)
        m.start("s1", "a", 1)
        m.stop("s1")
        assert len(m.pending) == 2  # start + stop both queued
        server_up[0] = True
        assert m.retry_tick() == 2
        assert m.pending == []

    def test_orphan_recovery_from_spool(self, tmp_path):
        spool = str(tmp_path / "acct.json")
        server = FakeRadiusServer()
        c = make_client(server)
        m = AccountingManager(c, spool_path=spool)
        m.start("s1", "alice", 5)
        # simulate crash: new manager over same spool
        m2 = AccountingManager(make_client(server), spool_path=spool)
        stops = [p for p in m2.pending if p.status == rp.ACCT_STOP]
        assert len(stops) == 1
        assert stops[0].payload["terminate_cause"] == rp.TERM_LOST_CARRIER
        assert m2.retry_tick() == 1


class TestCoA:
    def _processor(self):
        sessions = {"sess-1": type("S", (), {"ip": 0x0A000001, "mac": "02-AA"})()}
        applied = []
        disconnected = []
        proc = CoAProcessor(
            find_by_session_id=sessions.get,
            find_by_ip=lambda ip: next((s for s in sessions.values() if s.ip == ip), None),
            qos_update=lambda ip, pol: applied.append((ip, pol)) or True,
            disconnect=lambda s: disconnected.append(s) or True,
            policy_manager=PolicyManager(),
        )
        return proc, applied, disconnected

    def test_coa_policy_change(self):
        proc, applied, _ = self._processor()
        srv = CoAServer(SECRET, proc)
        req = RadiusPacket(rp.COA_REQUEST, 5)
        req.add(rp.ACCT_SESSION_ID, "sess-1")
        req.add(rp.FILTER_ID, "business-100mbps")
        raw = req.encode(SECRET)
        resp_raw = srv.handle_raw(raw)
        resp = RadiusPacket.decode(resp_raw)
        assert resp.code == rp.COA_ACK
        assert applied == [(0x0A000001, "business-100mbps")]

    def test_coa_unknown_policy_naks(self):
        proc, applied, _ = self._processor()
        srv = CoAServer(SECRET, proc)
        req = RadiusPacket(rp.COA_REQUEST, 6)
        req.add(rp.ACCT_SESSION_ID, "sess-1")
        req.add(rp.FILTER_ID, "no-such-policy")
        resp = RadiusPacket.decode(srv.handle_raw(req.encode(SECRET)))
        assert resp.code == rp.COA_NAK
        assert applied == []

    def test_disconnect(self):
        proc, _, disconnected = self._processor()
        srv = CoAServer(SECRET, proc)
        req = RadiusPacket(rp.DISCONNECT_REQUEST, 7)
        req.add(rp.ACCT_SESSION_ID, "sess-1")
        resp = RadiusPacket.decode(srv.handle_raw(req.encode(SECRET)))
        assert resp.code == rp.DISCONNECT_ACK
        assert len(disconnected) == 1

    def test_bad_authenticator_dropped(self):
        proc, _, _ = self._processor()
        srv = CoAServer(SECRET, proc)
        req = RadiusPacket(rp.COA_REQUEST, 8)
        req.add(rp.ACCT_SESSION_ID, "sess-1")
        raw = bytearray(req.encode(b"wrong-secret"))
        assert srv.handle_raw(bytes(raw)) is None
        assert srv.stats["bad_auth"] == 1


class TestPolicies:
    def test_defaults_present(self):
        pm = PolicyManager()
        p = pm.get("residential-100mbps")
        assert p and p.download_bps == 100_000_000 and p.upload_bps == 20_000_000

    def test_radius_attr_resolution(self):
        pm = PolicyManager()
        assert pm.from_radius_attributes(filter_id="business-1gbps").priority == 2
        adhoc = pm.from_radius_attributes(vendor_rate_down=5_000_000, vendor_rate_up=1_000_000)
        assert adhoc.download_bps == 5_000_000
        assert pm.from_radius_attributes(filter_id="nope") is None


class TestCHAPAuth:
    """authenticate_chap + the PPPoE RadiusVerifier bridge (auth.go's
    RADIUS mode: CHAP-Password/CHAP-Challenge Access-Requests)."""

    def test_chap_accept_and_reject(self):
        import hashlib

        srv = FakeRadiusServer(users={"alice": {"password": "pw123", "attrs": [
            (rp.FRAMED_IP_ADDRESS, 0x0A000042), (rp.FILTER_ID, "gold")]}})
        client = make_client(srv)
        challenge = b"C" * 16
        good = hashlib.md5(bytes([7]) + b"pw123" + challenge).digest()
        res = client.authenticate_chap("alice", 7, challenge, good)
        assert res is not None and res.success
        assert res.framed_ip == 0x0A000042 and res.policy_name == "gold"
        # wire shape: CHAP-Password = ident byte + response
        _, _, req = srv.requests[-1]
        assert req.get(rp.CHAP_PASSWORD) == bytes([7]) + good
        assert req.get(rp.CHAP_CHALLENGE) == challenge

        bad = client.authenticate_chap("alice", 7, challenge, b"x" * 16)
        assert bad is not None and not bad.success

    def test_pppoe_radius_verifier(self):
        """CredentialVerifier protocol over the RADIUS client: what the
        composition root installs when both PPPoE and RADIUS are on."""
        from bng_tpu.control.pppoe.auth import RadiusVerifier, chap_md5

        srv = FakeRadiusServer(users={"bob": {"password": "s3cret", "attrs": [
            (rp.SESSION_TIMEOUT, 1800)]}})
        v = RadiusVerifier(make_client(srv))

        res = v.verify_pap("bob", b"s3cret")
        assert res.ok and res.attributes["session_timeout"] == 1800
        assert not v.verify_pap("bob", b"wrong").ok

        ch = b"Z" * 16
        ok = v.verify_chap("bob", 3, ch, chap_md5(3, b"s3cret", ch))
        assert ok.ok and ok.username == "bob"
        assert not v.verify_chap("bob", 3, ch, b"n" * 16).ok

    def test_chap_timeout_fails_closed(self):
        srv = FakeRadiusServer(drop_first=99)
        client = make_client(srv)
        assert client.authenticate_chap("x", 1, b"c" * 16, b"r" * 16) is None
        from bng_tpu.control.pppoe.auth import RadiusVerifier

        res = RadiusVerifier(client).verify_chap("x", 1, b"c" * 16, b"r" * 16)
        assert not res.ok and "timeout" in res.reason
