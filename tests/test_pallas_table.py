"""Fused Pallas table-probe kernel (ISSUE 11): bit-exactness, impl
dispatch, lowering-gate coverage, and the no-narrow-gather HLO pin.

The contract: `pallas_lookup == xla_lookup == lookup_batch_host`
bit-for-bit across every table geometry the repo ships (DHCP sub/vlan/
cid, NAT sessions/reverse, stash-heavy, stash-free, empty, and the
1M-subscriber geometry at reduced nbuckets) — in interpret mode on CPU
so tier-1 proves the kernel without hardware. Mosaic lowering itself is
gated by runtime/verify.py on the chip (tpu_run.sh A/B step).
"""

import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from bng_tpu.ops import table as table_mod
from bng_tpu.ops.pallas_table import pallas_lookup, pallas_probe
from bng_tpu.ops.table import HostTable, device_lookup, xla_lookup

pytestmark = pytest.mark.kernels


def build_table(nbuckets, K, V, stash, n_entries, seed):
    rng = np.random.default_rng(seed)
    t = HostTable(nbuckets, K, V, stash=stash, name="t")
    keys = rng.integers(0, 2**32, size=(n_entries, K), dtype=np.uint32)
    keys = np.unique(keys, axis=0)
    vals = rng.integers(0, 2**32, size=(len(keys), V), dtype=np.uint32)
    for i in range(len(keys)):
        t.insert(keys[i], vals[i])
    return t, keys


def query_mix(keys, K, B, seed, miss_frac=0.3):
    """Hits + misses + in-batch duplicates."""
    rng = np.random.default_rng(seed + 1)
    if len(keys):
        q = keys[rng.integers(0, len(keys), B)].copy()
    else:
        q = np.zeros((B, K), np.uint32)
    miss = rng.random(B) < miss_frac
    q[miss] = rng.integers(0, 2**32, size=(int(miss.sum()), K),
                           dtype=np.uint32)
    return q


# every table geometry the repo ships, plus the edge shapes:
#   (nbuckets, K, V, stash, n_entries, B)
GEOMETRIES = [
    pytest.param(1 << 8, 2, 8, 64, 200, 256, id="dhcp-sub"),
    pytest.param(1 << 6, 1, 8, 64, 100, 64, id="vlan-small-batch"),
    pytest.param(1 << 6, 8, 8, 64, 100, 300, id="cid-k8-kw16"),
    pytest.param(1 << 8, 4, 16, 64, 300, 512, id="nat-sessions-v16"),
    pytest.param(1 << 8, 4, 8, 64, 300, 512, id="nat-reverse-v8"),
    pytest.param(1 << 3, 2, 8, 32, 38, 128, id="overfull-stash-hits"),
    pytest.param(1 << 8, 2, 8, 0, 100, 128, id="no-stash"),
    pytest.param(1 << 6, 2, 8, 64, 0, 128, id="empty-table"),
    # the 1M-subscriber sub-table geometry (K=2, V=8, stash=256) at
    # reduced nbuckets — same shapes/dtypes, CI-sized population
    pytest.param(1 << 12, 2, 8, 256, 6000, 1024, id="1m-geometry-reduced"),
]


class TestBitExactness:
    @pytest.mark.parametrize("nbuckets,K,V,stash,n,B", GEOMETRIES)
    def test_pallas_equals_xla_equals_host(self, nbuckets, K, V, stash,
                                           n, B):
        t, keys = build_table(nbuckets, K, V, stash, n, seed=nbuckets + K)
        state = t.device_state()
        q = query_mix(keys, K, B, seed=nbuckets)
        qd = jnp.asarray(q)

        ref = xla_lookup(state, qd, nbuckets, stash)
        got = pallas_lookup(state, qd, nbuckets, stash, interpret=True)
        assert np.array_equal(np.asarray(got.found), np.asarray(ref.found))
        assert np.array_equal(np.asarray(got.slot), np.asarray(ref.slot))
        assert np.array_equal(np.asarray(got.vals), np.asarray(ref.vals))
        # and both agree with the host-authoritative mirror
        hv = t.lookup_batch_host(q)
        rf = np.asarray(ref.found)
        assert np.array_equal(
            np.where(rf[:, None], np.asarray(ref.vals), 0), hv)

    def test_stash_geometry_actually_exercises_stash(self):
        """The overfull geometry must place entries in the stash, or the
        stash-broadcast path of the kernel is untested."""
        t, _ = build_table(1 << 3, 2, 8, 32, 38, seed=10)
        assert int(np.count_nonzero(
            np.asarray(t.device_state().stash_rows)[:, 2])) > 0

    def test_nonaligned_batch_padding(self):
        """B not a multiple of the lane tile: pad lanes never leak."""
        t, keys = build_table(1 << 6, 2, 8, 64, 80, seed=3)
        state = t.device_state()
        for B in (7, 129):  # below one tile / straddling two
            q = jnp.asarray(query_mix(keys, 2, B, seed=B))
            ref = xla_lookup(state, q, t.nbuckets, t.stash)
            got = pallas_lookup(state, q, t.nbuckets, t.stash,
                                interpret=True)
            assert np.array_equal(np.asarray(got.found),
                                  np.asarray(ref.found)), B
            assert np.array_equal(np.asarray(got.vals),
                                  np.asarray(ref.vals)), B


class TestImplDispatch:
    def test_device_lookup_dispatches_by_impl(self, monkeypatch):
        t, keys = build_table(1 << 6, 2, 8, 64, 60, seed=4)
        state = t.device_state()
        q = jnp.asarray(keys[:32])
        with table_mod.forced_impl("pallas"):
            via_pallas = device_lookup(state, q, t.nbuckets, t.stash)
        with table_mod.forced_impl("xla"):
            via_xla = device_lookup(state, q, t.nbuckets, t.stash)
        assert np.array_equal(np.asarray(via_pallas.vals),
                              np.asarray(via_xla.vals))

    def test_resolution_rules(self, monkeypatch):
        monkeypatch.setattr(table_mod, "TABLE_IMPL", "pallas")
        assert table_mod.resolved_table_impl() == "pallas"
        monkeypatch.setattr(table_mod, "TABLE_IMPL", "auto")
        # un-raced auto off-TPU -> xla (Mosaic is TPU-only)
        assert table_mod.resolved_table_impl() == "xla"
        table_mod.set_auto_choice("pallas")
        try:
            assert table_mod.resolved_table_impl() == "pallas"
        finally:
            table_mod.set_auto_choice(None)
        monkeypatch.setattr(table_mod, "TABLE_IMPL", "bogus")
        with pytest.raises(ValueError):
            table_mod.resolved_table_impl()
        # current_impl_label never raises (fingerprints call it)
        assert table_mod.current_impl_label() == "bogus"

    def test_forced_impl_wins_and_unwinds(self, monkeypatch):
        monkeypatch.setattr(table_mod, "TABLE_IMPL", "xla")
        with table_mod.forced_impl("pallas"):
            assert table_mod.resolved_table_impl() == "pallas"
        assert table_mod.resolved_table_impl() == "xla"
        with pytest.raises(ValueError):
            with table_mod.forced_impl("nope"):
                pass

    def test_engine_snapshots_impl_per_program(self, monkeypatch):
        """Engine construction pins the impl into its jit-cache keys —
        two engines under different impls coexist in one process."""
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        def mk():
            fp = FastPathTables(sub_nbuckets=1 << 8, vlan_nbuckets=64,
                                cid_nbuckets=64, max_pools=4)
            fp.set_server_config(bytes.fromhex("02aabbccdd01"),
                                 ip_to_u32("10.0.0.1"))
            nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                             sessions_nbuckets=1 << 8,
                             sub_nat_nbuckets=1 << 8)
            return Engine(fp, nat, batch_size=32, pkt_slot=512)

        monkeypatch.setattr(table_mod, "TABLE_IMPL", "pallas")
        e_pallas = mk()
        monkeypatch.setattr(table_mod, "TABLE_IMPL", "xla")
        e_xla = mk()
        assert e_pallas.table_impl == "pallas"
        assert e_xla.table_impl == "xla"
        assert e_pallas._step is not e_xla._step


class TestEndToEnd:
    def test_dora_offer_through_pallas_engine(self, monkeypatch):
        """A cached DISCOVER answered on-device with the Pallas probe
        compiled into the DHCP express program (donated chain + aliased
        packet batch) — the whole OFFER path, not just the lookup."""
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        monkeypatch.setattr(table_mod, "TABLE_IMPL", "pallas")
        fp = FastPathTables(sub_nbuckets=1 << 8, vlan_nbuckets=64,
                            cid_nbuckets=64, max_pools=4)
        fp.set_server_config(bytes.fromhex("02aabbccdd01"),
                             ip_to_u32("10.0.0.1"))
        fp.add_pool(1, ip_to_u32("10.0.0.0"), 16, ip_to_u32("10.0.0.1"),
                    ip_to_u32("1.1.1.1"), ip_to_u32("8.8.8.8"), 86400)
        mac = bytes.fromhex("02b700000001")
        fp.add_subscriber(mac, 1, ip_to_u32("10.0.0.42"),
                          lease_expiry=2_000_000_000)
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=1 << 8, sub_nat_nbuckets=1 << 8)
        eng = Engine(fp, nat, batch_size=32, pkt_slot=512)
        assert eng.table_impl == "pallas"
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=0x1234)
        frame = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                   p.encode().ljust(300, b"\x00"))
        out = eng.process_dhcp([frame], now=1_900_000_000)
        assert len(out["tx"]) == 1
        reply = dhcp_codec.decode(out["tx"][0][1][42:])
        assert reply.yiaddr == ip_to_u32("10.0.0.42")


class TestLoweringGateCoverage:
    def test_verify_checks_cover_the_kernel(self):
        """runtime/verify.py carries the new programs: the interp
        variant runs on every backend, the compiled variant is
        TPU-gated (the acceptance criterion's lowering-gate half)."""
        from bng_tpu.runtime.verify import CHECKS

        by_name = {n: tpu_only for n, _, tpu_only in CHECKS}
        assert by_name["table_lookup[xla]"] is False
        assert by_name["table_lookup[pallas-interp]"] is False
        assert by_name["table_lookup[pallas]"] is True
        assert by_name["dhcp_express[pallas]"] is True

    # (the CPU compile of the non-TPU checks — incl. pallas interpret and
    # the donated express program — already runs in tier-1 via
    # test_tpu_lowering.py::test_gate_harness_compiles_on_any_backend;
    # re-compiling the whole set here would double ~40s of tier-1 wall)


class TestHLOPins:
    def _hlo(self, impl):
        t, keys = build_table(1 << 10, 2, 8, 64, 500, seed=6)
        state = t.device_state()
        q = jnp.asarray(keys[:256])

        def look(state, q):
            with table_mod.forced_impl(impl):
                r = device_lookup(state, q, t.nbuckets, t.stash)
            return r.found, r.slot, r.vals

        return jax.jit(look).lower(state, q).as_text()

    def test_pallas_path_emits_no_narrow_gathers(self):
        """The acceptance pin: the Pallas program contains NO narrow
        (<8-words-per-row) stablehlo gather — the probe data moves by
        DMA, not by the §2 serialization shape. (Interpret-mode
        lowering is the CPU stand-in; the Mosaic binary has no XLA
        gathers at all.)"""
        hlo = self._hlo("pallas")
        for m in re.finditer(r"slice_sizes = array<i64: ([0-9, ]+)>", hlo):
            dims = [int(x) for x in m.group(1).split(",")]
            assert dims[-1] == 1 or dims[-1] >= 8, (
                f"narrow gather rows {dims} in pallas path")
        # and the wide row-probe gathers of the XLA cascade are gone
        assert re.search(r"slice_sizes = array<i64: 1, 32>", hlo) is None

    def test_xla_path_keeps_wide_probe_shape(self):
        """The XLA cascade still probes via 2 packed [1,32] row gathers
        (the test_hlo_structure contract, re-pinned here so an impl
        regression is attributable)."""
        hlo = self._hlo("xla")
        assert len(re.findall(r"slice_sizes = array<i64: 1, 32>", hlo)) == 2


class TestShardedPallas:
    @pytest.mark.slow  # a second mesh-program compile (~30 s on CPU)
    def test_sharded_cluster_pins_impl_and_steps(self, monkeypatch):
        """The sharded step traces the Pallas probe under shard_map (the
        fifth hot-path surface ISSUE 11 names): a DHCP DISCOVER batch
        over a 1-shard CPU mesh answers on-device under the kernel."""
        from bng_tpu.parallel.sharded import ShardedCluster
        from bng_tpu.utils.net import ip_to_u32
        from bng_tpu.control import dhcp_codec, packets

        monkeypatch.setattr(table_mod, "TABLE_IMPL", "pallas")
        cl = ShardedCluster(n_shards=1, batch_per_shard=32,
                            sub_nbuckets=1 << 8)
        assert cl.table_impl == "pallas"
        cl.set_server_config_all(bytes.fromhex("02aabbccdd01"),
                                 ip_to_u32("10.0.0.1"))
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 16,
                        ip_to_u32("10.0.0.1"), lease_time=86400)
        mac = bytes.fromhex("02b700000002")
        cl.add_subscriber(mac, pool_id=1, ip=ip_to_u32("10.0.0.43"),
                          lease_expiry=2_000_000_000)
        cl.sync_tables()
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=0x77)
        frame = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                   p.encode().ljust(300, b"\x00"))
        pkt = np.zeros((32, 512), dtype=np.uint8)
        length = np.zeros((32,), dtype=np.uint32)
        pkt[0, : len(frame)] = np.frombuffer(frame, dtype=np.uint8)
        length[0] = len(frame)
        out = cl.step(pkt, length, np.ones((32,), dtype=bool),
                      1_900_000_000, 0)
        assert int(np.asarray(out["verdict"])[0]) == 2  # VERDICT_TX


class TestWidenedRowCheckpointCompat:
    """The ISSUE 11 row widenings (nat reverse 4->8, pppoe 6->8) must not
    cold-start pre-upgrade checkpoints: a declared pure-pad historical
    width restores with the value rows zero-padded; anything undeclared
    still rejects (reject-on-mismatch is the default)."""

    def test_narrow_checkpoint_pads_into_widened_table(self):
        old = HostTable(1 << 5, 4, 4, stash=8, name="nat_reverse")
        key = np.arange(4, dtype=np.uint32)
        old.insert(key, np.asarray([9, 8, 7, 6], dtype=np.uint32))
        arrays = {k: v.copy() for k, v in old.checkpoint_arrays().items()}
        geom = old.checkpoint_geom()

        new = HostTable(1 << 5, 4, 8, stash=8, name="nat_reverse",
                        compat_val_pad_from=(4,))
        assert new.restore_arrays(arrays, geom) == 1
        got = new.lookup(key)
        assert got is not None
        assert list(got) == [9, 8, 7, 6, 0, 0, 0, 0]

    def test_undeclared_width_still_rejects(self):
        old = HostTable(1 << 5, 4, 4, stash=8, name="t")
        arrays = old.checkpoint_arrays()
        geom = old.checkpoint_geom()
        new = HostTable(1 << 5, 4, 8, stash=8, name="t")  # no compat decl
        with pytest.raises(ValueError):
            new.restore_arrays(arrays, geom)

    def test_live_nat_and_pppoe_tables_declare_compat(self):
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.tables import PPPoEFastPathTables
        from bng_tpu.utils.net import ip_to_u32

        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=1 << 8, sub_nat_nbuckets=1 << 8)
        assert nat.reverse.compat_val_pad_from == (4,)
        pp = PPPoEFastPathTables(nbuckets=1 << 8)
        assert pp.by_sid.compat_val_pad_from == (6,)
        assert pp.by_ip.compat_val_pad_from == (6,)


class TestRawProbe:
    def test_probe_slot_values_match_host_placement(self):
        """slot indices agree with the host mirror's physical placement
        (the device-authoritative writers — NAT accounting — scatter by
        these slots, so they must be placement-exact, not just
        found-consistent)."""
        t, keys = build_table(1 << 5, 2, 8, 16, 100, seed=8)
        state = t.device_state()
        q = jnp.asarray(keys[:64])
        found, slot, _ = pallas_probe(state.krows, state.stash_rows,
                                      state.vals, q, t.nbuckets, t.stash,
                                      interpret=True)
        for i in range(64):
            assert bool(np.asarray(found)[i])
            assert int(np.asarray(slot)[i]) == t._find_slot(keys[i])
