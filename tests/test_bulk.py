"""Bulk table-build paths (reference scale: 1M subscribers, bpf/maps.h:10).

Round-1 verdict: the per-subscriber Python insert loop made 1M infeasible;
these tests pin the vectorized bulk paths to the per-key semantics.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from bng_tpu.control.nat import NATManager
from bng_tpu.ops.table import HostTable, device_lookup
from bng_tpu.runtime.tables import FastPathTables
from bng_tpu.utils.net import ip_to_u32

NOW = 1_753_000_000


class TestHostTableBulkInsert:
    def test_matches_per_key_insert(self):
        nb = 1 << 10
        a = HostTable(nb, key_words=2, val_words=3, stash=64, name="a")
        b = HostTable(nb, key_words=2, val_words=3, stash=64, name="b")
        n = 1500
        keys = np.stack([np.arange(n, dtype=np.uint32),
                         np.arange(n, dtype=np.uint32) * 13 + 7], axis=1)
        vals = np.stack([np.arange(n, dtype=np.uint32)] * 3, axis=1)
        a.bulk_insert(keys, vals)
        for i in range(n):
            b.insert(keys[i], vals[i])
        assert a.count == b.count == n
        # every key resolves to the same value through both tables
        got_a = a.lookup_batch_host(keys)
        got_b = b.lookup_batch_host(keys)
        np.testing.assert_array_equal(got_a, vals)
        np.testing.assert_array_equal(got_b, vals)

    def test_device_lookup_agreement(self):
        nb = 1 << 12
        t = HostTable(nb, key_words=2, val_words=4, stash=128, name="d")
        n = 6000
        keys = np.stack([np.arange(n, dtype=np.uint32) + 5,
                         np.arange(n, dtype=np.uint32) * 3], axis=1)
        vals = np.tile(np.arange(n, dtype=np.uint32)[:, None], (1, 4))
        t.bulk_insert(keys, vals)
        res = device_lookup(t.device_state(), jnp.asarray(keys), nb, 128)
        assert bool(res.found.all())
        np.testing.assert_array_equal(np.asarray(res.vals), vals)
        # misses stay misses
        missk = np.stack([np.arange(64, dtype=np.uint32) + 1_000_000,
                          np.zeros(64, dtype=np.uint32)], axis=1)
        res2 = device_lookup(t.device_state(), jnp.asarray(missk), nb, 128)
        assert not bool(res2.found.any())

    def test_large_bulk_requires_full_upload(self):
        t = HostTable(1 << 10, key_words=1, val_words=1, stash=16)
        keys = np.arange(100, dtype=np.uint32)[:, None]
        t.bulk_insert(keys, keys)
        assert t._dirty_all
        with pytest.raises(RuntimeError, match="full upload"):
            t.make_update(32)
        t.device_state()  # full upload clears the flag
        t.insert([5000], [1])
        upd = t.make_update(32)
        # exactly one non-padding bucket row rides the update
        assert int((np.asarray(upd.bidx) < t.nbuckets).sum()) == 1

    def test_small_bulk_keeps_delta_sync(self):
        t = HostTable(1 << 10, key_words=1, val_words=1, stash=64)
        keys = np.arange(10, dtype=np.uint32)[:, None]
        t.bulk_insert(keys, keys)
        assert not t._dirty_all
        assert t.dirty_count() == 10

    def test_high_load_factor_residue_path(self):
        # fill to ~87% of capacity: residue must fall back to cuckoo kicks
        nb = 1 << 8
        cap = nb * 4
        t = HostTable(nb, key_words=1, val_words=1, stash=64)
        n = int(cap * 0.87)
        keys = (np.arange(n, dtype=np.uint32) * 2654435761 % (1 << 30))[:, None]
        keys = np.unique(keys, axis=0)
        t.bulk_insert(keys, keys)
        assert t.count == len(keys)
        got = t.lookup_batch_host(keys)
        np.testing.assert_array_equal(got, keys)


class TestFastPathBulk:
    def test_bulk_subscribers_visible_on_device(self):
        n = 5000
        fp = FastPathTables(sub_nbuckets=1 << 12, vlan_nbuckets=1 << 6,
                            cid_nbuckets=1 << 6, max_pools=4)
        macs = np.arange(n, dtype=np.uint64) + 0x02AA00000000
        idx = np.arange(n, dtype=np.uint64)
        fp.add_subscribers_bulk(macs, pool_ids=1,
                                ips=((10 << 24) + 2 + idx).astype(np.uint32),
                                lease_expiries=np.uint32(NOW + 900))
        assert fp.sub.count == n
        # same entry via the scalar API path
        got = fp.get_subscriber(int(macs[123]))
        assert got is not None and int(got[1]) == (10 << 24) + 2 + 123

    def test_bulk_then_scalar_update(self):
        fp = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=1 << 4,
                            cid_nbuckets=1 << 4, max_pools=4)
        macs = np.arange(200, dtype=np.uint64) + 0x02BB00000000
        fp.add_subscribers_bulk(macs, 1, np.arange(200, dtype=np.uint32) + 1,
                                np.uint32(NOW))
        assert fp.touch_lease(int(macs[7]), NOW + 500)
        got = fp.get_subscriber(int(macs[7]))
        assert int(got[4]) == NOW + 500  # AV_LEASE_EXP


class TestNATBulk:
    def _mgr(self):
        return NATManager(
            public_ips=[ip_to_u32("203.0.113.1"), ip_to_u32("203.0.113.2")],
            ports_per_subscriber=64, sessions_nbuckets=1 << 12,
            sub_nat_nbuckets=1 << 10, stash=64)

    def test_bulk_allocate_matches_scalar(self):
        a, b = self._mgr(), self._mgr()
        ips = [(10 << 24) | (i + 2) for i in range(300)]
        made = a.bulk_allocate_nat(ips)
        for ip in ips:
            assert b.allocate_nat(ip) is not None
        assert made == 300
        for ip in ips:
            ba, bb = a.blocks[ip], b.blocks[ip]
            assert (ba["public_ip"], ba["port_start"], ba["port_end"]) == (
                bb["public_ip"], bb["port_start"], bb["port_end"])
            assert np.array_equal(a.sub_nat.lookup([ip]), b.sub_nat.lookup([ip]))

    def test_bulk_flows_sessions_and_reverse(self):
        m = self._mgr()
        n = 2000
        n_subs = 500
        fi = np.arange(n)
        src = ((10 << 24) + 2 + fi % n_subs).astype(np.uint32)
        dst = (ip_to_u32("93.184.0.0") + fi // n_subs).astype(np.uint32)
        sport = (30000 + fi // n_subs).astype(np.uint32)
        m.bulk_allocate_nat(np.unique(src))
        nip, nport, ok = m.bulk_flows(src, dst, sport, 443, 17, 100, NOW)
        assert bool(ok.all())
        # sessions resolvable; reverse rows point back at the session key
        for i in (0, 999, 1999):
            skey = [int(src[i]), int(dst[i]), (int(sport[i]) << 16) | 443, 17]
            v = m.sessions.lookup(skey)
            assert v is not None and int(v[0]) == nip[i] and int(v[1]) == nport[i]
            rk = [int(dst[i]), int(nip[i]), (443 << 16) | int(nport[i]), 17]
            rv = m.reverse.lookup(rk)
            # key words lead the 8-word gather-fast reverse row
            assert rv is not None and list(rv[:4]) == skey
        # external ports unique per (pub_ip, port)
        pairs = set(zip(nip.tolist(), nport.tolist()))
        assert len(pairs) == n

    def test_live_flow_after_bulk_no_port_collision(self):
        m = self._mgr()
        src = np.full((8,), (10 << 24) | 2, dtype=np.uint32)
        dst = (ip_to_u32("93.184.0.0") + np.arange(8)).astype(np.uint32)
        sport = (40000 + np.arange(8)).astype(np.uint32)
        m.bulk_allocate_nat([int(src[0])])
        _, nport, ok = m.bulk_flows(src, dst, sport, 443, 17, 100, NOW)
        assert bool(ok.all())
        live = m.handle_new_flow(int(src[0]), ip_to_u32("9.9.9.9"), 50000, 443,
                                 17, 100, NOW)
        assert live is not None and live[1] not in set(nport.tolist())

    def test_bulk_flows_eim_shared_endpoint(self):
        # RFC 4787 EIM: flows from one internal endpoint share ONE mapping
        m = self._mgr()
        src = np.full((6,), (10 << 24) | 2, dtype=np.uint32)
        dst = (ip_to_u32("93.184.0.0") + np.arange(6)).astype(np.uint32)
        sport = np.full((6,), 5000, dtype=np.uint32)  # same endpoint
        m.bulk_allocate_nat([int(src[0])])
        nip, nport, ok = m.bulk_flows(src, dst, sport, 443, 17, 100, NOW)
        assert bool(ok.all())
        assert len(set(nport.tolist())) == 1, "EIM endpoint must map to one port"
        k = (int(src[0]), 5000, 17)
        assert m.eim[k][2] == 6  # refcount = number of flows
        # a later bulk batch on the same endpoint reuses the mapping
        nip2, nport2, ok2 = m.bulk_flows(
            src[:2], dst[:2] + 100, sport[:2], 443, 17, 100, NOW)
        assert bool(ok2.all()) and nport2[0] == nport[0]
        assert m.eim[k][2] == 8
        # an existing handle_new_flow mapping is reused too (not clobbered)
        live = m.handle_new_flow(int(src[0]), ip_to_u32("9.9.9.9"), 6000, 443,
                                 17, 100, NOW)
        nip3, nport3, ok3 = m.bulk_flows(
            src[:1], np.array([ip_to_u32("8.8.8.8")], np.uint32),
            np.array([6000], np.uint32), 443, 17, 100, NOW)
        assert nport3[0] == live[1]
        assert m.eim[(int(src[0]), 6000, 17)][2] == 2

    def test_bulk_flows_exhaustion_marks_not_ok(self):
        m = self._mgr()
        src = np.full((80,), (10 << 24) | 2, dtype=np.uint32)  # block holds 64
        dst = (ip_to_u32("93.184.0.0") + np.arange(80)).astype(np.uint32)
        sport = (40000 + np.arange(80)).astype(np.uint32)
        m.bulk_allocate_nat([int(src[0])])
        _, _, ok = m.bulk_flows(src, dst, sport, 443, 17, 100, NOW)
        assert int(ok.sum()) == 64 and not bool(ok[64:].any())


class TestGraftEntry:
    # tier-1 budget (PERF_NOTES §16 round): ~52s of pure compile on the
    # forced 8-host-device mesh — the heaviest single test in the fast
    # tier, moved to the slow tier (verify-slow/verify-all) to keep
    # tier-1 inside its 870s cap; the sharded SERVING path stays
    # tier-1-covered by tests/test_sharded_serving.py
    @pytest.mark.slow
    def test_dryrun_multichip_guarded(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)  # conftest already forced cpu; guard is idempotent


class TestBulkOnLiveStepLoop:
    """A bulk table build on a LIVE engine/cluster must recover via one
    full re-upload and serve the bulk-inserted entries on the very next
    step (code-review r3: argument evaluation order captured the stale
    pre-resync tables, silently discarding the re-upload)."""

    def _discover(self, mac_u64: int) -> bytes:
        from bng_tpu.control import dhcp_codec, packets

        mac = int(mac_u64).to_bytes(8, "big")[2:]
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
        p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_engine_step_after_bulk_serves_new_subscribers(self):
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        now = 1_753_000_000
        fp = FastPathTables(sub_nbuckets=1 << 10, vlan_nbuckets=64,
                            cid_nbuckets=64, max_pools=4, stash=64)
        fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
        fp.add_pool(1, ip_to_u32("10.0.0.0"), 16, ip_to_u32("10.0.0.1"),
                    lease_time=3600)
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        eng = Engine(fp, nat, batch_size=8, clock=lambda: float(now))
        # go live (first step uploads tables, clears dirty tracking)
        eng.process([b""])
        # bulk build ON THE LIVE ENGINE — abandons bounded-delta tracking
        n = 200
        macs = np.arange(n, dtype=np.uint64) + 0x02AB00000000
        idx = np.arange(n, dtype=np.uint64)
        fp.add_subscribers_bulk(
            macs, pool_ids=np.full(n, 1, np.uint32),
            ips=((10 << 24) + 2 + idx).astype(np.uint32),
            lease_expiries=np.uint32(now + 600))
        out = eng.process([self._discover(int(macs[0]))])
        assert len(out["tx"]) == 1, "bulk-inserted subscriber not served post-resync"

    def test_cluster_step_after_bulk_serves_new_subscribers(self):
        from bng_tpu.parallel.sharded import ShardedCluster
        from bng_tpu.utils.net import ip_to_u32

        now = 1_753_000_000
        n_dev = 4
        cl = ShardedCluster(n_dev, batch_per_shard=8, sub_nbuckets=1 << 10)
        cl.set_server_config_all(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
        cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 16, ip_to_u32("10.0.0.1"),
                        lease_time=3600)
        B = n_dev * cl.b
        zero = np.zeros((B, 512), np.uint8)
        zl = np.zeros((B,), np.uint32)
        fa = np.ones((B,), dtype=bool)
        cl.step(zero, zl, fa, now, 0)  # live
        # bulk build on shard 0's host mirror
        n = 200
        macs = np.arange(n, dtype=np.uint64) + 0x02AC00000000
        idx = np.arange(n, dtype=np.uint64)
        cl.fastpath[0].add_subscribers_bulk(
            macs, pool_ids=np.full(n, 1, np.uint32),
            ips=((10 << 24) + 2 + idx).astype(np.uint32),
            lease_expiries=np.uint32(now + 600))
        # pick a mac OWNED by shard 0 so the sharded lookup resolves it
        owned = next(int(m) for m in macs if cl.dhcp_sub_shard(int(m)) == 0)
        f = self._discover(owned)
        pkt = np.zeros((B, 512), np.uint8)
        ln = np.zeros((B,), np.uint32)
        pkt[0, : len(f)] = np.frombuffer(f, np.uint8)
        ln[0] = len(f)
        out = cl.step(pkt, ln, fa, now + 1, 0)
        assert out["verdict"][0] == 2, "bulk-inserted subscriber not served post-resync"


class TestReferenceCapacityGeometry:
    """The reference's NAT geometry (bpf/nat44.c:38-40 — 4M sessions,
    2M EIM endpoints, i.e. 2 flows per internal endpoint) stands up
    through the bulk path. Scaled 20x down for CPU CI (the full 4M build
    runs in the chip window via tpu_run.sh config2-4M); the STRUCTURE —
    sessions:EIM = 2:1, unique 5-tuples, reverse rows per session — is
    what this pins."""

    def test_4m_geometry_scaled(self, monkeypatch):
        import bench

        monkeypatch.setenv("BNG_BENCH_EIM_SHARE", "2")
        n_flows, n_subs = 200_000, 50_000
        nat, flows = bench._build_nat_flows(n_flows, n_subs, NOW)
        assert len(flows) == n_flows, bench._DIAG
        assert nat.sessions.count == n_flows
        assert nat.reverse.count == n_flows
        # the reference ratio: half as many EIM endpoints as sessions
        assert len(nat.eim) == n_flows // 2
        # every endpoint carries exactly its two flows
        refs = [m[2] for m in nat.eim.values()]
        assert min(refs) == max(refs) == 2
        # flows sharing an endpoint share ONE external mapping: the
        # device reverse table must still resolve both 5-tuples
        src, dst, sport = (int(x) for x in flows[0])
        k = nat.sessions.lookup(nat._key(src, dst, sport, 443, 17))
        k2 = nat.sessions.lookup(nat._key(src, dst + 1, sport, 443, 17))
        if k2 is not None:  # its pair flow exists in the batch
            assert (k[0], k[1]) == (k2[0], k2[1])  # same nat_ip/port
