"""Cluster-of-BNGs (bng_tpu/cluster): carve-plan law, coordinator
lifecycle, HA failover, the `_audit_cluster` planted-violation proofs,
checkpoint interop for the carve plan, the chaos scenario + 4M storm
determinism, and the dormant-L4 modules the cluster now leans on
(nexus watch, peerpool carve/return, resilience probes).

`make verify-cluster` runs this file (`cluster` marker, <60s); the
tier-1 Makefile line deselects the marker so the suite runs once."""

import copy
import json

import pytest

from bng_tpu.chaos.faults import SimClock
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import (_mac, _renew, _reply,
                                     dora_with_retries)
from bng_tpu.cluster import (ClusterCoordinator, ClusterPlan,
                             InlineInstance, InstanceSpec, elect_carver,
                             initial_plan, instance_for_mac, replan,
                             steer_macs_u48)
from bng_tpu.control import dhcp_codec
from bng_tpu.utils.net import fnv1a32, ip_to_u32

pytestmark = pytest.mark.cluster

SPACE = ip_to_u32("10.64.0.0")


def _coord(**kw):
    kw.setdefault("clock", SimClock())
    kw.setdefault("space_network", SPACE)
    kw.setdefault("space_prefix_len", 16)
    kw.setdefault("sub_nbuckets", 0)
    kw.setdefault("slice_size", 64)
    return ClusterCoordinator(**kw)


# ---------------------------------------------------------------------------
# the carve plan law
# ---------------------------------------------------------------------------

class TestPlan:
    def test_initial_carve_partitions_the_space(self):
        plan = initial_plan(SPACE, 16, ["c", "a", "b", "d"])
        assert plan.epoch == 1 and not plan.free
        seen = set()
        for p in plan.members.values():
            for b in p.blocks:
                assert b.network not in seen
                seen.add(b.network)
        assert plan.total_addresses() == 1 << 16
        # round-robin over SORTED ids: every carver computes the same
        assert [len(plan.members[i].blocks) for i in plan.member_ids()] \
            == [1, 1, 1, 1]

    def test_small_cluster_keeps_free_growth_blocks(self):
        plan = initial_plan(SPACE, 16, ["a", "b"])
        # minimum 4 blocks: 2 members x 2 blocks, none free but blocks
        # stay whole-power-of-two so a leaver's return is dealable
        assert plan.n_blocks == 4
        assert all(len(p.blocks) == 2 for p in plan.members.values())

    def test_replan_never_moves_a_survivor_block(self):
        plan = initial_plan(SPACE, 16, ["a", "b", "c", "d"])
        before = {i: list(p.blocks) for i, p in plan.members.items()}
        plan2 = replan(plan, ["a", "b", "c"])
        for iid in ("a", "b", "c"):
            assert plan2.members[iid].blocks == before[iid]
        assert plan2.epoch == plan.epoch + 1
        assert [b.index for b in plan2.free] \
            == sorted(b.index for b in before["d"])

    def test_replan_deals_free_blocks_only_to_empty_joiners(self):
        plan = initial_plan(SPACE, 16, ["a", "b", "c", "d"])
        plan = replan(plan, ["a", "b", "c"])          # d leaves -> free
        plan2 = replan(plan, ["a", "b", "c", "x"])    # x joins
        assert plan2.members["x"].blocks  # joiner built from the free list
        assert not plan2.free
        # serving members kept exactly their carve
        for iid in ("a", "b", "c"):
            assert plan2.members[iid].blocks == plan.members[iid].blocks

    def test_joiner_without_free_blocks_stays_pending(self):
        plan = initial_plan(SPACE, 16, ["a", "b", "c", "d"])
        plan2 = replan(plan, ["a", "b", "c", "d", "e"])
        assert not plan2.members["e"].blocks
        assert "e" not in plan2.serving_ids()
        assert "e" in plan2.member_ids()

    def test_replan_unchanged_membership_is_the_same_object(self):
        plan = initial_plan(SPACE, 16, ["a", "b"])
        assert replan(plan, ["b", "a"]) is plan

    def test_roundtrip_and_nat_slices(self):
        plan = initial_plan(SPACE, 16, ["a", "b"],
                            nat_base=ip_to_u32("100.64.0.0"),
                            nat_total=1024)
        plan2 = ClusterPlan.from_dict(
            json.loads(json.dumps(plan.to_dict())))
        assert plan2.to_dict() == plan.to_dict()
        per = 1024 // plan.n_blocks
        starts = set()
        for p in plan.members.values():
            for b in p.blocks:
                start, count = plan.nat_range(b)
                assert count == per and start not in starts
                starts.add(start)

    def test_steering_vector_matches_scalar_bit_exact(self):
        import numpy as np

        ids = ("a", "b", "c", "d", "e")
        u48 = (np.uint64(0x02C5) << np.uint64(32)) + np.arange(
            4096, dtype=np.uint64) * np.uint64(2654435761)
        idx = steer_macs_u48(u48 & np.uint64((1 << 48) - 1), len(ids))
        for j in range(0, 4096, 37):
            mac = int(u48[j]) & ((1 << 48) - 1)
            mb = mac.to_bytes(6, "big")
            assert ids[int(idx[j])] == instance_for_mac(mb, ids)
            assert int(idx[j]) == fnv1a32(mb) % len(ids)

    def test_elect_carver_is_lowest_sorted(self):
        assert elect_carver(["b", "a", "c"]) == "a"
        assert elect_carver([]) is None

    def test_space_too_small_raises(self):
        with pytest.raises(ValueError):
            initial_plan(SPACE, 29, ["a", "b", "c", "d", "e", "f", "g",
                                     "h", "i"])


# ---------------------------------------------------------------------------
# coordinator lifecycle
# ---------------------------------------------------------------------------

class TestCoordinator:
    def test_founding_carve_and_dora_through_front_door(self):
        clock = SimClock()
        coord = _coord(clock=clock)
        try:
            coord.add_instances(["bng-a", "bng-b", "bng-c"])
            assert coord.plan.epoch == 1
            macs = [_mac(100 + i) for i in range(30)]
            leased = dora_with_retries(coord, macs, clock)
            assert len(leased) == 30
            assert len(set(leased.values())) == 30
            # every lease landed inside its serving member's carve
            for m, ip in leased.items():
                owner = instance_for_mac(m, coord.member_ids())
                assert coord.plan.owner_of(ip) == owner
            st = coord.status()
            assert st["instances"] == 3
            assert sum(e["leases"] for e in st["members"].values()) == 30
        finally:
            coord.close()

    def test_remove_with_live_book_refused_then_forced(self):
        clock = SimClock()
        coord = _coord(clock=clock)
        try:
            coord.add_instances(["bng-a", "bng-b"])
            leased = dora_with_retries(
                coord, [_mac(200 + i) for i in range(12)], clock)
            assert leased
            victim = coord.member_ids()[0]
            assert coord.remove_instance(victim) is False
            assert coord.refused_removes == 1
            assert victim in coord.member_ids()
            assert coord.remove_instance(victim, force=True) is True
            assert victim not in coord.plan.member_ids()
        finally:
            coord.close()

    def test_elastic_join_builds_from_freed_blocks(self):
        clock = SimClock()
        coord = _coord(clock=clock)
        try:
            coord.add_instances(["bng-a", "bng-b", "bng-c", "bng-d"])
            # a drained member leaves cleanly; its blocks hit the free
            # list and the next joiner builds from them
            gone = coord.member_ids()[-1]
            assert coord.remove_instance(gone) is True
            assert coord.plan.free
            coord.add_instance("bng-x")
            m = coord.members["bng-x"]
            assert not m.pending and m.instance is not None
            leased = dora_with_retries(
                coord, [_mac(300 + i) for i in range(40)], clock)
            assert len(leased) == 40
            audit = audit_invariants(bng_cluster=coord)
            assert audit.ok, audit.violations_by_kind()
        finally:
            coord.close()

    def test_checkpoint_roundtrip_restores_the_carve(self):
        from bng_tpu.runtime.checkpoint import (build_checkpoint,
                                                decode_checkpoint,
                                                encode_checkpoint,
                                                restore_checkpoint)

        coord = _coord()
        try:
            coord.add_instances(["bng-a", "bng-b", "bng-c"])
            want = coord.checkpoint_plan()
            ck = decode_checkpoint(encode_checkpoint(
                build_checkpoint(7, 100.0, cluster_plan=coord)))
            coord2 = _coord()
            try:
                rows = restore_checkpoint(ck, cluster_coord=coord2)
                assert rows["cluster_plan.members"] == 3
                assert coord2.checkpoint_plan() == want
                # restored members are pending until their processes
                # register; a member that joins with its old id adopts
                # its carve instead of re-carving
                coord2.add_instances(["bng-a", "bng-b", "bng-c"])
                assert coord2.plan.epoch == want["epoch"]
                assert not any(m.pending
                               for m in coord2.members.values())
            finally:
                coord2.close()
        finally:
            coord.close()

    def test_corrupt_carve_plan_refuses_restore(self):
        from bng_tpu.runtime.checkpoint import (CheckpointError,
                                                build_checkpoint,
                                                decode_checkpoint,
                                                encode_checkpoint,
                                                restore_checkpoint)

        coord = _coord()
        try:
            coord.add_instances(["bng-a", "bng-b"])
            ck = decode_checkpoint(encode_checkpoint(
                build_checkpoint(7, 100.0, cluster_plan=coord)))
            ck.meta["components"]["cluster_plan"]["members"] = "garbage"
            coord2 = _coord()
            try:
                with pytest.raises(CheckpointError, match="cluster_plan"):
                    restore_checkpoint(ck, cluster_coord=coord2)
                # all-or-nothing: the refused restore touched nothing
                assert coord2.plan is None
            finally:
                coord2.close()
        finally:
            coord.close()

    def test_process_mode_smoke(self):
        clock = SimClock()
        coord = _coord(clock=clock, mode="process")
        try:
            coord.add_instances(["bng-a", "bng-b"])
            leased = dora_with_retries(
                coord, [_mac(400 + i) for i in range(8)], clock)
            assert len(leased) == 8
            st = coord.status()
            assert sum(e["leases"] for e in st["members"].values()) == 8
        finally:
            coord.close()


# ---------------------------------------------------------------------------
# HA failover
# ---------------------------------------------------------------------------

class TestFailover:
    def test_kill_promotes_standby_and_renewals_stick(self):
        clock = SimClock()
        coord = _coord(clock=clock)
        try:
            coord.add_instances(["bng-a", "bng-b", "bng-c"])
            macs = [_mac(500 + i) for i in range(24)]
            leased = dora_with_retries(coord, macs, clock)
            victim = coord.member_ids()[1]
            vmacs = [m for m in macs
                     if instance_for_mac(m, coord.member_ids()) == victim]
            assert vmacs
            coord.kill_instance(victim)
            # outage: the dead member's subscribers shed, others serve
            out = coord.handle_batch(
                [(i, _renew(m, leased[m], 0x6000 + i))
                 for i, m in enumerate(macs)], now=clock())
            shed = [m for (_l, rep), m in zip(out, macs) if rep is None]
            assert sorted(shed) == sorted(vmacs)
            assert coord.shed_frames == len(vmacs)

            for _ in range(16):
                if coord.members[victim].role == "promoted":
                    break
                clock.advance(1.0)
                coord.tick()
            assert coord.members[victim].role == "promoted"
            assert coord.failovers == 1

            # stickiness: renewals ACK with the ORIGINAL addresses
            out = coord.handle_batch(
                [(i, _renew(m, leased[m], 0x7000 + i))
                 for i, m in enumerate(vmacs)], now=clock())
            for (_l, rep), m in zip(out, vmacs):
                p = _reply(rep)
                assert p.msg_type == dhcp_codec.ACK
                assert p.yiaddr == leased[m]
            audit = audit_invariants(bng_cluster=coord)
            assert audit.ok, audit.violations_by_kind()
        finally:
            coord.close()


# ---------------------------------------------------------------------------
# _audit_cluster: planted violations
# ---------------------------------------------------------------------------

def _leased_cluster(clock, n=24):
    coord = _coord(clock=clock)
    coord.add_instances(["bng-a", "bng-b", "bng-c"])
    leased = dora_with_retries(
        coord, [_mac(700 + i) for i in range(n)], clock)
    assert len(leased) == n
    return coord, leased


def _books(coord, iid):
    return coord.members[iid].instance.fleet._inline


class TestAuditCluster:
    def test_clean_cluster_audits_clean(self):
        clock = SimClock()
        coord, _ = _leased_cluster(clock)
        try:
            rep = audit_invariants(bng_cluster=coord)
            assert rep.ok
            assert rep.checks["cluster_members"] == 3
            assert rep.checks["cluster_leases"] == 24
        finally:
            coord.close()

    def test_no_plan_is_a_finding(self):
        coord = _coord()
        try:
            # an empty coordinator is vacuously clean...
            assert audit_invariants(bng_cluster=coord).ok
            # ...but members with a LOST plan document are a finding
            coord.add_instances(["a", "b"])
            coord.plan = None
            rep = audit_invariants(bng_cluster=coord)
            assert not rep.ok
            assert "cluster-no-plan" in rep.violations_by_kind()
        finally:
            coord.close()

    def test_planted_foreign_ip_detected(self):
        clock = SimClock()
        coord, _ = _leased_cluster(clock)
        try:
            iid = coord.member_ids()[0]
            w = _books(coord, iid)[0]
            k, lease = next(iter(w.server.leases.items()))
            # point the lease at an address OUTSIDE the owner's carve
            other = coord.plan.members[coord.member_ids()[1]].blocks[0]
            lease.ip = other.network + 7
            rep = audit_invariants(bng_cluster=coord)
            assert not rep.ok
            assert rep.violations_by_kind().get("cluster-foreign-ip")
        finally:
            coord.close()

    def test_planted_double_ownership_detected(self):
        clock = SimClock()
        coord, _ = _leased_cluster(clock)
        try:
            a, b = coord.member_ids()[0], coord.member_ids()[1]
            wa = _books(coord, a)[0]
            k, lease = next(iter(wa.server.leases.items()))
            # the DESTINI clause one level up: the same (mac, ip) lease
            # surfacing in TWO instances' books
            _books(coord, b)[0].server.leases[k] = copy.copy(lease)
            rep = audit_invariants(bng_cluster=coord)
            assert not rep.ok
            kinds = rep.violations_by_kind()
            assert kinds.get("cluster-double-ownership")
        finally:
            coord.close()

    def test_planted_missteer_detected(self):
        clock = SimClock()
        coord, _ = _leased_cluster(clock)
        try:
            # move one lease's book entry to a member the steering
            # function would never pick for that MAC
            src = None
            for iid in coord.member_ids():
                w = _books(coord, iid)[0]
                if w.server.leases:
                    src, (k, lease) = iid, next(
                        iter(w.server.leases.items()))
                    break
            wrong = next(i for i in coord.member_ids()
                         if i != instance_for_mac(lease.mac,
                                                  coord.member_ids()))
            if wrong != src:
                del _books(coord, src)[0].server.leases[k]
                # keep it inside `wrong`'s carve so only the steering
                # check fires, not the carve one
                lease.ip = coord.plan.members[wrong].blocks[0].network + 9
                _books(coord, wrong)[0].server.leases[k] = lease
            rep = audit_invariants(bng_cluster=coord)
            assert not rep.ok
            assert rep.violations_by_kind().get("cluster-missteer")
        finally:
            coord.close()


# ---------------------------------------------------------------------------
# the chaos scenario + the 4M storm (reduced scale; full scale runs in
# the `bng chaos run` determinism gate)
# ---------------------------------------------------------------------------

class TestChaosIntegration:
    def test_failover_scenario_ok_and_deterministic(self):
        from bng_tpu.chaos.scenarios import cluster_failover_redora

        a = cluster_failover_redora(3)
        b = cluster_failover_redora(3)
        assert a["ok"], a
        assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                           sort_keys=True)

    def test_scale_storm_ok_and_deterministic(self):
        from bng_tpu.chaos.storms import cluster_scale_storm

        a = cluster_scale_storm(3, scale=0.01)
        b = cluster_scale_storm(3, scale=0.01)
        assert a["ok"], a
        assert a["instances"] >= 4
        assert set(a["slo"]) == set(a["leased"])
        assert all(v["ok"] for v in a["slo"].values())
        assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                           sort_keys=True)

    def test_storm_registered_in_runner_catalog(self):
        from bng_tpu.chaos.runner import scenario_catalog

        names = {n for n, _d in scenario_catalog()}
        assert "cluster_failover_redora" in names
        assert "cluster_scale_storm" in names


# ---------------------------------------------------------------------------
# instance spec / carve application edges
# ---------------------------------------------------------------------------

class TestInstance:
    def test_empty_carve_refused(self):
        with pytest.raises(ValueError):
            InlineInstance(InstanceSpec(
                instance_id="x", server_mac=b"\x02" * 6,
                server_ip=ip_to_u32("10.0.0.1"), blocks=[]),
                SimClock())

    def test_shrinking_an_undrained_block_refused(self):
        clock = SimClock()
        coord = _coord(clock=clock)
        try:
            coord.add_instances(["bng-a", "bng-b"])
            leased = dora_with_retries(
                coord, [_mac(800 + i) for i in range(10)], clock)
            assert leased
            iid = next(i for i in coord.member_ids()
                       if coord.members[i].instance.lease_count())
            inst = coord.members[iid].instance
            smaller = copy.deepcopy(coord.plan.members[iid])
            smaller.blocks = []
            before = list(inst.spec.blocks)
            # half-drained shrink is refused; the instance keeps serving
            # the OLD carve untouched
            assert inst.apply_plan(smaller) is False
            assert inst.spec.blocks == before
            assert inst.lease_count() > 0
        finally:
            coord.close()


# ---------------------------------------------------------------------------
# dormant L4 modules the cluster leans on
# ---------------------------------------------------------------------------

class TestMemoryStoreWatch:
    def test_notify_order_and_prefix_filter(self):
        from bng_tpu.control.nexus import MemoryStore

        store = MemoryStore()
        calls = []
        store.watch("a/", lambda k, v: calls.append(("first", k, v)))
        store.watch("a/", lambda k, v: calls.append(("second", k, v)))
        store.watch("b/", lambda k, v: calls.append(("other", k, v)))
        store.put("a/x", b"1")
        # registration order, prefix-filtered
        assert calls == [("first", "a/x", b"1"), ("second", "a/x", b"1")]
        calls.clear()
        store.delete("a/x")
        assert calls == [("first", "a/x", None), ("second", "a/x", None)]

    def test_cancel_is_idempotent_and_scoped(self):
        from bng_tpu.control.nexus import MemoryStore

        store = MemoryStore()
        got1, got2 = [], []
        cancel1 = store.watch("k/", lambda k, v: got1.append(k))
        store.watch("k/", lambda k, v: got2.append(k))
        store.put("k/1", b"x")
        cancel1()
        cancel1()  # idempotent: second cancel must not unhook others
        store.put("k/2", b"y")
        assert got1 == ["k/1"]
        assert got2 == ["k/1", "k/2"]

    def test_unsubscribe_during_notify_is_safe(self):
        from bng_tpu.control.nexus import MemoryStore

        store = MemoryStore()
        seen = []
        cancels = {}

        def once(key, value):
            seen.append(key)
            cancels["self"]()

        cancels["self"] = store.watch("", once)
        store.watch("", lambda k, v: seen.append("tail:" + k))
        store.put("p", b"1")  # cancel mid-notify: the tail still fires
        store.put("q", b"2")
        assert seen == ["p", "tail:p", "tail:q"]

    def test_typed_store_watch_cancel(self):
        from bng_tpu.control.nexus import (MemoryStore, SubscriberEntity,
                                           TypedStore)

        subs = TypedStore(MemoryStore(), "subscribers", SubscriberEntity)
        got = []
        cancel = subs.watch(lambda id_, obj: got.append((id_, obj)))
        subs.put("s1", SubscriberEntity(id="s1", mac="02aa"))
        cancel()
        subs.put("s2", SubscriberEntity(id="s2"))
        assert len(got) == 1
        assert got[0][0] == "s1" and got[0][1].mac == "02aa"


class TestPeerPoolEdges:
    def _pool(self):
        from bng_tpu.control.peerpool import PeerPool, PoolRange

        return PeerPool("n1", ["n1"], PoolRange(ip_to_u32("10.9.0.0"), 8))

    def test_allocate_is_idempotent_per_subscriber(self):
        p = self._pool()
        ip = p.allocate("sub-1")
        assert p.allocate("sub-1") == ip
        assert p.stats["local_allocs"] == 1

    def test_release_returns_the_address_for_reuse(self):
        from bng_tpu.control.peerpool import PeerPoolError

        p = self._pool()
        ips = {p.allocate(f"s{i}") for i in range(8)}
        assert len(ips) == 8
        with pytest.raises(PeerPoolError):
            p.allocate("overflow")
        assert p.release("s3") is True
        assert p.release("s3") is False  # double return: counted once
        assert p.allocate("late") in ips  # the freed address reused

    def test_release_unknown_subscriber_is_false(self):
        p = self._pool()
        assert p.release("ghost") is False


class TestResilienceProbes:
    def test_probe_interval_gates_the_checks(self):
        from bng_tpu.control.resilience import ResilienceManager

        probes = []

        def nexus_ok():
            probes.append(1)
            return True

        mgr = ResilienceManager(nexus_ok, check_interval_s=5.0)
        mgr.tick(10.0)
        mgr.tick(11.0)  # within the interval: probe NOT re-fired
        mgr.tick(14.9)
        assert len(probes) == 1
        mgr.tick(15.0)
        assert len(probes) == 2

    def test_raising_probe_folds_to_unhealthy_and_partitions(self):
        from bng_tpu.control.resilience import (PartitionState,
                                                ResilienceManager)

        def bad_probe():
            raise ConnectionError("nexus gone")

        mgr = ResilienceManager(bad_probe, check_interval_s=1.0,
                                failure_threshold=3)
        t = 0.0
        for _ in range(2):
            t += 1.0
            assert mgr.tick(t) == PartitionState.NORMAL
        t += 1.0
        assert mgr.tick(t) == PartitionState.PARTITIONED

    def test_recovery_after_partition(self):
        from bng_tpu.control.resilience import (PartitionState,
                                                ResilienceManager)

        healthy = {"ok": False}
        mgr = ResilienceManager(lambda: healthy["ok"],
                                check_interval_s=1.0,
                                failure_threshold=2)
        assert mgr.tick(1.0) == PartitionState.NORMAL
        assert mgr.tick(2.0) == PartitionState.PARTITIONED
        healthy["ok"] = True
        state = mgr.tick(3.0)
        assert state in (PartitionState.RECOVERING, PartitionState.NORMAL)
        assert mgr.tick(4.0) == PartitionState.NORMAL


# ---------------------------------------------------------------------------
# metrics + ledger cohort identity
# ---------------------------------------------------------------------------

class TestClusterMetrics:
    def test_record_cluster_families_and_reconciliation(self):
        from bng_tpu.control.metrics import BNGMetrics

        coord = _coord()
        try:
            coord.add_instances(["a", "b"])
            m = BNGMetrics()
            m.record_cluster(coord.status())
            assert m.cluster_instances.value(state="up") == 2
            assert m.cluster_plan_epoch.value() == 1
            assert m.cluster_addresses.value(instance="a") > 0
            coord.remove_instance("b")
            m.record_cluster(coord.status())
            # the departed member's gauge labels DROP (no stale rows)
            labels = {d["instance"]
                      for d in m.cluster_addresses.labeled()}
            assert labels == {"a"}
            assert m.cluster_recarves.value() == 2
        finally:
            coord.close()

    def test_fleet_blocked_gauge_clears_removed_blockers(self):
        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        # the full blocker vocabulary after ISSUE 20 shrank it again:
        # nexus joined radius and peer-pool off the list (each shard
        # allocates against the shared store through its own
        # HTTPAllocator), so a config reload from the old set to the
        # new one must DROP the retired labels
        m.record_fleet_blocked(["nexus", "radius", "peer-pool"])
        assert m.slowpath_fleet_blocked.value(blocker="radius") == 1
        m.record_fleet_blocked(["pppoe", "sharded"])
        # the satellite fix: a blocker that disappeared must leave the
        # scrape, not freeze at 1
        assert {d["blocker"]
                for d in m.slowpath_fleet_blocked.labeled()} \
            == {"pppoe", "sharded"}
        m.record_fleet_blocked([])
        assert m.slowpath_fleet_blocked.labeled() == []


class TestLedgerInstances:
    def _line(self, i, n_instances=None, value=10.0):
        line = {"metric": "serve Mpps", "value": value, "unit": "Mpps",
                "run_id": f"r{i}", "ts": f"2026-08-0{(i % 7) + 1}",
                "schema_version": 1, "batch": 1024,
                "env": {"backend": "tpu", "device_kind": "TPU v4"}}
        if n_instances is not None:
            line["n_instances"] = n_instances
        return line

    def test_legacy_lines_default_to_one_instance(self):
        from bng_tpu.telemetry.ledger import cohort_key, n_instances

        legacy = self._line(0)
        assert n_instances(legacy) == 1
        stamped = self._line(1, n_instances=1)
        assert cohort_key(legacy) == cohort_key(stamped)

    def test_cluster_lines_refuse_single_instance_history(self, tmp_path):
        from bng_tpu.telemetry import ledger as lg

        path = tmp_path / "bench_runs.jsonl"
        for i in range(5):
            lg.append(str(path), self._line(i))
        cand = self._line(9, n_instances=4, value=35.0)
        lg.append(str(path), cand)
        rep = lg.gate_file(str(path))
        assert rep.rc == 3  # incomparable cohort, never a regression
        # the refusal names BOTH sides of the identity
        note = " ".join(rep.notes)
        assert "instances=4" in note and "instances=1" in note
