"""Cuckoo table tests — host mirror vs device lookup consistency.

TPU analog of the reference's Go<->eBPF struct layout tests
(test/ebpf/maps_test.go:17-80): the host writer and device reader must agree
on layout and hashing bit-for-bit, or table data is silently corrupted.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bng_tpu.ops.table import HostTable, device_lookup, apply_update, WAYS


class TestPartialDrain:
    def test_half_drained_bucket_hides_undrained_sibling(self):
        """A partial drain must not expose a still-queued sibling insert as
        a hit with stale/zero vals (code-review r3 repro): the sibling
        reads as a MISS until its own drain ships its value row."""
        t = HostTable(1, key_words=1, val_words=2, stash=0, name="pd")
        state = t.device_state()
        sa = t.insert([1], [111, 0])
        sb = t.insert([2], [222, 0])
        assert sa // WAYS == sb // WAYS  # same (only) bucket
        state = apply_update(state, t.make_update(max_slots=1))
        res = device_lookup(state, jnp.asarray([[1], [2]], dtype=jnp.uint32), 1, 0)
        f = np.asarray(res.found)
        v = np.asarray(res.vals)
        # exactly one visible, with its real vals; the other is a clean miss
        assert sorted(f.tolist()) == [False, True]
        assert v[f][0][0] in (111, 222)
        # second drain completes the bucket: both visible, correct vals
        state = apply_update(state, t.make_update(max_slots=1))
        res = device_lookup(state, jnp.asarray([[1], [2]], dtype=jnp.uint32), 1, 0)
        assert np.asarray(res.found).all()
        np.testing.assert_array_equal(np.asarray(res.vals)[:, 0], [111, 222])


def make_queries(keys_list, K):
    return jnp.asarray(np.array(keys_list, dtype=np.uint32).reshape(-1, K))


class TestHostTable:
    def test_insert_lookup_delete(self):
        t = HostTable(nbuckets=64, key_words=2, val_words=4)
        t.insert([1, 2], [10, 20, 30, 40])
        assert t.lookup([1, 2]).tolist() == [10, 20, 30, 40]
        assert t.lookup([9, 9]) is None
        assert t.delete([1, 2])
        assert t.lookup([1, 2]) is None
        assert not t.delete([1, 2])
        assert t.count == 0

    def test_update_existing(self):
        t = HostTable(nbuckets=64, key_words=1, val_words=1)
        t.insert([5], [100])
        t.insert([5], [200])
        assert t.count == 1
        assert t.lookup([5])[0] == 200

    def test_high_load_factor(self):
        # 4-way cuckoo should comfortably hold 90% load.
        t = HostTable(nbuckets=256, key_words=2, val_words=2, stash=64)
        n = int(256 * WAYS * 0.9)
        for i in range(n):
            t.insert([i, i ^ 0xABCD], [i, i + 1])
        assert t.count == n
        for i in range(0, n, 37):
            assert t.lookup([i, i ^ 0xABCD])[0] == i

    def test_full_raises(self):
        t = HostTable(nbuckets=2, key_words=1, val_words=1, stash=2)
        with pytest.raises(RuntimeError):
            for i in range(1, 100):
                t.insert([i], [i])


class TestDeviceLookup:
    def test_matches_host(self):
        t = HostTable(nbuckets=128, key_words=2, val_words=3)
        rng = np.random.default_rng(7)
        keys = rng.integers(0, 2**32, size=(300, 2), dtype=np.uint32)
        keys = np.unique(keys, axis=0)
        for i, k in enumerate(keys):
            t.insert(k, [i, i * 2, i * 3])

        state = t.device_state()
        # present keys + some absent ones
        absent = rng.integers(0, 2**32, size=(50, 2), dtype=np.uint32)
        queries = np.concatenate([keys[:100], absent], axis=0)
        res = device_lookup(state, jnp.asarray(queries), t.nbuckets, t.stash)
        found = np.asarray(res.found)
        vals = np.asarray(res.vals)
        host_vals = t.lookup_batch_host(queries)
        for i in range(100):
            assert found[i], f"key {queries[i]} not found on device"
            assert vals[i].tolist() == host_vals[i].tolist()
        # absent keys: not found unless they collide with a real key (unique'd)
        present = {tuple(k) for k in keys}
        for i in range(100, len(queries)):
            if tuple(queries[i]) not in present:
                assert not found[i]

    def test_stash_entries_visible(self):
        # Force stash use with a tiny table.
        t = HostTable(nbuckets=2, key_words=1, val_words=1, stash=8)
        inserted = []
        try:
            for i in range(1, 50):
                t.insert([i], [i * 10])
                inserted.append(i)
        except RuntimeError:
            pass
        state = t.device_state()
        q = make_queries([[i] for i in inserted], 1)
        res = device_lookup(state, q, t.nbuckets, t.stash)
        assert bool(jnp.all(res.found))
        assert np.asarray(res.vals)[:, 0].tolist() == [i * 10 for i in inserted]

    def test_incremental_update(self):
        t = HostTable(nbuckets=64, key_words=1, val_words=1)
        t.insert([1], [11])
        state = t.device_state()
        assert t.dirty_count() == 0

        t.insert([2], [22])
        t.insert([1], [111])  # update
        upd = t.make_update(max_slots=8)
        state = apply_update(state, upd)
        res = device_lookup(state, make_queries([[1], [2], [3]], 1), t.nbuckets, t.stash)
        assert np.asarray(res.found).tolist() == [True, True, False]
        assert np.asarray(res.vals)[:2, 0].tolist() == [111, 22]

        t.delete([1])
        state = apply_update(state, t.make_update(max_slots=8))
        res = device_lookup(state, make_queries([[1]], 1), t.nbuckets, t.stash)
        assert not bool(res.found[0])

    def test_update_bounded_and_resumable(self):
        t = HostTable(nbuckets=64, key_words=1, val_words=1)
        state = t.device_state()
        for i in range(1, 21):
            t.insert([i], [i])
        assert t.dirty_count() == 20
        state = apply_update(state, t.make_update(max_slots=8))
        assert t.dirty_count() == 12
        state = apply_update(state, t.make_update(max_slots=8))
        state = apply_update(state, t.make_update(max_slots=8))
        assert t.dirty_count() == 0
        q = make_queries([[i] for i in range(1, 21)], 1)
        res = device_lookup(state, q, t.nbuckets, t.stash)
        assert bool(jnp.all(res.found))

    def test_jit_compatible(self):
        t = HostTable(nbuckets=64, key_words=2, val_words=2)
        t.insert([7, 8], [70, 80])
        state = t.device_state()
        f = jax.jit(lambda s, q: device_lookup(s, q, 64, t.stash))
        res = f(state, make_queries([[7, 8]], 2))
        assert bool(res.found[0])
        assert np.asarray(res.vals)[0].tolist() == [70, 80]
