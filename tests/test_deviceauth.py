"""Tests for control/deviceauth.py (dormant-module coverage, ISSUE 18).

The device-auth stack the control-plane transport wires in when a BNG
instance enrolls with its controller: identity detection from a (fake)
sysfs tree, the NONE/PSK/MTLS authenticators, the minimal X.509 DER
helpers, and the header-injecting transport wrapper. All jax-free;
MTLS paths use a hand-built synthetic DER certificate so no openssl
invocation (and no real key material) is needed.
"""

from __future__ import annotations

import base64
import os

import pytest

from bng_tpu.control.deviceauth import (
    MAX_TIMESTAMP_SKEW, PSK_SIGNATURE_HEADER, PSK_TIMESTAMP_HEADER,
    AuthenticatedTransport, AuthMode, DeviceIdentity, MTLSAuthenticator,
    NoneAuthenticator, PSKAuthenticator, _pem_to_der, cert_fingerprint,
    cert_not_after, generate_device_id, new_authenticator,
    read_device_identity, sanitize_id,
)

NOW = 1_700_000_000.0


class FakeClock:
    def __init__(self, t=NOW):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# identity detection
# ---------------------------------------------------------------------------

class TestIdentity:
    def test_sanitize_id(self):
        assert sanitize_id("AB c/1:2") == "ab-c-1-2"
        assert sanitize_id("ok_id-9") == "ok_id-9"

    def test_generate_device_id_precedence(self):
        assert generate_device_id("SN 01", "02:aa") == "dev-sn-01"
        assert generate_device_id("", "02:AA:bb") == "dev-02aabb"
        anon = generate_device_id("", "")
        assert anon.startswith("dev-") and len(anon) == 4 + 12

    def test_read_identity_from_fake_sys_tree(self, tmp_path):
        dmi = tmp_path / "sys/class/dmi/id"
        dmi.mkdir(parents=True)
        (dmi / "product_serial").write_text("BNG-42 \n")
        (dmi / "product_name").write_text("tpu-bng-host\n")
        for iface, addr in (("lo", "00:00:00:00:00:00"),
                            ("eth0", "02:aa:bb:cc:dd:ee")):
            d = tmp_path / "sys/class/net" / iface
            d.mkdir(parents=True)
            (d / "address").write_text(addr + "\n")
        ident = read_device_identity(str(tmp_path))
        assert ident.serial == "BNG-42"
        assert ident.model == "tpu-bng-host"
        assert ident.mac == "02:aa:bb:cc:dd:ee"  # lo skipped
        assert ident.device_id == "dev-bng-42"

    def test_read_identity_mac_fallback(self, tmp_path):
        d = tmp_path / "sys/class/net/eth0"
        d.mkdir(parents=True)
        (d / "address").write_text("02:aa:bb:cc:dd:ee\n")
        ident = read_device_identity(str(tmp_path))
        assert ident.serial == ""
        assert ident.device_id == "dev-02aabbccddee"

    def test_read_identity_empty_tree(self, tmp_path):
        ident = read_device_identity(str(tmp_path))
        assert ident.device_id.startswith("dev-")


# ---------------------------------------------------------------------------
# NONE + PSK authenticators
# ---------------------------------------------------------------------------

class TestNoneAuth:
    def test_headers_and_result(self):
        a = NoneAuthenticator(DeviceIdentity(device_id="dev-x",
                                             serial="SN9"))
        res = a.authenticate()
        assert res.success and res.mode == AuthMode.NONE
        h = a.http_headers()
        assert h == {"X-Device-ID": "dev-x", "X-Device-Serial": "SN9"}
        assert a.tls_config() is None


class TestPSK:
    KEY = "correct-horse-battery-staple"

    def _auth(self, clock=None):
        return PSKAuthenticator(psk=self.KEY, clock=clock or FakeClock(),
                                identity=DeviceIdentity(device_id="dev-p"))

    def test_short_psk_rejected(self):
        with pytest.raises(ValueError):
            PSKAuthenticator(psk="too-short")

    def test_psk_file_source(self, tmp_path):
        f = tmp_path / "psk"
        f.write_text(self.KEY + "\n")
        a = PSKAuthenticator(psk_file=str(f), clock=FakeClock())
        assert a.sign_message("m") == self._auth().sign_message("m")

    def test_sign_verify_roundtrip(self):
        a = self._auth()
        h = a.http_headers()
        assert h["X-Device-ID"] == "dev-p"
        # the server side accepts its own client's headers
        a.verify_signature("dev-p", h[PSK_TIMESTAMP_HEADER],
                           h[PSK_SIGNATURE_HEADER])

    def test_tampered_signature_rejected(self):
        a = self._auth()
        h = a.http_headers()
        bad = "0" * len(h[PSK_SIGNATURE_HEADER])
        with pytest.raises(ValueError, match="signature mismatch"):
            a.verify_signature("dev-p", h[PSK_TIMESTAMP_HEADER], bad)
        # a different device_id re-signs to a different digest
        with pytest.raises(ValueError, match="signature mismatch"):
            a.verify_signature("dev-q", h[PSK_TIMESTAMP_HEADER],
                               h[PSK_SIGNATURE_HEADER])

    def test_timestamp_skew_window(self):
        clock = FakeClock()
        a = self._auth(clock)
        h = a.http_headers()
        clock.t = NOW + MAX_TIMESTAMP_SKEW - 1  # inside the window
        a.verify_signature("dev-p", h[PSK_TIMESTAMP_HEADER],
                           h[PSK_SIGNATURE_HEADER])
        clock.t = NOW + MAX_TIMESTAMP_SKEW + 1  # replayed too late
        with pytest.raises(ValueError, match="skew"):
            a.verify_signature("dev-p", h[PSK_TIMESTAMP_HEADER],
                               h[PSK_SIGNATURE_HEADER])

    def test_bad_timestamp_format(self):
        a = self._auth()
        with pytest.raises(ValueError, match="invalid timestamp"):
            a.verify_signature("dev-p", "yesterday-ish", "00")

    def test_rotation_invalidates_old_signatures(self):
        a = self._auth()
        h = a.http_headers()
        with pytest.raises(ValueError):
            a.rotate_psk("short")
        a.rotate_psk("a-brand-new-shared-key")
        with pytest.raises(ValueError, match="signature mismatch"):
            a.verify_signature("dev-p", h[PSK_TIMESTAMP_HEADER],
                               h[PSK_SIGNATURE_HEADER])
        h2 = a.http_headers()  # signed under the new key
        a.verify_signature("dev-p", h2[PSK_TIMESTAMP_HEADER],
                           h2[PSK_SIGNATURE_HEADER])

    def test_close_zeroes_key_material(self):
        a = self._auth()
        n = len(self.KEY)
        a.close()
        assert a._psk == b"\x00" * n


# ---------------------------------------------------------------------------
# X.509 helpers + MTLS (synthetic DER certificate, no openssl)
# ---------------------------------------------------------------------------

def _der(tag: int, content: bytes) -> bytes:
    n = len(content)
    if n < 0x80:
        return bytes([tag, n]) + content
    b = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([tag, 0x80 | len(b)]) + b + content


def fake_cert_pem(not_after: str, not_before: str = "250101000000Z") -> str:
    """Minimal syntactically-valid Certificate DER: enough structure
    for cert_not_after's TBS walk ([0] version, serial, sigAlg, issuer,
    validity{UTCTime,UTCTime}, subject)."""
    validity = _der(0x30, _der(0x17, not_before.encode())
                    + _der(0x17, not_after.encode()))
    tbs = _der(0x30, _der(0xA0, _der(0x02, b"\x02"))  # [0] v3
               + _der(0x02, b"\x01")                   # serial
               + _der(0x30, b"")                       # sigAlg
               + _der(0x30, b"")                       # issuer
               + validity
               + _der(0x30, b""))                      # subject
    cert = _der(0x30, tbs + _der(0x30, b"") + _der(0x03, b"\x00"))
    b64 = base64.encodebytes(cert).decode()
    return ("-----BEGIN CERTIFICATE-----\n" + b64
            + "-----END CERTIFICATE-----\n")


class TestCertHelpers:
    def test_pem_without_cert_rejected(self):
        with pytest.raises(ValueError, match="no certificate"):
            _pem_to_der("-----BEGIN KEY-----\nAAAA\n-----END KEY-----")

    def test_utctime_century_rule(self):
        # YY<50 -> 20YY, YY>=50 -> 19YY: 2049 lands after 1950
        assert (cert_not_after(fake_cert_pem("490101000000Z"))
                > cert_not_after(fake_cert_pem("500101000000Z")))

    def test_fingerprint_tracks_der_bytes(self):
        a, b = fake_cert_pem("270101000000Z"), fake_cert_pem("280101000000Z")
        assert cert_fingerprint(a) != cert_fingerprint(b)
        assert cert_fingerprint(a) == cert_fingerprint(a)


class TestMTLS:
    def _write_pair(self, tmp_path, not_after="270101000000Z"):
        cert = tmp_path / "device.crt"
        key = tmp_path / "device.key"
        cert.write_text(fake_cert_pem(not_after))
        key.write_text("not-a-real-key")
        return str(cert), str(key)

    def test_accepts_before_expiry_rejects_after(self, tmp_path):
        cert, key = self._write_pair(tmp_path)
        clock = FakeClock()
        a = MTLSAuthenticator(cert, key, clock=clock,
                              identity=DeviceIdentity(device_id="dev-m"))
        na = cert_not_after(fake_cert_pem("270101000000Z"))
        clock.t = na - 1000.0
        res = a.authenticate()
        assert res.success and res.mode == AuthMode.MTLS
        assert a.expires_within(2000.0) and not a.expires_within(500.0)
        clock.t = na + 1.0
        res = a.authenticate()
        assert not res.success and res.error == "certificate expired"

    def test_rotation_reload_on_file_change(self, tmp_path):
        cert, key = self._write_pair(tmp_path)
        a = MTLSAuthenticator(cert, key, clock=FakeClock(),
                              identity=DeviceIdentity(device_id="dev-m"))
        fp0 = a.fingerprint
        assert not a.maybe_rotate()  # unchanged file -> no reload
        with open(cert, "w") as f:
            f.write(fake_cert_pem("280101000000Z"))
        os.utime(cert, (1, 1))  # force a visible mtime change
        assert a.maybe_rotate()
        assert a.fingerprint != fp0
        assert a.http_headers()["X-Device-Cert-Fingerprint"] == a.fingerprint


# ---------------------------------------------------------------------------
# dispatch + transport wrapper
# ---------------------------------------------------------------------------

class TestWiring:
    def test_new_authenticator_dispatch(self, tmp_path):
        assert isinstance(new_authenticator("none"), NoneAuthenticator)
        assert isinstance(
            new_authenticator(AuthMode.PSK, psk="0123456789abcdef",
                              clock=FakeClock()), PSKAuthenticator)
        cert = tmp_path / "c.crt"
        cert.write_text(fake_cert_pem("270101000000Z"))
        assert isinstance(
            new_authenticator("mtls", cert_file=str(cert), key_file="",
                              clock=FakeClock(),
                              identity=DeviceIdentity(device_id="d")),
            MTLSAuthenticator)
        with pytest.raises(ValueError):
            new_authenticator("bogus")

    def test_transport_injects_auth_headers(self):
        calls = []

        def base(method, url, headers, body):
            calls.append((method, url, headers, body))
            return 200

        auth = NoneAuthenticator(DeviceIdentity(device_id="dev-t"))
        tr = AuthenticatedTransport(base, auth)
        assert tr("POST", "http://c/v1/enroll",
                  {"Content-Type": "application/json",
                   "X-Device-ID": "spoofed"}, b"{}") == 200
        method, url, headers, body = calls[0]
        assert headers["Content-Type"] == "application/json"
        assert headers["X-Device-ID"] == "dev-t"  # auth wins over caller
        assert body == b"{}"
