"""DHCPv6 + SLAAC tests (mirrors pkg/dhcpv6 + pkg/slaac test strategy)."""

import struct

import pytest

from bng_tpu.control.dhcpv6 import protocol as p6
from bng_tpu.control.dhcpv6.protocol import (
    DHCPv6Message,
    DUID,
    IAAddress,
    IANA,
    IAPD,
    generate_duid_ll,
)
from bng_tpu.control.dhcpv6.server import (
    AddressPool6,
    DHCPv6Server,
    DHCPv6ServerConfig,
    PrefixPool6,
)
from bng_tpu.control.slaac import (
    SLAACConfig,
    SLAACServer,
    PrefixConfig,
    eui64_iid,
    link_local,
    stable_privacy_iid,
    _icmp6_checksum,
)

CLIENT_MAC = b"\x02\xcc\x00\x00\x00\x42"
CLIENT_DUID = generate_duid_ll(CLIENT_MAC).encode()


def mkserver(**kw):
    cfg = DHCPv6ServerConfig(
        dns_servers=[bytes.fromhex("20010db8000000000000000000000053")],
        domain_list=["isp.example"], **kw)
    return DHCPv6Server(
        cfg,
        address_pool=AddressPool6("2001:db8:100::/64", 3600, 7200),
        prefix_pool=PrefixPool6("2001:db8:f000::/40", delegated_len=56),
        clock=lambda: 1000.0,
    )


def solicit(iaid=1, pd=False, rapid=False):
    m = DHCPv6Message(p6.SOLICIT, 0x123456)
    m.add(p6.OPT_CLIENTID, CLIENT_DUID)
    m.add_ia_na(IANA(iaid))
    if pd:
        m.add_ia_pd(IAPD(iaid))
    if rapid:
        m.add(p6.OPT_RAPID_COMMIT, b"")
    return m


class TestCodec:
    def test_message_roundtrip(self):
        m = solicit(pd=True)
        back = DHCPv6Message.decode(m.encode())
        assert back.msg_type == p6.SOLICIT
        assert back.transaction_id == 0x123456
        assert back.client_duid == CLIENT_DUID
        assert len(back.ia_nas()) == 1 and len(back.ia_pds()) == 1

    def test_iana_roundtrip(self):
        ia = IANA(7, 100, 200)
        ia.addresses.append(IAAddress(b"\x20\x01" + b"\x00" * 14, 300, 400))
        back = IANA.decode(ia.encode())
        assert back.iaid == 7 and back.t1 == 100 and back.t2 == 200
        assert back.addresses[0].preferred == 300
        assert back.addresses[0].valid == 400

    def test_duid_ll(self):
        d = generate_duid_ll(CLIENT_MAC)
        assert d.duid_type == p6.DUID_LL
        back = DUID.decode(d.encode())
        assert back.data == struct.pack(">H", 1) + CLIENT_MAC

    def test_truncated_rejected(self):
        with pytest.raises(ValueError):
            DHCPv6Message.decode(b"\x01\x02")
        srv = mkserver()
        assert srv.handle_message(b"\x01") is None


class TestServer:
    def test_solicit_advertise_request_reply(self):
        srv = mkserver()
        adv_raw = srv.handle_message(solicit(pd=True).encode())
        adv = DHCPv6Message.decode(adv_raw)
        assert adv.msg_type == p6.ADVERTISE
        assert adv.server_duid == srv.duid.encode()
        ia = adv.ia_nas()[0]
        addr = ia.addresses[0].address
        assert addr.startswith(bytes.fromhex("20010db80100"))
        pd = adv.ia_pds()[0]
        assert pd.prefixes[0].prefix_len == 56
        # advertise does not commit
        assert len(srv.leases) == 0

        req = DHCPv6Message(p6.REQUEST, 0x654321)
        req.add(p6.OPT_CLIENTID, CLIENT_DUID)
        req.add(p6.OPT_SERVERID, srv.duid.encode())
        req.add_ia_na(IANA(1))
        req.add_ia_pd(IAPD(1))
        rep = DHCPv6Message.decode(srv.handle_message(req.encode()))
        assert rep.msg_type == p6.REPLY
        assert len(srv.leases) == 2
        assert rep.ia_nas()[0].t1 == 3600  # 0.5 * valid
        assert rep.ia_nas()[0].t2 == 5760  # 0.8 * valid
        # dns + domain options present
        assert rep.get(p6.OPT_DNS_SERVERS) is not None
        assert b"isp" in rep.get(p6.OPT_DOMAIN_LIST)

    def test_rapid_commit(self):
        srv = mkserver()
        rep = DHCPv6Message.decode(srv.handle_message(solicit(rapid=True).encode()))
        assert rep.msg_type == p6.REPLY
        assert rep.get(p6.OPT_RAPID_COMMIT) is not None
        assert len(srv.leases) == 1

    def test_renew_extends_rebind_recreates(self):
        srv = mkserver()
        srv.handle_message(solicit(rapid=True).encode())
        lease = next(iter(srv.leases.values()))
        addr0 = lease.address

        renew = DHCPv6Message(p6.RENEW, 1)
        renew.add(p6.OPT_CLIENTID, CLIENT_DUID)
        renew.add(p6.OPT_SERVERID, srv.duid.encode())
        renew.add_ia_na(IANA(1))
        rep = DHCPv6Message.decode(srv.handle_message(renew.encode()))
        assert rep.ia_nas()[0].addresses[0].address == addr0

        # renew for unknown IAID -> NoBinding
        renew2 = DHCPv6Message(p6.RENEW, 2)
        renew2.add(p6.OPT_CLIENTID, CLIENT_DUID)
        renew2.add(p6.OPT_SERVERID, srv.duid.encode())
        renew2.add_ia_na(IANA(99))
        rep2 = DHCPv6Message.decode(srv.handle_message(renew2.encode()))
        assert rep2.ia_nas()[0].status[0] == p6.STATUS_NO_BINDING

        # rebind for unknown IAID recreates
        rebind = DHCPv6Message(p6.REBIND, 3)
        rebind.add(p6.OPT_CLIENTID, CLIENT_DUID)
        rebind.add_ia_na(IANA(99))
        rep3 = DHCPv6Message.decode(srv.handle_message(rebind.encode()))
        assert rep3.ia_nas()[0].status is None
        assert len(rep3.ia_nas()[0].addresses) == 1

    def test_release_returns_to_pool(self):
        srv = mkserver()
        srv.handle_message(solicit(rapid=True).encode())
        addr = next(iter(srv.leases.values())).address
        rel = DHCPv6Message(p6.RELEASE, 5)
        rel.add(p6.OPT_CLIENTID, CLIENT_DUID)
        rel.add(p6.OPT_SERVERID, srv.duid.encode())
        rel.add_ia_na(IANA(1))
        rep = DHCPv6Message.decode(srv.handle_message(rel.encode()))
        assert rep.msg_type == p6.REPLY
        assert len(srv.leases) == 0
        # the address is reusable
        assert srv.addr_pool.allocate() == addr

    def test_decline_quarantines(self):
        srv = mkserver()
        srv.handle_message(solicit(rapid=True).encode())
        addr = next(iter(srv.leases.values())).address
        dec = DHCPv6Message(p6.DECLINE, 6)
        dec.add(p6.OPT_CLIENTID, CLIENT_DUID)
        dec.add(p6.OPT_SERVERID, srv.duid.encode())
        dec.add_ia_na(IANA(1))
        srv.handle_message(dec.encode())
        assert len(srv.leases) == 0
        # declined address is NOT handed out again
        assert srv.addr_pool.allocate() != addr

    def test_confirm_on_link(self):
        srv = mkserver()
        conf = DHCPv6Message(p6.CONFIRM, 7)
        conf.add(p6.OPT_CLIENTID, CLIENT_DUID)
        ia = IANA(1)
        ia.addresses.append(IAAddress(
            int(srv.addr_pool.net.network_address + 5).to_bytes(16, "big")))
        conf.add_ia_na(ia)
        rep = DHCPv6Message.decode(srv.handle_message(conf.encode()))
        code = struct.unpack(">H", rep.get(p6.OPT_STATUS_CODE)[:2])[0]
        assert code == p6.STATUS_SUCCESS

        conf2 = DHCPv6Message(p6.CONFIRM, 8)
        conf2.add(p6.OPT_CLIENTID, CLIENT_DUID)
        ia2 = IANA(1)
        ia2.addresses.append(IAAddress(bytes.fromhex("20010db8deadbeef") + b"\x00" * 8))
        conf2.add_ia_na(ia2)
        rep2 = DHCPv6Message.decode(srv.handle_message(conf2.encode()))
        code2 = struct.unpack(">H", rep2.get(p6.OPT_STATUS_CODE)[:2])[0]
        assert code2 == p6.STATUS_NOT_ON_LINK

    def test_info_request(self):
        srv = mkserver()
        m = DHCPv6Message(p6.INFORMATION_REQUEST, 9)
        rep = DHCPv6Message.decode(srv.handle_message(m.encode()))
        assert rep.msg_type == p6.REPLY
        assert rep.get(p6.OPT_DNS_SERVERS) is not None
        assert len(rep.ia_nas()) == 0

    def test_pd_prefixes_distinct(self):
        srv = mkserver()
        seen = set()
        for i in range(4):
            duid = generate_duid_ll(bytes([2, 0, 0, 0, 0, i])).encode()
            m = DHCPv6Message(p6.REQUEST, i)
            m.add(p6.OPT_CLIENTID, duid)
            m.add(p6.OPT_SERVERID, srv.duid.encode())
            m.add_ia_pd(IAPD(1))
            rep = DHCPv6Message.decode(srv.handle_message(m.encode()))
            pfx = rep.ia_pds()[0].prefixes[0]
            assert pfx.prefix_len == 56
            seen.add(pfx.prefix)
        assert len(seen) == 4

    def test_pool_exhaustion_status(self):
        srv = DHCPv6Server(DHCPv6ServerConfig(),
                           address_pool=AddressPool6("2001:db8::/126"),
                           clock=lambda: 0.0)
        codes = []
        for i in range(5):
            duid = generate_duid_ll(bytes([2, 0, 0, 0, 1, i])).encode()
            m = DHCPv6Message(p6.REQUEST, i)
            m.add(p6.OPT_CLIENTID, duid)
            m.add(p6.OPT_SERVERID, srv.duid.encode())
            m.add_ia_na(IANA(1))
            rep = DHCPv6Message.decode(srv.handle_message(m.encode()))
            ia = rep.ia_nas()[0]
            codes.append(ia.status[0] if ia.status else None)
        assert p6.STATUS_NO_ADDRS_AVAIL in codes
        assert codes.count(None) >= 1  # some succeeded

    def test_expiry_cleanup(self):
        t = [1000.0]
        srv = DHCPv6Server(DHCPv6ServerConfig(),
                           address_pool=AddressPool6("2001:db8::/64", 10, 20),
                           clock=lambda: t[0])
        srv.handle_message(solicit(rapid=True).encode())
        assert len(srv.leases) == 1
        t[0] = 1021.0
        assert srv.cleanup_expired() == 1
        assert len(srv.leases) == 0


class TestSLAAC:
    def mkserver(self, **kw):
        return SLAACServer(SLAACConfig(
            prefixes=[PrefixConfig(prefix=bytes.fromhex("20010db801000000") + b"\x00" * 8)],
            rdnss=[bytes.fromhex("20010db8000000000000000000000053")],
            dnssl=["isp.example"],
            mtu=1500, **kw))

    def test_eui64(self):
        iid = eui64_iid(CLIENT_MAC)
        assert iid == bytes([0x02 ^ 0x02, 0xCC, 0x00, 0xFF, 0xFE, 0x00, 0x00, 0x42])
        ll = link_local(CLIENT_MAC)
        assert ll[:2] == b"\xfe\x80" and ll[8:] == iid

    def test_stable_privacy_deterministic(self):
        p = bytes.fromhex("20010db801000000") + b"\x00" * 8
        a = stable_privacy_iid(p, CLIENT_MAC, b"secret")
        b = stable_privacy_iid(p, CLIENT_MAC, b"secret")
        c = stable_privacy_iid(p, CLIENT_MAC, b"other")
        assert a == b and a != c
        assert not a[0] & 0x02  # universal/local bit cleared

    def test_ra_frame_structure(self):
        srv = self.mkserver()
        f = srv.build_ra_frame()
        assert f[12:14] == b"\x86\xdd"  # IPv6
        assert f[20] == 58  # ICMPv6
        assert f[21] == 255  # hop limit
        icmp = f[54:]
        assert icmp[0] == 134  # RA
        # checksum verifies
        src, dst = f[22:38], f[38:54]
        body = bytearray(icmp)
        body[2:4] = b"\x00\x00"
        expect = _icmp6_checksum(src, dst, bytes(body))
        got = struct.unpack(">H", icmp[2:4])[0]
        assert got == expect
        # prefix option present with A+L flags
        assert b"\x03\x04\x40\xc0" in icmp
        # MTU option
        assert struct.pack(">BBHI", 5, 1, 0, 1500) in icmp
        # RDNSS
        assert bytes([25]) in icmp

    def test_managed_flag(self):
        srv = self.mkserver(managed=True, other_config=True)
        ra = srv.build_ra()
        assert ra[5] & 0x80 and ra[5] & 0x40

    def test_rs_answered(self):
        srv = self.mkserver()
        client_ll = link_local(CLIENT_MAC)
        rs = bytearray(54 + 8)
        rs[0:6] = b"\x33\x33\x00\x00\x00\x02"
        rs[6:12] = CLIENT_MAC
        rs[12:14] = b"\x86\xdd"
        rs[14] = 0x60
        rs[20] = 58
        rs[22:38] = client_ll
        rs[54] = 133  # RS
        out = srv.handle_frame(bytes(rs))
        assert out is not None
        assert out[0:6] == CLIENT_MAC  # unicast reply
        assert out[38:54] == client_ll
        assert srv.stats.rs_received == 1

    def test_periodic_tick(self):
        srv = self.mkserver()
        assert len(srv.tick(100.0)) == 1
        assert len(srv.tick(150.0)) == 0
        assert len(srv.tick(301.0)) == 1
        assert srv.stats.periodic == 2

    def test_non_rs_ignored(self):
        srv = self.mkserver()
        assert srv.handle_frame(b"\x00" * 80) is None
        assert srv.handle_frame(b"short") is None


def test_request_for_other_server_discarded():
    srv = mkserver()
    other = generate_duid_ll(b"\x02\xee\x00\x00\x00\x99").encode()
    req = DHCPv6Message(p6.REQUEST, 1)
    req.add(p6.OPT_CLIENTID, CLIENT_DUID)
    req.add(p6.OPT_SERVERID, other)
    req.add_ia_na(IANA(1))
    assert srv.handle_message(req.encode()) is None
    assert len(srv.leases) == 0


def test_rebind_keeps_presented_address_after_state_loss():
    srv = mkserver()
    # client holds 2001:db8:100::77 from before a server restart
    addr = (int(srv.addr_pool.net.network_address) + 0x77).to_bytes(16, "big")
    rebind = DHCPv6Message(p6.REBIND, 2)
    rebind.add(p6.OPT_CLIENTID, CLIENT_DUID)
    ia = IANA(1)
    ia.addresses.append(IAAddress(addr, 100, 200))
    rebind.add_ia_na(ia)
    rep = DHCPv6Message.decode(srv.handle_message(rebind.encode()))
    got = rep.ia_nas()[0].addresses[0].address
    assert got == addr  # NOT renumbered
    assert len(srv.leases) == 1


def test_dnssl_option_length():
    import struct as _s

    srv = SLAACServer(SLAACConfig(dnssl=["isp.example"]))
    ra = srv.build_ra()
    i = ra.find(bytes([31]))  # DNSSL type
    assert i > 0
    length_units = ra[i + 1]
    body = ra[i + 8:]
    # encoded domain: 3isp7example0 = 13 bytes -> padded to 16
    assert length_units == 1 + 16 // 8  # == 3 (RFC 6106)


class TestSlowPathDemux:
    """One slow queue, many protocol servers (cmd/bng socket-per-server
    role collapsed onto the ring): v4 DHCP, v6 DHCP (Eth/IPv6/UDP framed
    here), and SLAAC RS dispatch from raw Ethernet frames."""

    def _demux(self):
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.pool import Pool, PoolManager
        from bng_tpu.control.slaac import SLAACConfig, SLAACServer
        from bng_tpu.control.slowpath import SlowPathDemux
        from bng_tpu.control.dhcpv6.server import (DHCPv6Server,
                                                   DHCPv6ServerConfig)
        from bng_tpu.utils.net import ip_to_u32

        pools = PoolManager(None)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.4.0.0"),
                            prefix_len=24, gateway=ip_to_u32("10.4.0.1"),
                            lease_time=3600))
        v4 = DHCPServer(b"\x02\xbb\x00\x00\x00\x01", ip_to_u32("10.4.0.1"),
                        pools, clock=lambda: 1_753_000_000.0)
        v6 = DHCPv6Server(DHCPv6ServerConfig(),
                          clock=lambda: 1_753_000_000.0)
        ra = SLAACServer(SLAACConfig())
        return SlowPathDemux(dhcp=v4, dhcpv6=v6, slaac=ra), v6

    def test_v4_frames_still_answered(self):
        from bng_tpu.control import dhcp_codec, packets

        demux, _ = self._demux()
        mac = bytes.fromhex("02d40000 0001".replace(" ", ""))
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=0x99)
        disc = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))
        reply = demux(disc)
        assert reply is not None
        assert dhcp_codec.decode(reply[42:]).op == 2
        assert demux.stats["dhcp4"] == 1

    def test_v6_solicit_framed_roundtrip(self):
        from bng_tpu.control import packets

        demux, v6 = self._demux()
        mac = bytes.fromhex("02d600000001")
        link_local = bytes.fromhex("fe80000000000000") + mac[:3] + b"\xff\xfe" + mac[3:]
        sol = solicit()
        frame = packets.udp6_packet(
            mac, bytes.fromhex("333300010002"), link_local,
            bytes.fromhex("ff020000000000000000000000010002"),
            546, 547, sol.encode())
        reply_frame = demux(frame)
        assert reply_frame is not None and demux.stats["dhcp6"] == 1
        # the reply is a well-formed Eth/IPv6/UDP frame back to the client
        assert reply_frame[0:6] == mac  # dst = client
        assert reply_frame[12:14] == b"\x86\xdd"
        assert reply_frame[38:54] == link_local  # v6 dst = client ll
        sport = int.from_bytes(reply_frame[54:56], "big")
        dport = int.from_bytes(reply_frame[56:58], "big")
        assert (sport, dport) == (547, 546)
        adv = DHCPv6Message.decode(reply_frame[62:])
        assert adv.msg_type == p6.ADVERTISE

    def test_rs_gets_ra(self):
        demux, _ = self._demux()
        mac = bytes.fromhex("02d600000002")
        ll = bytes.fromhex("fe80000000000000") + mac[:3] + b"\xff\xfe" + mac[3:]
        # minimal ICMPv6 RS frame
        icmp = bytes([133, 0, 0, 0, 0, 0, 0, 0])
        ip6 = bytes([0x60, 0, 0, 0]) + len(icmp).to_bytes(2, "big") + bytes([58, 255]) + ll \
            + bytes.fromhex("ff020000000000000000000000000002")
        frame = bytes.fromhex("333300000002") + mac + b"\x86\xdd" + ip6 + icmp
        ra = demux(frame)
        assert ra is not None and demux.stats["slaac"] == 1
        assert ra[12:14] == b"\x86\xdd"

    def test_junk_unmatched(self):
        demux, _ = self._demux()
        assert demux(b"\x00" * 10) is None
        assert demux(b"\x02" * 12 + b"\x12\x34" + b"x" * 40) is None
        assert demux.stats["unmatched"] == 2

    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_cli_wires_demux_and_engine_ring_serves_v6(self):
        """End to end through the ENGINE ring: a DHCPv6 SOLICIT frame
        PASSes the device pipeline, the demux answers, the ADVERTISE
        comes back on the TX queue."""
        from bng_tpu.cli import BNGApp, BNGConfig
        from bng_tpu.control import packets
        from bng_tpu.runtime.ring import PyRing

        app = BNGApp(BNGConfig())
        try:
            assert "slowpath" in app.components
            ring = PyRing(nframes=64, frame_size=2048, depth=32)
            mac = bytes.fromhex("02d600000003")
            ll = bytes.fromhex("fe80000000000000") + mac[:3] + b"\xff\xfe" + mac[3:]
            frame = packets.udp6_packet(
                mac, bytes.fromhex("333300010002"), ll,
                bytes.fromhex("ff020000000000000000000000010002"),
                546, 547, solicit().encode())
            assert ring.rx_push(frame, from_access=True)
            app.components["engine"].process_ring(ring)
            got = ring.tx_pop()
            assert got is not None
            adv = DHCPv6Message.decode(got[0][62:])
            assert adv.msg_type == p6.ADVERTISE
        finally:
            app.close()


class TestRelay:
    """RFC 8415 §19 relay handling (reference shape: protocol.go:104-111
    RelayMessage; our server also PROCESSES the chain, which the
    reference's types alone never did)."""

    def _wrap(self, inner: bytes, hops=0, iface=b"eth0.100",
              link=None, peer=None):
        from bng_tpu.control.dhcpv6.protocol import RelayMessage

        return RelayMessage(
            p6.RELAY_FORW, hops,
            link or bytes.fromhex("20010db8000000010000000000000001"),
            peer or bytes.fromhex("fe80000000000000020000fffe000001"),
            options=([(p6.OPT_INTERFACE_ID, iface)] if iface else [])
            + [(p6.OPT_RELAY_MSG, inner)]).encode()

    def test_codec_roundtrip(self):
        from bng_tpu.control.dhcpv6.protocol import RelayMessage

        raw = self._wrap(solicit().encode(), hops=3)
        back = RelayMessage.decode(raw)
        assert back.msg_type == p6.RELAY_FORW and back.hop_count == 3
        assert back.get(p6.OPT_INTERFACE_ID) == b"eth0.100"
        inner = DHCPv6Message.decode(back.get(p6.OPT_RELAY_MSG))
        assert inner.msg_type == p6.SOLICIT

    def test_framed_relay_reply_goes_to_port_547(self):
        """RFC 8415 §7.2: relay agents listen on 547 — the framed
        Relay-Reply must be addressed there, not the client port."""
        from bng_tpu.control import packets as pk
        from bng_tpu.control.dhcpv6.protocol import RelayMessage
        from bng_tpu.control.slowpath import SlowPathDemux

        demux, v6 = self._mkdemux()
        relay_ip = bytes.fromhex("20010db80000000900000000000000fe")
        frame = pk.udp6_packet(
            bytes.fromhex("02e1a7000001"), bytes.fromhex("02bb0000 0001".replace(" ", "")),
            relay_ip, bytes.fromhex("20010db8000000000000000000000001"),
            547, 547, self._wrap(solicit().encode()))
        reply = demux(frame)
        assert reply is not None
        dport = int.from_bytes(reply[56:58], "big")
        assert dport == 547, f"Relay-Reply framed to {dport}"
        rep = RelayMessage.decode(reply[62:])
        assert rep.msg_type == p6.RELAY_REPL

    def _mkdemux(self):
        from bng_tpu.control.slowpath import SlowPathDemux

        v6 = mkserver()
        return SlowPathDemux(dhcpv6=v6), v6

    def test_relayed_solicit_gets_relay_reply(self):
        from bng_tpu.control.dhcpv6.protocol import RelayMessage

        srv = mkserver()
        out = srv.handle_message(self._wrap(solicit().encode()))
        assert out is not None
        rep = RelayMessage.decode(out)
        assert rep.msg_type == p6.RELAY_REPL
        assert rep.hop_count == 0
        # link/peer mirrored so the relay can route the reply
        assert rep.link_address.hex().startswith("20010db8")
        assert rep.peer_address.hex().startswith("fe80")
        # interface-id echoed VERBATIM (the relay's demux key)
        assert rep.get(p6.OPT_INTERFACE_ID) == b"eth0.100"
        adv = DHCPv6Message.decode(rep.get(p6.OPT_RELAY_MSG))
        assert adv.msg_type == p6.ADVERTISE
        assert len(adv.ia_nas()[0].addresses) == 1
        assert srv.stats.relay_forw == 1 and srv.stats.relay_repl == 1

    def test_nested_relay_chain(self):
        from bng_tpu.control.dhcpv6.protocol import RelayMessage

        srv = mkserver()
        lvl1 = self._wrap(solicit().encode(), hops=0, iface=b"inner")
        lvl2 = self._wrap(lvl1, hops=1, iface=b"outer",
                          link=bytes.fromhex("20010db8" + "00" * 12))
        out = srv.handle_message(lvl2)
        rep = RelayMessage.decode(out)
        assert rep.hop_count == 1
        assert rep.get(p6.OPT_INTERFACE_ID) == b"outer"
        inner_rep = RelayMessage.decode(rep.get(p6.OPT_RELAY_MSG))
        assert inner_rep.msg_type == p6.RELAY_REPL
        assert inner_rep.get(p6.OPT_INTERFACE_ID) == b"inner"
        adv = DHCPv6Message.decode(inner_rep.get(p6.OPT_RELAY_MSG))
        assert adv.msg_type == p6.ADVERTISE

    def test_hop_limit_and_garbage(self):
        srv = mkserver()
        # a relay loop (chain deeper than MAX_RELAY_HOPS) is dropped
        wrapped = solicit().encode()
        for _ in range(srv.MAX_RELAY_HOPS + 2):
            wrapped = self._wrap(wrapped, iface=None)
        assert srv.handle_message(wrapped) is None
        # truncated / empty relay frames never crash
        assert srv.handle_message(bytes([p6.RELAY_FORW])) is None
        assert srv.handle_message(self._wrap(b"")) is None
