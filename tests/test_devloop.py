"""Device-resident express serving loop (ISSUE 18).

The acceptance surface of the devloop ring pump
(bng_tpu/devloop/{ring,kernel,host}.py):

- **Bit identity vs the per-batch AOT oracle**: the whole loop path
  (ring staging -> megakernel -> async retire -> wire template
  patch-in) produces verdicts AND reply bytes identical to the PR-13
  per-batch AOT lane across >=3 table/ring geometries, including
  multi-ring fills and a partial flush ring.
- **Ring mechanics**: overfill guard, stale-tail zeroing on take(),
  cursor-vs-host audit agreement after every quiesce/flush barrier.
- **Gray-failure-loud fallbacks** (PAPERS.md): a compile failure at
  setup, a missing megakernel geometry at dispatch, an explicit
  devloop request without AOT admission, and an injected
  ``devloop.dispatch`` fault all degrade to per-batch serving while
  counting `bng_express_fallback_total{reason}` and firing the
  `express_fallback` flight-recorder trigger — never silently.
- **Telemetry attribution**: loop_fill / loop_wait / loop_retire +
  amortized dispatch stages carry samples; ring meta reaches the
  flight record.
- **Ledger cohort identity**: `express_loop` is a cohort key — a
  devloop candidate against per-batch history is the rc=3 refusal,
  never a silent trend (jax-free, mirrors test_ledger's idiom).
- **Determinism**: two fresh stacks over one frame sequence emit
  byte-identical replies and identical loop accounting.

The first geometry below matches tests/test_express and the chaos
devloop_storm scenario, so its compiled programs share the in-process
caches. `make verify-devloop` runs this file; the Makefile tier-1
lane deselects the marker (the driver's `-m 'not slow'` still runs it).
"""

from __future__ import annotations

import numpy as np
import pytest

from bng_tpu.chaos.faults import FAIL, FaultPlan, FaultSpec, armed
from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.metrics import BNGMetrics
from bng_tpu.control.nat import NATManager
from bng_tpu.devloop import kernel as devkernel
from bng_tpu.devloop.ring import CUR_SEQ, DescriptorRing
from bng_tpu.ops import express as ex
from bng_tpu.runtime.engine import Engine
from bng_tpu.runtime.scheduler import SchedulerConfig, TieredScheduler
from bng_tpu.runtime.tables import FastPathTables
from bng_tpu.telemetry import FlightRecorder, RecorderConfig, ledger
from bng_tpu.telemetry import spans as tele
from bng_tpu.telemetry.recorder import TRIG_EXPRESS_FALLBACK
from bng_tpu.utils.net import ip_to_u32, parse_mac

pytestmark = pytest.mark.devloop

SERVER_MAC = parse_mac("02:aa:bb:cc:dd:01")
SERVER_IP = ip_to_u32("10.0.0.1")
NOW = 1_700_000_000


class FakeClock:
    def __init__(self, t=float(NOW)):
        self.t = t

    def __call__(self):
        return self.t


def mac_of(i: int) -> bytes:
    return (0x02B0 << 32 | i).to_bytes(6, "big")


def build_fp(sub_nb=256, vlan_nb=64, cid_nb=64) -> FastPathTables:
    """The test_express subscriber matrix (three pools, vlan/qinq/
    opt82 tiers) — identical constants so compiled programs are shared
    with that suite's cache entries."""
    fp = FastPathTables(sub_nbuckets=sub_nb, vlan_nbuckets=vlan_nb,
                        cid_nbuckets=cid_nb, max_pools=8)
    fp.set_server_config(SERVER_MAC, SERVER_IP)
    fp.add_pool(1, ip_to_u32("10.0.0.0"), 24, SERVER_IP,
                ip_to_u32("8.8.8.8"), ip_to_u32("8.8.4.4"), 3600)
    fp.add_pool(2, ip_to_u32("10.1.0.0"), 16, ip_to_u32("10.1.0.1"),
                ip_to_u32("1.1.1.1"), 0, 7200)
    fp.add_pool(3, ip_to_u32("10.2.0.0"), 20, ip_to_u32("10.2.0.1"),
                0, 0, 600)
    fp.add_subscriber(mac_of(0), 1, ip_to_u32("10.0.0.50"), NOW + 600)
    fp.add_subscriber(mac_of(1), 2, ip_to_u32("10.1.0.60"), NOW + 600)
    fp.add_subscriber(mac_of(2), 3, ip_to_u32("10.2.0.70"), NOW + 600)
    fp.add_vlan_subscriber(100, 0, 1, ip_to_u32("10.0.0.80"), NOW + 600)
    fp.add_vlan_subscriber(200, 30, 2, ip_to_u32("10.1.0.90"), NOW + 600)
    fp.add_circuit_id_subscriber(b"port-7/0/1", 1, ip_to_u32("10.0.0.99"),
                                 NOW + 600)
    fp.add_subscriber(mac_of(9), 1, ip_to_u32("10.0.0.44"), NOW - 5)
    return fp


def dhcp_frame(mac, msg_type, vlans=None, giaddr=0, ciaddr=0,
               broadcast=False, circuit_id=b"", src_ip=0):
    pkt = dhcp_codec.build_request(mac, msg_type, giaddr=giaddr,
                                   ciaddr=ciaddr, broadcast=broadcast,
                                   circuit_id=circuit_id)
    if not circuit_id:
        pkt.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                            bytes([1, 3, 6, 15, 51, 54])))
    payload = pkt.encode().ljust(320, b"\x00")
    return packets.udp_packet(
        src_mac=mac, dst_mac=b"\xff" * 6, src_ip=src_ip,
        dst_ip=0xFFFFFFFF, src_port=68, dst_port=67, payload=payload,
        vlans=vlans)


def case_frames() -> list[bytes]:
    """The test_express addressing/resolution matrix (8 cases)."""
    return [
        dhcp_frame(mac_of(0), dhcp_codec.DISCOVER),
        dhcp_frame(mac_of(1), dhcp_codec.REQUEST),
        dhcp_frame(mac_of(2), dhcp_codec.DISCOVER, broadcast=True),
        dhcp_frame(mac_of(3), dhcp_codec.DISCOVER, vlans=[100]),
        dhcp_frame(mac_of(4), dhcp_codec.DISCOVER, vlans=[200, 30]),
        dhcp_frame(mac_of(5), dhcp_codec.DISCOVER,
                   circuit_id=b"port-7/0/1"),
        dhcp_frame(mac_of(0), dhcp_codec.REQUEST,
                   giaddr=ip_to_u32("10.9.9.9")),
        dhcp_frame(mac_of(0), dhcp_codec.REQUEST,
                   ciaddr=ip_to_u32("10.0.0.50"),
                   src_ip=ip_to_u32("10.0.0.50")),
    ]


def storm_frames(n: int) -> list[bytes]:
    """n frames cycling the case matrix — enough to fill several rings
    plus a partial flush slot."""
    base = case_frames()
    return [base[i % len(base)] for i in range(n)]


def build_sched(fp: FastPathTables, express_batch: int, *,
                loop="devloop", k=4, depth=2, express_aot=True,
                clock=None) -> TieredScheduler:
    clock = clock or FakeClock()
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=64, sub_nat_nbuckets=64)
    eng = Engine(fp, nat, batch_size=32, pkt_slot=512, clock=clock)
    return TieredScheduler(eng, SchedulerConfig(
        express_batch=express_batch, bulk_batch=32,
        express_aot=express_aot, express_loop=loop, devloop_k=k,
        devloop_depth=depth), clock=clock)


def run_frames(sched: TieredScheduler, frames: list[bytes]) -> dict:
    out = sched.process(frames)
    return {"tx": dict(out["tx"]), "slow": sorted(i for i, _ in out["slow"])}


# ---------------------------------------------------------------------------
# bit identity vs the per-batch AOT oracle
# ---------------------------------------------------------------------------

# (express_batch, devloop_k, sub_nb, vlan_nb, cid_nb) — the first row
# matches tests/test_express + chaos devloop_storm for cache sharing
# and stays in the fast tier; the other rows compile their own table +
# megakernel geometries and ride the `slow` mark (the test_express
# mold: `make verify-devloop` runs the WHOLE devloop marker, no slow
# deselect, so the 3-geometry identity claim stays machine-checked on
# every verify)
GEOMETRIES = [
    pytest.param(8, 4, 256, 64, 64),
    pytest.param(8, 2, 128, 32, 32, marks=pytest.mark.slow),
    pytest.param(4, 2, 64, 32, 32, marks=pytest.mark.slow),
]


class TestIdentity:
    @pytest.mark.parametrize("batch,k,sub_nb,vlan_nb,cid_nb", GEOMETRIES)
    def test_replies_bit_identical_to_aot(self, batch, k, sub_nb,
                                          vlan_nb, cid_nb):
        """Multi-ring fill + a partial flush ring: every reply byte and
        every slow-path routing decision matches the per-batch lane."""
        n = batch * k + batch + batch // 2  # k full slots + partial ring
        frames = storm_frames(n)
        oracle = build_sched(build_fp(sub_nb, vlan_nb, cid_nb), batch,
                             loop="aot")
        loop = build_sched(build_fp(sub_nb, vlan_nb, cid_nb), batch,
                           loop="devloop", k=k)
        assert oracle.express_loop == "aot"
        assert loop.express_loop == "devloop"
        want = run_frames(oracle, frames)
        got = run_frames(loop, frames)
        assert got["slow"] == want["slow"]
        assert got["tx"].keys() == want["tx"].keys()
        for i in want["tx"]:
            assert got["tx"][i] == want["tx"][i], f"frame {i} differs"
        dl = loop.stats_snapshot()["express"]["devloop"]
        assert dl["dispatches"] >= 2  # the full ring AND the flush ring
        assert dl["fallback_slots"] == 0

    def test_multi_round_identity_and_lease_state(self):
        """The chain threads ring-to-ring: later rounds see leases the
        earlier rings wrote, identically on both lanes."""
        batch, k = 8, 4
        frames = storm_frames(batch * k)
        oracle = build_sched(build_fp(), batch, loop="aot")
        loop = build_sched(build_fp(), batch, loop="devloop", k=k)
        for _ in range(3):
            want = run_frames(oracle, frames)
            got = run_frames(loop, frames)
            assert got == want
        assert loop._devloop.audit()["consistent"]


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------

class TestRing:
    def test_overfill_guard(self):
        ring = DescriptorRing(k=2, batch=4)
        for _ in range(2):
            ring.fill_slot([], [], [], None, 0.0)
        with pytest.raises(IndexError):
            ring.fill_slot([], [], [], None, 0.0)

    def test_k_validation(self):
        with pytest.raises(ValueError):
            DescriptorRing(k=0, batch=4)

    def test_take_zeroes_stale_tail(self):
        """A prior full occupancy of a staging buffer must not leak
        stale descriptors into a later partial ring's unfilled tail."""
        ring = DescriptorRing(k=2, batch=2, depth=1)
        row = np.full((ex.XD_WORDS,), 7, dtype=np.uint32)
        for _ in range(ring.depth + 2):  # cycle every buffer, full
            ring.fill_slot([row, row], [0, 1], [], None, 0.0)
            ring.fill_slot([row, row], [0, 1], [], None, 0.0)
            ring.take()
        ring.fill_slot([row], [0], [], None, 0.0)  # partial refill
        buf, n, _, _, _ = ring.take()
        assert n == 1
        assert buf[1].sum() == 0, "stale slot survived take()"

    def test_cursor_audit_after_quiesce(self):
        batch, k = 8, 4
        sched = build_sched(build_fp(), batch, loop="devloop", k=k)
        rounds = 3
        for _ in range(rounds):
            sched.process(storm_frames(batch * k + 3))
        sched.quiesce(now=float(NOW))
        audit = sched._devloop.audit()
        assert audit["consistent"], audit
        assert audit["staged"] == 0 and audit["inflight"] == 0
        # every staged slot reached the device exactly once
        assert audit["seq"] == sched._devloop.ring.slots_taken
        cur = sched._devloop.ring.read_cursors()
        assert int(cur[CUR_SEQ]) == audit["seq"]

    def test_snapshot_surfaces_loop_and_ring_stats(self):
        sched = build_sched(build_fp(), 8, loop="devloop", k=4)
        sched.process(storm_frames(32))
        snap = sched.stats_snapshot()["express"]
        assert snap["loop"] == "devloop"
        dl = snap["devloop"]
        assert dl["k"] == 4 and dl["dispatches"] >= 1
        assert 0.0 < dl["occupancy_avg"] <= 1.0


# ---------------------------------------------------------------------------
# gray-failure-loud fallbacks
# ---------------------------------------------------------------------------

class TestFallbacks:
    def test_compile_failure_degrades_to_aot_loudly(self, monkeypatch,
                                                    tmp_path):
        def boom(self, k, batch, device=None):
            raise RuntimeError("mosaic said no")

        monkeypatch.setattr(Engine, "compile_devloop_aot", boom)
        recorder = FlightRecorder(RecorderConfig(out_dir=str(tmp_path)))
        with tele.armed(recorder=recorder):
            sched = build_sched(build_fp(), 8, loop="devloop", k=4)
            assert sched.express_loop == "aot"  # resolved DOWN
            assert sched._devloop is None
            assert sched.express_fallbacks.get(
                "devloop_compile_failed") == 1
            out = run_frames(sched, case_frames())
            assert len(out["tx"]) == 8  # per-batch AOT serves
            assert recorder.triggers.get(TRIG_EXPRESS_FALLBACK, 0) == 1
            assert recorder.dump_paths, "fallback must leave a dump"
        m = BNGMetrics()
        m.collect_scheduler(sched)
        assert ('bng_express_fallback_total{reason='
                '"devloop_compile_failed"} 1' in m.registry.expose())

    def test_geometry_miss_serves_per_batch_loudly(self, tmp_path):
        """Deleting the compiled megakernel out from under a live pump
        (the runtime-retune shape of a geometry miss) must re-dispatch
        every staged slot per-batch — correct replies, loud counters."""
        batch, k = 8, 4
        frames = storm_frames(batch * k)
        oracle = build_sched(build_fp(), batch, loop="aot")
        want = run_frames(oracle, frames)
        sched = build_sched(build_fp(), batch, loop="devloop", k=k)
        key = devkernel.devloop_key(sched.engine, k, batch,
                                    sched._express_dev)
        saved = devkernel._DEVLOOP_AOT.pop(key)
        try:
            recorder = FlightRecorder(RecorderConfig(out_dir=str(tmp_path)))
            with tele.armed(recorder=recorder):
                got = run_frames(sched, frames)
                assert recorder.triggers.get(TRIG_EXPRESS_FALLBACK, 0) >= 1
        finally:
            devkernel._DEVLOOP_AOT[key] = saved
        assert got == want  # byte identity survives the degrade
        assert sched.express_fallbacks.get("devloop_miss", 0) >= 1
        dl = sched.stats_snapshot()["express"]["devloop"]
        assert dl["fallback_slots"] == k
        assert sched._devloop.audit()["consistent"]
        m = BNGMetrics()
        m.collect_scheduler(sched)
        assert ('bng_express_fallback_total{reason="devloop_miss"}'
                in m.registry.expose())

    def test_devloop_without_aot_admission_falls_back(self):
        sched = build_sched(build_fp(), 8, loop="devloop",
                            express_aot=False)
        assert sched.express_loop == "aot"
        assert sched.express_fallbacks.get("devloop_unavailable") == 1
        assert len(run_frames(sched, case_frames())["tx"]) == 8

    def test_env_var_overrides_config(self, monkeypatch):
        monkeypatch.setenv("BNG_EXPRESS_LOOP", "devloop")
        sched = build_sched(build_fp(), 8, loop="aot", k=4)
        assert sched.express_loop == "devloop"

    def test_invalid_loop_spelling_raises(self):
        with pytest.raises(ValueError):
            build_sched(build_fp(), 8, loop="turbo")

    def test_injected_dispatch_fault_mid_storm(self):
        """The chaos plant (devloop_storm's mechanism, unit-sized): one
        injected ``devloop.dispatch`` fail re-dispatches that ring's
        slots per-batch; replies stay byte-identical to a clean run."""
        batch, k = 8, 4
        frames = storm_frames(batch * k)
        oracle = build_sched(build_fp(), batch, loop="devloop", k=k)
        want = run_frames(oracle, frames)
        sched = build_sched(build_fp(), batch, loop="devloop", k=k)
        plan = FaultPlan(0, [FaultSpec("devloop.dispatch", FAIL)])
        with armed(plan, log=False) as inj:
            got = run_frames(sched, frames)
        assert got == want
        assert inj.injected == [("devloop.dispatch", "fail", 1)]
        assert sched.express_fallbacks.get("devloop_miss") == 1
        assert sched._devloop.fallback_slots == k
        assert sched._devloop.audit()["consistent"]


# ---------------------------------------------------------------------------
# telemetry attribution
# ---------------------------------------------------------------------------

class TestTelemetry:
    def test_loop_stages_carry_samples(self):
        batch, k = 8, 4
        with tele.armed() as tracer:
            sched = build_sched(build_fp(), batch, loop="devloop", k=k)
            sched.process(storm_frames(batch * k + 3))
            bd = tracer.breakdown()
        for stage in ("loop_fill", "loop_wait", "loop_retire",
                      "dispatch", "total"):
            assert stage in bd, f"{stage} missing from {sorted(bd)}"
        # amortization conserves batch counts: every staged batch gets
        # one fill, one wait and one amortized dispatch lap
        assert bd["loop_fill"]["count"] == bd["dispatch"]["count"]
        assert bd["loop_fill"]["count"] == bd["loop_wait"]["count"]

    def test_ring_meta_reaches_flight_record(self, tmp_path):
        recorder = FlightRecorder(RecorderConfig(out_dir=str(tmp_path)))
        with tele.armed(recorder=recorder):
            sched = build_sched(build_fp(), 8, loop="devloop", k=4)
            sched.process(storm_frames(32))
            assert recorder.meta.get("express_program") == "devloop"
            ring_meta = recorder.meta.get("devloop_ring")
            assert ring_meta["k"] == 4 and ring_meta["slots"] == 4


# ---------------------------------------------------------------------------
# ledger cohort identity (jax-free — mirrors test_ledger's idiom)
# ---------------------------------------------------------------------------

_STAGES = {"dispatch": 100.0, "device": 40.0, "total": 800.0}


def _line(i: int, scale: float = 1.0) -> dict:
    return {
        "schema_version": 1, "run_id": f"dl{i:02d}",
        "metric": "Mpps/chip DHCP+NAT44 fast path",
        "value": 0.05 * scale, "unit": "Mpps",
        "batch": 8192, "subscribers": 1_000_000, "flows": 1_000_000,
        "device": "TPU v5e chip0",
        "env": {"platform": "tpu", "device_kind": "TPU v5e"},
        "stage_breakdown": {
            s: {"count": 200, "p50_us": v / 2, "p99_us": v * (1 + 0.02 * i),
                "p999_us": v * 1.2, "mean_us": v / 2, "max_us": v * 1.3}
            for s, v in _STAGES.items()},
    }


class TestLedgerCohort:
    def test_accessor_defaults_to_per_batch(self):
        assert ledger.express_loop({}) == "per-batch"
        assert ledger.express_loop({"express_loop": "devloop"}) == "devloop"

    def test_devloop_never_scored_against_per_batch_history(self,
                                                            tmp_path):
        """The loop changes what a `dispatch` lap measures (one batch
        vs an amortized ring share): rc=3 refusal, never a trend."""
        path = str(tmp_path / "ledger.jsonl")
        for i in range(5):
            ledger.append(path, _line(i))  # unstamped -> per-batch
        cand = _line(9, scale=5.0)  # would look like a huge move
        cand["express_loop"] = "devloop"
        ledger.append(path, cand)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_INCOMPARABLE
        assert "devloop" in rep.notes[0]

    def test_devloop_cohort_gates_within_itself(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        for i in range(5):
            ledger.append(path, _line(i))
        for i in range(4):  # devloop history: 2x the per-batch headline
            ln = _line(20 + i, scale=2.0)
            ln["express_loop"] = "devloop"
            ledger.append(path, ln)
        bad = _line(30, scale=1.1)  # regressed vs ITS cohort only
        bad["express_loop"] = "devloop"
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION, rep.to_dict()


# ---------------------------------------------------------------------------
# determinism
# ---------------------------------------------------------------------------

class TestDeterminism:
    def test_two_fresh_stacks_are_byte_identical(self):
        batch, k = 8, 4
        frames = storm_frames(batch * (k + 1) + 5)

        def sweep():
            sched = build_sched(build_fp(), batch, loop="devloop", k=k)
            out = [run_frames(sched, frames) for _ in range(2)]
            sched.quiesce(now=float(NOW))
            return out, sched._devloop.stats(), sched._devloop.audit()

        out_a, stats_a, audit_a = sweep()
        out_b, stats_b, audit_b = sweep()
        assert out_a == out_b
        assert stats_a == stats_b
        assert audit_a == audit_b and audit_a["consistent"]
