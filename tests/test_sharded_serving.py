"""The ICI-sharded dataplane as the SERVING path (ISSUE 12).

Covers the promotion contract end to end:

* exact missteer accounting — wrong-shard punts split out of
  ShardTelemetry's PASS class (bng_shard_missteer_total), zero on a
  steered ring, nonzero when steering is sabotaged;
* sharded checkpoints — same-topology slot-exact round-trip, N->M and
  N->1->N re-shard round-trips audit-clean, reject-to-cold-start on
  geometry/CRC mismatch and on cross-topology (engine<->sharded) loads;
* sharded blue/green swap — audited flip, crash-at-flip keeps the
  active cluster;
* `bng run --shards N` — the composed app serves DORA through the
  steered ring with zero missteers, checkpoints, swaps, audits;
* ledger cohort identity — `n_shards` keys the cohort, a sharded
  candidate against single-device history refuses with both identities
  named (rc=3).

Every cluster here shares ONE geometry (the cli --shards default at
shard_nbuckets=64) so the mesh programs compile once per suite run.
"""

import numpy as np
import pytest

from bng_tpu.control import packets
from bng_tpu.control.dhcp_server import DHCPServer
from bng_tpu.parallel.sharded import ShardedCluster, ShardedFastPathSink
from bng_tpu.runtime.checkpoint import (CheckpointError,
                                        build_sharded_checkpoint,
                                        decode_checkpoint,
                                        encode_checkpoint,
                                        restore_checkpoint,
                                        restore_sharded_checkpoint)
from bng_tpu.utils.net import fnv1a32, ip_to_u32, parse_mac

pytestmark = pytest.mark.sharded

NOW = 1_753_000_000
SERVER_MAC = parse_mac("02:aa:bb:cc:dd:01")
SERVER_IP = ip_to_u32("10.0.0.1")
GEOM = dict(batch_per_shard=8, sub_nbuckets=64, vlan_nbuckets=64,
            cid_nbuckets=64, nat_sessions_nbuckets=64, qos_nbuckets=64,
            spoof_nbuckets=64)


def make_cluster(n: int = 2, **over) -> ShardedCluster:
    kw = {**GEOM, **over}
    cl = ShardedCluster(n, **kw)
    cl.set_server_config_all(SERVER_MAC, SERVER_IP)
    cl.add_pool_all(1, ip_to_u32("10.0.0.0"), 24, SERVER_IP,
                    lease_time=3600)
    return cl


def mac_i(i: int) -> bytes:
    return (0x02D0 << 32 | i).to_bytes(6, "big")


def populate(cl: ShardedCluster, n_subs: int = 8) -> list[bytes]:
    macs = [mac_i(i) for i in range(n_subs)]
    for i, m in enumerate(macs):
        cl.add_subscriber(m, pool_id=1, ip=ip_to_u32(f"10.0.0.{50 + i}"),
                          lease_expiry=NOW + 600)
    cl.allocate_nat(ip_to_u32("10.0.0.50"), NOW)
    cl.set_qos(ip_to_u32("10.0.0.50"), down_bps=8_000, up_bps=8_000,
               down_burst=1000, up_burst=1000)
    cl.add_spoof_binding(macs[0], ip_to_u32("10.0.0.50"), 1)
    if cl.garden is not None:
        cl.set_gardened(ip_to_u32("10.0.0.51"), True)
    return macs


def discover(mac: bytes, xid: int) -> bytes:
    from bng_tpu.control import dhcp_codec

    p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
    p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              p.encode().ljust(320, b"\x00"))


def audit_clean(cl, dhcp=None):
    from bng_tpu.chaos.invariants import audit_invariants

    rep = audit_invariants(cluster=cl, dhcp=dhcp, check_roundtrip=False)
    assert rep.ok, rep.to_dict()
    return rep


# ---------------------------------------------------------------------------
# missteer accounting
# ---------------------------------------------------------------------------

class TestMissteer:
    def test_steered_ring_counts_zero_missteers(self):
        """Ring-steered owner batches: cached renewals TX on device,
        slow-path DHCP misses stay legit PASSes, missteer == 0."""
        cl = make_cluster()
        macs = populate(cl)
        cl.sync_tables()
        ring = cl.make_ring(nframes=256, frame_size=2048, depth=64)
        # cached subscriber -> device TX; unknown subscriber -> legit punt
        assert ring.rx_push(discover(macs[0], 1), from_access=True)
        assert ring.rx_push(discover(mac_i(900), 2), from_access=True)
        served = {}

        def slow(frame):
            served["punt"] = True
            return None

        got = cl.process_ring(ring, NOW, 0, slow_path=slow)
        assert got == 2
        snap = cl.telemetry.snapshot()
        assert snap["missteer_total"] == 0
        assert snap["pass_total"] == 1  # the unknown MAC's legit punt
        assert served.get("punt")
        assert snap["psum_dhcp_hits"] >= 1

    def test_sabotaged_steering_counts_missteer_exactly(self):
        """A downstream frame for shard-owned NAT state landing on the
        WRONG shard (unsteered ring) is a missteer; the classifier
        counts it apart from legit slow-path punts."""
        from bng_tpu.runtime.ring import make_ring

        cl = make_cluster()
        populate(cl)
        nat_priv = ip_to_u32("10.0.0.50")
        owner = cl.affinity_shard_ip(nat_priv)
        _o, flow = cl.handle_new_flow(nat_priv, ip_to_u32("1.2.3.4"),
                                      40000, 443, 17, 600, NOW)
        assert flow is not None
        pub_ip, pub_port = flow
        cl.sync_tables()
        # an UNSTEERED ring (no pub-IP registration): downstream frames
        # fall back to dst-IP hashing — force the wrong shard
        ring = make_ring(nframes=256, frame_size=2048, depth=64,
                         prefer_native=True, n_shards=cl.n)
        down = packets.udp_packet(SERVER_MAC, mac_i(0),
                                  ip_to_u32("1.2.3.4"), pub_ip,
                                  443, pub_port, b"r" * 32)
        hashed = fnv1a32(int(pub_ip).to_bytes(4, "big")) % cl.n
        if hashed == owner:
            pytest.skip("dst-hash happens to match the owner for this "
                        "geometry — sabotage not expressible")
        assert ring.rx_push(down, from_access=False)
        got = cl.process_ring(ring, NOW + 1, 1000)
        assert got == 1
        snap = cl.telemetry.snapshot()
        assert snap["missteer_total"] == 1
        assert snap["pass_total"] == 0  # split OUT of the PASS class
        assert snap["per_shard"][hashed]["missteers"] == 1

    def test_metrics_export_missteer_family(self):
        from bng_tpu.control.metrics import BNGMetrics

        cl = make_cluster()
        cl.telemetry.missteers[1] = 3
        m = BNGMetrics()
        m.collect_sharded(cl)
        text = m.expose()
        assert 'bng_shard_missteer_total{shard="1"} 3' in text


# ---------------------------------------------------------------------------
# sharded checkpoints: round-trips, re-shard, rejects
# ---------------------------------------------------------------------------

def save_bytes(cl, dhcp=None) -> bytes:
    return encode_checkpoint(
        build_sharded_checkpoint(cl, 1, float(NOW), dhcp=dhcp))


class TestShardedCheckpoint:
    def test_same_topology_roundtrip_audit_clean(self):
        cl = make_cluster()
        macs = populate(cl)
        cl.sync_tables()
        data = save_bytes(cl)

        fresh = make_cluster()
        rows = restore_sharded_checkpoint(decode_checkpoint(data), fresh,
                                          now=NOW)
        assert any(k.startswith("shard0.") for k in rows)
        for m in macs:
            assert fresh.get_subscriber(m) is not None
        # NAT block survived slot-exact on its owner shard
        owner = fresh.affinity_shard_ip(ip_to_u32("10.0.0.50"))
        assert ip_to_u32("10.0.0.50") in fresh.nat[owner].blocks
        audit_clean(fresh)

    def test_reshard_n_to_m_and_back_audit_clean(self):
        """2 -> 1 -> 2: every subscriber row and every piece of
        affinity state lands on its owner under each topology, audits
        clean at every step (the N->M and N->1->N satellite)."""
        cl = make_cluster(2)
        macs = populate(cl)
        cl.sync_tables()
        data2 = save_bytes(cl)

        cl1 = make_cluster(1)
        rows = restore_sharded_checkpoint(decode_checkpoint(data2), cl1,
                                          now=NOW)
        assert rows["resharded_from"] == 2 and rows["resharded_to"] == 1
        assert rows["dhcp_rows"] == len(macs)
        for m in macs:
            assert cl1.get_subscriber(m) is not None
        audit_clean(cl1)

        data1 = save_bytes(cl1)
        cl2 = make_cluster(2)
        rows = restore_sharded_checkpoint(decode_checkpoint(data1), cl2,
                                          now=NOW)
        assert rows["resharded_from"] == 1 and rows["resharded_to"] == 2
        for m in macs:
            assert cl2.get_subscriber(m) is not None
        # affinity state on its owner under the final topology
        nat_priv = ip_to_u32("10.0.0.50")
        owner = cl2.affinity_shard_ip(nat_priv)
        assert nat_priv in cl2.nat[owner].blocks
        assert cl2.qos[owner].up.lookup(nat_priv) is not None
        audit_clean(cl2)

    def test_reshard_serves_on_device_after_restore(self):
        """Post-re-shard, a cached DISCOVER must be answered BY THE
        MESH on the new topology (rows reachable via owner routing)."""
        cl = make_cluster(2)
        macs = populate(cl)
        cl.sync_tables()
        data = save_bytes(cl)
        cl1 = make_cluster(1)
        restore_sharded_checkpoint(decode_checkpoint(data), cl1, now=NOW)
        ring = cl1.make_ring(nframes=256, frame_size=2048, depth=64)
        assert ring.rx_push(discover(macs[3], 9), from_access=True)
        cl1.process_ring(ring, NOW, 0)
        assert ring.tx_pop() is not None
        assert cl1.telemetry.psum_dhcp_hits >= 1

    def test_crc_corruption_rejects(self):
        cl = make_cluster()
        populate(cl)
        data = bytearray(save_bytes(cl))
        data[-5] ^= 0xFF
        with pytest.raises(CheckpointError):
            decode_checkpoint(bytes(data))

    def test_geometry_mismatch_rejects_to_cold_start(self):
        cl = make_cluster()
        populate(cl)
        data = save_bytes(cl)
        shrunk = make_cluster(2, sub_nbuckets=128)  # different geometry
        with pytest.raises(CheckpointError):
            restore_sharded_checkpoint(decode_checkpoint(data), shrunk,
                                       now=NOW)

    def test_cross_topology_loads_reject_both_ways(self):
        """A single-engine snapshot cannot hydrate a cluster and a
        sharded snapshot cannot hydrate a single-engine process."""
        from bng_tpu.runtime.checkpoint import build_checkpoint
        from bng_tpu.runtime.tables import FastPathTables

        cl = make_cluster()
        populate(cl)
        sharded_ckpt = decode_checkpoint(save_bytes(cl))
        with pytest.raises(CheckpointError, match="single-engine"):
            restore_checkpoint(sharded_ckpt,
                               fastpath=FastPathTables(sub_nbuckets=64))

        flat = build_checkpoint(1, float(NOW),
                                fastpath=FastPathTables(sub_nbuckets=64))
        with pytest.raises(CheckpointError, match="sharded"):
            restore_sharded_checkpoint(flat, make_cluster(), now=NOW)


# ---------------------------------------------------------------------------
# sharded blue/green swap
# ---------------------------------------------------------------------------

class TestShardedSwap:
    def test_clean_swap_flips_and_serves(self):
        from bng_tpu.runtime.ops import sharded_blue_green_swap

        cl = make_cluster()
        macs = populate(cl)
        cl.sync_tables()
        comps = {"cluster": cl}
        rep = sharded_blue_green_swap(comps)
        assert rep["outcome"] == "ok", rep
        assert rep["audit_ok"]
        assert comps["cluster"] is not cl
        # the standby serves the hydrated rows on device
        standby = comps["cluster"]
        ring = standby.make_ring(nframes=256, frame_size=2048, depth=64)
        assert ring.rx_push(discover(macs[0], 5), from_access=True)
        standby.process_ring(ring, NOW, 0)
        assert ring.tx_pop() is not None
        assert standby.telemetry.psum_dhcp_hits >= 1

    def test_crash_at_flip_keeps_active(self):
        from bng_tpu.chaos.faults import FAIL, FaultPlan, FaultSpec, armed
        from bng_tpu.runtime.ops import sharded_blue_green_swap

        cl = make_cluster()
        populate(cl)
        cl.sync_tables()
        comps = {"cluster": cl}
        plan = FaultPlan(3, [FaultSpec("ops.swap", FAIL, at_hit=1)])
        with armed(plan, log=False):
            rep = sharded_blue_green_swap(comps)
        assert rep["outcome"] == "failed"
        assert comps["cluster"] is cl
        audit_clean(cl)


# ---------------------------------------------------------------------------
# the composed serving path: bng run --shards N
# ---------------------------------------------------------------------------

class TestShardedApp:
    @pytest.fixture()
    def app(self):
        from bng_tpu.cli import BNGApp, BNGConfig

        cfg = BNGConfig(shards=2, shard_nbuckets=64, batch_size=16,
                        synthetic_subs=8, dhcpv6_enabled=False,
                        slaac_enabled=False, metrics_enabled=True)
        app = BNGApp(cfg)
        yield app
        app.close()

    def test_run_shards_end_to_end(self, app):
        """`bng run --shards 2` on the forced host-device CPU mesh:
        ring-steered batches reach owner shards with zero missteers,
        the slow path serves OFFERs, a sharded swap flips live, and the
        full app audit is clean (the acceptance-criteria path)."""
        c = app.components
        assert "cluster" in c and "engine" not in c
        for _ in range(20):
            app.drive_once()
        c["cluster"].flush_pipeline(app._slow_path)
        s = app.stats()
        assert s["dhcp"]["offer"] > 0
        assert s["sharded"]["missteers"] == 0
        assert s["sharded"]["frames"] > 0

        rep = app.engine_swap()
        assert rep["outcome"] == "ok", rep
        for _ in range(5):
            app.drive_once()
        c["cluster"].flush_pipeline(app._slow_path)

        # post-swap control-plane writes must follow the flip: a NEW
        # DORA's subscriber row lands on the SERVING cluster's shards
        # (the sink resolves the live reference, never the retired one)
        from bng_tpu.control import dhcp_codec

        dhcp = c["dhcp"]
        m = mac_i(321)
        offer = dhcp.handle_frame(discover(m, 0x71))
        assert offer is not None
        op = dhcp_codec.decode(packets.decode(offer).payload)
        req = dhcp_codec.build_request(m, dhcp_codec.REQUEST, xid=0x72,
                                       requested_ip=op.yiaddr,
                                       server_id=SERVER_IP)
        fr = packets.udp_packet(m, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                req.encode().ljust(320, b"\x00"))
        assert dhcp.handle_frame(fr) is not None
        assert c["cluster"].get_subscriber(m) is not None

        from bng_tpu.chaos.invariants import audit_app

        audit = audit_app(app)
        assert audit.ok, audit.to_dict()

    def test_full_dora_renewal_hits_device(self, app):
        """A full DORA through the composed app's steered ring, then a
        renewal DISCOVER answered ON DEVICE (psum hit) — the promoted
        path's fast-path proof with the missteer counter at 0."""
        from bng_tpu.control import dhcp_codec

        c = app.components
        ring = c["ring"]
        cl = c["cluster"]
        m = mac_i(77)

        def beat():
            app.drive_once()
            app.drive_once()
            cl.flush_pipeline(app._slow_path)
            return ring.tx_pop()

        assert ring.rx_push(discover(m, 0x51), from_access=True)
        offer = None
        for _ in range(6):
            got = beat()
            if got is not None:
                offer = got[0]
                break
        assert offer is not None
        od = packets.decode(offer)
        op = dhcp_codec.decode(od.payload)
        req = dhcp_codec.build_request(m, dhcp_codec.REQUEST, xid=0x52,
                                       requested_ip=op.yiaddr,
                                       server_id=od.src_ip)
        fr = packets.udp_packet(m, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                req.encode().ljust(320, b"\x00"))
        assert ring.rx_push(fr, from_access=True)
        for _ in range(6):
            if beat() is not None:
                break
        hits_before = cl.telemetry.psum_dhcp_hits
        assert ring.rx_push(discover(m, 0x53), from_access=True)
        reply = None
        for _ in range(6):
            got = beat()
            if got is not None:
                reply = got[0]
                break
        assert reply is not None
        assert cl.telemetry.psum_dhcp_hits > hits_before
        assert cl.telemetry.snapshot()["missteer_total"] == 0


# ---------------------------------------------------------------------------
# ledger cohort identity: n_shards
# ---------------------------------------------------------------------------

@pytest.mark.perf
class TestLedgerShardIdentity:
    def _line(self, value, shards=None, devices=None, **extra):
        ln = {"metric": "Sharded serving Mpps (ring-steered)",
              "value": value, "unit": "Mpps", "batch": 128,
              "device": "cpu", "schema_version": 1}
        if shards is not None:
            ln["n_shards"] = shards
        if devices is not None:
            ln["devices"] = devices
        ln.update(extra)
        return ln

    def test_n_shards_defaults_and_legacy_devices(self):
        from bng_tpu.telemetry import ledger

        assert ledger.n_shards({}) == 1
        assert ledger.n_shards({"n_shards": 8}) == 8
        assert ledger.n_shards({"devices": 4}) == 4  # config-5 spelling
        assert ledger.cohort_key(self._line(1.0, shards=8)) != \
            ledger.cohort_key(self._line(1.0, shards=1))

    def test_sharded_candidate_refuses_single_device_history(self):
        """rc=3 with BOTH identities named: an aggregate 8-shard Mpps
        line never trends against single-device history."""
        from bng_tpu.telemetry import ledger

        lines = [self._line(1.0) for _ in range(4)]
        lines.append(self._line(8.0, shards=8))
        rep = ledger.gate(lines)
        assert rep.rc == ledger.GATE_INCOMPARABLE
        note = " ".join(rep.notes)
        assert "shards=8" in note and "shards=1" in note

    def test_same_shard_cohort_gates_normally(self):
        from bng_tpu.telemetry import ledger

        lines = [self._line(8.0, shards=8) for _ in range(5)]
        lines.append(self._line(7.9, shards=8))
        assert ledger.gate(lines).rc == ledger.GATE_OK
        lines[-1] = self._line(2.0, shards=8)  # 4x collapse
        rep = ledger.gate(lines)
        assert rep.rc == ledger.GATE_REGRESSION


# ---------------------------------------------------------------------------
# the sink facade: owner routing for the DHCP server's writes
# ---------------------------------------------------------------------------

class TestShardedSink:
    def test_sink_routes_rows_to_owner_shards(self):
        cl = make_cluster()
        sink = ShardedFastPathSink(cl)
        macs = [mac_i(100 + i) for i in range(8)]
        for i, m in enumerate(macs):
            sink.add_subscriber(m, pool_id=1, ip=ip_to_u32(f"10.0.1.{i}"),
                                lease_expiry=NOW + 60)
        placed = 0
        for m in macs:
            o = cl.dhcp_sub_shard(m)
            assert cl.fastpath[o].get_subscriber(m) is not None
            other = (o + 1) % cl.n
            assert cl.fastpath[other].get_subscriber(m) is None
            placed += 1
        assert placed == len(macs)
        assert sink.remove_subscriber(macs[0])
        assert cl.get_subscriber(macs[0]) is None

    def test_sink_feeds_dhcp_server(self):
        """The DHCP server's _update_fastpath writes land on owner
        shards through the sink (the serving path's control plane)."""
        from bng_tpu.control.pool import Pool, PoolManager

        cl = make_cluster()
        sink = ShardedFastPathSink(cl)
        pools = PoolManager(fastpath_tables=sink)
        pools.add_pool(Pool(pool_id=2, network=ip_to_u32("10.9.0.0"),
                            prefix_len=24, gateway=ip_to_u32("10.9.0.1"),
                            dns_primary=ip_to_u32("1.1.1.1"),
                            lease_time=120))
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                            fastpath_tables=sink)
        m = mac_i(500)
        offer = server.handle_frame(discover(m, 0x99))
        assert offer is not None
        from bng_tpu.control import dhcp_codec

        op = dhcp_codec.decode(packets.decode(offer).payload)
        req = dhcp_codec.build_request(m, dhcp_codec.REQUEST, xid=0x9A,
                                       requested_ip=op.yiaddr,
                                       server_id=SERVER_IP)
        fr = packets.udp_packet(m, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                req.encode().ljust(320, b"\x00"))
        assert server.handle_frame(fr) is not None
        o = cl.dhcp_sub_shard(m)
        assert cl.fastpath[o].get_subscriber(m) is not None
