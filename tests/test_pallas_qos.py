"""Pallas segmented-prefix kernel vs the sort-based reference.

Interpret mode on CPU (the compile-and-lower gate of SURVEY.md §4.3 —
the TPU analog of loading eBPF programs through the verifier): same
inputs, bit-identical admission decisions between the two impls.
"""

import numpy as np
import jax.numpy as jnp
import pytest

import bng_tpu.ops.qos as qos_mod
from bng_tpu.ops.pallas_qos import LANE_TILE, seg_prefix_total
from bng_tpu.ops.qos import qos_kernel
from bng_tpu.runtime.engine import QoSTables


def ref_prefix_total(slot, vec):
    """O(B^2) numpy reference."""
    B = len(slot)
    pref = np.zeros((B,), dtype=np.float64)
    tot = np.zeros((B,), dtype=np.float64)
    for i in range(B):
        same = slot == slot[i]
        pref[i] = vec[: i + 1][same[: i + 1]].sum()
        tot[i] = vec[same].sum()
    return pref, tot


class TestSegPrefixTotal:
    @pytest.mark.parametrize("B", [64, LANE_TILE, 3 * LANE_TILE, 1000])
    def test_matches_reference(self, B):
        rng = np.random.default_rng(B)
        slot = rng.integers(0, max(2, B // 8), size=B).astype(np.int32)
        vec = rng.integers(64, 1500, size=B).astype(np.float32)
        pref, tot = seg_prefix_total(jnp.asarray(slot), jnp.asarray(vec),
                                     interpret=True)
        ref_p, ref_t = ref_prefix_total(slot, vec)
        np.testing.assert_allclose(np.asarray(pref), ref_p, rtol=0, atol=0)
        np.testing.assert_allclose(np.asarray(tot), ref_t, rtol=0, atol=0)

    def test_unique_negative_ids_never_group(self):
        B = 128
        slot = -1 - np.arange(B, dtype=np.int32)
        vec = np.full((B,), 100.0, dtype=np.float32)
        pref, tot = seg_prefix_total(jnp.asarray(slot), jnp.asarray(vec),
                                     interpret=True)
        np.testing.assert_array_equal(np.asarray(pref), vec)
        np.testing.assert_array_equal(np.asarray(tot), vec)


class TestQoSImplParity:
    def _run(self, impl, ips, lens, qos):
        old = qos_mod.PREFIX_IMPL
        qos_mod.PREFIX_IMPL = impl
        try:
            res = qos_kernel(jnp.asarray(ips), jnp.asarray(lens),
                             jnp.ones((len(ips),), dtype=bool),
                             qos.up.device_state(), qos.geom, jnp.uint32(1))
            return (np.asarray(res.allowed), np.asarray(res.dropped),
                    np.asarray(res.table.rows), np.asarray(res.stats))
        finally:
            qos_mod.PREFIX_IMPL = old

    def test_sort_and_pallas_agree(self):
        B = 512
        qos = QoSTables(nbuckets=256)
        n_subs = 16
        for i in range(n_subs):
            # tiny buckets so some lanes drop mid-batch
            qos.set_subscriber((10 << 24) | (i + 2), down_bps=8_000_000,
                               up_bps=8_000_000, up_burst=3000, down_burst=3000)
        rng = np.random.default_rng(0)
        ips = ((10 << 24) + 2 + rng.integers(0, n_subs * 2, size=B)).astype(np.uint32)
        lens = rng.integers(100, 1500, size=B).astype(np.uint32)

        a_sort = self._run("sort", ips, lens, qos)
        qos2 = QoSTables(nbuckets=256)
        for i in range(n_subs):
            qos2.set_subscriber((10 << 24) | (i + 2), down_bps=8_000_000,
                                up_bps=8_000_000, up_burst=3000, down_burst=3000)
        a_pal = self._run("pallas", ips, lens, qos2)

        np.testing.assert_array_equal(a_sort[0], a_pal[0])  # allowed
        np.testing.assert_array_equal(a_sort[1], a_pal[1])  # dropped
        np.testing.assert_array_equal(a_sort[2], a_pal[2])  # token state
        np.testing.assert_array_equal(a_sort[3], a_pal[3])  # stats

    def test_pallas_sequential_order_within_bucket(self):
        # one bucket, tokens for exactly 2 packets: lanes 0,1 pass, 2+ drop
        qos = QoSTables(nbuckets=64)
        qos.set_subscriber(0x0A000002, down_bps=8_000, up_bps=8_000,
                           up_burst=2000, down_burst=2000)
        old = qos_mod.PREFIX_IMPL
        qos_mod.PREFIX_IMPL = "pallas"
        try:
            ips = np.full((8,), 0x0A000002, dtype=np.uint32)
            lens = np.full((8,), 1000, dtype=np.uint32)
            res = qos_kernel(jnp.asarray(ips), jnp.asarray(lens),
                             jnp.ones((8,), dtype=bool),
                             qos.up.device_state(), qos.geom, jnp.uint32(1))
            allowed = np.asarray(res.allowed)
            assert list(allowed) == [True, True] + [False] * 6
        finally:
            qos_mod.PREFIX_IMPL = old
