"""Dormant-module coverage (ISSUE 17 satellite): QinQ double-tag parity
and ZTP bootstrap retry/backoff.

control/qinq.py and control/ztp.py shipped as parity ports with no
tests of their own. QinQ matters to the edge subsystem because the
tap/route tables key subscribers the same way the classifier does — a
drift between `VLANPair.key()` and the ring parser's {s_tag,c_tag}
packing would silently steer double-tagged subscribers to the wrong
shard. ZTP matters because a BNG that can't bootstrap never gets
warrants or routes pushed at all; the backoff loop is the part that
hides bugs (it swallows transport errors by design).
"""

import jax.numpy as jnp
import pytest

from bng_tpu.control.deviceauth import DeviceIdentity
from bng_tpu.control.qinq import (QinQConfig, QinQMapper, VLANPair,
                                  VLANRange)
from bng_tpu.control.ztp import (BootstrapClient, BootstrapConfig,
                                 BootstrapPending, build_vendor_option,
                                 discover_from_lease, extract_nexus_url,
                                 parse_vendor_options)
from bng_tpu.ops.parse import parse_batch

pytestmark = pytest.mark.edge


# ---------------------------------------------------------------------------
# QinQ: pair model + registry
# ---------------------------------------------------------------------------

class TestVLANPair:
    def test_tag_states(self):
        assert VLANPair(100, 200).is_double_tagged
        assert VLANPair(0, 200).is_single_tagged
        assert VLANPair().is_untagged
        assert str(VLANPair(100, 200)) == "100.200"
        assert str(VLANPair(0, 200)) == "200"
        assert str(VLANPair()) == "untagged"

    def test_out_of_range_rejected(self):
        with pytest.raises(ValueError):
            VLANPair(4096, 1)
        with pytest.raises(ValueError):
            VLANPair(1, -1)

    def test_key_packs_s_high_c_low(self):
        assert VLANPair(0x123, 0x456).key() == (0x123 << 16) | 0x456
        assert VLANPair(0, 7).key() == 7

    def test_range(self):
        r = VLANRange(10, 20)
        assert r.contains(10) and r.contains(20) and not r.contains(21)
        assert r.size() == 11
        assert VLANRange(5, 4).size() == 0


class TestQinQMapper:
    def test_register_and_bidirectional_lookup(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "sub-1")
        assert m.get_subscriber(VLANPair(100, 200)) == "sub-1"
        assert m.get_vlan("sub-1") == VLANPair(100, 200)

    def test_conflicting_registration_rejected(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "sub-1")
        with pytest.raises(ValueError, match="already registered"):
            m.register(VLANPair(100, 200), "sub-2")

    def test_move_subscriber_releases_old_pair(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "sub-1")
        m.register(VLANPair(100, 201), "sub-1")
        assert m.get_subscriber(VLANPair(100, 200)) is None
        assert m.get_vlan("sub-1") == VLANPair(100, 201)

    def test_s_tag_only_invalid(self):
        with pytest.raises(ValueError, match="outer without inner"):
            QinQMapper().register(VLANPair(100, 0), "sub-1")

    def test_config_gates(self):
        cfg = QinQConfig(s_tag_range=VLANRange(100, 110),
                         allow_single_tagged=False)
        m = QinQMapper(cfg)
        with pytest.raises(ValueError, match="single-tagged"):
            m.register(VLANPair(0, 200), "sub-1")
        with pytest.raises(ValueError, match="s_tag 99"):
            m.register(VLANPair(99, 200), "sub-1")
        with pytest.raises(ValueError, match="untagged"):
            m.register(VLANPair(), "sub-1")
        m.register(VLANPair(105, 200), "sub-1")

    def test_unregister_both_directions(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "sub-1")
        m.register(VLANPair(100, 201), "sub-2")
        m.unregister(VLANPair(100, 200))
        assert m.get_vlan("sub-1") is None
        m.unregister_subscriber("sub-2")
        assert m.get_subscriber(VLANPair(100, 201)) is None
        assert m.stats()["total_mappings"] == 0

    def test_stats_split_by_tagging(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "a")
        m.register(VLANPair(0, 300), "b")
        assert m.stats() == {"total_mappings": 2, "double_tagged": 1,
                             "single_tagged": 1}


class TestDoubleTagParity:
    """The load-bearing invariant: VLANPair.key() == the u32 the device
    parser derives from the wire == the fast-path vlan-table key."""

    @staticmethod
    def _qinq_frame(s_tag, c_tag):
        return (b"\x02" * 6 + b"\x04" * 6
                + b"\x88\xa8" + s_tag.to_bytes(2, "big")
                + b"\x81\x00" + c_tag.to_bytes(2, "big")
                + b"\x08\x00" + b"\x00" * 40)

    def test_parser_and_registry_agree_on_key(self):
        pair = VLANPair(0x123, 0x456)
        frame = self._qinq_frame(pair.s_tag, pair.c_tag)
        pkt = jnp.zeros((1, 128), jnp.uint8)
        pkt = pkt.at[0, : len(frame)].set(
            jnp.frombuffer(frame, jnp.uint8))
        p = parse_batch(pkt, jnp.asarray([len(frame)], jnp.int32))
        assert bool(p.is_qinq[0])
        wire_key = (int(p.s_tag[0]) << 16) | int(p.c_tag[0])
        assert wire_key == pair.key()

    def test_registry_key_reaches_fastpath_table(self):
        from bng_tpu.runtime.tables import FastPathTables

        fp = FastPathTables(sub_nbuckets=64, vlan_nbuckets=64,
                            cid_nbuckets=64, max_pools=4)
        pair = VLANPair(100, 200)
        m = QinQMapper()
        m.register(pair, "sub-1")
        fp.add_vlan_subscriber(pair.s_tag, pair.c_tag, 1, 0x0A000005,
                               1000)
        assert fp.vlan.lookup([pair.key()]) is not None
        assert fp.remove_vlan_subscriber(pair.s_tag, pair.c_tag)


# ---------------------------------------------------------------------------
# ZTP: discovery options + bootstrap retry/backoff
# ---------------------------------------------------------------------------

class TestZTPDiscovery:
    def test_option_224_wins_over_vendor(self):
        opts = {224: b"https://a", 43: build_vendor_option("https://b")}
        assert extract_nexus_url(opts) == "https://a"

    def test_vendor_tlv_roundtrip(self):
        raw = build_vendor_option("https://nexus.example")
        assert parse_vendor_options(raw) == "https://nexus.example"
        # unknown sub-types are skipped, truncated TLVs stop the walk
        padded = bytes([9, 2, 0, 0]) + raw
        assert parse_vendor_options(padded) == "https://nexus.example"
        assert parse_vendor_options(bytes([1, 200, 65])) == ""

    def test_discover_from_lease(self):
        r = discover_from_lease(ip="10.0.0.9", gateway="10.0.0.1",
                                options={224: b"https://n"})
        assert r.nexus_url == "https://n" and r.ip == "10.0.0.9"
        assert discover_from_lease().nexus_url == ""


def _client(transport, **cfg):
    sleeps = []
    clk = [0.0]

    def sleep(dt):
        sleeps.append(dt)
        clk[0] += dt

    c = BootstrapClient(
        BootstrapConfig(nexus_url="https://n", **cfg), transport,
        identity=DeviceIdentity(serial="SN1", mac="02:00:00:00:00:01",
                                model="bng-1"),
        clock=lambda: clk[0], sleep=sleep)
    return c, sleeps, clk


class TestZTPBootstrap:
    def test_transport_errors_back_off_exponentially_capped(self):
        calls = []

        def transport(req):
            calls.append(req.serial)
            if len(calls) < 6:
                raise ConnectionError("nexus unreachable")
            return {"status": "configured", "node_id": "n1"}

        c, sleeps, _clk = _client(transport, initial_backoff=1.0,
                                  max_backoff=4.0)
        cfg = c.bootstrap()
        assert cfg.node_id == "n1"
        # 1, 2, 4, then capped at max_backoff
        assert sleeps == [1.0, 2.0, 4.0, 4.0, 4.0]
        assert calls == ["SN1"] * 6

    def test_pending_honors_retry_after_and_resets_backoff(self):
        responses = iter([
            ConnectionError("down"),  # backoff 1 -> 2
            {"status": "pending", "retry_after": 7},  # contact: reset
            ConnectionError("down"),  # back to initial 1
            {"status": "configured", "node_id": "n1"},
        ])

        def transport(req):
            r = next(responses)
            if isinstance(r, Exception):
                raise r
            return r

        c, sleeps, _clk = _client(transport, initial_backoff=1.0,
                                  max_backoff=60.0)
        assert c.bootstrap().node_id == "n1"
        assert sleeps == [1.0, 7.0, 1.0]

    def test_pending_without_retry_after_uses_backoff(self):
        responses = iter([{"status": "pending"},
                          {"status": "configured"}])
        c, sleeps, _clk = _client(lambda req: next(responses))
        c.bootstrap()
        assert sleeps == [1.0]

    def test_max_retries_exceeded(self):
        c, _sleeps, _clk = _client(lambda req: {"status": "pending"},
                                   max_retries=3)
        with pytest.raises(TimeoutError, match="max retries"):
            c.bootstrap()
        assert c.attempts == 3

    def test_deadline_exceeded(self):
        c, _sleeps, _clk = _client(
            lambda req: (_ for _ in ()).throw(ConnectionError("down")),
            initial_backoff=10.0)
        with pytest.raises(TimeoutError, match="deadline"):
            c.bootstrap(deadline=25.0)

    def test_register_once_surfaces_pending(self):
        c, _sleeps, _clk = _client(
            lambda req: {"status": "pending", "retry_after": 3,
                         "message": "awaiting approval"})
        with pytest.raises(BootstrapPending) as exc:
            c.register_once()
        assert exc.value.retry_after == 3.0

    def test_configured_payload_mapped(self):
        c, _sleeps, clk = _client(
            lambda req: {"status": "configured", "node_id": "n1",
                         "site_id": "s1", "role": "active",
                         "pools": [{"id": 1}]})
        clk[0] = 99.0
        cfg = c.register_once()
        assert (cfg.node_id, cfg.site_id, cfg.role) == ("n1", "s1",
                                                        "active")
        assert cfg.pools == [{"id": 1}] and cfg.timestamp == 99.0
