"""Device-resident edge protection (ISSUE 17): intercept taps + route
rewrite on the fast path.

Covers the subsystem bottom-up: the tap-match/route-rewrite kernels
against host oracles, the EdgeTables host authority (bounded deltas,
foreign-filter preservation), the warrant compiler (filter cartesian,
wid stability, self-healing sync, bounded expiry reap), the engine and
sharded wiring (device filtering, mirror extraction at retire,
missteers==0), every `_audit_edge` clause against a planted violation,
the checkpoint ride (flat, re-shard, slot-exact), the antispoof
violation-lane counters + rate-limited log (satellite a), the new
metric families, and two-run byte-determinism for the three new chaos
entries including the `production_day` composite storm.

`make verify-edge` runs this file plus test_qinq_ztp.py under the
`edge` marker; tier-1 deselects it (the storms run there through
test_chaos's run_scenarios determinism gate instead).
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.intercept import InterceptManager, Warrant
from bng_tpu.control.routing import RoutingManager, StubPlatform, Upstream
from bng_tpu.edge import (CLASS_CODES, EdgeTables, InterceptTapProgram,
                          MirrorPump, RouteProgram)
from bng_tpu.edge.ops import (EST_MIRRORED, EST_ROUTE_REWRITES,
                              EST_TAP_FILTERED, RW_MAC_HI, RW_MAC_LO,
                              TC_ARMED, TW_WID, route_rewrite, tap_match)
from bng_tpu.utils.net import ip_to_u32, u32_to_ip

pytestmark = pytest.mark.edge

SERVER_MAC = bytes.fromhex("02aabbccdd01")
SERVER_IP = ip_to_u32("10.0.0.1")
NH_A = bytes.fromhex("02dd0000000a")
NH_B = bytes.fromhex("02dd0000000b")


def _warrant(wid_id="W-1", ip="10.0.0.5", clock=1000.0, ttl=2000.0, **kw):
    return Warrant(id=wid_id, liid=f"liid-{wid_id}", target_ipv4=ip,
                   valid_from=clock - 1.0, valid_until=clock + ttl, **kw)


# ---------------------------------------------------------------------------
# kernels: tap_match + route_rewrite vs host expectations
# ---------------------------------------------------------------------------

class TestKernels:
    def _match(self, edge, ips, sports, dports, protos=None, peers=None,
               lanes=None):
        n = len(ips)
        res = tap_match(
            jnp.asarray(ips, jnp.uint32),
            jnp.asarray(sports, jnp.uint32),
            jnp.asarray(dports, jnp.uint32),
            jnp.asarray(protos if protos is not None else [17] * n,
                        jnp.uint32),
            jnp.asarray(peers if peers is not None else [0] * n,
                        jnp.uint32),
            jnp.asarray(lanes if lanes is not None else [True] * n),
            edge.tap.device_state(),
            jnp.asarray(edge.tap_filters),
            jnp.asarray(edge.tap_config),
            edge.geom)
        return np.asarray(res.mirror), np.asarray(res.stats)

    def test_unfiltered_tap_mirrors_every_lane(self):
        edge = EdgeTables(nbuckets=64)
        ip = ip_to_u32("10.0.0.5")
        edge.arm_tap(ip, 7)
        mirror, stats = self._match(edge, [ip, ip + 1], [1000, 1000],
                                    [443, 443])
        assert mirror.tolist() == [7, 0]
        assert stats[EST_MIRRORED] == 1

    def test_port_filter_matches_src_or_dst(self):
        edge = EdgeTables(nbuckets=64)
        ip = ip_to_u32("10.0.0.5")
        edge.arm_tap(ip, 3, [(443, 0, 0)])
        mirror, stats = self._match(edge, [ip, ip, ip],
                                    [1000, 443, 1000],
                                    [443, 9999, 9999])
        # dst match, src match, neither (device-filtered)
        assert mirror.tolist() == [3, 3, 0]
        assert stats[EST_TAP_FILTERED] == 1

    def test_zero_warrant_config_adds_no_device_work(self):
        edge = EdgeTables(nbuckets=64)
        ip = ip_to_u32("10.0.0.5")
        mirror, stats = self._match(edge, [ip], [1], [2])
        assert mirror.tolist() == [0]
        assert stats.sum() == 0
        # the armed predicate is a single config word
        assert edge.tap_config[TC_ARMED] == 0

    def test_disarmed_after_reap_stops_mirroring(self):
        edge = EdgeTables(nbuckets=64)
        ip = ip_to_u32("10.0.0.5")
        edge.arm_tap(ip, 7)
        edge.disarm_tap(ip)
        mirror, _ = self._match(edge, [ip], [1], [2])
        assert mirror.tolist() == [0]

    def test_route_rewrite_stamps_next_hop_mac(self):
        edge = EdgeTables(nbuckets=64)
        ip = ip_to_u32("10.0.0.5")
        edge.set_route(ip, NH_A, 100, CLASS_CODES["business"])
        frame = packets.udp_packet(b"\x02" * 6, SERVER_MAC, ip,
                                   ip_to_u32("8.8.8.8"), 1, 2, b"x")
        pkt = jnp.zeros((2, 256), jnp.uint8)
        pkt = pkt.at[0, : len(frame)].set(
            jnp.frombuffer(frame, jnp.uint8))
        pkt = pkt.at[1, : len(frame)].set(
            jnp.frombuffer(frame, jnp.uint8))
        res = route_rewrite(pkt, jnp.asarray([ip, ip + 9], jnp.uint32),
                            jnp.asarray([True, True]),
                            edge.route.device_state(), edge.geom)
        out = np.asarray(res.out_pkt)
        assert bytes(out[0, :6]) == NH_A  # hit: rewritten
        assert bytes(out[1, :6]) == frame[:6]  # miss: untouched
        assert np.asarray(res.hit).tolist() == [True, False]


# ---------------------------------------------------------------------------
# host tables: deltas, filters, checkpoint state
# ---------------------------------------------------------------------------

class TestEdgeTables:
    def test_route_flap_is_bounded_deltas_not_resync(self):
        edge = EdgeTables(nbuckets=256)
        ips = [ip_to_u32("10.0.1.0") + i for i in range(32)]
        for ip in ips:
            edge.set_route(ip, NH_A, 100, 1)
        edge.make_updates()  # drain
        assert edge.dirty_count() == 0
        # flap re-steers 4 rows: the delta is exactly those rows
        for ip in ips[:4]:
            edge.set_route(ip, NH_B, 101, 1)
        assert edge.dirty_count() == 4

    def test_set_tap_filters_keeps_foreign_rows(self):
        edge = EdgeTables(nbuckets=64)
        edge.arm_tap(1, 1, [(80, 0, 0)])
        edge.arm_tap(2, 2, [(443, 0, 0), (8443, 0, 0)])
        edge.set_tap_filters(1, [(53, 17, 0)])
        rows = edge.tap_filters[edge.tap_filters[:, 0] != 0]
        by_wid = {}
        for r in rows:
            by_wid.setdefault(int(r[0]), []).append(int(r[1]))
        assert by_wid == {1: [53], 2: [443, 8443]}

    def test_checkpoint_state_roundtrip(self):
        edge = EdgeTables(nbuckets=64)
        ip = ip_to_u32("10.0.0.5")
        edge.arm_tap(ip, 3, [(443, 6, 0)])
        edge.set_route(ip, NH_A, 7, 2)
        meta, arrays = edge.checkpoint_state()
        e2 = EdgeTables(nbuckets=64)
        e2.restore_state(meta, arrays)
        assert e2.get_tap(ip)[TW_WID] == 3
        assert e2.tap_config[TC_ARMED] == 1
        assert e2.tap_filters[0].tolist() == [3, 443, 6, 0]
        got = e2.get_route(ip)
        assert (int(got[RW_MAC_HI]), int(got[RW_MAC_LO])) == (
            int.from_bytes(NH_A[:2], "big"),
            int.from_bytes(NH_A[2:], "big"))


# ---------------------------------------------------------------------------
# warrant compiler: filters, wid stability, sync, bounded reap
# ---------------------------------------------------------------------------

class TestInterceptCompile:
    def _stack(self, clk):
        im = InterceptManager(clock=lambda: clk[0])
        edge = EdgeTables(nbuckets=64)
        prog = InterceptTapProgram(edge, im, clock=lambda: clk[0])
        return im, edge, prog

    def test_compile_filters_cartesian(self):
        w = _warrant(filter_source_ports=[1000],
                     filter_dest_ports=[443, 80],
                     filter_protocols=[6])
        rows = InterceptTapProgram.compile_filters(w)
        assert sorted(rows) == [(80, 6, 0), (443, 6, 0), (1000, 6, 0)]
        assert InterceptTapProgram.compile_filters(_warrant()) == []

    def test_wid_stable_and_reverse_lookup(self):
        clk = [1000.0]
        im, edge, prog = self._stack(clk)
        im.add_warrant(_warrant("W-A", "10.0.0.5"))
        im.add_warrant(_warrant("W-B", "10.0.0.6"))
        a, b = prog.wid_for("W-A"), prog.wid_for("W-B")
        assert a != b and prog.wid_for("W-A") == a
        assert prog.warrant_for(a) == "W-A"
        assert prog.warrant_for(999) is None

    def test_sync_arms_and_self_heals_lost_rows(self):
        clk = [1000.0]
        im, edge, prog = self._stack(clk)
        im.add_warrant(_warrant("W-A", "10.0.0.5"))
        assert prog.sync()["armed"] == 1
        ip = ip_to_u32("10.0.0.5")
        assert edge.get_tap(ip) is not None
        # a row lost behind the program's back re-arms on the next sweep
        edge.disarm_tap(ip)
        assert prog.sync()["armed"] == 1
        assert edge.get_tap(ip) is not None

    def test_expiry_reap_is_bounded_and_removes_rows(self):
        clk = [1000.0]
        im, edge, prog = self._stack(clk)
        for i in range(6):
            im.add_warrant(_warrant(f"W-{i}", f"10.0.0.{10 + i}",
                                    ttl=100.0))
        prog.sync()
        assert len(edge.tap_rows()) == 6
        clk[0] = 5000.0
        # the bounded sweep: max_reaps caps one tick's work
        assert im.expire_warrants(max_reaps=4) == 4
        assert im.expire_warrants(max_reaps=4) == 2
        rep = prog.sync()
        assert rep["reaped"] == 6 and rep["rows"] == 0
        assert edge.tap_config[TC_ARMED] == 0


# ---------------------------------------------------------------------------
# audit: every _audit_edge clause against a planted violation
# ---------------------------------------------------------------------------

class TestAuditEdge:
    @pytest.fixture()
    def stack(self):
        clk = [1000.0]
        im = InterceptManager(clock=lambda: clk[0])
        im.add_warrant(_warrant("W-1", "10.0.0.5"))
        platform = StubPlatform()
        rman = RoutingManager(None, platform)
        rman.add_upstream(Upstream(name="ispA", interface="eth1",
                                   gateway="192.0.2.1", table=100,
                                   health_target="192.0.2.1", weight=1))
        platform.reachable["192.0.2.1"] = 0.01
        rman.check_health()
        edge = EdgeTables(nbuckets=64)
        tp = InterceptTapProgram(edge, im, clock=lambda: clk[0])
        rp = RouteProgram(edge, rman)
        rp.attach()
        rp.set_neighbor("192.0.2.1", NH_A)
        tp.sync()
        rp.bind_subscriber("10.0.0.5")
        return clk, im, edge, tp, rp

    def _kinds(self, edge, tp, rp):
        rep = audit_invariants(edge=edge, tap_program=tp, route_program=rp,
                               check_roundtrip=False)
        return rep.ok, rep.violations_by_kind()

    def test_clean_stack_passes(self, stack):
        _clk, _im, edge, tp, rp = stack
        ok, kinds = self._kinds(edge, tp, rp)
        assert ok, kinds

    def test_tap_orphan_no_warrant(self, stack):
        _clk, _im, edge, tp, rp = stack
        edge.arm_tap(ip_to_u32("10.9.9.9"), 99)
        ok, kinds = self._kinds(edge, tp, rp)
        assert not ok and "edge-tap-orphan" in kinds

    def test_tap_orphan_expired_warrant(self, stack):
        clk, _im, edge, tp, rp = stack
        clk[0] = 10_000.0
        ok, kinds = self._kinds(edge, tp, rp)
        assert not ok and "edge-tap-orphan" in kinds
        tp.sync()  # the reap heals it
        ok, kinds = self._kinds(edge, tp, rp)
        assert ok, kinds

    def test_tap_missing_armed_target(self, stack):
        _clk, _im, edge, tp, rp = stack
        edge.tap.delete([ip_to_u32("10.0.0.5")])
        edge._armed -= 1
        edge.tap_config[TC_ARMED] = edge._armed
        ok, kinds = self._kinds(edge, tp, rp)
        assert not ok and "edge-tap-missing" in kinds

    def test_route_divergence(self, stack):
        _clk, _im, edge, tp, rp = stack
        edge.set_route(ip_to_u32("10.0.0.5"), NH_B, 100, 1)
        ok, kinds = self._kinds(edge, tp, rp)
        assert not ok and "edge-route-divergence" in kinds
        rp.recompile()
        ok, kinds = self._kinds(edge, tp, rp)
        assert ok, kinds

    def test_route_orphan(self, stack):
        _clk, _im, edge, tp, rp = stack
        edge.set_route(ip_to_u32("10.7.7.7"), NH_B, 100, 1)
        ok, kinds = self._kinds(edge, tp, rp)
        assert not ok and "edge-route-orphan" in kinds

    def test_armed_count_skew(self, stack):
        _clk, _im, edge, tp, rp = stack
        edge.tap_config[TC_ARMED] = 5
        ok, kinds = self._kinds(edge, tp, rp)
        assert not ok and "edge-armed-count" in kinds


# ---------------------------------------------------------------------------
# engine wiring: device filtering, mirror extraction, antispoof lanes
# ---------------------------------------------------------------------------

def _client_frame(mac, msg_type, **kw):
    pkt = dhcp_codec.build_request(mac, msg_type, **kw)
    return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                              pkt.encode().ljust(320, b"\x00"))


class TestEngineEdge:
    @pytest.fixture()
    def engine(self):
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.nat import NATManager
        from bng_tpu.control.pool import Pool, PoolManager
        from bng_tpu.ops.antispoof import MODE_DISABLED, MODE_STRICT
        from bng_tpu.runtime.engine import (AntispoofTables, Engine)
        from bng_tpu.runtime.tables import FastPathTables

        fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=16)
        fastpath.set_server_config(SERVER_MAC, SERVER_IP)
        pools = PoolManager(fastpath)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=24, gateway=SERVER_IP,
                            dns_primary=ip_to_u32("1.1.1.1"),
                            lease_time=3600))
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                            fastpath_tables=fastpath,
                            nat_hook=lambda ip, now: nat.allocate_nat(
                                ip, now))
        spoof = AntispoofTables(nbuckets=64)
        spoof.set_config(MODE_DISABLED, True)
        edge = EdgeTables(nbuckets=64)
        mirrored = []
        eng = Engine(fastpath, nat, antispoof=spoof, edge=edge,
                     batch_size=8, slow_path=server.handle_frame,
                     mirror_sink=lambda lane, frame, wid: mirrored.append(
                         (lane, wid, frame)))
        mac = bytes.fromhex("02c0ffee0001")
        r = eng.process([_client_frame(mac, dhcp_codec.DISCOVER)])
        offer = dhcp_codec.decode(packets.decode(r["slow"][0][1]).payload)
        eng.process([_client_frame(mac, dhcp_codec.REQUEST,
                                   requested_ip=offer.yiaddr,
                                   server_id=SERVER_IP)])
        spoof.add_binding(mac, offer.yiaddr, MODE_STRICT)
        return eng, edge, mirrored, mac, offer.yiaddr

    def _data(self, mac, src_ip, dport, sport=40000):
        return packets.udp_packet(mac, SERVER_MAC, src_ip,
                                  ip_to_u32("8.8.8.8"), sport, dport,
                                  b"edge-test")

    def test_mirror_filter_and_rewrite(self, engine):
        eng, edge, mirrored, mac, ip = engine
        edge.arm_tap(ip, 7, [(443, 0, 0)])
        edge.set_route(ip, NH_A, 100, 1)
        res = eng.process([self._data(mac, ip, 443),
                           self._data(mac, ip, 53, sport=40001)])
        assert [(l, w) for l, w, _f in mirrored] == [(0, 7)]
        # the mirror carries the ORIGINAL ring bytes, not the rewrite
        assert bytes(mirrored[0][2][:6]) == SERVER_MAC
        assert len(res["fwd"]) == 2
        assert all(bytes(f[:6]) == NH_A for _l, f in res["fwd"])
        st = np.asarray(eng.stats.edge)
        assert st[EST_MIRRORED] == 1
        assert st[EST_TAP_FILTERED] == 1
        assert st[EST_ROUTE_REWRITES] == 2

    def test_spoofed_lanes_drop_count_and_rate_limit(self, engine):
        from bng_tpu.ops.antispoof import AST_DROPPED, AST_V4_VIOL

        eng, _edge, _m, mac, ip = engine
        before = np.asarray(eng.stats.spoof)[
            [AST_DROPPED, AST_V4_VIOL]].astype(int)
        emitted = []
        orig = eng._viol_log.report
        eng._viol_log.report = lambda exc, **f: emitted.append(
            orig(exc, **f)) or emitted[-1]
        burst = [self._data(mac, ip_to_u32("172.16.0.1") + i, 53,
                            sport=41000 + i) for i in range(8)]
        res = eng.process(burst)
        delta = np.asarray(eng.stats.spoof)[
            [AST_DROPPED, AST_V4_VIOL]].astype(int) - before
        assert delta.tolist() == [8, 8]
        assert len(res["fwd"]) == 0
        # every lane reported, the limiter decides which lines emit
        assert len(emitted) == 8
        assert emitted.count(True) <= eng._viol_log._limit.burst

    def test_metric_families_scrape(self, engine):
        from bng_tpu.control.metrics import BNGMetrics

        eng, edge, _m, mac, ip = engine
        edge.arm_tap(ip, 7)
        eng.process([self._data(mac, ip, 443)])
        im = InterceptManager()
        m = BNGMetrics()
        m.collect_antispoof(eng.stats)
        m.collect_edge(eng.stats, tables=edge)
        m.collect_intercept(im)
        text = m.registry.expose()
        for family in ("bng_antispoof_dropped_total",
                       "bng_edge_mirrored_total 1",
                       "bng_edge_taps_armed 1",
                       "bng_intercept_cc_records_total"):
            assert family in text, family

    def test_host_mirror_tables_include_edge(self, engine):
        eng, edge, _m, _mac, ip = engine
        edge.arm_tap(ip, 7)
        edge.set_route(ip, NH_A, 100, 1)
        eng.process([])  # drain
        rep = audit_invariants(engine=eng, check_roundtrip=False)
        assert rep.ok, rep.violations_by_kind()
        names = dict(eng.host_mirror_tables())
        assert "edge/tap" in names and "edge/route" in names


# ---------------------------------------------------------------------------
# sharded wiring + checkpoint ride
# ---------------------------------------------------------------------------

SHARD_KW = dict(batch_per_shard=8, sub_nbuckets=64, vlan_nbuckets=64,
                cid_nbuckets=64, nat_sessions_nbuckets=64, qos_nbuckets=64,
                spoof_nbuckets=64, garden_enabled=False, edge_enabled=True,
                edge_nbuckets=64)


class TestShardedEdge:
    def test_owner_routed_surface_and_filter_broadcast(self):
        from bng_tpu.parallel.sharded import ShardedCluster

        cl = ShardedCluster(2, **SHARD_KW)
        ip = ip_to_u32("10.0.5.9")
        o = cl.arm_tap(ip, 5, [(80, 6, 0)])
        assert o == cl.affinity_shard_ip(ip)
        assert cl.get_tap(ip) is not None
        # filter rows are warrant-global: every shard's dense copy holds them
        for e in cl.edge:
            assert e.tap_filters[0].tolist() == [5, 80, 6, 0]
        cl.set_route(ip, NH_A, 100, 1)
        assert cl.get_route(ip) is not None
        assert [r[0] for r in cl.tap_rows()] == [ip]
        assert [r[0] for r in cl.route_rows()] == [ip]

    def test_sharded_checkpoint_reshard(self):
        from bng_tpu.parallel.sharded import ShardedCluster
        from bng_tpu.runtime.checkpoint import (build_sharded_checkpoint,
                                                restore_sharded_checkpoint)

        cl = ShardedCluster(2, **SHARD_KW)
        ip = ip_to_u32("10.0.5.9")
        cl.arm_tap(ip, 5, [(80, 6, 0)])
        cl.set_route(ip, NH_A, 2, 1)
        ck = build_sharded_checkpoint(cl, 7, 0.0, quiesce=False)
        # re-shard 2 -> 1: rows re-steered by affinity, filters replicated
        cl1 = ShardedCluster(1, **SHARD_KW)
        rows = restore_sharded_checkpoint(ck, cl1)
        assert rows["edge_taps"] == 1 and rows["edge_routes"] == 1
        assert cl1.get_tap(ip) is not None
        assert cl1.edge[0].tap_config[TC_ARMED] == 1
        assert cl1.edge[0].tap_filters[0].tolist() == [5, 80, 6, 0]
        # slot-exact at the same n
        cl2 = ShardedCluster(2, **SHARD_KW)
        restore_sharded_checkpoint(ck, cl2)
        assert cl2.get_tap(ip) is not None

    def test_flat_checkpoint_component(self):
        from bng_tpu.runtime.checkpoint import (build_checkpoint,
                                                restore_checkpoint,
                                                roundtrip_checkpoint)

        e = EdgeTables(nbuckets=64)
        ip = ip_to_u32("10.0.0.5")
        e.arm_tap(ip, 3, [(443, 6, 0)])
        e.set_route(ip, NH_A, 7, 2)
        ck = roundtrip_checkpoint(build_checkpoint(1, 0.0, edge=e))
        e2 = EdgeTables(nbuckets=64)
        rows = restore_checkpoint(ck, edge=e2)
        assert rows["edge.tap"] == 1 and rows["edge.route"] == 1
        assert e2.get_tap(ip) is not None
        assert e2.tap_filters[0].tolist() == [3, 443, 6, 0]


# ---------------------------------------------------------------------------
# the chaos entries: sharded serving path + two-run determinism
# ---------------------------------------------------------------------------

class TestChaosEntries:
    def test_intercept_tap_live_serves_sharded(self):
        from bng_tpu.chaos.scenarios import intercept_tap_live

        r = intercept_tap_live(seed=123)
        assert r["ok"], json.dumps(r, indent=1)
        assert r["missteers"] == 0

    def test_route_flap_rewrite_serves_sharded(self):
        from bng_tpu.chaos.scenarios import route_flap_rewrite

        r = route_flap_rewrite(seed=123)
        assert r["ok"], json.dumps(r, indent=1)
        assert r["missteers"] == 0
        # flap moved a bounded delta, never the whole table
        assert 0 < r["dirty_after_flap"] <= 2 * r["bound"]

    @pytest.mark.slow  # tier-1 re-proves this at scale=1.0 via
    # test_chaos.py::test_run_scenarios_deterministic; the full-suite run
    # keeps the direct two-run pin
    def test_production_day_ok_and_deterministic(self):
        from bng_tpu.chaos.storms import production_day

        a = production_day(seed=31, scale=0.5)
        assert a["ok"], json.dumps(a, indent=1)
        b = production_day(seed=31, scale=0.5)
        assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                           sort_keys=True)

    @pytest.mark.slow  # same: covered by the tier-1 run_scenarios pin
    def test_scenarios_deterministic_two_run(self):
        from bng_tpu.chaos.scenarios import (intercept_tap_live,
                                             route_flap_rewrite)

        for fn in (intercept_tap_live, route_flap_rewrite):
            a, b = fn(seed=77), fn(seed=77)
            assert json.dumps(a, sort_keys=True) == json.dumps(
                b, sort_keys=True), fn.__name__

    def test_catalog_lists_edge_entries(self):
        from bng_tpu.chaos.runner import scenario_catalog

        cat = dict(scenario_catalog())
        for name in ("production_day", "intercept_tap_live",
                     "route_flap_rewrite"):
            assert name in cat
            assert len(cat[name]) <= 120
