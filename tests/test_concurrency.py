"""Deterministic race-schedule tests + runtime ownership assertions
(ISSUE 9) — the dynamic half of the concurrency discipline.

Two kinds of tests here:

* **Forced interleavings** of the PR-7 race schedules, driven by
  events/barriers (no free-running sleeps deciding the outcome): the
  OpsController timeout-vs-claim schedules and the `ops_status`-vs-
  transition schedule. Each test FAILS if the corresponding fix is
  reverted — the claim going back to check-then-act, the cancellation
  being dropped, or `ops_status` losing `with self._ctl`.
* **Ownership assertions** (`BNG_SANITIZE=1` only): `@owned_by`
  stamps on BNGApp / SlowPathFleet / OpsController turn an unlocked
  cross-context mutation into an OwnershipViolation, proving the
  sanitizer closes the same class the static pass (BNG060) flags.

`make verify-sanitize` runs this file with the sanitizer armed; tier-1
runs the schedule tests disarmed (they assert outcomes, not guard
mechanics — both must hold).
"""

import threading
import time

import pytest

from bng_tpu.analysis import sanitize
from bng_tpu.control.opsctl import OpsController

pytestmark = pytest.mark.race

needs_sanitizer = pytest.mark.skipif(
    not sanitize.enabled(),
    reason="ownership assertions arm only under BNG_SANITIZE=1")


def _app():
    from bng_tpu.cli import BNGApp, BNGConfig

    return BNGApp(BNGConfig(slowpath_workers=2,
                            slowpath_worker_mode="inline",
                            dhcpv6_enabled=False, slaac_enabled=False,
                            metrics_enabled=False, ctl_listen=""))


# ---------------------------------------------------------------------------
# schedule 1: the loop claims the op; the client deadline expires
# mid-execution (the PR-7 OpsController bug: a check-then-act flag told
# the client 'timeout' while the op executed anyway — the retry then
# doubled the transition)
# ---------------------------------------------------------------------------

class TestOpsTimeoutSchedules:
    def test_loop_claim_wins_client_waits_out_real_report(self):
        app = _app()
        try:
            ops = app.components["ops"]
            executing = threading.Event()
            release = threading.Event()
            real = app.fleet_resize

            def stalled_resize(n):
                executing.set()  # claim certainly taken: we are the op
                assert release.wait(10), "schedule wedged"
                return real(n)

            app.fleet_resize = stalled_resize
            result = {}

            def client():
                with sanitize.context("ctl"):
                    result["rep"] = ops.submit("fleet/resize", {"n": 3},
                                               timeout_s=0.05)

            tc = threading.Thread(target=client, daemon=True)
            tc.start()
            # wait for the enqueue, then drain on a 'loop' thread: the
            # claim happens inside run_pending before our stub runs
            deadline = time.monotonic() + 5
            while ops._q.qsize() == 0:
                assert time.monotonic() < deadline, "submit never enqueued"
            tl = threading.Thread(
                target=lambda: sanitize.ctx_enter("loop") or
                ops.run_pending(), daemon=True)
            tl.start()
            assert executing.wait(5)
            # hold the op captive until the client's 50 ms deadline has
            # certainly expired — the client is now in the loser branch
            # of the atomic claim
            time.sleep(0.15)
            release.set()
            tc.join(timeout=10)
            tl.join(timeout=10)
            assert not tc.is_alive() and not tl.is_alive()
            # the fix's contract: the client gets the REAL report, not
            # 'timeout' (reverting the atomic claim fails here), and
            # exactly one transition executed (no double resize)
            assert result["rep"]["outcome"] == "ok", result["rep"]
            assert app.components["fleet"].n == 3
            assert app.components["fleet"].resizes == 1
        finally:
            app.close()

    def test_client_timeout_first_cancels_the_op(self):
        """The mirror schedule, fully event-ordered: nothing drains
        until AFTER the client was told 'timeout' — the op must then
        never fire (the operator is about to retry)."""
        app = _app()
        try:
            ops = app.components["ops"]
            with sanitize.context("ctl"):
                rep = ops.submit("fleet/resize", {"n": 3}, timeout_s=0)
            assert rep["outcome"] == "timeout"
            # the loop drains strictly after: the claim must already be
            # the client's, so nothing executes
            with sanitize.context("loop"):
                assert ops.run_pending() == 0
            assert app.components["fleet"].n == 2
            assert app.components["fleet"].resizes == 0
            assert ops.stats_snapshot()["rejected"] == 1
        finally:
            app.close()


# ---------------------------------------------------------------------------
# schedule 2: ops_status vs a loop-side transition holding _ctl (the
# PR-7 review fix: the HTTP handler thread read fleet state mid-
# mutation; ops_status now takes _ctl)
# ---------------------------------------------------------------------------

class TestOpsStatusVsTransition:
    def test_status_blocks_until_transition_releases_ctl(self):
        app = _app()
        try:
            in_transition = threading.Event()
            release = threading.Event()
            status_done = threading.Event()
            result = {}

            def loop_side():
                sanitize.ctx_enter("loop")
                with app._ctl:  # a transition is mid-flight
                    in_transition.set()
                    assert release.wait(10), "schedule wedged"

            def ctl_side():
                sanitize.ctx_enter("ctl")
                result["status"] = app.ops_status()
                status_done.set()

            tl = threading.Thread(target=loop_side, daemon=True)
            tl.start()
            assert in_transition.wait(5)
            tc = threading.Thread(target=ctl_side, daemon=True)
            tc.start()
            # the fix's contract: ops_status CANNOT complete while the
            # transition holds _ctl (reverting `with self._ctl` in
            # ops_status returns a mid-mutation read here and fails)
            assert not status_done.wait(0.2), (
                "ops_status returned while a transition held _ctl — "
                "it reads fleet state mid-mutation")
            release.set()
            assert status_done.wait(5)
            tl.join(timeout=5)
            tc.join(timeout=5)
            st = result["status"]
            assert st["fleet"]["workers"] == 2
            assert st["ops"]["pending"] == 0
        finally:
            app.close()


# ---------------------------------------------------------------------------
# schedule 3: the SSE stream dies DURING _connect (on_stream_end fires
# before _connect returns) — `connected` must end up False, not a
# wedged True for a dead stream
# ---------------------------------------------------------------------------

class TestStandbyConnectOrdering:
    def test_stream_dying_during_connect_leaves_disconnected(self):
        from bng_tpu.control.ha import (ActiveSyncer, InMemorySessionStore,
                                        StandbySyncer)

        active = ActiveSyncer(InMemorySessionStore())
        standby = StandbySyncer(InMemorySessionStore(), lambda: active)

        class DyingStream:
            """Transport whose stream drops the instant it opens: the
            reader's finally fires on_stream_end (-> disconnect) before
            subscribe() returns to _connect — forced synchronously, the
            worst legal interleaving."""

            full_sync = staticmethod(active.full_sync)
            replay_since = staticmethod(active.replay_since)

            @staticmethod
            def subscribe(cb):
                cancel = active.subscribe(cb)
                standby.disconnect()  # the drop lands mid-_connect
                return cancel

        standby.transport = lambda: DyingStream()
        standby.tick(0.0)
        # pre-fix: _connect set connected=True AFTER subscribe and
        # overwrote the drop — tick() then early-returned forever
        assert standby.connected is False
        # the backoff path stays live: a later healthy connect works
        standby.transport = lambda: active
        standby.tick(10.0)
        assert standby.connected is True


# ---------------------------------------------------------------------------
# ownership assertions (BNG_SANITIZE=1): the dynamic BNG060 check
# ---------------------------------------------------------------------------

@needs_sanitizer
class TestOwnedBy:
    def _widget(self, owner="loop", guard="_ctl", attrs=None):
        @sanitize.owned_by(owner, guard=guard, attrs=attrs)
        class Widget:
            def __init__(self):
                self._ctl = threading.Lock()
                self.x = 0

        return Widget()

    def _run(self, fn):
        box = {}

        def run():
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — the box IS the report
                box["err"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(5)
        return box.get("err")

    def test_unnamed_context_writes_free(self):
        w = self._widget()
        w.x = 1  # no context stamp: construction/unit-test writes pass
        assert w.x == 1

    def test_owner_context_writes_free(self):
        w = self._widget()
        with sanitize.context("loop"):
            w.x = 2
        assert w.x == 2

    def test_cross_context_unlocked_write_raises(self):
        w = self._widget()

        def rogue():
            sanitize.ctx_enter("ctl")
            w.x = 3

        err = self._run(rogue)
        assert isinstance(err, sanitize.OwnershipViolation)
        assert "owned by 'loop'" in str(err) and w.x == 0

    def test_cross_context_write_under_guard_allowed(self):
        w = self._widget()

        def polite():
            sanitize.ctx_enter("ctl")
            with w._ctl:
                w.x = 4

        assert self._run(polite) is None
        assert w.x == 4

    def test_owner_inferred_at_first_named_write(self):
        w = self._widget(owner=None)
        with sanitize.context("scrape"):
            w.x = 5  # scrape stamps ownership of x

        def rogue():
            sanitize.ctx_enter("ctl")
            w.x = 6

        err = self._run(rogue)
        assert isinstance(err, sanitize.OwnershipViolation)
        assert "owned by 'scrape'" in str(err)

    def test_attr_filter_limits_checking(self):
        w = self._widget(attrs=("x",))

        def rogue():
            sanitize.ctx_enter("ctl")
            w.other = 1  # unchecked attr: free

        assert self._run(rogue) is None

    def test_guarded_lock_reentrancy_bookkeeping(self):
        g = sanitize.GuardedLock(threading.RLock())
        assert not g.held_by_me()
        with g:
            assert g.held_by_me()
            with g:
                assert g.held_by_me()
            assert g.held_by_me()
        assert not g.held_by_me()


@needs_sanitizer
class TestProductOwnership:
    def test_fleet_reach_in_from_ctl_raises(self):
        """The pre-PR-7 bug class, live: a ctl-side thread mutating
        fleet state directly (instead of routing through the ops queue
        to the loop) trips the @owned_by('loop') stamp."""
        app = _app()
        try:
            fleet = app.components["fleet"]
            err = {}

            def rogue():
                sanitize.ctx_enter("ctl")
                try:
                    fleet.batches += 1
                except sanitize.OwnershipViolation as e:
                    err["e"] = e

            t = threading.Thread(target=rogue, daemon=True)
            t.start()
            t.join(5)
            assert "e" in err, "ctl-context fleet mutation not caught"
        finally:
            app.close()

    def test_app_mutation_needs_ctl_from_other_contexts(self):
        app = _app()
        try:
            err = {}

            def unlocked():
                sanitize.ctx_enter("ctl")
                try:
                    app._last_expire = 1.0
                except sanitize.OwnershipViolation as e:
                    err["e"] = e

            def locked():
                sanitize.ctx_enter("ctl")
                with app._ctl:
                    app._last_expire = 2.0

            t = threading.Thread(target=unlocked, daemon=True)
            t.start()
            t.join(5)
            assert "e" in err, "unlocked ctl-context app mutation passed"
            t = threading.Thread(target=locked, daemon=True)
            t.start()
            t.join(5)
            assert app._last_expire == 2.0  # _ctl held: legal
        finally:
            app.close()

    def test_ops_counters_locked_bumps_pass(self):
        """The BNG060 fix for OpsController.rejected: submit's bump
        happens under _stats_lock from the ctl context — the stamp
        accepts it (and would reject a lock-dropping regression)."""
        app = _app()
        try:
            ops = app.components["ops"]

            def client():
                sanitize.ctx_enter("ctl")
                rep = ops.submit("bogus/op", {})
                assert rep["outcome"] == "rejected"

            t = threading.Thread(target=client, daemon=True)
            t.start()
            t.join(5)
            assert ops.rejected == 1
        finally:
            app.close()

    def test_engine_tables_rebind_from_ctl_raises(self):
        app = _app()
        try:
            engine = app.components["engine"]
            err = {}

            def rogue():
                sanitize.ctx_enter("ctl")
                try:
                    engine.tables = None
                except sanitize.OwnershipViolation as e:
                    err["e"] = e

            t = threading.Thread(target=rogue, daemon=True)
            t.start()
            t.join(5)
            assert "e" in err, "ctl-context engine.tables rebind passed"
            assert engine.tables is not None
        finally:
            app.close()

    def test_standby_stream_drop_from_reader_thread_heals(self):
        """The SSE reader's on_stream_end calls disconnect() on the
        reader ('ha-sync') thread while _cancel/connected are
        loop-stamped — disconnect must take _lock (unlocked it both
        races tick/_connect and trips the stamp, wedging `connected`
        True forever after a stream drop)."""
        from bng_tpu.control.ha import (ActiveSyncer, InMemorySessionStore,
                                        StandbySyncer)

        active = ActiveSyncer(InMemorySessionStore())
        standby = StandbySyncer(InMemorySessionStore(), lambda: active)
        with sanitize.context("loop"):
            standby.tick(0.0)  # connect: stamps _cancel/connected 'loop'
        assert standby.connected
        err = {}

        def stream_end():
            sanitize.ctx_enter("ha-sync")
            try:
                standby.disconnect()
            except sanitize.OwnershipViolation as e:
                err["e"] = e

        t = threading.Thread(target=stream_end, daemon=True)
        t.start()
        t.join(5)
        assert "e" not in err, f"locked disconnect rejected: {err['e']}"
        assert not standby.connected  # tick() can reconnect again
        with sanitize.context("loop"):
            standby.tick(1.0)
        assert standby.connected

    def test_standby_syncer_delta_under_lock_passes(self):
        """The BNG060 HA fix: a 'ha-sync'-context delta apply goes
        through _on_change's _lock and is accepted by the stamp."""
        from bng_tpu.control.ha import (ActiveSyncer, HAChange,
                                        InMemorySessionStore, SessionState,
                                        StandbySyncer)

        active = ActiveSyncer(InMemorySessionStore())
        standby = StandbySyncer(InMemorySessionStore(), lambda: active)
        standby.tick(0.0)  # connect on the "loop" side
        err = {}

        def sse_reader():
            sanitize.ctx_enter("ha-sync")
            try:
                standby._on_change(HAChange(
                    "put", session=SessionState(session_id="s1", ip=7),
                    seq=active._seq + 1))
            except sanitize.OwnershipViolation as e:
                err["e"] = e

        t = threading.Thread(target=sse_reader, daemon=True)
        t.start()
        t.join(5)
        assert "e" not in err, f"locked delta apply rejected: {err}"
        assert standby.store.get("s1").ip == 7
