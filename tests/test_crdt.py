"""CLSet CRDT store: convergence, modes, membership.

The round-2 verdict's done-criterion: a partition/heal test must merge two
diverged stores to identical state from both sides. Reference semantics:
pkg/nexus/clset.go, clset_store.go (modes), crdt_backend.go (membership).
"""

import itertools

import pytest

from bng_tpu.control.crdt import (
    CLSetStore, DistributedStore, Entry, ReadOnlyNodeError,
    MODE_MEMORY, MODE_READ, MODE_WRITE,
)
from bng_tpu.control.nexus import NexusClient, SubscriberEntity, TypedStore


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        self.t += 0.001  # strictly monotone: every event gets a fresh ts
        return self.t


def mk(node, clock=None):
    clock = clock or FakeClock()
    return CLSetStore(node, clock_ns=lambda: int(clock() * 1e9))


def assert_converged(a: CLSetStore, b: CLSetStore):
    assert a.digest() == b.digest()
    keys = set(a.digest())
    for k in keys:
        assert a.get(k) == b.get(k), k


class TestCLSetBasics:
    def test_kv_surface(self):
        s = mk("n1")
        assert s.get("x") is None
        s.put("x", b"1")
        assert s.get("x") == b"1"
        s.put("x", b"2")
        assert s.get("x") == b"2"
        assert s.delete("x")
        assert s.get("x") is None
        assert not s.delete("x")

    def test_list_prefix_and_watch(self):
        s = mk("n1")
        events = []
        s.watch("sub/", lambda k, v: events.append((k, v)))
        s.put("sub/a", b"1")
        s.put("other/b", b"2")
        s.delete("sub/a")
        assert s.list("sub/") == {}
        assert s.list("other/") == {"other/b": b"2"}
        assert events == [("sub/a", b"1"), ("sub/a", None)]

    def test_causal_length_parity(self):
        s = mk("n1")
        s.put("k", b"v")  # cl 1
        s.delete("k")  # cl 2
        s.put("k", b"v2")  # cl 3
        s.put("k", b"v3")  # cl 5 (update while present jumps 2)
        (cl, _, _) = s.digest()["k"]
        assert cl == 5


class TestConvergence:
    def test_partition_heal_identical_from_both_sides(self):
        """The verdict's done-criterion, literally."""
        clock = FakeClock()
        a, b = mk("a", clock), mk("b", clock)
        # shared prehistory
        a.put("sub/1", b"ip=10.0.0.1")
        a.sync_with(b)
        # --- partition: both sides diverge ---
        a.put("sub/2", b"ip=10.0.0.2")
        a.delete("sub/1")
        b.put("sub/1", b"ip=10.0.0.99")  # concurrent update vs delete
        b.put("sub/3", b"ip=10.0.0.3")
        # --- heal: full exchange, then verify identical state ---
        a.sync_with(b)
        b.sync_with(a)
        assert_converged(a, b)
        # concurrent update (cl 1->3) beats concurrent delete (cl 1->2)
        assert a.get("sub/1") == b"ip=10.0.0.99"
        assert a.get("sub/2") == b"ip=10.0.0.2"
        assert a.get("sub/3") == b"ip=10.0.0.3"

    def test_merge_order_independent(self):
        """Entries applied in any order and any repetition converge."""
        clock = FakeClock()
        src = mk("s", clock)
        for i in range(8):
            src.put(f"k{i}", bytes([i]))
        src.delete("k3")
        src.put("k3", b"re-added")
        entries = src.entries_for(list(src.digest()))
        items = list(entries.items())
        for perm in itertools.islice(itertools.permutations(items), 6):
            dst = mk("d", clock)
            for k, e in perm:
                dst.merge_entries({k: e})
                dst.merge_entries({k: e})  # idempotent re-delivery
            assert_converged(src, dst)

    def test_three_node_gossip_chain(self):
        clock = FakeClock()
        a, b, c = mk("a", clock), mk("b", clock), mk("c", clock)
        a.put("x", b"1")
        b.put("y", b"2")
        c.put("z", b"3")
        c.delete("z")
        # gossip only along a-b and b-c; a and c never talk directly
        a.sync_with(b)
        b.sync_with(c)
        a.sync_with(b)
        c.sync_with(b)
        assert_converged(a, b)
        assert_converged(b, c)
        assert a.get("y") == b"2" and c.get("x") == b"1"
        assert a.get("z") is None and a.tombstone_count() == 1

    def test_delete_wins_over_older_update_only(self):
        clock = FakeClock()
        a, b = mk("a", clock), mk("b", clock)
        a.put("k", b"v1")
        a.sync_with(b)
        b.delete("k")  # cl 2, later
        a.sync_with(b)
        b.sync_with(a)
        assert a.get("k") is None and b.get("k") is None

    def test_tie_break_deterministic(self):
        # same cl, same ts -> node id decides, identically on both sides
        a = CLSetStore("aaa", clock_ns=lambda: 5)
        b = CLSetStore("bbb", clock_ns=lambda: 5)
        a.put("k", b"from-a")
        b.put("k", b"from-b")
        a.sync_with(b)
        b.sync_with(a)
        assert a.get("k") == b.get("k") == b"from-b"  # "bbb" > "aaa"


class TestDistributedStore:
    def test_modes_gate_writes(self):
        m = DistributedStore("n1", mode=MODE_MEMORY)
        r = DistributedStore("n2", mode=MODE_READ)
        w = DistributedStore("n3", mode=MODE_WRITE)
        m.put("k", b"1")
        w.put("k", b"2")
        with pytest.raises(ReadOnlyNodeError):
            r.put("k", b"3")
        with pytest.raises(ReadOnlyNodeError):
            r.delete("k")

    def test_read_node_receives_merges(self):
        clock = FakeClock()
        w = DistributedStore("w1", mode=MODE_WRITE, clock=clock)
        r = DistributedStore("r1", mode=MODE_READ, clock=clock)
        w.add_peer(r)
        r.add_peer(w)
        w.put("sub/1", b"data")
        r.tick()
        assert r.get("sub/1") == b"data"

    def test_membership_and_ring(self):
        clock = FakeClock()
        w1 = DistributedStore("w1", mode=MODE_WRITE, clock=clock)
        w2 = DistributedStore("w2", mode=MODE_WRITE, clock=clock)
        r1 = DistributedStore("r1", mode=MODE_READ, clock=clock)
        for x, y in ((w1, w2), (w2, w1), (r1, w1), (w1, r1)):
            x.add_peer(y)
        w1.tick(); w2.tick(); r1.tick(); w1.tick()
        ms = w1.members()
        assert set(ms) == {"w1", "w2", "r1"}
        assert all(m.active for m in ms.values())
        w1.join_member_ring()
        # read nodes never own ranges
        assert w1.ring == {"w1", "w2"}
        # deterministic ownership across nodes
        w2.join_member_ring()
        for key in ("pool/a", "pool/b", "sub/42"):
            assert w1.owner_of(key) == w2.owner_of(key)

    def test_peer_ttl_expiry(self):
        clock = FakeClock()
        w1 = DistributedStore("w1", mode=MODE_WRITE, clock=clock, peer_ttl=10)
        w2 = DistributedStore("w2", mode=MODE_WRITE, clock=clock, peer_ttl=10)
        w1.add_peer(w2)
        w1.tick()
        assert w1.members()["w2"].active
        clock.t += 60  # w2 goes silent
        w1._heartbeat()
        assert not w1.members()["w2"].active
        w1.join_member_ring()
        assert w1.ring == {"w1"}

    def test_dead_peer_does_not_stall_tick(self):
        class Dead:
            def digest(self):
                raise ConnectionError("down")

        w = DistributedStore("w1", mode=MODE_WRITE)
        w.add_peer(Dead())
        assert w.tick() == 0  # no exception

    def test_nexus_client_over_distributed_store(self):
        """Drop-in for the nexus Store surface: TypedStore + NexusClient."""
        clock = FakeClock()
        w1 = DistributedStore("w1", mode=MODE_WRITE, clock=clock)
        w2 = DistributedStore("w2", mode=MODE_WRITE, clock=clock)
        w1.add_peer(w2)
        w2.add_peer(w1)
        c1 = NexusClient(store=w1, node_id="w1")
        c1.subscribers.put("s1", SubscriberEntity(
            id="s1", mac="02:00:00:00:00:01", circuit_id="cid1"))
        w2.tick()
        c2 = NexusClient(store=w2, node_id="w2")
        got = c2.get_subscriber_by_mac("02:00:00:00:00:01")
        assert got is not None and got.id == "s1"


class TestTombstonePruning:
    def test_prune_old_tombstones_only(self):
        clock = FakeClock()
        s = CLSetStore("n1", clock_ns=lambda: int(clock.t * 1e9))
        clock.t = 1000.0
        s.put("old", b"1"); s.delete("old")
        clock.t = 2000.0
        s.put("new", b"2"); s.delete("new")
        s.put("live", b"3")
        clock.t = 2500.0
        n = s.prune_tombstones(max_age_ns=int(600e9),
                               now_ns=int(clock.t * 1e9))
        assert n == 1  # "old" pruned, "new" (age 500s) kept
        assert s.tombstone_count() == 1 and s.key_count() == 1

    def test_distributed_tick_prunes(self):
        clock = FakeClock()
        w = DistributedStore("w1", mode=MODE_WRITE, clock=clock,
                             tombstone_ttl=10.0)
        w.put("k", b"v"); w.delete("k")
        assert w.store.tombstone_count() == 1
        clock.t += 100
        w.tick()
        assert w.store.tombstone_count() == 0
