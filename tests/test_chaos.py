"""Chaos harness + cross-authority invariant auditor (bng_tpu/chaos).

Covers the PR acceptance gates:

- fault_point API: disarmed no-op, deterministic seeded schedules,
  byte-mutation kinds, armed/disarm scoping;
- the kill-at-every-fault-point sweep for the fleet DORA path (plus
  drop/dup/reorder pipe faults) — service may degrade, the audit stays
  clean;
- auditor self-tests: a clean stack passes, and PLANTED violations
  (double-allocation, host/device mirror mismatch, stale fast-path row,
  orphaned NAT reverse row) are all detected;
- every scripted scenario ends with a clean invariant audit, and
  `bng chaos run --seed S` is bit-deterministic (identical JSON twice);
- `bng checkpoint restore --audit` accepts a good snapshot (rc=0) and
  refuses one that hydrates into inconsistent state (rc=2);
- the seeded soak (fast tier-1 run here; the long soak is @slow).
"""

import json

import numpy as np
import pytest

from bng_tpu.chaos import faults as F
from bng_tpu.chaos import runner
from bng_tpu.chaos.faults import (FaultInjector, FaultPlan, FaultSpec,
                                  SimClock, armed, fault_point,
                                  mutate_point)
from bng_tpu.chaos.invariants import audit_invariants
from bng_tpu.chaos.scenarios import (SCENARIOS, SERVER_IP, SERVER_MAC,
                                     _discover, _mac, _reply, _request,
                                     build_fleet, dora_with_retries)
from bng_tpu.control import dhcp_codec

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# fault_point API
# ---------------------------------------------------------------------------

class TestFaultPointAPI:
    def test_disarmed_is_noop(self):
        assert fault_point("fleet.scatter") is None
        assert mutate_point("ckpt.write", b"abc") == b"abc"

    def test_armed_fires_at_hit_then_disarms(self):
        plan = FaultPlan(1, [FaultSpec("p", F.KILL, at_hit=2, count=2)])
        with armed(plan, log=False) as inj:
            assert fault_point("p") is None          # hit 1
            assert fault_point("p").kind == F.KILL   # hit 2
            assert fault_point("p").kind == F.KILL   # hit 3 (count=2)
            assert fault_point("p") is None          # hit 4
            assert fault_point("other") is None
            assert inj.injected == [("p", F.KILL, 2), ("p", F.KILL, 3)]
        assert fault_point("p") is None  # context exit disarmed

    def test_armed_context_disarms_on_exception(self):
        with pytest.raises(RuntimeError):
            with armed(FaultPlan(1, [FaultSpec("p", F.KILL)]), log=False):
                raise RuntimeError("scenario died")
        assert fault_point("p") is None

    def test_generate_is_seed_deterministic(self):
        a = FaultPlan.generate(42, n_faults=12)
        b = FaultPlan.generate(42, n_faults=12)
        assert a.specs == b.specs
        assert FaultPlan.generate(43, n_faults=12).specs != a.specs
        for s in a.specs:
            assert s.kind in F.POINT_KINDS[s.point]

    def test_mutate_kinds(self):
        data = bytes(range(64))
        with armed(FaultPlan(1, [
                FaultSpec("m", F.TRUNCATE, at_hit=1, arg=16),
                FaultSpec("m", F.BITFLIP, at_hit=2, arg=10),
                FaultSpec("m", F.IO_ERROR, at_hit=3)]), log=False):
            assert mutate_point("m", data) == data[:-16]
            flipped = mutate_point("m", data)
            assert len(flipped) == len(data)
            assert flipped[10] == data[10] ^ (1 << 2)  # bit = arg % 8
            with pytest.raises(OSError):
                mutate_point("m", data)
            assert mutate_point("m", data) == data  # past the plan

    def test_injector_stats_snapshot(self):
        inj = FaultInjector(FaultPlan(1, [FaultSpec("p", F.SKEW)]),
                            log=False)
        inj.check("p")
        inj.check("p")
        snap = inj.stats_snapshot()
        assert snap["hits"] == {"p": 2}
        assert snap["by_kind"] == {F.SKEW: 1}


# ---------------------------------------------------------------------------
# fleet DORA under pipe-protocol faults: the kill-at-every-hit sweep
# ---------------------------------------------------------------------------

MACS = [_mac(i) for i in range(12)]


class TestFleetFaultSweep:
    @pytest.mark.parametrize("kill_hit", [1, 2, 3, 4, 5, 6])
    def test_kill_at_every_fault_point(self, kill_hit):
        """Today's ad-hoc fleet test killed one worker between batches;
        this sweep kills at EVERY scatter hit of the DORA path. Each
        kill costs at most one shard's service; consistency (the audit)
        must survive every one of them."""
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(3, clock)
        plan = FaultPlan(kill_hit, [
            FaultSpec("fleet.scatter", F.KILL, at_hit=kill_hit)])
        with armed(plan, log=False) as inj:
            leased = dora_with_retries(fleet, MACS, clock)
        assert len(inj.injected) == 1, "the kill must actually fire"
        assert fleet._dead and fleet.worker_failures >= 1
        # survivors' shards fully lease; no IP is handed out twice
        assert len(set(leased.values())) == len(leased)
        dead = next(iter(fleet._dead))
        from bng_tpu.control.fleet import shard_for_mac
        for m, _ip in leased.items():
            assert shard_for_mac(m, 3) != dead or kill_hit > 3, (
                "a lease on the dead shard can only predate the kill")
        report = audit_invariants(pools=pools, fleet=fleet,
                                  fastpath=fastpath)
        assert report.ok, report.to_dict()

    @pytest.mark.parametrize("kind", [F.DROP_BATCH, F.DUP_BATCH, F.REORDER])
    def test_nonfatal_pipe_faults_cost_nothing_durable(self, kind):
        """Dropped, duplicated or reordered batch delivery: retransmits
        recover full service and the audit stays clean."""
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(3, clock)
        plan = FaultPlan(3, [FaultSpec("fleet.scatter", kind, at_hit=2)])
        with armed(plan, log=False) as inj:
            leased = dora_with_retries(fleet, MACS, clock)
        assert len(inj.injected) == 1
        assert len(leased) == len(MACS)
        assert len(set(leased.values())) == len(MACS)
        report = audit_invariants(pools=pools, fleet=fleet,
                                  fastpath=fastpath)
        assert report.ok, report.to_dict()

    def test_admission_chaos_shed_is_service_only(self):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(2, clock)
        with armed(FaultPlan(1, [
                FaultSpec("admission.admit", F.FORCE_SHED)]), log=False):
            out = fleet.handle_batch([(0, _discover(_mac(0), 1))],
                                     now=clock())
        assert out == [(0, None)]
        assert fleet.admission.stats.shed["chaos"] == 1
        assert audit_invariants(pools=pools, fleet=fleet,
                                fastpath=fastpath).ok

    def test_dhcp_expiry_skew_releases_cleanly(self):
        """Forward clock skew early-expires leases — a re-DORA (service
        cost), never a leaked allocation or stale fast-path row."""
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(2, clock)
        leased = dora_with_retries(fleet, MACS, clock)
        assert len(leased) == len(MACS)
        with armed(FaultPlan(1, [
                FaultSpec("dhcp.expire", F.SKEW, at_hit=1, count=2,
                          arg=7200.0)]), log=False):
            expired = fleet.expire(int(clock()))
        assert expired == len(MACS)
        assert int(np.count_nonzero(fastpath.sub.used)) == 0
        report = audit_invariants(pools=pools, fleet=fleet,
                                  fastpath=fastpath)
        assert report.ok, report.to_dict()
        # the freed addresses are re-leasable
        again = dora_with_retries(fleet, MACS, clock)
        assert len(again) == len(MACS)


# ---------------------------------------------------------------------------
# auditor self-tests: clean pass + planted violations
# ---------------------------------------------------------------------------

class TestAuditorSelfTest:
    def _leased_fleet(self, n=3):
        clock = SimClock()
        fleet, pools, fastpath = build_fleet(n, clock)
        leased = dora_with_retries(fleet, MACS, clock)
        assert len(leased) == len(MACS)
        return fleet, pools, fastpath, leased

    def test_clean_stack_audits_clean(self):
        fleet, pools, fastpath, _ = self._leased_fleet()
        report = audit_invariants(pools=pools, fleet=fleet,
                                  fastpath=fastpath)
        assert report.ok
        assert report.checks["leases"] == len(MACS)
        assert report.checks["slice_granted"] > 0
        assert report.checks["fastpath_rows"] == len(MACS)
        assert report.checks["ckpt_bytes"] > 0

    def test_planted_double_grant_detected(self):
        """The deliberate double-allocation: one address granted to two
        workers' lease slices — the fleet's core correctness boundary."""
        fleet, pools, fastpath, _ = self._leased_fleet()
        w1_slice = fleet._inline[1].pools.pools[1]
        stolen = next(iter(w1_slice._granted))
        fleet._inline[0].pools.pools[1].grant([stolen])
        report = audit_invariants(pools=pools, fleet=fleet,
                                  fastpath=fastpath)
        assert not report.ok
        kinds = report.violations_by_kind()
        assert "double-grant" in kinds, kinds
        assert "carve-leak" in kinds, kinds  # parent owner can match one

    def test_planted_double_lease_detected(self):
        fleet, pools, fastpath, leased = self._leased_fleet()
        victim_ip = next(iter(leased.values()))
        intruder = _mac(999)
        w = fleet._inline[0]
        w.restore_state({"session_seq": 0, "leases": [{
            "mac": intruder.hex(), "ip": victim_ip, "pool_id": 1,
            "expiry": 2_000_000_000, "circuit_id": "", "remote_id": "",
            "s_tag": 0, "c_tag": 0, "session_id": "forged",
            "client_class": 0, "username": "", "qos_policy": ""}]})
        report = audit_invariants(pools=pools, fleet=fleet,
                                  fastpath=fastpath)
        assert not report.ok
        assert "double-lease" in report.violations_by_kind()

    def test_planted_stale_fastpath_row_detected(self):
        fleet, pools, fastpath, _ = self._leased_fleet()
        fastpath.add_subscriber(_mac(500), pool_id=1,
                                ip=SERVER_IP + 4000,
                                lease_expiry=2_000_000_000)
        report = audit_invariants(pools=pools, fleet=fleet,
                                  fastpath=fastpath)
        assert not report.ok
        assert "fastpath-stale-row" in report.violations_by_kind()

    def test_planted_nat_orphan_reverse_detected(self):
        from bng_tpu.control.nat import NATManager
        from bng_tpu.ops.parse import PROTO_UDP
        from bng_tpu.utils.net import ip_to_u32

        nat = NATManager(public_ips=[ip_to_u32("203.0.113.9")],
                         ports_per_subscriber=64,
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        sub = ip_to_u32("10.9.0.5")
        nat.allocate_nat(sub, 100)
        got = nat.handle_new_flow(sub, ip_to_u32("1.1.1.1"), 5000, 53,
                                  PROTO_UDP, 100, 100)
        assert got is not None
        assert audit_invariants(nat=nat, check_roundtrip=False).ok
        # sabotage: delete the reverse row out from under the session
        nat_ip, nat_port = got
        nat.reverse.delete(nat._key(ip_to_u32("1.1.1.1"), nat_ip, 53,
                                    nat_port, PROTO_UDP))
        report = audit_invariants(nat=nat, check_roundtrip=False)
        assert not report.ok
        kinds = report.violations_by_kind()
        assert "nat-missing-reverse" in kinds and "nat-reverse-count" in kinds


# ---------------------------------------------------------------------------
# engine-backed: host/device mirror proof + dispatch faults
# ---------------------------------------------------------------------------

def _engine_stack():
    """Engine + parent DHCP slow path. Geometry matches
    tests/test_fleet.build_engine so the jitted programs are shared via
    the lru cache (no extra tier-1 compiles)."""
    from bng_tpu.control.dhcp_server import DHCPServer
    from bng_tpu.control.nat import NATManager
    from bng_tpu.control.pool import Pool, PoolManager
    from bng_tpu.runtime.engine import Engine
    from bng_tpu.runtime.tables import FastPathTables
    from bng_tpu.utils.net import ip_to_u32

    fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                              cid_nbuckets=64, max_pools=16)
    fastpath.set_server_config(SERVER_MAC, SERVER_IP)
    pools = PoolManager(fastpath)
    pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                        prefix_len=16, gateway=SERVER_IP,
                        dns_primary=ip_to_u32("1.1.1.1"), lease_time=86400))
    nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                     sessions_nbuckets=256, sub_nat_nbuckets=64)
    server = DHCPServer(SERVER_MAC, SERVER_IP, pools,
                        fastpath_tables=fastpath)
    engine = Engine(fastpath, nat, batch_size=32,
                    slow_path=server.handle_frame)
    return engine, pools, fastpath, server


class TestEngineMirrorAudit:
    def test_mirror_clean_then_planted_mismatch(self):
        engine, pools, fastpath, server = _engine_stack()
        macs = [_mac(100 + i) for i in range(8)]
        res = engine.process([_discover(m, i) for i, m in enumerate(macs)])
        offers = {m: _reply(r).yiaddr
                  for (l, r), m in zip(res["slow"], macs)}
        res2 = engine.process([_request(m, offers[m], 50 + i)
                               for i, m in enumerate(macs)])
        assert all(_reply(r).msg_type == dhcp_codec.ACK
                   for _l, r in res2["slow"])
        report = audit_invariants(engine=engine, pools=pools,
                                  dhcp=server)
        assert report.ok, report.to_dict()
        assert report.checks["mirror_buckets.fastpath.sub"] == 512
        # plant the mirror mismatch: a host row mutated behind the dirty
        # tracking — the device now serves different bytes than the host
        # authority believes
        from bng_tpu.ops.dhcp import AV_IP
        slot = int(np.nonzero(fastpath.sub.used)[0][0])
        fastpath.sub.vals[slot, AV_IP] ^= 1
        report2 = audit_invariants(engine=engine, pools=pools,
                                   dhcp=server)
        assert not report2.ok
        kinds = report2.violations_by_kind()
        assert "mirror-mismatch" in kinds, kinds
        # un-plant and prove the auditor settles clean again
        fastpath.sub.vals[slot, AV_IP] ^= 1
        assert audit_invariants(engine=engine, pools=pools,
                                dhcp=server).ok

    def test_dispatch_and_slow_drain_faults(self):
        engine, _pools, _fastpath, _server = _engine_stack()
        from bng_tpu.chaos.faults import FaultInjectedError

        with armed(FaultPlan(1, [
                FaultSpec("engine.dispatch", F.FAIL, at_hit=1)]),
                log=False):
            with pytest.raises(FaultInjectedError):
                engine.process([_discover(_mac(1), 1)])
        # the failed dispatch consumed nothing durable: the next batch
        # serves normally
        out = engine.process([_discover(_mac(1), 2)])
        assert out["slow"][0][1] is not None
        errs = engine.stats.slow_errors
        with armed(FaultPlan(1, [
                FaultSpec("engine.slow_drain", F.FAIL, at_hit=1)]),
                log=False):
            out = engine.process([_discover(_mac(2), 3)])
        assert out["slow"] == [(0, None)]
        assert engine.stats.slow_errors == errs + 1


# ---------------------------------------------------------------------------
# scenarios + runner determinism
# ---------------------------------------------------------------------------

class TestScenarios:
    @pytest.mark.parametrize("name", sorted(SCENARIOS))
    def test_scenario_ends_with_clean_audit(self, name):
        result = SCENARIOS[name](seed=123)
        assert result["ok"], json.dumps(result, indent=1)

    def test_run_scenarios_deterministic(self):
        a = runner.canonical_json(runner.run_scenarios(seed=9))
        b = runner.canonical_json(runner.run_scenarios(seed=9))
        assert a == b

    def test_soak_fast(self):
        r = runner.soak(seed=5, epochs=3)
        assert r["ok"], json.dumps(r, indent=1)
        assert all(e["audit_ok"] for e in r["epochs"])

    def test_soak_deterministic(self):
        a = runner.canonical_json(runner.soak(seed=6, epochs=2))
        b = runner.canonical_json(runner.soak(seed=6, epochs=2))
        assert a == b

    def test_unknown_scenario_rejected(self):
        with pytest.raises(ValueError):
            runner.run_scenarios(seed=1, names=["nope"])

    def test_metrics_families_recorded(self):
        from bng_tpu.control.metrics import BNGMetrics

        m = BNGMetrics()
        r = runner.soak(seed=5, epochs=2, metrics=m)
        assert r["ok"]
        assert m.invariant_audits.value() == 2
        assert m.invariant_last_violations.value() == 0
        text = m.expose()
        assert "bng_chaos_faults_injected_total" in text
        assert "bng_invariant_audits_total" in text
        # the audit epoch gauge carries the LAST epoch index
        assert m.invariant_last_epoch.value() == 1

    @pytest.mark.slow
    def test_long_soak(self):
        r = runner.soak(seed=17, epochs=12, n_macs=48, workers=4,
                        n_faults=16)
        assert r["ok"], json.dumps(r, indent=1)
        assert len(r["injected"]["injected"]) >= 4


# ---------------------------------------------------------------------------
# CLI: bng chaos run / checkpoint restore --audit
# ---------------------------------------------------------------------------

class TestCLI:
    def test_chaos_run_bit_deterministic(self, capsys):
        from bng_tpu.cli import main

        # --storm-scale shrinks the storm scenarios for the tier-1 gate;
        # make verify-chaos runs the full-scale suite (flash crowd at
        # 100k) through the same byte-compare
        flags = ["chaos", "run", "--seed", "5", "--storm-scale", "0.02"]
        assert main(flags) == 0
        first = capsys.readouterr().out
        assert main(flags) == 0
        second = capsys.readouterr().out
        assert first == second
        assert json.loads(first)["ok"] is True

    def test_chaos_run_single_scenario(self, capsys):
        from bng_tpu.cli import main

        rc = main(["chaos", "run", "--seed", "5",
                   "--scenario", "nat_expiry_under_skew"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert list(out["scenarios"]) == ["nat_expiry_under_skew"]

    def _app_cfg(self, tmp_path):
        from bng_tpu.cli import BNGConfig

        return BNGConfig(
            slowpath_workers=2, slowpath_worker_mode="inline",
            checkpoint_dir=str(tmp_path), metrics_enabled=False,
            dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False)

    _CLI_FLAGS = ["--slowpath-workers", "2",
                  "--slowpath-worker-mode", "inline",
                  "--no-metrics-enabled", "--no-dhcpv6-enabled",
                  "--no-slaac-enabled", "--no-walled-garden-enabled"]

    def test_checkpoint_restore_audit_accepts_good_snapshot(
            self, tmp_path, capsys):
        from bng_tpu.cli import BNGApp, main

        app = BNGApp(self._app_cfg(tmp_path))
        try:
            leased = dora_with_retries(app.components["fleet"], MACS,
                                       SimClock())
            assert len(leased) == len(MACS)
            app.components["checkpointer"].save_now(reason="test")
        finally:
            app.close()
        rc = main(["checkpoint", "restore", "--checkpoint-dir",
                   str(tmp_path), "--audit"] + self._CLI_FLAGS)
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["audit"]["ok"] is True
        assert out["restored_rows"]["fleet.leases"] == len(MACS)

    def test_checkpoint_restore_audit_refuses_bad_snapshot(
            self, tmp_path, capsys):
        """A snapshot that hydrates into a double-leased address must
        exit rc=2 — it can never silently serve traffic."""
        from bng_tpu.cli import BNGApp, main

        app = BNGApp(self._app_cfg(tmp_path))
        try:
            fleet = app.components["fleet"]
            leased = dora_with_retries(fleet, MACS, SimClock())
            victim_ip = next(iter(leased.values()))
            fleet._inline[0].restore_state({
                "session_seq": 0, "revoke": [], "leases": [{
                    "mac": _mac(999).hex(), "ip": victim_ip,
                    "pool_id": 1, "expiry": 2_000_000_000,
                    "circuit_id": "", "remote_id": "", "s_tag": 0,
                    "c_tag": 0, "session_id": "forged",
                    "client_class": 0, "username": "",
                    "qos_policy": ""}]})
            app.components["checkpointer"].save_now(reason="test")
        finally:
            app.close()
        rc = main(["checkpoint", "restore", "--checkpoint-dir",
                   str(tmp_path), "--audit"] + self._CLI_FLAGS)
        out = json.loads(capsys.readouterr().out)
        assert rc == 2
        assert "double-lease" in out["audit"]["violations_by_kind"]

    def test_corrupt_newest_falls_back_then_audits(self, tmp_path,
                                                   capsys):
        """End to end: corrupt the NEWEST file on disk; restore --audit
        falls back to the older good snapshot and still passes."""
        from bng_tpu.cli import BNGApp, main

        app = BNGApp(self._app_cfg(tmp_path))
        try:
            dora_with_retries(app.components["fleet"], MACS, SimClock())
            app.components["checkpointer"].save_now(reason="test")
            app.components["checkpointer"].save_now(reason="test")
        finally:
            app.close()
        files = sorted(tmp_path.glob("ckpt-*.bngckpt"))
        assert len(files) == 2
        newest = files[-1]
        newest.write_bytes(newest.read_bytes()[:-200])  # torn write
        rc = main(["checkpoint", "restore", "--checkpoint-dir",
                   str(tmp_path), "--audit"] + self._CLI_FLAGS)
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert out["audit"]["ok"] is True
        assert out["restored_rows"]["fleet.leases"] == len(MACS)
