"""Perf-ledger + gate tests (telemetry/ledger.py): schema append,
legacy import round-trip, cohort identity (CPU-vs-TPU refusal), and the
trend gate detecting a planted single-stage 2x p99 regression — named,
and including the non-headline stages (`lane_wait`, `device_wait`,
`fleet`). Runs jax-free; `make verify-perf` runs the `perf` marker."""

from __future__ import annotations

import copy
import json
import os
import shutil

import pytest

from bng_tpu.telemetry import ledger

pytestmark = pytest.mark.perf

REPO_LEDGER = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench_runs.jsonl")

STAGES = {"dispatch": 100.0, "device": 40.0, "device_wait": 500.0,
          "lane_wait": 30.0, "fleet": 200.0, "worker": 80.0,
          "total": 800.0}


def _tpu_line(i: int, scale: float = 1.0) -> dict:
    """One current-era schema'd TPU line with a full stage breakdown
    (what a healthy post-PR bench round appends)."""
    return {
        "schema_version": 1, "run_id": f"r{i:02d}",
        "metric": "Mpps/chip DHCP+NAT44 fast path",
        "value": 0.05 * scale, "unit": "Mpps",
        "batch": 8192, "subscribers": 1_000_000, "flows": 1_000_000,
        "offer_device_only_p99_us": 45.0,
        "device": "TPU v5e chip0",
        "env": {"platform": "tpu", "device_kind": "TPU v5e",
                "host": "tpu-host", "jaxlib": "0.4.37"},
        "stage_breakdown": {
            s: {"count": 200, "p50_us": v / 2,
                "p99_us": v * (1 + 0.02 * i), "p999_us": v * 1.2,
                "mean_us": v / 2, "max_us": v * 1.3}
            for s, v in STAGES.items()},
    }


def _cohort(n: int = 5) -> list[dict]:
    return [_tpu_line(i) for i in range(n)]


@pytest.fixture
def real_lines():
    return ledger.read(REPO_LEDGER)


# ---------------------------------------------------------------------------
# acceptance: the repo's real ledger
# ---------------------------------------------------------------------------

class TestRealLedger:
    def test_gate_real_ledger_clean(self):
        rep = ledger.gate_file(REPO_LEDGER)
        assert rep.rc == ledger.GATE_OK, rep.to_dict()

    def test_cli_gate_real_ledger_rc0(self, capsys):
        from bng_tpu.cli import main

        rc = main(["perf", "gate", "--ledger", REPO_LEDGER])
        assert rc == 0
        assert "CLEAN" in capsys.readouterr().out

    @pytest.mark.parametrize("stage",
                             ["lane_wait", "device_wait", "fleet",
                              "dispatch", "device"])
    def test_planted_2x_single_stage_regression_named(self, stage,
                                                      tmp_path):
        """The acceptance shape: real ledger + a current-era cohort +
        ONE line whose single stage p99 doubled — the gate exits
        non-zero and NAMES the stage, headline or not."""
        path = str(tmp_path / "ledger.jsonl")
        shutil.copyfile(REPO_LEDGER, path)
        for line in _cohort():
            ledger.append(path, line)
        bad = _tpu_line(9)
        bad["stage_breakdown"][stage]["p99_us"] *= 2
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION
        assert [r["key"] for r in rep.regressions] == [f"stage:{stage}"]

    def test_clean_candidate_after_cohort(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        shutil.copyfile(REPO_LEDGER, path)
        for line in _cohort() + [_tpu_line(9)]:
            ledger.append(path, line)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_OK, rep.to_dict()
        # every stage was actually trend-checked, not just the headline
        checked = set(rep.checked)
        assert {f"stage:{s}" for s in STAGES} <= checked
        assert "value" in checked
        assert "offer_device_only_p99_us" in checked


# ---------------------------------------------------------------------------
# cohort identity: backend / geometry refusal
# ---------------------------------------------------------------------------

class TestCohorts:
    def test_cpu_fallback_never_scored_against_tpu(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)
        cpu = _tpu_line(9)
        cpu["backend_fallback"] = "cpu"
        cpu["device"] = "TFRT_CPU_0"
        cpu["env"] = {"platform": "cpu", "device_kind": "TFRT_CPU"}
        ledger.append(path, cpu)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_INCOMPARABLE
        assert "refusing the cross-identity comparison" in rep.notes[0]

    def test_pallas_never_scored_against_xla_history(self, tmp_path):
        """ISSUE 11: the table-probe impl is cohort identity. A Pallas
        candidate against an xla-only history (legacy lines default to
        xla) is the rc=3 refusal, never a silent comparison."""
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)  # no table_impl stamp -> 'xla'
        pallas = _tpu_line(9, scale=5.0)  # looks like a huge regression
        pallas["table_impl"] = "pallas"
        ledger.append(path, pallas)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_INCOMPARABLE
        assert "'pallas'" in rep.notes[0]
        assert "xla" in rep.notes[0]

    def test_pallas_cohort_gates_within_itself(self, tmp_path):
        """Once Pallas history exists, a regressed Pallas run is caught
        against ITS cohort (and the xla lines never dilute it)."""
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)  # xla history at scale 1.0
        for i in range(4):  # pallas cohort: 2x the xla throughput
            ln = _tpu_line(20 + i, scale=2.0)
            ln["table_impl"] = "pallas"
            ledger.append(path, ln)
        bad = _tpu_line(30, scale=1.1)  # ~45% below the pallas median,
        bad["table_impl"] = "pallas"    # yet still above xla's history
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION, rep.to_dict()

    def test_cluster_width_is_cohort_identity(self, tmp_path):
        """ISSUE 16: instance count joins the cohort key. A 4-instance
        cluster headline against single-instance history (legacy lines
        default to 1) is the rc=3 refusal naming both widths — a
        cluster aggregate is a different machine, not a 4x win."""
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)  # no n_instances stamp -> 1
        wide = _tpu_line(9, scale=4.0)
        wide["n_instances"] = 4
        ledger.append(path, wide)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_INCOMPARABLE
        assert "instances=4" in rep.notes[0]
        assert "instances=1" in rep.notes[0]

    def test_cluster_width_gates_within_itself(self, tmp_path):
        """Once 4-instance history exists, a regressed 4-instance run is
        caught against ITS cohort."""
        path = str(tmp_path / "ledger.jsonl")
        for i in range(4):
            ln = _tpu_line(40 + i, scale=4.0)
            ln["n_instances"] = 4
            ledger.append(path, ln)
        bad = _tpu_line(50, scale=2.0)  # half the cluster trend
        bad["n_instances"] = 4
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION, rep.to_dict()

    def test_autotune_depth_is_cohort_identity(self, tmp_path):
        """Sweep points differing only in pipeline depth are different
        operating points: a depth-2 point must not be trend-gated
        against depth-8 history (a fabricated 2-4x 'regression')."""
        path = str(tmp_path / "ledger.jsonl")
        for i in range(4):  # depth-8 history: 4x the depth-2 throughput
            ledger.append(path, {
                "metric": "autotune sweep point", "value": 40.0,
                "unit": "Mpps", "batch": 8192, "depth": 8,
                "table_impl": "pallas",
                "env": {"platform": "tpu", "device_kind": "TPU v5e"},
                "device": "TPU v5e chip0"})
        point = {"metric": "autotune sweep point", "value": 10.0,
                 "unit": "Mpps", "batch": 8192, "depth": 2,
                 "table_impl": "pallas",
                 "env": {"platform": "tpu", "device_kind": "TPU v5e"},
                 "device": "TPU v5e chip0"}
        ledger.append(path, point)
        rep = ledger.gate_file(path)
        # different cohort (depth differs) -> vacuous pass, never rc=1/3
        assert rep.rc == ledger.GATE_OK, rep.to_dict()
        assert rep.cohort_n == 0

    def test_host_class_lines_never_impl_split(self, tmp_path):
        """A pure-host metric (config-1 control plane: no device, no
        table probe) keeps ONE cohort whatever BNG_TABLE_IMPL said —
        the stamp cannot affect the metric, so it must not void the
        regression history behind an rc=3 refusal."""
        path = str(tmp_path / "ledger.jsonl")
        for i in range(4):
            ledger.append(path, {
                "metric": "DHCP slow-path req/s (config 1)",
                "value": 50_000.0, "unit": "req/s",
                "env": {"host": "h", "jaxlib": "0.4.37"}})
        bad = {"metric": "DHCP slow-path req/s (config 1)",
               "value": 20_000.0, "unit": "req/s",
               "table_impl": "pallas",  # stamped, but host-class
               "env": {"host": "h", "jaxlib": "0.4.37",
                       "table_impl": "pallas"}}
        ledger.append(path, bad)
        assert ledger.backend_class(bad) == "host"
        assert ledger.table_impl(bad) == "xla"
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION, rep.to_dict()

    def test_vector_host_path_never_scored_against_scalar(self, tmp_path):
        """ISSUE 14: the host serving path is cohort identity. A
        vectorized-host candidate against scalar-only history (legacy
        lines default to scalar) is the rc=3 refusal naming BOTH host
        paths, never a silent comparison."""
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)  # no host_path stamp -> 'scalar'
        vec = _tpu_line(9, scale=5.0)  # looks like a huge regression
        vec["host_path"] = "vector"
        ledger.append(path, vec)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_INCOMPARABLE
        assert "host='vector'" in rep.notes[0]
        assert "host=scalar" in rep.notes[0]
        assert "vectorized host path never trends" in rep.notes[0]

    def test_host_path_cohort_gates_within_itself(self, tmp_path):
        """Once vector-host history exists, a regressed vector run is
        caught against ITS cohort."""
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)  # scalar history
        for i in range(4):
            ln = _tpu_line(20 + i, scale=2.0)
            ln["host_path"] = "vector"
            ledger.append(path, ln)
        bad = _tpu_line(30, scale=1.1)
        bad["host_path"] = "vector"
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION, rep.to_dict()

    def test_host_path_env_spelling_reaches_cohort(self):
        a = {"metric": "m", "value": 1.0, "unit": "Mpps", "batch": 64,
             "device": "TPU v5e_0", "host_path": "vector"}
        b = {"metric": "m", "value": 1.0, "unit": "Mpps", "batch": 64,
             "device": "TPU v5e_0", "env": {"host_path": "vector"}}
        assert ledger.cohort_key(a) == ledger.cohort_key(b)
        assert ledger.host_path({"metric": "m"}) == "scalar"  # legacy

    def test_env_fingerprint_table_impl_reaches_cohort(self, tmp_path):
        """The bench emitters stamp table_impl inside env too; either
        spelling lands in the same cohort key."""
        a = {"metric": "m", "value": 1.0, "unit": "Mpps", "batch": 64,
             "device": "TPU v5e_0", "table_impl": "pallas"}
        b = {"metric": "m", "value": 1.0, "unit": "Mpps", "batch": 64,
             "device": "TPU v5e_0", "env": {"table_impl": "pallas"}}
        assert ledger.cohort_key(a) == ledger.cohort_key(b)
        assert ledger.table_impl({"metric": "m"}) == "xla"  # legacy default

    def test_young_same_backend_cohort_is_vacuous_not_refused(
            self, tmp_path):
        """After a backend migration (cpu history, first tpu runs) a
        merely YOUNG same-backend cohort passes vacuously; rc=3 is
        reserved for ZERO same-backend history (review finding,
        reproduced): only run 1 on the new backend refuses, runs 2+
        accumulate history instead of staying CI-red."""
        path = str(tmp_path / "l.jsonl")
        cpu_lines = _cohort()
        for line in cpu_lines:
            line = dict(line)
            line["device"] = "TFRT_CPU_0"
            line["env"] = {"platform": "cpu", "device_kind": "cpu"}
            ledger.append(path, line)
        # run 1 on tpu: zero tpu history -> explicit refusal
        ledger.append(path, _tpu_line(7))
        assert ledger.gate_file(path).rc == ledger.GATE_INCOMPARABLE
        # run 2: one tpu line exists -> young cohort, vacuous pass
        ledger.append(path, _tpu_line(8))
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_OK
        assert any("cohort too small" in n for n in rep.notes)

    def test_fallback_flag_wins_over_healthy_looking_fields(self):
        line = _tpu_line(0)
        line["backend_fallback"] = "cpu"
        assert ledger.backend_class(line) == "cpu"

    def test_no_device_is_host_class(self):
        assert ledger.backend_class({"metric": "m"}) == "host"

    def test_device_kind_strips_ordinal(self):
        assert ledger.device_kind({"device": "TFRT_CPU_0"}) == "TFRT_CPU"
        assert ledger.device_kind(
            {"env": {"device_kind": "TPU v5e"}}) == "TPU v5e"

    def test_device_kind_prefers_device_string_for_continuity(self):
        """A new-schema line carries BOTH the legacy `device` string and
        the jax env.device_kind spelling ('cpu'); the cohort key must
        follow the `device` string or every new run silently loses its
        legacy cohort and the gate passes vacuously (review finding,
        reproduced against the real ledger)."""
        new = {"device": "TFRT_CPU_0",
               "env": {"device_kind": "cpu", "platform": "cpu"}}
        legacy = ledger.normalize_legacy({"device": "TFRT_CPU_0"})
        assert ledger.device_kind(new) == ledger.device_kind(legacy)

    def test_new_schema_line_cohorts_with_legacy_history(self, tmp_path):
        """End to end: a regressed new-schema headline run on the same
        host/device as the legacy history must be SCORED against it,
        not vacuously passed."""
        path = str(tmp_path / "l.jsonl")
        shutil.copyfile(REPO_LEDGER, path)
        bad = {"metric": "Mpps/chip DHCP+NAT44 fast path",
               "value": 0.0003, "unit": "Mpps",  # ~10x under the trend
               "batch": 512, "subscribers": 2000, "flows": 2000,
               "device": "TFRT_CPU_0",
               "env": {"platform": "cpu", "device_kind": "cpu",
                       "host": "h", "jaxlib": "0.4.36"}}
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.cohort_n >= 3, rep.to_dict()
        assert rep.rc == ledger.GATE_REGRESSION
        assert rep.regressions[0]["key"] == "value"

    def test_geometry_splits_cohorts(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)
        other = _tpu_line(9)
        other["batch"] = 512  # different geometry: not comparable
        other["stage_breakdown"]["fleet"]["p99_us"] *= 10
        ledger.append(path, other)
        rep = ledger.gate_file(path)
        # no same-geometry history at all -> vacuous pass, never a
        # cross-geometry comparison
        assert rep.rc == ledger.GATE_OK
        assert any("cohort too small" in n for n in rep.notes)

    def test_young_ledger_vacuous_pass(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        ledger.append(path, _tpu_line(0))
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_OK
        assert any("cohort too small" in n for n in rep.notes)


# ---------------------------------------------------------------------------
# gate coverage beyond stages
# ---------------------------------------------------------------------------

class TestGateKeys:
    def test_headline_value_regression(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)
        slow = _tpu_line(9)
        slow["value"] = 0.02  # Mpps halved-and-then-some
        ledger.append(path, slow)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION
        assert rep.regressions[0]["key"] == "value"
        assert rep.regressions[0]["direction"] == "higher-better"

    def test_offer_device_p99_regression(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)
        bad = _tpu_line(9)
        bad["offer_device_only_p99_us"] = 95.0
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION
        assert rep.regressions[0]["key"] == "offer_device_only_p99_us"

    def test_vanished_stage_is_a_coverage_hole(self, tmp_path):
        """Dapper's failure mode: a stage every cohort line carries
        disappearing from the candidate is flagged, not ignored."""
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort():
            ledger.append(path, line)
        hole = _tpu_line(9)
        del hole["stage_breakdown"]["lane_wait"]
        ledger.append(path, hole)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION
        assert rep.regressions[0]["key"] == "stage:lane_wait"
        assert "coverage hole" in rep.regressions[0]["detail"]

    def test_untraced_candidate_is_a_note_not_a_regression(self,
                                                           tmp_path):
        """A candidate with NO stage_breakdown (loadtest without
        --trace) against a traced cohort must not fabricate a
        coverage-hole regression per stage — it gets a loud note and
        the headline checks still run (review finding, reproduced)."""
        path = str(tmp_path / "l.jsonl")
        for line in _cohort():
            ledger.append(path, line)
        plain = _tpu_line(9)
        del plain["stage_breakdown"]
        ledger.append(path, plain)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_OK, rep.to_dict()
        assert any("no stage_breakdown" in n for n in rep.notes)
        assert "value" in rep.checked  # headline still trended

    def test_2x_always_trips_even_in_noisy_cohort(self, tmp_path):
        """The hard cap bounds tolerated excess at 90% of the median:
        a 2x regression can never hide inside cohort noise."""
        path = str(tmp_path / "ledger.jsonl")
        # wildly noisy cohort: p99 swings 3x run to run
        for i, scale in enumerate((0.5, 1.0, 1.5, 0.7, 1.3)):
            line = _tpu_line(i)
            line["stage_breakdown"]["fleet"]["p99_us"] = 200.0 * scale
            ledger.append(path, line)
        bad = _tpu_line(9)
        bad["stage_breakdown"]["fleet"]["p99_us"] = 2 * 200.0  # 2x median
        ledger.append(path, bad)
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_REGRESSION
        assert "stage:fleet" in [r["key"] for r in rep.regressions]

    def test_newest_gateable_index(self):
        """bench.py --gate ties its verdict to THIS run by comparing
        this index against the pre-run line count: an error-only or
        append-less run must never earn a CLEAN verdict about stale
        history."""
        lines = [_tpu_line(0), _tpu_line(1),
                 {"metric": "m", "value": 0.0, "error": "child rc=1"}]
        assert ledger.newest_gateable_index(lines) == 1
        assert ledger.newest_gateable_index(
            [{"metric": "m", "error": "x"}]) is None
        assert ledger.newest_gateable_index([]) is None

    def test_error_lines_never_gate_or_serve_as_history(self, tmp_path):
        path = str(tmp_path / "ledger.jsonl")
        for line in _cohort() + [_tpu_line(9)]:
            ledger.append(path, line)
        ledger.append(path, {"metric": "Mpps/chip DHCP+NAT44 fast path",
                             "value": 0.0, "unit": "Mpps",
                             "error": "child rc=1"})
        rep = ledger.gate_file(path)
        # candidate is the last GATEABLE line, and it is clean
        assert rep.rc == ledger.GATE_OK
        assert rep.candidate["run_id"] == "r09"


# ---------------------------------------------------------------------------
# schema append / read / legacy import round-trip
# ---------------------------------------------------------------------------

class TestSchema:
    def test_append_stamps_schema(self, tmp_path):
        path = str(tmp_path / "l.jsonl")
        stamped = ledger.append(path, {"metric": "m", "value": 1.0})
        assert stamped["schema_version"] == ledger.SCHEMA_VERSION
        assert stamped["run_id"] and stamped["ts"]
        back = ledger.read(path)
        assert back[0] == stamped
        # ts leads the line (the bench_runs.jsonl convention)
        raw = open(path).read()
        assert raw.startswith('{"ts":')

    def test_corrupt_line_noted_not_fatal(self, tmp_path):
        path = str(tmp_path / "l.jsonl")
        for line in _cohort() + [_tpu_line(9)]:
            ledger.append(path, line)
        with open(path, "a") as f:
            f.write("{not json\n")
        rep = ledger.gate_file(path)
        assert rep.rc == ledger.GATE_OK
        assert any("corrupt" in n for n in rep.notes)

    def test_unreadable_ledger_rc2(self):
        rep = ledger.gate_file("/nonexistent/ledger.jsonl")
        assert rep.rc == ledger.GATE_INTERNAL

    def test_import_round_trip(self, real_lines, tmp_path):
        migrated = ledger.import_legacy(real_lines)
        assert len(migrated) == len(real_lines)
        assert all(ln["schema_version"] == 0 for ln in migrated)
        assert all(ln["run_id"].startswith("legacy-") for ln in migrated)
        # every original field survives the migration
        for orig, mig in zip(real_lines, migrated):
            for k, v in orig.items():
                assert mig[k] == v
        # device-bearing lines recover a device_kind fingerprint
        dev = [m for o, m in zip(real_lines, migrated) if o.get("device")]
        assert dev and all(
            m["env"]["device_kind"] == "TFRT_CPU" for m in dev)
        # idempotent: importing the migrated set changes nothing
        again = ledger.import_legacy(migrated)
        assert again == migrated
        # and the migrated ledger still gates clean
        path = str(tmp_path / "migrated.jsonl")
        with open(path, "w") as f:
            for ln in migrated:
                f.write(json.dumps(ln) + "\n")
        assert ledger.gate_file(path).rc == ledger.GATE_OK

    def test_gate_can_exclude_legacy(self, tmp_path):
        """The schema_version 0 tag is the explicit include-or-exclude
        handle: --no-legacy drops pre-schema lines from cohorts."""
        path = str(tmp_path / "l.jsonl")
        shutil.copyfile(REPO_LEDGER, path)
        rep = ledger.gate_file(path, include_legacy=False)
        assert rep.rc == ledger.GATE_OK
        assert any("nothing to gate" in n for n in rep.notes)

    def test_cli_import_writes_out(self, tmp_path, capsys):
        from bng_tpu.cli import main

        out = str(tmp_path / "migrated.jsonl")
        rc = main(["perf", "import", "--ledger", REPO_LEDGER,
                   "--out", out])
        assert rc == 0
        lines = ledger.read(out)
        assert len(lines) == len(ledger.read(REPO_LEDGER)) >= 54
        assert all("schema_version" in ln for ln in lines)

    def test_cli_gate_rc_contract(self, tmp_path, capsys):
        """rc=1 regression via the CLI (the documented contract)."""
        from bng_tpu.cli import main

        path = str(tmp_path / "l.jsonl")
        for line in _cohort():
            ledger.append(path, line)
        bad = _tpu_line(9)
        bad["stage_breakdown"]["fleet"]["p99_us"] *= 2
        ledger.append(path, bad)
        rc = main(["perf", "gate", "--ledger", path, "--json"])
        out = capsys.readouterr()
        assert rc == 1
        assert "stage:fleet" in out.err
        doc = json.loads(out.out)
        assert doc["rc"] == 1 and not doc["ok"]


class TestFingerprint:
    def test_fingerprint_never_imports_jax(self):
        """config-1 calls this before any backend probe: the
        fingerprint must read only already-imported state."""
        import subprocess
        import sys

        code = (
            "import sys; "
            "from bng_tpu.telemetry.ledger import environment_fingerprint;"
            "env = environment_fingerprint(); "
            "assert 'jax' not in sys.modules, 'fingerprint imported jax'; "
            "assert env.get('host'); print('ok')"
        )
        res = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=60)
        assert res.returncode == 0, res.stderr
        assert "ok" in res.stdout

    def test_fingerprint_with_jax_loaded(self):
        env = ledger.environment_fingerprint()
        assert env["host"]
        # conftest initialized jax on cpu: device identity rides along
        assert env.get("platform") == "cpu"
