"""PeerPool tests: owner routing, forwarding, failover, reconcile.

Mirrors the reference's multi-node-in-one-process strategy
(pkg/pool tests; SURVEY §4.6).
"""

import pytest

from bng_tpu.control.peerpool import PeerPool, PeerPoolError, PoolRange


def make_cluster(n=3, size=100):
    nodes = [f"node{i}" for i in range(n)]
    pools: dict[str, PeerPool] = {}
    down: set[str] = set()

    def transport(node_id):
        if node_id in down:
            raise ConnectionError(f"{node_id} down")
        return pools[node_id]

    for nid in nodes:
        pools[nid] = PeerPool(nid, nodes, PoolRange(0x0A000000, size),
                              transport=transport)
    return pools, down


class TestPeerPool:
    def test_owner_allocates_locally(self):
        pools, _ = make_cluster()
        p0 = pools["node0"]
        owner = p0.owner_ranked("sub-A")[0]
        ip = pools[owner].allocate("sub-A")
        assert pools[owner].stats["local_allocs"] == 1
        assert pools[owner].stats["forwarded"] == 0
        assert pools[owner].by_subscriber["sub-A"] == ip

    def test_non_owner_forwards(self):
        pools, _ = make_cluster()
        sub = "sub-B"
        owner = pools["node0"].owner_ranked(sub)[0]
        non_owner = next(n for n in pools if n != owner)
        ip = pools[non_owner].allocate(sub)
        assert pools[non_owner].stats["forwarded"] == 1
        assert pools[owner].by_subscriber[sub] == ip
        # idempotent: same subscriber -> same ip from any node
        assert pools[owner].allocate(sub) == ip
        for n in pools:
            assert pools[n].get(sub) == ip

    def test_failover_to_next_ranked(self):
        pools, down = make_cluster()
        sub = "sub-C"
        ranked = pools["node0"].owner_ranked(sub)
        owner = ranked[0]
        caller = next(n for n in pools if n != owner)
        down.add(owner)
        ip = pools[caller].allocate(sub)
        assert ip is not None
        # allocated on the next healthy ranked node (or caller itself)
        holder = next(n for n in pools if sub in pools[n].by_subscriber)
        assert holder != owner
        assert pools[caller].stats["failovers"] >= 1

    def test_owner_failure_marks_unhealthy_then_recovers(self):
        pools, down = make_cluster()
        sub = "sub-D"
        owner = pools["node0"].owner_ranked(sub)[0]
        caller = next(n for n in pools if n != owner)
        down.add(owner)
        for _ in range(3):
            pools[caller].allocate(sub)  # each call retries the dead owner
        # after threshold failures the owner is excluded from ranking
        if owner in pools[caller].peers:
            assert not pools[caller].peers[owner].healthy
            assert owner not in pools[caller]._healthy_nodes()
        down.discard(owner)
        pools[caller].health_check(now=100.0)
        assert pools[caller].peers[owner].healthy

    def test_deterministic_cross_node_allocation(self):
        pools, _ = make_cluster()
        # same subscriber from different entry nodes -> same ip
        ip1 = pools["node0"].allocate("sub-E")
        ip2 = pools["node1"].allocate("sub-E")
        assert ip1 == ip2

    def test_release(self):
        pools, _ = make_cluster()
        ip = pools["node0"].allocate("sub-F")
        assert pools["node1"].release("sub-F")
        assert pools["node0"].get("sub-F") is None
        # address is reusable
        ip2 = pools["node2"].allocate("sub-F")
        assert ip2 == ip  # deterministic: same candidate free again

    def test_exhaustion(self):
        pools, _ = make_cluster(n=1, size=3)
        p = pools["node0"]
        got = set()
        for i in range(3):
            got.add(p.allocate(f"s{i}"))
        assert len(got) == 3
        with pytest.raises(PeerPoolError):
            p.allocate("s-overflow")

    def test_reconcile_drops_double_allocation(self):
        pools, down = make_cluster(n=2, size=50)
        # simulate a partition double-allocation: both nodes own ip X
        pools["node0"].allocations[0x0A000005] = "sub-X"
        pools["node0"].by_subscriber["sub-X"] = 0x0A000005
        pools["node1"].allocations[0x0A000005] = "sub-Y"
        pools["node1"].by_subscriber["sub-Y"] = 0x0A000005
        conflicts = pools["node0"].reconcile()
        assert conflicts == 1
        holders = [n for n in pools
                   if 0x0A000005 in pools[n].allocations]
        assert len(holders) >= 1
        # only one subscriber keeps the address
        subs = {pools[n].allocations.get(0x0A000005) for n in holders}
        assert len(subs) == 1

    def test_status(self):
        pools, _ = make_cluster()
        pools["node0"].allocate("sub-G")
        st = pools["node0"].status()
        assert st["pool_size"] == 100
        assert st["healthy_peers"] == 2
