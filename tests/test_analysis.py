"""bngcheck analyzer tests: every pass must flag its planted violation
and stay silent on the clean corpus (ISSUE 6 acceptance).

Layout per pass: a miniature project tree is written under tmp_path
(mirroring the real repo-relative paths, because pass scoping and fact
extraction key on them), the pass runs on that tree, and the findings
are asserted by code. The clean-corpus tests run the full analyzer over
THIS repo and require zero non-baselined findings — the same gate
`make verify-static` enforces.

No jax import anywhere here: the static half is pure stdlib, and these
tests prove it stays that way (test_no_jax_import).
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from bng_tpu.analysis import baseline as baseline_mod
from bng_tpu.analysis import run_analysis
from bng_tpu.analysis.core import Finding, Project, run_passes
from bng_tpu.analysis.passes import ALL_PASSES, all_codes, build

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parents[1]


def write_tree(root: Path, files: dict[str, str]) -> Path:
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return root


def run_on(root: Path, select: set[str]) -> list[Finding]:
    project = Project.load(root, [root])
    return run_passes(project, build(select)).findings


def codes_of(findings) -> set[str]:
    return {f.code for f in findings}


# ---------------------------------------------------------------------------
# facts the registry fixtures share (miniature registries)
# ---------------------------------------------------------------------------

MINI_SPANS = """\
(RING, ADMIT, DISPATCH, TOTAL) = range(4)
STAGE_NAMES = ("ring", "admit", "dispatch", "total")
(LANE_ENGINE, LANE_BENCH) = range(2)
LANE_NAMES = ("engine", "bench")

_ACTIVE = None


def t():
    if _ACTIVE is None:
        return None
    return _ACTIVE.clock()


def lap(stage, t0, tok=None):
    if _ACTIVE is None or t0 is None:
        return
    _ACTIVE.lap(stage, t0, tok)
"""

MINI_FAULTS = """\
POINT_KINDS = {
    "engine.dispatch": ("fail", "delay"),
    "ckpt.write": ("truncate",),
}

_ACTIVE = None


def fault_point(name):
    if _ACTIVE is None:
        return None
    return _ACTIVE.check(name)
"""

MINI_METRICS = """\
class Registry:
    def counter(self, name, help_text, labels=()):
        return name


def declare(r):
    a = r.counter("bng_good_total", "fine")
    return a
"""

MINI_RECORDER = """\
TRIG_LATENCY = "latency_excursion"
TRIG_WORKER = "worker_death"
"""

MINI_CKPT = """\
def snapshot(meta, fastpath):
    meta["components"]["fastpath"] = {}
    return meta


def restore_into(ckpt, fastpath):
    targets = {"fastpath": fastpath}
    return targets
"""


# ---------------------------------------------------------------------------
# hotpath pass (BNG001/BNG002/BNG003)
# ---------------------------------------------------------------------------

class TestHotPathPass:
    def test_dispatch_scope_force_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/runtime/engine.py": """\
import numpy as np


class Engine:
    def _dispatch_step(self, pkt):
        res = self._step(pkt)
        v = np.asarray(res.verdict)       # BNG001: force in dispatch
        n = int(res.out_len)              # BNG001: scalar force on taint
        if res.verdict:                   # BNG001: truthiness on taint
            pass
        return res
"""})
        found = run_on(tmp_path, {"hotpath"})
        assert [f.code for f in found].count("BNG001") == 3
        details = {f.detail for f in found}
        assert "np.asarray" in details and "truthiness" in details

    def test_retire_scope_force_not_flagged(self, tmp_path):
        # same forces in a retire-side function: NOT dispatch-scoped
        write_tree(tmp_path, {"bng_tpu/runtime/engine.py": """\
import numpy as np


class Engine:
    def _apply_ring_verdicts(self, res):
        vv = np.asarray(res.verdict)
        return int(res.out_len)
"""})
        assert run_on(tmp_path, {"hotpath"}) == []

    def test_batch_scope_loop_flagged(self, tmp_path):
        # BNG004: per-frame loops in batch-native serving functions
        write_tree(tmp_path, {"bng_tpu/runtime/ring.py": """\
class PyRing:
    def _assemble_vec(self, out, out_len, out_flags):
        for i, f in enumerate(self._pending):   # BNG004: per-frame
            out[i] = f
        return len(self._pending)

    def _complete_vec(self, verdict, out, out_len, n):
        i = 0
        while i < n:                            # BNG004: per-frame
            i += 1
"""})
        found = run_on(tmp_path, {"hotpath"})
        assert [f.code for f in found].count("BNG004") == 2
        details = {f.detail for f in found}
        assert "for:(i, f)" in details and "while" in details

    def test_batch_scope_const_range_not_flagged(self, tmp_path):
        # bounded vectorized iteration (the 2-tag VLAN walk / 64-step
        # TLV scan shape) and comprehensions are the batch-native idiom
        write_tree(tmp_path, {"bng_tpu/runtime/hostpath.py": """\
def classify_dhcp_batch(buf, lens):
    et = buf[:, 12]
    for _ in range(2):
        et = et + 1
    rows = [r for r in (1, 2, 3)]
    return et
"""})
        assert run_on(tmp_path, {"hotpath"}) == []

    def test_batch_scope_other_function_not_flagged(self, tmp_path):
        # a per-frame loop OUTSIDE the batch scope (retire-side helper)
        write_tree(tmp_path, {"bng_tpu/runtime/ring.py": """\
class PyRing:
    def _retire_helper(self, batch):
        for f in batch:
            yield f
"""})
        assert run_on(tmp_path, {"hotpath"}) == []

    def test_hook_missing_guard_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/telemetry/spans.py": """\
_ACTIVE = None


def stamp(stage):
    _ACTIVE.stamp(stage)          # BNG003: no disarmed guard
"""})
        found = run_on(tmp_path, {"hotpath"})
        assert codes_of(found) == {"BNG003"}

    def test_hook_alloc_before_guard_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/chaos/faults.py": """\
_ACTIVE = None


def fault_point(name):
    meta = {"point": name}        # BNG002: allocates while disarmed
    if _ACTIVE is None:
        return None
    return _ACTIVE.check(name, meta)
"""})
        found = run_on(tmp_path, {"hotpath"})
        assert codes_of(found) == {"BNG002"}

    def test_alloc_in_guard_return_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/telemetry/spans.py": """\
_ACTIVE = None


def drain():
    if _ACTIVE is None:
        return []                 # BNG002: allocates per disarmed call
    return _ACTIVE.drain()
"""})
        assert codes_of(run_on(tmp_path, {"hotpath"})) == {"BNG002"}

    def test_guard_first_hook_clean(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/telemetry/spans.py": MINI_SPANS})
        assert run_on(tmp_path, {"hotpath"}) == []


# ---------------------------------------------------------------------------
# jit discipline (BNG010/BNG011/BNG012)
# ---------------------------------------------------------------------------

class TestJitDisciplinePass:
    def test_uncached_jit_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/ops/thing.py": """\
import jax


def make_step(geom):
    def step(x):
        return x
    return jax.jit(step)          # BNG010: no lru_cache on the factory
"""})
        assert "BNG010" in codes_of(run_on(tmp_path, {"jit-discipline"}))

    def test_cached_factory_clean(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/ops/thing.py": """\
import functools

import jax


@functools.lru_cache(maxsize=8)
def make_step(geom):
    def step(tables, upd, x):
        tables = apply_update(tables, upd)
        return tables, x
    return jax.jit(step, donate_argnums=(0,))
"""})
        assert run_on(tmp_path, {"jit-discipline"}) == []

    def test_missing_donate_on_table_step_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/ops/thing.py": """\
import functools

import jax


@functools.lru_cache(maxsize=8)
def make_step(geom):
    def step(tables, upd, x):
        tables = apply_fastpath_updates(tables, upd)
        return tables, x
    return jax.jit(step)          # BNG011: table step, no donation
"""})
        found = run_on(tmp_path, {"jit-discipline"})
        assert codes_of(found) == {"BNG011"}

    def test_missing_donate_on_express_entry_flagged(self, tmp_path):
        # ISSUE 13: the AOT-compiled express entry threads the dhcp
        # chain AND the descriptor batch (verdict block aliases it) —
        # a jitted step running the express probe program must donate
        # even when a refactor drops the in-step update apply
        write_tree(tmp_path, {"bng_tpu/runtime/thing.py": """\
import functools

import jax


@functools.lru_cache(maxsize=8)
def make_express(geom):
    def step(tables, desc, now_s):
        res = express_verdicts(tables, desc, geom, now_s)
        return tables, res.block
    return jax.jit(step)          # BNG011: express entry, no donation
"""})
        found = run_on(tmp_path, {"jit-discipline"})
        assert codes_of(found) == {"BNG011"}

    def test_donated_express_entry_clean(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/runtime/thing.py": """\
import functools

import jax


@functools.lru_cache(maxsize=8)
def make_express(geom):
    def step(tables, upd, desc, now_s):
        tables = apply_fastpath_updates(tables, upd)
        res = express_verdicts(tables, desc, geom, now_s)
        return tables, res.block
    return jax.jit(step, donate_argnums=(0, 2))
"""})
        assert run_on(tmp_path, {"jit-discipline"}) == []

    def test_bare_scalar_at_express_exe_call_flagged(self, tmp_path):
        # the AOT executable call site obeys the same fixed-width
        # scalar discipline as the jitted steps
        write_tree(tmp_path, {"bng_tpu/runtime/thing.py": """\
class Engine:
    def go(self, express_exe, tables, upd, desc, now):
        return self.express_exe(tables, upd, desc, int(now))  # BNG012
"""})
        found = run_on(tmp_path, {"jit-discipline"})
        assert [f.code for f in found] == ["BNG012"]

    def test_bare_scalar_at_step_call_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/runtime/thing.py": """\
class Engine:
    def go(self, pkt, now):
        return self._step(pkt, int(now), now * 1e6)  # BNG012 x2
"""})
        found = run_on(tmp_path, {"jit-discipline"})
        assert [f.code for f in found] == ["BNG012", "BNG012"]

    def test_unhashable_static_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/ops/thing.py": """\
import jax


def f(x, opts):
    return x


g = jax.jit(f, static_argnums=[1])   # BNG012: literal list
"""})
        assert "BNG012" in codes_of(run_on(tmp_path, {"jit-discipline"}))

    def test_bare_jit_decorator_in_function_flagged(self, tmp_path):
        # `@jax.jit` with no parentheses is an ast.Attribute, not a
        # Call — it must still be a BNG010 site inside an uncached body
        write_tree(tmp_path, {"bng_tpu/ops/thing.py": """\
import jax


def bench_config(geom):
    @jax.jit
    def step(x):
        return x
    return step(geom)
"""})
        found = run_on(tmp_path, {"jit-discipline"})
        assert codes_of(found) == {"BNG010"}
        assert found[0].detail == "jit-in-bench_config"

    def test_bare_jit_decorator_on_table_step_flagged(self, tmp_path):
        # the bare form cannot carry donate_argnums at all: a
        # table-applying body is BNG011 even at module level
        write_tree(tmp_path, {"bng_tpu/ops/thing.py": """\
import jax


@jax.jit
def step(tables, upd):
    return apply_fastpath_updates(tables, upd)
"""})
        found = run_on(tmp_path, {"jit-discipline"})
        assert codes_of(found) == {"BNG011"}

    def test_bare_jit_decorator_module_level_clean(self, tmp_path):
        # module-level bare @jax.jit on a non-table body: constructed
        # once at import, nothing to donate — clean
        write_tree(tmp_path, {"bng_tpu/ops/thing.py": """\
import jax


@jax.jit
def step(x):
    return x * 2
"""})
        assert run_on(tmp_path, {"jit-discipline"}) == []


# ---------------------------------------------------------------------------
# handler audit (BNG020/BNG021)
# ---------------------------------------------------------------------------

class TestHandlerAuditPass:
    def test_pass_only_broad_handler_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/control/foo.py": """\
def f(x):
    try:
        return x()
    except Exception:
        pass
"""})
        assert codes_of(run_on(tmp_path, {"handler-audit"})) == {"BNG020"}

    def test_silent_broad_handler_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/runtime/foo.py": """\
def f(x):
    ok = True
    try:
        x()
    except Exception:
        ok = False
    return ok
"""})
        assert codes_of(run_on(tmp_path, {"handler-audit"})) == {"BNG021"}

    def test_logging_counting_raising_handlers_clean(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/control/foo.py": """\
def f(x, log, stats):
    try:
        x()
    except Exception as e:
        log.warning("failed", error=str(e))
    try:
        x()
    except Exception:
        stats.errors += 1
    try:
        x()
    except Exception:
        raise
"""})
        assert run_on(tmp_path, {"handler-audit"}) == []

    def test_narrow_handler_not_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/control/foo.py": """\
def f(x):
    try:
        x()
    except ValueError:
        pass
"""})
        assert run_on(tmp_path, {"handler-audit"}) == []

    def test_outside_scope_not_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/utils/foo.py": """\
def f(x):
    try:
        x()
    except Exception:
        pass
"""})
        assert run_on(tmp_path, {"handler-audit"}) == []


# ---------------------------------------------------------------------------
# registry consistency (BNG030-BNG035)
# ---------------------------------------------------------------------------

REGISTRY_FACTS = {
    "bng_tpu/telemetry/spans.py": MINI_SPANS,
    "bng_tpu/chaos/faults.py": MINI_FAULTS,
    "bng_tpu/control/metrics.py": MINI_METRICS,
    "bng_tpu/telemetry/recorder.py": MINI_RECORDER,
    "bng_tpu/runtime/checkpoint.py": MINI_CKPT,
}


class TestRegistryPass:
    def test_unknown_stage_flagged(self, tmp_path):
        write_tree(tmp_path, {**REGISTRY_FACTS,
                              "bng_tpu/runtime/user.py": """\
from bng_tpu.telemetry import spans as tele


def f(t0):
    tele.lap(tele.BOGUS_STAGE, t0)
    tele.lap("dispatch", t0)
"""})
        found = [f for f in run_on(tmp_path, {"registry"})
                 if f.code == "BNG030"]
        assert {f.detail for f in found} == {"BOGUS_STAGE", "dispatch"}

    def test_unregistered_fault_point_flagged(self, tmp_path):
        write_tree(tmp_path, {**REGISTRY_FACTS,
                              "bng_tpu/control/user.py": """\
from bng_tpu.chaos.faults import fault_point


def f():
    fault_point("engine.dispatch")   # registered: clean
    fault_point("nope.unregistered")  # BNG031
"""})
        found = [f for f in run_on(tmp_path, {"registry"})
                 if f.code == "BNG031"]
        assert [f.detail for f in found] == ["nope.unregistered"]

    def test_unprefixed_and_stray_metric_flagged(self, tmp_path):
        write_tree(tmp_path, {**REGISTRY_FACTS,
                              "bng_tpu/control/metrics.py": MINI_METRICS
                              + """

def bad(r):
    return r.counter("foo_total", "no prefix")  # BNG032
""",
                              "bng_tpu/runtime/stray.py": """\
def f(r):
    return r.counter("bng_stray_total", "x")  # BNG035: not metrics.py
"""})
        found = run_on(tmp_path, {"registry"})
        assert {f.code for f in found} == {"BNG032", "BNG035"}

    def test_checkpoint_asymmetry_flagged(self, tmp_path):
        write_tree(tmp_path, {**REGISTRY_FACTS,
                              "bng_tpu/runtime/checkpoint.py": """\
def snapshot(meta, fastpath, nat):
    meta["components"]["fastpath"] = {}
    meta["components"]["nat"] = {}
    meta["components"]["orphan"] = {}       # save-only -> BNG033
    return meta


def restore_into(ckpt, fastpath, nat):
    comps = dict(ckpt)
    targets = {"fastpath": fastpath, "nat": nat}
    if "fastpath" in comps:
        pass
    return targets
"""})
        found = [f for f in run_on(tmp_path, {"registry"})
                 if f.code == "BNG033"]
        assert [f.detail for f in found] == ["save-only:orphan"]

    def test_unknown_trigger_reason_flagged(self, tmp_path):
        write_tree(tmp_path, {**REGISTRY_FACTS,
                              "bng_tpu/control/user.py": """\
from bng_tpu.telemetry import spans as tele


def f():
    tele.trigger("worker_death", "fine")
    tele.trigger("spooky_reason", "BNG034")
"""})
        found = [f for f in run_on(tmp_path, {"registry"})
                 if f.code == "BNG034"]
        assert [f.detail for f in found] == ["spooky_reason"]

    def test_missing_fact_source_is_loud(self, tmp_path):
        # no fact source anywhere in the tree: EVERY vocabulary-backed
        # check must say so, not silently check nothing
        write_tree(tmp_path, {"bng_tpu/runtime/user.py": "x = 1\n"})
        found = run_on(tmp_path, {"registry"})
        assert {f.code for f in found} == {"BNG990"}
        assert {f.detail for f in found} == {
            "stages", "fault-points", "trigger-reasons",
            "checkpoint-components"}


# ---------------------------------------------------------------------------
# single-writer (BNG040/BNG041)
# ---------------------------------------------------------------------------

class TestSingleWriterPass:
    def test_mutator_outside_allowlist_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/telemetry/rogue.py": """\
def f(engine, mac):
    engine.fastpath.add_subscriber(mac, pool_id=1, ip=1, lease_expiry=9)
"""})
        found = run_on(tmp_path, {"single-writer"})
        assert codes_of(found) == {"BNG040"}

    def test_tables_rebind_outside_engine_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/telemetry/rogue.py": """\
def f(engine, new):
    engine.tables = new
"""})
        found = run_on(tmp_path, {"single-writer"})
        assert codes_of(found) == {"BNG041"}

    def test_allowlisted_writer_clean(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/control/dhcp_server.py": """\
def f(tables, mac):
    tables.fastpath.add_subscriber(mac, pool_id=1, ip=1, lease_expiry=9)
"""})
        assert run_on(tmp_path, {"single-writer"}) == []

    def test_unrelated_insert_not_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/telemetry/fine.py": """\
def f(some_list, q):
    some_list.insert(0, q)      # not a table receiver
"""})
        assert run_on(tmp_path, {"single-writer"}) == []

    def test_fabric_membership_mutator_outside_allowlist_flagged(
            self, tmp_path):
        # ISSUE 19: the failure-detector views are single-writer state;
        # a rogue module watching/resetting slots desyncs verdicts from
        # the coordinator's HA ladder
        write_tree(tmp_path, {"bng_tpu/telemetry/rogue.py": """\
def f(coord, iid, now):
    coord.fabric_detector.watch(iid, now=now)
    coord.fabric_detector.reset(iid, now=now)
    coord.fabric_transport.reset_peer(iid)
"""})
        found = run_on(tmp_path, {"single-writer"})
        assert codes_of(found) == {"BNG040"}
        assert len(found) == 3

    def test_fabric_mutators_from_coordinator_clean(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/cluster/coordinator.py": """\
def f(self, iid, now):
    self.fabric_detector.watch(iid, now=now)
    self.fabric_transport.reset_peer(iid)
"""})
        assert run_on(tmp_path, {"single-writer"}) == []

    def test_generic_reset_receiver_not_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/telemetry/fine.py": """\
def f(histogram, sock):
    histogram.counters.reset()   # not a fabric receiver
    sock.reset_peer("x")         # bare name: no receiver chain match
"""})
        assert run_on(tmp_path, {"single-writer"}) == []

    def test_handoff_cursor_mutator_outside_allowlist_flagged(
            self, tmp_path):
        # ISSUE 20: the handoff receiver's ACK cursor / chunk map is
        # single-writer state — a rogue module feeding chunks or
        # manifests past the manager could half-hydrate a member
        # without the digest gate
        write_tree(tmp_path, {"bng_tpu/telemetry/rogue.py": """\
def f(member, src, body):
    member.handoff.receiver.set_manifest(src, body)
    member.handoff.receiver.accept_chunk(src, body)
"""})
        found = run_on(tmp_path, {"single-writer"})
        assert codes_of(found) == {"BNG040"}
        assert len(found) == 2

    def test_handoff_mutators_from_protocol_clean(self, tmp_path):
        write_tree(tmp_path,
                   {"bng_tpu/cluster/handoff/protocol.py": """\
def f(self, msg):
    self.receiver.set_manifest(msg.src, msg.body)
    self.receiver.accept_chunk(msg.src, msg.body)
"""})
        assert run_on(tmp_path, {"single-writer"}) == []


# ---------------------------------------------------------------------------
# fencing (BNG050)
# ---------------------------------------------------------------------------

class TestFencingPass:
    def test_unfenced_async_timing_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/utils/timing.py": """\
import time


def bench(engine, pkt):
    t1 = time.perf_counter()
    engine._dispatch_step(pkt)
    return time.perf_counter() - t1   # BNG050: measures enqueue only
"""})
        found = run_on(tmp_path, {"fencing"})
        assert codes_of(found) == {"BNG050"}

    def test_fenced_timing_clean(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/utils/timing.py": """\
import time

import jax


def bench(engine, pkt):
    t1 = time.perf_counter()
    res = engine._dispatch_step(pkt)
    jax.block_until_ready(res.verdict)
    return time.perf_counter() - t1
"""})
        assert run_on(tmp_path, {"fencing"}) == []

    def test_sync_surface_not_flagged(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/utils/timing.py": """\
import time


def bench(engine, frames):
    t1 = time.perf_counter()
    engine.process(frames)      # sync surface forces its own outputs
    return time.perf_counter() - t1
"""})
        assert run_on(tmp_path, {"fencing"}) == []


# ---------------------------------------------------------------------------
# concurrency pass (BNG060-BNG064) — ISSUE 9
# ---------------------------------------------------------------------------
#
# Each fixture tree carries a mini cli.py (the loop-roots fact: BNGApp
# tick/drive_once) plus a control/ module spawning its own thread, so
# the pass sees two contexts. The clean twin of every planted tree must
# stay silent — that asymmetry IS the test.

CONC_CLI = """\
class BNGApp:
    def __init__(self):
        self.w = Widget()

    def tick(self):
        self.w.poke()
"""

WIDGET_HEAD = """\
import threading


class Widget:
    def __init__(self):
        self._lock = threading.Lock()
        self.flag = 0
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._spin)
        self._t.start()

    def stop(self):
        self._t.join()

"""


def conc_tree(widget_tail: str) -> dict:
    return {"bng_tpu/cli.py": CONC_CLI,
            "bng_tpu/control/widget.py": WIDGET_HEAD + widget_tail}


class TestConcurrencyPass:
    def test_cross_context_unlocked_mutation_flagged(self, tmp_path):
        # flag written by the widget thread AND the loop, no lock
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        self.flag = 1

    def poke(self):
        self.flag = 2
"""))
        found = run_on(tmp_path, {"concurrency"})
        assert [f.code for f in found] == ["BNG060"]
        assert found[0].detail == "Widget.flag"

    def test_common_lock_clean(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        with self._lock:
            self.flag = 2
"""))
        assert run_on(tmp_path, {"concurrency"}) == []

    def test_constructor_writes_not_shared(self, tmp_path):
        # __init__ writes precede publication: the widget thread writing
        # what the constructor also wrote is not a race
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        return self.flag
"""))
        assert run_on(tmp_path, {"concurrency"}) == []

    def test_check_then_act_without_writers_lock_flagged(self, tmp_path):
        # writers agree on _lock; the loop tests the flag OUTSIDE it
        # then writes under it — the stale-decision shape (PR 7)
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        if not self.flag:
            with self._lock:
                self.flag = 2
"""))
        found = run_on(tmp_path, {"concurrency"})
        assert [f.code for f in found] == ["BNG062"]
        assert found[0].detail == "Widget.flag"

    def test_check_then_act_inside_lock_clean(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        with self._lock:
            if not self.flag:
                self.flag = 2
"""))
        assert run_on(tmp_path, {"concurrency"}) == []

    def test_bare_acquire_flagged_try_finally_clean(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        self._lock.acquire()
        self.flag = 2
        self._lock.release()

    def poke_safe(self):
        self._lock.acquire()
        try:
            self.flag = 3
        finally:
            self._lock.release()
"""))
        found = [f for f in run_on(tmp_path, {"concurrency"})
                 if f.code == "BNG061"]
        assert len(found) == 1
        assert found[0].scope == "Widget.poke"

    def test_blocking_under_loop_lock_flagged(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        import time
        with self._lock:
            time.sleep(0.1)
            self.flag = 2
"""))
        found = [f for f in run_on(tmp_path, {"concurrency"})
                 if f.code == "BNG063"]
        assert len(found) == 1 and "sleep" in found[0].detail

    def test_blocking_outside_lock_clean(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        import time
        time.sleep(0.1)
        with self._lock:
            self.flag = 2
"""))
        assert [f for f in run_on(tmp_path, {"concurrency"})
                if f.code == "BNG063"] == []

    def test_string_join_not_blocking(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        with self._lock:
            self.flag = 2
        return ",".join(str(x) for x in (1, 2))
"""))
        assert [f for f in run_on(tmp_path, {"concurrency"})
                if f.code == "BNG063"] == []

    def test_orphan_thread_flagged_stop_path_clean(self, tmp_path):
        write_tree(tmp_path, {
            "bng_tpu/cli.py": CONC_CLI.replace("Widget", "Orphan"),
            "bng_tpu/control/orphan.py": """\
import threading


class Orphan:
    def poke(self):
        pass

    def launch(self):
        threading.Thread(target=self._spin, daemon=True).start()

    def _spin(self):
        pass
"""})
        found = [f for f in run_on(tmp_path, {"concurrency"})
                 if f.code == "BNG064"]
        assert len(found) == 1 and found[0].scope == "Orphan.launch"
        # the stop-path twin (the same tree's Widget head has stop+join)
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        pass

    def poke(self):
        pass
"""))
        clean = [f for f in run_on(tmp_path, {"concurrency"})
                 if f.code == "BNG064"
                 and "widget" in f.path]
        assert clean == []

    def test_unresolvable_thread_target_is_loud(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        pass

    def poke(self):
        pass

    def weird(self, pick):
        threading.Thread(target=pick()).start()
"""))
        found = [f for f in run_on(tmp_path, {"concurrency"})
                 if f.code == "BNG990"]
        assert any(f.detail.startswith("thread-target:") for f in found)

    def test_missing_loop_roots_is_loud(self, tmp_path):
        # no cli.py/BNGApp anywhere: the pass must say the loop context
        # is unclassifiable, not silently check nothing
        write_tree(tmp_path, {"bng_tpu/control/solo.py": "X = 1\n"})
        found = run_on(tmp_path, {"concurrency"})
        assert any(f.code == "BNG990" and f.detail == "loop-roots"
                   for f in found)

    def test_same_named_classes_in_different_modules_dont_merge(
            self, tmp_path):
        # two `Handler` classes in different control/ modules, each
        # writing the same attr from a different context: their site
        # lists must stay separate (same-file class identity), or the
        # disjoint contexts would fabricate a cross-context BNG060
        handler = '''\
import threading


class Handler:
    def serve(self):
        threading.Thread(target=self._run).start()

    def stop(self):
        pass

    def _run(self):
        self.busy = 1
'''
        write_tree(tmp_path, {
            "bng_tpu/cli.py": "class BNGApp:\n    def tick(self):\n"
                              "        pass\n",
            "bng_tpu/control/alpha.py": handler,
            "bng_tpu/control/beta.py": handler,
        })
        assert [f for f in run_on(tmp_path, {"concurrency"})
                if f.code == "BNG060"] == []

    def test_worker_context_excluded_from_races(self, tmp_path):
        # a multiprocessing target shares no memory with the loop:
        # loop+worker mutation of the same attr is NOT a BNG060
        write_tree(tmp_path, {
            "bng_tpu/cli.py": CONC_CLI,
            "bng_tpu/control/widget.py": """\
import multiprocessing


class Widget:
    def __init__(self):
        self.flag = 0

    def launch(self):
        multiprocessing.Process(target=self._grind).start()

    def _grind(self):
        self.flag = 1

    def poke(self):
        self.flag = 2
"""})
        assert [f for f in run_on(tmp_path, {"concurrency"})
                if f.code == "BNG060"] == []


class TestConcurrencyFacts:
    def test_contexts_json_section(self, tmp_path):
        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        with self._lock:
            self.flag = 1

    def poke(self):
        with self._lock:
            self.flag = 2
"""))
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--no-baseline", "--json",
             "--select", "concurrency"],
            cwd=REPO, capture_output=True, text=True)
        doc = json.loads(out.stdout)
        ctx = doc["contexts"]
        fns = ctx["functions"]
        spin = fns["bng_tpu/control/widget.py::Widget._spin"]
        assert spin["contexts"] == ["thread:widget"]
        poke = fns["bng_tpu/control/widget.py::Widget.poke"]
        assert poke["contexts"] == ["loop"]
        assert any(e["context"] == "thread:widget" for e in ctx["entries"])
        assert ctx["unresolved_entry_points"] == []

    def test_repo_classification_matches_known_anchors(self, repo_report):
        """The real repo's classification must agree with the hand-known
        architecture: ops handlers are ctl, the fleet gather is
        loop-held-_ctl, the SSE delta apply is ha-sync."""
        from bng_tpu.analysis import facts
        from bng_tpu.analysis.core import Project as P

        project = P.load(REPO)
        model = facts.build_concurrency_model(project)
        rep = model.contexts_report()
        fns = rep["functions"]
        sub = fns["bng_tpu/control/opsctl.py::OpsController.submit"]
        assert "ctl" in sub["contexts"]
        gather = fns["bng_tpu/control/fleet.py::SlowPathFleet._gather"]
        assert gather["contexts"] == ["loop"]
        assert "_ctl" in gather["locks_held"]
        onchange = fns["bng_tpu/control/ha.py::StandbySyncer._on_change"]
        assert "ha-sync" in onchange["contexts"]
        run_p = fns["bng_tpu/control/opsctl.py::OpsController.run_pending"]
        assert "loop" in run_p["contexts"]

    def test_extraction_cache_hit_and_invalidation(self, tmp_path):
        import os

        from bng_tpu.analysis import facts
        from bng_tpu.analysis.core import Project as P

        write_tree(tmp_path, conc_tree("""\
    def _spin(self):
        self.flag = 1

    def poke(self):
        self.flag = 2
"""))
        m1 = facts.build_concurrency_model(P.load(tmp_path, [tmp_path]))
        assert m1.cache_hit is False
        assert (tmp_path / facts.CACHE_NAME).exists()
        m2 = facts.build_concurrency_model(P.load(tmp_path, [tmp_path]))
        assert m2.cache_hit is True
        # an edited file must not serve a stale summary: fix the race,
        # bump mtime past the cached key, re-run -> finding disappears
        w = tmp_path / "bng_tpu/control/widget.py"
        w.write_text(w.read_text().replace(
            "        self.flag = 2",
            "        with self._lock:\n            self.flag = 2").replace(
            "    def _spin(self):\n        self.flag = 1",
            "    def _spin(self):\n        with self._lock:\n"
            "            self.flag = 1"))
        st = w.stat()
        os.utime(w, ns=(st.st_atime_ns, st.st_mtime_ns + 10_000_000))
        found = run_on(tmp_path, {"concurrency"})
        assert [f for f in found if f.code == "BNG060"] == []

    def test_narrowed_scan_preserves_other_cache_entries(self, tmp_path):
        # a path-narrowed run must not evict the full tree's cached
        # summaries — the next full run should still warm-hit
        import json as _json

        from bng_tpu.analysis import facts
        from bng_tpu.analysis.core import Project as P

        write_tree(tmp_path, conc_tree('''\
    def _spin(self):
        self.flag = 1

    def poke(self):
        self.flag = 2
'''))
        facts.build_concurrency_model(P.load(tmp_path, [tmp_path]))
        full = set(_json.loads(
            (tmp_path / facts.CACHE_NAME).read_text())["files"])
        assert len(full) == 2
        narrow = P.load(tmp_path,
                        [tmp_path / "bng_tpu" / "control" / "widget.py"])
        facts.build_concurrency_model(narrow)
        kept = set(_json.loads(
            (tmp_path / facts.CACHE_NAME).read_text())["files"])
        assert kept == full
        m = facts.build_concurrency_model(P.load(tmp_path, [tmp_path]))
        assert m.cache_hit is True

    def test_selective_update_preserves_concurrency_entries(self, tmp_path):
        """--select handler-audit --update-baseline must not wipe a
        justified BNG06x entry (and vice versa) — the scope rule covers
        the new pass's codes."""
        write_tree(tmp_path, {"bng_tpu/control/foo.py": "x = 1\n"})
        bl = tmp_path / "bl.json"
        baseline_mod.write([
            Finding(code="BNG063", path="bng_tpu/control/fleet.py", line=7,
                    message="m", scope="SlowPathFleet._gather",
                    detail="recv@SlowPathFleet._gather"),
        ], bl)
        d = json.loads(bl.read_text())
        d["findings"][0]["justification"] = "the fan-in IS the batch"
        bl.write_text(json.dumps(d))
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--baseline", str(bl),
             "--select", "handler-audit", "--update-baseline"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        kept = json.loads(bl.read_text())["findings"]
        assert [(e["code"], e["justification"]) for e in kept] == [
            ("BNG063", "the fan-in IS the batch")]
        # a concurrency-selected update on a tree missing fleet.py also
        # keeps it: the entry's file is outside the scanned set
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--baseline", str(bl),
             "--select", "concurrency", "--update-baseline"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        kept = json.loads(bl.read_text())["findings"]
        assert ("BNG063", "the fan-in IS the batch") in [
            (e["code"], e["justification"]) for e in kept]


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------

class TestBaseline:
    def _finding(self, line=10):
        return Finding(code="BNG020", path="bng_tpu/control/x.py",
                       line=line, message="m", scope="F.g", detail="d")

    def test_roundtrip_and_line_independence(self, tmp_path):
        bl = tmp_path / "baseline.json"
        baseline_mod.write([self._finding(line=10)], bl)
        loaded = baseline_mod.load(bl)
        # the same finding at a DIFFERENT line still matches
        new, accepted, stale = baseline_mod.split(
            [self._finding(line=99)], loaded)
        assert new == [] and len(accepted) == 1 and stale == []

    def test_stale_entries_reported(self, tmp_path):
        bl = tmp_path / "baseline.json"
        baseline_mod.write([self._finding()], bl)
        new, accepted, stale = baseline_mod.split([], baseline_mod.load(bl))
        assert len(stale) == 1

    def test_update_preserves_justification(self, tmp_path):
        bl = tmp_path / "baseline.json"
        baseline_mod.write([self._finding()], bl)
        d = json.loads(bl.read_text())
        d["findings"][0]["justification"] = "because reasons"
        bl.write_text(json.dumps(d))
        old = baseline_mod.load(bl)
        baseline_mod.write([self._finding(line=42)], bl, old=old)
        assert (json.loads(bl.read_text())["findings"][0]["justification"]
                == "because reasons")

    def test_repo_baseline_fully_justified(self):
        """Every checked-in baseline entry carries a real justification
        (the satellite requirement: one-line tag each, no TODOs)."""
        d = json.loads((REPO / "bng_tpu/analysis/baseline.json").read_text())
        for e in d["findings"]:
            assert e["justification"] and "TODO" not in e["justification"], e


class TestNarrowGatherPass:
    """BNG014 (ISSUE 11): <8-word table/value rows — the PERF_NOTES §2
    gather-serialization shape — are machine-checked, not folklore."""

    TABLE_STUB = "WAYS = 4\n\n\nclass HostTable:\n    pass\n"

    def test_narrow_val_words_literal_flagged(self, tmp_path):
        write_tree(tmp_path, {
            "bng_tpu/ops/table.py": self.TABLE_STUB,
            "bng_tpu/control/newmap.py": """\
from bng_tpu.ops.table import HostTable


class Manager:
    def __init__(self):
        self.fwd = HostTable(1024, 4, val_words=16, name="wide_ok")
        self.rev = HostTable(1024, key_words=4, val_words=4,
                             name="narrow_rev")
"""})
        found = run_on(tmp_path, {"gather"})
        assert codes_of(found) == {"BNG014"}
        assert len(found) == 1
        assert "narrow_rev" in found[0].detail

    def test_narrow_val_words_via_constant_flagged(self, tmp_path):
        """Widths resolve through module-level constants anywhere in
        the scan set — the repo's *_WORDS convention."""
        write_tree(tmp_path, {
            "bng_tpu/ops/table.py": self.TABLE_STUB,
            "bng_tpu/ops/widths.py": "SHORT_WORDS = 6\nLONG_WORDS = 8\n",
            "bng_tpu/control/newmap.py": """\
from bng_tpu.ops.table import HostTable
from bng_tpu.ops.widths import LONG_WORDS, SHORT_WORDS

t_ok = HostTable(64, 1, LONG_WORDS, name="padded")
t_bad = HostTable(64, 1, SHORT_WORDS, name="short")
"""})
        found = run_on(tmp_path, {"gather"})
        assert len(found) == 1 and found[0].code == "BNG014"
        assert "short" in found[0].detail

    def test_conflicting_constant_names_resolve_same_file_first(
            self, tmp_path):
        """A cross-module name collision must not silently mis-resolve a
        width (the PR-9 collision lesson): the defining file's own value
        wins, and a name with CONFLICTING foreign definitions is
        unresolved — never first-scan-order-wins."""
        write_tree(tmp_path, {
            "bng_tpu/ops/table.py": self.TABLE_STUB,
            # scan order puts this wide same-named constant FIRST
            "bng_tpu/control/a_wide.py": "ROW_WORDS = 8\n",
            "bng_tpu/control/narrowmap.py": """\
from bng_tpu.ops.table import HostTable

ROW_WORDS = 4

t = HostTable(64, 1, ROW_WORDS, name="shadowed_narrow")
"""})
        found = run_on(tmp_path, {"gather"})
        assert codes_of(found) == {"BNG014"}
        assert "shadowed_narrow" in found[0].detail
        # ambiguous foreign-only reference -> unresolved, not flagged
        write_tree(tmp_path, {
            "bng_tpu/control/narrowmap.py": """\
from bng_tpu.ops.table import HostTable
from bng_tpu.control.b_conflict import OTHER_WORDS

t = HostTable(64, 1, OTHER_WORDS, name="ambiguous")
""",
            "bng_tpu/control/b_conflict.py": "OTHER_WORDS = 4\n",
            "bng_tpu/control/c_conflict.py": "OTHER_WORDS = 8\n"})
        assert run_on(tmp_path, {"gather"}) == []

    def test_wide_tables_clean(self, tmp_path):
        write_tree(tmp_path, {
            "bng_tpu/ops/table.py": self.TABLE_STUB,
            "bng_tpu/control/newmap.py": """\
from bng_tpu.ops.table import HostTable

t = HostTable(64, 2, val_words=8, name="fine")
"""})
        assert run_on(tmp_path, {"gather"}) == []

    def test_device_narrow_array_gather_flagged(self, tmp_path):
        """A fresh jnp array with <8-word literal rows gathered by a
        computed index inside ops/ device code."""
        write_tree(tmp_path, {"bng_tpu/ops/newkernel.py": """\
import jax.numpy as jnp


def kernel(slots):
    scratch = jnp.zeros((1024, 4), dtype=jnp.uint32)
    rows = scratch[slots]          # BNG014: 4-word rows, computed index
    head = scratch[0]              # constant index: not a gather
    window = scratch[2:6]          # slice: not a gather
    wide = jnp.zeros((1024, 8), dtype=jnp.uint32)
    ok = wide[slots]               # 8-word rows: fine
    return rows, head, window, ok
"""})
        found = run_on(tmp_path, {"gather"})
        assert codes_of(found) == {"BNG014"}
        assert len(found) == 1 and found[0].detail == "scratch-rows-4"

    def test_host_numpy_masks_not_flagged(self, tmp_path):
        """HostTable.bulk_insert-style numpy boolean masking is host
        code — it never reaches the TPU gather unit."""
        write_tree(tmp_path, {"bng_tpu/ops/hostside.py": """\
import numpy as np


def place(used, idxs):
    unplaced = np.ones((1024,), dtype=bool)
    take = idxs[unplaced[idxs]]
    unplaced[take] = False
    return unplaced
"""})
        assert run_on(tmp_path, {"gather"}) == []

    def test_missing_fact_source_is_loud(self, tmp_path):
        """ops/table.py present but no HostTable construction anywhere:
        the width facts are unextractable -> BNG990, never silence."""
        write_tree(tmp_path, {
            "bng_tpu/ops/table.py": "WAYS = 4\n"})
        found = run_on(tmp_path, {"gather"})
        assert codes_of(found) == {"BNG990"}


# ---------------------------------------------------------------------------
# the clean corpus + CLI (the acceptance gates)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def repo_report():
    t0 = time.perf_counter()
    report = run_analysis(REPO)
    report._elapsed_wall = time.perf_counter() - t0
    return report


class TestCleanCorpus:
    def test_repo_is_clean_against_baseline(self, repo_report):
        bl = baseline_mod.load()
        new, _accepted, stale = baseline_mod.split(repo_report.findings, bl)
        assert new == [], [f.to_dict() for f in new]
        assert stale == [], stale

    def test_full_scan_under_budget(self, repo_report):
        assert repo_report._elapsed_wall < 30.0, (
            f"analyzer took {repo_report._elapsed_wall:.1f}s")
        assert repo_report.files_scanned > 100  # the scan set, not a subset

    def test_every_pass_ran(self, repo_report):
        assert set(repo_report.passes_run) == {p.name for p in ALL_PASSES}

    def test_code_catalog_complete(self):
        codes = all_codes()
        for c in ("BNG001", "BNG002", "BNG003", "BNG010", "BNG011",
                  "BNG012", "BNG014", "BNG020", "BNG021", "BNG030",
                  "BNG031", "BNG032", "BNG033", "BNG034", "BNG035",
                  "BNG040", "BNG041", "BNG050", "BNG060", "BNG061",
                  "BNG062", "BNG063", "BNG064"):
            assert c in codes, c

    def test_no_jax_import(self):
        """`bng check` must not drag in jax (milliseconds, any box)."""
        out = subprocess.run(
            [sys.executable, "-c",
             "import sys; import bng_tpu.analysis.cli; "
             "sys.exit(1 if 'jax' in sys.modules else 0)"],
            cwd=REPO, capture_output=True)
        assert out.returncode == 0, out.stderr.decode()


class TestCLI:
    def test_module_entry_clean_repo_rc0(self):
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_planted_tree_rc1_and_json(self, tmp_path):
        write_tree(tmp_path, {"bng_tpu/control/foo.py": """\
def f(x):
    try:
        x()
    except Exception:
        pass
"""})
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--no-baseline", "--json",
             "--select", "handler-audit"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 1
        doc = json.loads(out.stdout)
        assert [f["code"] for f in doc["findings"]] == ["BNG020"]

    def test_bng_check_subcommand(self, capsys):
        from bng_tpu import cli as bng_cli

        rc = bng_cli.main(["check", "--codes"])
        assert rc == 0
        assert "BNG001" in capsys.readouterr().out

    def test_select_filter(self):
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--select",
             "handler-audit", "--json", "--no-baseline"],
            cwd=REPO, capture_output=True, text=True)
        doc = json.loads(out.stdout)
        assert doc["passes"] == ["handler-audit"]

    def test_selective_update_preserves_other_passes(self, tmp_path):
        # `--select hotpath --update-baseline` must NOT wipe baseline
        # entries belonging to passes that did not run
        write_tree(tmp_path, {"bng_tpu/control/foo.py": "x = 1\n"})
        bl = tmp_path / "bl.json"
        baseline_mod.write([
            # unselected pass's code, scanned file
            Finding(code="BNG020", path="bng_tpu/control/foo.py", line=3,
                    message="m", scope="f", detail="d"),
            # selected pass's code, UNscanned file
            Finding(code="BNG001", path="bng_tpu/runtime/other.py", line=9,
                    message="m", scope="g", detail="e"),
        ], bl)
        d = json.loads(bl.read_text())
        for e in d["findings"]:
            e["justification"] = "hand-written reason"
        bl.write_text(json.dumps(d))
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--baseline", str(bl),
             "--select", "hotpath", "--update-baseline"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        kept = json.loads(bl.read_text())["findings"]
        assert [(e["code"], e["justification"]) for e in kept] == [
            ("BNG001", "hand-written reason"),
            ("BNG020", "hand-written reason")]

    def test_update_with_no_baseline_rejected(self, tmp_path):
        # --no-baseline discards justifications; combined with
        # --update-baseline it would rewrite the file with TODO tags
        write_tree(tmp_path, {"bng_tpu/control/foo.py": "x = 1\n"})
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--no-baseline",
             "--update-baseline"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 2
        assert "mutually exclusive" in out.stderr

    # baseline.py's documented contract: "CI should reject a TODO tag"
    # — enforced by the driver, not just promised by the docstring
    _TODO_TREE = {"bng_tpu/control/foo.py": """\
def f(x):
    try:
        x()
    except Exception:
        pass
"""}

    def _check(self, tmp_path, bl, *extra):
        return subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--baseline", str(bl),
             "--select", "handler-audit", *extra],
            cwd=REPO, capture_output=True, text=True)

    def test_todo_tagged_baseline_fails_rc1(self, tmp_path):
        """The --update-baseline -> review -> justify flow: a freshly
        stamped entry fails `bng check` (rc=1, named) until a human
        replaces the TODO tag with a reason; then it passes."""
        write_tree(tmp_path, self._TODO_TREE)
        bl = tmp_path / "bl.json"
        out = self._check(tmp_path, bl, "--update-baseline")
        assert out.returncode == 0, out.stdout + out.stderr
        # the new entry is TODO-tagged -> the very next check fails
        out = self._check(tmp_path, bl)
        assert out.returncode == 1
        assert baseline_mod.TODO_TAG in out.stdout
        # a written justification makes the same baseline pass
        d = json.loads(bl.read_text())
        d["findings"][0]["justification"] = "reviewed: fixture swallow"
        bl.write_text(json.dumps(d))
        out = self._check(tmp_path, bl)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_todo_entries_in_json_report(self, tmp_path):
        write_tree(tmp_path, self._TODO_TREE)
        bl = tmp_path / "bl.json"
        assert self._check(tmp_path, bl, "--update-baseline").returncode == 0
        out = self._check(tmp_path, bl, "--json")
        assert out.returncode == 1
        doc = json.loads(out.stdout)
        assert len(doc["todo_baseline_entries"]) == 1
        assert doc["todo_baseline_entries"][0][0] == "BNG020"

    def test_todo_entry_out_of_scope_spares_selective_runs(self, tmp_path):
        """A TODO-tagged entry only fails runs that could re-verify it:
        a --select whose passes can't emit the entry's code, or a path
        scope that doesn't include the entry's file, must stay green —
        the same scope rule --update-baseline uses to preserve
        out-of-scope entries (which a narrow run can't re-stamp either,
        so failing on them would be permanently red)."""
        tree = dict(self._TODO_TREE)
        tree["bng_tpu/control/bar.py"] = "X = 1\n"
        write_tree(tmp_path, tree)
        bl = tmp_path / "bl.json"
        assert self._check(tmp_path, bl, "--update-baseline").returncode == 0
        # same pass, same paths: the debt is in scope -> red
        assert self._check(tmp_path, bl).returncode == 1
        # a pass set that can't emit BNG020 -> out of scope -> green
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path), str(tmp_path), "--baseline", str(bl),
             "--select", "hotpath"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
        # same pass, but the entry's file is outside the scanned paths
        out = subprocess.run(
            [sys.executable, "-m", "bng_tpu.analysis", "--root",
             str(tmp_path),
             str(tmp_path / "bng_tpu" / "control" / "bar.py"),
             "--baseline", str(bl), "--select", "handler-audit"],
            cwd=REPO, capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
