"""Seeded structured fuzzing of every parser boundary.

Role parity: pkg/dhcp/fuzz_test.go (FuzzParseDHCP / FuzzParseOptions /
FuzzBuildResponse). Strategy: start from VALID packets, apply seeded
byte-level mutations (truncation, bit flips, length-field lies, random
tails), and assert the contract every parser must keep:

  host codecs   — return a value or raise ValueError/IndexError-class
                  errors; never hang, never raise unexpected types,
                  never read past the buffer (bytes slicing guarantees
                  the last, the test pins the first two)
  device kernel — NEVER raises and NEVER produces out-of-bounds state:
                  any byte soup must come back with valid verdicts and
                  in-range lengths (the eBPF-verifier-memory-safety
                  analog for the TPU pipeline)

Deterministic seeds: failures reproduce byte-for-byte.
"""

import numpy as np
import pytest

from bng_tpu.control import dhcp_codec, packets
from bng_tpu.control.pppoe import codec as pppoe_codec
from bng_tpu.control.radius.packet import RadiusPacket
from bng_tpu.utils.net import ip_to_u32

# errors a codec may raise on garbage — anything else is a bug
OK_ERRORS = (ValueError, IndexError, KeyError, struct_err := __import__("struct").error)

N_CASES = 400


def mutations(rng: np.random.Generator, base: bytes):
    """Yield seeded mutants of one valid packet."""
    b = bytearray(base)
    for _ in range(N_CASES):
        kind = rng.integers(0, 5)
        m = bytearray(b)
        if kind == 0:  # truncate anywhere
            m = m[: int(rng.integers(0, len(m) + 1))]
        elif kind == 1:  # flip 1-8 random bytes
            for _ in range(int(rng.integers(1, 9))):
                if m:
                    m[int(rng.integers(len(m)))] = int(rng.integers(256))
        elif kind == 2:  # lie in a length-ish field
            if len(m) > 4:
                pos = int(rng.integers(len(m) - 2))
                m[pos] = 0xFF
                m[pos + 1] = int(rng.integers(256))
        elif kind == 3:  # random tail
            m += bytes(rng.integers(0, 256, size=int(rng.integers(1, 64)),
                                    dtype=np.uint8))
        else:  # pure noise, sized like the original
            m = bytearray(rng.integers(0, 256, size=len(m),
                                       dtype=np.uint8).tobytes())
        yield bytes(m)


class TestDHCPCodecFuzz:
    def test_decode_never_crashes(self):
        rng = np.random.default_rng(0xD0)
        p = dhcp_codec.build_request(b"\x02\xaa\x00\x00\x00\x01",
                                     dhcp_codec.DISCOVER, xid=0x1234)
        p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6])))
        base = p.encode()
        for mut in mutations(rng, base):
            try:
                out = dhcp_codec.decode(mut)
                assert out is not None
            except OK_ERRORS:
                pass

    def test_option_length_lies(self):
        """Options whose length byte points past the buffer must not OOB."""
        rng = np.random.default_rng(0xD1)
        p = dhcp_codec.build_request(b"\x02\xaa\x00\x00\x00\x02",
                                     dhcp_codec.REQUEST, xid=1)
        base = bytearray(p.encode())
        # find the options region (after the 240-byte fixed header + cookie)
        for _ in range(N_CASES):
            m = bytearray(base)
            pos = 240 + int(rng.integers(0, max(1, len(m) - 242)))
            m[pos] = int(rng.integers(1, 255))  # option code
            if pos + 1 < len(m):
                m[pos + 1] = 0xFF  # length far beyond the buffer
            try:
                dhcp_codec.decode(bytes(m))
            except OK_ERRORS:
                pass


class TestRadiusFuzz:
    def test_decode_never_crashes(self):
        rng = np.random.default_rng(0x5A)
        pkt = RadiusPacket(code=1, pid=7, authenticator=bytes(range(16)))
        pkt.add(1, b"alice")
        pkt.add(2, b"secretpw12345678")
        base = pkt.encode()
        for mut in mutations(rng, base):
            try:
                RadiusPacket.decode(mut)
            except OK_ERRORS:
                pass

    def test_attr_zero_length_terminates(self):
        """A 0-length attribute must not loop forever (classic parser DoS)."""
        pkt = RadiusPacket(code=1, pid=1, authenticator=bytes(16))
        raw = bytearray(pkt.encode())
        raw += bytes([1, 0, 65, 65])  # attr type 1, len 0 (invalid), junk
        raw[2:4] = len(raw).to_bytes(2, "big")
        try:
            RadiusPacket.decode(bytes(raw))
        except OK_ERRORS:
            pass  # rejecting is fine; hanging is the failure mode


class TestPPPoEFuzz:
    def test_discovery_and_cp_never_crash(self):
        rng = np.random.default_rng(0x99)
        disc = pppoe_codec.PPPoEPacket(
            code=pppoe_codec.CODE_PADI, session_id=0,
            payload=pppoe_codec.serialize_tags(
                [pppoe_codec.Tag(pppoe_codec.TAG_SERVICE_NAME, b"svc")]))
        lcp = pppoe_codec.CPPacket(code=1, identifier=3, options=[
            pppoe_codec.CPOption(1, b"\x05\xdc"), pppoe_codec.CPOption(5, b"\x00" * 4)])
        for base in (disc.encode(), lcp.encode()):
            for mut in mutations(rng, base):
                for parser in (pppoe_codec.PPPoEPacket.decode,
                               pppoe_codec.CPPacket.decode,
                               pppoe_codec.parse_tags,
                               pppoe_codec.parse_ppp):
                    try:
                        parser(mut)
                    except OK_ERRORS:
                        pass


class TestDeviceKernelFuzz:
    """The fused pipeline is the eBPF program analog: arbitrary wire bytes
    must never crash it or produce out-of-range outputs."""

    @pytest.fixture(scope="class")
    def engine(self):
        from bng_tpu.control.nat import NATManager
        from bng_tpu.runtime.engine import AntispoofTables, Engine, QoSTables
        from bng_tpu.runtime.tables import FastPathTables

        fp = FastPathTables(sub_nbuckets=256, vlan_nbuckets=64,
                            cid_nbuckets=64, max_pools=4)
        fp.set_server_config(bytes.fromhex("02aabbccdd01"), ip_to_u32("10.0.0.1"))
        fp.add_pool(1, ip_to_u32("10.0.0.0"), 24, ip_to_u32("10.0.0.1"),
                    ip_to_u32("1.1.1.1"), ip_to_u32("8.8.8.8"), 3600)
        fp.add_subscriber(bytes.fromhex("02deadbeef42"), pool_id=1,
                          ip=ip_to_u32("10.0.0.123"),
                          lease_expiry=2_000_000_000)
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.9")],
                         sub_nat_nbuckets=256)
        qos = QoSTables(nbuckets=64)
        qos.set_subscriber(ip_to_u32("10.0.0.123"), down_bps=1_000_000,
                           up_bps=1_000_000)
        return Engine(fp, nat, qos=qos,
                      antispoof=AntispoofTables(nbuckets=64),
                      batch_size=32, clock=lambda: 1_700_000_000.0)

    def _run(self, engine, frames):
        out = engine.process(frames, from_access=True)
        # contract: every lane lands in exactly one verdict bucket
        lanes = (len(out["tx"]) + len(out["fwd"]) + len(out["dropped"])
                 + len(out["slow"]))
        assert lanes == len(frames)
        # TX replies must be real frames (length-bounded, decodable L2)
        for _, f in out["tx"]:
            assert 14 <= len(f) <= engine.L

    def test_random_noise_frames(self, engine):
        rng = np.random.default_rng(0xF0)
        for _ in range(20):
            frames = [rng.integers(0, 256,
                                   size=int(rng.integers(1, engine.L)),
                                   dtype=np.uint8).tobytes()
                      for _ in range(8)]
            self._run(engine, frames)

    def test_mutated_dhcp_frames(self, engine):
        rng = np.random.default_rng(0xF1)
        p = dhcp_codec.build_request(bytes.fromhex("02deadbeef42"),
                                     dhcp_codec.DISCOVER, xid=7)
        base = packets.udp_packet(bytes.fromhex("02deadbeef42"), b"\xff" * 6,
                                  0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))
        muts = list(mutations(rng, base))
        for i in range(0, len(muts), 8):
            batch = [m[: engine.L] for m in muts[i : i + 8] if m]
            if batch:
                self._run(engine, batch)

    def test_mutated_udp_lengths(self, engine):
        """IP/UDP headers whose length fields lie about the payload."""
        rng = np.random.default_rng(0xF2)
        base = bytearray(packets.udp_packet(
            bytes.fromhex("02deadbeef42"), b"\x04" * 6,
            ip_to_u32("10.0.0.123"), ip_to_u32("8.8.8.8"), 40000, 443,
            b"d" * 64))
        for _ in range(N_CASES // 4):
            m = bytearray(base)
            # corrupt IP total length / UDP length fields specifically
            m[16] = int(rng.integers(256)); m[17] = int(rng.integers(256))
            m[38] = int(rng.integers(256)); m[39] = int(rng.integers(256))
            self._run(engine, [bytes(m)])


class TestRingClassifierFuzz:
    """The ring-side DHCP classifier parses untrusted wire bytes in C++ —
    byte soup and truncation-boundary frames must never crash either
    backend, and the C++/Python classifiers must agree bit-for-bit on
    every input (the fast-lane routing depends on that parity)."""

    def test_byte_soup_parity_and_no_crash(self):
        import numpy as np

        from bng_tpu.runtime.ring import (
            FLAG_DHCP_CTRL, NativeRing, PyRing, classify_dhcp, load_native,
        )

        rng = np.random.default_rng(0xF0F0)
        frames = []
        # pure noise at classifier-relevant lengths (header boundaries)
        for ln in [0, 1, 13, 14, 17, 18, 21, 22, 33, 34, 41, 42, 60, 100,
                   285, 286, 287, 288, 300, 512]:
            frames.append(bytes(rng.integers(0, 256, size=ln, dtype=np.uint8)))
        # near-DHCP frames: start from a valid one, corrupt one byte at a time
        from bng_tpu.control import dhcp_codec, packets

        mac = bytes.fromhex("02c0ffee0055")
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
        good = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))
        for pos in rng.integers(0, len(good), size=64):
            b = bytearray(good)
            b[pos] ^= 0xFF
            frames.append(bytes(b))
        # truncations of the valid frame across every parse boundary
        for cut in [13, 14, 33, 34, 41, 42, 275, 281, 282, 283, 284]:
            frames.append(good[:cut])

        backends = [PyRing]
        if load_native() is not None:
            backends.append(NativeRing)
        for cls in backends:
            ring = cls(nframes=256, frame_size=1024, depth=256)
            pushed = []
            for f in frames:
                if ring.rx_push(f, from_access=True):
                    pushed.append(f)
            B = len(pushed)
            pkt = np.zeros((max(B, 1), 1024), dtype=np.uint8)
            ln = np.zeros((max(B, 1),), dtype=np.uint32)
            fl = np.zeros((max(B, 1),), dtype=np.uint32)
            n = ring.assemble(pkt, ln, fl)
            assert n == B
            for i, f in enumerate(pushed):
                assert (fl[i] & FLAG_DHCP_CTRL) == classify_dhcp(f), \
                    f"{cls.__name__} classifier disagrees on frame {i}"
            if n:
                ring.complete(np.zeros((n,), dtype=np.uint8), pkt, ln, n)
            ring.close()


class TestCodecCacheInvalidation:
    """ADVICE r3: options_raw must never serve stale bytes after an
    in-place option REPLACEMENT (same count, different value)."""

    def test_replace_in_place_invalidates_raw_cache(self):
        p = dhcp_codec.DHCPPacket(op=2, xid=1, chaddr=b"\x02" * 6)
        p.options = [(dhcp_codec.OPT_MSG_TYPE, bytes([dhcp_codec.OFFER])),
                     (dhcp_codec.OPT_LEASE_TIME, (86400).to_bytes(4, "big"))]
        p.set_options_raw(dhcp_codec.encode_options(p.options))
        before = p.encode()
        # same option count, new value: the old count-based check missed this
        p.options[1] = (dhcp_codec.OPT_LEASE_TIME, (60).to_bytes(4, "big"))
        after = p.encode()
        assert after != before
        assert after == dhcp_codec.decode(after).encode()
        assert dhcp_codec.decode(after).opt(dhcp_codec.OPT_LEASE_TIME) == (60).to_bytes(4, "big")

    def test_unmutated_uses_raw_bytes_verbatim(self):
        p = dhcp_codec.DHCPPacket(op=2, xid=1, chaddr=b"\x02" * 6)
        p.options = [(dhcp_codec.OPT_MSG_TYPE, bytes([dhcp_codec.ACK]))]
        sentinel = dhcp_codec.encode_options(p.options) + b"\x00\x00"  # pad tail
        p.set_options_raw(sentinel)
        assert p.encode().endswith(sentinel)


class TestChecksum16Fold:
    """ADVICE r3: the mod-0xFFFF reduction must match the word-sum fold,
    including the nonzero-multiple-of-0xFFFF edge."""

    def _ref(self, data: bytes) -> int:
        if len(data) % 2:
            data += b"\x00"
        s = sum(int.from_bytes(data[i:i + 2], "big") for i in range(0, len(data), 2))
        while s > 0xFFFF:
            s = (s & 0xFFFF) + (s >> 16)
        return (~s) & 0xFFFF

    def test_matches_word_sum_reference(self):
        rng = np.random.default_rng(0xC5)
        from bng_tpu.control.packets import checksum16
        for n in (0, 1, 2, 3, 20, 1499, 65536):
            data = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
            assert checksum16(data) == self._ref(data)

    def test_ffff_multiple_edge(self):
        from bng_tpu.control.packets import checksum16
        assert checksum16(b"") == 0xFFFF
        assert checksum16(b"\xff\xff") == self._ref(b"\xff\xff") == 0
        assert checksum16(b"\xff\xfe\x00\x01") == self._ref(b"\xff\xfe\x00\x01")
