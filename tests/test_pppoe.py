"""PPPoE server tests: discovery, LCP, PAP/CHAP, IPCP, keepalive, teardown.

Mirrors the reference's pkg/pppoe/*_test.go strategy: drive the server
with synthetic client frames end-to-end, no sockets (SURVEY.md §4.6).
"""

import struct

import pytest

from bng_tpu.control.pppoe import codec
from bng_tpu.control.pppoe.auth import LocalVerifier, chap_md5
from bng_tpu.control.pppoe.codec import (
    CODE_PADI,
    CODE_PADO,
    CODE_PADR,
    CODE_PADS,
    CODE_PADT,
    CODE_SESSION,
    CP_CONF_ACK,
    CP_CONF_NAK,
    CP_CONF_REQ,
    CP_ECHO_REP,
    CP_ECHO_REQ,
    ETH_PPPOE_DISCOVERY,
    ETH_PPPOE_SESSION,
    PROTO_CHAP,
    PROTO_IPCP,
    PROTO_LCP,
    PROTO_PAP,
    CPOption,
    CPPacket,
    PPPoEPacket,
    Tag,
    eth_frame,
    find_tag,
    parse_eth,
    parse_ppp,
    parse_tags,
    serialize_tags,
)
from bng_tpu.control.pppoe.ipcp import OPT_IP_ADDRESS, OPT_PRIMARY_DNS
from bng_tpu.control.pppoe.lcp import OPT_MAGIC, OPT_MRU
from bng_tpu.control.pppoe.server import PPPoEServer, PPPoEServerConfig
from bng_tpu.control.pppoe.session import Phase, TerminateCause

CLIENT_MAC = b"\x02\xcc\x00\x00\x00\x07"


def mkserver(auth=PROTO_CHAP, **kw):
    cfg = PPPoEServerConfig(auth_proto=auth, our_ip=0x0A000001,
                            dns_primary=0x01010101, echo_interval_s=30.0,
                            **kw)
    verifier = LocalVerifier({"alice": b"secret123"})
    allocs = {}

    def allocate_ip(username, mac):
        ip = 0x0A000064 + len(allocs)
        allocs[mac] = ip
        return ip

    events = {"open": [], "close": []}
    srv = PPPoEServer(cfg, verifier, allocate_ip,
                      on_open=lambda s: events["open"].append(s),
                      on_close=lambda e: events["close"].append(e),
                      magic_source=lambda: 0xDEADBEEF,
                      challenge_source=lambda: b"C" * 16)
    return srv, events


class SimClient:
    """Minimal client side of PPPoE+PPP for driving the server."""

    def __init__(self, srv, mac=CLIENT_MAC):
        self.srv = srv
        self.mac = mac
        self.session_id = 0
        self.magic = 0x12345678
        self.lcp_acked = False  # server acked our conf-req
        self.lcp_ack_sent = False  # we acked the server's conf-req
        self.ip = 0
        self.dns = 0
        self.ipcp_done = False
        self.rx_discovery = []
        self.username = "alice"
        self.password = b"secret123"

    def _pump(self, frames, now):
        """Feed frames to the server, process replies until quiescent."""
        pending = list(frames)
        while pending:
            frame = pending.pop(0)
            for out in self.srv.handle_frame(frame, now):
                pending.extend(self._react(out, now))

    def _react(self, frame, now) -> list[bytes]:
        dst, src, etype, payload = parse_eth(frame)
        pkt = PPPoEPacket.decode(payload)
        if etype == ETH_PPPOE_DISCOVERY:
            self.rx_discovery.append(pkt)
            if pkt.code == CODE_PADO:
                tags = parse_tags(pkt.payload)
                cookie = find_tag(tags, codec.TAG_AC_COOKIE)
                out = [Tag(codec.TAG_SERVICE_NAME, b"")]
                if cookie:
                    out.append(cookie)
                padr = PPPoEPacket(CODE_PADR, 0, serialize_tags(out))
                return [eth_frame(src, self.mac, ETH_PPPOE_DISCOVERY,
                                  padr.encode())]
            if pkt.code == CODE_PADS and pkt.session_id:
                self.session_id = pkt.session_id
                # kick off our LCP conf-req
                req = CPPacket(CP_CONF_REQ, 1, options=[
                    CPOption(OPT_MRU, struct.pack(">H", 1492)),
                    CPOption(OPT_MAGIC, struct.pack(">I", self.magic))])
                return [self._ppp(PROTO_LCP, req.encode())]
            return []
        # session frames
        proto, body = parse_ppp(pkt.payload)
        if proto == PROTO_LCP:
            return self._lcp(body, now)
        if proto == PROTO_CHAP:
            return self._chap(body)
        if proto == PROTO_PAP:
            return []  # ack/nak — nothing to send
        if proto == PROTO_IPCP:
            return self._ipcp(body)
        return []

    def _ppp(self, proto, body) -> bytes:
        pkt = PPPoEPacket(CODE_SESSION, self.session_id,
                          codec.ppp_frame(proto, body))
        return eth_frame(self.srv.config.server_mac, self.mac,
                         ETH_PPPOE_SESSION, pkt.encode())

    def _lcp(self, body, now) -> list[bytes]:
        cp = CPPacket.decode(body)
        if cp.code == CP_CONF_REQ:
            self.lcp_ack_sent = True
            return [self._ppp(PROTO_LCP,
                              CPPacket(CP_CONF_ACK, cp.identifier,
                                       options=cp.options).encode())]
        if cp.code == CP_CONF_ACK:
            self.lcp_acked = True
            return []
        if cp.code == CP_ECHO_REQ:
            return [self._ppp(PROTO_LCP,
                              CPPacket(CP_ECHO_REP, cp.identifier,
                                       data=struct.pack(">I", self.magic)).encode())]
        return []

    def _chap(self, body) -> list[bytes]:
        code, ident = body[0], body[1]
        if code != 1:  # not a challenge
            return []
        length = struct.unpack(">H", body[2:4])[0]
        p = body[4:length]
        vlen = p[0]
        challenge = p[1 : 1 + vlen]
        resp = chap_md5(ident, self.password, challenge)
        out = bytes([len(resp)]) + resp + self.username.encode()
        pkt = struct.pack(">BBH", 2, ident, 4 + len(out)) + out
        return [self._ppp(PROTO_CHAP, pkt)]

    def pap_request(self) -> bytes:
        u, pw = self.username.encode(), self.password
        p = bytes([len(u)]) + u + bytes([len(pw)]) + pw
        pkt = struct.pack(">BBH", 1, 7, 4 + len(p)) + p
        return self._ppp(PROTO_PAP, pkt)

    def _ipcp(self, body) -> list[bytes]:
        cp = CPPacket.decode(body)
        if cp.code == CP_CONF_REQ:
            # ack the server's address
            return [self._ppp(PROTO_IPCP,
                              CPPacket(CP_CONF_ACK, cp.identifier,
                                       options=cp.options).encode())]
        if cp.code == CP_CONF_NAK:
            for o in cp.options:
                if o.type == OPT_IP_ADDRESS:
                    self.ip = struct.unpack(">I", o.data)[0]
                if o.type == OPT_PRIMARY_DNS:
                    self.dns = struct.unpack(">I", o.data)[0]
            opts = [CPOption(OPT_IP_ADDRESS, struct.pack(">I", self.ip))]
            if self.dns:
                opts.append(CPOption(OPT_PRIMARY_DNS, struct.pack(">I", self.dns)))
            return [self._ppp(PROTO_IPCP,
                              CPPacket(CP_CONF_REQ, 2, options=opts).encode())]
        if cp.code == CP_CONF_ACK:
            self.ipcp_done = True
            return []
        return []

    def connect(self, now=1000.0):
        padi = PPPoEPacket(CODE_PADI, 0, serialize_tags(
            [Tag(codec.TAG_SERVICE_NAME, b""),
             Tag(codec.TAG_HOST_UNIQ, b"HU01")]))
        self._pump([eth_frame(b"\xff" * 6, self.mac, ETH_PPPOE_DISCOVERY,
                              padi.encode())], now)
        # IPCP with 0.0.0.0 → expect NAK with assigned address
        if self.session_id and not self.ipcp_done:
            opts = [CPOption(OPT_IP_ADDRESS, b"\x00" * 4),
                    CPOption(OPT_PRIMARY_DNS, b"\x00" * 4)]
            self._pump([self._ppp(PROTO_IPCP,
                                  CPPacket(CP_CONF_REQ, 1, options=opts).encode())],
                       now)


def test_full_chap_session():
    srv, events = mkserver(auth=PROTO_CHAP)
    cli = SimClient(srv)
    cli.connect()
    assert cli.session_id != 0
    assert cli.lcp_acked and cli.lcp_ack_sent
    assert cli.ipcp_done
    assert cli.ip == 0x0A000064
    assert cli.dns == 0x01010101
    assert len(events["open"]) == 1
    sess = events["open"][0]
    assert sess.username == "alice"
    assert sess.assigned_ip == 0x0A000064
    assert sess.phase == Phase.OPEN
    assert srv.stats.auth_success == 1


def test_full_pap_session():
    srv, events = mkserver(auth=codec.PROTO_PAP)
    cli = SimClient(srv)
    cli.connect()
    assert cli.session_id != 0
    # PAP: client sends auth-request itself after LCP
    cli._pump([cli.pap_request()], 1001.0)
    opts = [CPOption(OPT_IP_ADDRESS, b"\x00" * 4)]
    cli._pump([cli._ppp(PROTO_IPCP,
                        CPPacket(CP_CONF_REQ, 1, options=opts).encode())], 1001.0)
    assert cli.ipcp_done
    assert len(events["open"]) == 1
    assert srv.stats.auth_success == 1


def test_chap_bad_password_terminates():
    srv, events = mkserver(auth=PROTO_CHAP)
    cli = SimClient(srv)
    cli.password = b"wrong"
    cli.connect()
    assert srv.stats.auth_failure == 1
    assert len(events["open"]) == 0
    # session got torn down
    assert len(srv.sessions) == 0


def test_bad_cookie_rejected():
    srv, _ = mkserver()
    padr = PPPoEPacket(CODE_PADR, 0, serialize_tags(
        [Tag(codec.TAG_AC_COOKIE, b"X" * 16)]))
    out = srv.handle_frame(eth_frame(srv.config.server_mac, CLIENT_MAC,
                                     ETH_PPPOE_DISCOVERY, padr.encode()), 0.0)
    assert len(out) == 1
    pkt = PPPoEPacket.decode(parse_eth(out[0])[3])
    assert pkt.code == CODE_PADS and pkt.session_id == 0
    tags = parse_tags(pkt.payload)
    assert find_tag(tags, codec.TAG_GENERIC_ERR) is not None


def test_keepalive_and_carrier_loss():
    srv, events = mkserver()
    cli = SimClient(srv)
    cli.connect(now=1000.0)
    assert len(events["open"]) == 1
    # tick past echo interval: server emits echo-request
    frames = srv.tick(1031.0)
    echo = []
    for f in frames:
        if parse_eth(f)[2] != ETH_PPPOE_SESSION:
            continue
        proto, body = parse_ppp(PPPoEPacket.decode(parse_eth(f)[3]).payload)
        if proto == PROTO_LCP and body[0] == CP_ECHO_REQ:
            echo.append(f)
    assert len(echo) == 1
    # client never answers: after max_missed echoes the session dies
    for i in range(2, 6):
        srv.tick(1000.0 + 31.0 * i)
    assert len(events["close"]) == 1
    assert events["close"][0].cause == TerminateCause.LOST_CARRIER


def test_echo_reply_keeps_session():
    srv, events = mkserver()
    cli = SimClient(srv)
    cli.connect(now=1000.0)
    for i in range(1, 10):
        now = 1000.0 + 31.0 * i
        for f in srv.tick(now):
            _, _, etype, payload = parse_eth(f)
            if etype == ETH_PPPOE_SESSION:
                cli._pump([], now)  # noop
                proto, body = parse_ppp(PPPoEPacket.decode(payload).payload)
                if proto == PROTO_LCP and body[0] == CP_ECHO_REQ:
                    cli._pump(cli._lcp(body, now), now)
    assert len(events["close"]) == 0
    assert len(srv.sessions) == 1


def test_padt_teardown_releases_ip():
    released = []
    srv, events = mkserver()
    srv.release_ip = lambda ip, mac: released.append((ip, mac))
    cli = SimClient(srv)
    cli.connect()
    padt = PPPoEPacket(CODE_PADT, cli.session_id, b"")
    srv.handle_frame(eth_frame(srv.config.server_mac, CLIENT_MAC,
                               ETH_PPPOE_DISCOVERY, padt.encode()), 2000.0)
    assert len(events["close"]) == 1
    ev = events["close"][0]
    assert ev.cause == TerminateCause.USER_REQUEST
    assert ev.session_time_s == pytest.approx(1000.0)
    assert released == [(0x0A000064, CLIENT_MAC)]


def test_admin_terminate():
    srv, events = mkserver()
    cli = SimClient(srv)
    cli.connect()
    frames = srv.terminate(cli.session_id, TerminateCause.ADMIN_RESET, 1500.0)
    # LCP Term-Req + PADT
    codes = []
    for f in frames:
        _, _, etype, payload = parse_eth(f)
        pkt = PPPoEPacket.decode(payload)
        if etype == ETH_PPPOE_DISCOVERY:
            codes.append(pkt.code)
    assert CODE_PADT in codes
    assert events["close"][0].cause == TerminateCause.ADMIN_RESET
    assert len(srv.sessions) == 0


def test_session_limit():
    srv, _ = mkserver(max_sessions=2)
    for i in range(3):
        mac = bytes([2, 0, 0, 0, 0, 10 + i])
        cli = SimClient(srv, mac=mac)
        cli.connect()
    assert len(srv.sessions) == 2


def test_rate_limit_on_auth():
    srv, _ = mkserver(auth=PROTO_CHAP)
    # same MAC hammering bad passwords
    for i in range(7):
        cli = SimClient(srv)
        cli.password = b"wrong"
        cli.connect(now=1000.0 + i)
    assert srv.stats.auth_failure >= 6
    # 6th+ attempts hit the limiter (5/min) — reason is rate limited, still a failure
    # now a correct attempt inside the window also fails (limiter)
    cli = SimClient(srv)
    cli.connect(now=1005.0)
    assert len(srv.sessions) == 0


def test_unknown_session_gets_padt():
    srv, _ = mkserver()
    pkt = PPPoEPacket(CODE_SESSION, 999, codec.ppp_frame(PROTO_LCP, b"\x09\x01\x00\x04"))
    out = srv.handle_frame(eth_frame(srv.config.server_mac, CLIENT_MAC,
                                     ETH_PPPOE_SESSION, pkt.encode()), 0.0)
    assert len(out) == 1
    reply = PPPoEPacket.decode(parse_eth(out[0])[3])
    assert reply.code == CODE_PADT


def test_codec_roundtrip():
    tags = [Tag(codec.TAG_SERVICE_NAME, b"svc"), Tag(codec.TAG_HOST_UNIQ, b"\x01\x02")]
    data = serialize_tags(tags)
    back = parse_tags(data)
    assert [(t.type, t.value) for t in back] == [(t.type, t.value) for t in tags]
    cp = CPPacket(CP_CONF_REQ, 7, options=[CPOption(1, b"\x05\xd4"),
                                           CPOption(5, b"\x11\x22\x33\x44")])
    back = CPPacket.decode(cp.encode())
    assert back.code == CP_CONF_REQ and back.identifier == 7
    assert [(o.type, o.data) for o in back.options] == \
        [(o.type, o.data) for o in cp.options]


def test_cp_packet_bad_length():
    with pytest.raises(ValueError):
        CPPacket.decode(b"\x01\x01\x00\x02")  # length < 4
    with pytest.raises(ValueError):
        PPPoEPacket.decode(b"\x11\x09\x00\x00\x00\xff")  # length > frame


def test_vlan_tagged_discovery_mirrored():
    """Tagged PADI gets a tagged PADO back (QinQ access lines)."""
    import struct as _s

    srv, events = mkserver()
    padi = PPPoEPacket(CODE_PADI, 0, serialize_tags(
        [Tag(codec.TAG_SERVICE_NAME, b"")]))
    # S-tag 100 (802.1ad) + C-tag 42 (802.1Q)
    frame = (b"\xff" * 6 + CLIENT_MAC + _s.pack(">HH", 0x88A8, 100)
             + _s.pack(">HH", 0x8100, 42)
             + _s.pack(">H", ETH_PPPOE_DISCOVERY) + padi.encode())
    out = srv.handle_frame(frame, 0.0)
    assert len(out) == 1
    reply = out[0]
    assert _s.unpack(">H", reply[12:14])[0] == 0x88A8
    assert _s.unpack(">H", reply[14:16])[0] == 100
    assert _s.unpack(">H", reply[16:18])[0] == 0x8100
    assert _s.unpack(">H", reply[18:20])[0] == 42
    assert _s.unpack(">H", reply[20:22])[0] == ETH_PPPOE_DISCOVERY


def test_half_open_sessions_reclaimed():
    """PADR floods that never finish LCP can't pin the session table."""
    srv, events = mkserver()
    for i in range(5):
        mac = bytes([2, 0, 0, 0, 1, i])
        cli = SimClient(srv, mac=mac)
        # only do discovery: PADI->PADO->PADR->PADS, then go silent.
        padi = PPPoEPacket(CODE_PADI, 0, serialize_tags(
            [Tag(codec.TAG_SERVICE_NAME, b"")]))
        frames = srv.handle_frame(
            eth_frame(b"\xff" * 6, mac, ETH_PPPOE_DISCOVERY, padi.encode()), 0.0)
        pado = PPPoEPacket.decode(parse_eth(frames[0])[3])
        cookie = find_tag(parse_tags(pado.payload), codec.TAG_AC_COOKIE)
        padr = PPPoEPacket(CODE_PADR, 0, serialize_tags([cookie]))
        srv.handle_frame(
            eth_frame(srv.config.server_mac, mac, ETH_PPPOE_DISCOVERY,
                      padr.encode()), 0.0)
    assert len(srv.sessions) == 5
    # past setup timeout: all reclaimed, no accounting events fired
    srv.tick(61.0)
    assert len(srv.sessions) == 0
    assert events["close"] == []  # never opened -> no teardown events


def test_redial_releases_old_session():
    """A client re-dialing PADI/PADR tears down its old open session."""
    released = []
    srv, events = mkserver()
    srv.release_ip = lambda ip, mac: released.append(ip)
    cli1 = SimClient(srv)
    cli1.connect()
    assert len(events["open"]) == 1
    old_sid = cli1.session_id
    # same MAC dials again
    cli2 = SimClient(srv)
    cli2.connect()
    assert len(events["close"]) == 1
    assert events["close"][0].session.session_id == old_sid
    assert released == [0x0A000064]
    assert len(srv.sessions) == 1


def test_malformed_auth_frame_does_not_kill_session():
    srv, events = mkserver(auth=PROTO_CHAP)
    cli = SimClient(srv)
    # drive up to AUTH phase but intercept before responding to challenge
    padi = PPPoEPacket(CODE_PADI, 0, serialize_tags(
        [Tag(codec.TAG_SERVICE_NAME, b"")]))
    frames = srv.handle_frame(
        eth_frame(b"\xff" * 6, CLIENT_MAC, ETH_PPPOE_DISCOVERY, padi.encode()), 0.0)
    pado = PPPoEPacket.decode(parse_eth(frames[0])[3])
    cookie = find_tag(parse_tags(pado.payload), codec.TAG_AC_COOKIE)
    padr = PPPoEPacket(CODE_PADR, 0, serialize_tags([cookie]))
    frames = srv.handle_frame(
        eth_frame(srv.config.server_mac, CLIENT_MAC, ETH_PPPOE_DISCOVERY,
                  padr.encode()), 0.0)
    sess = srv.sessions.by_mac(CLIENT_MAC)
    cli.session_id = sess.session_id
    # complete LCP so we are in AUTH
    for f in frames:
        _, _, etype, payload = parse_eth(f)
        if etype == ETH_PPPOE_SESSION:
            proto, body = parse_ppp(PPPoEPacket.decode(payload).payload)
            if proto == PROTO_LCP:
                for rf in cli._lcp(body, 0.0):
                    srv.handle_frame(rf, 0.0)
    req = CPPacket(CP_CONF_REQ, 1, options=[])
    srv.handle_frame(cli._ppp(PROTO_LCP, req.encode()), 0.0)
    assert sess.phase == Phase.AUTH
    # garbage CHAP response: truncated
    srv.handle_frame(cli._ppp(codec.PROTO_CHAP, b"\x02\x01\x00\x04"), 1.0)
    assert srv.sessions.by_mac(CLIENT_MAC) is not None  # session survives
    assert srv.stats.auth_failure == 0


def test_successful_auth_resets_rate_limiter():
    srv, events = mkserver(auth=PROTO_CHAP)
    # 6 successful reconnects in one window: all must succeed
    for i in range(6):
        cli = SimClient(srv)
        cli.connect(now=1000.0 + i * 2)
    assert srv.stats.auth_success == 6
    assert srv.stats.auth_failure == 0
