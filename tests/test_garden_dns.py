"""Tests for walled garden, QinQ, WiFi gateway, and DNS resolver."""

import threading
import time

import pytest

from bng_tpu.control.dns import (
    CLASS_IN, DNSConfig, InterceptAction, InterceptRule, Query, RCODE_NAME_ERROR,
    RCODE_REFUSED, RCODE_SUCCESS, RCODE_SERVER_FAILURE, Record, Resolver,
    Response, TYPE_A, TYPE_AAAA, TYPE_CNAME, cache_key, dns64_synthesize,
)
from bng_tpu.control import packets
from bng_tpu.control.qinq import QinQConfig, QinQMapper, VLANPair, VLANRange
from bng_tpu.control.walledgarden import (
    SubscriberState, WalledGardenConfig, WalledGardenManager,
)
from bng_tpu.control.wifi import (
    OperatingMode, WiFiGatewayManager, WiFiSessionState,
    default_olt_bng_config, default_wifi_config,
)
from bng_tpu.utils.net import ip_to_u32, u32_to_ip


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------- QinQ

class TestQinQ:
    def test_pair_classification(self):
        assert VLANPair(100, 200).is_double_tagged
        assert VLANPair(0, 200).is_single_tagged
        assert VLANPair().is_untagged
        assert str(VLANPair(100, 200)) == "100.200"
        assert str(VLANPair(0, 200)) == "200"

    def test_key_packing_matches_device_layout(self):
        assert VLANPair(0x0064, 0x00C8).key() == 0x006400C8

    def test_register_lookup_roundtrip(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "sub-1")
        assert m.get_subscriber(VLANPair(100, 200)) == "sub-1"
        assert m.get_vlan("sub-1") == VLANPair(100, 200)

    def test_conflicting_registration_rejected(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "sub-1")
        with pytest.raises(ValueError):
            m.register(VLANPair(100, 200), "sub-2")

    def test_reregister_moves_subscriber(self):
        m = QinQMapper()
        m.register(VLANPair(100, 200), "sub-1")
        m.register(VLANPair(100, 201), "sub-1")
        assert m.get_subscriber(VLANPair(100, 200)) is None
        assert m.get_vlan("sub-1") == VLANPair(100, 201)

    def test_range_enforcement(self):
        cfg = QinQConfig(s_tag_range=VLANRange(100, 199))
        m = QinQMapper(cfg)
        with pytest.raises(ValueError):
            m.register(VLANPair(500, 10), "sub-x")

    def test_unregister_subscriber(self):
        m = QinQMapper()
        m.register(VLANPair(7, 8), "s")
        m.unregister_subscriber("s")
        assert m.get_subscriber(VLANPair(7, 8)) is None
        assert m.stats()["total_mappings"] == 0

    def test_invalid_vid_rejected(self):
        with pytest.raises(ValueError):
            VLANPair(5000, 0)

    def test_stag_only_rejected(self):
        m = QinQMapper(QinQConfig(s_tag_range=VLANRange(100, 199),
                                  allow_single_tagged=False))
        with pytest.raises(ValueError):
            m.register(VLANPair(300, 0), "sub-x")


# -------------------------------------------------------- Walled garden

class TestWalledGarden:
    def test_unknown_mac_defaults_to_garden(self):
        m = WalledGardenManager()
        assert m.get_subscriber_state("02:00:00:00:00:01") == SubscriberState.UNKNOWN
        assert m.should_redirect("02:00:00:00:00:01", "93.184.216.34", 80)

    def test_provisioned_bypasses(self):
        m = WalledGardenManager()
        m.release_from_walled_garden("02:00:00:00:00:01")
        assert not m.should_redirect("02:00:00:00:00:01", "93.184.216.34", 80)

    def test_dns_always_allowed(self):
        m = WalledGardenManager()
        m.add_to_walled_garden("02:00:00:00:00:01")
        assert not m.should_redirect("02:00:00:00:00:01", "8.8.8.8", 53, proto=17)

    def test_portal_always_allowed(self):
        m = WalledGardenManager()
        cfg = m.config
        m.add_to_walled_garden("02:00:00:00:00:01")
        assert not m.should_redirect("02:00:00:00:00:01", cfg.portal_ip,
                                     cfg.portal_port, proto=6)

    def test_expiry_reverts_to_unknown(self):
        clk = FakeClock()
        m = WalledGardenManager(clock=clk)
        m.add_to_walled_garden("02:00:00:00:00:01", vlan_id=100)
        assert m.get_subscriber_state("02:00:00:00:00:01") == SubscriberState.WALLED_GARDEN
        clk.advance(m.config.default_timeout + 1)
        assert m.check_expired() == 1
        assert m.get_subscriber_state("02:00:00:00:00:01") == SubscriberState.UNKNOWN

    def test_provisioned_never_expires(self):
        clk = FakeClock()
        m = WalledGardenManager(clock=clk)
        m.release_from_walled_garden("02:00:00:00:00:01")
        clk.advance(1e6)
        assert m.check_expired() == 0
        assert m.get_subscriber_state("02:00:00:00:00:01") == SubscriberState.PROVISIONED

    def test_redirect_callback_and_stats(self):
        m = WalledGardenManager()
        hits = []
        m.on_redirect(lambda mac, ip: hits.append((mac, ip)))
        m.add_to_walled_garden("02:00:00:00:00:01")
        m.should_redirect("02:00:00:00:00:01", "1.2.3.4", 443)
        assert hits == [("02:00:00:00:00:01", "1.2.3.4")]
        assert m.stats()["redirects"] == 1
        assert m.stats()["WALLED_GARDEN"] == 1

    def test_partial_wildcard_destinations(self):
        from bng_tpu.control.walledgarden import AllowedDestination
        cfg = WalledGardenConfig(allowed_destinations=[
            AllowedDestination("1.2.3.4", 443, 0),   # any proto
            AllowedDestination("5.6.7.8", 0, 6),     # any TCP port
        ])
        m = WalledGardenManager(cfg)
        m.add_to_walled_garden("02:00:00:00:00:09")
        assert not m.should_redirect("02:00:00:00:00:09", "1.2.3.4", 443, proto=6)
        assert not m.should_redirect("02:00:00:00:00:09", "1.2.3.4", 443, proto=17)
        assert not m.should_redirect("02:00:00:00:00:09", "5.6.7.8", 8080, proto=6)
        assert m.should_redirect("02:00:00:00:00:09", "5.6.7.8", 8080, proto=17)

    def test_blocked_state(self):
        m = WalledGardenManager()
        m.block_mac("02:00:00:00:00:02")
        assert m.get_subscriber_state("02:00:00:00:00:02") == SubscriberState.BLOCKED
        assert m.should_redirect("02:00:00:00:00:02", "1.2.3.4", 80)


# ----------------------------------------------------------------- WiFi

class TestWiFiGateway:
    def test_mode_defaults(self):
        wifi = default_wifi_config()
        olt = default_olt_bng_config()
        assert wifi.allocation_trigger == "dhcp_discover"
        assert olt.allocation_trigger == "radius_auth"
        assert wifi.captive_portal_enabled and not olt.captive_portal_enabled
        assert olt.mode == OperatingMode.OLT_BNG

    def test_session_starts_in_grace_period(self):
        m = WiFiGatewayManager()
        s = m.create_session("02:aa:bb:cc:dd:01", hostname="phone", ip="10.1.0.5")
        assert s.state == WiFiSessionState.GRACE_PERIOD
        assert m.is_in_grace_period("02:aa:bb:cc:dd:01")
        assert m.needs_authentication("02:aa:bb:cc:dd:01")

    def test_portal_auth_flow(self):
        m = WiFiGatewayManager()
        m.create_session("02:aa:bb:cc:dd:01")
        m.authenticate_session("02:aa:bb:cc:dd:01", "portal", "user@example.com")
        s = m.get_session("02:aa:bb:cc:dd:01")
        assert s.authenticated and s.state == WiFiSessionState.AUTHENTICATED
        assert not m.needs_authentication("02:aa:bb:cc:dd:01")
        m.update_traffic_stats("02:aa:bb:cc:dd:01", 100, 200, 1, 2)
        assert m.get_session("02:aa:bb:cc:dd:01").state == WiFiSessionState.ACTIVE

    def test_olt_mode_skips_portal(self):
        m = WiFiGatewayManager(default_olt_bng_config())
        s = m.create_session("02:aa:bb:cc:dd:02")
        assert s.state == WiFiSessionState.ACTIVE and s.authenticated
        assert not m.needs_authentication("02:aa:bb:cc:dd:02")

    def test_grace_period_timeout_expires_session(self):
        clk = FakeClock()
        m = WiFiGatewayManager(clock=clk)
        m.create_session("02:aa:bb:cc:dd:03")
        clk.advance(m.config.grace_period + 1)
        assert m.expire_sessions() == 1
        assert m.get_session("02:aa:bb:cc:dd:03") is None

    def test_renewal_extends_lease(self):
        clk = FakeClock()
        m = WiFiGatewayManager(clock=clk)
        m.create_session("02:aa:bb:cc:dd:04")
        m.authenticate_session("02:aa:bb:cc:dd:04", "portal", "u")
        clk.advance(m.config.lease_duration - 1)
        m.renew_session("02:aa:bb:cc:dd:04")
        clk.advance(m.config.lease_duration - 1)
        assert m.expire_sessions() == 0

    def test_recreate_updates_ip_index(self):
        m = WiFiGatewayManager()
        m.create_session("02:aa:bb:cc:dd:07")  # DISCOVER, no IP yet
        m.create_session("02:aa:bb:cc:dd:07", ip="10.1.0.7", hostname="tv")
        s = m.get_session_by_ip("10.1.0.7")
        assert s is not None and s.hostname == "tv"

    def test_olt_mode_authenticated_survives_lease_expiry(self):
        clk = FakeClock()
        m = WiFiGatewayManager(default_olt_bng_config(), clock=clk)
        m.create_session("02:aa:bb:cc:dd:08")
        clk.advance(m.config.lease_duration + 1)
        assert m.expire_sessions() == 0  # session-termination mode: RADIUS tears down
        assert m.get_session("02:aa:bb:cc:dd:08") is not None

    def test_by_ip_index(self):
        m = WiFiGatewayManager()
        m.create_session("02:aa:bb:cc:dd:05", ip="10.1.0.9")
        assert m.get_session_by_ip("10.1.0.9").mac == "02:aa:bb:cc:dd:05"
        m.release_session("02:aa:bb:cc:dd:05")
        assert m.get_session_by_ip("10.1.0.9") is None

    def test_stats(self):
        m = WiFiGatewayManager()
        m.create_session("02:aa:bb:cc:dd:06")
        m.authenticate_session("02:aa:bb:cc:dd:06", "portal", "u")
        m.update_traffic_stats("02:aa:bb:cc:dd:06", 10, 20, 1, 1)
        st = m.stats()
        assert st["active_sessions"] == 1
        assert st["authenticated_sessions"] == 1
        assert st["total_bytes_in"] == 10


# ------------------------------------------------------------------ DNS

def _static_forwarder(table):
    def fwd(query):
        key = (query.name.rstrip("."), query.qtype)
        if key in table:
            return Response(query=query, answers=table[key])
        return Response(query=query, rcode=RCODE_NAME_ERROR)
    return fwd


class TestDNSResolver:
    def _resolver(self, table=None, clock=None, **cfg):
        config = DNSConfig(**cfg)
        fwd = _static_forwarder(table or {})
        return Resolver(config, forwarder=fwd, clock=clock or FakeClock())

    def test_forward_and_cache(self):
        clk = FakeClock()
        table = {("example.com", TYPE_A):
                 [Record(name="example.com", rtype=TYPE_A, ttl=120, ipv4="93.184.216.34")]}
        r = self._resolver(table, clock=clk)
        resp = r.resolve(Query(name="example.com", source="10.0.0.5"))
        assert resp.rcode == RCODE_SUCCESS and not resp.cached
        resp2 = r.resolve(Query(name="example.com", source="10.0.0.5"))
        assert resp2.cached and resp2.answers[0].ipv4 == "93.184.216.34"
        assert r.stats()["cache_hits"] == 1

    def test_ttl_clamping(self):
        clk = FakeClock()
        table = {("example.com", TYPE_A):
                 [Record(name="example.com", rtype=TYPE_A, ttl=1, ipv4="1.2.3.4")]}
        r = self._resolver(table, clock=clk, min_ttl=60)
        r.resolve(Query(name="example.com"))
        clk.advance(30)  # raw TTL of 1 would have expired; clamp keeps it
        assert r.resolve(Query(name="example.com")).cached

    def test_negative_cache(self):
        clk = FakeClock()
        r = self._resolver({}, clock=clk)
        assert r.resolve(Query(name="nope.invalid")).rcode == RCODE_NAME_ERROR
        resp = r.resolve(Query(name="nope.invalid"))
        assert resp.rcode == RCODE_NAME_ERROR and resp.cached

    def test_block_rule(self):
        r = self._resolver()
        r.add_intercept_rule(InterceptRule(domain="ads.example.com",
                                           action=InterceptAction.BLOCK))
        resp = r.resolve(Query(name="tracker.ads.example.com"))
        assert resp.rcode == RCODE_NAME_ERROR
        assert r.stats()["intercepted"] == 1

    def test_redirect_rule(self):
        r = self._resolver()
        r.add_intercept_rule(InterceptRule(domain="portal.isp.net",
                                           action=InterceptAction.REDIRECT,
                                           redirect_ip="10.0.0.80"))
        resp = r.resolve(Query(name="portal.isp.net"))
        assert resp.answers[0].ipv4 == "10.0.0.80"

    def test_cname_rule(self):
        r = self._resolver()
        r.add_intercept_rule(InterceptRule(domain="old.example.com", exact=True,
                                           action=InterceptAction.CNAME,
                                           cname="new.example.com"))
        resp = r.resolve(Query(name="old.example.com"))
        assert resp.answers[0].rtype == TYPE_CNAME
        assert resp.answers[0].target == "new.example.com"
        # exact match must not catch subdomains
        assert r.resolve(Query(name="x.old.example.com")).rcode == RCODE_NAME_ERROR

    def test_suffix_rule(self):
        r = self._resolver()
        r.add_intercept_rule(InterceptRule(domain_suffix=".evil.com",
                                           action=InterceptAction.BLOCK))
        assert r.resolve(Query(name="www.evil.com")).rcode == RCODE_NAME_ERROR

    def test_walled_garden_client_redirected(self):
        table = {("example.com", TYPE_A):
                 [Record(name="example.com", rtype=TYPE_A, ttl=60, ipv4="93.184.216.34")]}
        r = self._resolver(table)
        r.add_walled_garden_client("10.0.0.99")
        resp = r.resolve(Query(name="example.com", source="10.0.0.99"))
        assert resp.answers[0].ipv4 == r.config.walled_garden_redirect_ip
        # other clients unaffected
        resp2 = r.resolve(Query(name="example.com", source="10.0.0.5"))
        assert resp2.answers[0].ipv4 == "93.184.216.34"
        # release
        assert r.remove_walled_garden_client("10.0.0.99")
        resp3 = r.resolve(Query(name="example.com", source="10.0.0.99"))
        assert resp3.answers[0].ipv4 == "93.184.216.34"

    def test_dns64_synthesis(self):
        # v4-only domain: AAAA returns NOERROR-empty, A has a record
        def fwd(q):
            if q.qtype == TYPE_A and q.name.rstrip(".") == "v4only.example":
                return Response(query=q, answers=[Record(
                    name="v4only.example", rtype=TYPE_A, ttl=60, ipv4="192.0.2.33")])
            return Response(query=q, rcode=RCODE_SUCCESS)
        r = Resolver(DNSConfig(dns64_enabled=True), forwarder=fwd, clock=FakeClock())
        resp = r.resolve(Query(name="v4only.example", qtype=TYPE_AAAA))
        assert resp.answers[0].rtype == TYPE_AAAA
        assert resp.answers[0].ipv6 == "64:ff9b::c000:221"

    def test_dns64_not_applied_on_nxdomain(self):
        # RFC 6147: synthesize only on NOERROR-empty, never mask NXDOMAIN
        r = self._resolver({}, dns64_enabled=True)
        resp = r.resolve(Query(name="gone.example", qtype=TYPE_AAAA))
        assert resp.rcode == RCODE_NAME_ERROR and not resp.answers

    def test_dns64_helper(self):
        assert dns64_synthesize("64:ff9b::", "192.0.2.33") == "64:ff9b::c000:221"

    def test_rate_limit(self):
        clk = FakeClock()
        r = self._resolver({}, clock=clk, rate_limit_qps=1, rate_limit_burst=2)
        q = lambda: r.resolve(Query(name="x.test", source="10.9.9.9")).rcode
        assert q() != RCODE_REFUSED
        assert q() != RCODE_REFUSED
        assert q() == RCODE_REFUSED  # burst exhausted
        clk.advance(2.0)
        assert q() != RCODE_REFUSED  # refilled
        assert r.stats()["rate_limited"] >= 1

    def test_no_forwarder_is_servfail(self):
        r = Resolver(DNSConfig(), forwarder=None)
        assert r.resolve(Query(name="a.b")).rcode == RCODE_SERVER_FAILURE

    def test_cache_lru_eviction(self):
        clk = FakeClock()
        table = {(f"h{i}.test", TYPE_A):
                 [Record(name=f"h{i}.test", rtype=TYPE_A, ttl=600, ipv4=f"10.0.0.{i}")]
                 for i in range(5)}
        r = self._resolver(table, clock=clk, cache_size=3)
        for i in range(5):
            r.resolve(Query(name=f"h{i}.test"))
        assert r.cache.size() == 3
        assert r.cache.stats()["evictions"] == 2


# ------------------------------------------------------------ DNS wire
class TestDNSWireCodec:
    def test_query_roundtrip(self):
        from bng_tpu.control import dns_wire as w

        q = Query(name="www.example.com", qtype=TYPE_A)
        txid, decoded = w.decode_query(w.encode_query(q, 0x1234))
        assert txid == 0x1234
        assert decoded.name == "www.example.com" and decoded.qtype == TYPE_A

    def test_response_roundtrip_a_aaaa_cname(self):
        from bng_tpu.control import dns_wire as w

        q = Query(name="cdn.example.com", qtype=TYPE_A)
        resp = Response(query=q, answers=[
            Record(name="cdn.example.com", rtype=TYPE_CNAME, ttl=300,
                   target="edge.example.net"),
            Record(name="edge.example.net", rtype=TYPE_A, ttl=60,
                   ipv4="192.0.2.7"),
            Record(name="edge.example.net", rtype=28, ttl=60,
                   ipv6="2001:db8::7"),
        ])
        txid, _q, decoded = w.decode_response(w.encode_response(resp, 7))
        assert txid == 7 and decoded.rcode == RCODE_SUCCESS
        assert decoded.answers[0].target == "edge.example.net"
        assert decoded.answers[1].ipv4 == "192.0.2.7"
        assert decoded.answers[2].ipv6 == "2001:db8::7"

    def test_compression_pointer_parsing(self):
        """Real upstreams compress names; the parser must follow pointers
        with a bounded jump count."""
        import struct
        from bng_tpu.control import dns_wire as w

        # header + question "a.example.com" + answer whose name is a
        # pointer to offset 12 (the question name)
        hdr = struct.pack("!HHHHHH", 1, 0x8180, 1, 1, 0, 0)
        qname = b"\x01a\x07example\x03com\x00"
        question = qname + struct.pack("!HH", TYPE_A, 1)
        answer = b"\xc0\x0c" + struct.pack("!HHIH", TYPE_A, 1, 60, 4) + bytes(
            [192, 0, 2, 9])
        txid, q, resp = w.decode_response(hdr + question + answer)
        assert q.name == "a.example.com"
        assert resp.answers[0].name == "a.example.com"
        assert resp.answers[0].ipv4 == "192.0.2.9"

    def test_compression_loop_bounded(self):
        import struct
        import pytest as _pytest
        from bng_tpu.control import dns_wire as w

        hdr = struct.pack("!HHHHHH", 1, 0x8180, 1, 0, 0, 0)
        # name at offset 12 is a pointer to itself: must raise, not hang
        evil = b"\xc0\x0c" + struct.pack("!HH", TYPE_A, 1)
        with _pytest.raises(w.WireError):
            w.decode_response(hdr + evil)


def _fake_upstream(answers):
    """A real UDP socket answering canned (name, qtype) -> ipv4/None."""
    import socket as s
    import struct
    import threading
    from bng_tpu.control import dns_wire as w

    sock = s.socket(s.AF_INET, s.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(0.2)
    stop = threading.Event()
    seen = []

    def serve():
        while not stop.is_set():
            try:
                data, client = sock.recvfrom(4096)
            except (TimeoutError, s.timeout):
                continue
            except OSError:
                return
            txid, q = w.decode_query(data)
            seen.append(q.name)
            ip = answers.get((q.name, q.qtype))
            if ip is None:
                resp = Response(query=q, rcode=3)  # NXDOMAIN
            else:
                resp = Response(query=q, answers=[
                    Record(name=q.name, rtype=q.qtype, ttl=300, ipv4=ip)])
            sock.sendto(w.encode_response(resp, txid), client)

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    class H:
        addr = f"127.0.0.1:{sock.getsockname()[1]}"

        @staticmethod
        def close():
            stop.set()
            t.join(timeout=1)
            sock.close()

    H.seen = seen
    return H


class TestUDPForwarderAndServer:
    """End-to-end over real sockets: subscriber query -> DNSServer ->
    Resolver -> UDPForwarder -> fake upstream -> answer (VERDICT r3
    item 6 done-criterion)."""

    def test_forwarder_resolves_through_fake_upstream(self):
        from bng_tpu.control.dns_wire import UDPForwarder

        up = _fake_upstream({("www.example.com", TYPE_A): "192.0.2.55"})
        try:
            fwd = UDPForwarder([up.addr], timeout=2.0)
            resp = fwd(Query(name="www.example.com", qtype=TYPE_A))
            assert resp.rcode == RCODE_SUCCESS
            assert resp.answers[0].ipv4 == "192.0.2.55"
            assert fwd.stats["sent"] == 1
        finally:
            up.close()

    def test_forwarder_fails_over_dead_upstream(self):
        from bng_tpu.control.dns_wire import UDPForwarder

        up = _fake_upstream({("x.example.com", TYPE_A): "192.0.2.66"})
        try:
            # first upstream is a blackhole (TEST-NET port): must fail over
            fwd = UDPForwarder(["127.0.0.1:1", up.addr], timeout=0.3)
            resp = fwd(Query(name="x.example.com", qtype=TYPE_A))
            assert resp.answers[0].ipv4 == "192.0.2.66"
            assert fwd.stats["failovers"] == 1
        finally:
            up.close()

    def test_server_full_stack_with_walled_garden(self):
        import socket as s
        from bng_tpu.control.dns import DNSConfig
        from bng_tpu.control import dns_wire as w
        from bng_tpu.control.dns_wire import DNSServer, UDPForwarder

        up = _fake_upstream({("allowed.example.com", TYPE_A): "192.0.2.77"})
        try:
            cfg = DNSConfig(upstreams=[up.addr],
                            walled_garden_redirect_ip="10.255.255.1")
            resolver = Resolver(cfg, forwarder=UDPForwarder([up.addr],
                                                            timeout=2.0))
            srv = DNSServer(resolver, host="127.0.0.1", port=0)
            srv.start()
            try:
                client = s.socket(s.AF_INET, s.SOCK_DGRAM)
                client.settimeout(2.0)
                client.bind(("127.0.0.1", 0))

                def ask(name):
                    q = Query(name=name, qtype=TYPE_A)
                    client.sendto(w.encode_query(q, 0xBEEF),
                                  (srv.addr[0], srv.addr[1]))
                    data, _ = client.recvfrom(4096)
                    txid, _q, resp = w.decode_response(data)
                    assert txid == 0xBEEF
                    return resp

                # normal client forwards upstream
                resp = ask("allowed.example.com")
                assert resp.answers[0].ipv4 == "192.0.2.77"
                # cache hit: upstream sees the name only once
                resp = ask("allowed.example.com")
                assert resp.answers[0].ipv4 == "192.0.2.77"
                assert up.seen.count("allowed.example.com") == 1
                # walled-garden client gets the portal for EVERY name
                resolver.add_walled_garden_client("127.0.0.1")
                resp = ask("anything.else.example.org")
                assert resp.answers[0].ipv4 == "10.255.255.1"
                assert "anything.else.example.org" not in up.seen
                # garbage never kills the listener
                client.sendto(b"\x00\x01junk", (srv.addr[0], srv.addr[1]))
                resolver.remove_walled_garden_client("127.0.0.1")
                resp = ask("allowed.example.com")
                assert resp.answers[0].ipv4 == "192.0.2.77"
                client.close()
            finally:
                srv.stop()
        finally:
            up.close()

    def test_cli_wires_dns_and_garden_sync(self):
        """BNGApp run-wiring: dns_enabled serves a real socket; a garden
        MAC's lease IP lands in the resolver's client set on transition."""
        from bng_tpu.cli import BNGApp, BNGConfig
        from bng_tpu.utils.net import mac_to_u64

        up = _fake_upstream({("ok.example.com", TYPE_A): "192.0.2.88"})
        try:
            app = BNGApp(BNGConfig(dns_enabled=True,
                                   dns_listen="127.0.0.1:0",
                                   dns_upstreams=[up.addr]))
            try:
                dhcp = app.components["dhcp"]
                garden = app.components["walledgarden"]
                resolver = app.components["dns_resolver"]
                # simulate a lease for the MAC, then garden transition
                mac = "02:00:00:00:00:31"
                import types
                dhcp.leases[mac_to_u64(mac)] = types.SimpleNamespace(
                    ip=0x0A00002A)  # 10.0.0.42
                garden.add_to_walled_garden(mac)
                assert resolver.is_in_walled_garden("10.0.0.42")
                garden.release_from_walled_garden(mac)
                assert not resolver.is_in_walled_garden("10.0.0.42")
            finally:
                app.close()
        finally:
            up.close()


class TestDNSWireReviewFixes:
    """Review r4 regressions: non-address records must survive the
    forward path; garden/lease ordering must not leave enforcement holes."""

    def test_mx_txt_records_pass_through(self):
        import struct
        from bng_tpu.control.dns import TYPE_MX, TYPE_TXT
        from bng_tpu.control import dns_wire as w

        # upstream response with a compressed MX exchange + a TXT record
        hdr = struct.pack("!HHHHHH", 9, 0x8180, 1, 2, 0, 0)
        qname = b"\x04mail\x07example\x03com\x00"
        question = qname + struct.pack("!HH", TYPE_MX, 1)
        mx_rdata = struct.pack("!H", 10) + b"\xc0\x0c"  # pref 10, ptr to qname
        mx = b"\xc0\x0c" + struct.pack("!HHIH", TYPE_MX, 1, 300,
                                       len(mx_rdata)) + mx_rdata
        txt_rdata = b"\x07v=spf1!"
        txt = b"\xc0\x0c" + struct.pack("!HHIH", TYPE_TXT, 1, 300,
                                        len(txt_rdata)) + txt_rdata
        _txid, _q, resp = w.decode_response(hdr + question + mx + txt)
        assert len(resp.answers) == 2
        # re-encode (what DNSServer sends the subscriber) and decode again
        txid2, _q2, resp2 = w.decode_response(w.encode_response(resp, 9))
        assert len(resp2.answers) == 2, "non-address answers were dropped"
        # the MX exchange name was decompressed and survives re-encoding
        pref = struct.unpack("!H", resp2.answers[0].rdata[:2])[0]
        name, _ = w._decode_name(resp2.answers[0].rdata, 2)
        assert pref == 10 and name == "mail.example.com"
        assert resp2.answers[1].rdata == txt_rdata

    def test_garden_before_dhcp_lease_still_enforced(self):
        """MAC gardened BEFORE a lease exists: the grant must pull the
        IP into the resolver garden (review r4 finding 1)."""
        import types
        from bng_tpu.cli import BNGApp, BNGConfig
        from bng_tpu.utils.net import mac_to_u64

        app = BNGApp(BNGConfig(dns_enabled=True, dns_listen="127.0.0.1:0"))
        try:
            dhcp = app.components["dhcp"]
            garden = app.components["walledgarden"]
            resolver = app.components["dns_resolver"]
            mac = "02:00:00:00:00:41"
            garden.add_to_walled_garden(mac)  # no lease yet: no-op
            assert not resolver.is_in_walled_garden("10.0.0.91")
            lease = types.SimpleNamespace(ip=0x0A00005B, mac=mac,
                                          session_id="s1")  # 10.0.0.91
            dhcp.leases[mac_to_u64(mac)] = lease
            dhcp.accounting_hook("start", lease, "s1")  # the grant event
            assert resolver.is_in_walled_garden("10.0.0.91")
            # lease stop scrubs the IP even while still gardened, so a
            # reassigned address never inherits the portal
            dhcp.accounting_hook("stop", lease, "s1")
            assert not resolver.is_in_walled_garden("10.0.0.91")
        finally:
            app.close()

    def test_remove_and_expiry_fire_state_change(self):
        from bng_tpu.control.walledgarden import (SubscriberState,
                                                  WalledGardenConfig,
                                                  WalledGardenManager)

        clock = FakeClock()
        m = WalledGardenManager(WalledGardenConfig(default_timeout=10),
                                clock=clock)
        events = []
        m.on_state_change(lambda k, s: events.append((k, s)))
        m.release_from_walled_garden("02:00:00:00:00:51")
        m.remove_mac("02:00:00:00:00:51")
        assert events[-1][1] == SubscriberState.UNKNOWN
        m.add_to_walled_garden("02:00:00:00:00:52")
        clock.t += 100
        assert m.check_expired() == 1
        assert events[-1][1] == SubscriberState.UNKNOWN

    def test_build_failure_runs_cleanup(self):
        """A half-built app must release what it started (review r4)."""
        import pytest as _pytest
        from bng_tpu.cli import BNGApp, BNGConfig

        before = threading.active_count()
        with _pytest.raises(ValueError, match="routing_platform"):
            BNGApp(BNGConfig(dns_enabled=True, dns_listen="127.0.0.1:0",
                             routing_platform="linxu"))
        # the DNS listener thread started at step 2b must be gone
        for _ in range(20):
            if threading.active_count() <= before:
                break
            time.sleep(0.05)
        names = [t.name for t in threading.enumerate()]
        assert "bng-dns-udp" not in names, names


class TestForwarderDeadline:
    """Advisor r5: the per-upstream recv loop honors one DEADLINE, not a
    re-armed full timeout per stale reply, and rejects replies whose
    echoed question does not match the query (RFC 5452 entropy checks)."""

    def test_mismatch_flood_cannot_exceed_budget(self):
        import socket as _socket
        import struct
        import threading
        import time as _time

        from bng_tpu.control.dns_wire import UDPForwarder
        from bng_tpu.control.dns import Query

        # a hostile upstream that streams wrong-txid replies forever
        srv = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        stop = threading.Event()

        def flood():
            while not stop.is_set():
                try:
                    srv.settimeout(0.5)
                    data, addr = srv.recvfrom(4096)
                except OSError:
                    continue
                bad = struct.pack("!HHHHHH", 0xBAD0, 0x8180, 0, 0, 0, 0)
                for _ in range(50):
                    srv.sendto(bad, addr)
                    _time.sleep(0.005)

        t = threading.Thread(target=flood, daemon=True)
        t.start()
        try:
            fwd = UDPForwarder([f"127.0.0.1:{port}"], timeout=0.4)
            t0 = _time.monotonic()
            with pytest.raises(RuntimeError, match="all upstreams"):
                fwd(Query(name="x.test"))
            elapsed = _time.monotonic() - t0
            # old behavior: every stale reply re-armed 0.4s -> unbounded;
            # with the deadline the whole attempt stays near one budget
            assert elapsed < 1.5, f"deadline not honored: {elapsed:.1f}s"
            assert fwd.stats["timeouts"] == 1
        finally:
            stop.set()
            srv.close()

    def test_wrong_question_echo_rejected(self):
        import socket as _socket
        import threading

        from bng_tpu.control.dns_wire import (UDPForwarder, decode_query,
                                              encode_response)
        from bng_tpu.control.dns import Query, Record, Response

        srv = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]

        def answer_wrong_name():
            data, addr = srv.recvfrom(4096)
            txid, q = decode_query(data)[0:2]
            # same txid, DIFFERENT question: a cache-poisoning shape
            wrong = Response(query=Query(name="evil.test", qtype=q.qtype),
                             rcode=0,
                             answers=[Record(name="evil.test", rtype=1,
                                             ipv4="1.2.3.4")])
            srv.sendto(encode_response(wrong, txid), addr)

        t = threading.Thread(target=answer_wrong_name, daemon=True)
        t.start()
        try:
            fwd = UDPForwarder([f"127.0.0.1:{port}"], timeout=0.5)
            with pytest.raises(RuntimeError):
                fwd(Query(name="real.test"))  # poisoned answer never accepted
        finally:
            srv.close()


# ------------------------------------------------- walled garden wire view

def _wire_view(frame: bytes):
    """What the ring parser sees for garden classification: the DECODED
    frame's (src mac, dst ip, dst L4 port, ip proto) — no host-side
    session hints."""
    d = packets.decode(frame)
    return d.src_mac, u32_to_ip(d.dst_ip), d.dst_port, d.proto


class TestGardenWireView:
    """ISSUE 18 dormant-module pass: the host redirect decision and the
    wire view agree. Every flow below is built as real frame bytes
    (packets.udp_packet/tcp_packet), decoded, and classified from the
    decoded fields only — so the manager's (ip, port, proto) matching
    is pinned to exactly what the dataplane parser extracts."""

    SUB = bytes.fromhex("020000000001")   # gardened subscriber
    PROV = bytes.fromhex("020000000002")  # provisioned subscriber
    GW = bytes.fromhex("0200000000fe")

    def _frames(self, m):
        cfg = m.config
        web = ip_to_u32("93.184.216.34")
        dns = ip_to_u32(cfg.allowed_dns[0])
        portal = ip_to_u32(cfg.portal_ip)
        src = ip_to_u32("10.0.0.50")
        mk_udp = lambda mac, dst, dport: packets.udp_packet(
            mac, self.GW, src, dst, 40000, dport, b"x")
        mk_tcp = lambda mac, dst, dport: packets.tcp_packet(
            mac, self.GW, src, dst, 40000, dport)
        return [
            # (frame, should_redirect?)
            (mk_tcp(self.SUB, web, 80), True),          # gardened HTTP
            (mk_tcp(self.SUB, web, 443), True),         # gardened HTTPS
            (mk_udp(self.SUB, dns, 53), False),         # DNS/UDP bypass
            (mk_tcp(self.SUB, dns, 53), False),         # DNS/TCP bypass
            (mk_udp(self.SUB, dns, 5353), True),        # wrong port
            (mk_tcp(self.SUB, portal, cfg.portal_port), False),  # portal
            (mk_udp(self.SUB, portal, cfg.portal_port), True),   # portal
            # is allowed for TCP only: a UDP flow to it still diverts
            (mk_tcp(self.PROV, web, 80), False),        # provisioned
        ]

    def test_wire_decoded_flows_classify_like_host(self):
        m = WalledGardenManager()
        m.add_to_walled_garden(self.SUB)
        m.release_from_walled_garden(self.PROV)
        for i, (frame, want) in enumerate(self._frames(m)):
            mac, ip, port, proto = _wire_view(frame)
            assert m.should_redirect(mac, ip, port, proto) == want, \
                f"flow {i}: wire view ({ip}:{port}/{proto}) misclassified"

    def test_state_flip_reclassifies_same_bytes(self):
        """The SAME frame bytes flip classification when only the
        subscriber state moves — destination matching never caches."""
        m = WalledGardenManager()
        m.add_to_walled_garden(self.SUB)
        frame = packets.tcp_packet(self.SUB, self.GW,
                                   ip_to_u32("10.0.0.50"),
                                   ip_to_u32("93.184.216.34"), 40000, 80)
        mac, ip, port, proto = _wire_view(frame)
        assert m.should_redirect(mac, ip, port, proto)
        m.release_from_walled_garden(mac)
        assert not m.should_redirect(mac, ip, port, proto)
        m.add_to_walled_garden(mac)
        assert m.should_redirect(mac, ip, port, proto)

    def test_decoded_proto_distinguishes_udp_tcp(self):
        dns = "8.8.8.8"
        m = WalledGardenManager()
        m.add_to_walled_garden(self.SUB)
        udp = packets.udp_packet(self.SUB, self.GW, ip_to_u32("10.0.0.50"),
                                 ip_to_u32(dns), 40000, 53, b"q")
        tcp = packets.tcp_packet(self.SUB, self.GW, ip_to_u32("10.0.0.50"),
                                 ip_to_u32(dns), 40000, 53)
        assert packets.decode(udp).proto == 17
        assert packets.decode(tcp).proto == 6
        assert not m.should_redirect(*_wire_view(udp))
        assert not m.should_redirect(*_wire_view(tcp))
