"""ZTP TLS: pinning, expiry, DER parsing, and a real pinned handshake.

Fixtures are generated with openssl at test time (real certificates, not
hand-built ASN.1), mirroring the reference's use of the live TLS stack in
pkg/ztp/tls.go tests.
"""

import datetime
import json
import os
import socket
import ssl
import subprocess
import threading

import numpy as np
import pytest

from bng_tpu.control import ztp_tls as zt


def _openssl_selfsigned(tmp, cn="nexus.test", days=365, san="DNS:nexus.test,IP:127.0.0.1"):
    key = os.path.join(tmp, f"{cn}.key")
    crt = os.path.join(tmp, f"{cn}.crt")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", crt, "-days", str(days),
         "-subj", f"/CN={cn}", "-addext", f"subjectAltName={san}"],
        check=True, capture_output=True)
    return key, crt


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("ztp_tls"))
    key, crt = _openssl_selfsigned(tmp)
    with open(crt) as f:
        pem = f.read()
    der = zt.pem_to_der(pem)[0]
    return {"tmp": tmp, "key": key, "crt": crt, "pem": pem, "der": der}


class TestDERParser:
    def test_parse_real_openssl_cert(self, certs):
        info = zt.parse_certificate(certs["der"])
        assert info.subject == "CN=nexus.test"
        assert info.issuer == "CN=nexus.test"  # self-signed
        assert "nexus.test" in info.dns_names
        assert "127.0.0.1" in info.ip_addresses
        assert info.not_before is not None and info.not_after is not None
        assert info.not_after > info.not_before
        assert info.fingerprint == zt.cert_fingerprint(certs["der"])
        assert len(info.serial_number) > 0

    def test_ca_flag(self, certs):
        # openssl req -x509 marks CA:TRUE by default
        assert zt.parse_certificate(certs["der"]).is_ca

    def test_expiring_soon_math(self, certs):
        soon, remaining = zt.is_certificate_expiring_soon(certs["der"], 30)
        assert not soon and 300 < remaining < 400
        soon, _ = zt.is_certificate_expiring_soon(certs["der"], 400)
        assert soon

    def test_fuzz_never_crashes(self, certs):
        rng = np.random.default_rng(0x7E5)
        base = bytearray(certs["der"])
        for _ in range(300):
            m = bytearray(base)
            for _ in range(int(rng.integers(1, 8))):
                m[int(rng.integers(len(m)))] = int(rng.integers(256))
            if rng.integers(2):
                m = m[: int(rng.integers(1, len(m)))]
            try:
                zt.parse_certificate(bytes(m))
            except (ValueError, OverflowError):
                pass  # structured rejection only — never a crash/hang


class TestConfigValidation:
    def test_contradictions_rejected(self):
        with pytest.raises(ValueError, match="min_version"):
            zt.validate_tls_config(zt.TLSConfig(min_version="1.0"))
        with pytest.raises(ValueError, match="pick one"):
            zt.validate_tls_config(zt.TLSConfig(
                insecure_skip_verify=True, pinned_certs=["ab" * 32]))
        with pytest.raises(ValueError, match="authenticates nobody"):
            zt.validate_tls_config(zt.TLSConfig(require_valid_chain=False))
        with pytest.raises(ValueError, match="hex SHA-256"):
            zt.validate_tls_config(zt.TLSConfig(
                require_valid_chain=False, pinned_certs=["zz"]))
        zt.validate_tls_config(zt.TLSConfig())  # defaults are valid

    def test_fingerprint_normalization(self):
        fp = "AB:CD:" + "11" * 30
        assert zt.normalize_fingerprint(fp) == "abcd" + "11" * 30


class TestVerifyPeer:
    def test_pin_match_and_mismatch(self, certs):
        fp = zt.cert_fingerprint(certs["der"])
        cfg = zt.TLSConfig(require_valid_chain=False, pinned_certs=[fp])
        res = zt.verify_peer([certs["der"]], cfg)
        assert res.valid and res.pinning_verified
        bad = zt.TLSConfig(require_valid_chain=False,
                           pinned_certs=["00" * 32])
        with pytest.raises(zt.CertificateValidationError, match="pinned"):
            zt.verify_peer([certs["der"]], bad)

    def test_expired_and_not_yet_valid(self, certs):
        cfg = zt.TLSConfig()
        future = datetime.datetime(2900, 1, 1, tzinfo=datetime.timezone.utc)
        with pytest.raises(zt.CertificateValidationError, match="expired"):
            zt.verify_peer([certs["der"]], cfg, now=future)
        past = datetime.datetime(2000, 1, 1, tzinfo=datetime.timezone.utc)
        with pytest.raises(zt.CertificateValidationError, match="not yet"):
            zt.verify_peer([certs["der"]], cfg, now=past)

    def test_expiry_warning_surface(self, certs):
        cfg = zt.TLSConfig(cert_expiry_warning_days=9999)
        res = zt.verify_peer([certs["der"]], cfg)
        assert res.valid and any("expires in" in w for w in res.warnings)

    def test_empty_chain_rejected(self):
        with pytest.raises(zt.CertificateValidationError, match="no peer"):
            zt.verify_peer([], zt.TLSConfig())


class TestPinnedHandshake:
    """Real TLS over loopback: the bootstrap scenario — self-signed Nexus,
    no CA, SHA-256 pin (TOFU), https_get_json enforces the pin before the
    request (tls.go:208-229 enforcement point)."""

    def _serve_tls(self, certs, payload: dict):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certs["crt"], certs["key"])
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        port = srv.getsockname()[1]
        body = json.dumps(payload).encode()

        def serve():
            srv.settimeout(5)
            try:
                while True:
                    conn, _ = srv.accept()
                    try:
                        tls = ctx.wrap_socket(conn, server_side=True)
                        tls.recv(4096)
                        tls.sendall(
                            b"HTTP/1.1 200 OK\r\nContent-Length: "
                            + str(len(body)).encode()
                            + b"\r\nContent-Type: application/json\r\n\r\n"
                            + body)
                        tls.close()
                    except (ssl.SSLError, OSError):
                        pass
            except (TimeoutError, socket.timeout, OSError):
                pass

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        return srv, port

    def test_pinned_bootstrap_roundtrip(self, certs):
        srv, port = self._serve_tls(certs, {"device_id": "bng-007"})
        try:
            fp = zt.cert_fingerprint(certs["der"])
            cfg = zt.TLSConfig(require_valid_chain=False, pinned_certs=[fp],
                               server_name="nexus.test")
            status, parsed, warnings = zt.https_get_json(
                f"https://127.0.0.1:{port}/api/v1/bootstrap", cfg)
            assert status == 200 and parsed == {"device_id": "bng-007"}
        finally:
            srv.close()

    def test_wrong_pin_aborts_before_request(self, certs):
        srv, port = self._serve_tls(certs, {"never": "served"})
        try:
            cfg = zt.TLSConfig(require_valid_chain=False,
                               pinned_certs=["11" * 32])
            with pytest.raises(zt.CertificateValidationError):
                zt.https_get_json(f"https://127.0.0.1:{port}/x", cfg)
        finally:
            srv.close()

    def test_ca_validated_handshake(self, certs):
        """require_valid_chain path: the self-signed cert IS the CA."""
        srv, port = self._serve_tls(certs, {"ok": 1})
        try:
            cfg = zt.TLSConfig(ca_cert_pem=certs["pem"],
                               server_name="nexus.test")
            # hostname mismatch (we dial 127.0.0.1 but check_hostname is
            # on): Python checks against the IP SAN — 127.0.0.1 IS in the
            # SAN, so this validates end-to-end through the real chain
            status, parsed, _ = zt.https_get_json(
                f"https://127.0.0.1:{port}/x", cfg)
            assert status == 200 and parsed == {"ok": 1}
        finally:
            srv.close()


class TestBootstrapOverPinnedTLS:
    """BootstrapClient -> make_https_transport -> real pinned TLS server:
    the full ZTP registration flow the reference runs over tls.go."""

    def test_register_through_pinned_channel(self, certs):
        from bng_tpu.control.ztp import (BootstrapClient, BootstrapConfig,
                                         DeviceIdentity, make_https_transport)

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certs["crt"], certs["key"])
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(2)
        port = srv.getsockname()[1]
        got = {}

        def serve_one():
            srv.settimeout(5)
            conn, _ = srv.accept()
            tls = ctx.wrap_socket(conn, server_side=True)
            raw = b""
            while b"\r\n\r\n" not in raw:
                raw += tls.recv(8192)
            head, _, body_part = raw.partition(b"\r\n\r\n")
            clen = 0
            for line in head.decode(errors="replace").split("\r\n"):
                if line.lower().startswith("content-length:"):
                    clen = int(line.split(":", 1)[1])
            while len(body_part) < clen:
                body_part += tls.recv(8192)
            got["body"] = body_part.decode(errors="replace")
            body = json.dumps({"status": "configured", "node_id": "bng-42",
                               "site_id": "site-1", "role": "active"}).encode()
            tls.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                        + str(len(body)).encode() + b"\r\n\r\n" + body)
            tls.close()

        t = threading.Thread(target=serve_one, daemon=True)
        t.start()
        try:
            cfg = BootstrapConfig(
                nexus_url=f"https://127.0.0.1:{port}",
                pin_fingerprint=zt.cert_fingerprint(certs["der"]))
            client = BootstrapClient(
                cfg, make_https_transport(cfg),
                identity=DeviceIdentity(serial="SN123", mac="02:00:00:00:00:01"))
            dev = client.register_once()
            assert dev.node_id == "bng-42" and dev.role == "active"
            assert json.loads(got["body"])["serial"] == "SN123"
        finally:
            srv.close()

    def test_wrong_pin_never_sends_registration(self, certs):
        from bng_tpu.control.ztp import (BootstrapClient, BootstrapConfig,
                                         DeviceIdentity, make_https_transport)

        cfg = BootstrapConfig(nexus_url="https://127.0.0.1:1",
                              pin_fingerprint="22" * 32)
        client = BootstrapClient(
            cfg, make_https_transport(cfg),
            identity=DeviceIdentity(serial="SN1", mac="02:00:00:00:00:02"),
            sleep=lambda s: None)
        with pytest.raises(Exception):
            client.register_once()


class TestAgentBootstrapOverTLS:
    """Agent.start() runs the full ZTP registration over the pinned
    channel and adopts the returned identity (agent/bootstrap.go role)."""

    def test_agent_adopts_bootstrap_identity(self, certs):
        from bng_tpu.control.agent import Agent, AgentConfig, AgentState
        from bng_tpu.control.nexus import NexusClient
        from bng_tpu.control.ztp import (BootstrapClient, BootstrapConfig,
                                         DeviceIdentity, make_https_transport)

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(certs["crt"], certs["key"])
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(2)
        port = srv.getsockname()[1]

        def serve_one():
            srv.settimeout(5)
            conn, _ = srv.accept()
            tls = ctx.wrap_socket(conn, server_side=True)
            raw = b""
            while b"\r\n\r\n" not in raw:
                raw += tls.recv(8192)
            body = json.dumps({"status": "configured",
                               "node_id": "olt-agent-3",
                               "role": "standby"}).encode()
            tls.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: "
                        + str(len(body)).encode() + b"\r\n\r\n" + body)
            tls.close()

        threading.Thread(target=serve_one, daemon=True).start()
        try:
            bcfg = BootstrapConfig(
                nexus_url=f"https://127.0.0.1:{port}",
                pin_fingerprint=zt.cert_fingerprint(certs["der"]))
            bclient = BootstrapClient(
                bcfg, make_https_transport(bcfg),
                identity=DeviceIdentity(serial="SN9", mac="02:00:00:00:00:09"))
            agent = Agent(AgentConfig(device_id="pre-bootstrap"),
                          NexusClient(node_id="n1"),
                          bootstrap_client=bclient)
            agent.start()
            assert agent.state == AgentState.ONLINE
            assert agent.config.device_id == "olt-agent-3"
            assert agent.device_config.role == "standby"
            assert agent.stats["bootstrapped"] == 1
        finally:
            srv.close()
