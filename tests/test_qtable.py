"""Packed QoS table: host mirror <-> device lookup agreement.

Mirrors tests/test_table.py's strategy for the generic cuckoo table
(SURVEY.md §4: map tests are host/device agreement tests) for the
bucket-packed layout of ops/qtable.py.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from bng_tpu.ops.qtable import (
    QW_TOKENS, HostQTable, QTableGeom, WAYS, apply_qupdate, qlookup,
)


def _set_device_tokens(st, slot, value: float):
    """Simulate the device-side token writeback for one slot."""
    u = np.array(value, dtype=np.float32).view(np.uint32)
    return st._replace(rows=st.rows.at[slot, QW_TOKENS].set(jnp.uint32(u)))


def _mk(nbuckets=256, n=100, seed=0):
    t = HostQTable(nbuckets, name="t")
    rng = np.random.default_rng(seed)
    ips = rng.choice(1 << 24, size=n, replace=False).astype(np.uint32) + 1
    for i, ip in enumerate(ips):
        t.insert(int(ip), rate_bps=1_000_000 + i, burst=3000 + i, priority=i % 8)
    return t, ips


class TestHostMirror:
    def test_insert_lookup_roundtrip(self):
        t, ips = _mk()
        for i, ip in enumerate(ips):
            got = t.lookup(int(ip))
            assert got is not None
            assert got["rate_bps"] == 1_000_000 + i
            assert got["burst"] == 3000 + i
            assert got["priority"] == i % 8
            assert got["tokens"] == float(3000 + i)

    def test_update_in_place_reseeds_tokens(self):
        t, ips = _mk()
        ip = int(ips[0])
        s0 = t.lookup(ip)["slot"]
        t.insert(ip, rate_bps=5, burst=99, priority=1, start_full=False)
        got = t.lookup(ip)
        assert got["slot"] == s0  # same slot, config replaced
        assert got["rate_bps"] == 5
        assert got["tokens"] == 0.0
        assert t.count == len(ips)  # not double-counted

    def test_delete(self):
        t, ips = _mk()
        assert t.delete(int(ips[3]))
        assert t.lookup(int(ips[3])) is None
        assert not t.delete(int(ips[3]))
        assert t.count == len(ips) - 1

    def test_64bit_rate_split(self):
        t = HostQTable(64)
        t.insert(42, rate_bps=10_000_000_000, burst=1 << 30)
        assert t.lookup(42)["rate_bps"] == 10_000_000_000

    def test_full_table_raises_and_rolls_back(self):
        t = HostQTable(2)  # 8 slots
        installed = []
        with pytest.raises(RuntimeError, match="full"):
            for ip in range(1, 1000):
                t.insert(ip, rate_bps=1, burst=1)
                installed.append(ip)
        # every successfully-installed policy must still resolve
        for ip in installed:
            assert t.lookup(ip) is not None, ip


class TestDeviceLookup:
    def test_agreement_with_host(self):
        t, ips = _mk(n=200, seed=1)
        st = t.device_state()
        g = QTableGeom(t.nbuckets)
        rng = np.random.default_rng(2)
        miss = rng.integers(1 << 24, 1 << 25, size=50).astype(np.uint32)
        q = np.concatenate([ips, miss])
        res = qlookup(st, jnp.asarray(q), g)
        found = np.asarray(res.found)
        assert found[: len(ips)].all()
        assert not found[len(ips):].any()
        for i, ip in enumerate(ips):
            h = t.lookup(int(ip))
            assert int(np.asarray(res.slot)[i]) == h["slot"]
            assert int(np.asarray(res.burst)[i]) == h["burst"]
            got_rate = int(np.asarray(res.rate_lo)[i]) | (int(np.asarray(res.rate_hi)[i]) << 32)
            assert got_rate == h["rate_bps"]
            assert float(np.asarray(res.tokens)[i]) == h["tokens"]

    def test_update_drain_matches_full_upload(self):
        t, ips = _mk(n=60, seed=3)
        st = t.device_state()  # clears dirty
        # mutate: one delete, one update, one fresh insert
        t.delete(int(ips[0]))
        t.insert(int(ips[1]), rate_bps=777, burst=888, priority=3)
        t.insert(0xDEAD, rate_bps=9, burst=10)
        assert t.dirty_count() > 0
        while t.dirty_count():
            st = apply_qupdate(st, t.make_update(4))
        ref = t.device_state()
        np.testing.assert_array_equal(np.asarray(st.rows), np.asarray(ref.rows))
        # tokens: drained slots seeded; untouched slots keep device values
        q = np.asarray([ips[1], 0xDEAD], dtype=np.uint32)
        res = qlookup(st, jnp.asarray(q), QTableGeom(t.nbuckets))
        assert np.asarray(res.found).all()
        assert float(np.asarray(res.tokens)[0]) == 888.0
        assert float(np.asarray(res.tokens)[1]) == 10.0

    def test_update_does_not_clobber_sibling_tokens(self):
        """Device-authoritative tokens of other ways survive a policy sync
        (way-granular updates only touch changed slots)."""
        t = HostQTable(1)  # single bucket: all entries are siblings
        a = t.insert(1, rate_bps=1000, burst=100)
        st = t.device_state()
        # device drains subscriber 1's tokens to 7.0
        st = _set_device_tokens(st, a, 7.0)
        t.insert(2, rate_bps=2000, burst=200)  # same bucket, new way
        while t.dirty_count():
            st = apply_qupdate(st, t.make_update(2))
        res = qlookup(st, jnp.asarray(np.asarray([1, 2], dtype=np.uint32)),
                      QTableGeom(1))
        assert float(np.asarray(res.tokens)[0]) == 7.0  # preserved
        assert float(np.asarray(res.tokens)[1]) == 200.0  # seeded


class TestBulkInsert:
    def test_bulk_matches_serial(self):
        rng = np.random.default_rng(7)
        n = 5000
        ips = rng.choice(1 << 26, size=n, replace=False).astype(np.uint32) + 1
        rates = rng.integers(1_000_000, 100_000_000, size=n).astype(np.uint64)
        bursts = rng.integers(1500, 1 << 20, size=n).astype(np.uint32)
        t = HostQTable(1 << 12)
        t.bulk_insert(ips, rates, bursts)
        assert t.count == n
        st = t.device_state()
        res = qlookup(st, jnp.asarray(ips), QTableGeom(t.nbuckets))
        assert np.asarray(res.found).all()
        np.testing.assert_array_equal(np.asarray(res.burst), bursts)
        got_rate = np.asarray(res.rate_lo).astype(np.uint64) | (
            np.asarray(res.rate_hi).astype(np.uint64) << np.uint64(32))
        np.testing.assert_array_equal(got_rate, rates)

    def test_small_bulk_stays_on_delta_path(self):
        """A <=256-entry bulk insert must reach the device via make_update
        (code-review r3 finding: vectorized placements skipped dirty marks)."""
        t = HostQTable(1 << 8)
        st = t.device_state()
        ips = (np.arange(100) + 1).astype(np.uint32)
        t.bulk_insert(ips, np.full(100, 5, np.uint64), np.full(100, 1500, np.uint32))
        assert t.dirty_count() > 0 and not t._dirty_all
        while t.dirty_count():
            st = apply_qupdate(st, t.make_update(16))
        res = qlookup(st, jnp.asarray(ips), QTableGeom(t.nbuckets))
        assert np.asarray(res.found).all()
        np.testing.assert_array_equal(np.asarray(res.tokens), 1500.0)

    def test_two_ways_same_bucket_both_reseed(self):
        """Two policy changes in one bucket between drains both re-seed
        (code-review r3 finding: dict held only the latest slot)."""
        t = HostQTable(1)  # everything shares bucket 0
        t.insert(1, rate_bps=8, burst=111)
        t.insert(2, rate_bps=8, burst=222)
        st = t.device_state()
        # device token state diverges, then both policies are re-installed
        for s in range(WAYS):
            st = _set_device_tokens(st, s, 3.0)
        t.insert(1, rate_bps=8, burst=111)
        t.insert(2, rate_bps=8, burst=222)
        while t.dirty_count():
            st = apply_qupdate(st, t.make_update(4))
        res = qlookup(st, jnp.asarray(np.asarray([1, 2], dtype=np.uint32)),
                      QTableGeom(1))
        assert float(np.asarray(res.tokens)[0]) == 111.0
        assert float(np.asarray(res.tokens)[1]) == 222.0

    def test_bulk_invalidates_delta_sync(self):
        t = HostQTable(1 << 10)
        ips = (np.arange(2000) + 1).astype(np.uint32)
        t.bulk_insert(ips, np.full(2000, 1, np.uint64), np.full(2000, 1500, np.uint32))
        with pytest.raises(RuntimeError, match="full upload"):
            t.make_update(8)
        t.device_state()  # resync
        t.insert(99999, rate_bps=1, burst=1)
        assert t.dirty_count() == 1


class TestTimestampWrap:
    def test_refill_across_u32_us_wrap(self):
        """The µs clock wraps every ~71.6 minutes; refill must compute the
        elapsed time modulo 2^32 (uint32 wrap-safe diff), not go negative
        or grant a huge refill at the boundary."""
        import jax.numpy as jnp

        from bng_tpu.ops.qos import qos_kernel
        from bng_tpu.runtime.engine import QoSTables

        qos = QoSTables(nbuckets=64)
        # 8 Mbps = 1e6 B/s; burst 10kB
        qos.set_subscriber(0x0A000002, down_bps=8_000_000, up_bps=8_000_000,
                           up_burst=10_000, down_burst=10_000)
        st = qos.up.device_state()
        ips = jnp.full((4,), 0x0A000002, dtype=jnp.uint32)
        lens = jnp.full((4,), 2_000, dtype=jnp.uint32)
        active = jnp.ones((4,), dtype=bool)

        # drain most of the bucket just before the wrap point
        t1 = jnp.uint32(0xFFFFFF00)
        r1 = qos_kernel(ips, lens, active, st, qos.geom, t1)
        assert list(np.asarray(r1.allowed)) == [True] * 4  # 8k of 10k burst
        st = r1.table

        # 2ms later, ACROSS the wrap: refill = 2000us * 1B/us = 2000B.
        # bucket = min(2000 + 2000, burst); exactly two 2000B packets pass
        t2 = jnp.uint32((0xFFFFFF00 + 2_000) & 0xFFFFFFFF)
        assert int(t2) < int(t1)  # genuinely wrapped
        r2 = qos_kernel(ips, lens, active, st, qos.geom, t2)
        assert list(np.asarray(r2.allowed)) == [True, True, False, False], \
            np.asarray(r2.allowed)
