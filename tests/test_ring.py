"""Native packet ring: ABI layout, SPSC semantics, verdict demux, and the
ring-driven end-to-end DORA loop.

The ABI tests are the test/ebpf/maps_test.go role (reference asserts
unsafe.Sizeof(Go mirror) == C layout, maps_test.go:17-80): here the C
library self-describes bng_desc offsets and the ctypes mirror must match
byte-for-byte, or host<->native frame descriptors would corrupt.

Every behavioral test runs against BOTH backends (NativeRing via the C++
.so built from native/bngring.cpp, and the PyRing stub) — the reference's
_linux.go/_stub.go parity discipline (SURVEY.md §4.6).
"""

import ctypes as C

import numpy as np
import pytest

from bng_tpu.runtime.ring import (
    Desc,
    NativeRing,
    PyRing,
    RingStats,
    load_native,
    wire_pump,
)

native_available = load_native() is not None


@pytest.fixture(params=["native", "py"])
def ring_cls(request):
    if request.param == "native":
        if not native_available:
            pytest.skip("native toolchain unavailable")
        return NativeRing
    return PyRing


class TestABI:
    """Host mirror <-> C layout (maps_test.go:17-80 role)."""

    @pytest.mark.skipif(not native_available, reason="no native lib")
    def test_desc_layout(self):
        lib = load_native()
        assert lib.bng_abi_desc_size() == C.sizeof(Desc)
        assert lib.bng_abi_desc_addr_off() == Desc.addr.offset
        assert lib.bng_abi_desc_len_off() == Desc.len.offset
        assert lib.bng_abi_desc_flags_off() == Desc.flags.offset

    @pytest.mark.skipif(not native_available, reason="no native lib")
    def test_stats_layout_and_version(self):
        lib = load_native()
        assert lib.bng_abi_stats_size() == C.sizeof(RingStats)
        assert lib.bng_abi_version() == 3


class TestRingBasics:
    def test_push_assemble_roundtrip(self, ring_cls):
        r = ring_cls(nframes=64, frame_size=256, depth=32)
        frames = [bytes([i]) * (20 + i) for i in range(5)]
        for i, f in enumerate(frames):
            assert r.rx_push(f, from_access=(i % 2 == 0))
        assert r.rx_pending() == 5

        out = np.zeros((8, 128), dtype=np.uint8)
        ln = np.zeros((8,), dtype=np.uint32)
        fl = np.zeros((8,), dtype=np.uint32)
        n = r.assemble(out, ln, fl)
        assert n == 5
        for i, f in enumerate(frames):
            assert bytes(out[i, : ln[i]]) == f
            assert (fl[i] & 1) == (1 if i % 2 == 0 else 0)
        r.close()

    def test_verdict_demux(self, ring_cls):
        r = ring_cls(nframes=64, frame_size=256, depth=32)
        for i in range(4):
            r.rx_push(bytes([i]) * 64)
        out = np.zeros((8, 128), dtype=np.uint8)
        ln = np.zeros((8,), dtype=np.uint32)
        fl = np.zeros((8,), dtype=np.uint32)
        n = r.assemble(out, ln, fl)
        assert n == 4

        # lane 0 TX (rewritten), 1 DROP, 2 FWD (rewritten), 3 PASS
        out[0, :4] = (0xAA, 0xBB, 0xCC, 0xDD)
        ln[0] = 4
        out[2, :2] = (0x11, 0x22)
        ln[2] = 2
        verdict = np.array([2, 1, 3, 0], dtype=np.uint8)
        r.complete(verdict, out, ln, n)

        assert r.tx_pending() == 1 and r.fwd_pending() == 1 and r.slow_pending() == 1
        frame, _ = r.tx_pop()
        assert frame == bytes([0xAA, 0xBB, 0xCC, 0xDD])
        frame, _ = r.fwd_pop()
        assert frame == bytes([0x11, 0x22])
        frame, _ = r.slow_pop()
        assert frame == bytes([3]) * 64  # PASS keeps original bytes
        s = r.stats()
        assert s["tx"] == 1 and s["fwd"] == 1 and s["drop"] == 1 and s["slow"] == 1
        r.close()

    def test_frames_recycle(self, ring_cls):
        r = ring_cls(nframes=8, frame_size=128, depth=8)
        out = np.zeros((8, 128), dtype=np.uint8)
        ln = np.zeros((8,), dtype=np.uint32)
        fl = np.zeros((8,), dtype=np.uint32)
        for _round in range(5):  # > nframes total frames: must recycle
            for i in range(4):
                assert r.rx_push(b"x" * 60)
            n = r.assemble(out, ln, fl)
            r.complete(np.full((n,), 1, dtype=np.uint8), out, ln, n)  # DROP all
        assert r.free_frames() == 8

    def test_fill_exhaustion(self, ring_cls):
        r = ring_cls(nframes=8, frame_size=128, depth=16)
        ok = sum(1 for _ in range(12) if r.rx_push(b"y" * 32))
        assert ok == 8  # only nframes fit
        assert r.stats()["fill_empty"] >= 1 or r.free_frames() == 0
        r.close()

    def test_oversize_frame_rejected(self, ring_cls):
        r = ring_cls(nframes=8, frame_size=128, depth=8)
        assert not r.rx_push(b"z" * 500)
        r.close()

    def test_tx_inject(self, ring_cls):
        r = ring_cls(nframes=8, frame_size=128, depth=8)
        assert r.tx_inject(b"reply" * 4)
        frame, fl = r.tx_pop()
        assert frame == b"reply" * 4 and (fl & 1) == 1
        r.close()

    def test_two_inflight_windows_fifo(self, ring_cls):
        """Double buffering: two assemble..complete windows may be open
        (the pipelined engine's contract); a third is refused; complete()
        retires strictly FIFO."""
        r = ring_cls(nframes=8, frame_size=128, depth=8)
        out1 = np.zeros((4, 64), dtype=np.uint8)
        out2 = np.zeros((4, 64), dtype=np.uint8)
        ln1 = np.zeros((4,), dtype=np.uint32)
        ln2 = np.zeros((4,), dtype=np.uint32)
        fl = np.zeros((4,), dtype=np.uint32)

        r.rx_push(b"a" * 32)
        assert r.assemble(out1, ln1, fl) == 1  # window 1
        r.rx_push(b"b" * 32)
        r.rx_push(b"c" * 32)
        assert r.assemble(out2, ln2, fl) == 2  # window 2 (double buffer)
        r.rx_push(b"d" * 32)
        assert r.assemble(out1, ln1, fl) == 0  # third window refused

        # FIFO: the first complete retires window 1 (the 1-frame batch);
        # PASS it so the original bytes prove which batch retired
        r.complete(np.array([0], dtype=np.uint8), out1, ln1, 1)
        frame, _ = r.slow_pop()
        assert frame == b"a" * 32
        r.complete(np.array([0, 0], dtype=np.uint8), out2, ln2, 2)
        assert r.slow_pop()[0] == b"b" * 32
        assert r.slow_pop()[0] == b"c" * 32
        # both windows closed: assemble works again
        assert r.assemble(out1, ln1, fl) == 1
        r.close()


class TestWire:
    def test_loopback_pump_flips_direction(self, ring_cls):
        a = ring_cls(nframes=32, frame_size=256, depth=16)
        b = ring_cls(nframes=32, frame_size=256, depth=16)
        a.rx_push(b"ping" * 8, from_access=True)
        out = np.zeros((4, 128), dtype=np.uint8)
        ln = np.zeros((4,), dtype=np.uint32)
        fl = np.zeros((4,), dtype=np.uint32)
        n = a.assemble(out, ln, fl)
        r_verdict = np.array([3], dtype=np.uint8)  # FWD
        a.complete(r_verdict, out, ln, n)
        moved = wire_pump(a, b, budget=8)
        assert moved == 1
        n = b.assemble(out, ln, fl)
        assert n == 1 and (fl[0] & 1) == 0  # arrived on the core side
        a.close()
        b.close()

    def test_pump_does_not_leak_dhcp_ctrl_flag(self, ring_cls):
        """A FWD'd access-side DHCP frame arriving on the core side must
        NOT keep its control bit (code-review r3: a stale bit would smuggle
        network-side frames past the fast lane's direction gate)."""
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.runtime.ring import FLAG_DHCP_CTRL

        a = ring_cls(nframes=32, frame_size=1024, depth=16)
        b = ring_cls(nframes=32, frame_size=1024, depth=16)
        mac = bytes.fromhex("02c0ffee0041")
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
        f = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                               p.encode().ljust(320, b"\x00"))
        assert a.rx_push(f, from_access=True)
        out = np.zeros((4, 1024), dtype=np.uint8)
        ln = np.zeros((4,), dtype=np.uint32)
        fl = np.zeros((4,), dtype=np.uint32)
        n = a.assemble(out, ln, fl)
        assert fl[0] & FLAG_DHCP_CTRL  # classified on the access side
        a.complete(np.array([3], dtype=np.uint8), out, ln, n)  # FWD
        assert wire_pump(a, b, budget=8) == 1
        n = b.assemble(out, ln, fl)
        assert n == 1 and (fl[0] & FLAG_DHCP_CTRL) == 0
        a.close()
        b.close()


class TestRingEngine:
    """Ring-driven end-to-end: the production I/O loop."""

    def _stack(self, ring):
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.nat import NATManager
        from bng_tpu.control.pool import Pool, PoolManager
        from bng_tpu.runtime.engine import Engine
        from bng_tpu.runtime.tables import FastPathTables
        from bng_tpu.utils.net import ip_to_u32

        server_mac = bytes.fromhex("02aabbccdd01")
        server_ip = ip_to_u32("10.0.0.1")
        fastpath = FastPathTables(sub_nbuckets=512, vlan_nbuckets=64,
                                  cid_nbuckets=64, max_pools=16)
        fastpath.set_server_config(server_mac, server_ip)
        pools = PoolManager(fastpath)
        pools.add_pool(Pool(pool_id=1, network=ip_to_u32("10.0.0.0"),
                            prefix_len=24, gateway=server_ip,
                            dns_primary=ip_to_u32("1.1.1.1"), lease_time=3600))
        nat = NATManager(public_ips=[ip_to_u32("203.0.113.1")],
                         sessions_nbuckets=256, sub_nat_nbuckets=64)
        server = DHCPServer(server_mac, server_ip, pools,
                            fastpath_tables=fastpath,
                            clock=lambda: 1_753_000_000.0)
        engine = Engine(fastpath, nat, batch_size=8,
                        slow_path=server.handle_frame,
                        clock=lambda: 1_753_000_000.0)
        return engine, server

    def test_ring_dora_slow_then_fast(self, ring_cls):
        from bng_tpu.control import dhcp_codec, packets

        ring = ring_cls(nframes=64, frame_size=1024, depth=32)
        engine, server = self._stack(ring)
        mac = bytes.fromhex("02c0ffee0009")

        def discover():
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
            p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST, bytes([1, 3, 6, 51, 54])))
            return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                      p.encode().ljust(320, b"\x00"))

        # DISCOVER #1: misses on device -> PASS -> slow path -> OFFER injected
        ring.rx_push(discover(), from_access=True)
        n = engine.process_ring(ring)
        assert n == 1
        assert engine.stats.passed == 1
        got = ring.tx_pop()
        assert got is not None
        offer, _ = got
        parsed = dhcp_codec.decode(packets.decode(offer).payload)
        assert parsed.msg_type == dhcp_codec.OFFER

        # REQUEST via slow path installs the fast-path entry
        req = dhcp_codec.build_request(mac, dhcp_codec.REQUEST)
        req.options.append((dhcp_codec.OPT_REQUESTED_IP, parsed.yiaddr.to_bytes(4, 'big')))
        req.options.append((dhcp_codec.OPT_SERVER_ID,
                            packets.decode(offer).src_ip.to_bytes(4, "big")))
        ring.rx_push(packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                        req.encode().ljust(320, b"\x00")))
        engine.process_ring(ring)
        ack, _ = ring.tx_pop()
        assert dhcp_codec.decode(packets.decode(ack).payload).msg_type == dhcp_codec.ACK

        # DISCOVER #2: answered ON DEVICE (TX verdict, no slow path)
        before_passed = engine.stats.passed
        ring.rx_push(discover(), from_access=True)
        engine.process_ring(ring)
        assert engine.stats.tx == 1
        assert engine.stats.passed == before_passed
        offer2, _ = ring.tx_pop()
        assert dhcp_codec.decode(packets.decode(offer2).payload).msg_type == dhcp_codec.OFFER
        ring.close()


    def test_pipelined_ring_loop_matches_sync(self, ring_cls):
        """Double-buffered dispatch: same verdicts, one-call delay, stats
        identical after flush (SURVEY §7 dispatch design)."""
        from bng_tpu.control import dhcp_codec, packets

        ring = ring_cls(nframes=64, frame_size=1024, depth=32)
        engine, server = self._stack(ring)
        mac = bytes.fromhex("02c0ffee0010")

        def discover(xid):
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
            p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                              bytes([1, 3, 6, 51, 54])))
            return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                      p.encode().ljust(320, b"\x00"))

        # call 1: dispatches, retires nothing (pipe filling)
        ring.rx_push(discover(1), from_access=True)
        assert engine.process_ring_pipelined(ring) == 0
        assert ring.tx_pop() is None  # verdicts not applied yet

        # call 2: retires batch 1 (slow-path OFFER appears), dispatches #2
        ring.rx_push(discover(2), from_access=True)
        assert engine.process_ring_pipelined(ring) == 1
        offer, _ = ring.tx_pop()
        parsed = dhcp_codec.decode(packets.decode(offer).payload)
        assert parsed.msg_type == dhcp_codec.OFFER

        # flush retires the tail batch
        assert engine.flush_pipeline(ring) == 1
        offer2, _ = ring.tx_pop()
        assert dhcp_codec.decode(
            packets.decode(offer2).payload).msg_type == dhcp_codec.OFFER
        assert engine.flush_pipeline(ring) == 0  # idempotent
        assert engine.stats.passed == 2 and engine.stats.batches == 2

        # empty calls are cheap no-ops
        assert engine.process_ring_pipelined(ring) == 0
        ring.close()



    def test_pipelined_dispatch_failure_fails_closed(self, ring_cls):
        """Dispatch dying mid-pipeline: the previous batch's verdicts
        still apply (FIFO retire first), the new window closes via DROP,
        and the ring stays fully usable (code-review r3 finding)."""
        from bng_tpu.control import dhcp_codec, packets

        ring = ring_cls(nframes=64, frame_size=1024, depth=32)
        engine, server = self._stack(ring)
        mac = bytes.fromhex("02c0ffee0011")

        def discover(xid):
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=xid)
            p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                              bytes([1, 3, 6, 51, 54])))
            return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                      p.encode().ljust(320, b"\x00"))

        ring.rx_push(discover(1), from_access=True)
        assert engine.process_ring_pipelined(ring) == 0  # batch A in flight

        real_dispatch = engine._dispatch_step
        real_dhcp = engine._run_dhcp_batch

        def boom(*a, **k):
            raise RuntimeError("synthetic device error")

        # DHCP batches ride the fast lane; patch BOTH dispatch entry points
        # so the failure covers whichever program the batch routes to
        engine._dispatch_step = boom
        engine._run_dhcp_batch = boom
        ring.rx_push(discover(2), from_access=True)
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="synthetic"):
            engine.process_ring_pipelined(ring)  # batch B dispatch dies
        engine._dispatch_step = real_dispatch
        engine._run_dhcp_batch = real_dhcp

        # batch A's OFFER still arrived (retired before the fail-close)
        got = ring.tx_pop()
        assert got is not None
        assert dhcp_codec.decode(
            packets.decode(got[0]).payload).msg_type == dhcp_codec.OFFER
        # batch B was dropped fail-closed; no window leaked: ring drives on
        assert engine._inflight is None
        ring.rx_push(discover(3), from_access=True)
        assert engine.process_ring_pipelined(ring) == 0
        assert engine.flush_pipeline() == 1
        assert ring.tx_pop() is not None  # DISCOVER #3 answered
        assert ring.free_frames() > 0
        ring.close()



class TestFillPoolConcurrency:
    """The fill pool is MPMC (Vyukov per-slot sequences): wire, engine and
    slow-path threads all alloc/free frames concurrently (round-1 ADVICE:
    the SPSC cursors corrupted under exactly this pattern). Drive all three
    roles at once and assert frame conservation — a lost or doubled frame
    descriptor fails the accounting."""

    def test_three_thread_stress_conserves_frames(self):
        import threading
        import time

        from bng_tpu.runtime.ring import NativeRing, load_native

        if load_native() is None:
            import pytest

            pytest.skip("no C++ toolchain for the native ring")

        nframes = 256
        ring = NativeRing(nframes=nframes, frame_size=256, depth=64)
        stop = threading.Event()
        errors = []

        def wire():
            f = b"\x02" * 60
            while not stop.is_set():
                ring.rx_push(f, from_access=True)
                ring.tx_pop()
                ring.fwd_pop()

        def engine():
            B, slot = 32, 256
            out = np.zeros((B, slot), dtype=np.uint8)
            ln = np.zeros((B,), dtype=np.uint32)
            fl = np.zeros((B,), dtype=np.uint32)
            rng = np.random.default_rng(0)
            while not stop.is_set():
                n = ring.assemble(out, ln, fl)
                if n == 0:
                    continue
                verdict = rng.integers(0, 4, size=B).astype(np.uint8)
                ring.complete(verdict, out, ln, n)
                ring.tx_inject(b"\x03" * 64)

        def slow():
            while not stop.is_set():
                ring.slow_pop()

        threads = [threading.Thread(target=t, daemon=True)
                   for t in (wire, engine, slow)]
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=5)
            if t.is_alive():
                errors.append(f"{t} wedged")
        assert not errors

        # quiesce: drain every ring, then every frame must be back in fill
        B, slot = 64, 256
        out = np.zeros((B, slot), dtype=np.uint8)
        ln = np.zeros((B,), dtype=np.uint32)
        fl = np.zeros((B,), dtype=np.uint32)
        for _ in range(20):
            n = ring.assemble(out, ln, fl)
            if n:
                ring.complete(np.ones((B,), dtype=np.uint8), out, ln, n)  # DROP
            while ring.tx_pop() is not None:
                pass
            while ring.fwd_pop() is not None:
                pass
            while ring.slow_pop() is not None:
                pass
        assert ring.free_frames() == nframes, (
            f"frame leak/duplication: {ring.free_frames()}/{nframes} free, "
            f"stats={ring.stats()}")
        ring.close()


class TestDHCPClassify:
    """Ring-side control classification (BNG_DESC_F_DHCP_CTRL, bit1):
    IPv4/UDP dst:67 with 0-2 VLAN tags, parity between the C++ and PyRing
    classifiers — enables the engine's DHCP-only fast lane on all-control
    batches."""

    def _dhcp_frame(self, vlans=None):
        from bng_tpu.control import dhcp_codec, packets

        mac = bytes.fromhex("02c0ffee0031")
        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
        f = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                               p.encode().ljust(320, b"\x00"))
        if vlans:
            # insert 802.1Q/802.1ad tags after the MACs
            tags = b""
            ets = ([0x88A8, 0x8100] if len(vlans) == 2 else [0x8100])
            for et, vid in zip(ets, vlans):
                tags += et.to_bytes(2, "big") + vid.to_bytes(2, "big")
            f = f[:12] + tags + f[12:]
        return f

    def test_classifier_parity_and_tagging(self, ring_cls):
        from bng_tpu.control import packets
        from bng_tpu.runtime.ring import FLAG_DHCP_CTRL, classify_dhcp

        ring = ring_cls(nframes=64, frame_size=1024, depth=32)
        frames = [self._dhcp_frame(), self._dhcp_frame([100]),
                  self._dhcp_frame([100, 200])]
        data = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, 0x0A000002,
                                  0x08080808, 1234, 80, b"x")
        # port 67 but NOT DHCP (no BOOTP/magic): natable transit, not control
        port67 = packets.udp_packet(b"\x02" * 6, b"\x04" * 6, 0x0A000002,
                                    0x08080808, 1234, 67, b"y" * 300)
        # a fragment of a dst-67 flow: no parseable L4
        frag = bytearray(self._dhcp_frame())
        frag[20] = 0x20  # MF flag in the IPv4 frag word
        frag = bytes(frag)
        pushes = frames + [data, port67, frag]
        for f in pushes:
            assert ring.rx_push(f)
        # network-side DHCP must NOT classify (direction gate)
        assert ring.rx_push(self._dhcp_frame(), from_access=False)
        B = 8
        pkt = np.zeros((B, 1024), dtype=np.uint8)
        ln = np.zeros((B,), dtype=np.uint32)
        fl = np.zeros((B,), dtype=np.uint32)
        n = ring.assemble(pkt, ln, fl)
        assert n == 7
        want = [True, True, True, False, False, False, False]
        assert [(x & FLAG_DHCP_CTRL) != 0 for x in fl[:7]] == want
        # python-side classifier agrees bit-for-bit with what the ring set
        for i, f in enumerate(pushes):
            assert classify_dhcp(f) == (fl[i] & FLAG_DHCP_CTRL)
        ring.complete(np.zeros((n,), dtype=np.uint8), pkt, ln, n)

    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_all_control_batch_takes_fast_lane(self, ring_cls):
        ring = ring_cls(nframes=64, frame_size=1024, depth=32)
        eng_test = TestRingEngine()
        engine, server = eng_test._stack(ring)
        calls = {"dhcp": 0}
        orig = engine._run_dhcp_batch

        def spy(pkt, length, now):
            calls["dhcp"] += 1
            return orig(pkt, length, now)

        engine._run_dhcp_batch = spy
        # all-control batch -> fast lane
        assert ring.rx_push(self._dhcp_frame())
        assert engine.process_ring(ring) == 1
        assert calls["dhcp"] == 1
        # mixed batch -> fused step (spy not called again)
        from bng_tpu.control import packets
        assert ring.rx_push(self._dhcp_frame())
        assert ring.rx_push(packets.udp_packet(
            b"\x02" * 6, b"\x04" * 6, 0x0A000002, 0x08080808, 1234, 80, b"x"))
        assert engine.process_ring(ring) == 2
        assert calls["dhcp"] == 1
        # the slow path answered the DISCOVER both times (server reply TX'd)
        assert engine.stats.passed >= 2


class TestShardSteering:
    """Ring->shard subscriber steering (owner-routing at the host ring,
    the pkg/pool/peer.go:230-368 role): C++/PyRing decision parity, the
    affinity invariant (control plane and ring agree on the owner), the
    per-shard lane-range batch layout, and padding-lane accounting."""

    def _ip_frame(self, src_ip, dst_ip, vlans=None, sport=1234, dport=443):
        from bng_tpu.control import packets

        f = packets.udp_packet(b"\x02\xaa\x00\x00\x00\x07", b"\x04" * 6,
                               src_ip, dst_ip, sport, dport, b"p" * 64,
                               vlans=vlans)
        return f

    def _dhcp_frame(self, mac):
        from bng_tpu.control import dhcp_codec, packets

        p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
        return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                  p.encode().ljust(320, b"\x00"))

    def _corpus(self):
        rng = np.random.default_rng(0x51EE)
        frames = []
        for i in range(24):  # IPv4 up/down, 0-2 VLAN tags
            vl = [None, [100], [100, 200]][i % 3]
            frames.append(self._ip_frame(0x0A000000 + i, 0xCB007100 + (i % 4),
                                         vlans=vl))
        for i in range(4):  # DHCP control
            frames.append(self._dhcp_frame(bytes([2, 0xAA, 0, 0, 0, i])))
        frames.append(b"\x02" * 6 + b"\x04" * 6 + b"\x86\xdd" + b"\x00" * 60)
        frames.append(b"\x01\x02\x03")  # shorter than an Ethernet header
        frames.append(bytes(rng.integers(0, 256, size=200, dtype=np.uint8)))
        return frames

    @pytest.mark.skipif(not native_available, reason="no native lib")
    def test_shard_of_native_py_parity(self):
        from bng_tpu.runtime.ring import (FLAG_DHCP_CTRL, FLAG_FROM_ACCESS,
                                          classify_dhcp, shard_of)

        n = 8
        pub = {0xCB007100 + s % n: s for s in range(4)}
        nr = NativeRing(nframes=64, frame_size=2048, depth=32, n_shards=n)
        try:
            for ip, s in pub.items():
                assert nr.steer_pub_ip(ip, s)
            for f in self._corpus():
                for fa in (True, False):
                    fl = FLAG_FROM_ACCESS if fa else 0
                    if fa:
                        fl |= classify_dhcp(f)
                    assert nr.shard_of(f, fl) == shard_of(f, fl, n, pub), (
                        f[:20].hex(), fl)
        finally:
            nr.close()

    def test_steering_spec(self, ring_cls):
        """Upstream = FNV(src IP) % n; downstream = pub-IP owner, else
        FNV(dst IP) % n; DHCP/non-IP = FNV(src MAC) % n."""
        from bng_tpu.runtime.ring import FLAG_DHCP_CTRL, FLAG_FROM_ACCESS
        from bng_tpu.utils.net import fnv1a32

        n = 8
        r = ring_cls(nframes=64, frame_size=2048, depth=32, n_shards=n)
        assert r.steer_pub_ip(0xCB007105, 5)
        assert not r.steer_pub_ip(0xCB007106, n)  # shard out of range
        up = self._ip_frame(0x0A0000FE, 0xCB007105)
        assert (r.shard_of(up, FLAG_FROM_ACCESS)
                == fnv1a32(bytes([10, 0, 0, 0xFE])) % n)
        # downstream to the registered public IP -> owner shard 5
        down = self._ip_frame(0x01020304, 0xCB007105)
        assert r.shard_of(down, 0) == 5
        # downstream to an unregistered IP -> dst-IP hash
        down2 = self._ip_frame(0x01020304, 0x08080808)
        assert r.shard_of(down2, 0) == fnv1a32(bytes([8, 8, 8, 8])) % n
        # DHCP control + non-IPv4: src-MAC hash
        mac = bytes([2, 0xAA, 0, 0, 0, 9])
        dh = self._dhcp_frame(mac)
        assert (r.shard_of(dh, FLAG_FROM_ACCESS | FLAG_DHCP_CTRL)
                == fnv1a32(mac) % n)
        v6 = b"\x02" * 6 + mac + b"\x86\xdd" + b"\x00" * 60
        assert r.shard_of(v6, FLAG_FROM_ACCESS) == fnv1a32(mac) % n
        r.close()

    def test_assemble_sharded_lane_ranges_and_padding(self, ring_cls):
        """Shard i's frames land at rows i*b..; padding rows are zeroed and
        complete() recycles only real frames."""
        from bng_tpu.utils.net import fnv1a32

        n, b, slot = 4, 4, 256
        r = ring_cls(nframes=64, frame_size=512, depth=16, n_shards=n)
        # craft src IPs that steer to shards 1 and 3
        by_shard = {}
        ip = 0x0A000001
        while len(by_shard) < 2 or any(len(v) < 2 for v in by_shard.values()):
            s = fnv1a32(ip.to_bytes(4, "big")) % n
            if s in (1, 3):
                by_shard.setdefault(s, []).append(ip)
            ip += 1
            if len(by_shard.get(1, [])) >= 2 and len(by_shard.get(3, [])) >= 2:
                break
        frames = {s: [self._ip_frame(i, 0x08080808) for i in ips[:2]]
                  for s, ips in by_shard.items()}
        for s in (1, 3):
            for f in frames[s]:
                assert r.rx_push(f, from_access=True)
        out = np.full((n * b, slot), 0xEE, dtype=np.uint8)  # stale bytes
        ln = np.full((n * b,), 99, dtype=np.uint32)
        fl = np.full((n * b,), 99, dtype=np.uint32)
        got = r.assemble_sharded(out, ln, fl)
        assert got == 4
        for s in (1, 3):
            for k, f in enumerate(frames[s]):
                row = s * b + k
                assert ln[row] == len(f)
                assert bytes(out[row, : len(f)]) == f
        # padding rows: len 0, flags 0, bytes zeroed (no stale 0xEE)
        for row in (0, 1, 2 * b, 1 * b + 2, 3 * b + 3):
            assert ln[row] == 0 and fl[row] == 0
            assert not out[row].any()
        # complete with n = total rows; every verdict PASS
        r.complete(np.zeros((n * b,), dtype=np.uint8), out, ln, n * b)
        assert r.slow_pending() == 4  # only the real frames
        drained = 0
        while r.slow_pop() is not None:
            drained += 1
        assert drained == 4
        assert r.free_frames() == 64
        r.close()

    def test_assemble_sharded_overflow_stays_queued(self, ring_cls):
        from bng_tpu.utils.net import fnv1a32

        n, b = 2, 1
        r = ring_cls(nframes=64, frame_size=512, depth=16, n_shards=n)
        ip = 0x0A000001
        while fnv1a32(ip.to_bytes(4, "big")) % n != 1:
            ip += 1
        f = self._ip_frame(ip, 0x08080808)
        for _ in range(3):
            assert r.rx_push(f, from_access=True)
        out = np.zeros((n * b, 256), dtype=np.uint8)
        ln = np.zeros((n * b,), dtype=np.uint32)
        fl = np.zeros((n * b,), dtype=np.uint32)
        assert r.assemble_sharded(out, ln, fl) == 1  # region is 1 row
        assert r.shard_rx_pending(1) == 2  # the rest stay queued, in order
        r.complete(np.zeros((n * b,), dtype=np.uint8), out, ln, n * b)
        assert r.assemble_sharded(out, ln, fl) == 1
        r.complete(np.zeros((n * b,), dtype=np.uint8), out, ln, n * b)
        assert r.shard_rx_pending(1) == 1
        r.close()

    def test_assemble_sharded_empty_opens_no_window(self, ring_cls):
        r = ring_cls(nframes=64, frame_size=512, depth=16, n_shards=2)
        out = np.zeros((4, 256), dtype=np.uint8)
        ln = np.zeros((4,), dtype=np.uint32)
        fl = np.zeros((4,), dtype=np.uint32)
        assert r.assemble_sharded(out, ln, fl) == 0
        with pytest.raises(RuntimeError):
            r.complete(np.zeros((4,), dtype=np.uint8), out, ln, 4)
        r.close()
