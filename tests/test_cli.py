"""Tests for the composition root and CLI (cmd/bng parity)."""

import io
import json

import pytest

from bng_tpu.cli import (
    BNGApp, BNGConfig, load_config_file, main, resolve_secret, run_demo,
)


class TestConfig:
    def test_resolve_secret_prefers_file(self, tmp_path):
        f = tmp_path / "secret"
        f.write_text("s3cret\n")
        assert resolve_secret("inline", str(f)) == "s3cret"
        assert resolve_secret("inline", "") == "inline"

    def test_yaml_overlay_cli_wins(self, tmp_path):
        f = tmp_path / "bng.yaml"
        f.write_text("server-ip: 10.9.0.1\nlease-time: 600\n"
                     "nat-enabled: false\n")
        cfg = BNGConfig(server_ip="10.1.1.1")
        cfg = load_config_file(str(f), {"server_ip"}, cfg)
        assert cfg.server_ip == "10.1.1.1"  # CLI wins
        assert cfg.lease_time == 600  # YAML fills the rest
        assert cfg.nat_enabled is False

    def test_unknown_yaml_keys_ignored(self, tmp_path):
        f = tmp_path / "bng.yaml"
        f.write_text("bogus-key: 1\nlease-time: 120\n")
        cfg = load_config_file(str(f), set(), BNGConfig())
        assert cfg.lease_time == 120


class TestApp:
    def test_full_wiring(self):
        app = BNGApp(BNGConfig(ha_role="active", bgp_enabled=True))
        try:
            for name in ("fastpath", "antispoof", "walledgarden", "pools",
                         "nexus", "subscribers", "qos", "policies", "nat",
                         "nat_logger", "dhcp", "engine", "dhcpv6", "slaac",
                         "ha", "bgp", "metrics", "collector"):
                assert name in app.components, name
            st = app.stats()
            assert st["pools"][1]["size"] > 0
            assert st["engine"]["batches"] == 0
        finally:
            app.close()

    def test_minimal_wiring(self):
        app = BNGApp(BNGConfig(nat_enabled=False, qos_enabled=False,
                               walled_garden_enabled=False,
                               metrics_enabled=False, dhcpv6_enabled=False,
                               slaac_enabled=False))
        try:
            assert "nat_logger" not in app.components
            assert "walledgarden" not in app.components
            assert "metrics" not in app.components
            assert "dhcp" in app.components and "engine" in app.components
        finally:
            app.close()

    def test_dhcp_dora_through_app(self):
        """The composition root produces a working slow path end to end."""
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.utils.net import ip_to_u32, u32_to_ip

        def client_frame(mac, msg_type, **kw):
            pkt = dhcp_codec.build_request(mac, msg_type, **kw)
            return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                                      pkt.encode().ljust(320, b"\x00"))

        app = BNGApp(BNGConfig(pool_cidr="10.50.0.0/24"))
        try:
            dhcp = app.components["dhcp"]
            mac = bytes.fromhex("02deadbeef01")
            offer = dhcp.handle_frame(client_frame(
                mac, dhcp_codec.DISCOVER, xid=0x1234))
            assert offer is not None
            msg = dhcp_codec.decode(packets.decode(offer).payload)
            assert msg.yiaddr != 0
            ack = dhcp.handle_frame(client_frame(
                mac, dhcp_codec.REQUEST, xid=0x1235,
                requested_ip=msg.yiaddr,
                server_id=ip_to_u32(app.config.server_ip)))
            assert ack is not None
            ack_msg = dhcp_codec.decode(packets.decode(ack).payload)
            assert ack_msg.yiaddr == msg.yiaddr
            assert u32_to_ip(ack_msg.yiaddr).startswith("10.50.0.")
            # NAT hook fired: subscriber has a port block
            nat = app.components["nat"]
            assert nat.blocks.get(ack_msg.yiaddr) is not None
        finally:
            app.close()

    def test_metrics_collect_after_traffic(self):
        app = BNGApp(BNGConfig())
        try:
            app.components["collector"].collect_once()
            text = app.components["metrics"].expose()
            assert "bng_pool_utilization_ratio" in text
        finally:
            app.close()

    def test_yaml_multi_pool(self, tmp_path):
        f = tmp_path / "bng.yaml"
        f.write_text(
            "pools:\n"
            "  - cidr: 10.1.0.0/24\n    lease_time: 300\n"
            "  - cidr: 10.2.0.0/24\n    client_class: 2\n")
        cfg = load_config_file(str(f), set(), BNGConfig())
        app = BNGApp(cfg)
        try:
            assert len(app.components["pools"].pools) == 2
        finally:
            app.close()


class TestDemo:
    def test_demo_lifecycle(self):
        out = io.StringIO()
        results = run_demo(subscriber_count=4, out=out)
        assert results["provisioned"] == 4
        assert results["active"] == 2  # odd ONTs have subscriber records
        assert results["walled"] == 2
        text = out.getvalue()
        assert "ACTIVE" in text and "WALLED GARDEN" in text


class TestMain:
    def test_version(self, capsys):
        assert main(["version"]) == 0
        assert "bng-tpu" in capsys.readouterr().out

    def test_demo_command(self, capsys):
        assert main(["demo", "--subscribers", "2"]) == 0
        assert "demo complete" in capsys.readouterr().out

    def test_run_once_smoke(self, capsys):
        assert main(["run", "--once", "--no-metrics-enabled"]) == 0
        st = json.loads(capsys.readouterr().out)
        assert st["node_id"] == "bng0" and "engine" in st

    def test_stats_command(self, capsys):
        assert main(["stats"]) == 0
        assert "pools" in json.loads(capsys.readouterr().out)

    def test_cli_flag_override(self, capsys):
        assert main(["run", "--once", "--node-id", "edge-7",
                     "--no-nat-enabled"]) == 0
        st = json.loads(capsys.readouterr().out)
        assert st["node_id"] == "edge-7"


class TestClusteredRun:
    """Two real `bng-tpu run` processes clustering over HTTP (the round-2
    verdict's done-criterion for real transports)."""

    def test_active_process_serves_standby_and_failover(self):
        import re
        import subprocess
        import sys
        import time

        from bng_tpu.control.cluster_http import HTTPActiveProxy
        from bng_tpu.control.ha import InMemorySessionStore, StandbySyncer

        import os

        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}  # child must never claim the TPU
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "bng_tpu.cli", "run",
             "--ha-role", "active", "--cluster-listen", "127.0.0.1:0",
             "--no-metrics-enabled", "--no-nat-enabled",
             "--no-dhcpv6-enabled", "--no-slaac-enabled"],
            stderr=subprocess.PIPE, text=True, env=env)
        try:
            url = None
            t0 = time.time()
            while time.time() - t0 < 60:
                line = proc.stderr.readline()
                m = re.search(r"cluster on (http://\S+)", line or "")
                if m:
                    url = m.group(1)
                    break
            assert url, "active never announced its cluster listener"

            store = InMemorySessionStore()
            standby = StandbySyncer(store, transport=lambda: HTTPActiveProxy(
                url, on_stream_end=lambda: standby.disconnect()))
            standby.tick(now=0.0)
            assert standby.connected  # full sync from the other process
            assert standby.stats["full_syncs"] == 1

            # active process dies -> stream ends -> standby reconnect loop
            proc.terminate()
            proc.wait(timeout=10)
            t0 = time.time()
            while standby.connected and time.time() - t0 < 10:
                time.sleep(0.05)
            assert not standby.connected
            standby.tick(now=5.0)  # retry fails, backoff continues
            assert not standby.connected
        finally:
            if proc.poll() is None:
                proc.kill()


class TestWireDrive:
    def test_synthetic_source_drives_engine(self):
        """`run --synthetic-subs N` beats: DISCOVERs ride the ring through
        the pipelined engine; first pass slow-path OFFERs, then cached
        device replies once the fast path warms."""
        app = BNGApp(BNGConfig(synthetic_subs=4, batch_size=16,
                               metrics_enabled=False, dhcpv6_enabled=False,
                               slaac_enabled=False, nat_enabled=True))
        try:
            att = app.components["wire_attachment"]
            assert att.mode == "memory"  # no NIC in CI: stub rung
            total = 0
            for _ in range(8):
                total += app.drive_once()
            eng = app.components["engine"]
            ring = app.components["ring"]
            eng.flush_pipeline()
            assert eng.stats.batches >= 2
            # every synthetic DISCOVER got an answer: slow path at first
            # (passed), device replies (tx) once cached
            assert eng.stats.passed > 0
            assert ring.tx_pending() > 0  # OFFERs queued for the wire
        finally:
            app.close()

    def test_synthetic_source_drives_scheduler(self):
        """`run --scheduler-enabled` beats: the tiered scheduler owns the
        loop — DISCOVERs classify to the express lane, OFFER replies land
        on the TX ring, per-lane stats count dispatches."""
        app = BNGApp(BNGConfig(synthetic_subs=4, batch_size=16,
                               scheduler_enabled=True,
                               sched_express_batch=16,
                               sched_express_max_wait_us=0.0,  # ship every beat
                               metrics_enabled=False, dhcpv6_enabled=False,
                               slaac_enabled=False, nat_enabled=True))
        try:
            sched = app.components["scheduler"]
            ring = app.components["ring"]
            assert hasattr(ring, "rx_pop")  # scheduler got a PyRing
            for _ in range(8):
                app.drive_once()
            snap = sched.stats_snapshot()
            assert snap["express"]["batches"] >= 1
            assert snap["express"]["frames_dispatched"] > 0
            assert sched.bulk.stats.enqueued == 0  # pure-DHCP source
            assert ring.tx_pending() > 0  # OFFERs queued for the wire
        finally:
            app.close()

    def test_no_ring_drive_is_noop(self):
        app = BNGApp(BNGConfig(metrics_enabled=False, dhcpv6_enabled=False,
                               slaac_enabled=False))
        try:
            assert app.components.get("ring") is None
            assert app.drive_once() == 0
        finally:
            app.close()


def _wire_rung_possible():
    try:
        from bng_tpu.runtime import xdp_redirect, xsk
        from tests.test_xsk import _veth_ok

        return (xsk.probe() != "unavailable" and xdp_redirect.probe()
                and _veth_ok())
    except Exception:
        return False


@pytest.mark.skipif(not _wire_rung_possible(),
                    reason="needs CAP_NET_ADMIN + AF_XDP + CAP_BPF")
class TestAppOnLiveWire:
    """The WHOLE app on a real veth: BNGApp binds AF_XDP copy mode, loads
    the redirect program through the kernel verifier, and answers a DHCP
    DISCOVER that arrives through the actual kernel — the closest thing
    to the reference's in-kernel XDP_TX this container can host."""

    IF_A, IF_B = "bngct0", "bngct1"

    # compile-heavy veth e2e (~38s); tier-1 keeps the memory-rung wire
    # twin (test_wire_pump) and TestWireDrive — slow tier runs this one
    @pytest.mark.slow
    def test_dora_over_kernel_wire(self):
        import socket as so
        import subprocess
        import time as _time

        from bng_tpu.cli import BNGApp, BNGConfig
        from bng_tpu.control import dhcp_codec, packets

        subprocess.run(["ip", "link", "del", self.IF_A], capture_output=True)
        subprocess.run(["ip", "link", "add", self.IF_A, "type", "veth",
                        "peer", "name", self.IF_B], check=True,
                       capture_output=True)
        for i in (self.IF_A, self.IF_B):
            subprocess.run(["ip", "link", "set", i, "up"],
                           check=True, capture_output=True)
        _time.sleep(0.3)
        app = None
        tx = rx = None
        try:
            app = BNGApp(BNGConfig(wire_if=self.IF_A, pool_cidr="10.9.0.0/24"))
            att = app.components["wire_attachment"]
            assert att.mode == "copy", (att.mode, att.detail)  # real rung
            assert "xdp_redirect" in app.components

            mac = bytes.fromhex("02c11e000001")
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER, xid=0x42)
            p.options.append((dhcp_codec.OPT_PARAM_REQ_LIST,
                              bytes([1, 3, 6, 51, 54])))
            disc = packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68,
                                      67, p.encode().ljust(320, b"\x00"))
            tx = so.socket(so.AF_PACKET, so.SOCK_RAW)
            tx.bind((self.IF_B, 0))
            rx = so.socket(so.AF_PACKET, so.SOCK_RAW, so.htons(0x0003))
            rx.bind((self.IF_B, 0))
            rx.settimeout(0.05)
            # first beat feeds the kernel fill ring (before it, the
            # redirect has nowhere to put frames) and compiles the step
            app.drive_once()

            offer = None
            last_send = 0.0
            deadline = _time.time() + 90
            while _time.time() < deadline and offer is None:
                if _time.time() - last_send > 0.5:  # clients retransmit
                    tx.send(disc)
                    last_send = _time.time()
                app.drive_once()
                try:
                    data = rx.recv(4096)
                except TimeoutError:
                    continue
                # replies to a broadcast DISCOVER go to ff:ff... —
                # match on BOOTP op/xid, not the L2 destination
                if len(data) > 280 and data[0:6] in (mac, b"\xff" * 6):
                    try:
                        reply = dhcp_codec.decode(data[42:])
                    except Exception:
                        continue
                    if reply.op == 2 and reply.xid == 0x42:
                        offer = reply
            assert offer is not None, "no OFFER came back through the kernel"
            assert offer.yiaddr != 0
            assert offer.opt(dhcp_codec.OPT_MSG_TYPE) == bytes(
                [dhcp_codec.OFFER])
        finally:
            if tx:
                tx.close()
            if rx:
                rx.close()
            if app:
                app.close()
            subprocess.run(["ip", "link", "del", self.IF_A],
                           capture_output=True)


class TestPPPoEThroughApp:
    """PPPoE in the composition root (VERDICT r4 missing #1): PADI ->
    PADS -> LCP -> CHAP -> IPCP negotiated over the ring via
    App.drive_once(), then the first DATA packet NATs on the device.
    Reference wiring: cmd/bng/main.go:1063-1180 + pkg/pppoe/server.go."""

    def _app(self, clock=None):
        from bng_tpu.runtime.ring import PyRing

        cfg = BNGConfig(
            pppoe_enabled=True, pppoe_auth="chap",
            pppoe_users=[{"username": "alice", "password": "secret123"}],
            dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False, metrics_enabled=False,
            batch_size=8)
        app = BNGApp(cfg, **({"clock": clock} if clock else {}))
        ring = PyRing(nframes=128, frame_size=2048, depth=32)
        app.components["ring"] = ring
        return app, ring

    def _mk_client(self, app, ring):
        from tests.test_pppoe import SimClient

        class RingClient(SimClient):
            def _pump(cli, frames, now):
                pending = list(frames)
                while pending:
                    for f in pending:
                        assert ring.rx_push(f, from_access=True)
                    pending = []
                    for _ in range(4):  # pipelined loop needs extra beats
                        app.drive_once()
                    while (got := ring.tx_pop()) is not None:
                        pending.extend(cli._react(got[0], now))

        return RingClient(app.components["pppoe"])

    @pytest.mark.slow  # compile-heavy; tier-1 runs -m 'not slow'
    def test_chap_negotiation_then_device_nat(self):
        from bng_tpu.control import packets
        from bng_tpu.control.pppoe import codec
        from bng_tpu.ops import pppoe as P
        from bng_tpu.utils.net import ip_to_u32

        app, ring = self._app()
        try:
            cli = self._mk_client(app, ring)
            cli.connect()
            assert cli.session_id != 0
            assert cli.ipcp_done and cli.ip != 0
            # OPEN session published to the device tables
            pp = app.components["pppoe_tables"]
            assert pp.by_sid.count == 1 and pp.by_ip.count == 1
            # and the subscriber got NAT + QoS provisioned (open hooks)
            assert app.components["nat"].blocks.get(cli.ip) is not None

            # ---- session data: inner IPv4 to the WAN ----
            inner = packets.udp_packet(
                cli.mac, bytes.fromhex("02aabbccdd01"), cli.ip,
                ip_to_u32("8.8.8.8"), 40000, 53, b"q" * 16)[14:]
            data = codec.eth_frame(
                app.components["pppoe"].config.server_mac, cli.mac,
                codec.ETH_PPPOE_SESSION,
                codec.PPPoEPacket(code=0, session_id=cli.session_id,
                                  payload=codec.ppp_frame(P.PPP_IPV4,
                                                          inner)).encode())
            fwd = None
            for _ in range(6):  # pkt 1 punts (session create), pkt 2 FWDs
                assert ring.rx_push(data, from_access=True)
                for _ in range(3):
                    app.drive_once()
                got = ring.fwd_pop()
                if got is not None:
                    fwd = got[0]
                    break
            assert fwd is not None, "PPPoE data never fast-pathed"
            d = packets.decode(fwd)
            assert d.ethertype == 0x0800  # decapped on device
            assert d.src_ip == ip_to_u32("203.0.113.1")  # SNAT applied
        finally:
            app.close()

    def test_tick_emits_keepalives_to_ring(self):
        import itertools

        t = itertools.count(1000.0, 0.0)  # frozen clock we control below

        class Clock:
            now = 1000.0

            def __call__(self):
                return Clock.now

        app, ring = self._app(clock=Clock())
        try:
            cli = self._mk_client(app, ring)
            cli.connect(now=Clock.now)
            assert cli.session_id != 0 and cli.ipcp_done
            # drain anything left on TX before the tick
            while ring.tx_pop() is not None:
                pass
            Clock.now += 31.0  # past echo_interval_s=30
            app.tick()
            from bng_tpu.control.pppoe.codec import (ETH_PPPOE_SESSION,
                                                     PPPoEPacket, parse_ppp)
            seen = []
            while (got := ring.tx_pop()) is not None:
                frame = got[0]
                if int.from_bytes(frame[12:14], "big") != ETH_PPPOE_SESSION:
                    continue
                seen.append(parse_ppp(PPPoEPacket.decode(frame[14:]).payload))
            # among the tick's frames (IPV6CP retransmits may precede it)
            # is the LCP Echo-Request keepalive
            assert any(proto == 0xC021 and body[0] == 9
                       for proto, body in seen), seen
        finally:
            app.close()


class TestMaintenanceHeartbeat:
    """App.tick drives the reference's periodic goroutines (VERDICT r4
    missing #2): lease cleanup (pkg/dhcp/server.go:1100-1163) and NAT
    session expiry (bpf/nat44.c:49-53 timeouts) actually fire in a
    production run — an expired lease stops fast-pathing and an idle NAT
    session leaves the device table without a restart."""

    # compile-heavy (~25s: garden-off app is its own trace) + long tick
    # body; lease/NAT aging stays proven by test_e2e expiry + the storm
    # suite's expire_batch drives — slow tier runs the app-level twin
    @pytest.mark.slow
    def test_expired_lease_and_idle_nat_age_out(self):
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.utils.net import ip_to_u32

        class Clock:
            now = 2_000_000.0

            def __call__(self):
                return Clock.now

        app = BNGApp(BNGConfig(
            metrics_enabled=False, dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False, lease_time=300), clock=Clock())
        try:
            engine = app.components["engine"]
            dhcp = app.components["dhcp"]
            nat = app.components["nat"]
            mac = bytes.fromhex("02beef000001")

            def client_frame(msg_type, **kw):
                pkt = dhcp_codec.build_request(mac, msg_type, **kw)
                return packets.udp_packet(
                    mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                    pkt.encode().ljust(320, b"\x00"))

            # DORA -> lease + fast path + NAT block
            engine.process([client_frame(dhcp_codec.DISCOVER)])
            r = engine.process([client_frame(
                dhcp_codec.REQUEST, requested_ip=0,
                server_id=ip_to_u32(app.config.server_ip))])
            ack = dhcp_codec.decode(packets.decode(r["slow"][0][1]).payload)
            ip = ack.yiaddr
            assert dhcp.leases and nat.blocks.get(ip) is not None
            # device now answers DISCOVER
            assert len(engine.process([client_frame(dhcp_codec.DISCOVER)])["tx"]) == 1

            # data flow -> NAT session (punt creates, second forwards)
            data = packets.udp_packet(mac, bytes.fromhex("02aabbccdd01"),
                                      ip, ip_to_u32("8.8.8.8"), 40000, 53,
                                      b"x" * 16)
            engine.process([data])
            assert nat.sessions.count > 0
            assert len(engine.process([data])["fwd"]) == 1

            # idle past lease(300) + NAT UDP timeout -> ONE tick reaps both
            Clock.now += 400.0
            app.tick()
            assert dhcp.leases == {}, "lease cleanup never fired"
            assert nat.sessions.count == 0, "NAT sessions never expired"
            # the fast path no longer answers: DISCOVER goes slow again
            r2 = engine.process([client_frame(dhcp_codec.DISCOVER)])
            assert r2["tx"] == [] and len(r2["slow"]) == 1
        finally:
            app.close()


class TestNexusPeerResilienceWiring:
    """The rest of runBNG's construction order (main.go:628-756): Nexus
    HTTPAllocator feeding the DHCP allocation cascade, the peer pool on
    the cluster wire, and the resilience partition FSM driven by
    App.tick — all reachable from `bng run` flags."""

    def _nexus(self):
        """A mini central Nexus: our own ClusterServer + allocator mount."""
        from bng_tpu.control.cluster_http import ClusterServer

        class Backend:
            def __init__(self):
                self.ips = {}
                self.next = 10
                # heal-time conflict view: ip_str -> (subscriber, at)
                self.by_ip = {}

            def allocate(self, subscriber_id, pool_hint):
                if subscriber_id not in self.ips:
                    self.ips[subscriber_id] = f"10.77.0.{self.next}"
                    self.next += 1
                return self.ips[subscriber_id]

            def lookup(self, sid):
                return self.ips.get(sid)

            def lookup_by_ip(self, ip):
                return self.by_ip.get(ip)

            def release(self, sid):
                return self.ips.pop(sid, None) is not None

            def pool_info(self):
                return {"pools": []}

        backend = Backend()
        srv = ClusterServer().mount_allocator(backend).start()
        return srv, backend

    def test_nexus_first_allocation_then_partition_fallback(self):
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.control.resilience import PartitionState
        from bng_tpu.utils.net import u32_to_ip

        class Clock:
            now = 5_000_000.0

            def __call__(self):
                return Clock.now

        srv, backend = self._nexus()
        app = BNGApp(BNGConfig(
            nexus_url=srv.url, pool_cidr="10.77.0.0/16",
            metrics_enabled=False, dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False, nat_enabled=False,
            qos_enabled=False), clock=Clock())
        try:
            assert "nexus_allocator" in app.components
            assert "resilience" in app.components
            dhcp = app.components["dhcp"]
            mac = bytes.fromhex("02ae00000001".zfill(12))

            def discover():
                p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
                return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF,
                                          68, 67,
                                          p.encode().ljust(320, b"\x00"))

            # allocation rides Nexus FIRST: the offered IP is the
            # backend's answer, reserved in the matching local pool
            offer = dhcp_codec.decode(
                packets.decode(dhcp.handle_frame(discover())).payload)
            assert u32_to_ip(offer.yiaddr) == backend.ips[mac.hex()]

            # Nexus dies -> FSM partitions after threshold ticks
            srv.close()
            for i in range(4):
                Clock.now += 6.0
                app.tick()
            res = app.components["resilience"]
            assert res.state == PartitionState.PARTITIONED
            # allocation still works (local pool, no per-DISCOVER timeout)
            mac = bytes.fromhex("02ae00000002")
            offer2 = dhcp_codec.decode(
                packets.decode(dhcp.handle_frame(discover())).payload)
            assert offer2.yiaddr != 0
            # commit the lease: the partition-time allocation is recorded
            # for heal-time conflict resolution (hook fires on ACK)
            from bng_tpu.utils.net import ip_to_u32 as _ip32
            req = dhcp_codec.build_request(
                mac, dhcp_codec.REQUEST, requested_ip=offer2.yiaddr,
                server_id=_ip32(app.config.server_ip))
            ack = dhcp.handle_frame(packets.udp_packet(
                mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                req.encode().ljust(320, b"\x00")))
            assert ack is not None
            assert res.conflicts.count == 1

            # ---- heal WITH a conflict: the central store claims the
            # partition-allocated IP belongs to someone ELSE (earlier
            # timestamp wins -> our local lease is the loser and gets
            # force-renumbered, manager.go:342-528) ----
            from bng_tpu.utils.net import u32_to_ip
            from bng_tpu.control.cluster_http import ClusterServer as _CS

            backend.by_ip[u32_to_ip(offer2.yiaddr)] = ("other-node-sub",
                                                       Clock.now - 9999.0)
            srv2 = _CS(srv.host, srv.port).mount_allocator(backend).start()
            try:
                for _ in range(4):
                    Clock.now += 6.0
                    app.tick()
                from bng_tpu.control.resilience import PartitionState as _PS
                assert res.state == _PS.NORMAL
                assert res.events.conflicts_found == 1
                assert res.events.renumbered == 1
                # the loser lease is GONE: the client will re-DORA
                assert dhcp.leases == {}
            finally:
                srv2.close()
        finally:
            app.close()
            srv.close()

    def test_peer_pool_forward_through_app(self):
        from bng_tpu.control.cluster_http import ClusterServer
        from bng_tpu.control.peerpool import PeerPool, PoolRange

        # a real remote peer: bare PeerPool mounted on its own listener
        remote = PeerPool("n2", ["n1", "n2"],
                          PoolRange(network=0x0A640001, size=500))
        remote_srv = ClusterServer().mount_pool(remote).start()

        app = BNGApp(BNGConfig(
            node_id="n1", cluster_listen="127.0.0.1:0",
            peer_pool_cidr="10.100.0.0/23",
            peer_pool_nodes=[{"node": "n1", "url": "http://unused:1"},
                             {"node": "n2", "url": remote_srv.url}],
            metrics_enabled=False, dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False))
        try:
            pool = app.components["peerpool"]
            # our own listener serves the pool endpoints too
            assert app.components["cluster_server"].pool is pool
            # a subscriber owned by n2 forwards over real HTTP
            sub = next(s for s in (f"sub{i}" for i in range(100))
                       if pool.owner_ranked(s)[0] == "n2")
            ip = pool.allocate(sub)
            assert pool.stats["forwarded"] == 1
            assert remote.by_subscriber[sub] == ip
            app.tick()  # drives health_check without error
        finally:
            app.close()
            remote_srv.close()

    def test_degraded_auth_serves_cached_profile(self):
        """RADIUS outage: a subscriber who authenticated before keeps
        working from the cached profile (radius_handler.go role); a fresh
        subscriber does not. Auth fires on REQUEST when no lease exists,
        so the outage case needs the lease expired first."""
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.control.radius import packet as rp
        from bng_tpu.utils.net import ip_to_u32 as _ip32
        from tests.test_radius import FakeRadiusServer

        class Clock:
            now = 6_000_000.0

            def __call__(self):
                return Clock.now

        srv, _ = self._nexus()  # resilience needs a nexus health signal
        app = BNGApp(BNGConfig(
            nexus_url=srv.url, lease_time=300,
            radius_server="10.0.0.5:1812", radius_secret="s3cr3t",
            metrics_enabled=False, dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False, nat_enabled=False), clock=Clock())
        try:
            radius = app.components["radius"]
            radius.transport = FakeRadiusServer(users={
                "": {"password": "", "attrs": [(rp.FILTER_ID, "gold")]}})
            dhcp = app.components["dhcp"]
            mac = bytes.fromhex("02aa00000001")

            def dora(m):
                p = dhcp_codec.build_request(m, dhcp_codec.DISCOVER)
                offer = dhcp.handle_frame(packets.udp_packet(
                    m, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                    p.encode().ljust(320, b"\x00")))
                if offer is None:
                    return None
                omsg = dhcp_codec.decode(packets.decode(offer).payload)
                r = dhcp_codec.build_request(
                    m, dhcp_codec.REQUEST, requested_ip=omsg.yiaddr,
                    server_id=_ip32(app.config.server_ip))
                return dhcp.handle_frame(packets.udp_packet(
                    m, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                    r.encode().ljust(320, b"\x00")))

            assert dora(mac) is not None  # auth OK -> profile cached
            # lease expires, then the RADIUS outage begins
            Clock.now += 400.0
            app.tick()
            assert dhcp.leases == {}
            radius.transport = lambda *a: None  # timeout everywhere
            # known subscriber: re-auth times out -> cached profile serves
            assert dora(mac) is not None
            stats = app.components["resilience"].radius_handler.stats
            assert stats["cache_hits"] == 1
            # known subscriber's reply is a real ACK
            # unknown subscriber: no cache -> NAK
            nak = dora(bytes.fromhex("02aa00000099"))
            if nak is not None:
                msg = dhcp_codec.decode(packets.decode(nak).payload)
                assert msg.msg_type == dhcp_codec.NAK
        finally:
            app.close()
            srv.close()


class TestCoAThroughApp:
    """RFC 5176 dynamic authorization reaches both session kinds from
    `bng run` (cmd/bng wiring of coa.go + coa_handler.go): a Disconnect
    tears down a live PPPoE session (PADT to the wire) and a CoA
    policy change rewrites a DHCP subscriber's device QoS row."""

    def _coa_send(self, app, pkt_bytes):
        import socket as so

        coa = app.components["coa"]
        s = so.socket(so.AF_INET, so.SOCK_DGRAM)
        s.settimeout(3.0)
        s.sendto(pkt_bytes, ("127.0.0.1", coa.addr[1]))
        data, _ = s.recvfrom(4096)
        s.close()
        return data

    def test_disconnect_pppoe_and_coa_dhcp_policy(self):
        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.control.radius import packet as rp
        from bng_tpu.control.radius.packet import (RadiusPacket,
                                                   new_request_authenticator)
        from bng_tpu.runtime.ring import PyRing
        from bng_tpu.utils.net import ip_to_u32
        from tests.test_pppoe import SimClient

        app = BNGApp(BNGConfig(
            pppoe_enabled=True, pppoe_auth="chap",
            pppoe_users=[{"username": "alice", "password": "secret123"}],
            radius_server="10.0.0.5:1812", radius_secret="s3cr3t",
            coa_listen="127.0.0.1:0",
            dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False, metrics_enabled=False,
            batch_size=8))
        try:
            # RADIUS auth server is unreachable; PPPoE falls back? No —
            # with radius configured the verifier is RADIUS-backed, so
            # use a working fake transport for the CHAP exchange
            from tests.test_radius import FakeRadiusServer
            app.components["radius"].transport = FakeRadiusServer(users={
                "alice": {"password": "secret123"},
                "": {"password": ""}})  # MAC-auth DHCP subscribers

            ring = PyRing(nframes=128, frame_size=2048, depth=32)
            app.components["ring"] = ring

            class RingClient(SimClient):
                def _pump(cli, frames, now):
                    pending = list(frames)
                    while pending:
                        for f in pending:
                            assert ring.rx_push(f, from_access=True)
                        pending = []
                        for _ in range(4):
                            app.drive_once()
                        while (got := ring.tx_pop()) is not None:
                            pending.extend(cli._react(got[0], now))

            cli = RingClient(app.components["pppoe"])
            cli.connect()
            assert cli.session_id and cli.ipcp_done

            # ---- Disconnect-Request by Framed-IP over the REAL socket
            req = RadiusPacket(rp.DISCONNECT_REQUEST, 7)
            req.add(rp.FRAMED_IP_ADDRESS, cli.ip)
            data = self._coa_send(app, req.encode(b"s3cr3t"))
            resp = RadiusPacket.decode(data)
            assert resp.code == rp.DISCONNECT_ACK
            assert app.components["pppoe"].sessions.get(cli.session_id) is None
            # the PADT rides the demux pending queue to the TX ring
            for _ in range(2):
                app.drive_once()
            padt_seen = False
            from bng_tpu.control.pppoe.codec import (CODE_PADT,
                                                     ETH_PPPOE_DISCOVERY,
                                                     PPPoEPacket)
            while (got := ring.tx_pop()) is not None:
                f = got[0]
                if int.from_bytes(f[12:14], "big") == ETH_PPPOE_DISCOVERY:
                    if PPPoEPacket.decode(f[14:]).code == CODE_PADT:
                        padt_seen = True
            assert padt_seen, "no PADT reached the wire"

            # ---- CoA policy change for a DHCP subscriber ----
            dhcp = app.components["dhcp"]
            mac = bytes.fromhex("02cc00000001")
            p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
            offer = dhcp.handle_frame(packets.udp_packet(
                mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                p.encode().ljust(320, b"\x00")))
            o = dhcp_codec.decode(packets.decode(offer).payload)
            r = dhcp_codec.build_request(
                mac, dhcp_codec.REQUEST, requested_ip=o.yiaddr,
                server_id=ip_to_u32(app.config.server_ip))
            assert dhcp.handle_frame(packets.udp_packet(
                mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                r.encode().ljust(320, b"\x00"))) is not None

            coa = RadiusPacket(rp.COA_REQUEST, 9)
            coa.add(rp.FRAMED_IP_ADDRESS, o.yiaddr)
            coa.add(rp.FILTER_ID, "business-100mbps")
            data = self._coa_send(app, coa.encode(b"s3cr3t"))
            assert RadiusPacket.decode(data).code == rp.COA_ACK
            # device QoS row carries the new policy's rate
            qos = app.components["qos"]
            row = qos.down.lookup(o.yiaddr)
            pol = app.components["policies"].get("business-100mbps")
            assert row is not None and pol is not None
            assert row["rate_bps"] == pol.download_bps
            assert row["priority"] == pol.priority
        finally:
            app.close()

    def test_coa_reaches_fleet_owned_lease(self):
        """ISSUE 19: when the slow-path fleet serves, DHCPv4 leases
        live in the workers — the CoA locators fall through the parent
        books to the MAC-steered shard, a policy change lands on the
        owning worker's lease, and a Disconnect force-expires it."""
        from bng_tpu.control.radius import packet as rp
        from bng_tpu.control.radius.packet import RadiusPacket
        from tests.test_fleet import dora, mac_of
        from tests.test_radius import FakeRadiusServer

        app = BNGApp(BNGConfig(
            slowpath_workers=2, slowpath_worker_mode="inline",
            radius_server="10.0.0.5:1812", radius_secret="s3cr3t",
            coa_listen="127.0.0.1:0",
            dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False, metrics_enabled=False,
            batch_size=8))
        try:
            assert app.fleet_blockers == []  # radius no longer blocks
            fleet = app.components["fleet"]
            fake = FakeRadiusServer(users={"": {"password": ""}})
            app.components["radius"].transport = fake
            for w in fleet._inline:
                w.radius.transport = fake
            mac = mac_of(1)
            leased = dora(fleet, [mac])
            ip = leased[mac]
            assert app.components["dhcp"].leases == {}  # parent empty

            coa = RadiusPacket(rp.COA_REQUEST, 11)
            coa.add(rp.FRAMED_IP_ADDRESS, ip)
            coa.add(rp.FILTER_ID, "business-100mbps")
            data = self._coa_send(app, coa.encode(b"s3cr3t"))
            assert RadiusPacket.decode(data).code == rp.COA_ACK
            from bng_tpu.control.fleet import shard_for_mac
            owner = fleet._inline[shard_for_mac(mac, 2)]
            lease = next(iter(owner.server.leases.values()))
            assert lease.qos_policy == "business-100mbps"

            req = RadiusPacket(rp.DISCONNECT_REQUEST, 12)
            req.add(rp.FRAMED_IP_ADDRESS, ip)
            data = self._coa_send(app, req.encode(b"s3cr3t"))
            assert RadiusPacket.decode(data).code == rp.DISCONNECT_ACK
            assert owner.server.leases == {}
            assert fleet.coa_handled >= 2
        finally:
            app.close()


class TestHAFedBySessions:
    """VERDICT-grade gap closed in round 5: the active's HA syncer is FED
    by real session lifecycles — a DORA on the active appears in the
    standby's replicated store (with NAT block fields), and the lease's
    release deletes it. Previously ActiveSyncer replicated an
    always-empty store in a production run."""

    def test_lease_lifecycle_replicates_to_standby(self):
        import time as _time

        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.utils.net import ip_to_u32

        active = BNGApp(BNGConfig(
            ha_role="active", cluster_listen="127.0.0.1:0",
            metrics_enabled=False, dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False))
        standby = None
        try:
            url = active.components["cluster_server"].url
            standby = BNGApp(BNGConfig(
                ha_role="standby", ha_peer=url,
                metrics_enabled=False, dhcpv6_enabled=False,
                slaac_enabled=False, walled_garden_enabled=False))
            standby.tick()
            assert standby.components["ha"].connected

            dhcp = active.components["dhcp"]
            mac = bytes.fromhex("02ha00000001".replace("h", "b"))

            def frame(msg, **kw):
                p = dhcp_codec.build_request(mac, msg, **kw)
                return packets.udp_packet(mac, b"\xff" * 6, 0, 0xFFFFFFFF,
                                          68, 67,
                                          p.encode().ljust(320, b"\x00"))

            offer = dhcp_codec.decode(packets.decode(
                dhcp.handle_frame(frame(dhcp_codec.DISCOVER))).payload)
            assert dhcp.handle_frame(frame(
                dhcp_codec.REQUEST, requested_ip=offer.yiaddr,
                server_id=ip_to_u32(active.config.server_ip))) is not None
            sid = next(iter(dhcp.leases.values())).session_id

            # the session rides the SSE wire into the standby's store
            store = standby.components["ha_store"]
            for _ in range(100):
                if store.get(sid) is not None:
                    break
                _time.sleep(0.05)
            repl = store.get(sid)
            assert repl is not None, "session never replicated"
            assert repl.ip == offer.yiaddr and repl.mac == mac.hex()
            assert repl.session_kind == "ipoe"
            assert repl.nat_public_ip != 0  # NAT block fields rode along

            # release -> delete delta reaches the standby
            rel = dhcp_codec.build_request(mac, dhcp_codec.RELEASE,
                                           ciaddr=offer.yiaddr)
            dhcp.handle_frame(packets.udp_packet(
                mac, b"\xff" * 6, offer.yiaddr,
                ip_to_u32(active.config.server_ip), 68, 67,
                rel.encode().ljust(320, b"\x00")))
            for _ in range(100):
                if store.get(sid) is None:
                    break
                _time.sleep(0.05)
            assert store.get(sid) is None, "release never replicated"
        finally:
            if standby is not None:
                standby.close()
            active.close()

    def test_renewal_and_coa_repush_track_in_standby(self):
        """Renewals re-push (stale lease_expiry on the standby = failover
        treats live subscribers as expired) and a CoA policy change
        re-pushes with the new plan."""
        import time as _time

        from bng_tpu.control import dhcp_codec, packets
        from bng_tpu.control.radius import packet as rp
        from bng_tpu.control.radius.packet import RadiusPacket
        from bng_tpu.utils.net import ip_to_u32
        from tests.test_radius import FakeRadiusServer

        class Clock:
            now = 8_000_000.0

            def __call__(self):
                return Clock.now

        active = BNGApp(BNGConfig(
            ha_role="active", cluster_listen="127.0.0.1:0",
            radius_server="10.0.0.5:1812", radius_secret="s3cr3t",
            coa_listen="127.0.0.1:0", lease_time=600,
            metrics_enabled=False, dhcpv6_enabled=False, slaac_enabled=False,
            walled_garden_enabled=False), clock=Clock())
        standby = None
        try:
            active.components["radius"].transport = FakeRadiusServer(
                users={"": {"password": ""}})
            url = active.components["cluster_server"].url
            standby = BNGApp(BNGConfig(
                ha_role="standby", ha_peer=url, metrics_enabled=False,
                dhcpv6_enabled=False, slaac_enabled=False,
                walled_garden_enabled=False))
            standby.tick()
            store = standby.components["ha_store"]
            dhcp = active.components["dhcp"]
            mac = bytes.fromhex("02ba00000077")

            def request():
                p = dhcp_codec.build_request(mac, dhcp_codec.DISCOVER)
                offer = dhcp_codec.decode(packets.decode(
                    dhcp.handle_frame(packets.udp_packet(
                        mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                        p.encode().ljust(320, b"\x00")))).payload)
                r = dhcp_codec.build_request(
                    mac, dhcp_codec.REQUEST, requested_ip=offer.yiaddr,
                    server_id=ip_to_u32(active.config.server_ip))
                dhcp.handle_frame(packets.udp_packet(
                    mac, b"\xff" * 6, 0, 0xFFFFFFFF, 68, 67,
                    r.encode().ljust(320, b"\x00")))
                return offer.yiaddr

            ip = request()
            sid = next(iter(dhcp.leases.values())).session_id

            def wait(pred, what):
                for _ in range(120):
                    if pred():
                        return
                    _time.sleep(0.05)
                raise AssertionError(what)

            wait(lambda: store.get(sid) is not None, "no initial session")
            first_expiry = store.get(sid).lease_expiry

            Clock.now += 300.0  # half-life renewal (same sid, same ip)
            assert request() == ip
            assert next(iter(dhcp.leases.values())).session_id == sid
            wait(lambda: store.get(sid) is not None
                 and store.get(sid).lease_expiry > first_expiry,
                 "renewal never re-pushed the extended expiry")

            # CoA policy change re-pushes with the new plan
            coa = RadiusPacket(rp.COA_REQUEST, 3)
            coa.add(rp.FRAMED_IP_ADDRESS, ip)
            coa.add(rp.FILTER_ID, "business-100mbps")
            import socket as so

            s = so.socket(so.AF_INET, so.SOCK_DGRAM)
            s.settimeout(3.0)
            s.sendto(coa.encode(b"s3cr3t"),
                     ("127.0.0.1", active.components["coa"].addr[1]))
            resp = RadiusPacket.decode(s.recvfrom(4096)[0])
            s.close()
            assert resp.code == rp.COA_ACK
            wait(lambda: store.get(sid) is not None
                 and store.get(sid).qos_policy == "business-100mbps",
                 "CoA policy change never reached the standby")
        finally:
            if standby is not None:
                standby.close()
            active.close()
